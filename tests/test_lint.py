"""sitpu-lint golden tests (docs/STATIC_ANALYSIS.md).

Per checker: the seeded bad fixture is flagged, the good twin is clean,
and inline suppressions are honored. Plus the baseline gate mechanics,
the repo-wide clean run against the committed baseline, and the ledger
round-trip (every statically discovered degrade component appears in
``obs.ledger_registry()`` and vice versa).

Pure host-side AST work — no jax arrays, no device, fast.
"""

import os

import pytest

from scenery_insitu_tpu.tools.lint import counters as C
from scenery_insitu_tpu.tools.lint import ledger as L
from scenery_insitu_tpu.tools.lint import pallas as P
from scenery_insitu_tpu.tools.lint import thread as TH
from scenery_insitu_tpu.tools.lint import trace as TR
from scenery_insitu_tpu.tools.lint.runner import (default_baseline_path,
                                                  run_checks, run_lint)
from scenery_insitu_tpu.tools.lint.core import (Baseline, find_repo_root,
                                                load_sources)

ROOT = find_repo_root()
FIX = os.path.join(ROOT, "tests", "lint_fixtures")


def fixture_sources(*names):
    return load_sources(ROOT, [os.path.join(FIX, n) for n in names])


def codes_of(diags):
    return [d.code for d in diags]


# ------------------------------------------------------------ SITPU-LEDGER

class TestLedger:
    def test_bad_flagged(self):
        diags = L.check(fixture_sources("bad_ledger.py"))
        msgs = [d.message for d in diags]
        # ImportError impl swap, print-and-swap handler, probe consult
        assert len(diags) == 3, diags
        assert any("missing optional dependency" in m for m in msgs)
        assert any("swaps result" in m for m in msgs)
        assert any("have_turbo" in m for m in msgs)
        assert {d.symbol for d in diags} == {"load_codec", "pick_backend",
                                             "run"}

    def test_good_clean(self):
        # run_checks applies the inline-suppression filter, so the good
        # fixture (whose one remaining handler carries a justified
        # disable comment) comes out fully clean
        diags = run_checks(fixture_sources("good_ledger.py"))
        assert diags == [], [d.render() for d in diags]

    def test_suppression_honored(self):
        # the raw checker DOES flag suppressed(); the runner's
        # suppression filter is what silences it — prove both halves
        src = fixture_sources("good_ledger.py")
        raw = L.check(src)
        assert [d.symbol for d in raw] == ["suppressed"]
        assert src[0].suppressed(raw[0].line, raw[0].code)
        assert run_checks(src) == []

    def test_discovery_literal_components(self):
        srcs = fixture_sources("good_ledger.py")
        comps = L.discover_degrade_components(srcs)
        assert set(comps) == {"fixture.codec", "fixture.backend",
                              "fixture.turbo"}


# ----------------------------------------------------------- SITPU-COUNTER

class TestCounter:
    def test_bad_flagged(self):
        diags = C.check(fixture_sources("bad_counter.py"))
        msgs = [d.message for d in diags]
        # unregistered literal, unregistered *_counter default,
        # unregistered *_counter keyword, dynamic non-parameter name
        assert len(diags) == 4, [d.render() for d in diags]
        assert sum("not registered" in m for m in msgs) == 3
        assert any("'frames_rendered_totally_unregistered'" in m
                   for m in msgs)
        assert any("'fixture_unregistered_steps'" in m for m in msgs)
        assert any("'fixture_unregistered_hops'" in m for m in msgs)
        assert any("dynamic variable 'metric'" in m for m in msgs)

    def test_good_clean(self):
        # run_checks applies the inline-suppression filter, silencing
        # the one deliberately-suppressed dynamic name
        diags = run_checks(fixture_sources("good_counter.py"))
        assert diags == [], [d.render() for d in diags]

    def test_counter_param_pattern_accepted(self):
        # the raw checker only flags the suppressed dynamic call — the
        # *_counter-parameter call and registered literals are clean
        raw = C.check(fixture_sources("good_counter.py"))
        assert [d.symbol for d in raw] == ["suppressed"]

    def test_discovery(self):
        srcs = fixture_sources("good_counter.py")
        disc = C.discover_counters(srcs)
        assert set(disc) == {"frame_scan_builds", "ring_steps_built",
                             "dcn_hops_built"}


# ------------------------------------------------------------ SITPU-THREAD

THREAD_KW = dict(config_path="tests/lint_fixtures/thread_config.py",
                 session_paths=("tests/lint_fixtures/thread_session.py",))


def thread_check(pipeline, with_session=False):
    names = ["thread_config.py", pipeline]
    kw = dict(THREAD_KW)
    if with_session:
        names.append("thread_session.py")
    else:
        kw["session_paths"] = ()
    srcs = fixture_sources(*names)
    return TH.check(srcs,
                    pipeline_path=f"tests/lint_fixtures/{pipeline}", **kw)


class TestThread:
    def test_knob_derivation_from_config(self):
        srcs = fixture_sources("thread_config.py")
        knobs = TH.derive_knobs(srcs[0])
        assert knobs == ["exchange", "ring_slots", "wire", "schedule",
                         "wave_tiles", "k_budget"]

    def test_real_config_derivation(self):
        srcs = load_sources(
            ROOT, [os.path.join(ROOT, "scenery_insitu_tpu", "config.py")])
        knobs = TH.derive_knobs(srcs[0])
        assert set(knobs) == {"exchange", "ring_slots", "wire", "schedule",
                              "wave_tiles", "k_budget", "rebalance",
                              "rebalance_period", "rebalance_hysteresis",
                              "rebalance_min_depth", "rebalance_quantum",
                              "rebalance_bricks", "rebalance_max_moves",
                              "temporal_reuse"}

    def test_deleted_wire_forwarding_fails(self):
        """The acceptance-criteria demo: a builder whose wire= forwarding
        was deleted fails SITPU-THREAD."""
        diags = thread_check("bad_thread.py")
        by_sym = {}
        for d in diags:
            by_sym.setdefault(d.symbol, []).append(d.message)
        assert any("accepts knob 'wire' but never forwards it" in m
                   for m in by_sym["distributed_bad_step"])
        # the one-knob builder is missing the rest of the matrix
        missing = [m for m in by_sym["distributed_missing_step"]
                   if "does not accept knob" in m]
        assert len(missing) == 5
        # the dropped-object builder never threads comp_cfg
        assert any("never forwards it" in m
                   for m in by_sym["distributed_dropped_obj_step"])

    def test_good_builders_clean(self):
        diags = thread_check("good_thread.py")
        assert diags == [], [d.render() for d in diags]

    def test_session_plumbing(self):
        diags = thread_check("good_thread.py", with_session=True)
        msgs = [d.message for d in diags]
        assert len(diags) == 3, [d.render() for d in diags]
        assert any("does not forward knob 'wire'" in m for m in msgs)
        assert any("does not bind comp_cfg" in m for m in msgs)
        # the same forgetful call also fails the topology binding rule
        assert any("does not bind 'topology'" in m for m in msgs)

    def test_topology_threading_enforced(self):
        """ISSUE 14: every distributed builder must accept AND consume
        the TopologyConfig — a builder that drops it silently composites
        flat on a hierarchical mesh."""
        diags = thread_check("bad_thread.py")
        by_sym = {}
        for d in diags:
            by_sym.setdefault(d.symbol, []).append(d.message)
        for sym in ("distributed_bad_step", "distributed_missing_step",
                    "distributed_dropped_obj_step"):
            assert any("does not accept 'topology'" in m
                       for m in by_sym[sym]), by_sym[sym]
        # the compliant fixtures resolve it — clean
        assert thread_check("good_thread.py") == []

    def test_real_builders_thread_whole_matrix(self):
        """The real pipeline/session: only the documented, baselined
        plain-builder gaps (ring_slots/k_budget) may appear."""
        paths = [os.path.join(ROOT, p) for p in
                 ("scenery_insitu_tpu/config.py",
                  "scenery_insitu_tpu/parallel/pipeline.py",
                  "scenery_insitu_tpu/runtime/session.py")]
        diags = TH.check(load_sources(ROOT, paths))
        assert all("does not accept knob" in d.message
                   and d.symbol.startswith("distributed_plain_step")
                   for d in diags), [d.render() for d in diags]
        assert {d.symbol for d in diags} <= {"distributed_plain_step",
                                             "distributed_plain_step_mxu"}


# ------------------------------------------------------------- SITPU-TRACE

class TestTrace:
    def test_bad_flagged(self):
        diags = TR.check(fixture_sources("bad_trace.py"))
        msgs = [d.message for d in diags]
        assert any("Python `if` on a traced value" in m for m in msgs)
        assert any("float() on a traced value" in m for m in msgs)
        assert any("pulls a traced value to host" in m for m in msgs)
        assert any("inside a lax.scan body" in m for m in msgs)
        assert any("static_argnames ['engine']" in m for m in msgs)
        assert len(diags) == 5, [d.render() for d in diags]

    def test_good_clean(self):
        diags = TR.check(fixture_sources("good_trace.py"))
        assert diags == [], [d.render() for d in diags]

    def test_real_pipeline_clean(self):
        """The distributed pipeline (ring/waves/scan machinery) must stay
        free of host-sync hazards — this is the invariant that protects
        the PR 4/8 overlap structure."""
        paths = [os.path.join(ROOT, "scenery_insitu_tpu", "parallel",
                              "pipeline.py")]
        diags = TR.check(load_sources(ROOT, paths))
        assert diags == [], [d.render() for d in diags]


# ------------------------------------------------------------ SITPU-PALLAS

class TestPallas:
    def test_bad_flagged(self):
        diags = P.check(fixture_sources("bad_pallas.py"))
        msgs = [d.message for d in diags]
        assert any("not behind a Mosaic compile probe" in m for m in msgs)
        assert any("tile-divisibility" in m for m in msgs)
        assert any("SMEM scalar block" in m for m in msgs)
        assert len(diags) == 3, [d.render() for d in diags]

    def test_good_clean(self):
        diags = P.check(fixture_sources("good_pallas.py"))
        assert diags == [], [d.render() for d in diags]

    def test_real_kernels_probed(self):
        """Every production pallas_call sits behind a probe (the
        fold_microbench experiment kernels are baselined, not clean)."""
        pkg = os.path.join(ROOT, "scenery_insitu_tpu")
        paths = []
        for dirpath, _, files in os.walk(pkg):
            if "tools" in dirpath or "__pycache__" in dirpath:
                continue
            paths += [os.path.join(dirpath, f) for f in files
                      if f.endswith(".py")]
        diags = P.check(load_sources(ROOT, paths))
        assert diags == [], [d.render() for d in diags]


# ---------------------------------------------------------- baseline gate

class TestBaseline:
    def test_gate_mechanics(self, tmp_path):
        diags = L.check(fixture_sources("bad_ledger.py"))
        assert diags
        # no baseline: everything is new
        new, acc, stale = Baseline([]).split(diags)
        assert len(new) == len(diags) and not acc and not stale
        # full baseline: everything accepted
        bl = Baseline([Baseline.entry_for(d, "seeded fixture") for d in
                       diags])
        new, acc, stale = bl.split(diags)
        assert not new and len(acc) == len(diags) and not stale
        # baseline survives a save/load round trip
        p = tmp_path / "bl.json"
        bl.save(str(p))
        new, acc, _ = Baseline.load(str(p)).split(diags)
        assert not new and len(acc) == len(diags)
        # stale entries are reported once the finding disappears
        _, _, stale = bl.split(diags[1:])
        assert len(stale) == 1

    def test_cli_fail_on_stale(self, tmp_path):
        """ISSUE 15 satellite: with --fail-on-stale a baseline entry
        that no longer matches any finding FAILS the gate instead of
        lingering as a dead row (CI runs the flag)."""
        from scenery_insitu_tpu.tools.lint.__main__ import main as cli

        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        bl = tmp_path / "bl.json"
        Baseline([{"code": "SITPU-LEDGER", "path": "gone.py",
                   "message": "long since fixed", "symbol": "f",
                   "reason": "a debt that was paid off and never pruned"
                   }]).save(str(bl))
        args = ["--baseline", str(bl), str(clean)]
        assert cli(args) == 0                     # stale alone passes...
        assert cli(["--fail-on-stale"] + args) == 1   # ...the flag gates
        # and the committed baseline stays stale-free under the flag
        assert cli(["--fail-on-stale"]) == 0

    def test_reasons_are_mandatory(self):
        with pytest.raises(ValueError, match="without a reason"):
            Baseline([{"code": "X", "path": "p", "message": "m",
                       "reason": ""}])

    def test_committed_baseline_reasons(self):
        bl = Baseline.load(default_baseline_path())
        assert bl.entries, "committed baseline missing"
        assert all(len(e["reason"]) > 20 for e in bl.entries)

    def test_repo_is_clean_against_baseline(self):
        """The acceptance criterion: the suite exits 0 on the repo."""
        new, accepted, stale, _ = run_lint()
        assert new == [], [d.render() for d in new]
        assert stale == [], stale

    def test_unparseable_file_is_a_finding_not_a_crash(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def oops(:\n")
        new, _, _, diags = run_lint(paths=[str(bad)],
                                    repo_root=str(tmp_path))
        assert [d.code for d in diags] == ["SITPU-PARSE"]
        assert new == diags

    def test_inline_suppression_filtered_by_runner(self):
        srcs = fixture_sources("good_ledger.py", "bad_ledger.py")
        diags = run_checks(srcs)
        # bad fixture findings survive, nothing from the good one
        assert all("bad_ledger" in d.path for d in diags
                   if d.code == "SITPU-LEDGER")


# ------------------------------------------------- ledger round-trip test

class TestLedgerRoundTrip:
    def test_registry_matches_static_scan(self):
        """Every statically discovered degrade component is registered in
        obs.ledger_registry() and every registry row has a live site."""
        from scenery_insitu_tpu import obs
        from scenery_insitu_tpu.tools.lint.core import default_scan_paths

        srcs = load_sources(ROOT, default_scan_paths(ROOT))
        discovered = L.discover_degrade_components(srcs)
        registry = obs.ledger_registry()
        assert set(discovered) - set(registry) == set(), \
            f"degrade sites missing from obs.ledger_registry(): " \
            f"{ {c: discovered[c] for c in set(discovered) - set(registry)} }"
        assert set(registry) - set(discovered) == set(), \
            f"registry rows with no degrade site: " \
            f"{sorted(set(registry) - set(discovered))}"

    def test_registry_descriptions(self):
        from scenery_insitu_tpu import obs

        reg = obs.ledger_registry()
        assert all(isinstance(v, str) and len(v) > 10
                   for v in reg.values())

    def test_counter_registry_matches_static_scan(self):
        """Counter twin of the degrade round-trip: every statically
        discovered counter name is registered in obs.counter_registry()
        and every registry row has a live count() site."""
        from scenery_insitu_tpu import obs
        from scenery_insitu_tpu.tools.lint.core import default_scan_paths

        srcs = load_sources(ROOT, default_scan_paths(ROOT))
        discovered = C.discover_counters(srcs)
        registry = obs.counter_registry()
        assert set(discovered) - set(registry) == set(), \
            f"count() sites missing from obs.counter_registry(): " \
            f"{ {c: discovered[c] for c in set(discovered) - set(registry)} }"
        assert set(registry) - set(discovered) == set(), \
            f"registry rows with no count() site: " \
            f"{sorted(set(registry) - set(discovered))}"

    def test_counter_registry_descriptions(self):
        from scenery_insitu_tpu import obs

        reg = obs.counter_registry()
        assert all(isinstance(v, str) and len(v) > 10
                   for v in reg.values())

    def test_runtime_entry_matches_registry(self):
        """A runtime degrade of a registered component round-trips into
        the ledger snapshot."""
        from scenery_insitu_tpu import obs

        before = {tuple(sorted(e.items())) for e in obs.ledger()}
        obs.degrade("io.vdi_codec", "zstd", "zlib",
                    "lint round-trip test entry", warn=False)
        after = obs.ledger()
        assert any(e["component"] == "io.vdi_codec" for e in after)
        assert "io.vdi_codec" in obs.ledger_registry()
        assert len(after) >= len(before)
