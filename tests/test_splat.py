"""Particle splatting + distributed sort-first compositing tests
(SURVEY.md §7 step 8; ≅ reference InVisRenderer/Head particle path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.ops.splat import speed_colors, splat_particles
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.parallel.particles import (distributed_particle_step,
                                                   shard_particles)

W, H = 64, 48


def _cam():
    return Camera.create((0.0, 0.0, 5.0), target=(0.0, 0.0, 0.0),
                         fov_y_deg=50.0, near=0.5, far=50.0)


class TestSplat:
    def test_center_particle_lands_center_pixel(self):
        pos = jnp.array([[0.0, 0.0, 0.0]])
        rgba = jnp.array([[1.0, 0.0, 0.0, 1.0]])
        out = splat_particles(pos, rgba, 0.3, _cam(), W, H, stamp=11)
        img = np.asarray(out.image)
        dep = np.asarray(out.depth)
        cy, cx = H // 2, W // 2
        assert img[3, cy, cx] == 1.0          # opaque at center
        assert img[0, cy, cx] > 0.0           # red
        assert img[1, cy, cx] == 0.0
        # impostor depth at sphere front ≈ distance - radius
        assert dep[cy, cx] == pytest.approx(5.0 - 0.3, abs=0.05)
        # empty background stays transparent with +inf depth
        assert img[3, 0, 0] == 0.0
        assert np.isinf(dep[0, 0])

    def test_nearer_particle_wins(self):
        pos = jnp.array([[0.0, 0.0, 0.0], [0.0, 0.0, 1.0]])  # 2nd is nearer
        rgba = jnp.array([[1.0, 0.0, 0.0, 1.0], [0.0, 1.0, 0.0, 1.0]])
        out = splat_particles(pos, rgba, 0.3, _cam(), W, H, stamp=11)
        img = np.asarray(out.image)
        cy, cx = H // 2, W // 2
        assert img[1, cy, cx] > 0.0 and img[0, cy, cx] == 0.0

    def test_behind_camera_culled(self):
        pos = jnp.array([[0.0, 0.0, 10.0]])   # behind the eye at z=5
        rgba = jnp.ones((1, 4))
        out = splat_particles(pos, rgba, 0.3, _cam(), W, H)
        assert np.asarray(out.image).max() == 0.0

    def test_shading_brightest_at_center(self):
        pos = jnp.array([[0.0, 0.0, 0.0]])
        rgba = jnp.array([[1.0, 1.0, 1.0, 1.0]])
        out = splat_particles(pos, rgba, 0.5, _cam(), W, H, stamp=15)
        img = np.asarray(out.image)
        cy, cx = H // 2, W // 2
        covered = img[3] > 0
        assert covered.sum() > 4
        assert img[0, cy, cx] == img[0][covered].max()
        # rim is dimmer than center (impostor normal shading)
        assert img[0][covered].min() < img[0, cy, cx] * 0.8

    def test_jit_compatible(self):
        f = jax.jit(lambda p, c: splat_particles(p, c, 0.2, _cam(), W, H))
        pos = jax.random.uniform(jax.random.PRNGKey(0), (50, 3), minval=-1,
                                 maxval=1)
        out = f(pos, jnp.ones((50, 4)))
        assert out.image.shape == (4, H, W)
        assert np.isfinite(np.asarray(out.image)).all()


class TestSpeedColors:
    def test_monotone_in_speed(self):
        vel = jnp.array([[0.1, 0, 0], [1.0, 0, 0], [3.0, 0, 0]])
        rgba = np.asarray(speed_colors(vel, "grays"))
        assert rgba.shape == (3, 4)
        # grays colormap: faster -> brighter
        assert rgba[0, 0] < rgba[1, 0] < rgba[2, 0]
        assert (rgba[:, 3] == 1.0).all()

    def test_explicit_stats_match_population(self):
        key = jax.random.PRNGKey(1)
        vel = jax.random.normal(key, (256, 3))
        speed = jnp.linalg.norm(vel, axis=-1)
        a = speed_colors(vel, "jet")
        b = speed_colors(vel, "jet", mean=jnp.mean(speed),
                         std=jnp.std(speed))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


class TestDistributedParticles:
    def test_matches_single_device(self):
        n_dev = jax.device_count()
        mesh = make_mesh(n_dev)
        n = 64 * n_dev
        key = jax.random.PRNGKey(2)
        k1, k2 = jax.random.split(key)
        pos = jax.random.uniform(k1, (n, 3), minval=-1.2, maxval=1.2)
        vel = jax.random.normal(k2, (n, 3))

        cam = _cam()
        step = distributed_particle_step(mesh, W, H, radius=0.15, stamp=9)
        out = step(shard_particles(pos, mesh), shard_particles(vel, mesh),
                   cam)

        rgba = speed_colors(vel, "jet")
        ref = splat_particles(pos, rgba, 0.15, cam, W, H, stamp=9)

        img = np.asarray(out.image)
        rimg = np.asarray(ref.image)
        # depth buffers must agree exactly (min over the same fragment set)
        np.testing.assert_allclose(np.asarray(out.depth),
                                   np.asarray(ref.depth), atol=1e-6)
        # colors agree except where equal-depth ties resolve differently
        agree = np.isclose(img, rimg, atol=1e-5).all(axis=0)
        assert agree.mean() > 0.999


class TestParticlePipeline:
    def test_lj_frame_step_jits_and_moves(self):
        from scenery_insitu_tpu.models.pipelines import lj_particle_frame_step
        from scenery_insitu_tpu.sim import particles as pt

        state, params, spec = pt.lj_init(128, density=0.4)
        step = jax.jit(lj_particle_frame_step(
            W, H, params=params, spec=spec, sim_steps=2, radius=0.4))
        eye = jnp.array([0.0, 0.0, float(state.box) * 1.6], jnp.float32)
        img, dep, pos, vel = step(state.pos, state.vel, state.box, eye)
        assert img.shape == (4, H, W)
        assert np.isfinite(np.asarray(img)).all()
        assert np.asarray(img)[3].max() > 0.0            # something visible
        assert not np.allclose(np.asarray(pos), np.asarray(state.pos))
