"""Unit tests of the shared supersegment state machine on tiny synthetic
streams (1x1 images so expected outputs are hand-checkable)."""

import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.ops import supersegments as ss


def _feed(items, k=4, thr=0.5, gap_eps=-1.0):
    """items: list of (rgba tuple, t0, t1). Returns (color [K,4], depth [K,2])."""
    st = ss.init_state(k, 1, 1)
    for rgba, t0, t1 in items:
        st = ss.push(st, k, jnp.full((1, 1), thr),
                     jnp.asarray(rgba, jnp.float32).reshape(4, 1, 1),
                     jnp.full((1, 1), t0), jnp.full((1, 1), t1), gap_eps)
    c, d = ss.finalize(st)
    return np.asarray(c)[:, :, 0, 0], np.asarray(d)[:, :, 0, 0]


def test_single_run_merges():
    items = [((0.2, 0.0, 0.0, 0.5), 1.0, 1.1),
             ((0.2, 0.0, 0.0, 0.5), 1.1, 1.2)]
    c, d = _feed(items)
    # one segment: alpha = 1-(1-.5)^2 = .75, extent [1.0, 1.2]
    assert np.isclose(c[0, 3], 0.75, atol=1e-6)
    assert np.allclose(d[0], [1.0, 1.2], atol=1e-6)
    assert not np.isfinite(d[1, 0])


def test_color_break_splits():
    items = [((0.5, 0.0, 0.0, 0.5), 1.0, 1.1),
             ((0.0, 0.5, 0.0, 0.5), 1.1, 1.2)]
    c, d = _feed(items, thr=0.2)
    assert c[0, 3] == 0.5 and c[1, 3] == 0.5
    assert np.allclose(d[0], [1.0, 1.1]) and np.allclose(d[1], [1.1, 1.2])
    assert c[0, 0] > 0.2 and c[1, 1] > 0.1  # first red, second green


def test_gap_via_empty_sample():
    items = [((0.2, 0.2, 0.2, 0.4), 1.0, 1.1),
             ((0.0, 0.0, 0.0, 0.0), 1.1, 1.2),   # transparent gap
             ((0.2, 0.2, 0.2, 0.4), 1.2, 1.3)]
    c, d = _feed(items, thr=0.9)
    assert c[0, 3] > 0 and c[1, 3] > 0
    assert np.allclose(d[0], [1.0, 1.1]) and np.allclose(d[1], [1.2, 1.3])


def test_gap_eps_breaks_segments():
    items = [((0.2, 0.2, 0.2, 0.4), 1.0, 1.1),
             ((0.2, 0.2, 0.2, 0.4), 2.0, 2.1)]   # same color, depth gap
    c_nogap, d_nogap = _feed(items, thr=0.9, gap_eps=-1.0)
    c_gap, d_gap = _feed(items, thr=0.9, gap_eps=0.01)
    assert not np.isfinite(d_nogap[1, 0])        # merged without gap check
    assert np.isfinite(d_gap[1, 0])              # split with gap check
    assert np.allclose(d_gap[1], [2.0, 2.1])


def test_overflow_merges_into_last_slot():
    # alternating colors force a break at every item; k=2 → last slot absorbs
    items = []
    for i in range(6):
        col = (0.8, 0.0, 0.0, 0.5) if i % 2 == 0 else (0.0, 0.8, 0.0, 0.5)
        items.append((col, 1.0 + 0.1 * i, 1.1 + 0.1 * i))
    c, d = _feed(items, k=2, thr=0.1)
    assert np.isfinite(d[0, 0]) and np.isfinite(d[1, 0])
    assert np.isclose(d[1, 1], 1.6, atol=1e-5)   # last slot extends to the end


def test_alpha_under_ordering():
    # opaque-ish first segment dominates the composited color
    items = [((0.9, 0.0, 0.0, 0.9), 1.0, 1.1),
             ((0.0, 0.9, 0.0, 0.9), 1.1, 1.2)]
    c, _ = _feed(items, thr=0.2)
    assert c[0, 0] > 5 * c[1, 1] * (1 - 0.9) or True  # segments stored separately
    # re-compose front-to-back: red contribution >> green
    total = c[0] + (1 - c[0][3]) * c[1]
    assert total[0] > total[1] * 5


def test_count_matches_write():
    rng = np.random.default_rng(3)
    h = w = 4
    n = 24
    vals = rng.random((n, h, w)).astype(np.float32)
    alphas = (rng.random((n, h, w)) > 0.3).astype(np.float32) * 0.5
    thr = jnp.full((h, w), 0.15, jnp.float32)
    cstate = ss.init_count(h, w)
    wstate = ss.init_state(8, h, w)
    for i in range(n):
        rgba = jnp.stack([jnp.asarray(vals[i]) * alphas[i],
                          jnp.zeros((h, w)), jnp.zeros((h, w)),
                          jnp.asarray(alphas[i])])
        t0 = jnp.full((h, w), float(i))
        t1 = t0 + 1.0
        cstate = ss.push_count(cstate, thr, rgba)
        wstate = ss.push(wstate, 8, thr, rgba, t0, t1)
    color, depth = ss.finalize(wstate)
    live = np.asarray((color[:, 3] > 0).sum(axis=0))
    counts = np.asarray(cstate.count)
    # where counts fit in k, written segments == counted segments
    fits = counts <= 8
    assert (live[fits] == counts[fits]).all()


def test_adaptive_threshold_monotone():
    # synthetic count function: higher threshold → fewer segments
    def count_fn(thr):
        return jnp.ceil(10.0 * (1.0 - thr / 2.0)).astype(jnp.int32)
    thr = ss.adaptive_threshold(count_fn, 4, 8, 2, 2)
    c = np.asarray(count_fn(thr))
    assert (c <= 4).all()
