"""Novel-view VDI rendering tests (SURVEY.md §7 step 9;
≅ EfficientVDIRaycast validation — the reference checked its optimized
walker against brute-force stepping, EfficientVDIRaycast.comp:452-567; here
we check against the same-view decode and the ground-truth raycast)."""

import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import RenderConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera, orbit
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import render_vdi_same_view
from scenery_insitu_tpu.core.volume import procedural_volume
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
from scenery_insitu_tpu.ops.vdi_render import (frustum_aabb, original_eye,
                                               render_vdi)
from scenery_insitu_tpu.utils.image import psnr

W = H = 48
STEPS = 96


def _cam(eye=(0.0, 0.0, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _vdi(k=12):
    vol = procedural_volume(24, kind="blobs", seed=3)
    tf = TransferFunction.ramp(0.1, 0.9, 0.6)
    vdi, meta = generate_vdi(vol, tf, _cam(), W, H,
                             VDIConfig(max_supersegments=k, adaptive_iters=3),
                             max_steps=STEPS)
    return vol, tf, vdi, meta


def test_original_eye_roundtrip():
    cam = _cam((1.2, -0.4, 3.0))
    _, _, _, meta = _vdi()
    from scenery_insitu_tpu.core.camera import view_matrix
    meta = meta._replace(view=view_matrix(cam))
    np.testing.assert_allclose(np.asarray(original_eye(meta)),
                               np.asarray(cam.eye), atol=1e-5)


def test_frustum_aabb_contains_volume():
    vol, _, _, meta = _vdi()
    lo, hi = frustum_aabb(meta)
    lo, hi = np.asarray(lo), np.asarray(hi)
    assert (lo <= np.asarray(vol.world_min)).all()
    assert (hi >= np.asarray(vol.world_max)).all()


def test_same_view_matches_direct_decode():
    _, _, vdi, meta = _vdi()
    img = render_vdi(vdi, meta, _cam(), W, H, steps=2 * STEPS)
    ref = render_vdi_same_view(vdi)
    p = psnr(np.asarray(img), np.asarray(ref))
    assert p > 25.0, p


def test_novel_view_close_to_ground_truth():
    vol, tf, vdi, meta = _vdi()
    cam2 = orbit(_cam(), jnp.float32(0.25))     # ~14 degrees around target
    img = render_vdi(vdi, meta, cam2, W, H, steps=2 * STEPS)
    truth = raycast(vol, tf, cam2, W, H,
                    RenderConfig(max_steps=2 * STEPS)).image
    p = psnr(np.asarray(img), np.asarray(truth))
    assert p > 18.0, p


def test_view_from_behind_differs():
    _, _, vdi, meta = _vdi()
    cam_back = orbit(_cam(), jnp.float32(np.pi))
    img_b = np.asarray(render_vdi(vdi, meta, cam_back, W, H, steps=STEPS))
    img_f = np.asarray(render_vdi(vdi, meta, _cam(), W, H, steps=STEPS))
    # content exists from behind too (slabs are view-independent geometry)
    assert img_b[3].max() > 0.1
    assert not np.allclose(img_b, img_f, atol=1e-3)


def test_jit_and_finite():
    _, _, vdi, meta = _vdi(k=6)
    f = jax.jit(lambda v, m: render_vdi(v, m, _cam((0.5, 0.5, 3.5)),
                                        32, 32, steps=64))
    img = np.asarray(f(vdi, meta))
    assert img.shape == (4, 32, 32)
    assert np.isfinite(img).all()
    assert (img >= -1e-6).all()
