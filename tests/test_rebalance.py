"""Occupancy-driven render rebalancing (CompositeConfig.rebalance ==
"occupancy"; docs/PERF.md "Render rebalancing"): slice_plan unit
behavior (conservation, min-depth clamp, quantum rounding, hysteresis
stability), the reslab_z band shuffle (even-plan == halo_exchange_z
row-for-row, uneven band contents + clamp + zero padding, halo-depth
validation naming the offending rank), and composite invariance — a
REBALANCED frame must equal the EVEN frame across the builder matrix on
the 8-device virtual mesh.

Parity gates, and why each is what it is:
- gather VDI step: BITWISE. The distributed gather steps ladder their
  samples against the GLOBAL box (ops/vdi_gen sample_min/max), so every
  sample position, value, and supersegment boundary is identical under
  any render plan.
- mxu steps (both march regimes, waves cross, temporal): 1e-5 — the
  PR-6 fusion-noise gate for separately-compiled programs. The slice
  ladder is global, so with power-of-two voxel spacing the diffs here
  measure 0.0; the gate absorbs non-exact spacings.
- The scene keeps content >= 2 slices away from every band boundary of
  BOTH decompositions and under the per-rank K budget: a supersegment
  that straddles a rank cut is split at the cut (per-rank generation),
  which changes the VDI's segment STRUCTURE (not its radiance) — an
  inherent property of sort-last VDI generation, not of rebalancing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       SliceMarchConfig, VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.ops import occupancy as occ
from scenery_insitu_tpu.parallel.mesh import (halo_exchange_z, make_mesh,
                                              reslab_z, validate_plan)
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  shard_volume)
from scenery_insitu_tpu.utils.compat import shard_map

N = 8
D = 32
HW = 16
PLAN = (8, 4, 4, 4, 4, 2, 2, 4)      # bounds 8,12,16,20,24,26,28
ATOL = 1e-5                          # PR-6 fusion-noise gate


def _cam(eye=(0.0, 0.2, 4.0)):
    return Camera.create(eye, fov_y_deg=50.0, near=0.5, far=20.0)


def _tf():
    return TransferFunction.ramp(0.05, 0.8, 0.7)


def _scene():
    """Skewed scene (live work concentrated low-z), smooth constant-value
    blobs >= 2 slices clear of every boundary of the even split AND of
    PLAN, voxel spacing an exact power of two (2/32)."""
    data = np.zeros((D, HW, HW), np.float32)
    blobs = [(1, 3, 0.3), (5, 7, 0.5), (9, 11, 0.7), (13, 15, 0.4),
             (17, 19, 0.6), (21, 23, 0.8), (29, 31, 0.45)]
    for a, b, v in blobs:
        data[a:b] = v
    vox = 2.0 / D
    origin = jnp.asarray([-HW * vox / 2, -HW * vox / 2, -1.0], jnp.float32)
    spacing = jnp.full((3,), vox, jnp.float32)
    return jnp.asarray(data), origin, spacing


def _mxu_spec(cam, cfg_kw=None):
    from scenery_insitu_tpu.ops import slicer

    return slicer.make_spec(cam, (D, HW, HW),
                            SliceMarchConfig(matmul_dtype="f32", scale=2.0,
                                             **(cfg_kw or {})),
                            multiple_of=N)


def _assert_vdi_close(a, b, atol=ATOL):
    ac, ad = np.asarray(a[0]), np.asarray(a[1])
    bc, bd = np.asarray(b[0]), np.asarray(b[1])
    np.testing.assert_allclose(ac, bc, atol=atol, rtol=0)
    assert (np.isinf(ad) == np.isinf(bd)).all()
    fin = np.isfinite(ad)
    np.testing.assert_allclose(ad[fin], bd[fin], atol=atol, rtol=0)


# ------------------------------------------------------- slice_plan units

def test_slice_plan_conservation():
    rng = np.random.default_rng(0)
    for _ in range(20):
        prof = rng.random(16)
        n = int(rng.integers(2, 9))
        plan = occ.slice_plan(prof, 64, n, min_depth=2,
                              quantum=int(rng.integers(1, 5)))
        assert len(plan) == n
        assert sum(plan) == 64
        assert min(plan) >= 2


def test_slice_plan_equalizes_skew():
    """All the live work in the first quarter -> the even split's
    straggler factor collapses under the plan. Uncapped
    (max_depth=d) the equalization is near-perfect; the DEFAULT cap
    (2 * ceil(d/n)) trades some of it for a bounded padding tax
    (every rank scans max(plan) chunks) but must still reduce."""
    prof = np.zeros(32)
    prof[:8] = 1.0
    even = occ.even_plan(128, 8)
    s_even = occ.straggler_factor(prof, 128, even)
    assert s_even > 2.0
    free = occ.slice_plan(prof, 128, 8, min_depth=4, quantum=1,
                          max_depth=128)
    assert occ.straggler_factor(prof, 128, free) < s_even / 1.5
    capped = occ.slice_plan(prof, 128, 8, min_depth=4, quantum=1)
    assert max(capped) <= 2 * (128 // 8)
    assert occ.straggler_factor(prof, 128, capped) < s_even
    # dense region split across more ranks than the even split gives it
    assert sum(1 for b in np.cumsum(capped)[:-1] if b <= 32) >= 3


def test_slice_plan_min_depth_clamp():
    prof = np.zeros(16)
    prof[0] = 100.0                      # all work in slice band 0
    plan = occ.slice_plan(prof, 32, 8, min_depth=3, quantum=1)
    assert sum(plan) == 32
    # min_depth 3 is infeasible for 8 ranks over 32 slices; it clamps to
    # d // n and every band still keeps at least that
    assert min(plan) >= min(3, 32 // 8)


def test_slice_plan_quantum_rounding():
    rng = np.random.default_rng(3)
    prof = rng.random(16)
    plan = occ.slice_plan(prof, 64, 4, min_depth=4, quantum=4)
    bounds = np.cumsum(plan)
    assert all(b % 4 == 0 for b in bounds)


def test_slice_plan_hysteresis_stability():
    rng = np.random.default_rng(4)
    prof = rng.random(16)
    plan = occ.slice_plan(prof, 64, 4, min_depth=2, quantum=1)
    # a small perturbation of the profile keeps the PREVIOUS plan object
    prof2 = prof + rng.normal(0, 0.01, 16).clip(-0.05, 0.05)
    plan2 = occ.slice_plan(prof2, 64, 4, min_depth=2, quantum=1,
                           prev=plan, hysteresis=0.5)
    assert plan2 == plan
    # hysteresis off tracks the perturbation freely (may or may not
    # move); a LARGE shift must break through hysteresis
    prof3 = prof[::-1].copy()
    plan3 = occ.slice_plan(prof3, 64, 4, min_depth=2, quantum=1,
                           prev=plan, hysteresis=0.25)
    assert sum(plan3) == 64


def test_plan_work_and_straggler():
    prof = np.ones(8)
    even = occ.even_plan(32, 4)
    w = occ.plan_work(prof, 32, even)
    assert len(w) == 4 and abs(max(w) - min(w)) < 1e-9
    assert abs(occ.straggler_factor(prof, 32, even) - 1.0) < 1e-9


def test_z_live_profile():
    tf = _tf()
    field = jnp.zeros((16, 8, 8), jnp.float32)
    field = field.at[4:8].set(0.5)       # one live z quarter
    prof = np.asarray(occ.z_live_profile(field, tf, nzb=4))
    assert prof.shape == (4,)
    assert prof[1] > 0.9 and prof[0] < 0.1 and prof[2] < 0.1


# ---------------------------------------------------------- reslab_z

def _run_sharded(fn, data, mesh):
    f = jax.jit(shard_map(fn, mesh=mesh, in_specs=P("ranks", None, None),
                          out_specs=P("ranks", None, None),
                          check_vma=False))
    return np.asarray(f(shard_volume(data, mesh)))


def test_reslab_even_plan_matches_halo_exchange():
    mesh = make_mesh(N)
    data = jnp.asarray(
        np.random.default_rng(0).random((D, 8, 8)).astype(np.float32))
    even = occ.even_plan(D, N)
    a = _run_sharded(lambda x: reslab_z(x, even, "ranks"), data, mesh)
    b = _run_sharded(lambda x: halo_exchange_z(x, "ranks"), data, mesh)
    np.testing.assert_array_equal(a, b)


def test_reslab_uneven_bands_clamp_and_padding():
    mesh = make_mesh(N)
    raw = np.random.default_rng(1).random((D, 8, 8)).astype(np.float32)
    starts = np.concatenate([[0], np.cumsum(PLAN)])
    pmax = max(PLAN)
    out = _run_sharded(lambda x: reslab_z(x, PLAN, "ranks"),
                       jnp.asarray(raw), mesh)
    out = out.reshape(N, pmax + 2, 8, 8)
    for r in range(N):
        p, g0 = PLAN[r], starts[r]
        # band rows: global [g0-1, g0+p+1) with edge clamp
        ref = raw[np.clip(np.arange(g0 - 1, g0 + p + 1), 0, D - 1)]
        np.testing.assert_array_equal(out[r, :p + 2], ref)
        # rows past the band + halo are zero (the march masks them; the
        # occupancy pyramid admits zero for them)
        assert (out[r, p + 2:] == 0).all()


def test_reslab_halo_depth_validation_names_rank_and_knob():
    with pytest.raises(ValueError, match=r"rank 5.*rebalance_min_depth"):
        validate_plan((8, 4, 4, 4, 4, 2, 2, 4), 8, h=3)


def test_plan_without_occupancy_rebalance_rejected():
    mesh = make_mesh(N)
    with pytest.raises(ValueError, match="rebalance"):
        distributed_vdi_step(
            mesh, _tf(), HW, HW, VDIConfig(max_supersegments=4),
            CompositeConfig(max_output_supersegments=6), plan=PLAN)


def test_rebalance_config_validation():
    with pytest.raises(ValueError, match="rebalance"):
        CompositeConfig(rebalance="auto")
    with pytest.raises(ValueError, match="rebalance_period"):
        CompositeConfig(rebalance_period=0)
    with pytest.raises(ValueError, match="rebalance_quantum"):
        CompositeConfig(rebalance_quantum=0)


# -------------------------------------- parity: rebalanced == even split

def _vdi_cfgs(rebalance):
    return (VDIConfig(max_supersegments=10, adaptive_iters=2),
            CompositeConfig(max_output_supersegments=12, adaptive_iters=2,
                            rebalance=rebalance))


def test_rebalanced_gather_vdi_step_bitwise():
    """Gather engine: the global sample ladder makes every sample
    position/value identical under any plan — BITWISE equality."""
    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    outs = {}
    for p in (None, PLAN):
        vc, cc = _vdi_cfgs("occupancy" if p else "even")
        step = distributed_vdi_step(mesh, _tf(), HW, HW, vc, cc,
                                    max_steps=48, plan=p)
        v = step(sdata, origin, spacing, _cam())
        outs[p is not None] = (np.asarray(v.color), np.asarray(v.depth))
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    np.testing.assert_array_equal(outs[True][1], outs[False][1])


@pytest.mark.parametrize("eye", [(0.0, 0.2, 4.0),    # march axis z
                                 (3.8, 0.3, 0.6)])   # march axis x
def test_rebalanced_mxu_step_matches_even(eye):
    """MXU engine in both march regimes: the planned band march (z
    regime: w_bounds-masked padded band; x regime: v_bounds over the
    band interval) equals the even split at the 1e-5 gate."""
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam(eye)
    spec = _mxu_spec(cam)
    outs = {}
    for p in (None, PLAN):
        vc, cc = _vdi_cfgs("occupancy" if p else "even")
        step = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc, plan=p)
        v, meta = step(sdata, origin, spacing, cam)
        outs[p is not None] = (v.color, v.depth,
                               np.asarray(meta.volume_dims))
    _assert_vdi_close(outs[True][:2], outs[False][:2])
    # the metadata must keep describing the GLOBAL volume
    np.testing.assert_array_equal(outs[True][2], outs[False][2])


def test_rebalanced_waves_cross_matches_even_frame():
    """Waves x rebalance cross: a PLANNED band marched in tile waves
    still equals the even frame schedule."""
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    vc, cc = _vdi_cfgs("even")
    even, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc)(
        sdata, origin, spacing, cam)
    vc, cc = _vdi_cfgs("occupancy")
    cc = CompositeConfig(max_output_supersegments=12, adaptive_iters=2,
                         rebalance="occupancy", schedule="waves",
                         wave_tiles=2)
    waved, _ = distributed_vdi_step_mxu(mesh, _tf(), spec, vc, cc,
                                        plan=PLAN)(
        sdata, origin, spacing, cam)
    _assert_vdi_close((waved.color, waved.depth), (even.color, even.depth))


def test_rebalanced_mxu_temporal_matches_even():
    """Temporal mode: the planned seeding march + 3 carried frames match
    the even split (threshold maps included)."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal)

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    cfg_t = VDIConfig(max_supersegments=10, adaptive_mode="temporal")
    runs = {}
    for p in (None, PLAN):
        cc = CompositeConfig(max_output_supersegments=12, adaptive_iters=2,
                             rebalance="occupancy" if p else "even")
        thr = distributed_initial_threshold_mxu(
            mesh, _tf(), spec, cfg_t, plan=p)(sdata, origin, spacing, cam)
        step = distributed_vdi_step_mxu_temporal(mesh, _tf(), spec, cfg_t,
                                                 cc, plan=p)
        frames = []
        for _ in range(3):
            (v, _), thr = step(sdata, origin, spacing, cam, thr)
            frames.append((np.asarray(v.color), np.asarray(v.depth)))
        runs[p is not None] = (frames, np.asarray(thr.thr))
    np.testing.assert_allclose(runs[True][1], runs[False][1], atol=1e-6,
                               rtol=0)
    for fr_p, fr_e in zip(runs[True][0], runs[False][0]):
        _assert_vdi_close(fr_p, fr_e)


def test_rebalanced_plain_steps_match_even():
    """Plain chains, both engines. Gather: global sample ladder (the
    one residual is the early-exit gate flipping within ~1 ulp of the
    threshold — bounded by one sample's alpha; gate 1e-5 holds on this
    scene). MXU: slice ladder exact."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_plain_step_mxu)

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    for build in ("gather", "mxu"):
        imgs = {}
        for p in (None, PLAN):
            kw = dict(rebalance="occupancy" if p else "even", plan=p)
            if build == "gather":
                step = distributed_plain_step(
                    mesh, _tf(), HW, HW, RenderConfig(max_steps=48), **kw)
                out = step(sdata, origin, spacing, cam)
            else:
                step = distributed_plain_step_mxu(mesh, _tf(),
                                                  _mxu_spec(cam), **kw)
                out, _ = step(sdata, origin, spacing, cam)
            imgs[p is not None] = np.asarray(out)
        np.testing.assert_allclose(imgs[True], imgs[False], atol=ATOL,
                                   rtol=0, err_msg=build)


def test_rebalanced_hybrid_step_matches_even():
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_hybrid_step_mxu)
    from scenery_insitu_tpu.parallel.particles import shard_particles

    data, origin, spacing = _scene()
    mesh = make_mesh(N)
    sdata = shard_volume(data, mesh)
    cam = _cam()
    spec = _mxu_spec(cam)
    pos = jax.random.uniform(jax.random.PRNGKey(7), (64, 3),
                             minval=-0.8, maxval=0.8)
    vel = jax.random.normal(jax.random.PRNGKey(8), (64, 3)) * 0.1
    p_, v_ = shard_particles(pos, mesh), shard_particles(vel, mesh)
    imgs = {}
    for p in (None, PLAN):
        vc, cc = _vdi_cfgs("occupancy" if p else "even")
        step = distributed_hybrid_step_mxu(mesh, _tf(), spec, vc, cc,
                                           radius=0.05, stamp=3, plan=p)
        img, _ = step(sdata, origin, spacing, p_, v_, cam)
        imgs[p is not None] = np.asarray(img)
    np.testing.assert_allclose(imgs[True], imgs[False], atol=ATOL, rtol=0)


# --------------------------------------------- observability + session

def test_rebalance_build_emits_obs_counters():
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step_mxu

    data, origin, spacing = _scene()
    rec = obs.Recorder(enabled=True)
    prev = obs.set_recorder(rec)
    try:
        mesh = make_mesh(N)
        vc, cc = _vdi_cfgs("occupancy")
        step = distributed_vdi_step_mxu(mesh, _tf(), _mxu_spec(_cam()),
                                        vc, cc, plan=PLAN)
        step(shard_volume(data, mesh), origin, spacing, _cam())
    finally:
        obs.set_recorder(prev)
    assert rec.counters.get("rebalance_steps_built", 0) >= 1
    builds = [e for e in rec.events if e.get("name") == "rebalance_build"]
    assert builds and builds[0]["attrs"]["plan"] == list(PLAN)
    assert builds[0]["attrs"]["max_depth"] == max(PLAN)


class _SkewedSim:
    """Static skewed field (content low-z only) for session replans."""

    kind = "static_skew"

    def __init__(self, d=16, hw=16):
        f = np.zeros((d, hw, hw), np.float32)
        f[1:4] = 0.6
        self.field = jnp.asarray(f)

    def advance(self, n):
        pass


def test_session_replans_and_rebuilds():
    """InSituSession under rebalance=occupancy: the host-side re-plan
    fetches live fractions, adopts an uneven plan, mints the
    rebalance_plan event + occupancy.replan ledger row, and the
    rebuilt steps keep rendering finite frames."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "render.width=16", "render.height=16", "render.max_steps=16",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=6", "composite.adaptive_iters=2",
        "composite.rebalance=occupancy", "composite.rebalance_period=1",
        "composite.rebalance_quantum=1", "composite.rebalance_min_depth=1",
        "composite.rebalance_hysteresis=0.05",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1",
        "obs.enabled=true")
    obs.clear_ledger()
    sess = InSituSession(cfg, sim=_SkewedSim())
    payload = sess.run(3)
    assert np.isfinite(payload["vdi_color"]).all()
    assert sess._plan is not None and sum(sess._plan) == 16
    assert sess._plan != occ.even_plan(16, N)
    assert sess.obs.counters.get("rebalance_replans", 0) >= 1
    ev = [e for e in sess.obs.events if e.get("name") == "rebalance_plan"]
    assert ev and ev[0]["attrs"]["plan"] == list(sess._plan)
    assert ev[0]["attrs"]["straggler_planned"] \
        <= ev[0]["attrs"]["straggler_even"]
    assert any(e["component"] == "occupancy.replan" for e in obs.ledger())


def test_session_rebalance_inert_on_single_rank():
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.runtime.session import InSituSession

    cfg = FrameworkConfig().with_overrides(
        "render.width=16", "render.height=16", "render.max_steps=16",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=6", "composite.adaptive_iters=2",
        "composite.rebalance=occupancy",
        "sim.grid=[16,16,16]", "sim.steps_per_frame=1")
    obs.clear_ledger()
    sess = InSituSession(cfg, mesh=make_mesh(1), sim=_SkewedSim())
    sess.run(1)
    assert sess._plan is None
    assert any(e["component"] == "occupancy.rebalance"
               for e in obs.ledger())
