"""Tests for the render benchmark harness (runtime/benchmark.py +
benchmarks/render_bench.py CLI): sweep stats, CSV format, flythrough
interpolation and the CLI end-to-end at tiny sizes."""

import os
import subprocess
import sys

import numpy as np
import jax.numpy as jnp

from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.runtime.benchmark import (benchmark_views, fps_csv,
                                                  interpolate_path,
                                                  record_flythrough)


def _cam():
    return Camera.create((0.0, 0.4, 2.5), fov_y_deg=45.0, near=0.3, far=10.0)


def test_benchmark_views_and_csv(tmp_path):
    calls = []

    def render(cam):
        calls.append(np.asarray(cam.eye))
        return jnp.full((4, 8, 8), 0.5)

    results = benchmark_views(render, _cam(), num_views=3, frames=2,
                              warmup=1, screenshot_dir=str(tmp_path))
    assert len(results) == 3
    assert all(st.n == 2 for _, st in results)
    # 3 views x (1 warmup + 2 timed)
    assert len(calls) == 9
    # distinct eyes per view
    eyes = {tuple(np.round(calls[i * 3], 4)) for i in range(3)}
    assert len(eyes) == 3
    assert sorted(os.listdir(tmp_path)) == ["view00.png", "view01.png",
                                            "view02.png"]

    csv = fps_csv(results)
    lines = csv.strip().split("\n")
    assert lines[0].startswith("yaw_deg;avg_fps")
    assert len(lines) == 4
    row = lines[1].split(";")
    assert len(row) == 6 and int(row[5]) == 2
    # min_fps <= avg_fps <= max_fps
    assert float(row[2]) <= float(row[1]) <= float(row[3])


def test_interpolate_path_endpoints():
    a = _cam()
    b = Camera.create((2.0, 0.0, 0.5), target=(0.1, 0.0, 0.0),
                      fov_y_deg=60.0)
    path = interpolate_path([a, b], frames_per_segment=4)
    assert len(path) == 5
    assert np.allclose(np.asarray(path[0].eye), np.asarray(a.eye))
    assert np.allclose(np.asarray(path[-1].eye), np.asarray(b.eye))
    # monotone progress along the segment
    xs = [float(c.eye[0]) for c in path]
    assert all(x1 <= x2 + 1e-6 for x1, x2 in zip(xs, xs[1:]))


def test_record_flythrough(tmp_path):
    render = lambda cam: jnp.full((4, 8, 8), 0.3)
    path = interpolate_path([_cam(), Camera.create((0.0, 0.4, -2.5))], 3)
    n = record_flythrough(render, path, str(tmp_path / "fly"))
    assert n == len(path)
    assert len(os.listdir(tmp_path / "fly")) == n


def test_render_bench_cli(tmp_path):
    env = dict(os.environ,
               PYTHONPATH="/root/repo",
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "/root/repo/benchmarks/render_bench.py",
         "--grid", "16", "--views", "2", "--frames", "2", "--width", "32",
         "--height", "24", "--steps", "24", "--engine", "gather",
         "--out-dir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = out.stdout.strip().split("\n")
    assert lines[0].startswith("yaw_deg") and len(lines) == 3
    assert os.path.exists(tmp_path / "fps_procedural_gather_plain.csv")
    shots = tmp_path / "procedural_gather_plain"
    assert sorted(os.listdir(shots)) == ["view00.png", "view01.png"]


def test_scaling_bench_cli():
    """Scaling sweep smoke: runs 1->4 on the virtual mesh, emits one JSON
    line with per-n fps/efficiency/all_to_all rows (the BASELINE scaling
    metric's ready-to-run harness)."""
    import json

    env = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "/root/repo/benchmarks/scaling_bench.py",
         "--max-ranks", "4", "--grid", "16", "--k", "4",
         "--frames", "2", "--sim-steps", "2"],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    ns = [row["n"] for row in rep["sweep"]]
    assert ns == [1, 2, 4]
    assert rep["sweep"][0]["efficiency"] == 1.0
    for row in rep["sweep"]:
        assert row["fps"] > 0
        if row["n"] > 1:
            assert row["all_to_all_ms"] > 0
