"""Tests for the ISSUE-1 HBM-traffic levers: the time-fused 2D-blocked
sim stencil's guard rails, the bf16 marched-volume path, the on-device
frame scan, and the pallas_seg argument-form/probe fixes that rode along
(ADVICE.md round 5)."""

import jax.numpy as jnp
import numpy as np
import pytest

from scenery_insitu_tpu.config import (FrameworkConfig, SliceMarchConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import for_dataset
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.runtime.session import InSituSession
from scenery_insitu_tpu.sim import grayscott as gs


# ------------------------------------------------------------ compat shim


def test_compat_shim_surface():
    """The one-place JAX version shim must expose the new-API surface on
    whatever JAX is installed (the seed pinned `jax.shard_map`, absent
    here — the tier-1 collection failure this PR removes)."""
    from scenery_insitu_tpu.utils import compat

    assert callable(compat.shard_map)
    assert callable(compat.tpu_compiler_params)
    p = compat.tpu_compiler_params(
        dimension_semantics=("arbitrary",))
    assert p.dimension_semantics == ("arbitrary",)


# ------------------------------------------------- stencil guard rails


def test_step_pallas2d_rejects_bad_tile():
    """An explicit (tz, th) off the T | tz | D and T | th | H lattice
    must raise instead of floor-dividing the grid and silently leaving
    output tiles unwritten."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 32, 128), n_seeds=1)
    pvec = jnp.stack([st.params.f, st.params.k, st.params.du,
                      st.params.dv, st.params.dt])
    for tz, th in ((12, 32), (8, 24), (6, 32), (8, 12)):
        with pytest.raises(ValueError, match="violates"):
            ps.step_pallas2d(st.u, st.v, pvec, 4, interpret=True,
                             tz=tz, th=th)
    with pytest.raises(ValueError, match="both tz and th"):
        ps.step_pallas2d(st.u, st.v, pvec, 4, interpret=True, tz=8)


def test_step_pallas_rejects_bad_tz():
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    st = gs.GrayScott.init((16, 16, 128), n_seeds=1)
    pvec = jnp.stack([st.params.f, st.params.k, st.params.du,
                      st.params.dv, st.params.dt])
    for tz in (12, 6):   # 12 does not divide 16; 6 % t_steps(4) != 0
        with pytest.raises(ValueError, match="violates"):
            ps.step_pallas(st.u, st.v, pvec, 4, interpret=True, tz=tz)


def test_modeled_sim_traffic_fusion_wins():
    """The schedule-model traffic of a fused 512^3 10-step advance must
    undercut the roll floor by >= 2x (the PERF.md lever-1 claim the
    bench's traffic-model fallback now encodes)."""
    from scenery_insitu_tpu.sim import pallas_stencil as ps

    shape = (512, 512, 512)
    fused = ps.modeled_sim_traffic(shape, 10, fused=True)
    rolled = ps.modeled_sim_traffic(shape, 10, fused=False)
    assert rolled == 10 * 2 * 2 * 4.0 * 512 ** 3
    assert fused < rolled / 2.0


# ------------------------------------------------- bf16 marched volume


def _small_vol(grid=16, seed_steps=30):
    st = gs.multi_step(gs.GrayScott.init((grid,) * 3, n_seeds=2),
                       seed_steps)
    return Volume.centered(st.field, extent=2.0)


def test_render_dtype_threads_from_config():
    cfg = SliceMarchConfig(render_dtype="bf16", matmul_dtype="f32")
    spec = slicer.make_spec(Camera.create((0.0, 0.2, 2.5)), (16, 16, 16),
                            cfg)
    assert spec.render_dtype == "bf16"
    vol = _small_vol()
    assert slicer.permute_volume(vol, spec).dtype == jnp.bfloat16
    f32spec = slicer.make_spec(Camera.create((0.0, 0.2, 2.5)),
                               (16, 16, 16), SliceMarchConfig())
    assert slicer.permute_volume(vol, f32spec).dtype == jnp.float32
    with pytest.raises(ValueError, match="render_dtype"):
        SliceMarchConfig(render_dtype="f16")


def test_bf16_march_matches_f32():
    """The bf16 marched-volume copy must reproduce the f32 VDI within
    storage-rounding tolerance (accumulation stays f32 — only the volume
    values themselves are rounded once)."""
    vol = _small_vol()
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.3, 2.5), fov_y_deg=50.0, near=0.3,
                        far=20.0)
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    outs = {}
    for rdt in ("f32", "bf16"):
        cfg = SliceMarchConfig(scale=1.0, matmul_dtype="f32",
                               render_dtype=rdt)
        spec = slicer.make_spec(cam, vol.data.shape, cfg)
        vdi, _, _ = slicer.generate_vdi_mxu(vol, tf, cam, spec, vdi_cfg)
        outs[rdt] = np.asarray(vdi.color)
    assert np.isfinite(outs["bf16"]).all()
    # bf16 has ~3 decimal digits; color channels are O(1)
    np.testing.assert_allclose(outs["bf16"], outs["f32"], atol=0.05)
    # and the paths must actually differ (the cast really happened)
    assert np.abs(outs["bf16"] - outs["f32"]).max() > 0.0


def test_bf16_render_slices_matches_f32():
    vol = _small_vol()
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.2, 0.4, 2.5), fov_y_deg=50.0, near=0.3,
                        far=20.0)
    outs = {}
    for rdt in ("f32", "bf16"):
        cfg = SliceMarchConfig(scale=1.0, matmul_dtype="f32",
                               render_dtype=rdt)
        spec = slicer.make_spec(cam, vol.data.shape, cfg)
        axcam = slicer.make_axis_camera(vol, cam, spec)
        out = slicer.render_slices(vol, tf, axcam, spec)
        outs[rdt] = np.asarray(out.image)
    np.testing.assert_allclose(outs["bf16"], outs["f32"], atol=0.05)


def test_bf16_distributed_matches_f32():
    """The distributed rank-slab path casts before the halo exchange;
    the composited frame must stay within bf16 tolerance of f32."""
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_vdi_step_mxu, shard_volume)

    mesh = make_mesh(4)
    st = gs.multi_step(gs.GrayScott.init((16, 16, 16), n_seeds=2), 30)
    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.3, 2.5), fov_y_deg=50.0, near=0.3,
                        far=20.0)
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.full((3,), 2.0 / 16, jnp.float32)
    vdi_cfg = VDIConfig(max_supersegments=6, adaptive_iters=2)
    outs = {}
    for rdt in ("f32", "bf16"):
        cfg = SliceMarchConfig(scale=1.0, matmul_dtype="f32",
                               render_dtype=rdt)
        spec = slicer.make_spec(cam, (16, 16, 16), cfg, multiple_of=4)
        step = distributed_vdi_step_mxu(mesh, tf, spec, vdi_cfg)
        vdi, _ = step(shard_volume(st.field, mesh), origin, spacing, cam)
        outs[rdt] = np.asarray(vdi.color)
    np.testing.assert_allclose(outs["bf16"], outs["f32"], atol=0.05)


# ------------------------------------------------- on-device frame scan


def _session_cfg(extra=()):
    base = ["render.width=32", "render.height=24", "render.max_steps=24",
            "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
            "composite.max_output_supersegments=8",
            "composite.adaptive_iters=2", "sim.grid=[16,16,16]",
            "sim.steps_per_frame=2"]
    return FrameworkConfig().with_overrides(*(base + list(extra)))


def _collect(sess, frames):
    got = []
    sess.sinks.append(lambda i, p: got.append((i, p["vdi_color"].copy())))
    sess.run(frames)
    return got


def test_scan_frames_matches_eager_gather():
    """scan_frames must produce the same frame sequence as the eager
    loop (same sim ladder, same per-frame cameras), one launch per
    block — including a final partial block."""
    eager = InSituSession(_session_cfg(), mesh=make_mesh(2))
    eager.orbit_rate = 0.1
    scan = InSituSession(_session_cfg(["runtime.scan_frames=2"]),
                         mesh=make_mesh(2))
    scan.orbit_rate = 0.1
    fe = _collect(eager, 5)
    fs = _collect(scan, 5)
    assert [i for i, _ in fe] == [i for i, _ in fs] == list(range(5))
    for (_, a), (_, b) in zip(fe, fs):
        np.testing.assert_allclose(a, b, atol=1e-4)
    assert np.allclose(np.asarray(eager.camera.eye),
                       np.asarray(scan.camera.eye))
    assert scan.frame_index == 5


def test_scan_frames_matches_eager_mxu_temporal():
    extra = ["slicer.engine=mxu", "slicer.scale=1.0",
             "slicer.matmul_dtype=f32", "vdi.adaptive_mode=temporal",
             "mesh.num_devices=4"]
    eager = InSituSession(_session_cfg(extra))
    scan = InSituSession(_session_cfg(extra + ["runtime.scan_frames=2"]))
    fe = _collect(eager, 4)
    fs = _collect(scan, 4)
    assert len(fe) == len(fs) == 4
    for (_, a), (_, b) in zip(fe, fs):
        assert np.isfinite(b).all()
        np.testing.assert_allclose(a, b, atol=1e-3)
    # the temporal threshold state was carried across blocks
    assert len(scan._mxu_thr) == 1


def test_scan_frames_meta_matches_eager():
    """Per-frame metadata (index, view of the replayed camera) must be
    identical between the scan blocks and the eager loop."""
    metas_e, metas_s = [], []
    eager = InSituSession(_session_cfg(), mesh=make_mesh(2),
                          sinks=[lambda i, p: metas_e.append(p["meta"])])
    eager.orbit_rate = 0.2
    eager.run(4)
    scan = InSituSession(_session_cfg(["runtime.scan_frames=4"]),
                         mesh=make_mesh(2),
                         sinks=[lambda i, p: metas_s.append(p["meta"])])
    scan.orbit_rate = 0.2
    scan.run(4)
    for me, ms in zip(metas_e, metas_s):
        assert int(me.index) == int(ms.index)
        np.testing.assert_allclose(np.asarray(me.view),
                                   np.asarray(ms.view), atol=1e-6)


def test_scan_frames_unsupported_mode_falls_back():
    """Particle sessions have no traceable volume state — the session
    must log the downgrade and run the eager loop, not die."""
    logs = []
    cfg = _session_cfg(["sim.kind=lennard_jones", "sim.num_particles=32",
                        "sim.particle_radius=0.3",
                        "runtime.scan_frames=3"])
    sess = InSituSession(cfg, mesh=make_mesh(2), log=logs.append)
    payload = sess.run(2)
    assert payload["image"].shape == (4, 24, 32)
    assert any("falling back to the eager loop" in l for l in logs)


def test_scan_frames_regime_crossing_block_runs_eagerly():
    """A block whose camera ladder crosses march regimes cannot be
    scanned (the step is regime-specialized) — it must run eagerly and
    still produce every frame."""
    extra = ["slicer.engine=mxu", "slicer.scale=1.0",
             "slicer.matmul_dtype=f32", "mesh.num_devices=2",
             "runtime.scan_frames=6"]
    logs = []
    sess = InSituSession(_session_cfg(extra), log=logs.append)
    sess.orbit_rate = 0.6           # crosses a regime within 6 frames
    got = _collect(sess, 6)
    assert [i for i, _ in got] == list(range(6))
    assert all(np.isfinite(c).all() for _, c in got)
    assert any("regime crossing" in l for l in logs)


# ------------------------------------------------- pallas_seg satellites


def test_fold_chunk_packed_rejects_mixed_depth_forms():
    from scenery_insitu_tpu.ops import pallas_seg as psg

    k, h, w = 4, 8, 16
    packed = psg.init_seg_packed(k, h, w)
    rgba = jnp.zeros((2, 4, h, w), jnp.float32)
    t = jnp.zeros((2, h, w), jnp.float32)
    sk = jnp.zeros((2,), jnp.float32)
    ln = jnp.ones((h, w), jnp.float32)
    thr = jnp.float32(0.1)
    with pytest.raises(ValueError, match="cannot be mixed"):
        psg.fold_chunk_packed(packed, rgba, t0=t, t1=t, threshold=thr,
                              max_k=k, sk0=sk)
    with pytest.raises(ValueError, match="cannot be mixed"):
        psg.fold_chunk_packed(packed, rgba, t0=t, threshold=thr,
                              max_k=k, sk0=sk, sk1=sk, length=ln)
    with pytest.raises(ValueError, match="COMPLETE depth form"):
        psg.fold_chunk_packed(packed, rgba, threshold=thr, max_k=k,
                              sk0=sk, sk1=sk)
    with pytest.raises(ValueError, match="COMPLETE depth form"):
        psg.fold_chunk_packed(packed, rgba, t0=t, threshold=thr, max_k=k)
    # both complete forms still work (interpret mode)
    out = psg.fold_chunk_packed(packed, rgba, t0=t, t1=t, threshold=thr,
                                max_k=k, interpret=True)
    assert out[0].shape == (k, 4, h, w)
    out = psg.fold_chunk_packed(packed, rgba, threshold=thr, max_k=k,
                                sk0=sk, sk1=sk, length=ln, interpret=True)
    assert out[0].shape == (k, 4, h, w)
