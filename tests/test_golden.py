"""Golden-fixture regression tests: re-render the committed configs of
tests/golden/make_golden.py and compare against the committed images /
VDI arrays. A kernel change that shifts output breaks one of these with
the config name in the message (the mechanical version of the
reference's dump→reload→look-at-it validation loop, SURVEY.md §4.2).

Also pins the Vulkan reference-frame normalization protocol
(ops/vdi_convert: gamma / projection fix / y-flip) with exact unit
checks — the day a Vulkan render of the reference exists, comparing it
against `to_reference_frame(ours)` by PSNR is the whole procedure
(documented in PARITY.md)."""

import os

import numpy as np
import pytest

from tests.golden.make_golden import GOLDEN_DIR, build_all

_CACHE = {}


def _rendered():
    if "out" not in _CACHE:
        _CACHE["out"] = build_all(out_dir=None)
    return _CACHE["out"]


def _load_png(name):
    from PIL import Image

    return np.asarray(Image.open(
        os.path.join(GOLDEN_DIR, f"golden_{name}.png")), np.float32)


def _to_png_space(img_chw, gamma=2.2):
    from scenery_insitu_tpu.utils.image import to_display

    return np.asarray(to_display(np.asarray(img_chw), gamma), np.float32)


# reference_frame is already gamma-encoded by to_reference_frame, so its
# PNG round trip uses gamma=1.0 (exactly one encode in the stored pixels)
_PNG_GAMMA = {"reference_frame": 1.0}


@pytest.mark.parametrize("name", ["raycast_gather", "raycast_mxu",
                                  "vdi_decode", "novel_view",
                                  "vdi_gather_decode", "reference_frame"])
def test_golden_image(name):
    got = _to_png_space(_rendered()[name], _PNG_GAMMA.get(name, 2.2))
    want = _load_png(name)
    assert got.shape == want.shape, (
        f"{name}: shape {got.shape} != committed {want.shape}")
    # 8-bit space: tiny FP drift tolerated, real regressions are far above
    maxdiff = float(np.abs(got - want).max())
    assert maxdiff <= 3.0, (
        f"golden image {name!r} drifted: max 8-bit diff {maxdiff:.1f} "
        "(if the change is intentional, regenerate via "
        "tests/golden/make_golden.py and commit)")


def test_golden_vdi_arrays():
    out = _rendered()
    with np.load(os.path.join(GOLDEN_DIR, "golden_vdi.npz")) as z:
        np.testing.assert_allclose(out["vdi_color"], z["color"],
                                   rtol=1e-4, atol=1e-5,
                                   err_msg="composited VDI color drifted")
        got_d, want_d = out["vdi_depth"], z["depth"]
        live = np.isfinite(want_d)
        assert (np.isfinite(got_d) == live).all(), "VDI slot liveness"
        np.testing.assert_allclose(got_d[live], want_d[live],
                                   rtol=1e-4, atol=1e-5,
                                   err_msg="composited VDI depth drifted")


def test_pallas_fold_matches_golden():
    """The Pallas fold schedule must reproduce the committed (XLA-fold)
    VDI fixture — pins schedule-independence to a committed artifact.
    Shares make_golden.build_vdi so the configs cannot drift apart."""
    from tests.golden.make_golden import build_vdi

    comp, _, _ = build_vdi(fold="pallas")
    with np.load(os.path.join(GOLDEN_DIR, "golden_vdi.npz")) as z:
        np.testing.assert_allclose(np.asarray(comp.color), z["color"],
                                   rtol=2e-4, atol=2e-5)


# ------------------------- Vulkan-convention converters (exact semantics)


def test_vulkan_projection_fix_semantics():
    """fix @ P maps GL NDC (y up, z in [-1,1]) to Vulkan NDC (y down,
    z in [0,1]) — the matrix of DistributedVolumes.kt:67-79."""
    import jax.numpy as jnp

    from scenery_insitu_tpu.core.camera import Camera, projection_matrix
    from scenery_insitu_tpu.ops.vdi_convert import (projection_gl_to_vulkan,
                                                    projection_vulkan_to_gl)

    cam = Camera.create((0.2, 0.4, 3.0), fov_y_deg=50.0, near=0.5, far=10.0)
    p_gl = projection_matrix(cam, 64, 48)
    p_vk = projection_gl_to_vulkan(p_gl)

    def ndc(p, v):
        c = np.asarray(p @ jnp.asarray(v, jnp.float32))
        return c[:3] / c[3]

    for point in ([0.1, 0.2, -0.6, 1.0], [-0.3, 0.1, -5.0, 1.0]):
        g = ndc(p_gl, point)
        v = ndc(p_vk, point)
        np.testing.assert_allclose(v[0], g[0], rtol=1e-6)        # x same
        np.testing.assert_allclose(v[1], -g[1], rtol=1e-6)       # y flipped
        np.testing.assert_allclose(v[2], (g[2] + 1.0) / 2.0,     # z [0,1]
                                   rtol=1e-5)
        assert 0.0 <= v[2] <= 1.0
    # exact round trip
    np.testing.assert_allclose(np.asarray(projection_vulkan_to_gl(p_vk)),
                               np.asarray(p_gl), atol=1e-6)


def test_gamma_and_flip_roundtrip():
    from scenery_insitu_tpu.ops.vdi_convert import (flip_y, gamma_decode,
                                                    gamma_encode,
                                                    to_reference_frame)

    rng = np.random.default_rng(0)
    img = rng.random((4, 8, 6)).astype(np.float32)
    enc = np.asarray(gamma_encode(img))
    # alpha untouched, rgb = v^(1/2.2)
    np.testing.assert_allclose(enc[3], img[3])
    np.testing.assert_allclose(enc[:3], img[:3] ** (1 / 2.2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gamma_decode(enc)), img,
                               rtol=1e-4, atol=1e-6)
    flipped = np.asarray(flip_y(img))
    np.testing.assert_array_equal(flipped, img[:, ::-1, :])
    ref = np.asarray(to_reference_frame(img))
    np.testing.assert_allclose(ref, np.asarray(flip_y(gamma_encode(img))))
