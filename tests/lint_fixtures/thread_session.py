# SITPU-THREAD session-plumbing fixture: one compliant call, one call
# that forgets a knob, one that drops the config object. Parsed by the
# linter only (the builder names resolve against the fixture pipeline).


def build_good(sess):
    step = distributed_knob_step(
        sess.mesh, sess.tf, 64, 48,
        exchange=sess.cfg.composite.exchange,
        wire=sess.cfg.composite.wire,
        schedule=sess.cfg.composite.schedule,
        wave_tiles=sess.cfg.composite.wave_tiles,
        ring_slots=sess.cfg.composite.ring_slots,
        k_budget=sess.cfg.composite.k_budget,
        topology=sess.cfg.topology)
    obj = distributed_obj_step(sess.mesh, sess.tf, sess.cfg.vdi,
                               sess.cfg.composite,
                               topology=sess.cfg.topology)
    return step, obj


def build_bad(sess):
    # forgets wire= — the builder default silently masks cfg.composite.wire
    # (and forgets topology= — a hierarchical mesh would composite flat)
    step = distributed_knob_step(
        sess.mesh, sess.tf, 64, 48,
        exchange=sess.cfg.composite.exchange,
        schedule=sess.cfg.composite.schedule,
        wave_tiles=sess.cfg.composite.wave_tiles,
        ring_slots=sess.cfg.composite.ring_slots,
        k_budget=sess.cfg.composite.k_budget)
    # never binds comp_cfg — the builder default runs, not the session's
    obj = distributed_obj_step(sess.mesh, sess.tf,
                               topology=sess.cfg.topology)
    return step, obj
