# SITPU-THREAD good fixture: the two compliant builder shapes. Parsed by
# the linter only.


def distributed_obj_step(mesh, tf, vdi_cfg=None, comp_cfg=None):
    """Whole-object threading: comp_cfg flows into the composite call —
    every current and future knob rides along."""
    def step(data, cam):
        return composite_cfg(march(data, cam), comp_cfg)
    return step


def distributed_knob_step(mesh, tf, width, height,
                          exchange="all_to_all", wire="f32",
                          schedule="frame", wave_tiles=4,
                          ring_slots=0, k_budget="static"):
    """Explicit-knob threading: the full matrix accepted and forwarded."""
    def step(data, cam):
        return composite(march(data, cam), exchange=exchange, wire=wire,
                         schedule=schedule, wave_tiles=wave_tiles,
                         ring_slots=ring_slots, k_budget=k_budget)
    return step


def march(data, cam):
    return data


def composite(frag, **kw):
    return frag


def composite_cfg(frag, cfg):
    return frag
