# SITPU-THREAD good fixture: the two compliant builder shapes. Parsed by
# the linter only.


def distributed_obj_step(mesh, tf, vdi_cfg=None, comp_cfg=None,
                         topology=None):
    """Whole-object threading: comp_cfg flows into the composite call —
    every current and future knob rides along — and the mesh topology is
    resolved, not dropped."""
    topo = resolve_topology(mesh, topology)

    def step(data, cam):
        return composite_cfg(march(data, cam), comp_cfg, topo)
    return step


def distributed_knob_step(mesh, tf, width, height,
                          exchange="all_to_all", wire="f32",
                          schedule="frame", wave_tiles=4,
                          ring_slots=0, k_budget="static",
                          topology=None):
    """Explicit-knob threading: the full matrix accepted and forwarded."""
    topo = resolve_topology(mesh, topology)

    def step(data, cam):
        return composite(march(data, cam), exchange=exchange, wire=wire,
                         schedule=schedule, wave_tiles=wave_tiles,
                         ring_slots=ring_slots, k_budget=k_budget,
                         topo=topo)
    return step


def march(data, cam):
    return data


def composite(frag, **kw):
    return frag


def composite_cfg(frag, cfg):
    return frag


def resolve_topology(mesh, topology):
    return topology
