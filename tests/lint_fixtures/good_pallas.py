# SITPU-PALLAS good fixture: the same kernel behind a compile probe,
# with a divisibility guard and a (1, 1) SMEM scalar block. Parsed by
# the linter only.
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_H = 8
TILE_W = 128


def _kernel(x_ref, o_ref, s_ref):
    o_ref[...] = x_ref[...] * 2.0
    s_ref[0, 0] = jnp.max(x_ref[...])


def double_chunk(x):
    h, w = x.shape
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    smem = pl.BlockSpec((1, 1), lambda i: (i, 0),
                        memory_space=pltpu.SMEM)
    return pl.pallas_call(
        _kernel, grid=(h // TILE_H,),
        in_specs=[pl.BlockSpec((TILE_H, w), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((TILE_H, w), lambda i: (i, 0)), smem],
        out_shape=[jax.ShapeDtypeStruct((h, w), jnp.float32),
                   jax.ShapeDtypeStruct((h // TILE_H, 1), jnp.float32)],
    )(x)


_PROBE: dict = {}


def double_compile_ok(h: int = TILE_H, w: int = TILE_W) -> bool:
    """One-time Mosaic-acceptance probe for `double_chunk`."""
    key = (jax.default_backend(), int(h), int(w))
    ok = _PROBE.get(key)
    if ok is None:
        try:
            sds = jax.ShapeDtypeStruct((h, w), jnp.float32)
            jax.jit(double_chunk).lower(sds).compile()
            ok = True
        except Exception:
            from scenery_insitu_tpu import obs

            obs.degrade("fixture.double_fold", "pallas", "xla",
                        f"Mosaic rejected double_chunk at {h}x{w}")
            ok = False
        _PROBE[key] = ok
    return ok
