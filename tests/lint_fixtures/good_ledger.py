# SITPU-LEDGER good fixture: the same fallback shapes, ledgered (or
# legitimately exempt). Parsed by the linter only.
from scenery_insitu_tpu import obs


def load_codec():
    try:
        import fastcodec
        return fastcodec
    except ImportError:
        obs.degrade("fixture.codec", "fastcodec", "slowcodec",
                    "fastcodec not installed")
        import slowcodec
        return slowcodec


def pick_backend(data):
    try:
        result = fast_path(data)
    except Exception as e:
        obs.degrade("fixture.backend", "fast", "slow", str(e)[:80])
        result = slow_path(data)
    return result


def have_turbo():
    try:
        import turbo  # noqa: F401
        return True
    except ImportError:
        return False


def run(data):
    if have_turbo():
        return turbo_run(data)
    obs.degrade("fixture.turbo", "turbo", "plain", "turbo not installed",
                warn=False)
    return plain_run(data)


def strict(data):
    # re-raising handlers propagate the failure — not a fallback
    try:
        return fast_path(data)
    except Exception as e:
        raise RuntimeError("fast path is mandatory here") from e


def suppressed(data):
    try:
        return fast_path(data)
    except Exception:  # sitpu-lint: disable=SITPU-LEDGER
        # justified inline: covered by the caller's ledger entry
        return slow_path(data)


def fast_path(data):
    return data


def slow_path(data):
    return data


def turbo_run(data):
    return data


def plain_run(data):
    return data
