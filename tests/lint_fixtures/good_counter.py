# SITPU-COUNTER good fixture: the same shapes done right — registered
# literals, names threaded through *_counter parameters. Parsed by the
# linter only.
import itertools


def render(rec, data):
    rec.count("frame_scan_builds")
    return data


def exchange_ring(rec, hops, hop_counter="ring_steps_built"):
    # dynamic name is fine when it arrives via a *_counter-suffixed
    # parameter whose default (and every literal override) is registered
    rec.count(hop_counter, hops)
    return hops


def relabel(rec, hops):
    return exchange_ring(rec, hops, hop_counter="dcn_hops_built")


def suppressed(rec, metric):
    rec.count(metric)  # sitpu-lint: disable=SITPU-COUNTER
    return metric


def fine(rec):
    seq = itertools.count(1)
    return next(seq)
