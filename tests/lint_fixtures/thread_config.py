# SITPU-THREAD fixture config: a mini CompositeConfig whose dataclass
# fields DERIVE the knob matrix (the checker must not hardcode knob
# names). Parsed by the linter only.
from dataclasses import dataclass


@dataclass(frozen=True)
class CompositeConfig:
    max_output_supersegments: int = 20
    adaptive: bool = True
    adaptive_iters: int = 6
    backend: str = "auto"
    exchange: str = "all_to_all"
    ring_slots: int = 0
    wire: str = "f32"
    schedule: str = "frame"
    wave_tiles: int = 4
    k_budget: str = "static"
    k_budget_min: int = 4
