# SITPU-TRACE good fixture: the same shapes written device-safe. Parsed
# by the linter only.
import jax
import jax.numpy as jnp
import numpy as np

_WEIGHTS = jnp.array([0.25, 0.5, 0.25])     # hoisted out of the scan


def build_step(cfg):
    def step(field, cam):
        # static config branch: fine (cfg is host configuration)
        if cfg.threshold > 0:
            field = jnp.where(field.max() > cfg.threshold,
                              field * 0.5, field)
        # shape queries on traced values are trace-time constants
        d, h, w = field.shape
        if h % 8:
            field = field[:, : h - h % 8]
        # None-checks are pytree structure, not traced booleans
        if cam is None:
            cam = jnp.zeros((3,))
        return field * (1.0 / (d * h * w))

    return jax.jit(step)


def scan_loop(frames):
    def body(carry, _):
        state = carry * _WEIGHTS.sum()
        return state, state

    def run(state):
        return jax.lax.scan(body, state, None, length=frames)

    return jax.jit(run)


def host_report(field_host):
    # NOT a traced context: eager host code may convert freely
    arr = np.asarray(field_host)
    return float(arr.mean())


def good_static(field, scale, mode):
    return field * scale


good_static_jit = jax.jit(good_static, static_argnames=("mode",))
