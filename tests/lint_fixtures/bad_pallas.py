# SITPU-PALLAS bad fixture: a kernel entry with no compile probe, no
# divisibility handling, and a mis-shaped SMEM scalar output. Parsed by
# the linter only — never imported or executed.
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_H = 8
TILE_W = 128


def _kernel(x_ref, o_ref, s_ref):
    o_ref[...] = x_ref[...] * 2.0
    s_ref[0, 0] = jnp.max(x_ref[...])


def double_chunk(x):
    # no % guard / padding: h not a multiple of TILE_H floors the grid
    h, w = x.shape
    # SMEM scalar output shaped (TILE_H, 1) instead of (1, 1)
    smem = pl.BlockSpec((TILE_H, 1), lambda i: (i, 0),
                        memory_space=pltpu.SMEM)
    return pl.pallas_call(
        _kernel, grid=(h // TILE_H,),
        in_specs=[pl.BlockSpec((TILE_H, w), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((TILE_H, w), lambda i: (i, 0)), smem],
        out_shape=[jax.ShapeDtypeStruct((h, w), jnp.float32),
                   jax.ShapeDtypeStruct((h // TILE_H, 1), jnp.float32)],
    )(x)
