# SITPU-LEDGER bad fixture: behavior-changing fallbacks with no ledger
# entry. Parsed by the linter only — never imported or executed.


def load_codec():
    try:
        import fastcodec
        return fastcodec
    except ImportError:
        # swaps the codec implementation silently — must degrade()
        import slowcodec
        return slowcodec


def pick_backend(data):
    try:
        result = fast_path(data)
    except Exception as e:
        print(f"fast path failed ({e}); using slow path")
        result = slow_path(data)
    return result


def have_turbo():
    # probe predicate: returning a constant from the handler is FINE
    # here — the caller owns the fallback decision
    try:
        import turbo  # noqa: F401
        return True
    except ImportError:
        return False


def run(data):
    # consults the probe, silently picks an implementation, no ledger
    if have_turbo():
        return turbo_run(data)
    return plain_run(data)


def fast_path(data):
    return data


def slow_path(data):
    return data


def turbo_run(data):
    return data


def plain_run(data):
    return data
