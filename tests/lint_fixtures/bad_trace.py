# SITPU-TRACE bad fixture: host-sync / retrace hazards inside traced
# code. Parsed by the linter only — never imported or executed.
import jax
import jax.numpy as jnp
import numpy as np


def build_step(cfg):
    def step(field, cam):
        # Python `if` on a traced comparison: trace-time error / retrace
        if field.max() > cfg.threshold:
            field = field * 0.5
        # host-sync concretization of a traced value
        peak = float(field.max())
        # host pull inside compiled code
        host = np.asarray(field)
        return field + peak + host.mean()

    return jax.jit(step)


def scan_loop(frames):
    def body(carry, _):
        state = carry
        # per-iteration literal re-materialization inside the scan body
        weights = jnp.array([0.25, 0.5, 0.25])
        state = state * weights.sum()
        return state, state

    def run(state):
        return jax.lax.scan(body, state, None, length=frames)

    return jax.jit(run)


def bad_static(field, scale, mode):
    return field * scale


# names a parameter bad_static() does not have
bad_static_jit = jax.jit(bad_static, static_argnames=("mode", "engine"))
