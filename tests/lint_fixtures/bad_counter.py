# SITPU-COUNTER bad fixture: counter names the catalog cannot account
# for. Parsed by the linter only — never imported or executed.
import itertools


def render(rec, data):
    # C1: literal name that is not in obs.counter_registry()
    rec.count("frames_rendered_totally_unregistered")
    return data


def exchange(rec, hops, metric):
    # C2: dynamic name that is not a *_counter-suffixed parameter of
    # the enclosing function — the catalog cannot see it
    rec.count(metric, hops)
    return hops


def build(rec, steps, step_counter="fixture_unregistered_steps"):
    # C1 via the *_counter-parameter default: the default string is a
    # counter name and it is not registered
    rec.count(step_counter, steps)
    return steps


def relabel(rec, hops):
    # C1 via a *_counter keyword literal: relabels the shared machinery
    # onto an unregistered name
    return exchange_ring(rec, hops, hop_counter="fixture_unregistered_hops")


def fine(rec):
    # non-Recorder count() calls are out of scope
    seq = itertools.count(1)
    return next(seq)


def exchange_ring(rec, hops, hop_counter="ring_steps_built"):
    rec.count(hop_counter, hops)
    return hops
