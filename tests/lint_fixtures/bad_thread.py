# SITPU-THREAD bad fixture: distributed step builders that drop knobs.
# Parsed by the linter only.


def distributed_bad_step(mesh, tf, width, height,
                         exchange="all_to_all", wire="f32",
                         schedule="frame", wave_tiles=4,
                         ring_slots=0, k_budget="static"):
    """Accepts the full knob matrix but the ``wire`` forwarding has been
    DELETED (the acceptance-criteria demo: this is exactly what removing
    ``wire=...`` from a real builder's composite call looks like)."""
    def step(data, cam):
        frag = march(data, cam)
        return composite(frag, exchange=exchange,
                         schedule=schedule, wave_tiles=wave_tiles,
                         ring_slots=ring_slots, k_budget=k_budget)
    return step


def distributed_missing_step(mesh, tf, width, height,
                             exchange="all_to_all"):
    """Accepts only one knob of the matrix — every other knob is
    invisible to callers and silently pinned to the composite default."""
    def step(data, cam):
        return composite(march(data, cam), exchange=exchange)
    return step


def distributed_dropped_obj_step(mesh, tf, comp_cfg=None):
    """Takes the whole config object and then never threads it."""
    def step(data, cam):
        return composite_default(march(data, cam))
    return step


def march(data, cam):
    return data


def composite(frag, **kw):
    return frag


def composite_default(frag):
    return frag
