"""The asynchronous delivery plane (ISSUE 19; runtime/delivery.py,
docs/PERF.md "Async delivery"): ordering contract, bitwise parity with
the serial path, worker-thread quarantine + reset, shed/backpressure
policies, teardown drains, HBM release, and the parallel per-tile
encode byte-identity contracts."""

import gc
import threading
import time
import weakref

import numpy as np
import pytest

from scenery_insitu_tpu import obs
from scenery_insitu_tpu.config import DeliveryConfig, FrameworkConfig
from scenery_insitu_tpu.parallel.mesh import make_mesh
from scenery_insitu_tpu.runtime.delivery import DeliveryExecutor
from scenery_insitu_tpu.runtime.failsafe import SinkGuard
from scenery_insitu_tpu.runtime.session import InSituSession


def _cfg(**kw):
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=24",
        "vdi.max_supersegments=6", "vdi.adaptive_iters=2",
        "composite.max_output_supersegments=8",
        "composite.adaptive_iters=2", "sim.grid=[16,16,16]",
        "sim.steps_per_frame=2", "runtime.stats_window=2")
    return cfg.with_overrides(*[f"{k}={v}" for k, v in kw.items()])


class _CaptureSink:
    """Frame sink recording (frame, color bytes, thread name) — the
    cross-run bitwise comparator."""

    def __init__(self):
        self.lock = threading.Lock()
        self.calls = []

    def __call__(self, index, payload):
        with self.lock:
            self.calls.append(
                (int(payload["frame"]),
                 np.asarray(payload["vdi_color"]).tobytes(),
                 threading.current_thread().name))


# ---------------------------------------------------------------- parity

def test_async_session_bitwise_matches_serial():
    """delivery.enabled must change WHERE sinks run, never what they
    see: same frame order, bit-identical payload bytes, and the
    delivery counters account for every frame."""
    runs = {}
    for name, ovs in (("serial", {}),
                      ("async", {"delivery.enabled": "true",
                                 "runtime.pipeline_depth": "2"})):
        sink = _CaptureSink()
        sess = InSituSession(_cfg(**ovs), mesh=make_mesh(4),
                             sinks=[sink])
        sess.run(3)
        runs[name] = (sink.calls, dict(sess.obs.counters))
    serial, async_ = runs["serial"][0], runs["async"][0]
    assert [c[0] for c in serial] == [c[0] for c in async_] == [0, 1, 2]
    for (_, sb, _), (_, ab, _) in zip(serial, async_):
        assert sb == ab
    # serial ran inline on the loop thread, async on the worker
    assert all(th != "delivery-worker" for _, _, th in serial)
    assert all(th == "delivery-worker" for _, _, th in async_)
    counters = runs["async"][1]
    assert counters["delivery_frames_enqueued"] == 3
    assert counters["delivery_frames_delivered"] == 3
    assert counters["delivery_frames_inflight"] == 0
    assert counters.get("delivery_sheds", 0) == 0


def test_pipeline_depth_without_delivery_is_bitwise():
    """pipeline_depth alone (async fetch, inline sinks) must be
    bit-identical to the depth-1 default, frames in order."""
    runs = []
    for depth in (1, 3):
        sink = _CaptureSink()
        sess = InSituSession(
            _cfg(**{"runtime.pipeline_depth": str(depth)}),
            mesh=make_mesh(4), sinks=[sink])
        sess.run(4)
        runs.append(sink.calls)
    assert [c[0] for c in runs[0]] == [c[0] for c in runs[1]]
    for (_, b0, _), (_, b1, _) in zip(*runs):
        assert b0 == b1


# ------------------------------------------------------------- ordering

def test_tile_ordering_contract_async():
    """Ordering contract under async delivery: within a frame the tile
    payloads arrive in ascending column order, THEN the frame sinks run
    (the frame closes after its tiles); across frames strictly FIFO."""
    events, lock = [], threading.Lock()

    def tile_sink(index, payload):
        with lock:
            events.append(("tile", int(payload["frame"]),
                           int(payload["tile"]), int(payload["col0"])))

    def frame_sink(index, payload):
        with lock:
            events.append(("frame", int(payload["frame"]), None, None))

    cfg = _cfg(**{"composite.schedule": "waves",
                  "delivery.enabled": "true",
                  "runtime.pipeline_depth": "2"})
    sess = InSituSession(cfg, mesh=make_mesh(4), sinks=[frame_sink])
    sess.tile_sinks.append(tile_sink)
    sess.run(3)

    frames_seen = []
    last_tile = {}
    for kind, f, t, col0 in events:
        if kind == "tile":
            assert f not in frames_seen, "tile after its frame closed"
            if f in last_tile:
                assert t == last_tile[f][0] + 1, "tiles out of order"
                assert col0 > last_tile[f][1], "columns not ascending"
            else:
                assert t == 0
            last_tile[f] = (t, col0)
        else:
            frames_seen.append(f)
    assert frames_seen == [0, 1, 2]
    assert set(last_tile) == {0, 1, 2}


# --------------------------------------------- quarantine on the worker

def test_worker_thread_quarantine_and_reset():
    """SinkGuard shared with the delivery worker: a sink failing on the
    worker thread quarantines after max_failures, the ledger records
    it, reset() re-admits it and it runs again — all off the loop
    thread."""
    obs.clear_ledger()
    bad_calls, good_calls = [], []

    def bad(index, payload):
        bad_calls.append(index)
        raise ValueError("sink bug")

    def good(index, payload):
        good_calls.append(index)

    guard = SinkGuard(max_failures=2)
    ex = DeliveryExecutor(DeliveryConfig(enabled=True), guard, [],
                          [bad, good])
    try:
        for i in range(4):
            ex.submit(i, {"frame": i})
        assert ex.drain(timeout_s=30.0)
        # bad failed twice then quarantined; good never missed a frame
        assert guard.is_quarantined(bad)
        assert bad_calls == [0, 1]
        assert good_calls == [0, 1, 2, 3]
        assert any(e["component"] == "session.sink"
                   and e["to"] == "quarantined" for e in obs.ledger())
        # operator reset: re-admitted, runs again on the worker
        assert guard.reset(bad)
        assert not guard.is_quarantined(bad)
        ex.submit(4, {"frame": 4})
        assert ex.drain(timeout_s=30.0)
        assert 4 in bad_calls
        assert any(e["component"] == "session.sink"
                   and e["to"] == "re-admitted" for e in obs.ledger())
    finally:
        ex.close()


# ------------------------------------------------------- overflow policy

def test_block_policy_is_lossless():
    done = []

    def slow(index, payload):
        time.sleep(0.02)
        done.append(index)

    ex = DeliveryExecutor(
        DeliveryConfig(enabled=True, queue_frames=2, overflow="block"),
        SinkGuard(), [], [slow])
    try:
        for i in range(8):
            assert ex.submit(i, {"frame": i})
        assert ex.drain(timeout_s=30.0)
    finally:
        ex.close()
    assert done == list(range(8))
    assert ex.sheds == 0


def test_drop_oldest_sheds_and_never_blocks():
    obs.clear_ledger()
    done = []

    def slow(index, payload):
        time.sleep(0.05)
        done.append(index)

    rec = obs.get_recorder()
    base_sheds = rec.counters.get("delivery_sheds", 0)
    ex = DeliveryExecutor(
        DeliveryConfig(enabled=True, queue_frames=1,
                       overflow="drop_oldest"),
        SinkGuard(), [], [slow])
    try:
        t0 = time.monotonic()
        results = [ex.submit(i, {"frame": i}) for i in range(10)]
        # submissions return instantly — the loop never waits on the sink
        assert time.monotonic() - t0 < 0.25
        assert ex.drain(timeout_s=30.0)
    finally:
        ex.close()
    assert ex.sheds > 0 and not all(results)
    assert ex.delivered + ex.sheds == ex.enqueued == 10
    # survivors strictly FIFO, no duplicates
    assert done == sorted(done) and len(set(done)) == len(done)
    assert rec.counters.get("delivery_sheds", 0) - base_sheds == ex.sheds
    assert any(e["component"] == "delivery.shed" for e in obs.ledger())


# ------------------------------------------------------------- teardown

def test_drain_timeout_abandons_and_ledgers():
    obs.clear_ledger()
    release = threading.Event()

    def wedged(index, payload):
        release.wait(30.0)

    ex = DeliveryExecutor(
        DeliveryConfig(enabled=True, queue_frames=8),
        SinkGuard(), [], [wedged])
    try:
        for i in range(3):
            ex.submit(i, {"frame": i})
        assert ex.drain(timeout_s=0.2) is False
        assert any(e["component"] == "delivery.drain"
                   for e in obs.ledger())
    finally:
        release.set()
        ex.close(timeout_s=1.0)


def test_crash_path_drains_delivery():
    """An exception on the loop thread mid-run must still drain the
    delivery queue (the flight-recorder teardown path): every frame the
    device already paid for is delivered exactly once, no duplicates."""
    sink = _CaptureSink()
    sess = InSituSession(
        _cfg(**{"delivery.enabled": "true",
                "runtime.pipeline_depth": "2"}),
        mesh=make_mesh(4), sinks=[sink])
    calls = {"n": 0}
    orig = sess.slo.observe

    def bomb(name, *a, **kw):
        # loop-thread observations only — the delivery worker shares
        # this SLOEngine for delivery_lag_ms and must stay healthy
        if name == "frame_ms":
            calls["n"] += 1
            if calls["n"] >= 3:
                raise RuntimeError("mid-run failure")
        return orig(name, *a, **kw)

    sess.slo.observe = bomb
    with pytest.raises(RuntimeError, match="mid-run failure"):
        sess.run(6)
    delivered = [c[0] for c in sink.calls]
    # whatever was enqueued before the crash arrived, in order, once
    assert delivered == sorted(delivered)
    assert len(set(delivered)) == len(delivered)
    assert len(delivered) >= 1
    counters = sess.obs.counters
    assert counters["delivery_frames_delivered"] == len(delivered)
    assert counters["delivery_frames_inflight"] == 0


# ------------------------------------------------------------ HBM release

def test_device_buffers_released_after_retire():
    """The depth-k pipeline must not pin device frames: once a frame is
    retired (host copy landed, sinks fed) its device buffers die — the
    pre-PR-19 eager loop kept an extra frame alive in its ``pending``
    slot. Weakrefs on every retired entry's jax leaves must all clear
    by the end of the run."""
    import jax

    refs = []
    sess = InSituSession(
        _cfg(**{"runtime.pipeline_depth": "2",
                "delivery.enabled": "true"}),
        mesh=make_mesh(8), sinks=[lambda i, p: None])
    orig = sess._retire

    def spy(entry, fetch, payload):
        refs.extend(weakref.ref(leaf)
                    for leaf in jax.tree_util.tree_leaves(entry[1])
                    if isinstance(leaf, jax.Array))
        return orig(entry, fetch, payload)

    sess._retire = spy
    payload = sess.run(4)
    assert refs, "retire spy saw no device leaves"
    del payload          # np views may pin the final frame's buffers
    gc.collect()
    alive = [r for r in refs if r() is not None]
    assert not alive, f"{len(alive)}/{len(refs)} device leaves pinned"


# ------------------------------------------- parallel per-tile encode

def test_save_vdi_workers_byte_identical(tmp_path):
    from scenery_insitu_tpu.core.vdi import VDI
    from scenery_insitu_tpu.io.vdi_io import save_vdi

    rng = np.random.default_rng(3)
    vdi = VDI(rng.random((6, 4, 24, 32)).astype(np.float32),
              np.sort(rng.random((6, 2, 24, 32)).astype(np.float32),
                      axis=1))
    paths = {}
    for w in (1, 4):
        p = str(tmp_path / f"w{w}.npz")
        save_vdi(p, vdi, codec="zlib", workers=w)
        paths[w] = open(p, "rb").read()
    assert paths[1] == paths[4]


def test_publisher_delta_forces_serial_encode():
    """Parallel per-tile encode is stateless; the temporal-delta
    encoder is stateful per tile — requesting both must degrade to
    serial with a ``delivery.encode`` ledger row, not race."""
    from scenery_insitu_tpu.config import DeltaConfig
    from scenery_insitu_tpu.runtime.streaming import VDIPublisher

    obs.clear_ledger()
    pub = VDIPublisher("tcp://127.0.0.1:0", codec="zlib",
                       precision="qpack8",
                       delta=DeltaConfig(enabled=True),
                       encode_workers=4)
    try:
        assert pub.encode_workers == 1
        assert any(e["component"] == "delivery.encode"
                   for e in obs.ledger())
    finally:
        pub.close()
