"""Regression sentinel (benchmarks/regression_gate.py): the committed
artifacts must self-check clean, a synthetic perturbation must be
flagged, and the degrade paths (unknown schema, missing baseline) must
land in the ledger instead of failing the world. No jax needed."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import regression_gate as rg  # noqa: E402

from scenery_insitu_tpu import obs  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_ledger():
    obs.clear_ledger()
    yield
    obs.clear_ledger()


def _write(d, name, doc):
    # fresh artifacts go OUTSIDE the results dir — committed_baseline
    # scans every *.json there, and a fresh file inside would become its
    # own (lexicographically newest) baseline
    path = os.path.join(str(d), name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _hier(value):
    return {"metric": "hier_weak_scaling_test", "value": value}


# ------------------------------------------------------- committed truth

def test_self_check_committed_baselines_pass():
    """The acceptance half the CI lane runs: every committed artifact of
    a known family still clears its floors."""
    failures, report = rg.self_check()
    assert failures == [], failures
    assert report["ok"] and report["families"]
    # the families the repo has actually landed artifacts for
    assert {"lod_ladder", "delta_ab", "hier_weak_scaling",
            "serve_bench", "scenario_bench"} <= set(report["families"])


def test_main_self_check_exit_code():
    assert rg.main(["--json"]) == 0


# --------------------------------------------------- synthetic regression

def test_synthetic_perturbation_is_flagged(tmp_path):
    """The other acceptance half: perturb a gated key beyond its noise
    band in the worse direction and the gate must fail."""
    _write(tmp_path, "base_r1.json", _hier(2.0))
    # a 40% drop blows through the 35% NOISY band
    fresh = _write(tmp_path / "out", "fresh.json", _hier(1.2))
    failures, report = rg.check_fresh(fresh, results_dir=str(tmp_path))
    assert any("regressed" in f for f in failures), failures
    assert report["family"] == "hier_weak_scaling"
    assert rg.main(["--fresh", fresh, "--results-dir", str(tmp_path)]) == 1


def test_within_band_move_passes(tmp_path):
    _write(tmp_path, "base_r1.json", _hier(2.0))
    fresh = _write(tmp_path / "out", "fresh.json", _hier(1.9))  # 5% move
    failures, _ = rg.check_fresh(fresh, results_dir=str(tmp_path))
    assert failures == []


def test_floor_violation_flagged_even_vs_matching_baseline(tmp_path):
    """A floor is absolute: a baseline that is itself under the floor
    does not grandfather the fresh artifact in."""
    _write(tmp_path, "base_r1.json", _hier(0.5))
    fresh = _write(tmp_path / "out", "fresh.json", _hier(0.5))   # floor is 0.7
    failures, _ = rg.check_fresh(fresh, results_dir=str(tmp_path))
    assert any("floor" in f for f in failures), failures


def test_key_vanishing_from_fresh_artifact_flagged(tmp_path):
    """A fresh artifact that silently stops reporting a gated key is a
    regression, not a pass."""
    _write(tmp_path, "base_r1.json", {
        "kind": "delta_ab",
        "scenes": {"slab": {"wire": {"bytes_ratio": 0.5}}}})
    fresh = _write(tmp_path / "out", "fresh.json",
                   {"kind": "delta_ab", "scenes": {}})
    failures, _ = rg.check_fresh(fresh, results_dir=str(tmp_path))
    assert any("missing from fresh" in f for f in failures), failures


# ------------------------------------------------------- degrade ledger

def test_unknown_schema_is_skipped_and_ledgered(tmp_path):
    fresh = _write(tmp_path / "out", "fresh.json", {"hello": "world"})
    failures, report = rg.check_fresh(fresh, results_dir=str(tmp_path))
    assert failures == [] and report["family"] is None
    assert any(e["component"] == "regression.artifact"
               for e in obs.ledger()), obs.ledger()


def test_missing_baseline_degrades_to_record_only(tmp_path):
    fresh = _write(tmp_path / "out", "fresh.json", _hier(0.9))
    failures, report = rg.check_fresh(fresh, results_dir=str(tmp_path))
    assert failures == [] and report["baseline"] is None
    assert any(e["component"] == "regression.baseline"
               for e in obs.ledger()), obs.ledger()


def test_trajectory_row_recorded(tmp_path):
    _write(tmp_path, "base_r1.json", _hier(2.0))
    fresh = _write(tmp_path / "out", "fresh.json", _hier(1.95))
    assert rg.main(["--fresh", fresh, "--record",
                    "--results-dir", str(tmp_path)]) == 0
    rows = [json.loads(ln) for ln in
            open(tmp_path / "trajectory.jsonl")]
    assert rows and rows[-1]["type"] == "trajectory"
    assert rows[-1]["family"] == "hier_weak_scaling"
    assert rows[-1]["keys"] == {"weak_efficiency": 1.95}
    assert rows[-1]["baseline"] == "base_r1.json"
