"""Native LZ4 block codec tests (ingest/native/lz4_block.cpp): byte-level
round trips, FORMAT CONFORMANCE against an independent pure-Python block
decoder written straight from the public spec (so the C++ compressor's
streams are pinned to the format, not merely to its own decompressor),
corrupt-input rejection, and the VDI wire path with codec="lz4"
(≅ reference VDICompositingTest.kt:251-304 compressing per-rank segments,
VDICompressionBenchmarks.kt:23-372)."""

import subprocess

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain")

from scenery_insitu_tpu.io import lz4


def ref_decode_block(buf: bytes) -> bytes:
    """Independent LZ4 block decoder, transcribed from the public format
    description: [token][lit-run][literals][offset LE16][match-run]...,
    255-continuation lengths, minmatch 4, last sequence literal-only."""
    out = bytearray()
    i = 0
    n = len(buf)
    while i < n:
        token = buf[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = buf[i]
                i += 1
                lit += b
                if b != 255:
                    break
        out += buf[i:i + lit]
        i += lit
        if i >= n:
            break
        off = buf[i] | (buf[i + 1] << 8)
        i += 2
        assert 0 < off <= len(out), "offset outside decoded prefix"
        mlen = token & 15
        if mlen == 15:
            while True:
                b = buf[i]
                i += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        for _ in range(mlen):          # byte-wise: overlap semantics
            out.append(out[-off])
    return bytes(out)


PAYLOADS = {
    "zeros": b"\x00" * 4096,
    "text": b"the quick brown fox jumps over the lazy dog " * 64,
    "random": np.random.default_rng(3).bytes(4096),
    "sparse_f32": np.where(
        np.random.default_rng(4).random(4096) > 0.9,
        np.random.default_rng(5).random(4096), 0.0
    ).astype(np.float32).tobytes(),
    "tiny": b"ab",
    "empty": b"",
}


@pytest.mark.parametrize("name", sorted(PAYLOADS))
def test_roundtrip(name):
    data = PAYLOADS[name]
    assert lz4.decompress(lz4.compress(data)) == data


@pytest.mark.parametrize("name", sorted(PAYLOADS))
def test_conformance_against_independent_decoder(name):
    """C++-compressed stream decoded by the spec-transcribed Python
    decoder — any conformant LZ4 block decoder must accept our output."""
    data = PAYLOADS[name]
    blob = lz4.compress(data)
    n = int.from_bytes(blob[:8], "little")
    assert n == len(data)
    assert ref_decode_block(blob[8:]) == data


def test_sizes_sweep():
    rng = np.random.default_rng(0)
    for size in (1, 3, 12, 13, 15, 16, 64, 255, 256, 1000, 65535, 65536,
                 200_000):
        base = rng.bytes(max(1, size // 17))
        data = (base * (size // len(base) + 1))[:size]
        assert lz4.decompress(lz4.compress(data)) == data, size


def test_window_limit_respected():
    """A repeat farther than 65535 bytes must be emitted as literals
    (offsets are 16-bit) — output still round-trips AND conforms."""
    rng = np.random.default_rng(1)
    marker = b"ABCDEFGHIJKLMNOP" * 4
    data = marker + rng.bytes(70_000) + marker
    blob = lz4.compress(data)
    assert lz4.decompress(blob) == data
    assert ref_decode_block(blob[8:]) == data


def test_truncated_blob_rejected():
    blob = lz4.compress(b"hello world " * 100)
    with pytest.raises(ValueError):
        lz4.decompress(blob[:len(blob) // 2])
    with pytest.raises(ValueError):
        lz4.decompress(blob[:5])           # shorter than the size header


def test_oversized_header_rejected_before_allocation():
    """An untrusted wire header claiming gigabytes must be rejected by
    the expansion bound, not by attempting the allocation."""
    evil = (1 << 40).to_bytes(8, "little") + b"\x00" * 16
    with pytest.raises(ValueError, match="max expansion"):
        lz4.decompress(evil)


def test_compresses_real_vdi_payload():
    data = np.where(np.random.default_rng(2).random((8, 4, 64, 64)) > 0.85,
                    1.0, 0.0).astype(np.float32).tobytes()
    blob = lz4.compress(data)
    assert len(blob) < len(data) // 3      # sparse VDI planes compress


def test_vdi_segment_wire_path():
    from scenery_insitu_tpu.core.vdi import VDI
    from scenery_insitu_tpu.io.vdi_io import (pack_vdi_segments,
                                              unpack_vdi_segments)

    k, h, w = 4, 16, 32
    rng = np.random.default_rng(6)
    color = np.where(rng.random((k, 4, h, w)) > 0.8,
                     rng.random((k, 4, h, w)), 0.0).astype(np.float32)
    depth = np.sort(rng.random((k, 2, h, w)).astype(np.float32), axis=1)
    vdi = VDI(color, depth)
    blobs, climits, dlimits = pack_vdi_segments(vdi, 4, codec="lz4")
    assert list(climits) + list(dlimits) == [len(b) for b in blobs]
    out = unpack_vdi_segments(blobs, k, h, w, codec="lz4")
    np.testing.assert_array_equal(np.asarray(out.color), color)
    np.testing.assert_array_equal(np.asarray(out.depth), depth)
