"""Shared-memory ingest bridge tests (SURVEY.md §7 step 7, layer L1):
protocol round-trips, never-blocking producer, zero-copy pinning, the C++
demo simulation as external producer, and an InSituSession driven by it
(≅ the reference's shm_mpiproducer/consumer pair under mpirun and the
C++-drives-renderer operator boundary)."""

import os
import subprocess
import threading
import time
import uuid

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain")

from scenery_insitu_tpu.ingest.shm import (DEMO_PRODUCER, ShmConsumer,
                                           ShmProducer, ShmVolumeSource,
                                           ensure_built)


def _chan():
    return f"/sitpu_test_{uuid.uuid4().hex[:12]}"


def test_build():
    assert os.path.exists(ensure_built())


def test_roundtrip_and_ordering():
    shape = (8, 8, 8)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        seqs = []
        for i in range(5):
            frame = np.full(shape, float(i), np.float32)
            s = prod.publish(frame)
            assert s > 0
            got = cons.latest(timeout_ms=1000)
            assert got is not None
            arr, seq = got
            seqs.append(seq)
            np.testing.assert_array_equal(arr, frame)
        assert seqs == sorted(seqs)
        # no new frame -> poll returns None immediately
        assert cons.latest(timeout_ms=0) is None
    finally:
        cons.close()
        prod.close()


def test_consumer_sees_newest_only():
    """A slow consumer skips intermediate frames (the transport carries
    'the newest state', not a queue — same as the reference's double
    buffer)."""
    shape = (4,)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        for i in range(10):
            prod.publish(np.full(shape, float(i), np.float32))
        arr, seq = cons.latest(timeout_ms=1000)
        assert seq == 10
        np.testing.assert_array_equal(arr, np.full(shape, 9.0, np.float32))
    finally:
        cons.close()
        prod.close()


def test_producer_never_blocks_when_readers_pin_everything():
    shape = (4,)
    ch = _chan()
    prod = ShmProducer(ch, shape, nslots=2)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        assert prod.publish(np.zeros(shape, np.float32)) == 1
        pinned, _ = cons.latest(timeout_ms=1000, copy=False)
        # slot 0 = latest (skipped), its twin is pinned? with nslots=2 the
        # writer must avoid the latest slot AND every pinned slot
        s2 = prod.publish(np.ones(shape, np.float32))
        s3 = prod.publish(np.full(shape, 2.0, np.float32))
        # at least one of the writes must have been dropped (seq == 0) or
        # succeeded without corrupting the pinned view
        np.testing.assert_array_equal(np.asarray(pinned),
                                      np.zeros(shape, np.float32))
        assert (s2 == 0) or (s3 == 0) or True  # no deadlock is the point
        cons.release(pinned.slot)
        assert prod.publish(np.full(shape, 3.0, np.float32)) > 0
    finally:
        cons.close()
        prod.close()


def test_zero_copy_view_aliases_shm():
    shape = (16,)
    ch = _chan()
    prod = ShmProducer(ch, shape, nslots=3)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        prod.publish(np.arange(16, dtype=np.float32))
        pinned, _ = cons.latest(copy=False, timeout_ms=1000)
        assert not pinned.flags.owndata          # aliases the mapping
        np.testing.assert_array_equal(np.asarray(pinned),
                                      np.arange(16, dtype=np.float32))
        cons.release(pinned.slot)
    finally:
        cons.close()
        prod.close()


def test_blocking_wait_wakes_on_publish():
    shape = (4,)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    result = {}

    def waiter():
        result["got"] = cons.latest(timeout_ms=5000)

    t = threading.Thread(target=waiter)
    try:
        t.start()
        time.sleep(0.2)                          # let it block
        prod.publish(np.full(shape, 7.0, np.float32))
        t.join(timeout=5)
        assert not t.is_alive()
        arr, seq = result["got"]
        np.testing.assert_array_equal(arr, np.full(shape, 7.0, np.float32))
    finally:
        cons.close()
        prod.close()


def test_cpp_demo_producer_field_mode():
    """Consume frames produced by the standalone C++ simulation binary —
    the true cross-language operator boundary."""
    ensure_built()
    ch = _chan()
    d = 12
    proc = subprocess.Popen(
        [DEMO_PRODUCER, ch, "field", str(d), "50", "2"],
        stdout=subprocess.DEVNULL)
    try:
        cons = ShmConsumer(ch, (d, d, d), timeout_ms=5000)
        seqs = []
        for _ in range(5):
            got = cons.latest(timeout_ms=2000)
            assert got is not None
            arr, seq = got
            seqs.append(seq)
            assert np.isfinite(arr).all()
            assert arr.max() > 0.5               # the Gaussian blob peak
        assert seqs == sorted(seqs) and len(set(seqs)) == 5
        cons.close()
    finally:
        proc.wait(timeout=10)


def test_session_driven_by_external_cpp_sim():
    """InSituSession rendering a volume stream from the C++ producer —
    the reference's headline capability (OpenFPM sim drives renderer),
    standalone-testable (its repo 'can not be used standalone')."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    ensure_built()
    ch = _chan()
    d = 16
    proc = subprocess.Popen(
        [DEMO_PRODUCER, ch, "field", str(d), "400", "2"],
        stdout=subprocess.DEVNULL)
    try:
        src = ShmVolumeSource(ch, (d, d, d), timeout_ms=5000)
        cfg = FrameworkConfig().with_overrides(
            "render.width=32", "render.height=24", "render.max_steps=16",
            "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
            "composite.max_output_supersegments=4",
            "composite.adaptive_iters=1", "sim.steps_per_frame=1",
            "runtime.dataset=procedural")
        sess = InSituSession(cfg, mesh=make_mesh(2), sim=src)
        payload = sess.run(3)
        assert payload["vdi_color"].shape == (4, 4, 24, 32)
        assert np.isfinite(payload["vdi_color"]).all()
        assert payload["vdi_color"].max() > 0.0  # blob is visible
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_concurrent_stress_no_torn_frames():
    """Race stress (the reference ships NO race detection — SURVEY §5):
    one producer process-thread publishing checksummed frames as fast as
    possible, two consumer threads reading concurrently with and without
    copy. Every observed frame must be internally consistent (checksum
    matches its sequence stamp) and sequences must be non-decreasing per
    consumer — i.e. no torn reads, no reordering, under real contention."""
    chan = _chan()
    shape = (64, 257)      # odd second dim: exercises unaligned strides
    frames = 400
    prod = ShmProducer(chan, shape, nslots=4)
    stop = threading.Event()
    errors = []

    def producer():
        base = np.empty(shape, np.float32)
        for i in range(1, frames + 1):
            base.fill(float(i))
            base[-1, -1] = i * 2.0    # tail stamp: torn-write detector
            prod.publish(base)
        stop.set()

    def consumer(copy: bool):
        con = ShmConsumer(chan, shape, timeout_ms=2000)
        last = 0.0
        deadline = time.time() + 60     # bound the never-saw-a-frame case
        try:
            while ((not stop.is_set() or last == 0.0)
                   and time.time() < deadline):
                got = con.latest(timeout_ms=200, copy=copy)
                if got is None:
                    continue
                frame, _seq = got
                head = float(frame[0, 0])
                tail = float(frame[-1, -1])
                mid = float(frame[shape[0] // 2, shape[1] // 2])
                if not copy:
                    con.release(frame.slot)
                if head < last:
                    errors.append(f"value went backwards {last} -> {head}")
                if tail != head * 2.0 or mid != head:
                    errors.append(
                        f"torn frame {head}: tail {tail} mid {mid}")
                last = head
        except Exception as e:      # surfaced by the main thread
            errors.append(repr(e))
        finally:
            con.close()

    ths = [threading.Thread(target=consumer, args=(True,)),
           threading.Thread(target=consumer, args=(False,))]
    for t in ths:
        t.start()
    try:
        producer()
    finally:
        stop.set()      # a producer error must not leave consumers spinning
    for t in ths:
        t.join(timeout=30)
        assert not t.is_alive(), "consumer thread wedged"
    prod.close()
    assert not errors, errors[:5]
