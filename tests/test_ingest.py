"""Shared-memory ingest bridge tests (SURVEY.md §7 step 7, layer L1):
protocol round-trips, never-blocking producer, zero-copy pinning, the C++
demo simulation as external producer, and an InSituSession driven by it
(≅ the reference's shm_mpiproducer/consumer pair under mpirun and the
C++-drives-renderer operator boundary)."""

import os
import subprocess
import threading
import time
import uuid

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    subprocess.run(["which", "g++"], capture_output=True).returncode != 0,
    reason="no C++ toolchain")

from scenery_insitu_tpu.ingest.shm import (DEMO_PRODUCER, ShmConsumer,
                                           ShmProducer, ShmVolumeSource,
                                           ensure_built)


def _chan():
    return f"/sitpu_test_{uuid.uuid4().hex[:12]}"


def test_build():
    assert os.path.exists(ensure_built())


def test_roundtrip_and_ordering():
    shape = (8, 8, 8)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        seqs = []
        for i in range(5):
            frame = np.full(shape, float(i), np.float32)
            s = prod.publish(frame)
            assert s > 0
            got = cons.latest(timeout_ms=1000)
            assert got is not None
            arr, seq = got
            seqs.append(seq)
            np.testing.assert_array_equal(arr, frame)
        assert seqs == sorted(seqs)
        # no new frame -> poll returns None immediately
        assert cons.latest(timeout_ms=0) is None
    finally:
        cons.close()
        prod.close()


def test_consumer_sees_newest_only():
    """A slow consumer skips intermediate frames (the transport carries
    'the newest state', not a queue — same as the reference's double
    buffer)."""
    shape = (4,)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        for i in range(10):
            prod.publish(np.full(shape, float(i), np.float32))
        arr, seq = cons.latest(timeout_ms=1000)
        assert seq == 10
        np.testing.assert_array_equal(arr, np.full(shape, 9.0, np.float32))
    finally:
        cons.close()
        prod.close()


def test_producer_never_blocks_when_readers_pin_everything():
    shape = (4,)
    ch = _chan()
    prod = ShmProducer(ch, shape, nslots=2)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        assert prod.publish(np.zeros(shape, np.float32)) == 1
        pinned, _ = cons.latest(timeout_ms=1000, copy=False)
        # slot 0 = latest (skipped), its twin is pinned? with nslots=2 the
        # writer must avoid the latest slot AND every pinned slot
        s2 = prod.publish(np.ones(shape, np.float32))
        s3 = prod.publish(np.full(shape, 2.0, np.float32))
        # at least one of the writes must have been dropped (seq == 0) or
        # succeeded without corrupting the pinned view
        np.testing.assert_array_equal(np.asarray(pinned),
                                      np.zeros(shape, np.float32))
        assert (s2 == 0) or (s3 == 0) or True  # no deadlock is the point
        cons.release(pinned.slot)
        assert prod.publish(np.full(shape, 3.0, np.float32)) > 0
    finally:
        cons.close()
        prod.close()


def test_zero_copy_view_aliases_shm():
    shape = (16,)
    ch = _chan()
    prod = ShmProducer(ch, shape, nslots=3)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    try:
        prod.publish(np.arange(16, dtype=np.float32))
        pinned, _ = cons.latest(copy=False, timeout_ms=1000)
        assert not pinned.flags.owndata          # aliases the mapping
        np.testing.assert_array_equal(np.asarray(pinned),
                                      np.arange(16, dtype=np.float32))
        cons.release(pinned.slot)
    finally:
        cons.close()
        prod.close()


def test_blocking_wait_wakes_on_publish():
    shape = (4,)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    cons = ShmConsumer(ch, shape, timeout_ms=2000)
    result = {}

    def waiter():
        result["got"] = cons.latest(timeout_ms=5000)

    t = threading.Thread(target=waiter)
    try:
        t.start()
        time.sleep(0.2)                          # let it block
        prod.publish(np.full(shape, 7.0, np.float32))
        t.join(timeout=5)
        assert not t.is_alive()
        arr, seq = result["got"]
        np.testing.assert_array_equal(arr, np.full(shape, 7.0, np.float32))
    finally:
        cons.close()
        prod.close()


def test_shm_source_stall_and_recover():
    """Satellite (ISSUE 11): a stalled/dead producer must not kill the
    render loop — ShmVolumeSource keeps rendering last-good data under
    an `ingest.stall` ledger row, polls without blocking while stalled,
    and recovers the moment frames resume."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.ingest.shm import ShmVolumeSource

    shape = (6, 6, 6)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    prod.publish(np.full(shape, 1.0, np.float32))
    src = ShmVolumeSource(ch, shape, timeout_ms=2000,
                          frame_timeout_ms=100, device_put=False)
    try:
        src.advance(1)
        np.testing.assert_array_equal(np.asarray(src.field),
                                      np.full(shape, 1.0, np.float32))
        assert not src.stalled
        # producer goes quiet: the source stalls, keeps last-good data
        src.advance(1)
        assert src.stalled and src.stall_count == 1
        assert any(e["component"] == "ingest.stall"
                   for e in obs.ledger())
        np.testing.assert_array_equal(np.asarray(src.field),
                                      np.full(shape, 1.0, np.float32))
        # while stalled, advance polls non-blocking (no 100 ms waits)
        t0 = time.monotonic()
        for _ in range(5):
            src.advance(1)
        assert time.monotonic() - t0 < 0.4
        assert src.stall_count == 1          # one episode, minted once
        # frames resume: the stall clears and new data renders
        prod.publish(np.full(shape, 2.0, np.float32))
        src.advance(1)
        assert not src.stalled
        np.testing.assert_array_equal(np.asarray(src.field),
                                      np.full(shape, 2.0, np.float32))
    finally:
        src.consumer.close()
        prod.close()


def test_sharded_source_stall_keeps_last_good():
    """The multi-rank twin: a silent producer SET stalls the sharded
    source onto last-good data (ledgered), without blocking the loop."""
    from scenery_insitu_tpu import obs
    from scenery_insitu_tpu.ingest.shm import ShmShardedVolumeSource

    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 1:
        pytest.skip("no devices")
    from scenery_insitu_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(1)
    shape = (4, 4, 4)
    ch = _chan()
    prod = ShmProducer(ch, shape)
    prod.publish(np.full(shape, 3.0, np.float32))
    src = ShmShardedVolumeSource([ch], shape, mesh, timeout_ms=2000,
                                 frame_timeout_ms=100)
    try:
        src.advance()
        assert float(np.asarray(src.field)[0, 0, 0]) == 3.0
        src.advance()                        # nothing newer -> stall
        assert src.stalled
        assert any(e["component"] == "ingest.stall"
                   for e in obs.ledger())
        t0 = time.monotonic()
        src.advance()                        # stalled advances don't block
        assert time.monotonic() - t0 < 0.4
        prod.publish(np.full(shape, 4.0, np.float32))
        src.advance()
        assert not src.stalled
        assert float(np.asarray(src.field)[0, 0, 0]) == 4.0
    finally:
        src.close()
        prod.close()


def test_cpp_demo_producer_field_mode():
    """Consume frames produced by the standalone C++ simulation binary —
    the true cross-language operator boundary."""
    ensure_built()
    ch = _chan()
    d = 12
    proc = subprocess.Popen(
        [DEMO_PRODUCER, ch, "field", str(d), "50", "2"],
        stdout=subprocess.DEVNULL)
    try:
        cons = ShmConsumer(ch, (d, d, d), timeout_ms=5000)
        seqs = []
        for _ in range(5):
            got = cons.latest(timeout_ms=2000)
            assert got is not None
            arr, seq = got
            seqs.append(seq)
            assert np.isfinite(arr).all()
            assert arr.max() > 0.5               # the Gaussian blob peak
        assert seqs == sorted(seqs) and len(set(seqs)) == 5
        cons.close()
    finally:
        proc.wait(timeout=10)


def test_session_driven_by_external_cpp_sim():
    """InSituSession rendering a volume stream from the C++ producer —
    the reference's headline capability (OpenFPM sim drives renderer),
    standalone-testable (its repo 'can not be used standalone')."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    ensure_built()
    ch = _chan()
    d = 16
    proc = subprocess.Popen(
        [DEMO_PRODUCER, ch, "field", str(d), "400", "2"],
        stdout=subprocess.DEVNULL)
    try:
        src = ShmVolumeSource(ch, (d, d, d), timeout_ms=5000)
        cfg = FrameworkConfig().with_overrides(
            "render.width=32", "render.height=24", "render.max_steps=16",
            "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
            "composite.max_output_supersegments=4",
            "composite.adaptive_iters=1", "sim.steps_per_frame=1",
            "runtime.dataset=procedural")
        sess = InSituSession(cfg, mesh=make_mesh(2), sim=src)
        payload = sess.run(3)
        assert payload["vdi_color"].shape == (4, 4, 24, 32)
        assert np.isfinite(payload["vdi_color"]).all()
        assert payload["vdi_color"].max() > 0.0  # blob is visible
    finally:
        proc.kill()
        proc.wait(timeout=10)


def _run_slab_producers(n: int, d: int, frames: int):
    """Run n slab producers to completion (one per rank) + one whole-field
    producer of the same deterministic Gaussian; returns (slab_channels,
    whole_channel). Exited producers leave their final frame in the ring,
    so consumers see one static, bit-identical frame set — parity between
    the multi-rank and whole-field feeds is then exact, not statistical."""
    ensure_built()
    chans = [_chan() for _ in range(n)]
    whole = _chan()
    procs = [subprocess.Popen(
        [DEMO_PRODUCER, c, "slab", str(d), str(frames), "0", str(r), str(n)],
        stdout=subprocess.DEVNULL) for r, c in enumerate(chans)]
    procs.append(subprocess.Popen(
        [DEMO_PRODUCER, whole, "field", str(d), str(frames), "0"],
        stdout=subprocess.DEVNULL))
    for p in procs:
        assert p.wait(timeout=30) == 0
    return chans, whole


def test_sharded_source_assembles_coherent_global_field():
    """N external slab producers -> ONE mesh-sharded global jax.Array:
    values bit-equal to the whole-field producer's frame, shards placed
    one-per-device with the distributed pipeline's sharding (so the
    session's shard_volume re-placement is a no-op)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from scenery_insitu_tpu.ingest.shm import ShmShardedVolumeSource
    from scenery_insitu_tpu.parallel.mesh import make_mesh

    n, d = 2, 16
    chans, whole = _run_slab_producers(n, d, frames=3)
    mesh = make_mesh(n)
    src = ShmShardedVolumeSource(chans, (d // n, d, d), mesh,
                                 timeout_ms=5000, frame_timeout_ms=300)
    try:
        field = src.field
        assert field.shape == (d, d, d)
        assert len(set(src.last_seqs)) == 1          # coherent frame set
        assert field.sharding.is_equivalent_to(
            NamedSharding(mesh, P(mesh.axis_names[0], None, None)),
            field.ndim)
        shards = {s.device: s.data.shape for s in field.addressable_shards}
        assert len(shards) == n
        assert set(shards.values()) == {(d // n, d, d)}
        ref = ShmConsumer(whole, (d, d, d), timeout_ms=5000)
        want, _ = ref.latest(timeout_ms=2000)
        ref.close()
        assert np.array_equal(np.asarray(field), want)
        # advance with exited producers keeps the last coherent frame
        src.advance(1)
        assert src.last_seqs and np.asarray(src.field).max() > 0.5
    finally:
        src.close()
        from scenery_insitu_tpu.ingest.shm import unlink
        for c in chans + [whole]:
            unlink(c)


def test_session_driven_by_multirank_external_producers():
    """The last operator-boundary gap (round-4 VERDICT item 5): N
    demo_producer processes, one per rank slab, feed the DISTRIBUTED
    pipeline through an InSituSession over the virtual mesh — and the
    render equals the same session fed the whole field through one
    channel (≅ DistributedVolumeRenderer.kt:136-160's per-rank MPI
    partners vs a single-source run)."""
    from scenery_insitu_tpu.config import FrameworkConfig
    from scenery_insitu_tpu.ingest.shm import (ShmShardedVolumeSource,
                                               unlink)
    from scenery_insitu_tpu.parallel.mesh import make_mesh
    from scenery_insitu_tpu.runtime.session import InSituSession

    n, d = 4, 16
    chans, whole = _run_slab_producers(n, d, frames=3)
    mesh = make_mesh(n)
    cfg = FrameworkConfig().with_overrides(
        "render.width=32", "render.height=24", "render.max_steps=16",
        "vdi.max_supersegments=4", "vdi.adaptive_iters=1",
        "composite.max_output_supersegments=4",
        "composite.adaptive_iters=1", "sim.steps_per_frame=1",
        "runtime.dataset=procedural")
    src_multi = ShmShardedVolumeSource(chans, (d // n, d, d), mesh,
                                       timeout_ms=5000,
                                       frame_timeout_ms=300)
    # channels already exist (producers ran to completion), so the short
    # timeout only bounds the keep-last-frame wait per advance
    src_single = ShmVolumeSource(whole, (d, d, d), timeout_ms=1500)
    try:
        pay_m = InSituSession(cfg, mesh=mesh, sim=src_multi).run(2)
        pay_s = InSituSession(cfg, mesh=mesh, sim=src_single).run(2)
        assert pay_m["vdi_color"].max() > 0.0        # blob visible
        np.testing.assert_array_equal(pay_m["vdi_color"],
                                      pay_s["vdi_color"])
        np.testing.assert_array_equal(pay_m["vdi_depth"],
                                      pay_s["vdi_depth"])
    finally:
        src_multi.close()
        src_single.consumer.close()
        for c in chans + [whole]:
            unlink(c)


def test_concurrent_stress_no_torn_frames():
    """Race stress (the reference ships NO race detection — SURVEY §5):
    one producer process-thread publishing checksummed frames as fast as
    possible, two consumer threads reading concurrently with and without
    copy. Every observed frame must be internally consistent (checksum
    matches its sequence stamp) and sequences must be non-decreasing per
    consumer — i.e. no torn reads, no reordering, under real contention."""
    chan = _chan()
    shape = (64, 257)      # odd second dim: exercises unaligned strides
    frames = 400
    prod = ShmProducer(chan, shape, nslots=4)
    stop = threading.Event()
    errors = []

    def producer():
        base = np.empty(shape, np.float32)
        for i in range(1, frames + 1):
            base.fill(float(i))
            base[-1, -1] = i * 2.0    # tail stamp: torn-write detector
            prod.publish(base)
        stop.set()

    def consumer(copy: bool):
        con = ShmConsumer(chan, shape, timeout_ms=2000)
        last = 0.0
        deadline = time.time() + 60     # bound the never-saw-a-frame case
        try:
            while ((not stop.is_set() or last == 0.0)
                   and time.time() < deadline):
                got = con.latest(timeout_ms=200, copy=copy)
                if got is None:
                    continue
                frame, _seq = got
                head = float(frame[0, 0])
                tail = float(frame[-1, -1])
                mid = float(frame[shape[0] // 2, shape[1] // 2])
                if not copy:
                    con.release(frame.slot)
                if head < last:
                    errors.append(f"value went backwards {last} -> {head}")
                if tail != head * 2.0 or mid != head:
                    errors.append(
                        f"torn frame {head}: tail {tail} mid {mid}")
                last = head
        except Exception as e:      # surfaced by the main thread
            errors.append(repr(e))
        finally:
            con.close()

    ths = [threading.Thread(target=consumer, args=(True,)),
           threading.Thread(target=consumer, args=(False,))]
    for t in ths:
        t.start()
    try:
        producer()
    finally:
        stop.set()      # a producer error must not leave consumers spinning
    for t in ths:
        t.join(timeout=30)
        assert not t.is_alive(), "consumer thread wedged"
    prod.close()
    assert not errors, errors[:5]
