"""The asynchronous delivery plane (docs/PERF.md "Async delivery"):
take the host off the frame's critical path.

The serial delivery path runs tile slicing, compression, CRC stamping
and disk/zmq sinks inline on the render-loop thread, so steady-state
frame time is device + host. The reference ran its H264 encode/stream
on a dedicated thread off the render loop (SURVEY §0), and the
Distributed FrameBuffer literature shows tile-granular delivery
overlapped with rendering is the standard shape for this pipeline —
``DeliveryExecutor`` is that worker tier: the loop enqueues one job per
fetched frame (the host numpy payloads, nothing device-resident) onto a
bounded FIFO, and a single worker thread runs the sinks behind the same
PR-11 ``SinkGuard`` quarantine the inline path uses.

Ordering contract (unchanged from the inline path, and tested in
tests/test_delivery.py): within one frame every tile payload is
delivered in ascending column order, then the frame sinks run — the
frame "closes" only after its tiles are out the door; across frames the
queue is strictly FIFO. A single worker makes the contract structural
rather than something a lock ladder must re-earn; the parallelism that
matters (per-tile encode) lives INSIDE sinks like ``VDIPublisher``,
which fan the deterministic encode work across a pool and still emit
wire bytes in tile order.

Overflow is a stated policy, not an accident (docs/ROBUSTNESS.md "Shed
semantics"): ``block`` applies lossless backpressure (correctness sinks
— disk dumps, checkpoints), ``drop_oldest`` sheds the stalest
undelivered frame latest-wins (live streaming, where delivering an old
frame late is worse than not delivering it) and every shed mints a
``delivery.shed`` ledger row, a ``delivery_sheds`` counter bump and a
typed ``delivery_shed`` event. ``drain()`` empties the queue on
teardown and is wired into the session's flight-recorder crash paths,
so an exception in the loop does not lose frames the device already
paid for.

jax-free on purpose, like failsafe.py: the payloads are host numpy by
the time they reach this tier.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence

from scenery_insitu_tpu import obs as _obs
from scenery_insitu_tpu.obs.collector import lineage

_SHED_REASON = ("bounded delivery queue overflowed with overflow="
                "'drop_oldest'; the stalest undelivered frame was shed "
                "latest-wins (docs/ROBUSTNESS.md 'Shed semantics')")
_DRAIN_REASON = ("teardown drain timed out with frames still queued; "
                 "remaining jobs were abandoned so shutdown could "
                 "proceed")


class _FrameJob:
    """One frame's delivery work: the frame payload plus its tile
    payloads in ascending column order (host numpy only)."""

    __slots__ = ("index", "payload", "tiles", "t_enqueue")

    def __init__(self, index: int, payload: dict,
                 tiles: Sequence[dict]):
        self.index = index
        self.payload = payload
        self.tiles = list(tiles)
        self.t_enqueue = time.perf_counter()


class DeliveryExecutor:
    """Background sink tier draining a bounded per-frame queue off the
    render-loop thread.

    ``tile_sinks`` / ``sinks`` are the session's LIVE lists (the same
    objects users append to mid-run); ``guard`` is the session's
    SinkGuard, shared so quarantine state is one truth whether a sink
    ran inline or on the worker."""

    def __init__(self, cfg, guard, tile_sinks: List, sinks: List,
                 recorder=None, slo=None, log=None):
        self.cfg = cfg
        self.guard = guard
        self.tile_sinks = tile_sinks
        self.sinks = sinks
        self.slo = slo
        self.log = log or (lambda s: None)
        self._recorder = recorder
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._active = 0           # jobs popped but not yet delivered
        self._stop = False
        self.sheds = 0
        self.delivered = 0
        self.enqueued = 0
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="delivery-worker")
        self._worker.start()

    # ------------------------------------------------------------ helpers
    def _rec(self):
        return self._recorder or _obs.get_recorder()

    @property
    def depth(self) -> int:
        """Current queue depth (jobs queued + in delivery) — the
        queue-depth gauge; also published as the value of the
        ``delivery_frames_inflight`` counter."""
        with self._cond:
            return len(self._q) + self._active

    # ------------------------------------------------------------- submit
    def submit(self, index: int, payload: dict,
               tiles: Sequence[dict] = ()) -> bool:
        """Enqueue one frame's delivery; called from the loop thread.
        Returns False when this submission caused a shed (an OLDER frame
        was dropped under ``drop_oldest`` — the submitted frame itself
        is always accepted; under ``block`` the call waits for space
        instead and always returns True)."""
        rec = self._rec()
        job = _FrameJob(index, payload, tiles)
        shed = None
        with self._cond:
            if self._stop:
                raise RuntimeError("DeliveryExecutor is closed")
            if self.cfg.overflow == "block":
                while len(self._q) >= self.cfg.queue_frames \
                        and not self._stop:
                    self._cond.wait(0.05)
            elif len(self._q) >= self.cfg.queue_frames:
                shed = self._q.popleft()
                self.sheds += 1
            self._q.append(job)
            self.enqueued += 1
            gauge = len(self._q) + self._active
            self._cond.notify_all()
        rec.count("delivery_frames_enqueued")
        rec.count("delivery_frames_inflight")
        rec.event("delivery_queue_depth", frame=index, depth=gauge)
        if shed is not None:
            rec.count("delivery_sheds")
            rec.count("delivery_frames_inflight", -1)
            rec.event("delivery_shed", frame=shed.index,
                      tiles=len(shed.tiles), queued_behind=index)
            _obs.degrade("delivery.shed", f"frame {shed.index}",
                         "shed", _SHED_REASON, warn=False)
            self.log(f"delivery: shed frame {shed.index} "
                     f"(queue {self.cfg.queue_frames} full, "
                     f"overflow=drop_oldest)")
        return shed is None

    # ------------------------------------------------------------- worker
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(0.1)
                if not self._q:
                    return                          # stopped and empty
                job = self._q.popleft()
                self._active += 1
                self._cond.notify_all()
            try:
                self._deliver(job)
            finally:
                with self._cond:
                    self._active -= 1
                    self._cond.notify_all()

    def _deliver(self, job: _FrameJob) -> None:
        """Run one frame's sinks on the worker thread — tile payloads in
        ascending column order first, then the frame sinks (the frame
        closes after its tiles); every callable behind the SinkGuard.
        The spans here record on the worker's own span stack (the
        recorder's stack is thread-local) and carry the frame id, so
        traces attribute delivery time to the frame it belongs to."""
        rec = self._rec()
        with rec.span("deliver", frame=job.index, worker="delivery",
                      tiles=len(job.tiles)):
            for tp in job.tiles:
                with rec.span("tile", frame=job.index,
                              tile=tp.get("tile")):
                    rec.count("tiles_delivered")
                    self.guard.run(self.tile_sinks, job.index, tp,
                                   kind="tile sink")
            with rec.span("sinks", frame=job.index):
                self.guard.run(self.sinks, job.index, job.payload)
        lineage("deliver", "send", job.index, rec=rec)
        self.delivered += 1
        lag_ms = (time.perf_counter() - job.t_enqueue) * 1e3
        rec.count("delivery_frames_delivered")
        rec.count("delivery_frames_inflight", -1)
        if self.slo is not None:
            self.slo.observe("delivery_lag_ms", lag_ms, frame=job.index)

    # ----------------------------------------------------------- teardown
    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Block until every queued frame is delivered (queue empty AND
        worker idle). Returns True on a clean drain; a timeout ledgers
        the abandon (``delivery.drain``) and returns False — the caller
        is tearing down and must not hang forever on a wedged sink.
        Safe to call from crash paths: never raises."""
        deadline = time.monotonic() + (self.cfg.drain_timeout_s
                                       if timeout_s is None else timeout_s)
        try:
            with self._cond:
                while self._q or self._active:
                    if not self._worker.is_alive():
                        break
                    if time.monotonic() >= deadline:
                        left = len(self._q) + self._active
                        self._q.clear()
                        self._cond.notify_all()
                        _obs.degrade("delivery.drain",
                                     f"{left} frames queued", "abandoned",
                                     _DRAIN_REASON, warn=False)
                        self.log(f"delivery: drain timed out with {left} "
                                 f"frames undelivered")
                        return False
                    self._cond.wait(0.05)
        except Exception as e:
            try:
                _obs.degrade("delivery.drain", "drain", "aborted",
                             f"drain error: {e!r}", warn=False)
            except Exception:
                pass        # torn-down obs; the original error wins
            return False    # crash-path caller; never raises
        return True

    def close(self, timeout_s: Optional[float] = None) -> bool:
        """Drain, then stop the worker. Idempotent."""
        clean = self.drain(timeout_s)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._worker.join(timeout=2.0)
        return clean
