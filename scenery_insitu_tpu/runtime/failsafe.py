"""Per-callable failure isolation for the delivery plane
(docs/ROBUSTNESS.md "Session failure isolation").

A frame sink, tile sink or ``on_steer`` callback lives in the same
process as the render loop but on the other side of a failure domain:
its bugs are not the session's bugs, and an exception inside one must
not abort an hours-long in-situ run. ``SinkGuard`` catches per callable,
counts CONSECUTIVE failures, and quarantines (disables + ``session.sink``
ledger) any callable that fails ``max_failures`` times in a row — a
success in between resets the count, so a transiently failing sink (disk
briefly full, socket mid-reconnect) keeps running.

jax-free on purpose: ``runtime/head.py`` (transport + numpy only) uses
the same guard for its sinks.
"""

from __future__ import annotations

from typing import Callable, Iterable

from scenery_insitu_tpu import obs as _obs


def _name_of(fn: Callable) -> str:
    return getattr(fn, "__qualname__",
                   getattr(fn, "__name__", type(fn).__name__))


class SinkGuard:
    """Failure-isolation wrapper around a list of callables the render
    loop must survive. State is keyed on the callable's identity, so the
    public sink lists (``sess.sinks`` / ``sess.tile_sinks`` /
    ``sess.on_steer``) stay plain lists users append to."""

    def __init__(self, max_failures: int = 3, log=None,
                 domain: str = "session"):
        if max_failures < 1:
            raise ValueError(f"max_failures must be >= 1, "
                             f"got {max_failures}")
        self.max_failures = max_failures
        self.log = log or (lambda s: None)
        self.domain = domain
        # state is keyed on id(fn) but each entry HOLDS the callable:
        # the strong reference pins the object alive, so a freed sink's
        # address can never be recycled into another callable's
        # quarantine/failure record
        self._failures = {}        # id(fn) -> (count, fn)
        self._quarantined = {}     # id(fn) -> fn
        self.quarantined_names = []

    def is_quarantined(self, fn: Callable) -> bool:
        return id(fn) in self._quarantined

    def call(self, fn: Callable, *args, kind: str = "sink") -> bool:
        """Run ``fn(*args)`` inside the guard; returns True on success,
        False when it failed or is quarantined. Never raises."""
        key = id(fn)
        if key in self._quarantined:
            return False
        try:
            fn(*args)
        except Exception as e:
            n = self._failures.get(key, (0, fn))[0] + 1
            self._failures[key] = (n, fn)
            rec = _obs.get_recorder()
            rec.count("sink_failures")
            name = _name_of(fn)
            self.log(f"{kind} {name!r} failed "
                     f"({n}/{self.max_failures}): {e!r}")
            if n >= self.max_failures:
                self._quarantined[key] = fn
                self.quarantined_names.append(name)
                rec.count("sinks_quarantined")
                _obs.degrade(
                    "session.sink", f"{kind} {name}", "quarantined",
                    f"failed {self.max_failures} consecutive times in "
                    f"{self.domain}; disabled for the rest of the run",
                    warn=False)
            return False
        self._failures.pop(key, None)   # consecutive failures only
        return True

    def run(self, fns: Iterable[Callable], *args,
            kind: str = "sink") -> int:
        """Run every callable in ``fns`` against ``args``; returns how
        many succeeded. Quarantined entries are skipped silently."""
        ok = 0
        for fn in list(fns):
            if self.call(fn, *args, kind=kind):
                ok += 1
        return ok
