"""Per-callable failure isolation for the delivery plane
(docs/ROBUSTNESS.md "Session failure isolation").

A frame sink, tile sink or ``on_steer`` callback lives in the same
process as the render loop but on the other side of a failure domain:
its bugs are not the session's bugs, and an exception inside one must
not abort an hours-long in-situ run. ``SinkGuard`` catches per callable,
counts CONSECUTIVE failures, and quarantines (disables + ``session.sink``
ledger) any callable that fails ``max_failures`` times in a row — a
success in between resets the count, so a transiently failing sink (disk
briefly full, socket mid-reconnect) keeps running.

jax-free on purpose: ``runtime/head.py`` (transport + numpy only) uses
the same guard for its sinks.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

from scenery_insitu_tpu import obs as _obs


def _name_of(fn: Callable) -> str:
    return getattr(fn, "__qualname__",
                   getattr(fn, "__name__", type(fn).__name__))


class SinkGuard:
    """Failure-isolation wrapper around a list of callables the render
    loop must survive. State is keyed on the callable's identity, so the
    public sink lists (``sess.sinks`` / ``sess.tile_sinks`` /
    ``sess.on_steer``) stay plain lists users append to."""

    def __init__(self, max_failures: int = 3, log=None,
                 domain: str = "session"):
        if max_failures < 1:
            raise ValueError(f"max_failures must be >= 1, "
                             f"got {max_failures}")
        self.max_failures = max_failures
        self.log = log or (lambda s: None)
        self.domain = domain
        # state is keyed on id(fn) but each entry HOLDS the callable:
        # the strong reference pins the object alive, so a freed sink's
        # address can never be recycled into another callable's
        # quarantine/failure record
        self._failures = {}        # id(fn) -> (count, fn)
        self._quarantined = {}     # id(fn) -> fn
        self.quarantined_names = []
        # the guard is shared between the render loop and the delivery
        # executor's worker threads (runtime/delivery.py), so the
        # count/quarantine bookkeeping must be atomic — the guarded
        # callables themselves run OUTSIDE the lock (a slow sink must
        # not serialize the other workers)
        self._lock = threading.Lock()

    def is_quarantined(self, fn: Callable) -> bool:
        with self._lock:
            return id(fn) in self._quarantined

    def reset(self, fn: Callable) -> bool:
        """Lift ``fn``'s quarantine and clear its failure count (an
        operator fixed the sink mid-run — re-admit it). Returns True
        when the callable was actually quarantined. Ledgered so the
        re-admission is as visible as the quarantine was."""
        key = id(fn)
        with self._lock:
            was = self._quarantined.pop(key, None) is not None
            self._failures.pop(key, None)
            if was:
                name = _name_of(fn)
                if name in self.quarantined_names:
                    self.quarantined_names.remove(name)
        if was:
            _obs.degrade(
                "session.sink", f"quarantined {_name_of(fn)}",
                "re-admitted",
                "quarantine reset by the operator; failure count "
                "cleared", warn=False)
        return was

    def call(self, fn: Callable, *args, kind: str = "sink") -> bool:
        """Run ``fn(*args)`` inside the guard; returns True on success,
        False when it failed or is quarantined. Never raises.
        Thread-safe: callable from delivery worker threads."""
        key = id(fn)
        with self._lock:
            if key in self._quarantined:
                return False
        try:
            fn(*args)
        except Exception as e:
            rec = _obs.get_recorder()
            rec.count("sink_failures")
            name = _name_of(fn)
            with self._lock:
                n = self._failures.get(key, (0, fn))[0] + 1
                self._failures[key] = (n, fn)
                quarantine = n >= self.max_failures
                if quarantine and key not in self._quarantined:
                    self._quarantined[key] = fn
                    self.quarantined_names.append(name)
                else:
                    quarantine = False
            self.log(f"{kind} {name!r} failed "
                     f"({n}/{self.max_failures}): {e!r}")
            if quarantine:
                rec.count("sinks_quarantined")
                _obs.degrade(
                    "session.sink", f"{kind} {name}", "quarantined",
                    f"failed {self.max_failures} consecutive times in "
                    f"{self.domain}; disabled for the rest of the run",
                    warn=False)
            return False
        with self._lock:
            self._failures.pop(key, None)   # consecutive failures only
        return True

    def run(self, fns: Iterable[Callable], *args,
            kind: str = "sink") -> int:
        """Run every callable in ``fns`` against ``args``; returns how
        many succeeded. Quarantined entries are skipped silently."""
        ok = 0
        for fn in list(fns):
            if self.call(fn, *args, kind=kind):
                ok += 1
        return ok
