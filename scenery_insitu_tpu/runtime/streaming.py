"""Streaming + steering (SURVEY.md §7 step 10b, layer L7).

≅ the reference's side channels:
- ZMQ PUB of VDI frames ``[size-ascii | metadata | color | depth]`` with
  LZ4-compressed buffers (VolumeFromFileExample.kt:996-1037) →
  ``VDIPublisher``/``VDISubscriber`` multipart messages
  ``[msgpack header, color blob, depth blob]`` with io.vdi_io codecs.
- msgpack camera/steering messages applied inside the render loop,
  dispatched by payload size (DistributedVolumeRenderer.kt:747-774;
  Head.adjustCamera, Head.kt:137-161) → typed msgpack dicts with a
  ``"type"`` field, applied by ``apply_steering``.
- the headless InSituMaster relay that rebroadcasts viewer messages to all
  render ranks (InSituMaster.kt:14-45) → ``SteeringRelay``.
- H264/UDP video stream + movie writer (DistributedVolumeRenderer.kt:
  275-291) → ``video_sink`` (cv2 VideoWriter; this image has no ffmpeg/
  libx264, so the codec is what cv2 ships — the transport role, not the
  exact bitstream).

Everything degrades gracefully: constructing any endpoint raises
ImportError only when pyzmq is genuinely missing, and the session works
fully without streaming attached.

Self-healing delivery plane (docs/ROBUSTNESS.md): every frame/tile
message carries a publisher **epoch**, a monotone u32 **sequence
number** and a **CRC32 per blob**, so the subscriber validates wire
bytes BEFORE decode and drops corrupt/truncated messages as typed
``StreamDrop`` records instead of raising; sequence gaps, duplicates
and publisher restarts are detected and ledgered (``stream.gap`` /
``stream.integrity``). Publishers emit heartbeats when idle
(``maybe_heartbeat``), subscribers track last-seen time and reconnect
past ``fault.liveness_timeout_s`` with bounded exponential backoff
(utils/retry.py), and ``FrameAssembler`` turns tile streams back into
frames, abandoning incomplete frames once ``fault.assembler_window``
newer ones have started.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Tuple, Union

import numpy as np

from scenery_insitu_tpu import obs as _obs
from scenery_insitu_tpu.obs.collector import lineage, trace_ctx
from scenery_insitu_tpu.config import DeltaConfig, FaultConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.io.vdi_io import compress, decompress
from scenery_insitu_tpu.utils.retry import Backoff

_META_FIELDS = VDIMetadata._fields

# ------------------------------------------------- sequence-space helpers

SEQ_MASK = 0xFFFFFFFF
_EPOCH_COUNT = itertools.count(1)


def _make_epoch() -> int:
    """Publisher-incarnation id: distinguishes a restarted publisher
    (sequence counter reset) from a sequence gap on a live one. Random
    32-bit (collision odds ~2^-32 per restart — a pid/counter scheme
    collides at 2^-16, which over long deployments silently blackholes
    the successor's stream as 'stale'); xor'd with a process counter so
    even an exhausted entropy pool cannot hand two publishers in one
    process the same epoch. Tests pass ``epoch=`` explicitly for
    determinism."""
    r = int.from_bytes(os.urandom(4), "little")
    return ((r ^ next(_EPOCH_COUNT)) & SEQ_MASK) or 1


def seq_delta(a: int, b: int, bits: int = 32) -> int:
    """Wrap-aware ``a - b`` in modular sequence space, mapped into
    ``[-2**(bits-1), 2**(bits-1))`` — positive means ``a`` is newer.
    Shared by the VDI stream continuity check and the UDP video
    receiver's eviction (a u32 frame counter wraps after ~2.3 years at
    60 FPS, and an unwrapped ``f < fid - 4`` comparison would leak and
    misorder across the wrap)."""
    mask = (1 << bits) - 1
    half = 1 << (bits - 1)
    d = (a - b) & mask
    return d - (1 << bits) if d >= half else d


@dataclass(frozen=True)
class StreamDrop:
    """Typed record of one message the subscriber refused: ``kind`` is
    ``"integrity"`` (failed checksum/size/shape validation before
    decode), ``"stale"`` (duplicate or reordered sequence number),
    ``"malformed"`` (header unparseable) or ``"resync"`` (a temporal-
    delta P/SKIP record whose base tile is not retained — an earlier
    drop broke the chain; the stream recovers on the next forced
    I-tile, within ``delta.iframe_period`` frames). Returned instead of
    raising — the stream outlives any single bad message. ``frame`` is
    the refused message's frame index when its header parsed far enough
    to carry one — a refused frame still STARTED, so stream-head
    bookkeeping (the serving tier's bounded-staleness clock) must
    advance past it."""

    kind: str
    reason: str
    epoch: Optional[int] = None
    seq: Optional[int] = None
    frame: Optional[int] = None


_HEARTBEAT = object()        # receive-loop sentinel: liveness, not a frame


class _HeartbeatPacer:
    """Shared idle-heartbeat pacing: subclasses define ``heartbeat()``
    and keep ``_last_send`` fresh; ``maybe_heartbeat()`` fires one only
    after ``fault.heartbeat_period_s`` of silence."""

    def maybe_heartbeat(self) -> bool:
        """Heartbeat only if nothing was sent for
        ``fault.heartbeat_period_s``; returns True when one went out.
        Cheap to call every loop iteration."""
        if (time.monotonic() - self._last_send
                < self.fault.heartbeat_period_s):
            return False
        self.heartbeat()
        return True


class _ReconnectSupervisor:
    """Shared liveness supervision (docs/ROBUSTNESS.md): track last-seen
    traffic and, past ``fault.liveness_timeout_s``, re-establish the
    socket via the subclass's ``_reopen()``, pacing retries on the
    bounded backoff ladder. Supervision is OPT-IN (``fault=`` passed to
    the constructor): idle publishers are normal, and without a
    heartbeat pump a healthy-but-slow stream must not be torn down.
    A failed re-open (e.g. transient EADDRINUSE right after close) is
    ledgered and retried on the next backoff tick, never raised into
    the render loop."""

    _what = "stream"             # names the stream in the ledger reason

    def _init_supervision(self, supervised: bool) -> None:
        self._supervised = supervised
        self._backoff = Backoff(self.fault.backoff_base_s,
                                self.fault.backoff_cap_s)
        self._last_seen = time.monotonic()
        self._next_reconnect = 0.0

    def _supervise(self) -> None:
        t = self.fault.liveness_timeout_s
        if not self._supervised or t <= 0:
            return
        now = time.monotonic()
        if now - self._last_seen <= t:
            self._backoff.reset()
            return
        if now < self._next_reconnect:
            return
        self._next_reconnect = now + self._backoff.next_delay()
        try:
            self._reopen()
        except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (mints below)
            _obs.degrade(
                "stream.liveness", "reconnecting", "reconnect failed",
                f"socket re-open failed ({type(e).__name__}); retrying "
                "on the backoff ladder", warn=False)
            return
        self.stats["reconnects"] += 1
        _obs.get_recorder().count("stream_reconnects")
        _obs.degrade(
            "stream.liveness", "connected", "reconnecting",
            f"no {self._what} traffic past liveness_timeout_s={t}; "
            "re-dialing with bounded backoff", warn=False)


def _msgpack():
    import msgpack
    return msgpack


def _zmq():
    import zmq
    return zmq


# --------------------------------------------------------------- VDI stream

class VDIPublisher(_HeartbeatPacer):
    """PUB endpoint streaming (metadata, color, depth) per frame.

    ``precision="qpack8"`` runs the sort-last wire quantizer
    (ops.wire.qpack8_quantize_np; docs/PERF.md "Wire formats") as a
    pre-codec pass on every frame: buffers shrink 4× BEFORE the byte
    codec, the [near, far] scale and the precision tag travel in the
    frame header, and the metadata's ``precision`` field is stamped so
    subscribers (which dequantize transparently) and any archived
    headers agree on what the bytes are. Lossy by the wire contract."""

    def __init__(self, bind: str = "tcp://*:6655", codec: str = "zstd",
                 level: int = -1, precision: str = "f32",
                 fault: Optional[FaultConfig] = None,
                 epoch: Optional[int] = None,
                 delta: Optional[DeltaConfig] = None,
                 encode_workers: int = 1):
        from scenery_insitu_tpu.io.vdi_io import resolve_codec

        if precision not in ("f32", "qpack8"):
            raise ValueError(f"precision must be 'f32' or 'qpack8', "
                             f"got {precision!r}")
        if encode_workers < 1:
            raise ValueError(f"encode_workers must be >= 1, "
                             f"got {encode_workers}")
        # temporal-delta wire codec (docs/PERF.md "Temporal deltas"):
        # per-tile SKIP / residual / I-tile records against the retained
        # previous frame. Code-space comparison is only exact on the
        # monotone qpack8 quantizer, so f32 + delta is a config error.
        self._delta = None
        if delta is not None and delta.enabled:
            if precision != "qpack8":
                raise ValueError(
                    "delta.enabled requires precision='qpack8' (the "
                    "P-frame codec compares qpack8 code space)")
            from scenery_insitu_tpu.ops.delta import DeltaEncoder

            self._delta = DeltaEncoder(delta.iframe_period)
        # parallel tile encode (docs/PERF.md "Async delivery"): the
        # column-block tile is the independent unit, so the per-tile
        # quantize/compress/CRC work of publish_tile fans out across a
        # small thread pool; wire messages still post in submission
        # (ascending column) order, so delivered bytes are bit-identical
        # to the serial path. The temporal-delta codec is stateful per
        # tile key (encode order IS the codec state), so delta forces
        # the serial path — ledgered, not silent.
        self.encode_workers = int(encode_workers)
        if self.encode_workers > 1 and self._delta is not None:
            from scenery_insitu_tpu import obs as _obs
            _obs.degrade("delivery.encode",
                         f"{self.encode_workers} encode workers",
                         "serial",
                         "temporal delta is stateful per tile (P-frame "
                         "records compare against the retained previous "
                         "tile), so parallel encode would race the "
                         "codec state", warn=False)
            self.encode_workers = 1
        self._pool = None
        self._enc_pending = deque()   # futures in tile submission order
        zmq = _zmq()
        # degrade the default codec when the optional zstandard package
        # is absent (the resolved name travels in every frame header, so
        # subscribers stay consistent)
        self.codec = resolve_codec(codec)
        self.level = level
        self.precision = precision
        self.fault = fault or FaultConfig()
        # stream continuity identity (docs/ROBUSTNESS.md): the epoch
        # names this publisher incarnation, seq counts every message
        # (frames, tiles AND heartbeats share one counter, so idle
        # heartbeats keep the continuity check alive)
        self.epoch = _make_epoch() if epoch is None else int(epoch)
        self.seq = 0
        self.last_bytes = {}       # header/color/depth sizes of last send
        self._last_send = time.monotonic()
        # serializes frame publishes with the optional background
        # heartbeat pump (zmq sockets are not thread-safe)
        self._send_lock = threading.Lock()
        self._hb_stop = None
        self._hb_thread = None
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUB)
        if bind.endswith(":0"):                      # ephemeral port for tests
            port = self.sock.bind_to_random_port(bind[:-2])
            self.endpoint = f"{bind[:-2].replace('*', '127.0.0.1')}:{port}"
        else:
            self.sock.bind(bind)
            self.endpoint = bind.replace("*", "127.0.0.1")

    def _next_seq(self) -> int:
        self.seq = (self.seq + 1) & SEQ_MASK
        return self.seq

    def heartbeat(self) -> None:
        """Send one idle heartbeat (single-part message carrying only
        the continuity header) — subscribers refresh their last-seen
        time and sequence tracking without receiving a frame."""
        with self._send_lock:
            self.sock.send(_msgpack().packb(
                {"hb": 1, "epoch": self.epoch, "seq": self._next_seq()}))
            self._last_send = time.monotonic()

    def start_heartbeats(self) -> None:
        """Opt-in background heartbeat pump (docs/ROBUSTNESS.md): a
        daemon thread fires ``maybe_heartbeat`` so supervised
        subscribers can tell a slow frame from a dead publisher even
        when the render loop is stalled inside a dispatch. Sends are
        lock-serialized with the frame publishes; ``close()`` stops the
        thread. Pair with ``VDISubscriber(fault=...)``."""
        if self._hb_thread is not None:
            return
        self._hb_stop = threading.Event()

        def pump():
            # wake at half the period so an idle gap is detected within
            # ~1.5 periods worst case
            while not self._hb_stop.wait(
                    self.fault.heartbeat_period_s / 2):
                self.maybe_heartbeat()

        self._hb_thread = threading.Thread(
            target=pump, daemon=True, name="vdi-publisher-heartbeat")
        self._hb_thread.start()

    def publish(self, vdi: VDI, meta: VDIMetadata) -> int:
        """Send one frame; returns wire bytes (≅ the compressed publish loop,
        VolumeFromFileExample.kt:974-1037). Any tile encodes still in
        flight post first — the frame message closes the frame AFTER its
        tiles, whatever the pool's timing."""
        self.flush_tiles()
        return self._send(vdi, meta, None)

    def publish_tile(self, vdi: VDI, meta: VDIMetadata, tile: int,
                     tiles: int, col0: int) -> int:
        """Send one finished column-block tile of a frame BEFORE the
        frame closes (the tile-wave delivery unit — docs/PERF.md "Tile
        waves"; wired to the session by `stream_tile_sink`). The
        multipart message is the frame format plus a ``tile`` header
        {tile, tiles, col0}; `VDISubscriber.receive_tile` returns the
        placement so a viewer can assemble the frame incrementally (or
        start a partial novel-view render on the columns it has).

        With ``encode_workers > 1`` the encode runs on the pool and the
        wire post is deferred (messages still go out in submission
        order; ``flush_tiles``/``publish`` forces them out) — the call
        then returns 0 and the flush accounts the bytes."""
        th = {"tile": int(tile), "tiles": int(tiles), "col0": int(col0)}
        if self.encode_workers > 1:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(
                    max_workers=self.encode_workers,
                    thread_name_prefix="vdi-encode")
            self._enc_pending.append(
                self._pool.submit(self._encode, vdi, meta, th))
            # bound the in-flight window: post (in order) anything the
            # pool already finished, and never hold more than 2x the
            # pool width of undelivered encodes
            while self._enc_pending and (
                    self._enc_pending[0].done()
                    or len(self._enc_pending) > 2 * self.encode_workers):
                self._post(*self._enc_pending.popleft().result())
            return 0
        return self._send(vdi, meta, th)

    def flush_tiles(self) -> int:
        """Post every deferred tile encode, in submission order; returns
        the wire bytes flushed. No-op on the serial path."""
        total = 0
        while self._enc_pending:
            total += self._post(*self._enc_pending.popleft().result())
        return total

    def _send(self, vdi: VDI, meta: VDIMetadata,
              tile: Optional[dict]) -> int:
        return self._post(*self._encode(vdi, meta, tile))

    def _encode(self, vdi: VDI, meta: VDIMetadata,
                tile: Optional[dict]):
        """Deterministic encode half (quantize, delta, compress, CRC,
        header fields sans seq) — pure per tile, safe on pool threads.
        The seq-dependent wire post lives in ``_post``."""
        fidx = int(np.asarray(meta.index))
        with _obs.get_recorder().span(
                "encode", frame=fidx,
                sink="vdi_publisher", codec=self.codec,
                precision=self.precision,
                **({"tile": tile["tile"]} if tile else {})):
            color = np.ascontiguousarray(np.asarray(vdi.color))
            depth = np.ascontiguousarray(np.asarray(vdi.depth))
            qscale = None
            dhead = None
            if self.precision == "qpack8":
                from scenery_insitu_tpu.ops.wire import (WIRE_CODES,
                                                         qpack8_quantize_np)

                color, depth, near, far = qpack8_quantize_np(color, depth)
                qscale = [float(near), float(far)]
                meta = meta._replace(
                    precision=np.int32(WIRE_CODES[self.precision]))
                if self._delta is not None:
                    # P-frame codec: the declared shapes stay the FULL
                    # tile's code shapes; the blobs carry the record's
                    # payload (ops/delta.py) and the delta header says
                    # how to re-split it
                    from scenery_insitu_tpu.io.vdi_io import (
                        pack_delta_blobs)

                    key = int(tile["tile"]) if tile else -1
                    drec = self._delta.encode(key, color, depth, near,
                                              far)
                    dhead, cblob, dblob = pack_delta_blobs(
                        drec, self.codec, self.level)
            else:
                # stamp what THIS frame ships — a meta that rode in from a
                # quantized hop must not mislabel the f32 buffers sent here
                meta = meta._replace(precision=np.int32(0))
            if dhead is None:
                cblob = compress(np.ascontiguousarray(color).tobytes(),
                                 self.codec, self.level)
                dblob = compress(np.ascontiguousarray(depth).tobytes(),
                                 self.codec, self.level)
            fields = {
                "codec": self.codec,
                "precision": self.precision,
                "qscale": qscale,
                "delta": dhead,
                "tile": tile,
                # integrity + continuity (docs/ROBUSTNESS.md): CRCs are
                # of the WIRE blobs, so truncation/corruption is caught
                # before any decompress/reshape runs on the subscriber
                "epoch": self.epoch,
                "crc": [zlib.crc32(cblob), zlib.crc32(dblob)],
                "color_shape": list(color.shape),
                "depth_shape": list(depth.shape),
                "meta": {f: np.asarray(getattr(meta, f)).tolist()
                         for f in _META_FIELDS},
                # frame lineage (docs/OBSERVABILITY.md "Fleet tracing"):
                # frame id + origin rank + origin wall clock ride every
                # frame-bytes message; old decoders ignore unknown keys
                "tc": trace_ctx(fidx, _obs.get_recorder().rank),
            }
        return fields, cblob, dblob, fidx, tile

    def _post(self, fields: dict, cblob: bytes, dblob: bytes,
              fidx: int, tile: Optional[dict]) -> int:
        """Wire half: mint the seq and send. Loop/worker thread only —
        posts must happen in tile order (the seq is the subscriber's
        continuity check), so this is never called from the pool."""
        lineage("tile" if tile else "publish", "send", fidx,
                **({"tile": tile["tile"]} if tile else {}))
        with self._send_lock:
            # seq is minted INSIDE the lock: a background heartbeat
            # claiming a later seq but reaching the wire first would
            # make this frame read as stale at the subscriber
            header = _msgpack().packb({**fields,
                                       "seq": self._next_seq()})
            self.sock.send_multipart([header, cblob, dblob])
            self._last_send = time.monotonic()
        self.last_bytes = {"header": len(header), "color": len(cblob),
                           "depth": len(dblob)}
        return len(header) + len(cblob) + len(dblob)

    def force_iframe(self) -> None:
        """Scene cut: drop the delta codec's retained tiles so every
        tile's next record is a full I-tile (a TF change or dataset
        swap makes residuals meaningless; counted ``iframe_forced``).
        No-op when the delta codec is off."""
        if self._delta is not None:
            self._delta.reset()

    @property
    def delta_stats(self) -> Optional[dict]:
        """The delta encoder's record/byte accounting (None when off)."""
        return None if self._delta is None else dict(self._delta.stats)

    def close(self) -> None:
        try:
            self.flush_tiles()     # deferred encodes must not be lost
        except Exception:
            pass
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
        self.sock.close(linger=0)


class VDISubscriber(_ReconnectSupervisor):
    """SUB endpoint for the streamed-VDI client (novel-view rendering of
    received VDIs via ops.vdi_render).

    Hardened against the wire (docs/ROBUSTNESS.md): every message is
    validated BEFORE decode — part count, header parse, per-blob CRC32,
    then decompressed byte counts against the declared shapes × itemsize
    — and a failing message comes back as a typed ``StreamDrop`` (never
    an exception). Sequence continuity (gaps, duplicates, publisher
    restarts) is tracked per epoch and ledgered (``stream.gap``);
    ``self.stats`` counts frames/drops/gaps/heartbeats/reconnects.

    Liveness supervision is OPT-IN: construct with ``fault=`` and the
    subscriber reconnects with bounded exponential backoff
    (``stream.liveness``) after ``liveness_timeout_s`` of silence —
    pair it with a publisher that pumps ``maybe_heartbeat()``, or a
    healthy-but-slow stream would be torn down mid-frame."""

    def __init__(self, connect: str = "tcp://localhost:6655",
                 fault: Optional[FaultConfig] = None):
        from scenery_insitu_tpu.ops.delta import DeltaDecoder

        self.connect = connect
        self.fault = fault or FaultConfig()
        self.last_epoch: Optional[int] = None
        self.last_seq: Optional[int] = None
        self.stats = {"frames": 0, "drops": 0, "gaps": 0, "stale": 0,
                      "heartbeats": 0, "epoch_changes": 0, "reconnects": 0,
                      "resyncs": 0}
        # wire bytes of the most recent multipart message (heartbeats
        # included) — the receive-side twin of VDIPublisher.last_bytes,
        # consumed by the hierarchical head assembler's dcn_bytes
        # accounting (parallel/hier.py)
        self.last_recv_bytes = 0
        # temporal-delta reconstruction state (docs/PERF.md "Temporal
        # deltas"): transparent — only messages carrying a delta header
        # consult it, and an epoch change resets it (the restarted
        # publisher's encoder shares no state with the old stream)
        self._delta = DeltaDecoder()
        # whole-frame transparency for `receive` (bugfix, ISSUE 13): a
        # consumer that joins a TILE-granular stream mid-frame must not
        # mistake one column block for the whole frame the metadata
        # describes — tile messages assemble here and only complete
        # frames surface
        self._assembler = None
        self._init_supervision(supervised=fault is not None)
        self._open()

    def _open(self) -> None:
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.SUB)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.sock.connect(self.connect)

    def _reopen(self) -> None:
        """A PUB/SUB reconnect is idempotent — worst case it
        re-subscribes to a healthy stream."""
        self.sock.close(linger=0)
        self._open()

    def receive(self, timeout_ms: Optional[int] = None
                ) -> Union[None, StreamDrop, Tuple[VDI, VDIMetadata]]:
        """Whole-frame receive. Whole-frame messages return directly;
        TILE messages (`VDIPublisher.publish_tile`) feed an internal
        `FrameAssembler` and only COMPLETE frames surface — pre-fix a
        tile message came back as if it were the frame its metadata
        describes (window_dims names the FULL width), so every
        whole-frame consumer (examples/vdi_client.py, the serve tier)
        silently rendered one column block as the scene. A consumer
        joining mid-stream therefore waits for the next frame whose
        tiles it saw from tile 0 — the same "first contact must wait"
        contract the temporal-delta codec has (a P/SKIP record before
        the first I-tile is a typed ``resync`` StreamDrop, never an
        error). Returns None on timeout, StreamDrop for refused
        messages, else (VDI, metadata)."""
        deadline = (None if timeout_ms is None
                    else time.monotonic() + timeout_ms / 1000.0)
        while True:
            wait = (None if deadline is None else
                    max(0, int((deadline - time.monotonic()) * 1000)))
            got = self.receive_tile(wait)
            if got is None or isinstance(got, StreamDrop):
                return got
            vdi, meta, tile = got
            if tile is None:
                return vdi, meta
            if self._assembler is None:
                self._assembler = FrameAssembler(fault=self.fault)
            out = self._assembler.add(vdi, meta, tile)
            if out is not None:
                return out
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def receive_tile(self, timeout_ms: Optional[int] = None
                     ) -> Union[None, StreamDrop,
                                Tuple[VDI, VDIMetadata, Optional[dict]]]:
        """Like `receive`, but also returns the tile placement header
        ({tile, tiles, col0}) of a `VDIPublisher.publish_tile` message —
        None for whole-frame messages. Tiles of frame f arrive in
        column order before frame f closes, so a viewer can assemble
        incrementally (see `FrameAssembler`).

        Returns None on timeout, a `StreamDrop` for a message that
        failed validation, or the decoded (VDI, meta, tile) tuple.
        Heartbeats are consumed internally (they refresh liveness and
        sequence tracking) and never surface."""
        deadline = (None if timeout_ms is None
                    else time.monotonic() + timeout_ms / 1000.0)
        while True:
            self._supervise()
            if deadline is not None:
                wait = max(0.0, deadline - time.monotonic())
                if not self.sock.poll(int(wait * 1000)):
                    return None
            elif not self.sock.poll(1000):
                continue          # blocking mode: re-check liveness 1/s
            parts = self.sock.recv_multipart()
            self.last_recv_bytes = sum(len(p) for p in parts)
            got = self._decode(parts)
            if got is _HEARTBEAT:
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                continue
            return got

    # ------------------------------------------------------- validation
    def _drop(self, kind: str, reason: str, epoch=None,
              seq=None, frame=None) -> StreamDrop:
        self.stats["drops"] += 1
        if kind == "stale":
            self.stats["stale"] += 1
        if kind == "resync":
            self.stats["resyncs"] += 1
        _obs.get_recorder().count("stream_drops")
        if kind == "resync":
            _obs.degrade(
                "stream.delta_resync", "stream message",
                "dropped before decode",
                "temporal-delta record without its base tile retained; "
                "recovering on the next I-tile (forced within "
                "delta.iframe_period frames)", warn=False)
        elif kind == "stale":
            _obs.degrade(
                "stream.gap", "stream message", "dropped before decode",
                "duplicate or reordered message", warn=False)
        else:
            _obs.degrade(
                "stream.integrity", "stream message",
                "dropped before decode",
                "failed integrity validation (checksum/size/shape/"
                "header)", warn=False)
        return StreamDrop(kind, reason, epoch, seq, frame)

    @staticmethod
    def _header_frame(h: dict) -> Optional[int]:
        """Best-effort frame index from a parsed header — StreamDrop
        bookkeeping only; the caller mints the drop itself."""
        try:
            return int(np.asarray(h["meta"]["index"]))
        except Exception:  # sitpu-lint: disable=SITPU-LEDGER (bookkeeping; the caller mints the drop)
            return None

    def _track_continuity(self, h: dict) -> Optional[StreamDrop]:
        """Update epoch/seq tracking from one parsed header; returns a
        StreamDrop for stale (duplicate/reordered) messages, else None.
        Messages from pre-continuity publishers (no epoch/seq) pass."""
        epoch, seq = h.get("epoch"), h.get("seq")
        if epoch is None or seq is None:
            return None
        if self.last_epoch is not None and epoch != self.last_epoch:
            self.stats["epoch_changes"] += 1
            _obs.degrade("stream.gap", f"epoch {self.last_epoch}",
                         f"epoch {epoch}",
                         "publisher restarted (epoch changed); sequence "
                         "tracking reset", warn=False)
            self.last_seq = None
            # the restarted publisher's delta encoder starts fresh — its
            # first record per tile is an I-tile, so dropping the old
            # retained tiles loses nothing and can never patch a new
            # residual onto a stale base
            self._delta.reset()
            # partial tile frames from the old incarnation can never
            # complete (its frame indices restart too) — drop them
            # rather than pasting old-epoch tiles into new-epoch frames
            self._assembler = None
        self.last_epoch = epoch
        if self.last_seq is not None:
            d = seq_delta(seq, self.last_seq)
            if d <= 0:
                return self._drop("stale",
                                  f"seq {seq} after {self.last_seq}",
                                  epoch, seq, self._header_frame(h))
            if d > 1:
                self.stats["gaps"] += d - 1
                _obs.get_recorder().count("stream_gap_messages", d - 1)
                _obs.degrade("stream.gap", "contiguous sequence",
                             f"{d - 1} message(s) missing",
                             "sequence gap detected on the VDI stream",
                             warn=False)
        self.last_seq = seq
        return None

    def _decode(self, parts):
        """Validate one multipart message and decode it, or explain why
        not. Order matters: cheap checks (part count, header parse,
        CRC of the wire blobs) run before any decompress/reshape."""
        self._last_seen = time.monotonic()
        self._backoff.reset()
        msgpack = _msgpack()
        if len(parts) == 1:
            try:
                h = msgpack.unpackb(parts[0])
            except Exception:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
                return self._drop("malformed", "unparseable single-part "
                                               "message")
            if isinstance(h, dict) and h.get("hb"):
                self.stats["heartbeats"] += 1
                # a stale/duplicated heartbeat is counted by the
                # continuity tracker but carries no frame — heartbeats
                # NEVER surface to the caller
                self._track_continuity(h)
                return _HEARTBEAT
            return self._drop("integrity", "single-part message is not "
                                           "a heartbeat")
        if len(parts) != 3:
            return self._drop("integrity",
                              f"expected 3 parts, got {len(parts)} "
                              "(truncated multipart)")
        header, cblob, dblob = parts
        try:
            h = msgpack.unpackb(header)
            if not isinstance(h, dict):
                raise TypeError("header is not a map")
            cshape = tuple(int(x) for x in h["color_shape"])
            dshape = tuple(int(x) for x in h["depth_shape"])
            codec = h["codec"]
        except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
            return self._drop("malformed", f"bad header: {e!r}")
        epoch, seq = h.get("epoch"), h.get("seq")
        fidx = self._header_frame(h)
        # continuity first, ONCE: a message that is both stale and
        # corrupt is one refusal, not two ledger rows. A corrupt blob
        # still advances seq tracking — the header parsed, so the
        # message was received-and-refused, not missing (no spurious
        # gap on its successor).
        stale = self._track_continuity(h)
        if stale is not None:
            return stale
        crc = h.get("crc")
        if crc is not None and list(crc) != [zlib.crc32(cblob),
                                             zlib.crc32(dblob)]:
            return self._drop("integrity", "blob checksum mismatch",
                              epoch, seq, fidx)
        precision = h.get("precision", "f32")
        dh = h.get("delta")
        cdt, ddt = ((np.uint32, np.uint16) if precision == "qpack8"
                    else (np.float32, np.float32))
        try:
            craw = (decompress(cblob, codec) if cblob else b"")
            draw = (decompress(dblob, codec) if dblob else b"")
        except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
            return self._drop("integrity", f"decompress failed: {e!r}",
                              epoch, seq, fidx)
        if dh is not None:
            # delta records declare the FULL tile's shapes but carry a
            # record payload — the expected byte counts come from the
            # delta header instead (io/vdi_io.delta_expected_bytes)
            from scenery_insitu_tpu.io.vdi_io import delta_expected_bytes

            try:
                want_c, want_d = delta_expected_bytes(dh, cshape, dshape)
            except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
                return self._drop("malformed",
                                  f"bad delta header: {e!r}", epoch,
                                  seq, fidx)
        else:
            want_c = int(np.prod(cshape)) * np.dtype(cdt).itemsize
            want_d = int(np.prod(dshape)) * np.dtype(ddt).itemsize
        if len(craw) != want_c or len(draw) != want_d:
            # a truncated/corrupt blob must be rejected HERE — handing
            # it to frombuffer/reshape is the pre-PR crash
            return self._drop(
                "integrity",
                f"blob bytes ({len(craw)}, {len(draw)}) != declared "
                f"shapes ({want_c}, {want_d})", epoch, seq, fidx)
        if dh is not None:
            # temporal-delta reconstruction: (retained tile + record) ->
            # the current frame's qpack8 codes, bit-exact. A record
            # whose base the decoder does not hold (an earlier message
            # was dropped) is a resync wait, not an error.
            from scenery_insitu_tpu.io.vdi_io import unpack_delta_payload
            from scenery_insitu_tpu.ops.wire import qpack8_dequantize_np

            try:
                cpay, dpay = unpack_delta_payload(dh, craw, draw,
                                                  cshape, dshape)
                tile_h = h.get("tile")
                key = int(tile_h["tile"]) if tile_h else -1
                near, far = h["qscale"]
                got = self._delta.apply(key, dh["mode"], int(dh["gen"]),
                                        int(dh["base"]), cpay, dpay,
                                        (float(near), float(far)))
            except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
                return self._drop("integrity",
                                  f"delta decode failed: {e!r}",
                                  epoch, seq, fidx)
            if got is None:
                return self._drop(
                    "resync", f"{dh['mode']} record for tile {key} "
                              f"patches generation {dh['base']} which "
                              "is not retained", epoch, seq, fidx)
            qc, qd, near, far = got
            try:
                color, depth = qpack8_dequantize_np(qc, qd, near, far)
                meta = self._unpack_meta(h)
            except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
                return self._drop("integrity", f"decode failed: {e!r}",
                                  epoch, seq, fidx)
            self.stats["frames"] += 1
            lineage("tile" if h.get("tile") else "publish", "recv",
                    fidx, ctx=h.get("tc"))
            return VDI(color, depth), meta, h.get("tile")
        try:
            if precision == "qpack8":
                # the publisher's pre-codec quantize pass (header
                # carries the [near, far] scale): dequantize back to f32
                from scenery_insitu_tpu.ops.wire import (
                    qpack8_dequantize_np)

                qc = np.frombuffer(craw, np.uint32).reshape(cshape)
                qd = np.frombuffer(draw, np.uint16).reshape(dshape)
                near, far = h["qscale"]
                color, depth = qpack8_dequantize_np(qc, qd, near, far)
            else:
                color = np.frombuffer(craw, np.float32).reshape(cshape)
                depth = np.frombuffer(draw, np.float32).reshape(dshape)
            meta = self._unpack_meta(h)
        except Exception as e:  # sitpu-lint: disable=SITPU-LEDGER (drops mint via _drop)
            return self._drop("integrity", f"decode failed: {e!r}",
                              epoch, seq, fidx)
        self.stats["frames"] += 1
        lineage("tile" if h.get("tile") else "publish", "recv",
                fidx, ctx=h.get("tc"))
        return VDI(color, depth), meta, h.get("tile")

    @staticmethod
    def _unpack_meta(h: dict) -> VDIMetadata:
        m = h["meta"]
        return VDIMetadata.create(
            projection=np.asarray(m["projection"], np.float32),
            view=np.asarray(m["view"], np.float32),
            model=np.asarray(m["model"], np.float32),
            volume_dims=np.asarray(m["volume_dims"], np.float32),
            window_dims=np.asarray(m["window_dims"], np.int32),
            nw=float(np.asarray(m["nw"])),
            index=int(np.asarray(m["index"])),
            precision=int(np.asarray(m.get("precision", 0))))

    def close(self) -> None:
        self.sock.close(linger=0)


class FrameAssembler:
    """Assemble `publish_tile` streams back into whole frames — the
    ``VideoReceiver._parts`` eviction pattern, generalized to the VDI
    tile stream (docs/ROBUSTNESS.md "Degraded frames").

    Feed it every successful `receive_tile` result; whole-frame messages
    pass straight through, tile messages accumulate per frame index and
    the frame is returned once all tiles arrived (pasted in col0 order).
    An incomplete frame is ABANDONED — ledgered ``stream.gap``, counted
    in ``stats["abandoned"]`` — once ``window`` newer frames have
    started, so one lost tile costs one frame, not unbounded memory."""

    def __init__(self, window: Optional[int] = None,
                 fault: Optional[FaultConfig] = None):
        if window is None:
            # the config-threaded default: FrameworkConfig.fault
            # (pass a session's cfg.fault here so the knob is live)
            window = (fault or FaultConfig()).assembler_window
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self._frames = {}   # frame index -> {tiles: {t: (col0, vdi)},
                            #                 total, meta}
        self._newest = None  # newest frame index ever seen
        self.stats = {"assembled": 0, "abandoned": 0, "tiles": 0,
                      "passthrough": 0, "late_tiles": 0}

    def add(self, vdi: VDI, meta: VDIMetadata, tile: Optional[dict]
            ) -> Optional[Tuple[VDI, VDIMetadata]]:
        """Returns the completed (VDI, meta) when this message closed a
        frame (or was a whole-frame message), else None."""
        if tile is None:
            self.stats["passthrough"] += 1
            return vdi, meta
        idx = int(np.asarray(meta.index))
        if self._newest is not None and idx < self._newest - self.window:
            # straggler tile of a frame already past the eviction
            # horizon (assembled or abandoned) — re-creating its entry
            # would re-abandon it once per late tile
            self.stats["late_tiles"] += 1
            return None
        self._newest = (idx if self._newest is None
                        else max(self._newest, idx))
        entry = self._frames.setdefault(
            idx, {"tiles": {}, "total": int(tile["tiles"]), "meta": meta})
        entry["tiles"][int(tile["tile"])] = (int(tile["col0"]), vdi)
        self.stats["tiles"] += 1
        self._evict(newest=self._newest)
        if idx not in self._frames \
                or len(entry["tiles"]) < entry["total"]:
            return None
        del self._frames[idx]
        placed = sorted(entry["tiles"].values(), key=lambda cv: cv[0])
        color = np.concatenate([np.asarray(v.color) for _, v in placed],
                               axis=-1)
        depth = np.concatenate([np.asarray(v.depth) for _, v in placed],
                               axis=-1)
        self.stats["assembled"] += 1
        return VDI(color, depth), entry["meta"]

    def _evict(self, newest: int) -> None:
        for old in [f for f in self._frames if f < newest - self.window]:
            del self._frames[old]
            self.stats["abandoned"] += 1
            _obs.get_recorder().count("frames_abandoned")
            _obs.degrade(
                "stream.gap", "complete tile frame",
                "frame abandoned incomplete",
                f"tile loss: a frame was still incomplete after "
                f"{self.window} newer frames started", warn=False)


# ----------------------------------------------------------------- steering

def make_camera_message(cam: Camera) -> dict:
    """Viewer -> renderer camera pose (≅ the msgpack camera payload,
    VolumeFromFileExample.kt:907-918). Carries the FULL camera —
    near/far included: the serve tier re-renders through this pose, and
    the near plane participates in ray generation, so an elided clip
    range would silently shift every served pixel (steering consumers
    ignore the extra fields)."""
    return {"type": "camera",
            "eye": np.asarray(cam.eye).tolist(),
            "target": np.asarray(cam.target).tolist(),
            "up": np.asarray(cam.up).tolist(),
            "fov_y": float(np.asarray(cam.fov_y)),
            "near": float(np.asarray(cam.near)),
            "far": float(np.asarray(cam.far))}


def make_tf_message(points, colormap: str = "hot") -> dict:
    """Viewer -> renderer transfer-function update (≅ updateVis's TF
    payload, DistributedVolumeRenderer.kt:747-774 — there dispatched by
    payload size, here an explicit type). ``points`` are (value, alpha)
    control points; the renderer rebuilds its TF and recompiles the
    affected steps (rare user action; knot arrays are fixed-shape, so
    the pipeline shapes never change)."""
    return {"type": "tf",
            "points": [[float(v), float(a)] for v, a in points],
            "colormap": str(colormap)}


def tf_from_message(msg: dict):
    """Build the TransferFunction a 'tf' steering message describes."""
    from scenery_insitu_tpu.core.transfer import TransferFunction

    return TransferFunction.points(
        [tuple(p) for p in msg["points"]],
        colormap=msg.get("colormap", "hot"))


def apply_steering(cam: Camera, msg: dict) -> Tuple[Camera, dict]:
    """Apply one steering message; returns (camera, side_effects). Unknown
    types pass through in side_effects (≅ updateVis dispatch,
    DistributedVolumeRenderer.kt:747-774 — there by payload size, here by
    the explicit type tag)."""
    import jax.numpy as jnp

    kind = msg.get("type")
    if kind == "camera":
        cam = cam._replace(
            eye=jnp.asarray(msg["eye"], jnp.float32),
            target=jnp.asarray(msg.get("target", np.asarray(cam.target)),
                               jnp.float32),
            up=jnp.asarray(msg.get("up", np.asarray(cam.up)), jnp.float32))
        if "fov_y" in msg:
            cam = cam._replace(fov_y=jnp.float32(msg["fov_y"]))
        return cam, {}
    return cam, {kind: msg}


class SteeringEndpoint(_ReconnectSupervisor):
    """Renderer-side SUB socket draining steering messages each frame.

    The socket is network-facing: one malformed or oversized message
    must not kill an in-situ run mid-simulation. ``drain`` therefore
    validates per message — size cap first (before unpack), then msgpack
    parse, then "is it a dict" — drops failures on the
    ``stream.steering`` ledger and KEEPS draining. Heartbeats
    (``{"hb": 1}``) refresh liveness and are consumed; past
    ``fault.liveness_timeout_s`` with no traffic the endpoint re-opens
    its socket with bounded backoff (liveness is opt-in here: steering
    is bursty, so the default FaultConfig applies only when ``fault`` is
    passed — pass one to enable supervision)."""

    def __init__(self, connect_or_bind: str = "tcp://*:6656",
                 bind: bool = True, fault: Optional[FaultConfig] = None):
        # None = liveness supervision off (idle viewers are normal);
        # the size cap still applies with the default FaultConfig
        self.fault = fault or FaultConfig()
        self.bind = bind
        self.stats = {"messages": 0, "dropped": 0, "heartbeats": 0,
                      "reconnects": 0}
        self._init_supervision(supervised=fault is not None)
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.SUB)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        if bind and connect_or_bind.endswith(":0"):
            port = self.sock.bind_to_random_port(connect_or_bind[:-2])
            # the REAL re-bindable address keeps the wildcard host; the
            # display/connect endpoint rewrites it for local viewers
            self._addr = f"{connect_or_bind[:-2]}:{port}"
            self.endpoint = (f"{connect_or_bind[:-2].replace('*', '127.0.0.1')}"
                             f":{port}")
        elif bind:
            self.sock.bind(connect_or_bind)
            self._addr = connect_or_bind
            self.endpoint = connect_or_bind.replace("*", "127.0.0.1")
        else:
            self.sock.connect(connect_or_bind)
            self._addr = connect_or_bind
            self.endpoint = connect_or_bind

    def _reopen(self) -> None:
        """Tear down and re-establish the socket on the ORIGINAL address
        (a '*' bind must stay a wildcard bind — rewriting it to the
        loopback display form would cut off every remote viewer)."""
        zmq = _zmq()
        self.sock.close(linger=0)
        self.sock = self.ctx.socket(zmq.SUB)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        if self.bind:
            self.sock.bind(self._addr)
        else:
            self.sock.connect(self._addr)

    _what = "steering"

    def _drop_steering(self, why: str) -> None:
        self.stats["dropped"] += 1
        _obs.get_recorder().count("steering_drops")
        _obs.degrade("stream.steering", "steering message", "dropped",
                     why, warn=False)

    def drain(self) -> Iterator[dict]:
        zmq = _zmq()
        self._supervise()
        while True:
            try:
                raw = self.sock.recv(zmq.NOBLOCK)
            except zmq.Again:
                return
            self._last_seen = time.monotonic()
            if len(raw) > self.fault.max_message_bytes:
                self._drop_steering(
                    "message exceeds fault.max_message_bytes")
                continue
            try:
                msg = _msgpack().unpackb(raw)
            except Exception:
                self._drop_steering("unparseable msgpack from the "
                                    "network-facing socket")
                continue
            if not isinstance(msg, dict):
                self._drop_steering("steering payload is not a map")
                continue
            if msg.get("hb"):
                self.stats["heartbeats"] += 1
                continue
            self.stats["messages"] += 1
            yield msg

    def close(self) -> None:
        self.sock.close(linger=0)


class SteeringPublisher(_HeartbeatPacer):
    """Viewer-side PUB socket (≅ the ZMQ publisher feeding InSituMaster)."""

    def __init__(self, connect: str,
                 fault: Optional[FaultConfig] = None):
        zmq = _zmq()
        self.fault = fault or FaultConfig()
        self._last_send = time.monotonic()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUB)
        self.sock.connect(connect)

    def send(self, msg: dict) -> None:
        self.sock.send(_msgpack().packb(msg))
        self._last_send = time.monotonic()

    def heartbeat(self) -> None:
        """Idle keepalive so a supervised SteeringEndpoint can tell a
        quiet viewer from a dead one."""
        self.send({"hb": 1})

    def close(self) -> None:
        self.sock.close(linger=0)


class SteeringRelay:
    """Headless relay: SUB upstream, PUB to every render endpoint
    (≅ InSituMaster forwarding payloads to all ranks via MPI broadcast,
    InSituMaster.kt:14-45 — here the fan-out is a PUB socket)."""

    def __init__(self, upstream_bind: str = "tcp://*:6655",
                 downstream_bind: str = "tcp://*:6656"):
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sub = self.ctx.socket(zmq.SUB)
        self.sub.setsockopt(zmq.SUBSCRIBE, b"")
        self.pub = self.ctx.socket(zmq.PUB)
        for sock, ep in ((self.sub, upstream_bind), (self.pub, downstream_bind)):
            if ep.endswith(":0"):
                port = sock.bind_to_random_port(ep[:-2])
                ep = f"{ep[:-2].replace('*', '127.0.0.1')}:{port}"
            else:
                sock.bind(ep)
                ep = ep.replace("*", "127.0.0.1")
            if sock is self.sub:
                self.upstream = ep
            else:
                self.downstream = ep

    def pump(self, max_messages: int = 64) -> int:
        """Forward pending messages; returns count."""
        zmq = _zmq()
        n = 0
        for _ in range(max_messages):
            try:
                self.pub.send(self.sub.recv(zmq.NOBLOCK))
                n += 1
            except zmq.Again:
                break
        return n

    def close(self) -> None:
        self.sub.close(linger=0)
        self.pub.close(linger=0)


def stream_tile_sink(publisher: VDIPublisher) -> Callable[[int, dict], None]:
    """Session TILE sink (``InSituSession.tile_sinks``) publishing every
    delivered column-block tile the moment the session fetches it —
    paired with ``composite.schedule = "waves"``, subscribers see the
    frame's first columns while later tiles are still in flight
    (docs/PERF.md "Tile waves"). Tile payloads arrive as host numpy
    arrays and are published as-is — no device round trip on the
    latency-motivated path."""

    def sink(index: int, payload: dict) -> None:
        if "vdi_color" not in payload or "tile" not in payload:
            return
        publisher.publish_tile(
            VDI(payload["vdi_color"], payload["vdi_depth"]),
            payload["meta"], payload["tile"], payload["tiles"],
            payload["col0"])

    return sink


def stream_sink(publisher: VDIPublisher) -> Callable[[int, dict], None]:
    """Session sink that publishes every fetched VDI frame (≅ transmitVDIs
    mode, VolumeFromFileExample.kt:996-1037). Requires payloads carrying
    ``meta`` (InSituSession provides it)."""
    import jax.numpy as jnp

    def sink(index: int, payload: dict) -> None:
        if "vdi_color" not in payload or "meta" not in payload:
            return
        publisher.publish(VDI(jnp.asarray(payload["vdi_color"]),
                              jnp.asarray(payload["vdi_depth"])),
                          payload["meta"])

    return sink


# -------------------------------------------------------- live video stream

class VideoStreamer:
    """LIVE video over UDP (≅ the reference's H264/UDP:3337 stream,
    DistributedVolumeRenderer.kt:275-291). This image ships no
    ffmpeg/libx264, so frames go out as JPEG (cv2.imencode) — the MJPEG
    transport role of the reference's stream, same socket shape. Frames
    larger than one datagram are chunked ``[magic, frame, part, nparts,
    t_origin | payload]``; receivers reassemble and drop incomplete
    frames (UDP semantics: newest complete frame wins, stalls never
    block the renderer). ``t_origin`` (f64 unix seconds, stamped once
    per frame) is the frame-lineage trace context of this hop
    (docs/OBSERVABILITY.md "Fleet tracing")."""

    MAGIC = b"SIVD"
    CHUNK = 60000
    HEADER = "!4sIHHd"
    HEADER_BYTES = 20

    def __init__(self, host: str = "127.0.0.1", port: int = 3337,
                 quality: int = 85, gamma: float = 2.2):
        import socket

        self.addr = (host, port)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.quality = quality
        self.gamma = gamma
        self.frame_id = 0

    def send_frame(self, img: np.ndarray) -> int:
        """img f32[4, H, W] premultiplied -> JPEG datagrams; returns bytes
        sent."""
        import struct

        import cv2

        from scenery_insitu_tpu import obs as _obs

        with _obs.get_recorder().span("encode", frame=self.frame_id,
                                      sink="video_streamer"):
            rgb = np.clip(np.asarray(img[:3]), 0.0, 1.0) ** (1.0 / self.gamma)
            frame = (np.moveaxis(rgb, 0, -1) * 255).astype(np.uint8)
            ok, jpg = cv2.imencode(".jpg", frame[:, :, ::-1],
                                   [cv2.IMWRITE_JPEG_QUALITY, self.quality])
        if not ok:
            return 0
        blob = jpg.tobytes()
        nparts = -(-len(blob) // self.CHUNK)
        sent = 0
        t_origin = time.time()
        for p in range(nparts):
            payload = blob[p * self.CHUNK:(p + 1) * self.CHUNK]
            head = struct.pack(self.HEADER, self.MAGIC,
                               self.frame_id & 0xFFFFFFFF, p, nparts,
                               t_origin)
            sent += self.sock.sendto(head + payload, self.addr)
        lineage("video", "send", self.frame_id)
        # wrap in lockstep with the u32 wire field — the receiver's
        # eviction compares in wrap-aware sequence space (seq_delta)
        self.frame_id = (self.frame_id + 1) & SEQ_MASK
        return sent

    def close(self) -> None:
        self.sock.close()


class VideoReceiver:
    """Receiving end of VideoStreamer (a viewer/monitor process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 3337,
                 timeout_s: float = 1.0):
        import socket

        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.settimeout(timeout_s)
        self.port = self.sock.getsockname()[1]
        self._parts = {}

    def receive_frame(self) -> Optional[np.ndarray]:
        """Blocks up to the timeout for one COMPLETE frame -> u8[H, W, 3]
        RGB, or None."""
        import socket as _socket
        import struct

        import cv2

        deadline = time.monotonic() + self.sock.gettimeout()
        while time.monotonic() < deadline:
            try:
                pkt, _ = self.sock.recvfrom(65536)
            except (_socket.timeout, TimeoutError):
                return None
            hb = VideoStreamer.HEADER_BYTES
            if len(pkt) < hb or pkt[:4] != VideoStreamer.MAGIC:
                continue
            _, fid, part, nparts, t_origin = struct.unpack(
                VideoStreamer.HEADER, pkt[:hb])
            if nparts == 0 or part >= nparts:
                continue                                   # corrupt/foreign
            parts = self._parts.setdefault(fid, {})
            parts[part] = pkt[hb:]
            # evict incomplete older frames (lost datagrams must not
            # leak) — wrap-aware: the u32 frame id wraps on long
            # streams, and an unwrapped `f < fid - 4` would both leak
            # the pre-wrap entries forever and mis-evict post-wrap ones
            for old in [f for f in self._parts
                        if seq_delta(fid, f) > 4]:
                del self._parts[old]
            if all(p in parts for p in range(nparts)):
                blob = b"".join(parts[p] for p in range(nparts))
                del self._parts[fid]
                img = cv2.imdecode(np.frombuffer(blob, np.uint8),
                                   cv2.IMREAD_COLOR)
                if img is None:
                    continue
                lineage("viewer", "recv", int(fid),
                        ctx={"frame": int(fid), "t": t_origin})
                return img[:, :, ::-1]                     # BGR -> RGB
        return None

    def close(self) -> None:
        self.sock.close()


def _payload_image(payload: dict) -> Optional[np.ndarray]:
    """Session payload -> displayable premultiplied image (decodes VDI
    payloads to the same-view image). Shared by every video sink."""
    if "image" in payload:
        return payload["image"]
    if "vdi_color" in payload:
        import jax.numpy as jnp

        from scenery_insitu_tpu.core.vdi import render_vdi_same_view
        return np.asarray(render_vdi_same_view(
            VDI(jnp.asarray(payload["vdi_color"]),
                jnp.asarray(payload["vdi_depth"]))))
    return None


def live_video_sink(streamer: VideoStreamer) -> Callable[[int, dict], None]:
    """Session sink streaming every fetched frame live."""

    def sink(index: int, payload: dict) -> None:
        img = _payload_image(payload)
        if img is not None:
            streamer.send_frame(img)

    return sink


# -------------------------------------------------------------- video sinks

def _open_video_writer(path: str, fps: float, size: Tuple[int, int]):
    """Open a cv2 VideoWriter, preferring a real H264 encoder when the
    cv2 build ships one (the reference streams H264 —
    DistributedVolumeRenderer.kt:275-291 VideoEncoder → UDP:3337). Probes
    avc1/H264 and falls back to mp4v. This image's cv2 carries no
    libx264/openh264 and no ffmpeg/PyAV exists either (checked 2026-07-31),
    so mp4v is the expected outcome for THIS cv2 path; a guaranteed real
    H264 bitstream is available regardless via the vendored I_PCM writer
    (`io/h264.py`, ``video_sink(..., codec="h264_ipcm")``) — conformance
    pinned by decoding through cv2's H264 decoder in tests/test_h264.py.
    A failed probe may print cv2/ffmpeg codec errors to stderr once
    (native-layer prints, not exceptions); the fallback proceeds
    regardless. Returns (writer, fourcc_used)."""
    import cv2

    for cc in ("avc1", "H264"):
        try:
            w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*cc), fps, size)
        except cv2.error:
            continue
        if w.isOpened():
            return w, cc
        w.release()
    return (cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), fps,
                            size), "mp4v")


def video_sink(path: str, fps: float = 30.0, gamma: float = 2.2,
               codec: str = "auto") -> Callable[[int, dict], None]:
    """Movie-writer sink for session image payloads (≅ the reference's
    VideoEncoder movie file, DistributedVolumeRenderer.kt:285). Lazily opens
    the writer on the first frame (size unknown until then); the codec
    actually used is exposed as ``sink.codec`` after that.

    ``codec="auto"`` (default): cv2 writer, H264 when the build has an
    encoder, else mp4v (`_open_video_writer`). ``codec="h264_ipcm"``:
    the vendored always-available REAL H264 elementary stream
    (io/h264.h264_sink — all-intra I_PCM, lossless in YUV, large files;
    give ``path`` an .h264 extension so players treat it as an
    elementary stream)."""
    if codec == "h264_ipcm":
        from scenery_insitu_tpu.io.h264 import h264_sink

        inner = h264_sink(path, gamma=gamma, fps=fps)

        def sink(index: int, payload: dict) -> None:
            img = _payload_image(payload)
            if img is not None:
                inner(img)

        sink.codec = inner.codec
        sink.release = inner.close
        return sink
    if codec != "auto":
        raise ValueError(f"unknown video codec {codec!r} "
                         "(expected 'auto' or 'h264_ipcm')")
    state = {"writer": None}

    def sink(index: int, payload: dict) -> None:
        img = _payload_image(payload)
        if img is None:
            return
        rgb = np.clip(img[:3], 0.0, 1.0) ** (1.0 / gamma)
        frame = (np.moveaxis(rgb, 0, -1) * 255).astype(np.uint8)
        if state["writer"] is None:
            h, w = frame.shape[:2]
            state["writer"], sink.codec = _open_video_writer(
                path, fps, (w, h))
        state["writer"].write(frame[:, :, ::-1])          # RGB -> BGR

    sink.codec = None
    sink.release = lambda: (state["writer"].release()
                            if state["writer"] else None)
    return sink
