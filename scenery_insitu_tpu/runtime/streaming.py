"""Streaming + steering (SURVEY.md §7 step 10b, layer L7).

≅ the reference's side channels:
- ZMQ PUB of VDI frames ``[size-ascii | metadata | color | depth]`` with
  LZ4-compressed buffers (VolumeFromFileExample.kt:996-1037) →
  ``VDIPublisher``/``VDISubscriber`` multipart messages
  ``[msgpack header, color blob, depth blob]`` with io.vdi_io codecs.
- msgpack camera/steering messages applied inside the render loop,
  dispatched by payload size (DistributedVolumeRenderer.kt:747-774;
  Head.adjustCamera, Head.kt:137-161) → typed msgpack dicts with a
  ``"type"`` field, applied by ``apply_steering``.
- the headless InSituMaster relay that rebroadcasts viewer messages to all
  render ranks (InSituMaster.kt:14-45) → ``SteeringRelay``.
- H264/UDP video stream + movie writer (DistributedVolumeRenderer.kt:
  275-291) → ``video_sink`` (cv2 VideoWriter; this image has no ffmpeg/
  libx264, so the codec is what cv2 ships — the transport role, not the
  exact bitstream).

Everything degrades gracefully: constructing any endpoint raises
ImportError only when pyzmq is genuinely missing, and the session works
fully without streaming attached.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.io.vdi_io import compress, decompress

_META_FIELDS = VDIMetadata._fields


def _msgpack():
    import msgpack
    return msgpack


def _zmq():
    import zmq
    return zmq


# --------------------------------------------------------------- VDI stream

class VDIPublisher:
    """PUB endpoint streaming (metadata, color, depth) per frame.

    ``precision="qpack8"`` runs the sort-last wire quantizer
    (ops.wire.qpack8_quantize_np; docs/PERF.md "Wire formats") as a
    pre-codec pass on every frame: buffers shrink 4× BEFORE the byte
    codec, the [near, far] scale and the precision tag travel in the
    frame header, and the metadata's ``precision`` field is stamped so
    subscribers (which dequantize transparently) and any archived
    headers agree on what the bytes are. Lossy by the wire contract."""

    def __init__(self, bind: str = "tcp://*:6655", codec: str = "zstd",
                 level: int = -1, precision: str = "f32"):
        from scenery_insitu_tpu.io.vdi_io import resolve_codec

        if precision not in ("f32", "qpack8"):
            raise ValueError(f"precision must be 'f32' or 'qpack8', "
                             f"got {precision!r}")
        zmq = _zmq()
        # degrade the default codec when the optional zstandard package
        # is absent (the resolved name travels in every frame header, so
        # subscribers stay consistent)
        self.codec = resolve_codec(codec)
        self.level = level
        self.precision = precision
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUB)
        if bind.endswith(":0"):                      # ephemeral port for tests
            port = self.sock.bind_to_random_port(bind[:-2])
            self.endpoint = f"{bind[:-2].replace('*', '127.0.0.1')}:{port}"
        else:
            self.sock.bind(bind)
            self.endpoint = bind.replace("*", "127.0.0.1")

    def publish(self, vdi: VDI, meta: VDIMetadata) -> int:
        """Send one frame; returns wire bytes (≅ the compressed publish loop,
        VolumeFromFileExample.kt:974-1037)."""
        return self._send(vdi, meta, None)

    def publish_tile(self, vdi: VDI, meta: VDIMetadata, tile: int,
                     tiles: int, col0: int) -> int:
        """Send one finished column-block tile of a frame BEFORE the
        frame closes (the tile-wave delivery unit — docs/PERF.md "Tile
        waves"; wired to the session by `stream_tile_sink`). The
        multipart message is the frame format plus a ``tile`` header
        {tile, tiles, col0}; `VDISubscriber.receive_tile` returns the
        placement so a viewer can assemble the frame incrementally (or
        start a partial novel-view render on the columns it has)."""
        return self._send(vdi, meta,
                          {"tile": int(tile), "tiles": int(tiles),
                           "col0": int(col0)})

    def _send(self, vdi: VDI, meta: VDIMetadata,
              tile: Optional[dict]) -> int:
        from scenery_insitu_tpu import obs as _obs

        with _obs.get_recorder().span(
                "encode", frame=int(np.asarray(meta.index)),
                sink="vdi_publisher", codec=self.codec,
                precision=self.precision,
                **({"tile": tile["tile"]} if tile else {})):
            color = np.ascontiguousarray(np.asarray(vdi.color))
            depth = np.ascontiguousarray(np.asarray(vdi.depth))
            qscale = None
            if self.precision == "qpack8":
                from scenery_insitu_tpu.ops.wire import (WIRE_CODES,
                                                         qpack8_quantize_np)

                color, depth, near, far = qpack8_quantize_np(color, depth)
                qscale = [float(near), float(far)]
                meta = meta._replace(
                    precision=np.int32(WIRE_CODES[self.precision]))
            else:
                # stamp what THIS frame ships — a meta that rode in from a
                # quantized hop must not mislabel the f32 buffers sent here
                meta = meta._replace(precision=np.int32(0))
            cblob = compress(np.ascontiguousarray(color).tobytes(),
                             self.codec, self.level)
            dblob = compress(np.ascontiguousarray(depth).tobytes(),
                             self.codec, self.level)
            header = _msgpack().packb({
                "codec": self.codec,
                "precision": self.precision,
                "qscale": qscale,
                "tile": tile,
                "color_shape": list(color.shape),
                "depth_shape": list(depth.shape),
                "meta": {f: np.asarray(getattr(meta, f)).tolist()
                         for f in _META_FIELDS},
            })
        self.sock.send_multipart([header, cblob, dblob])
        return len(header) + len(cblob) + len(dblob)

    def close(self) -> None:
        self.sock.close(linger=0)


class VDISubscriber:
    """SUB endpoint for the streamed-VDI client (novel-view rendering of
    received VDIs via ops.vdi_render)."""

    def __init__(self, connect: str = "tcp://localhost:6655"):
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.SUB)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.sock.connect(connect)

    def receive(self, timeout_ms: Optional[int] = None
                ) -> Optional[Tuple[VDI, VDIMetadata]]:
        got = self.receive_tile(timeout_ms)
        return None if got is None else got[:2]

    def receive_tile(self, timeout_ms: Optional[int] = None
                     ) -> Optional[Tuple[VDI, VDIMetadata,
                                         Optional[dict]]]:
        """Like `receive`, but also returns the tile placement header
        ({tile, tiles, col0}) of a `VDIPublisher.publish_tile` message —
        None for whole-frame messages. Tiles of frame f arrive in
        column order before frame f closes, so a viewer can assemble
        incrementally: allocate on the first tile (tiles * width
        columns), paste each tile at its col0."""
        if timeout_ms is not None:
            if not self.sock.poll(timeout_ms):
                return None
        header, cblob, dblob = self.sock.recv_multipart()
        h = _msgpack().unpackb(header)
        precision = h.get("precision", "f32")
        if precision == "qpack8":
            # the publisher's pre-codec quantize pass (header carries the
            # [near, far] scale): dequantize back to the f32 convention
            from scenery_insitu_tpu.ops.wire import qpack8_dequantize_np

            qc = np.frombuffer(decompress(cblob, h["codec"]), np.uint32) \
                .reshape(h["color_shape"])
            qd = np.frombuffer(decompress(dblob, h["codec"]), np.uint16) \
                .reshape(h["depth_shape"])
            near, far = h["qscale"]
            color, depth = qpack8_dequantize_np(qc, qd, near, far)
        else:
            color = np.frombuffer(decompress(cblob, h["codec"]), np.float32) \
                .reshape(h["color_shape"])
            depth = np.frombuffer(decompress(dblob, h["codec"]), np.float32) \
                .reshape(h["depth_shape"])
        m = h["meta"]
        meta = VDIMetadata.create(
            projection=np.asarray(m["projection"], np.float32),
            view=np.asarray(m["view"], np.float32),
            model=np.asarray(m["model"], np.float32),
            volume_dims=np.asarray(m["volume_dims"], np.float32),
            window_dims=np.asarray(m["window_dims"], np.int32),
            nw=float(np.asarray(m["nw"])), index=int(np.asarray(m["index"])),
            precision=int(np.asarray(m.get("precision", 0))))
        return VDI(color, depth), meta, h.get("tile")

    def close(self) -> None:
        self.sock.close(linger=0)


# ----------------------------------------------------------------- steering

def make_camera_message(cam: Camera) -> dict:
    """Viewer -> renderer camera pose (≅ the msgpack camera payload,
    VolumeFromFileExample.kt:907-918)."""
    return {"type": "camera",
            "eye": np.asarray(cam.eye).tolist(),
            "target": np.asarray(cam.target).tolist(),
            "up": np.asarray(cam.up).tolist(),
            "fov_y": float(np.asarray(cam.fov_y))}


def make_tf_message(points, colormap: str = "hot") -> dict:
    """Viewer -> renderer transfer-function update (≅ updateVis's TF
    payload, DistributedVolumeRenderer.kt:747-774 — there dispatched by
    payload size, here an explicit type). ``points`` are (value, alpha)
    control points; the renderer rebuilds its TF and recompiles the
    affected steps (rare user action; knot arrays are fixed-shape, so
    the pipeline shapes never change)."""
    return {"type": "tf",
            "points": [[float(v), float(a)] for v, a in points],
            "colormap": str(colormap)}


def tf_from_message(msg: dict):
    """Build the TransferFunction a 'tf' steering message describes."""
    from scenery_insitu_tpu.core.transfer import TransferFunction

    return TransferFunction.points(
        [tuple(p) for p in msg["points"]],
        colormap=msg.get("colormap", "hot"))


def apply_steering(cam: Camera, msg: dict) -> Tuple[Camera, dict]:
    """Apply one steering message; returns (camera, side_effects). Unknown
    types pass through in side_effects (≅ updateVis dispatch,
    DistributedVolumeRenderer.kt:747-774 — there by payload size, here by
    the explicit type tag)."""
    import jax.numpy as jnp

    kind = msg.get("type")
    if kind == "camera":
        cam = cam._replace(
            eye=jnp.asarray(msg["eye"], jnp.float32),
            target=jnp.asarray(msg.get("target", np.asarray(cam.target)),
                               jnp.float32),
            up=jnp.asarray(msg.get("up", np.asarray(cam.up)), jnp.float32))
        if "fov_y" in msg:
            cam = cam._replace(fov_y=jnp.float32(msg["fov_y"]))
        return cam, {}
    return cam, {kind: msg}


class SteeringEndpoint:
    """Renderer-side SUB socket draining steering messages each frame."""

    def __init__(self, connect_or_bind: str = "tcp://*:6656", bind: bool = True):
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.SUB)
        self.sock.setsockopt(zmq.SUBSCRIBE, b"")
        if bind and connect_or_bind.endswith(":0"):
            port = self.sock.bind_to_random_port(connect_or_bind[:-2])
            self.endpoint = (f"{connect_or_bind[:-2].replace('*', '127.0.0.1')}"
                             f":{port}")
        elif bind:
            self.sock.bind(connect_or_bind)
            self.endpoint = connect_or_bind.replace("*", "127.0.0.1")
        else:
            self.sock.connect(connect_or_bind)
            self.endpoint = connect_or_bind

    def drain(self) -> Iterator[dict]:
        zmq = _zmq()
        while True:
            try:
                yield _msgpack().unpackb(self.sock.recv(zmq.NOBLOCK))
            except zmq.Again:
                return

    def close(self) -> None:
        self.sock.close(linger=0)


class SteeringPublisher:
    """Viewer-side PUB socket (≅ the ZMQ publisher feeding InSituMaster)."""

    def __init__(self, connect: str):
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUB)
        self.sock.connect(connect)

    def send(self, msg: dict) -> None:
        self.sock.send(_msgpack().packb(msg))

    def close(self) -> None:
        self.sock.close(linger=0)


class SteeringRelay:
    """Headless relay: SUB upstream, PUB to every render endpoint
    (≅ InSituMaster forwarding payloads to all ranks via MPI broadcast,
    InSituMaster.kt:14-45 — here the fan-out is a PUB socket)."""

    def __init__(self, upstream_bind: str = "tcp://*:6655",
                 downstream_bind: str = "tcp://*:6656"):
        zmq = _zmq()
        self.ctx = zmq.Context.instance()
        self.sub = self.ctx.socket(zmq.SUB)
        self.sub.setsockopt(zmq.SUBSCRIBE, b"")
        self.pub = self.ctx.socket(zmq.PUB)
        for sock, ep in ((self.sub, upstream_bind), (self.pub, downstream_bind)):
            if ep.endswith(":0"):
                port = sock.bind_to_random_port(ep[:-2])
                ep = f"{ep[:-2].replace('*', '127.0.0.1')}:{port}"
            else:
                sock.bind(ep)
                ep = ep.replace("*", "127.0.0.1")
            if sock is self.sub:
                self.upstream = ep
            else:
                self.downstream = ep

    def pump(self, max_messages: int = 64) -> int:
        """Forward pending messages; returns count."""
        zmq = _zmq()
        n = 0
        for _ in range(max_messages):
            try:
                self.pub.send(self.sub.recv(zmq.NOBLOCK))
                n += 1
            except zmq.Again:
                break
        return n

    def close(self) -> None:
        self.sub.close(linger=0)
        self.pub.close(linger=0)


def stream_tile_sink(publisher: VDIPublisher) -> Callable[[int, dict], None]:
    """Session TILE sink (``InSituSession.tile_sinks``) publishing every
    delivered column-block tile the moment the session fetches it —
    paired with ``composite.schedule = "waves"``, subscribers see the
    frame's first columns while later tiles are still in flight
    (docs/PERF.md "Tile waves"). Tile payloads arrive as host numpy
    arrays and are published as-is — no device round trip on the
    latency-motivated path."""

    def sink(index: int, payload: dict) -> None:
        if "vdi_color" not in payload or "tile" not in payload:
            return
        publisher.publish_tile(
            VDI(payload["vdi_color"], payload["vdi_depth"]),
            payload["meta"], payload["tile"], payload["tiles"],
            payload["col0"])

    return sink


def stream_sink(publisher: VDIPublisher) -> Callable[[int, dict], None]:
    """Session sink that publishes every fetched VDI frame (≅ transmitVDIs
    mode, VolumeFromFileExample.kt:996-1037). Requires payloads carrying
    ``meta`` (InSituSession provides it)."""
    import jax.numpy as jnp

    def sink(index: int, payload: dict) -> None:
        if "vdi_color" not in payload or "meta" not in payload:
            return
        publisher.publish(VDI(jnp.asarray(payload["vdi_color"]),
                              jnp.asarray(payload["vdi_depth"])),
                          payload["meta"])

    return sink


# -------------------------------------------------------- live video stream

class VideoStreamer:
    """LIVE video over UDP (≅ the reference's H264/UDP:3337 stream,
    DistributedVolumeRenderer.kt:275-291). This image ships no
    ffmpeg/libx264, so frames go out as JPEG (cv2.imencode) — the MJPEG
    transport role of the reference's stream, same socket shape. Frames
    larger than one datagram are chunked ``[magic, frame, part, nparts |
    payload]``; receivers reassemble and drop incomplete frames (UDP
    semantics: newest complete frame wins, stalls never block the
    renderer)."""

    MAGIC = b"SIVD"
    CHUNK = 60000

    def __init__(self, host: str = "127.0.0.1", port: int = 3337,
                 quality: int = 85, gamma: float = 2.2):
        import socket

        self.addr = (host, port)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.quality = quality
        self.gamma = gamma
        self.frame_id = 0

    def send_frame(self, img: np.ndarray) -> int:
        """img f32[4, H, W] premultiplied -> JPEG datagrams; returns bytes
        sent."""
        import struct

        import cv2

        from scenery_insitu_tpu import obs as _obs

        with _obs.get_recorder().span("encode", frame=self.frame_id,
                                      sink="video_streamer"):
            rgb = np.clip(np.asarray(img[:3]), 0.0, 1.0) ** (1.0 / self.gamma)
            frame = (np.moveaxis(rgb, 0, -1) * 255).astype(np.uint8)
            ok, jpg = cv2.imencode(".jpg", frame[:, :, ::-1],
                                   [cv2.IMWRITE_JPEG_QUALITY, self.quality])
        if not ok:
            return 0
        blob = jpg.tobytes()
        nparts = -(-len(blob) // self.CHUNK)
        sent = 0
        for p in range(nparts):
            payload = blob[p * self.CHUNK:(p + 1) * self.CHUNK]
            head = struct.pack("!4sIHH", self.MAGIC,
                               self.frame_id & 0xFFFFFFFF, p, nparts)
            sent += self.sock.sendto(head + payload, self.addr)
        self.frame_id += 1
        return sent

    def close(self) -> None:
        self.sock.close()


class VideoReceiver:
    """Receiving end of VideoStreamer (a viewer/monitor process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 3337,
                 timeout_s: float = 1.0):
        import socket

        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.settimeout(timeout_s)
        self.port = self.sock.getsockname()[1]
        self._parts = {}

    def receive_frame(self) -> Optional[np.ndarray]:
        """Blocks up to the timeout for one COMPLETE frame -> u8[H, W, 3]
        RGB, or None."""
        import socket as _socket
        import struct

        import cv2

        deadline = time.monotonic() + self.sock.gettimeout()
        while time.monotonic() < deadline:
            try:
                pkt, _ = self.sock.recvfrom(65536)
            except (_socket.timeout, TimeoutError):
                return None
            if len(pkt) < 12 or pkt[:4] != VideoStreamer.MAGIC:
                continue
            _, fid, part, nparts = struct.unpack("!4sIHH", pkt[:12])
            if nparts == 0 or part >= nparts:
                continue                                   # corrupt/foreign
            parts = self._parts.setdefault(fid, {})
            parts[part] = pkt[12:]
            # evict incomplete older frames (lost datagrams must not leak)
            for old in [f for f in self._parts if f < fid - 4]:
                del self._parts[old]
            if all(p in parts for p in range(nparts)):
                blob = b"".join(parts[p] for p in range(nparts))
                del self._parts[fid]
                img = cv2.imdecode(np.frombuffer(blob, np.uint8),
                                   cv2.IMREAD_COLOR)
                if img is None:
                    continue
                return img[:, :, ::-1]                     # BGR -> RGB
        return None

    def close(self) -> None:
        self.sock.close()


def _payload_image(payload: dict) -> Optional[np.ndarray]:
    """Session payload -> displayable premultiplied image (decodes VDI
    payloads to the same-view image). Shared by every video sink."""
    if "image" in payload:
        return payload["image"]
    if "vdi_color" in payload:
        import jax.numpy as jnp

        from scenery_insitu_tpu.core.vdi import render_vdi_same_view
        return np.asarray(render_vdi_same_view(
            VDI(jnp.asarray(payload["vdi_color"]),
                jnp.asarray(payload["vdi_depth"]))))
    return None


def live_video_sink(streamer: VideoStreamer) -> Callable[[int, dict], None]:
    """Session sink streaming every fetched frame live."""

    def sink(index: int, payload: dict) -> None:
        img = _payload_image(payload)
        if img is not None:
            streamer.send_frame(img)

    return sink


# -------------------------------------------------------------- video sinks

def _open_video_writer(path: str, fps: float, size: Tuple[int, int]):
    """Open a cv2 VideoWriter, preferring a real H264 encoder when the
    cv2 build ships one (the reference streams H264 —
    DistributedVolumeRenderer.kt:275-291 VideoEncoder → UDP:3337). Probes
    avc1/H264 and falls back to mp4v. This image's cv2 carries no
    libx264/openh264 and no ffmpeg/PyAV exists either (checked 2026-07-31),
    so mp4v is the expected outcome for THIS cv2 path; a guaranteed real
    H264 bitstream is available regardless via the vendored I_PCM writer
    (`io/h264.py`, ``video_sink(..., codec="h264_ipcm")``) — conformance
    pinned by decoding through cv2's H264 decoder in tests/test_h264.py.
    A failed probe may print cv2/ffmpeg codec errors to stderr once
    (native-layer prints, not exceptions); the fallback proceeds
    regardless. Returns (writer, fourcc_used)."""
    import cv2

    for cc in ("avc1", "H264"):
        try:
            w = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*cc), fps, size)
        except cv2.error:
            continue
        if w.isOpened():
            return w, cc
        w.release()
    return (cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), fps,
                            size), "mp4v")


def video_sink(path: str, fps: float = 30.0, gamma: float = 2.2,
               codec: str = "auto") -> Callable[[int, dict], None]:
    """Movie-writer sink for session image payloads (≅ the reference's
    VideoEncoder movie file, DistributedVolumeRenderer.kt:285). Lazily opens
    the writer on the first frame (size unknown until then); the codec
    actually used is exposed as ``sink.codec`` after that.

    ``codec="auto"`` (default): cv2 writer, H264 when the build has an
    encoder, else mp4v (`_open_video_writer`). ``codec="h264_ipcm"``:
    the vendored always-available REAL H264 elementary stream
    (io/h264.h264_sink — all-intra I_PCM, lossless in YUV, large files;
    give ``path`` an .h264 extension so players treat it as an
    elementary stream)."""
    if codec == "h264_ipcm":
        from scenery_insitu_tpu.io.h264 import h264_sink

        inner = h264_sink(path, gamma=gamma, fps=fps)

        def sink(index: int, payload: dict) -> None:
            img = _payload_image(payload)
            if img is not None:
                inner(img)

        sink.codec = inner.codec
        sink.release = inner.close
        return sink
    if codec != "auto":
        raise ValueError(f"unknown video codec {codec!r} "
                         "(expected 'auto' or 'h264_ipcm')")
    state = {"writer": None}

    def sink(index: int, payload: dict) -> None:
        img = _payload_image(payload)
        if img is None:
            return
        rgb = np.clip(img[:3], 0.0, 1.0) ** (1.0 / gamma)
        frame = (np.moveaxis(rgb, 0, -1) * 255).astype(np.uint8)
        if state["writer"] is None:
            h, w = frame.shape[:2]
            state["writer"], sink.codec = _open_video_writer(
                path, fps, (w, h))
        state["writer"].write(frame[:, :, ::-1])          # RGB -> BGR

    sink.codec = None
    sink.release = lambda: (state["writer"].release()
                            if state["writer"] else None)
    return sink
