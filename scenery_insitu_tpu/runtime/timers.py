"""Per-phase frame timing (≅ the reference's hand-rolled Timer data class +
nanoTime spans around every phase, dumped with totals and windowed averages
every 100 frames: DistributedVolumeRenderer.kt:85-108, 622-648, and the fps
CSV ``avg;min;max;stddev;n`` harness, VolumeFromFileExample.kt:777-794).

Also emits the machine-greppable per-iteration markers the reference's
compositing benchmark greps for (``#COMP:rank:iter:sec#`` style,
VDICompositingTest.kt:301,397-398).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional


class PhaseStats:
    __slots__ = ("values",)

    def __init__(self):
        self.values: List[float] = []

    def add(self, seconds: float) -> None:
        self.values.append(seconds)

    @property
    def n(self) -> int:
        return len(self.values)

    @property
    def total(self) -> float:
        return sum(self.values)

    @property
    def avg(self) -> float:
        return self.total / self.n if self.values else 0.0

    @property
    def vmin(self) -> float:
        return min(self.values) if self.values else 0.0

    @property
    def vmax(self) -> float:
        return max(self.values) if self.values else 0.0

    @property
    def stddev(self) -> float:
        if len(self.values) < 2:
            return 0.0
        m = self.avg
        return (sum((v - m) ** 2 for v in self.values) / (self.n - 1)) ** 0.5

    def csv(self) -> str:
        """`avg;min;max;stddev;n` — the reference's fps-CSV row format."""
        return (f"{self.avg:.6f};{self.vmin:.6f};{self.vmax:.6f};"
                f"{self.stddev:.6f};{self.n}")


class Timers:
    """Phase timer registry with windowed dumps.

    >>> t = Timers(window=100, log=print)
    >>> with t.phase("generate"): ...
    >>> t.frame_done()       # dumps stats every `window` frames
    """

    def __init__(self, window: int = 100, log=None, rank: int = 0):
        self.window = window
        self.log = log or (lambda s: None)
        self.rank = rank
        self.stats: Dict[str, PhaseStats] = defaultdict(PhaseStats)
        self.window_stats: Dict[str, PhaseStats] = defaultdict(PhaseStats)
        self.frames = 0

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.stats[name].add(dt)
            self.window_stats[name].add(dt)

    def record(self, name: str, seconds: float) -> None:
        self.stats[name].add(seconds)
        self.window_stats[name].add(seconds)

    def marker(self, tag: str, iteration: int, seconds: float) -> None:
        """Machine-greppable marker (≅ #COMP:rank:iter:sec#)."""
        self.log(f"#{tag}:{self.rank}:{iteration}:{seconds:.6f}#")

    def frame_done(self) -> None:
        self.frames += 1
        if self.frames % self.window == 0:
            self.dump_window()

    def dump_window(self) -> None:
        self.log(f"=== frame {self.frames} (window of {self.window}) ===")
        for name, st in sorted(self.window_stats.items()):
            self.log(f"  {name:>16}: avg {st.avg * 1e3:8.3f} ms  "
                     f"total {st.total:7.3f} s  n={st.n}")
        self.window_stats = defaultdict(PhaseStats)

    def csv(self) -> str:
        lines = ["phase;avg;min;max;stddev;n"]
        for name, st in sorted(self.stats.items()):
            lines.append(f"{name};{st.csv()}")
        return "\n".join(lines)

    def fps(self, phase: str = "frame") -> float:
        st = self.stats.get(phase)
        return 1.0 / st.avg if st and st.avg > 0 else 0.0
