"""Per-phase frame timing (≅ the reference's hand-rolled Timer data class +
nanoTime spans around every phase, dumped with totals and windowed averages
every 100 frames: DistributedVolumeRenderer.kt:85-108, 622-648, and the fps
CSV ``avg;min;max;stddev;n`` harness, VolumeFromFileExample.kt:777-794).

Also emits the machine-greppable per-iteration markers the reference's
compositing benchmark greps for (``#COMP:rank:iter:sec#`` style,
VDICompositingTest.kt:301,397-398).

Stats are running aggregates (n, sum, sumsq, min, max) — O(1) memory over
arbitrarily long campaigns.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional


class PhaseStats:
    __slots__ = ("n", "total", "sumsq", "vmin", "vmax")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.sumsq = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def add(self, seconds: float) -> None:
        self.n += 1
        self.total += seconds
        self.sumsq += seconds * seconds
        self.vmin = min(self.vmin, seconds)
        self.vmax = max(self.vmax, seconds)

    @property
    def avg(self) -> float:
        return self.total / self.n if self.n else 0.0

    @property
    def stddev(self) -> float:
        if self.n < 2:
            return 0.0
        var = (self.sumsq - self.total * self.total / self.n) / (self.n - 1)
        return math.sqrt(max(var, 0.0))

    def csv(self) -> str:
        """`avg;min;max;stddev;n` — the reference's fps-CSV row format."""
        vmin = 0.0 if self.n == 0 else self.vmin
        vmax = 0.0 if self.n == 0 else self.vmax
        return (f"{self.avg:.6f};{vmin:.6f};{vmax:.6f};"
                f"{self.stddev:.6f};{self.n}")


class Timers:
    """Phase timer registry with windowed dumps.

    >>> t = Timers(window=100, log=print)
    >>> with t.phase("generate"): ...
    >>> t.frame_done()       # dumps stats every `window` frames

    ``frame_done`` also records the wall time between consecutive calls as
    the implicit "frame" phase, so ``fps()`` reports end-to-end frame rate.
    """

    def __init__(self, window: int = 100, log=None, rank: int = 0):
        self.window = window
        self.log = log or (lambda s: None)
        self.rank = rank
        self.stats: Dict[str, PhaseStats] = defaultdict(PhaseStats)
        self.window_stats: Dict[str, PhaseStats] = defaultdict(PhaseStats)
        self.frames = 0
        self._last_frame_t: Optional[float] = None
        # recorder spans feed record() from the delivery worker threads
        # too; the defaultdict first-touch and the PhaseStats
        # read-modify-write must be atomic across threads
        self._lock = threading.Lock()

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self.stats[name].add(seconds)
            self.window_stats[name].add(seconds)

    def marker(self, tag: str, iteration: int, seconds: float) -> None:
        """Machine-greppable marker (≅ #COMP:rank:iter:sec#)."""
        self.log(f"#{tag}:{self.rank}:{iteration}:{seconds:.6f}#")

    def frame_done(self) -> None:
        now = time.perf_counter()
        if self._last_frame_t is not None:
            self.record("frame", now - self._last_frame_t)
        self._last_frame_t = now
        self.frames += 1
        if self.frames % self.window == 0:
            self.dump_window()

    def dump_window(self) -> None:
        self.log(f"=== frame {self.frames} (window of {self.window}) ===")
        for name, st in sorted(self.window_stats.items()):
            self.log(f"  {name:>16}: avg {st.avg * 1e3:8.3f} ms  "
                     f"total {st.total:7.3f} s  n={st.n}")
        # reset so each dump is a true per-window average — without this
        # the "windowed" lines silently accumulate over the whole run
        self.window_stats = defaultdict(PhaseStats)

    def dump_totals(self) -> None:
        """Final dump: flush the partial window frame_done never reached
        (a 250-frame run at window=100 leaves 50 frames undumped), then
        the whole-run totals. Idempotent on the window part."""
        if any(st.n for st in self.window_stats.values()):
            self.log(f"=== frame {self.frames} (final partial window) ===")
            for name, st in sorted(self.window_stats.items()):
                self.log(f"  {name:>16}: avg {st.avg * 1e3:8.3f} ms  "
                         f"total {st.total:7.3f} s  n={st.n}")
            self.window_stats = defaultdict(PhaseStats)
        self.log(f"=== totals over {self.frames} frames ===")
        for name, st in sorted(self.stats.items()):
            self.log(f"  {name:>16}: avg {st.avg * 1e3:8.3f} ms  "
                     f"total {st.total:7.3f} s  n={st.n}")

    # alias so recorder/session teardown paths read naturally
    close = dump_totals

    def csv(self) -> str:
        lines = ["phase;avg;min;max;stddev;n"]
        for name, st in sorted(self.stats.items()):
            lines.append(f"{name};{st.csv()}")
        return "\n".join(lines)

    def fps(self, phase: str = "frame") -> float:
        st = self.stats.get(phase)
        return 1.0 / st.avg if st and st.avg > 0 else 0.0
