"""Scene-driven session: the operator boundary for EXTERNAL multi-grid
simulations (≅ the reference's C++-driven entry points — updateData with
per-partner grid lists, addVolume/updateVolume/setVolumeDims,
DistributedVolumeRenderer.kt:136-160, DistributedVolumes.kt:142-250 —
driving a render loop the sim paces).

Unlike InSituSession (which advances a built-in sim and runs the
even-slab distributed pipeline), SceneSession renders whatever grids the
driver has pushed into its MultiGridScene — arbitrary counts, uneven
extents, ghost layers — through the whole-scene VDI path, and feeds the
same sinks/steering machinery. The driver calls ``update_data`` /
``update_grid`` between frames exactly like OpenFPM called the JNI
callbacks.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from scenery_insitu_tpu import obs as _obs
from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.scene import MultiGridScene
from scenery_insitu_tpu.core.transfer import TransferFunction, for_dataset
from scenery_insitu_tpu.runtime.failsafe import SinkGuard

Sink = Callable[[int, dict], None]


class SceneSession:
    def __init__(self, cfg: Optional[FrameworkConfig] = None,
                 camera: Optional[Camera] = None,
                 tf: Optional[TransferFunction] = None,
                 sinks: Sequence[Sink] = (), log=None):
        self.cfg = cfg or FrameworkConfig()
        self.log = log or (lambda s: None)
        self.scene = MultiGridScene()
        # same recorder-wraps-timers layering as InSituSession (spans
        # feed the PhaseStats either way; events only when obs enabled)
        self.obs = _obs.Recorder.from_config(
            self.cfg.obs, rank=jax.process_index(), log=self.log,
            window=self.cfg.runtime.stats_window)
        self.timers = self.obs.timers
        # always take over the process slot (see InSituSession.__init__)
        _obs.set_recorder(self.obs)
        # same live SLO engine as InSituSession — the driver paces the
        # loop, so frame_ms is observed per render_frame call
        from scenery_insitu_tpu.obs.slo import SLOEngine
        self.slo = SLOEngine(self.cfg.slo, recorder=self.obs)
        self.tf = tf or for_dataset(self.cfg.runtime.dataset)
        self.camera = camera or Camera.create(
            (0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.3, far=20.0)
        self.sinks: List[Sink] = list(sinks)
        # same per-callable failure isolation as InSituSession (sinks +
        # on_steer run behind the guard; see drain_steering)
        self._sink_guard = SinkGuard(self.cfg.fault.max_sink_failures,
                                     log=self.log)
        # same asynchronous delivery plane as InSituSession (docs/PERF.md
        # "Async delivery"): delivery.enabled runs the frame sinks on a
        # background worker; close() drains (SceneSession has no tile
        # path, so jobs carry no tile payloads)
        self._delivery = None
        if self.cfg.delivery.enabled:
            from scenery_insitu_tpu.runtime.delivery import (
                DeliveryExecutor)
            self._delivery = DeliveryExecutor(
                self.cfg.delivery, self._sink_guard, [], self.sinks,
                recorder=self.obs, slo=self.slo, log=self.log)
        self.frame_index = 0
        self.orbit_rate = 0.0
        self.steering = None
        self.on_steer: List[Callable[[dict], None]] = []
        from scenery_insitu_tpu.ops import slicer as _slicer
        self._slicer = _slicer
        self.engine = _slicer.resolve_engine(self.cfg.slicer.engine)
        self._steps = {}   # (regime, grid-set signature) -> jitted step
        self._thr = {}      # same key -> carried temporal threshold state
        self._thr_init = {}  # same key -> jitted threshold seeder
        self._extent_cache = None  # (lo, hi, sp, rounded tuple) host copy
        self._temporal = (self.cfg.runtime.generate_vdis
                          and self.engine == "mxu"
                          and self.cfg.vdi.adaptive
                          and self.cfg.vdi.adaptive_mode == "temporal")
        # runtime TF updates: drop compiled steps (TF is baked in)
        self.on_steer.append(self._apply_tf_message)

    def _apply_tf_message(self, msg: dict) -> None:
        """'tf' steering: drop the per-signature step/threshold caches so
        the next frame compiles with the new transfer function. Shared
        protocol logic (parsing, malformed-payload containment) lives in
        session.apply_tf_steering."""
        from scenery_insitu_tpu.runtime.session import apply_tf_steering

        def invalidate():
            self._steps.clear()
            self._thr.clear()
            self._thr_init.clear()

        apply_tf_steering(self, msg, invalidate)

    # ------------------------------------------------- operator boundary
    def update_data(self, partner: int, grids, origins, spacing,
                    ghost_lo=None, ghost_hi=None) -> None:
        """≅ updateData(partnerNo, numGrids, grids, origins, ...)."""
        self.scene.update_data(partner, grids, origins, spacing,
                               ghost_lo, ghost_hi)
        self._extent_cache = None

    def update_grid(self, partner: int, gid: int, data) -> None:
        """≅ updateVolume(id, buffer) — new timestep for one grid.

        Does NOT invalidate the extent cache: update_grid only replaces
        grid DATA (MultiGridScene keeps origin/spacing/ghosts), so the
        world extent cannot change — and the canonical driver loop calls
        this every timestep, where a host/device sync per dispatch would
        stall the async frame pipeline. Layout changes go through
        `update_data`, which does invalidate."""
        self.scene.update_grid(partner, gid, data)

    # -------------------------------------------------------------- frames
    def render_frame(self) -> dict:
        if self.scene.num_grids == 0:
            raise RuntimeError("no grids; call update_data first "
                               "(≅ the reference spinning on missing data, "
                               "DistributedVolumes.kt:151-153 — made loud)")
        from scenery_insitu_tpu.runtime.session import (
            advance_camera_and_index, drain_steering)

        import time as _time

        t_f = _time.perf_counter()
        drain_steering(self)
        with self.obs.span("dispatch", frame=self.frame_index,
                           engine=self.engine,
                           grids=self.scene.num_grids):
            step, key = self._step()
            gs = self.scene.grids
            args = (tuple(g.volume.data for g in gs),
                    tuple(g.volume.origin for g in gs),
                    tuple(g.volume.spacing for g in gs), self.camera)
            if self._temporal:
                from scenery_insitu_tpu.runtime.session import (
                    drop_on_regime_reentry)
                drop_on_regime_reentry(self, self._thr, key)
                thr = self._thr.get(key)
                if thr is None:     # seed on first frame of this regime
                    thr = self._thr_init[key](*args)
                out, self._thr[key] = step(*args, thr)
            else:
                out = step(*args)
        with self.obs.span("fetch", frame=self.frame_index):
            if self.cfg.runtime.generate_vdis:
                vdi, meta = out
                payload = {"vdi_color": np.asarray(vdi.color),
                           "vdi_depth": np.asarray(vdi.depth),
                           "meta": meta._replace(
                               index=np.int32(self.frame_index))}
            else:
                payload = {"image": np.asarray(out)}
            payload["frame"] = self.frame_index
        if self._delivery is not None:
            self._delivery.submit(self.frame_index, payload)
        else:
            with self.obs.span("sinks", frame=self.frame_index):
                self._sink_guard.run(self.sinks, self.frame_index,
                                     payload)
        advance_camera_and_index(self)
        self.timers.frame_done()
        self.slo.observe("frame_ms", (_time.perf_counter() - t_f) * 1e3,
                         frame=self.frame_index - 1)
        # the driver paces this loop (no run() bracket to flush at), so
        # write the obs sinks at every stats-window boundary — flush()
        # rewrites whole snapshots, so the files are always loadable
        if self.frame_index % self.timers.window == 0:
            self.obs.flush()
        return payload

    def close(self) -> None:
        """End-of-campaign teardown: drain the async delivery queue,
        flush the final partial timer window + totals and write the obs
        sinks."""
        if self._delivery is not None:
            self._delivery.drain()
        self.timers.dump_totals()
        self.obs.flush()

    def prewarm_regimes(self, regimes=None) -> dict:
        """Precompile the render step for each (axis, sign) camera regime
        against the CURRENT scene (same rationale as
        InSituSession.prewarm_regimes: a regime crossing mid-session
        otherwise stalls on a fresh jit). Call after `update_data` —
        a later grid-set signature change recompiles regardless (the
        cache is keyed on both). Temporal threshold state and the
        reentry tracker are snapshotted and restored; the camera and
        frame index are untouched. Returns {(axis, sign): seconds}."""
        import time as _time

        if self.scene.num_grids == 0:
            raise RuntimeError("no grids; call update_data first")
        # only the MXU VDI path compiles per regime — gather/plain steps
        # have no regime dependence and would fill the bounded step cache
        # with byte-identical duplicates
        if self.engine != "mxu" or not self.cfg.runtime.generate_vdis:
            return {}
        from scenery_insitu_tpu.runtime.session import regime_camera

        if regimes is None:
            regimes = [(a, s) for a in (0, 1, 2) for s in (1, -1)]
        cam0 = self.camera
        thr0 = dict(self._thr)
        had_last = hasattr(self, "_last_regime_key")
        last0 = getattr(self, "_last_regime_key", None)
        active_key = None
        times = {}
        try:
            for regime in regimes:
                cam = regime_camera(cam0, regime, self._slicer)
                self.camera = cam
                t0 = _time.perf_counter()
                step, key = self._step()
                gs = self.scene.grids
                args = (tuple(g.volume.data for g in gs),
                        tuple(g.volume.origin for g in gs),
                        tuple(g.volume.spacing for g in gs), cam)
                if self._temporal:
                    thr = self._thr_init[key](*args)
                    out, _ = step(*args, thr)
                else:
                    out = step(*args)
                jax.block_until_ready(out)
                times[tuple(regime)] = round(_time.perf_counter() - t0, 2)
        finally:
            self.camera = cam0
            # drop restored threshold entries whose step was evicted by
            # the cache bound (they would be orphaned forever), and keep
            # the ACTIVE regime's step most-recent so prewarming many
            # regimes can't evict the one the loop is about to use
            self._thr = {kk: v for kk, v in thr0.items()
                         if kk in self._steps}
            try:
                _, active_key = self._step()
                if active_key in self._steps:
                    self._steps[active_key] = self._steps.pop(active_key)
            except Exception:
                pass
            if had_last:
                self._last_regime_key = last0
            elif hasattr(self, "_last_regime_key"):
                del self._last_regime_key
        return times

    def _step(self):
        """(jitted step, cache key) for the current camera regime and the
        current grid-set SIGNATURE — one compilation per signature, like
        InSituSession._mxu_step. Data, origins, spacings and the camera
        are traced; shapes + ghosts are static, and so is the mxu
        intermediate-grid spec, whose dims derive from the scene's world
        extent — hence the signature also carries the rounded global
        bounds + spacing (a driver that repartitions, moves grids, or
        changes resolution triggers exactly one recompile; same-extent
        timestep updates reuse the cache)."""
        regime = self._slicer.choose_axis(self.camera)
        gs = self.scene.grids
        sig = tuple((tuple(g.volume.data.shape), g.ghost_lo, g.ghost_hi)
                    for g in gs)
        mxu_vdi = (self.cfg.runtime.generate_vdis and self.engine == "mxu")
        # only the mxu spec bakes extent-derived statics; the gather/plain
        # steps trace origins+spacings, so extent in THEIR key would force
        # a recompile per scene movement for nothing. The extent is cached
        # host-side (invalidated by update_data/update_grid) so cache-hit
        # frames never sync device values on the dispatch path.
        extent = None
        lo = hi = sp = None
        if mxu_vdi:
            if self._extent_cache is None:
                lo, hi = self.scene.global_bounds()
                sp = gs[0].volume.spacing
                self._extent_cache = (
                    lo, hi, sp,
                    tuple(round(float(x), 5) for arr in (lo, hi, sp)
                          for x in np.asarray(arr)))
            lo, hi, sp, extent = self._extent_cache
        key = (regime, sig, extent, self.engine,
               self.cfg.runtime.generate_vdis)
        step = self._steps.get(key)
        if step is not None:
            return step, key

        self.obs.count("compile_step")
        self.obs.event("compile", frame=self.frame_index,
                       what="scene_step", regime=str(regime))
        ghosts = [(g.ghost_lo, g.ghost_hi) for g in gs]
        r = self.cfg.render
        cfg = self.cfg
        tf = self.tf
        spec = None
        if mxu_vdi:
            dims = tuple(int(round(float(d)))
                         for d in np.asarray((hi - lo) / sp))   # (x, y, z)
            spec = self._slicer.make_spec(self.camera,
                                          (dims[2], dims[1], dims[0]),
                                          cfg.slicer, axis_sign=regime)

        def scene_of(datas, origins, spacings):
            sc = MultiGridScene()
            for i, (d, o, s) in enumerate(zip(datas, origins, spacings)):
                sc.set_grid(0, i, d, o, s, *ghosts[i])
            return sc

        if self._temporal:
            def fn(datas, origins, spacings, cam, thr):
                sc = scene_of(datas, origins, spacings)
                return sc.generate_vdi_mxu_temporal(tf, cam, spec, thr,
                                                    cfg.vdi, cfg.composite)

            def fn_out(datas, origins, spacings, cam, thr):
                out, meta, thr2 = fn(datas, origins, spacings, cam, thr)
                return (out, meta), thr2

            step = jax.jit(fn_out)
            self._thr_init[key] = jax.jit(
                lambda datas, origins, spacings, cam:
                scene_of(datas, origins, spacings).initial_thresholds(
                    tf, cam, spec, cfg.vdi))
            self._steps[key] = step
            self._evict()
            return step, key

        def fn(datas, origins, spacings, cam):
            sc = scene_of(datas, origins, spacings)
            if mxu_vdi:
                return sc.generate_vdi_mxu(tf, cam, spec, cfg.vdi,
                                           cfg.composite)
            if cfg.runtime.generate_vdis:
                return sc.generate_vdi(tf, cam, r.width, r.height,
                                       cfg.vdi, cfg.composite,
                                       max_steps=r.max_steps)
            return sc.render(tf, cam, r.width, r.height, r)

        step = jax.jit(fn)
        self._steps[key] = step
        self._evict()
        return step, key

    _MAX_CACHED_STEPS = 8

    def _evict(self):
        """Bound the compiled-step / threshold caches: a drifting scene
        mints a new extent key per movement, and an unbounded dict would
        retain every stale executable + [G, nj, ni] threshold state for
        the life of the session. Insertion order ≈ recency here (a key is
        inserted once and then only hit), so dropping the oldest entries
        is an adequate LRU."""
        while len(self._steps) > self._MAX_CACHED_STEPS:
            old = next(iter(self._steps))
            self._steps.pop(old)
            self._thr.pop(old, None)
            self._thr_init.pop(old, None)
