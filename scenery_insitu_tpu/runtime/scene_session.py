"""Scene-driven session: the operator boundary for EXTERNAL multi-grid
simulations (≅ the reference's C++-driven entry points — updateData with
per-partner grid lists, addVolume/updateVolume/setVolumeDims,
DistributedVolumeRenderer.kt:136-160, DistributedVolumes.kt:142-250 —
driving a render loop the sim paces).

Unlike InSituSession (which advances a built-in sim and runs the
even-slab distributed pipeline), SceneSession renders whatever grids the
driver has pushed into its MultiGridScene — arbitrary counts, uneven
extents, ghost layers — through the whole-scene VDI path, and feeds the
same sinks/steering machinery. The driver calls ``update_data`` /
``update_grid`` between frames exactly like OpenFPM called the JNI
callbacks.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.scene import MultiGridScene
from scenery_insitu_tpu.core.transfer import TransferFunction, for_dataset
from scenery_insitu_tpu.runtime.timers import Timers

Sink = Callable[[int, dict], None]


class SceneSession:
    def __init__(self, cfg: Optional[FrameworkConfig] = None,
                 camera: Optional[Camera] = None,
                 tf: Optional[TransferFunction] = None,
                 sinks: Sequence[Sink] = (), log=None):
        self.cfg = cfg or FrameworkConfig()
        self.log = log or (lambda s: None)
        self.scene = MultiGridScene()
        self.timers = Timers(window=self.cfg.runtime.stats_window,
                             log=self.log)
        self.tf = tf or for_dataset(self.cfg.runtime.dataset)
        self.camera = camera or Camera.create(
            (0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.3, far=20.0)
        self.sinks: List[Sink] = list(sinks)
        self.frame_index = 0
        self.orbit_rate = 0.0
        self.steering = None
        self.on_steer: List[Callable[[dict], None]] = []
        from scenery_insitu_tpu.ops import slicer as _slicer
        self._slicer = _slicer
        self.engine = _slicer.resolve_engine(self.cfg.slicer.engine)
        self._specs = {}           # (regime, grid signature) -> AxisSpec

    # ------------------------------------------------- operator boundary
    def update_data(self, partner: int, grids, origins, spacing,
                    ghost_lo=None, ghost_hi=None) -> None:
        """≅ updateData(partnerNo, numGrids, grids, origins, ...)."""
        self.scene.update_data(partner, grids, origins, spacing,
                               ghost_lo, ghost_hi)

    def update_grid(self, partner: int, gid: int, data) -> None:
        """≅ updateVolume(id, buffer) — new timestep for one grid."""
        self.scene.update_grid(partner, gid, data)

    # -------------------------------------------------------------- frames
    def render_frame(self) -> dict:
        if self.scene.num_grids == 0:
            raise RuntimeError("no grids; call update_data first "
                               "(≅ the reference spinning on missing data, "
                               "DistributedVolumes.kt:151-153 — made loud)")
        from scenery_insitu_tpu.runtime.session import (
            advance_camera_and_index, drain_steering)

        drain_steering(self)
        r = self.cfg.render
        with self.timers.phase("dispatch"):
            if self.cfg.runtime.generate_vdis and self.engine == "mxu":
                spec = self._spec()
                vdi, meta = self.scene.generate_vdi_mxu(
                    self.tf, self.camera, spec, self.cfg.vdi,
                    self.cfg.composite)
            elif self.cfg.runtime.generate_vdis:
                vdi, meta = self.scene.generate_vdi(
                    self.tf, self.camera, r.width, r.height, self.cfg.vdi,
                    self.cfg.composite, max_steps=r.max_steps)
            else:
                img = self.scene.render(self.tf, self.camera,
                                        r.width, r.height, r)
                vdi, meta = None, None
        with self.timers.phase("fetch"):
            if vdi is not None:
                payload = {"vdi_color": np.asarray(vdi.color),
                           "vdi_depth": np.asarray(vdi.depth),
                           "meta": meta._replace(
                               index=np.int32(self.frame_index))}
            else:
                payload = {"image": np.asarray(img)}
            payload["frame"] = self.frame_index
        with self.timers.phase("sinks"):
            for s in self.sinks:
                s(self.frame_index, payload)
        advance_camera_and_index(self)
        self.timers.frame_done()
        return payload

    def _spec(self):
        """AxisSpec for the current camera regime + scene shape (cached;
        sized from the scene's global voxel extent)."""
        regime = self._slicer.choose_axis(self.camera)
        lo, hi = self.scene.global_bounds()
        sp = self.scene.grids[0].volume.spacing
        dims = tuple(int(round(float(d)))
                     for d in np.asarray((hi - lo) / sp))   # (x, y, z)
        key = (regime, dims)
        spec = self._specs.get(key)
        if spec is None:
            shape_dhw = (dims[2], dims[1], dims[0])
            spec = self._slicer.make_spec(self.camera, shape_dhw,
                                          self.cfg.slicer, axis_sign=regime)
            self._specs[key] = spec
        return spec
