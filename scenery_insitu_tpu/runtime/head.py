"""The head/display node — a standalone viewer process assembling remote
render ranks' images (≅ Head.kt: a master node that receives each rank's
color+depth planes, binds them as ColorBuffer$rank/DepthBuffer$rank and
min-depth composites on a fullscreen quad, Head.kt:40-183 +
NaiveCompositor.frag:15-28; its camera moves are published back over ZMQ,
Head.kt:137-161).

Here the head is transport + numpy: render ranks PUSH ``[msgpack header |
image blob | depth blob]`` per frame (``RankImageSender``), the head
collects one set per frame index, depth-min composites
(ops.composite.composite_depth_min semantics, done in numpy — the head
node owns no accelerator), and hands frames to sinks (PNG, movie, live
UDP video). Steering messages go back through the ordinary
SteeringPublisher → SteeringRelay → render ranks chain.

Run standalone:  python -m scenery_insitu_tpu.runtime.head --ranks 2
                 [--bind tcp://*:6677] [--frames 10] [--out dir/]
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from scenery_insitu_tpu import obs as _obs
from scenery_insitu_tpu.obs.collector import lineage, trace_ctx
from scenery_insitu_tpu.runtime.failsafe import SinkGuard
from scenery_insitu_tpu.runtime.streaming import _msgpack, _zmq

Sink = Callable[[int, dict], None]


class RankImageSender:
    """Render-rank side: push this rank's (image, depth) per frame to the
    head (≅ the MPI iSend of image planes the reference's ranks did,
    SharedSpheresExample.kt:174-207 / scenery's client mode)."""

    def __init__(self, rank: int, connect: str = "tcp://localhost:6677"):
        zmq = _zmq()
        self.rank = rank
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUSH)
        self.sock.connect(connect)

    def send(self, frame: int, image: np.ndarray, depth: np.ndarray) -> None:
        """image f32[4, H, W] premultiplied; depth f32[H, W] (+inf empty)."""
        image = np.ascontiguousarray(image, np.float32)
        depth = np.ascontiguousarray(depth, np.float32)
        header = _msgpack().packb({
            "rank": self.rank, "frame": int(frame),
            "image_shape": list(image.shape),
            "depth_shape": list(depth.shape),
            "tc": trace_ctx(frame, self.rank)})
        self.sock.send_multipart([header, image.tobytes(), depth.tobytes()])
        lineage("head", "send", int(frame), rank=self.rank)

    def close(self) -> None:
        self.sock.close(linger=0)


def depth_min_composite_np(images: List[np.ndarray],
                           depths: List[np.ndarray]
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-pixel nearest-rank pick (numpy twin of ops.composite
    .composite_depth_min; ≅ NaiveCompositor.frag:15-28)."""
    imgs = np.stack(images)                                # [n, 4, H, W]
    deps = np.stack(depths)                                # [n, H, W]
    idx = np.argmin(deps, axis=0)                          # [H, W]
    img = np.take_along_axis(imgs, idx[None, None], axis=0)[0]
    dep = np.take_along_axis(deps, idx[None], axis=0)[0]
    return img, dep


class HeadNode:
    """Collect per-rank frames, composite complete sets, feed sinks.

    Per-rank liveness (docs/ROBUSTNESS.md): a rank silent for
    ``stale_frames`` frames is marked DOWN (``head.rank_down`` ledger)
    and subsequent frames composite WITHOUT it — the payload carries
    ``degraded=True`` + ``missing_ranks`` so sinks can flag the frame —
    and the rank is re-admitted the moment it sends again. Malformed
    rank messages are dropped on the ``stream.integrity`` ledger
    instead of killing the pump, and sinks run behind a ``SinkGuard``
    (a repeatedly-throwing sink is quarantined, not fatal)."""

    def __init__(self, num_ranks: int, bind: str = "tcp://*:6677",
                 sinks: Tuple[Sink, ...] = (), stale_frames: int = 8,
                 max_sink_failures: int = 3):
        zmq = _zmq()
        self.n = num_ranks
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PULL)
        if bind.endswith(":0"):
            port = self.sock.bind_to_random_port(bind[:-2])
            self.endpoint = f"{bind[:-2].replace('*', '127.0.0.1')}:{port}"
        else:
            self.sock.bind(bind)
            self.endpoint = bind.replace("*", "127.0.0.1")
        self.sinks = list(sinks)
        self.stale_frames = stale_frames
        self._pending: Dict[int, Dict[int, Tuple[np.ndarray, np.ndarray]]] = {}
        self.frames_composited = 0
        self.frames_degraded = 0
        self.latest: Optional[np.ndarray] = None
        self.down: set = set()          # ranks currently marked down
        self._last_frame: Dict[int, int] = {}  # rank -> newest frame seen
        self._newest: Optional[int] = None     # newest frame index seen
        self._first: Optional[int] = None      # first frame index seen
        self._done: set = set()         # recently composited frame indices
        # frame-index plausibility window: a jump beyond this resets the
        # stream bookkeeping instead of being trusted into _newest
        self._max_jump = max(1000, 16 * stale_frames)
        self._guard = SinkGuard(max_sink_failures, domain="head")

    # ---------------------------------------------------------- liveness
    def _mark_down(self) -> bool:
        """Ranks (0..n-1 by the sender contract) whose newest
        contribution lags the stream by more than stale_frames are
        down; never-seen ranks count from the first frame observed.
        Returns True when the down set grew (pending frames must be
        re-checked against the shrunken live set)."""
        if self._newest is None:
            return False
        grew = False
        floor = self._first if self._first is not None else self._newest
        for r in range(self.n):
            if r in self.down:
                continue
            last = self._last_frame.get(r, floor - 1)
            if self._newest - last > self.stale_frames:
                self.down.add(r)
                grew = True
                _obs.get_recorder().count("head_ranks_down")
                _obs.degrade(
                    "head.rank_down", f"rank {r} contributing",
                    "compositing without it",
                    f"rank silent for more than stale_frames="
                    f"{self.stale_frames} frames; re-admitted on "
                    "return", warn=False)
        return grew

    def _readmit(self, rank: int) -> None:
        """Re-admit a down rank only once it has CAUGHT UP to within the
        stale horizon — a rank that keeps sending but stays lagged would
        otherwise flap up/down on every message, turning the liveness
        counters into churn."""
        if rank not in self.down:
            return
        if self._newest is not None and \
                self._newest - self._last_frame.get(rank, 0) \
                > self.stale_frames:
            return
        self.down.discard(rank)
        _obs.get_recorder().count("head_ranks_readmitted")
        _obs.get_recorder().event("head_rank_up", rank=rank)

    # --------------------------------------------------------- composite
    def _composite(self, frame: int,
                   ranks: Dict[int, Tuple[np.ndarray, np.ndarray]]
                   ) -> None:
        imgs = [ranks[r][0] for r in sorted(ranks)]
        deps = [ranks[r][1] for r in sorted(ranks)]
        out, dmin = depth_min_composite_np(imgs, deps)
        self.latest = out
        self.frames_composited += 1
        payload = {"image": out, "depth": dmin, "frame": frame}
        missing = sorted(set(range(self.n)) - set(ranks))
        if missing:
            # degraded-frame semantics (docs/ROBUSTNESS.md): the frame
            # ships, flagged, rather than stalling the whole stream on
            # a dead rank
            payload["degraded"] = True
            payload["missing_ranks"] = missing
            self.frames_degraded += 1
            _obs.get_recorder().count("head_degraded_frames")
        lineage("composite", "send", frame, ranks=len(ranks))
        self._guard.run(self.sinks, frame, payload, kind="head sink")

    def pump(self, timeout_ms: int = 100) -> int:
        """Receive pending rank messages; composite every completed frame
        set; returns number of frames composited this call."""
        _zmq()                  # fail fast if pyzmq is missing
        done = 0
        while self.sock.poll(timeout_ms):
            parts = self.sock.recv_multipart()
            try:
                header, iblob, dblob = parts
                h = _msgpack().unpackb(header)
                img = np.frombuffer(iblob, np.float32) \
                    .reshape(h["image_shape"])
                dep = np.frombuffer(dblob, np.float32) \
                    .reshape(h["depth_shape"])
                frame = int(h["frame"])
                rank = int(h["rank"])
                # parseable-but-inconsistent messages must be refused
                # HERE: a ragged set reaching np.stack in the composite
                # would kill the pump
                if not 0 <= rank < self.n:
                    raise ValueError(f"rank {rank} outside 0..{self.n}")
                if frame < 0:
                    raise ValueError(f"negative frame {frame}")
                if img.ndim != 3 or dep.shape != img.shape[1:]:
                    raise ValueError("depth/image shape mismatch")
                peers = self._pending.get(frame)
                if peers:
                    p_img, _ = next(iter(peers.values()))
                    if p_img.shape != img.shape:
                        raise ValueError(
                            "image shape disagrees with this frame's "
                            "other ranks")
            except Exception:
                _obs.degrade(
                    "stream.integrity", "head rank message",
                    "dropped before composite",
                    "malformed rank frame (part count, header, blob "
                    "size/shape, rank/frame range, or cross-rank shape "
                    "mismatch)", warn=False)
                timeout_ms = 0
                continue
            lineage("head", "recv", frame, ctx=h.get("tc"), rank=rank)
            if self._newest is not None and \
                    abs(frame - self._newest) > self._max_jump:
                # a frame index wildly outside the plausible window —
                # a corrupt-but-parseable counter or a restarted sender
                # session. Treating it as truth would poison liveness
                # and eviction (one absurd index silently refuses every
                # real frame after it); reset the stream bookkeeping
                # instead and start over from this message.
                _obs.degrade(
                    "stream.gap", f"head stream at frame {self._newest}",
                    f"reset to frame {frame}",
                    "frame index jumped beyond the plausibility window; "
                    "head stream state reset (sender restart or corrupt "
                    "counter)", warn=False)
                self._pending.clear()
                self._done.clear()
                self._last_frame.clear()
                self.down.clear()
                self._newest = self._first = None
            self._newest = (frame if self._newest is None
                            else max(self._newest, frame))
            if self._first is None:
                self._first = frame
            self._last_frame[rank] = max(self._last_frame.get(rank,
                                                              frame),
                                         frame)
            self._readmit(rank)
            down_grew = self._mark_down()
            if frame in self._done or frame < self._newest - self.stale_frames:
                # late data for a frame already shipped or already past
                # the eviction horizon (a rank lagging further than the
                # _done set remembers) — a second, more-degraded
                # composite of the same index would misorder the sinks
                timeout_ms = 0
                continue
            self._pending.setdefault(frame, {})[rank] = (img, dep)
            # a frame completes when every LIVE rank contributed (down
            # ranks' late data still composites if it arrived in time).
            # When a rank just went down, every OLDER pending frame was
            # waiting on it too — re-check them all, oldest first, so
            # they ship before newer frames rather than trailing out of
            # order through the eviction path.
            live = set(range(self.n)) - self.down
            check = (sorted(self._pending) if down_grew else
                     [frame] if frame in self._pending else [])
            for f in check:
                if live <= set(self._pending[f]):
                    self._composite(f, self._pending.pop(f))
                    self._done.add(f)
                    done += 1
            # stragglers that can never complete — on EVERY message, not
            # only on completion (a dead rank must not leak the live
            # ranks' frames forever). Non-empty sets composite DEGRADED
            # instead of vanishing: partial work beats a dropped frame.
            for old in sorted(f for f in self._pending
                              if f < self._newest - self.stale_frames):
                # no _done bookkeeping needed for evicted frames: they
                # are past the horizon, so the frame-age check above
                # already refuses any late re-contribution
                self._composite(old, self._pending.pop(old))
                done += 1
            # _done only needs to remember frames still inside the
            # horizon (older ones are refused by the age check)
            self._done -= {f for f in self._done
                           if f < self._newest - self.stale_frames}
            timeout_ms = 0                                 # drain non-blocking
        return done

    def run(self, frames: int, timeout_s: float = 60.0) -> int:
        """Pump until ``frames`` sets composited or timeout; returns count."""
        t0 = time.monotonic()
        while (self.frames_composited < frames
               and time.monotonic() - t0 < timeout_s):
            self.pump(timeout_ms=100)
        return self.frames_composited

    def close(self) -> None:
        self.sock.close(linger=0)


def head_sender_sink(sender: RankImageSender) -> Sink:
    """Session sink forwarding plain/particle frames to the head node
    (payloads with image+depth — the particle and plain modes)."""

    def sink(index: int, payload: dict) -> None:
        if "image" in payload and "depth" in payload:
            sender.send(index, payload["image"], payload["depth"])

    return sink


if __name__ == "__main__":
    import argparse
    import os

    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=2)
    ap.add_argument("--bind", default="tcp://*:6677")
    ap.add_argument("--frames", type=int, default=10)
    ap.add_argument("--out", default=None, help="PNG output directory")
    ap.add_argument("--video-port", type=int, default=0,
                    help="also stream composited frames over UDP")
    args = ap.parse_args()

    sinks = []
    if args.out:
        from scenery_insitu_tpu.utils.image import save_png

        os.makedirs(args.out, exist_ok=True)
        sinks.append(lambda i, p: save_png(
            os.path.join(args.out, f"head{i:05d}.png"), p["image"]))
    if args.video_port:
        from scenery_insitu_tpu.runtime.streaming import (VideoStreamer,
                                                          live_video_sink)

        sinks.append(live_video_sink(VideoStreamer(port=args.video_port)))

    head = HeadNode(args.ranks, args.bind, tuple(sinks))
    print(f"[head] listening on {head.endpoint} for {args.ranks} ranks",
          flush=True)
    got = head.run(args.frames)
    print(f"[head] composited {got} frames", flush=True)
