"""The head/display node — a standalone viewer process assembling remote
render ranks' images (≅ Head.kt: a master node that receives each rank's
color+depth planes, binds them as ColorBuffer$rank/DepthBuffer$rank and
min-depth composites on a fullscreen quad, Head.kt:40-183 +
NaiveCompositor.frag:15-28; its camera moves are published back over ZMQ,
Head.kt:137-161).

Here the head is transport + numpy: render ranks PUSH ``[msgpack header |
image blob | depth blob]`` per frame (``RankImageSender``), the head
collects one set per frame index, depth-min composites
(ops.composite.composite_depth_min semantics, done in numpy — the head
node owns no accelerator), and hands frames to sinks (PNG, movie, live
UDP video). Steering messages go back through the ordinary
SteeringPublisher → SteeringRelay → render ranks chain.

Run standalone:  python -m scenery_insitu_tpu.runtime.head --ranks 2
                 [--bind tcp://*:6677] [--frames 10] [--out dir/]
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from scenery_insitu_tpu.runtime.streaming import _msgpack, _zmq

Sink = Callable[[int, dict], None]


class RankImageSender:
    """Render-rank side: push this rank's (image, depth) per frame to the
    head (≅ the MPI iSend of image planes the reference's ranks did,
    SharedSpheresExample.kt:174-207 / scenery's client mode)."""

    def __init__(self, rank: int, connect: str = "tcp://localhost:6677"):
        zmq = _zmq()
        self.rank = rank
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PUSH)
        self.sock.connect(connect)

    def send(self, frame: int, image: np.ndarray, depth: np.ndarray) -> None:
        """image f32[4, H, W] premultiplied; depth f32[H, W] (+inf empty)."""
        image = np.ascontiguousarray(image, np.float32)
        depth = np.ascontiguousarray(depth, np.float32)
        header = _msgpack().packb({
            "rank": self.rank, "frame": int(frame),
            "image_shape": list(image.shape),
            "depth_shape": list(depth.shape)})
        self.sock.send_multipart([header, image.tobytes(), depth.tobytes()])

    def close(self) -> None:
        self.sock.close(linger=0)


def depth_min_composite_np(images: List[np.ndarray],
                           depths: List[np.ndarray]
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-pixel nearest-rank pick (numpy twin of ops.composite
    .composite_depth_min; ≅ NaiveCompositor.frag:15-28)."""
    imgs = np.stack(images)                                # [n, 4, H, W]
    deps = np.stack(depths)                                # [n, H, W]
    idx = np.argmin(deps, axis=0)                          # [H, W]
    img = np.take_along_axis(imgs, idx[None, None], axis=0)[0]
    dep = np.take_along_axis(deps, idx[None], axis=0)[0]
    return img, dep


class HeadNode:
    """Collect per-rank frames, composite complete sets, feed sinks."""

    def __init__(self, num_ranks: int, bind: str = "tcp://*:6677",
                 sinks: Tuple[Sink, ...] = (), stale_frames: int = 8):
        zmq = _zmq()
        self.n = num_ranks
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.PULL)
        if bind.endswith(":0"):
            port = self.sock.bind_to_random_port(bind[:-2])
            self.endpoint = f"{bind[:-2].replace('*', '127.0.0.1')}:{port}"
        else:
            self.sock.bind(bind)
            self.endpoint = bind.replace("*", "127.0.0.1")
        self.sinks = list(sinks)
        self.stale_frames = stale_frames
        self._pending: Dict[int, Dict[int, Tuple[np.ndarray, np.ndarray]]] = {}
        self.frames_composited = 0
        self.latest: Optional[np.ndarray] = None

    def pump(self, timeout_ms: int = 100) -> int:
        """Receive pending rank messages; composite every completed frame
        set; returns number of frames composited this call."""
        _zmq()                  # fail fast if pyzmq is missing
        done = 0
        while self.sock.poll(timeout_ms):
            header, iblob, dblob = self.sock.recv_multipart()
            h = _msgpack().unpackb(header)
            img = np.frombuffer(iblob, np.float32).reshape(h["image_shape"])
            dep = np.frombuffer(dblob, np.float32).reshape(h["depth_shape"])
            frame = h["frame"]
            self._pending.setdefault(frame, {})[h["rank"]] = (img, dep)
            if len(self._pending[frame]) == self.n:
                ranks = self._pending.pop(frame)
                imgs = [ranks[r][0] for r in sorted(ranks)]
                deps = [ranks[r][1] for r in sorted(ranks)]
                out, dmin = depth_min_composite_np(imgs, deps)
                self.latest = out
                self.frames_composited += 1
                done += 1
                payload = {"image": out, "depth": dmin, "frame": frame}
                for s in self.sinks:
                    s(frame, payload)
            # drop stragglers that can never complete — on EVERY message,
            # not only on completion (a dead rank must not leak the live
            # ranks' frames forever)
            for old in [f for f in self._pending
                        if f < frame - self.stale_frames]:
                del self._pending[old]
            timeout_ms = 0                                 # drain non-blocking
        return done

    def run(self, frames: int, timeout_s: float = 60.0) -> int:
        """Pump until ``frames`` sets composited or timeout; returns count."""
        t0 = time.monotonic()
        while (self.frames_composited < frames
               and time.monotonic() - t0 < timeout_s):
            self.pump(timeout_ms=100)
        return self.frames_composited

    def close(self) -> None:
        self.sock.close(linger=0)


def head_sender_sink(sender: RankImageSender) -> Sink:
    """Session sink forwarding plain/particle frames to the head node
    (payloads with image+depth — the particle and plain modes)."""

    def sink(index: int, payload: dict) -> None:
        if "image" in payload and "depth" in payload:
            sender.send(index, payload["image"], payload["depth"])

    return sink


if __name__ == "__main__":
    import argparse
    import os

    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=2)
    ap.add_argument("--bind", default="tcp://*:6677")
    ap.add_argument("--frames", type=int, default=10)
    ap.add_argument("--out", default=None, help="PNG output directory")
    ap.add_argument("--video-port", type=int, default=0,
                    help="also stream composited frames over UDP")
    args = ap.parse_args()

    sinks = []
    if args.out:
        from scenery_insitu_tpu.utils.image import save_png

        os.makedirs(args.out, exist_ok=True)
        sinks.append(lambda i, p: save_png(
            os.path.join(args.out, f"head{i:05d}.png"), p["image"]))
    if args.video_port:
        from scenery_insitu_tpu.runtime.streaming import (VideoStreamer,
                                                          live_video_sink)

        sinks.append(live_video_sink(VideoStreamer(port=args.video_port)))

    head = HeadNode(args.ranks, args.bind, tuple(sinks))
    print(f"[head] listening on {head.endpoint} for {args.ranks} ranks",
          flush=True)
    got = head.run(args.frames)
    print(f"[head] composited {got} frames", flush=True)
