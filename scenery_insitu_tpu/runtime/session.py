"""The in-situ session loop — per-frame orchestration
(≅ ``manageVDIGeneration``, reference DistributedVolumes.kt:683-933, and the
older DistributedVolumeRenderer.kt:450-654).

Where the reference interlocks generation and compositing with
postRenderLambdas, @Volatile flags and AtomicIntegers across three threads
(DistributedVolumes.kt:126-130, 736-796), here one jitted SPMD step runs
sim-advance → VDI generate → all_to_all → composite, and the Python loop
only paces frames, fetches results asynchronously (dispatch frame N+1
before blocking on frame N — JAX's async dispatch gives the overlap the
reference hand-built), feeds sinks, and keeps the per-phase timer taxonomy
(§5 tracing) for the benchmark metrics.

Runs standalone with the built-in simulations — fixing the reference's
"cannot be used standalone" limitation (README.md:16) — or driven
externally by supplying a custom sim adapter (anything with
``advance(n)`` + ``.field``, see VolumeSimAdapter).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu import obs as _obs
from scenery_insitu_tpu.config import FrameworkConfig
from scenery_insitu_tpu.core.camera import Camera, orbit
from scenery_insitu_tpu.core.transfer import TransferFunction, for_dataset
from scenery_insitu_tpu.core.vdi import VDI
from scenery_insitu_tpu.parallel.topology import (make_topology_mesh,
                                                  resolve_mesh_topology)
from scenery_insitu_tpu.parallel.pipeline import (distributed_plain_step,
                                                  distributed_vdi_step,
                                                  shard_volume)
from scenery_insitu_tpu.runtime.failsafe import SinkGuard
from scenery_insitu_tpu.sim import grayscott as gs
from scenery_insitu_tpu.sim import vortex as vx

Sink = Callable[[int, dict], None]


def steer_session(sess, msg: dict) -> None:
    """Apply ONE steering-protocol message to ``sess`` (camera updates
    in place, other kinds to the on_steer callbacks). The zmq drain and
    the in-process path (scenario steering hooks —
    scenery_insitu_tpu/scenarios) route through this same consumer.

    on_steer callbacks run behind the session's SinkGuard: an exception
    in one callback must not kill the drain (or the run) — a callback
    failing ``fault.max_sink_failures`` consecutive times is quarantined
    on the ``session.sink`` ledger."""
    from scenery_insitu_tpu.runtime.streaming import apply_steering
    sess.camera, other = apply_steering(sess.camera, msg)
    for kind_msg in other.values():
        sess._sink_guard.run(sess.on_steer, kind_msg,
                             kind="on_steer callback")


def drain_steering(sess) -> None:
    """Apply all pending steering messages to ``sess``. Shared by
    InSituSession and SceneSession so the steering protocol has ONE
    consumer (`steer_session`)."""
    if sess.steering is None:
        return
    with sess.obs.span("steer", frame=sess.frame_index):
        for msg in sess.steering.drain():
            steer_session(sess, msg)


def apply_tf_steering(sess, msg: dict, invalidate) -> None:
    """Shared handler for 'tf' steering messages (the reference's
    updateVis TF path, DistributedVolumeRenderer.kt:747-774): swap
    ``sess.tf`` and call ``invalidate()`` to drop the compiled steps that
    baked the old TF in as constants. Malformed payloads are logged and
    IGNORED — the steering socket is network-facing, and a buggy viewer
    must not be able to kill an in-situ run mid-simulation."""
    if msg.get("type") != "tf":
        return
    from scenery_insitu_tpu.runtime.streaming import tf_from_message

    try:
        tf = tf_from_message(msg)
    except Exception as e:
        sess.log(f"ignoring malformed tf steering message: {e!r}")
        return
    sess.tf = tf
    invalidate()


def _tf_fingerprint(tf) -> str:
    """Content identity of a TransferFunction (knot arrays hashed) —
    the recompile-or-reuse cache key of steered TF updates
    (docs/SCENARIOS.md "Steered transfer functions"): two messages
    describing the same polyline map to the same compiled steps."""
    import hashlib

    h = hashlib.sha1()
    for leaf in tf:
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()[:16]


def regime_camera(cam0, regime, slicer_mod):
    """Synthetic camera guaranteed to resolve to ``regime`` under
    choose_axis: eye on the regime's axis at the original distance with a
    small off-axis bias (stable argmax, up never parallel). ONE
    implementation for every prewarm path — the synthesis must stay in
    lockstep with choose_axis's convention. Raises on invalid regimes
    (also the only validation of caller-supplied tuples)."""
    a, s = regime
    if a not in (0, 1, 2) or s not in (1, -1):
        raise ValueError(f"invalid march regime {regime!r} "
                         "(expected (axis in 0..2, sign ±1))")
    eye = np.asarray(cam0.eye, np.float64)
    tgt = np.asarray(cam0.target, np.float64)
    dist = float(np.linalg.norm(eye - tgt)) or 2.5
    off = np.full(3, 0.2 * dist)
    off[a] = 0.0
    new_eye = tgt.copy() - off
    new_eye[a] = tgt[a] - s * dist
    cam = cam0._replace(eye=jnp.asarray(new_eye, jnp.float32))
    if slicer_mod.choose_axis(cam) != (a, s):
        # loud, -O-proof: a step compiled under a mislabeled regime key
        # would silently poison the cache and the prewarm timings
        raise RuntimeError(
            f"regime_camera drifted from choose_axis for {regime!r}")
    return cam


def drop_on_regime_reentry(sess, store: dict, key) -> None:
    """Shared temporal-threshold policy of both sessions: when the camera
    enters a regime key other than the previous frame's, drop that key's
    carried threshold state so it re-seeds — a map frozen many frames ago
    (while the camera was elsewhere and the data kept evolving) would cost
    the controller several overflow-degraded frames to walk back. The
    tracker attribute is checkpoint-restored VERBATIM (runtime/checkpoint)
    so resumed runs make identical drop/keep decisions."""
    if key != getattr(sess, "_last_regime_key", key):
        store.pop(key, None)
    sess._last_regime_key = key


def advance_camera_and_index(sess) -> None:
    """Benchmark-orbit the camera (if enabled) and bump the frame index."""
    if sess.orbit_rate:
        sess.camera = orbit(sess.camera, jnp.float32(sess.orbit_rate))
    sess.frame_index += 1


class VolumeSimAdapter:
    """Uniform facade over the built-in volume sims (kind -> state/advance/
    field)."""

    def __init__(self, cfg: FrameworkConfig, seed: int = 0):
        kind = cfg.sim.kind
        self.kind = kind
        if kind == "gray_scott":
            self.state = gs.GrayScott.from_config(cfg.sim, seed=seed)
            # fused_stencil routes through the time-fused Pallas kernel
            # on TPU (T steps per HBM round trip of u, v); off-TPU or
            # with the flag off it is exactly the XLA roll path
            adv = (gs.multi_step_fast if cfg.sim.fused_stencil
                   else gs.multi_step)
            self._advance = lambda s, n: adv(s, n)
        elif kind == "vortex":
            self.state = vx.VortexFlow.init_ring(tuple(cfg.sim.grid),
                                                 vx.VortexParams.create(dt=cfg.sim.dt))
            self._advance = lambda s, n: vx.multi_step(s, n)
        else:
            raise ValueError(f"unknown volume sim kind {cfg.sim.kind!r}")

    def advance(self, n: int) -> None:
        self.state = self._advance(self.state, n)

    @property
    def field(self) -> jnp.ndarray:
        return self.state.field


class ParticleSimAdapter:
    """Session facade over the built-in particle sims (lennard_jones | sho;
    ≅ the reference's MD-driven InVisRenderer path and the SHO workload of
    its shm producer, shm_mpiproducer.cpp:85-122)."""

    def __init__(self, cfg: FrameworkConfig, seed: int = 0):
        from functools import partial

        from scenery_insitu_tpu.sim import particles as pt

        kind = cfg.sim.kind
        self.kind = kind
        n = cfg.sim.num_particles
        if kind == "lennard_jones":
            self.state, params, spec = pt.lj_init(n, seed=seed)
            self._advance = partial(pt.lj_multi_step, params=params,
                                    spec=spec)
        elif kind == "sho":
            self.state, params = pt.sho_init(n, seed=seed)

            @partial(jax.jit, static_argnames="n")
            def sho_multi(s, n):
                return jax.lax.fori_loop(
                    0, n, lambda _, st: pt.sho_step(st, params), s)

            self._advance = sho_multi
        else:
            raise ValueError(f"unknown particle sim kind {kind!r}")

    def advance(self, n: int) -> None:
        self.state = self._advance(self.state, n=n)

    @property
    def pos(self) -> jnp.ndarray:
        return self.state.pos

    @property
    def vel(self) -> jnp.ndarray:
        return self.state.vel


class HybridSimAdapter:
    """Vortex flow + passive tracers for the hybrid session mode
    (BASELINE.md Config 5)."""

    def __init__(self, cfg: FrameworkConfig, seed: int = 0):
        grid = tuple(cfg.sim.grid)
        self.kind = "hybrid"
        self.flow = vx.VortexFlow.init_ring(
            grid, vx.VortexParams.create(dt=cfg.sim.dt))
        self.tracers = vx.seed_tracers(grid, cfg.sim.num_particles,
                                       seed=seed)

        @jax.jit
        def _adv(u, pos, n):
            params = self.flow.params

            def body(_, carry):
                fl, p = carry
                p = vx.advect_tracers(fl.u, p, params.dt)
                return vx.step(fl), p

            fl, p = jax.lax.fori_loop(0, n, body,
                                      (vx.VortexFlow(u, params), pos))
            return fl.u, p

        self._adv = _adv

    def advance(self, n: int) -> None:
        u, self.tracers = self._adv(self.flow.u, self.tracers,
                                    jnp.int32(n))
        self.flow = self.flow._replace(u=u)

    @property
    def field(self) -> jnp.ndarray:
        return self.flow.field


class InSituSession:
    def __init__(self, cfg: Optional[FrameworkConfig] = None,
                 mesh=None, camera: Optional[Camera] = None,
                 tf: Optional[TransferFunction] = None,
                 sim: Optional[VolumeSimAdapter] = None,
                 sinks: Sequence[Sink] = (), log=None):
        self.cfg = cfg or FrameworkConfig()
        self.log = log or (lambda s: None)
        if mesh is not None:
            self.mesh = mesh
        else:
            # mesh topology is first-class (docs/MULTIHOST.md): a
            # hierarchical TopologyConfig builds the 2-D (hosts, ranks)
            # mesh and the distributed steps composite in two levels.
            # Particle sessions composite sort-first (all_gather +
            # depth-min) — no sort-last exchange to split — so a
            # hierarchy request there is inert, ledgered, and the flat
            # mesh renders
            topo_cfg = self.cfg.topology
            particles = (isinstance(sim, ParticleSimAdapter)
                         or (sim is None and self.cfg.sim.kind
                             in ("lennard_jones", "sho")))
            if particles and topo_cfg.num_hosts > 1:
                _obs.degrade(
                    "topology.hier", f"num_hosts={topo_cfg.num_hosts}",
                    "flat", "particle sessions composite sort-first — "
                    "no two-level sort-last composite to run", warn=False)
                topo_cfg = None
            self.mesh, _ = make_topology_mesh(topo_cfg, self.cfg.mesh)
        # the flat axis view + total rank count every mesh consumer uses
        # (a plain name on 1-D meshes, the (hosts, ranks) tuple on 2-D)
        self._flat_axis, self._n_ranks, self._topo = resolve_mesh_topology(
            self.mesh, topology=(self.cfg.topology
                                 if len(self.mesh.axis_names) > 1
                                 else None))
        # the recorder wraps+subsumes the per-phase Timers: every span
        # feeds `self.timers` (same PhaseStats/windowed dumps as before),
        # and with obs enabled also records structured frame/rank events
        self.obs = _obs.Recorder.from_config(
            self.cfg.obs, rank=jax.process_index(), log=self.log,
            window=self.cfg.runtime.stats_window)
        self.timers = self.obs.timers
        # ALWAYS take over the process slot (enabled or not): the
        # library-level span/degrade sites route through get_recorder(),
        # and a stale enabled recorder from a finished session would
        # otherwise keep absorbing this session's events
        _obs.set_recorder(self.obs)
        # live SLO engine (docs/OBSERVABILITY.md "SLO engine"): rolling
        # p50/p99 over frame latency + per-phase budgets, checked on the
        # loop; session.slo.snapshot() is the health signal
        from scenery_insitu_tpu.obs.slo import SLOEngine
        self.slo = SLOEngine(self.cfg.slo, recorder=self.obs)
        # fleet telemetry side-channel (docs/OBSERVABILITY.md "Fleet
        # tracing"): obs.collector configured -> batched event publish
        # on the frame loop, non-blocking, drops ledgered
        self._obs_pub = None
        if self.cfg.obs.collector:
            from scenery_insitu_tpu.obs.collector import ObsPublisher
            self._obs_pub = ObsPublisher(
                self.cfg.obs.collector, self.cfg.obs.collector_hb,
                rank=self.obs.rank,
                interval_s=self.cfg.obs.collector_interval_s)
        if sim is not None:
            self.sim = sim
        elif self.cfg.sim.kind in ("lennard_jones", "sho"):
            self.sim = ParticleSimAdapter(self.cfg)
        elif self.cfg.sim.kind == "hybrid":
            self.sim = HybridSimAdapter(self.cfg)
        else:
            self.sim = VolumeSimAdapter(self.cfg)
        self.tf = tf or for_dataset(
            self.cfg.sim.kind if self.cfg.runtime.dataset == "procedural"
            else self.cfg.runtime.dataset)
        self.camera = camera or Camera.create(
            (0.0, 0.6, 3.0), fov_y_deg=50.0, near=0.3, far=20.0)
        self.sinks: List[Sink] = list(sinks)
        # session failure isolation (docs/ROBUSTNESS.md): every frame
        # sink, tile sink and on_steer callback runs behind this guard —
        # one failing fault.max_sink_failures consecutive times is
        # quarantined (session.sink ledger) instead of killing the run
        self._sink_guard = SinkGuard(self.cfg.fault.max_sink_failures,
                                     log=self.log)
        # tile-granular delivery (docs/PERF.md "Tile waves"): with
        # composite.schedule == "waves" every VDI frame is also split
        # into its n_ranks * wave_tiles column-block tiles and each tile
        # payload ({vdi_color, vdi_depth, tile, tiles, col0, frame,
        # meta}) is handed to these sinks IN COLUMN ORDER before the
        # frame sinks see the assembled frame — subscribers (e.g.
        # streaming.stream_tile_sink) start decoding the first columns
        # while later tiles are still being fetched
        self.tile_sinks: List[Sink] = []
        # the asynchronous delivery plane (docs/PERF.md "Async
        # delivery"): delivery.enabled moves the post-fetch sink work
        # (tile payloads in column order, then the frame sinks) onto a
        # background worker draining a bounded FIFO, so steady-state
        # frame time is max(device, host) instead of device + host. The
        # executor shares the SinkGuard and the LIVE sink lists above;
        # run()/teardown drain it so no fetched frame is lost.
        self._delivery = None
        if self.cfg.delivery.enabled:
            from scenery_insitu_tpu.runtime.delivery import (
                DeliveryExecutor)
            self._delivery = DeliveryExecutor(
                self.cfg.delivery, self._sink_guard, self.tile_sinks,
                self.sinks, recorder=self.obs, slo=self.slo,
                log=self.log)
        self.frame_index = 0
        # render rebalancing (docs/PERF.md "Render rebalancing"): the
        # current planned z-band depths per rank (None = even split) and
        # the frame of the last host-side re-plan; see _maybe_replan.
        # rebalance="bricks" keeps a BrickMap instead (docs/SCENARIOS.md
        # "Brick maps": non-convex brick→rank assignment, re-planned by
        # brick-stealing)
        self._plan = None
        self._bricks = None
        self._plan_frame = None
        # steered-TF recompile-or-reuse (docs/SCENARIOS.md "Steered
        # transfer functions"): compiled-step caches stashed under the
        # outgoing TF's identity key, restored when a steered TF repeats
        self._step_cache = {}
        self.orbit_rate = 0.0  # radians/frame camera sweep (benchmark mode)
        self.steering = None   # optional streaming.SteeringEndpoint
        self.on_steer: List[Callable[[dict], None]] = []  # non-camera msgs
        self._pending_meta = {}  # frame index -> VDIMetadata at dispatch

        from scenery_insitu_tpu.ops import slicer as _slicer
        self._slicer = _slicer
        self.engine = _slicer.resolve_engine(self.cfg.slicer.engine)
        self._build_steps()
        # runtime TF updates (the reference's updateVis TF payload):
        # rebuild the compiled steps — the TF is baked in as constants
        self.on_steer.append(self._apply_tf_message)

        # world placement: sim grid centered, largest side = 2 world units
        if self.mode == "particles":
            # particle box [0, box) is rendered centered by the step itself
            d = h = w = 1
            self._origin = jnp.zeros((3,), jnp.float32)
            self._spacing = jnp.ones((3,), jnp.float32)
        else:
            d, h, w = (tuple(self.cfg.sim.grid) if sim is None
                       else np.asarray(self.sim.field.shape))
            vox = 2.0 / max(d, h, w)
            self._origin = jnp.asarray(
                [-w * vox / 2, -h * vox / 2, -d * vox / 2], jnp.float32)
            self._spacing = jnp.full((3,), vox, jnp.float32)
            cc = self.cfg.composite
            if cc.rebalance == "bricks" and cc.rebalance_bricks \
                    and int(d) % cc.rebalance_bricks:
                # impossible geometry must fail at session build, not
                # minutes in at the first replan (BrickMap would reject
                # it there; the knob is the fix to name)
                raise ValueError(
                    f"composite.rebalance_bricks={cc.rebalance_bricks} "
                    f"does not divide the volume depth {int(d)} (use 0 "
                    f"for auto, or a divisor)")

    def _build_steps(self) -> None:
        """(Re)build the distributed steps for the current mode/engine/TF
        and reset the per-regime caches. Called at construction and after
        a runtime transfer-function change (the TF is a compile-time
        constant of every step)."""
        r = self.cfg.render
        # step-cache rebuilds drop every compiled executable — counted so
        # a trace can attribute a mid-run compile stall (e.g. a TF
        # steering update) to its cause
        self.obs.count("build_steps")
        self._mxu_steps = {}   # regime key -> jitted distributed step
        self._mxu_thr = {}     # regime key -> temporal threshold state
        self._mxu_reuse = {}   # regime key -> temporal-reuse ReuseState
        self._scan_steps = {}  # (kind, regime, block) -> scan executable
        self._profile_fn = None  # jitted z-live-profile fetch (replan)
        self._ranges_fn = None   # jitted z-range fetch (LOD TF gate)
        self._tf_key = _tf_fingerprint(self.tf)
        self.mode = "vdi"
        if isinstance(self.sim, ParticleSimAdapter):
            # sort-first sphere rendering (≅ InVisRenderer + Head)
            from scenery_insitu_tpu.parallel.particles import (
                distributed_particle_step)
            self.mode = "particles"
            self._step = distributed_particle_step(
                self.mesh, r.width, r.height,
                radius=self.cfg.sim.particle_radius)
        elif isinstance(self.sim, HybridSimAdapter):
            # hybrid is implemented on the slice-march engine only (the
            # particle layer shares the virtual camera's rays); the engine
            # knob is overridden so telemetry reports what actually runs
            self.mode = "hybrid"
            self.engine = "mxu"
            self._step = None
        elif self.cfg.runtime.generate_vdis and self.engine == "mxu":
            self._step = None
        elif self.cfg.runtime.generate_vdis:
            self._step = distributed_vdi_step(
                self.mesh, self.tf, r.width, r.height,
                self.cfg.vdi, self.cfg.composite, max_steps=r.max_steps,
                plan=self._plan, bricks=self._bricks,
                topology=self.cfg.topology)
        elif self.engine == "mxu":
            # TPU plain mode: slice march + column exchange + nearest-first
            # composite on the intermediate grid, homography-warped to the
            # display camera per frame (≅ DistributedVolumeRenderer.kt:
            # 175-189's plain pipeline, re-scheduled for the MXU)
            self.mode = "plain"
            self._step = None
        else:
            self.mode = "plain"
            cc = self.cfg.composite
            self._step = distributed_plain_step(
                self.mesh, self.tf, r.width, r.height, r,
                exchange=cc.exchange,
                wire=cc.wire,
                schedule=cc.schedule,
                wave_tiles=cc.wave_tiles,
                rebalance=cc.rebalance,
                rebalance_period=cc.rebalance_period,
                rebalance_hysteresis=cc.rebalance_hysteresis,
                rebalance_min_depth=cc.rebalance_min_depth,
                rebalance_quantum=cc.rebalance_quantum,
                rebalance_bricks=cc.rebalance_bricks,
                rebalance_max_moves=cc.rebalance_max_moves,
                temporal_reuse=cc.temporal_reuse,
                plan=self._plan, bricks=self._bricks,
                topology=self.cfg.topology)

        self._temporal = (self.cfg.vdi.adaptive
                          and self.cfg.vdi.adaptive_mode == "temporal"
                          and self.mode in ("vdi", "hybrid")
                          and self.engine == "mxu")
        # temporal fragment reuse (docs/PERF.md "Temporal deltas"): the
        # carried-state plumbing exists on the MXU VDI step only; other
        # modes' builders (gather/hybrid/plain) ledger the knob inert,
        # and the particle step never consults CompositeConfig at all —
        # say so here rather than silently rendering every frame
        # brick-partitioned marches carry no reuse plumbing — the builder
        # ledgers the inert knob (delta.reuse) when a map is active
        self._reuse = (self.cfg.composite.temporal_reuse == "ranges"
                       and self.mode == "vdi" and self.engine == "mxu"
                       and self._step is None and self._bricks is None)
        if self.cfg.composite.temporal_reuse == "ranges" \
                and not self._reuse and self.mode == "particles":
            _obs.degrade("delta.reuse", "ranges", "off",
                         "particle sessions march no volume fragments",
                         warn=False)
        # particle/plain modes never consult cfg.vdi — only reject the
        # mode that would hit the slicer's temporal-needs-state error at
        # trace time (gather VDI generation)
        if (self.cfg.vdi.adaptive
                and self.cfg.vdi.adaptive_mode == "temporal"
                and not self._temporal and self.mode == "vdi"):
            raise ValueError(
                "adaptive_mode='temporal' is carried threshold state of "
                "the MXU VDI pipeline — this session resolved to mode="
                f"{self.mode!r} engine={self.engine!r}; use 'histogram' "
                "there")

    def _apply_tf_message(self, msg: dict) -> None:
        """'tf' steering: swap the TF and recompile-OR-REUSE (knot
        arrays are fixed-shape, so pipeline shapes never change). Shared
        protocol logic lives in `apply_tf_steering`."""
        apply_tf_steering(self, msg, self._tf_invalidate)

    def _decomp_key(self):
        """The render-decomposition half of the step-cache key — cached
        steps bake the plan / brick map in as build-time geometry (for
        LOD maps that includes the LEVEL tuple: a level change
        materializes different pooled volumes, so steps compiled for
        one level assignment must never serve another)."""
        return (self._plan,
                None if self._bricks is None
                else (self._bricks.owner, self._bricks.level))

    def _tf_invalidate(self) -> None:
        """Steered-TF recompile-or-reuse keyed on TF identity
        (docs/SCENARIOS.md "Steered transfer functions"): the outgoing
        TF's compiled steps are stashed under its fingerprint, and a
        steered TF seen before (same knots, same render decomposition)
        restores them instead of recompiling — a time-varying TF
        schedule cycling through k looks pays k compiles total, not one
        per update. Carried temporal threshold / reuse state re-seeds
        either way (it tracks scene content under the OLD TF)."""
        if self.cfg.lod.enabled:
            # the TF-straddle coarsening gate is TF-dependent: force the
            # level replan to re-run before the next march so a brick
            # whose range straddles a NEW opacity edge refines on the
            # very next frame, never a stale one (render_frame replans
            # before it dispatches; tests/test_lod.py property test)
            self._plan_frame = None
        old_key = (self._tf_key,) + self._decomp_key()
        self._step_cache[old_key] = (self._mxu_steps, self._scan_steps,
                                     self._step, self._profile_fn,
                                     self._ranges_fn)
        while len(self._step_cache) > 8:        # bound compiled-step pins
            self._step_cache.pop(next(iter(self._step_cache)))
        new_fp = _tf_fingerprint(self.tf)
        self.obs.count("tf_updates")
        entry = self._step_cache.get((new_fp,) + self._decomp_key())
        if entry is not None:
            (self._mxu_steps, self._scan_steps, self._step,
             self._profile_fn, self._ranges_fn) = entry
            self._mxu_thr = {}
            self._mxu_reuse = {}
            self._tf_key = new_fp
            self.obs.count("tf_steps_reused")
            self.obs.event("tf_update", frame=self.frame_index,
                           reused=True, key=new_fp)
            return
        self.obs.event("tf_update", frame=self.frame_index, reused=False,
                       key=new_fp)
        _obs.degrade("scenario.tf_update", "compiled steps", "recompile",
                     "a steered transfer function not seen before "
                     "rebuilds the compiled steps (TF knots are "
                     "compile-time constants)", warn=False)
        self._build_steps()

    # ------------------------------------------------------------- frames

    def render_frame(self):
        """Advance the sim and dispatch one render step (device arrays)."""
        drain_steering(self)
        self._maybe_replan()
        with self.obs.span("sim", frame=self.frame_index,
                           kind=self.sim.kind):
            self.sim.advance(self.cfg.sim.steps_per_frame)
        with self.obs.span("dispatch", frame=self.frame_index,
                           mode=self.mode, engine=self.engine):
            if self.mode == "particles":
                from scenery_insitu_tpu.parallel.particles import (
                    shard_particles)
                centered = self.sim.pos - self.sim.state.box / 2.0
                out = self._step(shard_particles(centered, self.mesh),
                                 shard_particles(self.sim.vel, self.mesh),
                                 self.camera)
                meta = self.frame_metadata(self.frame_index)
            elif self.mode == "hybrid":
                out, meta = self._hybrid_dispatch()
                meta = meta._replace(index=jnp.int32(self.frame_index))
            else:
                field = shard_volume(self.sim.field, self.mesh)
                if self._step is not None:
                    out = self._step(field, self._origin, self._spacing,
                                     self.camera)
                    meta = self.frame_metadata(self.frame_index)
                elif self.mode == "plain":
                    out = self._plain_mxu_dispatch(field)
                    meta = self.frame_metadata(self.frame_index)
                else:
                    out, meta = self._mxu_step()(field, self._origin,
                                                 self._spacing, self.camera)
                    meta = meta._replace(index=jnp.int32(self.frame_index))
        # metadata snapshot BEFORE the camera advances (fetch is pipelined
        # one frame behind, so it must not see the next frame's pose)
        self._pending_meta[self.frame_index] = meta
        # bound the dict: the fetch runs at most pipeline_depth frames
        # behind, so any older entry is unreachable — without this, a
        # headless run(fetch=False) loop (which never pops) grows it
        # forever
        for k in [k for k in self._pending_meta
                  if k < self.frame_index
                  - self.cfg.runtime.pipeline_depth]:
            del self._pending_meta[k]
        self.obs.count("frames_eager_dispatch")
        advance_camera_and_index(self)
        return out

    def run(self, frames: int, fetch: bool = True,
            profile_dir: Optional[str] = None) -> dict:
        """Run the loop with one-frame async pipelining; returns last
        fetched payload.

        ``profile_dir``: capture a device-side profiler trace of the run
        (open with xprof/tensorboard) — the per-op/per-phase breakdown the
        host-side timers cannot see because the frame is one fused program
        (the reference logged host-side phase spans instead,
        DistributedVolumeRenderer.kt:622-648; see also
        benchmarks/phase_bench.py for the split-stage numbers).

        ``cfg.runtime.scan_frames > 1`` rolls blocks of frames into one
        lax.scan executable per launch (parallel/pipeline.frame_scan) —
        same frames, one dispatch — for supported modes; unsupported
        modes log the downgrade and run the eager loop."""
        import contextlib

        if self.cfg.runtime.scan_frames > 1:
            ok, reason = self._scan_supported()
            if ok:
                return self._run_scan(frames, fetch, profile_dir)
            self.log(f"scan_frames={self.cfg.runtime.scan_frames}: "
                     f"falling back to the eager loop ({reason})")
            _obs.degrade("session.scan_frames", "scan", "eager", reason,
                         warn=False)

        ctx = (jax.profiler.trace(profile_dir) if profile_dir
               else contextlib.nullcontext())
        depth = self.cfg.runtime.pipeline_depth
        try:
            with ctx:
                # depth-k device->host pipeline (docs/PERF.md "Async
                # delivery"): the deque holds the in-flight device
                # frames, newest last; a frame retires (fetch + sink
                # delivery, device refs dropped) once `depth` newer
                # dispatches are in flight. depth 1 is bitwise the
                # historical one-deep overlap.
                pending = deque()
                payload = {}
                last = frames - 1
                for i in range(frames):
                    t_f = time.perf_counter()
                    out = self.render_frame()
                    if fetch:
                        # start the device->host copy at dispatch time,
                        # but only when somebody consumes it (sinks
                        # registered, or the caller-visible payload of
                        # the final frame) — a sink-less run pays no
                        # host transfer at all
                        consume = bool(self.sinks or self.tile_sinks) \
                            or i == last
                        if consume:
                            self._start_host_copy(out)
                        pending.append(
                            (self.frame_index - 1, out, consume))
                    else:
                        pending.append(
                            (self.frame_index - 1, out, False))
                    out = None      # the deque holds the only device ref
                    while len(pending) > depth:
                        payload = self._retire(pending.popleft(),
                                               fetch, payload)
                    self.timers.frame_done()
                    self.slo.observe(
                        "frame_ms",
                        (time.perf_counter() - t_f) * 1e3,
                        frame=self.frame_index - 1)
                    if self._obs_pub is not None:
                        self._obs_pub.pump(self.obs)
                while pending:
                    payload = self._retire(pending.popleft(), fetch,
                                           payload)
        except BaseException:
            # flight recorder: an unhandled exception must not lose the
            # final unflushed obs window — drain the delivery queue
            # first (frames the device already paid for), dump, then
            # keep raising
            if self._delivery is not None:
                self._delivery.drain()
            _obs.flight_flush(self.obs, where="run")
            if self._obs_pub is not None:
                self._obs_pub.pump(self.obs, force=True)
            raise
        # end-of-run teardown: drain the async delivery queue, the final
        # partial window frame_done never reached, the whole-run totals,
        # and the obs sinks
        if self._delivery is not None:
            self._delivery.drain()
        self.timers.dump_totals()
        self.obs.flush()
        if self._obs_pub is not None:
            self._obs_pub.pump(self.obs, force=True)
        return payload

    def _retire(self, entry, fetch: bool, payload: dict) -> dict:
        """Retire one pipelined frame: fetch + deliver it when it has
        consumers, otherwise just pace the loop on its device
        completion. The caller already dropped the deque reference, so
        the frame's device buffers free as soon as this returns — the
        pipeline pins exactly `pipeline_depth` frames of HBM, never
        more."""
        index, out, consume = entry
        if fetch and consume:
            return self._fetch(index, out)
        if fetch:
            self._sync_nofetch(index, out)
        return payload

    def _start_host_copy(self, out) -> None:
        """Kick off the device->host transfer of every buffer in ``out``
        without blocking (``copy_to_host_async``): by the time the
        depth-k pipeline retires this frame, the bytes are already on
        the host and ``np.asarray`` is a cheap wrap, not a sync.
        Best-effort — a backend without the method just pays the sync in
        ``_fetch`` like before."""
        try:
            for leaf in jax.tree_util.tree_leaves(out):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
        except Exception:
            pass

    def _sync_nofetch(self, index: int, out) -> None:
        """Retire a pipelined frame nobody consumes: drop its metadata
        snapshot and pace on device completion WITHOUT the device->host
        copy the historical path paid here (``fetch=True`` with no
        sinks used to ``np.asarray`` every frame just to throw the
        bytes away)."""
        self._pending_meta.pop(index, None)
        with self.obs.span("fetch", frame=index, host_copy=False):
            jax.block_until_ready(out)

    def _fetch(self, index: int, out) -> dict:
        from scenery_insitu_tpu.ops.splat import SplatOutput
        meta = self._pending_meta.pop(index, None)
        if meta is None:
            meta = self.frame_metadata(index)
        tiles = ()
        tiled = bool(self.tile_sinks) \
            and self.cfg.composite.schedule == "waves"
        with self.obs.span("fetch", frame=index):
            if isinstance(out, VDI):
                # ONE device->host transfer; the tile delivery below and
                # the frame payload share these buffers (a no-op wrap
                # when _start_host_copy already landed the bytes)
                color = np.asarray(out.color)
                depth = np.asarray(out.depth)
                if tiled:
                    if self._delivery is not None:
                        # async path: slice the tile payloads (views,
                        # no copy) here; the worker delivers them in
                        # the same ascending column order
                        tiles = self._tile_payloads(index, meta,
                                                    color, depth)
                    else:
                        # tile-granular path: each finished column
                        # block is delivered BEFORE the frame payload
                        # is assembled — the frame "closes" (frame
                        # sinks run) only after every tile is already
                        # out the door
                        self._deliver_tiles(index, None, meta,
                                            color=color, depth=depth)
                payload = {"vdi_color": color, "vdi_depth": depth}
            elif isinstance(out, SplatOutput):
                payload = {"image": np.asarray(out.image),
                           "depth": np.asarray(out.depth)}
            else:
                payload = {"image": np.asarray(out)}
            payload["frame"] = index
            payload["meta"] = meta
        if self._delivery is not None:
            # off the critical path: the worker runs the tile sinks then
            # the frame sinks behind the shared SinkGuard; the loop only
            # pays the enqueue (or backpressure, per overflow policy)
            self._delivery.submit(index, payload, tiles)
        else:
            with self.obs.span("sinks", frame=index):
                self._sink_guard.run(self.sinks, index, payload)
        return payload

    def _tile_payloads(self, index: int, meta, color, depth) -> list:
        """Slice one composited VDI frame into its column-block tile
        payloads, ascending global column order (tile t covers columns
        [t*wb, (t+1)*wb)). Tiles are the wave schedule's unit — n_ranks
        * wave_tiles blocks; a width the tiling does not divide degrades
        to per-rank blocks. Slices are views: no host copy here."""
        n = self._n_ranks
        tiles = n * self.cfg.composite.wave_tiles
        w_total = color.shape[-1]
        if w_total % tiles:
            tiles = n                       # waves degraded to frame
        wb = w_total // tiles
        return [{
            "vdi_color": color[..., t * wb:(t + 1) * wb],
            "vdi_depth": depth[..., t * wb:(t + 1) * wb],
            "frame": index, "tile": t, "tiles": tiles,
            "col0": t * wb, "meta": meta,
        } for t in range(tiles)]

    def _deliver_tiles(self, index: int, out, meta=None,
                       color=None, depth=None) -> None:
        """Hand every column-block tile of one composited VDI frame to
        the tile sinks, in ascending global column order (the delivery
        contract: tile t arrives before tile t+1 and before the frame's
        own sinks)."""
        if meta is None:
            meta = self._pending_meta.get(index,
                                          self.frame_metadata(index))
        if color is None:
            color = np.asarray(out.color)
            depth = np.asarray(out.depth)
        for payload in self._tile_payloads(index, meta, color, depth):
            with self.obs.span("tile", frame=index,
                               tile=payload["tile"]):
                self.obs.count("tiles_delivered")
                self._sink_guard.run(self.tile_sinks, index, payload,
                                     kind="tile sink")

    # ------------------------------------------------ render rebalancing

    def _replan_profile(self):
        """Fetch the GLOBAL per-z-bin live profile of the current field
        (host numpy) — each rank reduces its even slab in data layout
        (ops/occupancy.z_live_profile, one sweep, no permute) and the
        profiles concatenate along the mesh axis. The jitted reduction
        is cached until the TF or steps change (_build_steps resets)."""
        from jax.sharding import PartitionSpec as P

        from scenery_insitu_tpu.ops import occupancy as _occ
        from scenery_insitu_tpu.utils.compat import shard_map

        if self._profile_fn is None:
            axis = self._flat_axis
            n = self._n_ranks
            tf = self.tf
            dn = int(self.sim.field.shape[0]) // n
            nzb = _occ._cap_divisor(dn, 32)

            def prof(local):
                return _occ.z_live_profile(local, tf, nzb=nzb)

            self._profile_fn = jax.jit(shard_map(
                prof, mesh=self.mesh, in_specs=P(axis, None, None),
                out_specs=P(axis), check_vma=False))
        field = shard_volume(self.sim.field, self.mesh)
        return np.asarray(self._profile_fn(field))

    def _replan_ranges(self):
        """Fetch the GLOBAL per-z-bin sampled value range of the current
        field (host numpy) — `ops/occupancy.z_range_profile` on each
        rank's even slab, concatenated along the mesh axis. The LOD
        planner's TF-straddle gate input (docs/PERF.md "LOD marching");
        cached like `_replan_profile`."""
        from jax.sharding import PartitionSpec as P

        from scenery_insitu_tpu.ops import occupancy as _occ
        from scenery_insitu_tpu.utils.compat import shard_map

        if self._ranges_fn is None:
            axis = self._flat_axis
            n = self._n_ranks
            dn = int(self.sim.field.shape[0]) // n
            nzb = _occ._cap_divisor(dn, 32)

            def rng(local):
                return _occ.z_range_profile(local, nzb=nzb)

            self._ranges_fn = jax.jit(shard_map(
                rng, mesh=self.mesh, in_specs=P(axis, None, None),
                out_specs=(P(axis), P(axis)), check_vma=False))
        field = shard_volume(self.sim.field, self.mesh)
        lo, hi = self._ranges_fn(field)
        return np.asarray(lo), np.asarray(hi)

    def _maybe_replan(self) -> None:
        """Host-side re-plan of the RENDER z decomposition
        (CompositeConfig.rebalance == "occupancy"; docs/PERF.md "Render
        rebalancing"), every ``rebalance_period`` frames: fetch the live
        profile, run ops/occupancy.slice_plan (quantum + hysteresis keep
        the plan stable), and when the plan actually CHANGES, drop the
        compiled steps so the next dispatch rebuilds them on the new
        band split — one recompile per adopted plan, minted on the
        fallback ledger (occupancy.replan) with a ``rebalance_plan``
        event carrying the slice histogram and modeled straggler
        factors."""
        cc = self.cfg.composite
        if self.cfg.lod.enabled and cc.rebalance != "bricks":
            # LOD levels live on the brick map — without the brick
            # partition there is nothing to carry them (configured-but-
            # inert knob: say so once, don't silently render level 0)
            _obs.degrade(
                "lod.inert", "lod", "off",
                f"lod.enabled needs composite.rebalance='bricks' to "
                f"carry levels (got {cc.rebalance!r}); every march "
                "samples at level 0", warn=False)
        if cc.rebalance not in ("occupancy", "bricks"):
            return
        n = self._n_ranks
        # an LOD session replans on a single rank too: a level change
        # alters WHAT that rank marches, not just who marches what
        lod_on = (self.cfg.lod.enabled and cc.rebalance == "bricks"
                  and self.mode == "vdi" and hasattr(self.sim, "field"))
        if self.mode == "particles" or not hasattr(self.sim, "field") \
                or (n == 1 and not lod_on):
            # configured-but-inert knob: say so once instead of silently
            # rendering even splits forever
            _obs.degrade(
                "occupancy.rebalance", "occupancy", "even",
                ("single-rank mesh has one band" if n == 1 else
                 f"mode {self.mode!r} renders no volume field to "
                 "rebalance"), warn=False)
            return
        if cc.rebalance == "bricks" and self.mode != "vdi":
            # only the gather/MXU VDI builders consume a brick map —
            # replanning here would recompile hybrid/plain steps that
            # ledger the map inert and render even slabs regardless
            _obs.degrade(
                "bricks.partition", "bricks", "slabs",
                f"mode {self.mode!r} has no brick march (gather/MXU VDI "
                "steps only); the even z-slab decomposition renders",
                warn=False)
            return
        if self._plan_frame is not None and \
                self.frame_index - self._plan_frame < cc.rebalance_period:
            return
        if cc.rebalance == "bricks":
            self._replan_bricks(cc, n)
            return
        from scenery_insitu_tpu.ops import occupancy as _occ

        d = int(self.sim.field.shape[0])
        with self.obs.span("replan", frame=self.frame_index):
            profile = self._replan_profile()
            even = _occ.even_plan(d, n)
            prev = self._plan if self._plan is not None else even
            plan = _occ.slice_plan(
                profile, d, n, min_depth=cc.rebalance_min_depth,
                quantum=cc.rebalance_quantum, prev=prev,
                hysteresis=cc.rebalance_hysteresis)
        self._plan_frame = self.frame_index
        if plan == prev:
            return                      # stable — nothing recompiles
        self.obs.count("rebalance_replans")
        self.obs.event(
            "rebalance_plan", frame=self.frame_index, plan=list(plan),
            straggler_even=round(_occ.straggler_factor(profile, d, even),
                                 3),
            straggler_planned=round(_occ.straggler_factor(profile, d,
                                                          plan), 3))
        _obs.degrade("occupancy.replan", f"plan{tuple(prev)}",
                     f"plan{tuple(plan)}",
                     "render bands re-planned from fetched live "
                     "fractions; affected steps recompile", warn=False)
        self._plan = plan if plan != even else None
        self._build_steps()

    def _replan_bricks(self, cc, n: int) -> None:
        """Brick-stealing re-plan (CompositeConfig.rebalance == "bricks";
        docs/SCENARIOS.md "Brick maps"): bin the fetched z live profile
        into per-brick work and greedily move at most
        ``rebalance_max_moves`` bricks from the most- to the least-loaded
        rank (parallel.bricks.steal_plan, hysteresis-stable). An adopted
        map change drops the compiled steps exactly like a slab replan;
        a map that converges back to the even-convex assignment restores
        the brickless fast path.

        With ``lod.enabled`` the replan ALSO selects per-brick
        refinement levels (`parallel.lod.select_levels`: screen-space
        error + empty coarsening + hysteresis + the TF-straddle gate)
        and scales the stolen work into level units
        (`parallel.lod.level_work_scale`) — a level-2 brick is ~64x
        cheaper than its level-0 self, and equalizing raw live work
        would re-create the straggler the levels just removed. A level
        change recompiles exactly like an ownership change (the
        `_decomp_key` carries the level tuple)."""
        from scenery_insitu_tpu.parallel import bricks as _bk

        lod = self.cfg.lod
        d = int(self.sim.field.shape[0])
        with self.obs.span("replan", frame=self.frame_index):
            profile = self._replan_profile()
            nb = cc.rebalance_bricks or _bk.auto_nbricks(d, n)
            work = _bk.brick_work(profile, d, nb)
            seed = _bk.BrickMap.contiguous(d, n, nb)
            prev = (self._bricks if self._bricks is not None
                    and self._bricks.nbricks == nb else seed)
            if lod.enabled:
                from scenery_insitu_tpu.core.transfer import opacity_edges
                from scenery_insitu_tpu.parallel import lod as _lod

                lo, hi = self._replan_ranges()
                shp = self.sim.field.shape                  # (D, H, W)
                dims = (int(shp[2]), int(shp[1]), int(shp[0]))
                cam = self.camera
                levels = _lod.select_levels(
                    _lod.per_brick(profile, nb, red="mean"),
                    _lod.per_brick(lo, nb, red="min"),
                    _lod.per_brick(hi, nb, red="max"),
                    opacity_edges(self.tf, lod.tf_edge_eps),
                    dims=dims, origin=np.asarray(self._origin),
                    spacing=np.asarray(self._spacing),
                    eye=np.asarray(cam.eye), fov_y=float(cam.fov_y),
                    height_px=self.cfg.render.height, cfg=lod,
                    prev=(self._bricks.level
                          if self._bricks is not None
                          and self._bricks.nbricks == nb else None))
                prev = prev.with_levels(levels)
                work = work * _lod.level_work_scale(
                    levels, dims, self.cfg.render.width,
                    self.cfg.render.height)
            bm = _bk.steal_plan(prev, work,
                                max_moves=cc.rebalance_max_moves,
                                hysteresis=cc.rebalance_hysteresis)
        self._plan_frame = self.frame_index
        new = None if bm.is_even_convex() else bm
        cur = self._bricks
        if (new is None) == (cur is None) and \
                (new is None or (new.owner == cur.owner
                                 and new.level == cur.level)):
            return                      # stable — nothing recompiles
        self.obs.count("rebalance_replans")
        levels_now = list(bm.level)
        self.obs.event(
            "rebalance_plan", frame=self.frame_index, kind="bricks",
            nbricks=nb, owner=list(bm.owner), level=levels_now,
            max_level=int(max(levels_now)) if levels_now else 0,
            straggler_even=round(_bk.straggler_factor(seed, work), 3),
            straggler_planned=round(_bk.straggler_factor(bm, work), 3))
        _obs.degrade("occupancy.replan",
                     f"bricks{tuple(prev.owner)}",
                     f"bricks{tuple(bm.owner)}",
                     "brick ownership re-planned from fetched live "
                     "fractions; affected steps recompile", warn=False)
        self._bricks = new
        self._build_steps()

    def _enter_regime(self, key) -> None:
        if key != getattr(self, "_last_regime_key", key):
            self.obs.count("regime_switches")
            # carried reuse fragments share the temporal-threshold
            # staleness policy: the field kept evolving while the
            # camera was in another regime, and a re-entered regime's
            # retained signature could mask that (the camera leaves
            # match again) — re-seed instead
            self._mxu_reuse.pop(key, None)
        drop_on_regime_reentry(self, self._mxu_thr, key)

    def _note_dirty(self, ru) -> None:
        """Host-side accounting of the reuse carry's LAST decision
        (docs/OBSERVABILITY.md): ``delta_march_skipped`` counts tiles
        whose march never issued, and the per-frame dirty histogram
        event carries the per-rank bits. Reads the INCOMING carry — the
        decision it describes is the previous frame's, which has
        already executed (no extra sync on the in-flight dispatch)."""
        d = np.asarray(ru.dirty)
        if not np.asarray(ru.valid).any():
            return                       # seed state: nothing decided yet
        cc = self.cfg.composite
        n = d.size
        tiles_per_rank = (cc.wave_tiles
                          if cc.schedule == "waves" and n > 1 else 1)
        clean = int((d == 0).sum())
        if clean:
            self.obs.count("delta_march_skipped", clean * tiles_per_rank)
        self.obs.event("delta_dirty_tiles", frame=self.frame_index - 1,
                       dirty=[int(x) for x in d],
                       tiles_per_rank=tiles_per_rank,
                       skipped_tiles=clean * tiles_per_rank,
                       total_tiles=n * tiles_per_rank)

    # ------------------------------------------------- frame-scan blocks

    def _scan_supported(self):
        """Can this session roll frames into lax.scan blocks? Volume-sim
        VDI sessions only: particles/hybrid/plain carry host-side render
        state per frame, and a custom sim adapter gives no traceable
        (state, advance) pair."""
        if self.mode != "vdi":
            return False, f"mode {self.mode!r} (volume VDI sessions only)"
        if not isinstance(self.sim, VolumeSimAdapter):
            return (False, "custom sim adapter (need the built-in "
                           "traceable state/advance pair)")
        return True, ""

    def _scan_runner(self, block: int, regime):
        """Build (or fetch) the scanned-block executable for a march
        regime (None = the gather engine's regime-free step) and block
        size; returns (runner, seed) where seed is the temporal
        threshold seeder or None."""
        from scenery_insitu_tpu.parallel.pipeline import (
            distributed_initial_threshold_mxu, distributed_vdi_step_mxu,
            distributed_vdi_step_mxu_temporal, frame_scan)

        key = ("scan", regime, block)
        entry = self._scan_steps.get(key)
        if entry is None:
            # cache miss = one fresh scan-block jit at next dispatch
            self.obs.count("compile_scan_block")
            self.obs.event("compile", frame=self.frame_index,
                           what="scan_block", regime=str(regime),
                           block=block)
            comp_cfg = self.cfg.composite
            if self._reuse:
                # the scan body does not thread the reuse carry — a
                # scanned block re-marches every frame (the scan's
                # whole point is zero host round trips per frame, which
                # is also what the host-held carry would need)
                import dataclasses as _dc

                comp_cfg = _dc.replace(comp_cfg, temporal_reuse="off")
                _obs.degrade("delta.reuse", "ranges", "off",
                             "scan blocks do not thread the reuse "
                             "carry; scanned frames re-march",
                             warn=False)
            if regime is None:
                step, seed = self._step, None
            else:
                n = self._n_ranks
                spec = self._slicer.make_spec(
                    self.camera, self.sim.field.shape, self.cfg.slicer,
                    axis_sign=regime, multiple_of=n)
                if self._temporal:
                    step = distributed_vdi_step_mxu_temporal(
                        self.mesh, self.tf, spec, self.cfg.vdi,
                        comp_cfg, plan=self._plan, bricks=self._bricks,
                        topology=self.cfg.topology)
                    seed = distributed_initial_threshold_mxu(
                        self.mesh, self.tf, spec, self.cfg.vdi,
                        plan=self._plan, bricks=self._bricks)
                else:
                    step = distributed_vdi_step_mxu(
                        self.mesh, self.tf, spec, self.cfg.vdi,
                        comp_cfg, plan=self._plan, bricks=self._bricks,
                        topology=self.cfg.topology)
                    seed = None
            steps_per_frame = self.cfg.sim.steps_per_frame
            mesh_n = self._n_ranks
            if mesh_n > 1 and self.sim.kind == "gray_scott":
                # inside the scanned executable GSPMD propagates the
                # render step's z-sharding back into the sim advance, and
                # the fused Pallas stencil's periodic wrap is per-buffer
                # (sim/pallas_stencil.py docstring) — pin the roll
                # formulation, whose rolls XLA lowers to ICI halo
                # exchanges, whenever the mesh can actually shard
                advance = lambda s: gs.multi_step(s, steps_per_frame)
            else:
                advance = lambda s: self.sim._advance(s, steps_per_frame)
            entry = (frame_scan(step, advance, block,
                                temporal=self._temporal), seed)
            self._scan_steps[key] = entry
        return entry

    def _run_scan(self, frames: int, fetch: bool,
                  profile_dir: Optional[str]) -> dict:
        """The scan-block twin of the eager loop: identical frames (same
        sim advance, same per-frame camera ladder, same metadata), one
        executable launch per block. Steering drains and regime changes
        take effect at block boundaries only; a block whose host-replayed
        camera path crosses march regimes runs eagerly instead (a scan
        body cannot re-specialize mid-block). In temporal mode a missing
        threshold state is seeded from the PRE-block field (the eager
        loop seeds post-advance — one frame of controller lag, adapted
        away like any temporal-mode scene change)."""
        import contextlib

        ctx = (jax.profiler.trace(profile_dir) if profile_dir
               else contextlib.nullcontext())
        payload = {}
        try:
            with ctx:
                payload = self._scan_loop(frames, fetch, payload)
        except BaseException:
            # flight recorder (same contract as the eager loop): drain
            # the delivery queue first, then dump
            if self._delivery is not None:
                self._delivery.drain()
            _obs.flight_flush(self.obs, where="run_scan")
            if self._obs_pub is not None:
                self._obs_pub.pump(self.obs, force=True)
            raise
        if self._delivery is not None:
            self._delivery.drain()
        self.timers.dump_totals()
        self.obs.flush()
        if self._obs_pub is not None:
            self._obs_pub.pump(self.obs, force=True)
        return payload

    def _scan_loop(self, frames: int, fetch: bool, payload: dict) -> dict:
        done = 0
        while done < frames:
            t_blk = time.perf_counter()
            block = min(self.cfg.runtime.scan_frames, frames - done)
            drain_steering(self)
            self._maybe_replan()
            # host replay of the block's camera ladder — frame i of
            # the scan renders with exactly this camera (orbit is
            # applied identically in-scan)
            cams = [self.camera]
            for _ in range(block - 1):
                cams.append(orbit(cams[-1],
                                  jnp.float32(self.orbit_rate)))
            mxu = self._step is None
            regime = None
            crossing = False
            if mxu:
                regimes = {self._slicer.choose_axis(c) for c in cams}
                crossing = len(regimes) > 1
            # eager fallback for blocks the cached scan executable
            # cannot serve: a regime crossing (the step is
            # regime-specialized) or a short TAIL block (compiling a
            # one-off scan of the whole pipeline for a different
            # length costs far more than the frames it would save)
            if crossing or block < self.cfg.runtime.scan_frames:
                if crossing:
                    self.log(f"scan_frames: march regime crossing "
                             f"inside a {block}-frame block — running "
                             "it eagerly")
                    _obs.degrade(
                        "session.scan_block", "scan", "eager",
                        "march regime crossing inside a block",
                        warn=False)
                else:
                    # a tail block is expected on long runs, but it
                    # still ran eagerly — the ledger must say so (a
                    # run SHORTER than scan_frames is all tail, and
                    # an empty ledger would read as "scan was live")
                    self.obs.count("scan_tail_eager_frames", block)
                    self.log(f"scan_frames: {block}-frame tail block "
                             "below the scan length — running it "
                             "eagerly")
                    _obs.degrade(
                        "session.scan_block", "scan", "eager",
                        "tail block shorter than scan_frames",
                        warn=False)
                for _ in range(block):
                    out = self.render_frame()
                    if fetch:
                        payload = self._fetch(self.frame_index - 1,
                                              out)
                    self.timers.frame_done()
                self._scan_block_done(t_blk, block)
                done += block
                continue
            if mxu:
                regime = next(iter(regimes))
                if self._temporal:
                    self._enter_regime(regime)
            runner, seed = self._scan_runner(block, regime)
            self.obs.count("scan_blocks_dispatched")
            self.obs.count("frames_scan_dispatch", block)
            with self.obs.span("dispatch", frame=self.frame_index,
                               scan_block=block,
                               regime=str(regime)):
                args = (self.sim.state, self._origin, self._spacing,
                        self.camera, jnp.float32(self.orbit_rate))
                if self._temporal:
                    thr = self._mxu_thr.get(regime)
                    if thr is None:
                        field = shard_volume(self.sim.field, self.mesh)
                        thr = seed(field, self._origin, self._spacing,
                                   self.camera)
                    (st, cam, thr2), outs = runner(*args, thr)
                    self._mxu_thr[regime] = thr2
                else:
                    (st, cam, _), outs = runner(*args)
            self.sim.state = st
            self.camera = cam
            start = self.frame_index
            self.frame_index += block
            if fetch:
                vdi = outs[0] if mxu else outs
                metas = outs[1] if mxu else None
                with self.obs.span("fetch", frame=start,
                                   scan_block=block):
                    color = np.asarray(vdi.color)
                    depth = np.asarray(vdi.depth)
                for i in range(block):
                    idx = start + i
                    if metas is not None:
                        meta = jax.tree_util.tree_map(
                            lambda x, i=i: x[i], metas)
                        meta = meta._replace(index=jnp.int32(idx))
                    else:
                        meta = self.frame_metadata(idx, camera=cams[i])
                    tiled = bool(self.tile_sinks) \
                        and self.cfg.composite.schedule == "waves"
                    payload = {"vdi_color": color[i],
                               "vdi_depth": depth[i],
                               "frame": idx, "meta": meta}
                    if self._delivery is not None:
                        tiles = (self._tile_payloads(
                            idx, meta, color[i], depth[i])
                            if tiled else ())
                        self._delivery.submit(idx, payload, tiles)
                    else:
                        if tiled:
                            self._deliver_tiles(idx, None, meta,
                                                color=color[i],
                                                depth=depth[i])
                        with self.obs.span("sinks", frame=idx):
                            self._sink_guard.run(self.sinks, idx,
                                                 payload)
                    self.timers.frame_done()
            else:
                for _ in range(block):
                    self.timers.frame_done()
            self._scan_block_done(t_blk, block)
            done += block
        return payload

    def _scan_block_done(self, t_blk: float, block: int) -> None:
        """Per-block SLO + telemetry bookkeeping: the block's wall clock
        amortizes over its frames (the scan's per-frame latency is the
        block mean by construction)."""
        dt_ms = (time.perf_counter() - t_blk) * 1e3 / max(1, block)
        for i in range(block):
            self.slo.observe("frame_ms", dt_ms,
                             frame=self.frame_index - block + i)
        if self._obs_pub is not None:
            self._obs_pub.pump(self.obs)

    def prewarm_regimes(self, regimes=None) -> dict:
        """Precompile the distributed MXU step for each (axis, sign) march
        regime BEFORE the camera path reaches it. A regime crossing
        mid-run otherwise stalls on a fresh jit of the whole SPMD frame —
        10-24 s at the 512^3 flagship scale per the round-3 captures —
        inside what should be a steady interactive loop (the reference
        never pays this: GPU raycasting has no march-axis specialization;
        this is the TPU design's one compile-shaped cost, so the session
        must be able to hoist it to startup).

        Renders one throwaway frame per regime with the CURRENT field and
        a synthetic camera on that regime's axis (same distance/target).
        Completely invisible to the loop's own state: the camera,
        temporal-threshold cache and regime-reentry tracker are restored;
        the sim, frame index and sinks are never touched. Modes without
        per-regime compilation (particles, gather engine) return {}.

        regimes: iterable of (axis, sign); default all six.
        Returns {(axis, sign): seconds} (compile + one frame each).
        """
        import time as _time

        if self.engine != "mxu" or self.mode == "particles" \
                or (self.mode == "vdi" and self._step is not None):
            return {}
        if regimes is None:
            regimes = [(a, s) for a in (0, 1, 2) for s in (1, -1)]
        cam0 = self.camera
        thr0 = dict(self._mxu_thr)
        reuse0 = dict(self._mxu_reuse)
        had_last = hasattr(self, "_last_regime_key")
        last0 = getattr(self, "_last_regime_key", None)
        times = {}
        try:
            for regime in regimes:
                a, s = regime
                cam = regime_camera(cam0, regime, self._slicer)
                self.camera = cam
                t0 = _time.perf_counter()
                with self.obs.span("prewarm", regime=str(regime)):
                    if self.mode == "hybrid":
                        out, _ = self._hybrid_dispatch()
                    else:
                        field = shard_volume(self.sim.field, self.mesh)
                        if self.mode == "plain":
                            out = self._plain_mxu_dispatch(field)
                        else:
                            out, _ = self._mxu_step()(field, self._origin,
                                                      self._spacing, cam)
                    jax.block_until_ready(out)
                times[(a, s)] = round(_time.perf_counter() - t0, 2)
        finally:
            self.camera = cam0
            self._mxu_thr = thr0
            self._mxu_reuse = reuse0
            if had_last:
                self._last_regime_key = last0
            elif hasattr(self, "_last_regime_key"):
                del self._last_regime_key
        return times

    def _hybrid_dispatch(self):
        """Dispatch one distributed hybrid frame: volume VDI + tracers,
        merged on the virtual grid, warped to the display camera. In
        temporal mode the VDI pass carries per-regime threshold state
        (seeded on first use) exactly like the plain VDI pipeline."""
        from scenery_insitu_tpu.core.volume import Volume
        from scenery_insitu_tpu.parallel.particles import shard_particles
        from scenery_insitu_tpu.parallel.pipeline import (
            distributed_hybrid_step_mxu, distributed_initial_threshold_mxu)
        from scenery_insitu_tpu.sim import vortex as _vx

        regime = self._slicer.choose_axis(self.camera)
        key = ("hybrid",) + regime
        if self._temporal:
            self._enter_regime(key)
        entry = self._mxu_steps.get(key)
        if entry is None:
            self.obs.count("compile_step")
            self.obs.event("compile", frame=self.frame_index,
                           what="hybrid_step", regime=str(regime))
            n = self._n_ranks
            spec = self._slicer.make_spec(self.camera, self.sim.field.shape,
                                          self.cfg.slicer, axis_sign=regime,
                                          multiple_of=n)
            step = distributed_hybrid_step_mxu(
                self.mesh, self.tf, spec, self.cfg.vdi, self.cfg.composite,
                radius=self.cfg.sim.particle_radius * float(self._spacing[0]),
                stamp=5, temporal=self._temporal, plan=self._plan,
                bricks=self._bricks, topology=self.cfg.topology)
            seed = (distributed_initial_threshold_mxu(
                        self.mesh, self.tf, spec, self.cfg.vdi,
                        plan=self._plan)
                    if self._temporal else None)
            r = self.cfg.render
            slicer = self._slicer

            @jax.jit
            def warp(img, field, cam):
                vol = Volume(field, self._origin, self._spacing)
                axcam = slicer.make_axis_camera(vol, cam, spec)
                return slicer.warp_to_camera(img, axcam, spec, cam,
                                             r.width, r.height, r.background)

            entry = (step, seed, warp)
            self._mxu_steps[key] = entry
        step, seed, warp = entry
        field = self.sim.field
        vel = _vx.tracer_velocities(self.sim.flow.u, self.sim.tracers)
        world = _vx.tracers_to_world(self.sim.tracers, self._origin,
                                     self._spacing)
        sfield = shard_volume(field, self.mesh)
        args = (sfield, self._origin, self._spacing,
                shard_particles(world, self.mesh),
                shard_particles(vel, self.mesh), self.camera)
        if self._temporal:
            thr = self._mxu_thr.get(key)
            if thr is None:
                thr = seed(sfield, self._origin, self._spacing, self.camera)
            (img, meta), self._mxu_thr[key] = step(*args, thr)
        else:
            img, meta = step(*args)
        return warp(img, field, self.camera), meta

    def _plain_mxu_dispatch(self, field):
        """Dispatch one distributed plain-image frame on the slice-march
        engine: per-rank `render_slices` + column all_to_all + nearest-
        first composite (one SPMD program per march regime), then the
        homography warp to the display camera."""
        from scenery_insitu_tpu.parallel.pipeline import (
            distributed_plain_step_mxu)

        regime = self._slicer.choose_axis(self.camera)
        key = ("plain",) + regime
        entry = self._mxu_steps.get(key)
        if entry is None:
            self.obs.count("compile_step")
            self.obs.event("compile", frame=self.frame_index,
                           what="plain_step", regime=str(regime))
            n = self._n_ranks
            spec = self._slicer.make_spec(self.camera, self.sim.field.shape,
                                          self.cfg.slicer, axis_sign=regime,
                                          multiple_of=n)
            cc = self.cfg.composite
            step = distributed_plain_step_mxu(
                self.mesh, self.tf, spec, self.cfg.render,
                exchange=cc.exchange,
                wire=cc.wire,
                schedule=cc.schedule,
                wave_tiles=cc.wave_tiles,
                rebalance=cc.rebalance,
                rebalance_period=cc.rebalance_period,
                rebalance_hysteresis=cc.rebalance_hysteresis,
                rebalance_min_depth=cc.rebalance_min_depth,
                rebalance_quantum=cc.rebalance_quantum,
                rebalance_bricks=cc.rebalance_bricks,
                rebalance_max_moves=cc.rebalance_max_moves,
                temporal_reuse=cc.temporal_reuse,
                plan=self._plan, bricks=self._bricks,
                topology=self.cfg.topology)
            r = self.cfg.render
            slicer = self._slicer

            @jax.jit
            def warp(img, axcam, cam):
                return slicer.warp_to_camera(img, axcam, spec, cam,
                                             r.width, r.height, r.background)

            entry = (step, warp)
            self._mxu_steps[key] = entry
        step, warp = entry
        img, axcam = step(field, self._origin, self._spacing, self.camera)
        return warp(img, axcam, self.camera)

    def _mxu_step(self):
        """Jitted MXU distributed step for the camera's current march
        regime; one compilation per (axis, sign), cached (the camera may
        orbit across axis boundaries mid-session). In temporal mode the
        returned callable seeds and threads the per-regime threshold
        state internally, so callers see the same 4-arg signature."""
        from scenery_insitu_tpu.parallel.pipeline import (
            distributed_initial_reuse_mxu,
            distributed_initial_threshold_mxu, distributed_vdi_step_mxu,
            distributed_vdi_step_mxu_temporal)

        regime = self._slicer.choose_axis(self.camera)
        if self._temporal or self._reuse:
            self._enter_regime(regime)
        step = self._mxu_steps.get(regime)
        if step is None:
            self.obs.count("compile_step")
            self.obs.event("compile", frame=self.frame_index,
                           what="vdi_step", regime=str(regime))
            n = self._n_ranks
            spec = self._slicer.make_spec(self.camera, self.sim.field.shape,
                                          self.cfg.slicer, axis_sign=regime,
                                          multiple_of=n)
            tol = self.cfg.delta.range_tol
            rseed = (distributed_initial_reuse_mxu(
                         self.mesh, self.tf, spec, self.cfg.vdi,
                         self.cfg.composite, plan=self._plan)
                     if self._reuse else None)
            if self._temporal:
                inner = distributed_vdi_step_mxu_temporal(
                    self.mesh, self.tf, spec, self.cfg.vdi,
                    self.cfg.composite, plan=self._plan,
                    bricks=self._bricks, reuse_tol=tol,
                    topology=self.cfg.topology)
                seed = distributed_initial_threshold_mxu(
                    self.mesh, self.tf, spec, self.cfg.vdi,
                    plan=self._plan, bricks=self._bricks)

                def step(field, origin, spacing, cam,
                         _regime=regime, _inner=inner, _seed=seed,
                         _rseed=rseed):
                    thr = self._mxu_thr.get(_regime)
                    if thr is None:
                        thr = _seed(field, origin, spacing, cam)
                    if _rseed is None:
                        out, self._mxu_thr[_regime] = _inner(
                            field, origin, spacing, cam, thr)
                        return out
                    ru = self._mxu_reuse.get(_regime)
                    if ru is None:
                        ru = _rseed(field, origin, spacing, cam)
                    if getattr(self.obs, "enabled", False):
                        self._note_dirty(ru)
                    out, self._mxu_thr[_regime], \
                        self._mxu_reuse[_regime] = _inner(
                            field, origin, spacing, cam, thr, ru)
                    return out
            elif self._reuse:
                inner = distributed_vdi_step_mxu(
                    self.mesh, self.tf, spec, self.cfg.vdi,
                    self.cfg.composite, plan=self._plan, reuse_tol=tol,
                    topology=self.cfg.topology)
                # (bricks force _reuse off at _build_steps, so this
                # branch never carries a brick map)

                def step(field, origin, spacing, cam,
                         _regime=regime, _inner=inner, _rseed=rseed):
                    ru = self._mxu_reuse.get(_regime)
                    if ru is None:
                        ru = _rseed(field, origin, spacing, cam)
                    if getattr(self.obs, "enabled", False):
                        self._note_dirty(ru)
                    out, self._mxu_reuse[_regime] = _inner(
                        field, origin, spacing, cam, ru)
                    return out
            else:
                step = distributed_vdi_step_mxu(
                    self.mesh, self.tf, spec, self.cfg.vdi,
                    self.cfg.composite, plan=self._plan,
                    bricks=self._bricks, topology=self.cfg.topology)
            self._mxu_steps[regime] = step
        return step

    def frame_metadata(self, index: int, camera: Optional[Camera] = None):
        """VDIMetadata for the current camera/volume placement (≅ the
        per-frame VDIData the reference builds, DistributedVolumes.kt:
        706-716). NOTE: built from the CURRENT camera (or the explicit
        ``camera`` — the scan path replays the block's camera ladder) —
        call before the camera advances for exact correspondence."""
        from scenery_insitu_tpu.core.camera import (projection_matrix,
                                                    view_matrix)
        from scenery_insitu_tpu.core.vdi import VDIMetadata
        camera = camera if camera is not None else self.camera
        r = self.cfg.render
        shape = (np.asarray(self.sim.field.shape)
                 if hasattr(self.sim, "field") else np.zeros(3, np.int32))
        return VDIMetadata.create(
            projection=projection_matrix(camera, r.width, r.height),
            view=view_matrix(camera),
            volume_dims=np.asarray(shape[::-1], np.float32),   # (x, y, z)
            window_dims=(r.width, r.height),
            nw=float(self._spacing[0]), index=index)

    def device_snapshot(self) -> dict:
        """Per-regime XLA cost-analysis snapshot (bytes/flops) of every
        compiled step this session holds, keyed like the step caches
        (obs/device.cost_snapshot — the same numbers bench.py's roofline
        fields use). Best-effort: steps that are host-side closures
        (temporal mode threads threshold state in Python) or whose mode
        takes different operands report as unavailable rather than
        raising; lowering hits the compile cache, so this is cheap after
        the first frame. The snapshot is also recorded as an obs event so
        a metrics file carries the device-side truth next to the spans."""
        from scenery_insitu_tpu.obs import device as _dev

        snaps = {}
        if self.mode in ("vdi", "plain"):
            field = shard_volume(self.sim.field, self.mesh)
            args = (field, self._origin, self._spacing, self.camera)
            if self._step is not None:
                snaps["gather" if self.mode == "vdi" else "plain"] = \
                    _dev.cost_snapshot(self._step, *args)
            for key, entry in self._mxu_steps.items():
                step = entry[0] if isinstance(entry, tuple) else entry
                if not hasattr(step, "lower"):
                    snaps[str(key)] = {"source": "unavailable",
                                       "error": "host-side closure "
                                                "(temporal step)"}
                    continue
                snaps[str(key)] = _dev.cost_snapshot(step, *args)
        else:
            # hybrid/particle steps take mode-specific operands this
            # generic path does not reconstruct — report them as
            # unavailable rather than returning an empty dict
            keys = (list(self._mxu_steps) if self._mxu_steps
                    else ([self.mode] if self._step is not None else []))
            for key in keys:
                snaps[str(key)] = {"source": "unavailable",
                                   "error": f"mode {self.mode!r} operands "
                                            "not snapshotted"}
        if snaps:
            self.obs.event("device_snapshot", frame=self.frame_index,
                           regimes=list(snaps))
        return snaps


def vdi_sink(directory: str, dataset: str = "session", every: int = 1,
             codec: str = "zstd", workers: int = 1) -> Sink:
    """Dump composited VDIs as .npz artifacts — the render-product
    checkpoint stream offline renderers replay (≅ saveFinal VDIDataIO +
    buffer dumps, DistributedVolumes.kt:846-851, 910-915).

    ``workers`` threads io.vdi_io.save_vdi's per-member compression
    (byte-identical artifacts, shorter sink time — wire it to
    cfg.delivery.encode_workers on the async delivery plane)."""
    from scenery_insitu_tpu.core.vdi import VDI as _VDI
    from scenery_insitu_tpu.io.vdi_io import dump_path, save_vdi

    def sink(index: int, payload: dict) -> None:
        if index % every or "vdi_color" not in payload:
            return
        save_vdi(dump_path(directory, dataset, index, "vdi"),
                 _VDI(payload["vdi_color"], payload["vdi_depth"]),
                 codec=codec, workers=workers)

    return sink


def vdi_tile_sink(directory: str, dataset: str = "session", every: int = 1,
                  codec: str = "zstd", workers: int = 1) -> Sink:
    """Tile-granular twin of `vdi_sink` for ``InSituSession.tile_sinks``
    (composite.schedule == "waves"): each finished column-block tile is
    dumped as its own .npz the moment it is delivered — an offline
    consumer can start on the first columns before the frame closes. The
    artifact carries its (tile, tiles, col0) placement
    (io.vdi_io.save_vdi ``tile=``), so `io.vdi_io.load_vdi_tile` can
    reassemble frames."""
    from scenery_insitu_tpu.core.vdi import VDI as _VDI
    from scenery_insitu_tpu.io.vdi_io import dump_path, save_vdi

    def sink(index: int, payload: dict) -> None:
        if index % every or "vdi_color" not in payload \
                or "tile" not in payload:
            return
        save_vdi(dump_path(directory, dataset, index,
                           f"vditile{payload['tile']:02d}"),
                 _VDI(payload["vdi_color"], payload["vdi_depth"]),
                 payload.get("meta"), codec=codec,
                 tile=(payload["tile"], payload["tiles"],
                       payload["col0"]), workers=workers)

    return sink


def png_sink(directory: str, gamma: float = 2.2, every: int = 1) -> Sink:
    """Dump frames/VDI same-view decodes as PNGs (≅ the reference's
    screenshot + SystemHelpers.dumpToFile outputs)."""
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view
    from scenery_insitu_tpu.utils.image import save_png
    os.makedirs(directory, exist_ok=True)

    def sink(index: int, payload: dict) -> None:
        if index % every:
            return
        if "image" in payload:
            img = payload["image"]
        else:
            img = np.asarray(render_vdi_same_view(
                VDI(jnp.asarray(payload["vdi_color"]),
                    jnp.asarray(payload["vdi_depth"]))))
        save_png(os.path.join(directory, f"frame{index:05d}.png"), img, gamma)

    return sink
