"""Session checkpoint / resume.

The reference checkpoints only the *render product* — VDIDataIO metadata +
raw VDI buffer dumps reloaded by the offline viewers
(DistributedVolumes.kt:910-915; VDICompositingTest.kt:162-163); the
simulation itself cannot be resumed. This framework already matches that
(io/vdi_io.py artifacts + vdi_sink); this module goes further and
checkpoints the *session* — simulation state, frame index, camera pose,
and the carried temporal-threshold controller state — so an in-situ run
can stop and resume bit-exactly.

Format: one ``.npz`` with a JSON header entry. Arrays are fetched to host
(a resumed session re-places them onto its mesh via the normal dispatch
path). For multi-host runs, checkpoint per process or switch the payload
to orbax; the header/state contract here is the same either way.
"""

from __future__ import annotations


import json
from typing import TYPE_CHECKING

import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:   # pragma: no cover
    from scenery_insitu_tpu.runtime.session import InSituSession

_VERSION = 1
_CAMERA_FIELDS = ("eye", "target", "up", "fov_y", "near", "far")


def _sim_arrays(sim) -> dict:
    """kind-specific state arrays of a sim adapter (host numpy)."""
    kind = sim.kind
    if kind in ("gray_scott",):
        return {"u": sim.state.u, "v": sim.state.v}
    if kind == "vortex":
        return {"u": sim.state.u}
    if kind in ("lennard_jones", "sho"):
        return {"pos": sim.state.pos, "vel": sim.state.vel,
                "box": sim.state.box}
    if kind == "hybrid":
        return {"u": sim.flow.u, "tracers": sim.tracers}
    raise ValueError(f"unknown sim kind {kind!r}")


def _restore_sim(sim, arrays: dict) -> None:
    kind = sim.kind
    a = {k: jnp.asarray(v) for k, v in arrays.items()}
    if kind == "gray_scott":
        sim.state = sim.state._replace(u=a["u"], v=a["v"])
    elif kind == "vortex":
        sim.state = sim.state._replace(u=a["u"])
    elif kind in ("lennard_jones", "sho"):
        sim.state = sim.state._replace(pos=a["pos"], vel=a["vel"],
                                       box=a["box"])
    elif kind == "hybrid":
        sim.flow = sim.flow._replace(u=a["u"])
        sim.tracers = a["tracers"]
    else:
        raise ValueError(f"unknown sim kind {kind!r}")


def save_session(sess: "InSituSession", path: str) -> None:
    """Checkpoint a session to ``path`` (.npz)."""
    from scenery_insitu_tpu.ops.supersegments import ThresholdState

    header = {
        "version": _VERSION,
        "sim_kind": sess.sim.kind,
        "mode": sess.mode,
        "engine": sess.engine,
        "temporal": bool(getattr(sess, "_temporal", False)),
        "mesh_devices": int(sess._n_ranks),
        "frame_index": sess.frame_index,
        "orbit_rate": float(sess.orbit_rate),
        "thr_regimes": sorted(sess._mxu_thr.keys()),
        "last_regime": getattr(sess, "_last_regime_key", None),
    }
    arrays = {f"sim/{k}": np.asarray(v)
              for k, v in _sim_arrays(sess.sim).items()}
    for name, val in zip(_CAMERA_FIELDS, sess.camera):
        arrays[f"camera/{name}"] = np.asarray(val)
    # the transfer function is runtime-mutable state since TF steering
    # (apply_tf_steering): without it a resumed session would silently
    # render with the constructor TF
    for name, val in zip(type(sess.tf)._fields, sess.tf):
        arrays[f"tf/{name}"] = np.asarray(val)
    for regime, thr in sess._mxu_thr.items():
        # join EVERY key part: hybrid-mode keys are ('hybrid', axis, sign)
        # and both signs of an axis must keep distinct tags
        tag = "thr/" + "_".join(str(p) for p in regime)
        for field in ThresholdState._fields:
            arrays[f"{tag}/{field}"] = np.asarray(getattr(thr, field))
    with open(path, "wb") as f:       # stream; no in-memory zip copy
        np.savez(f, __header__=np.frombuffer(
            json.dumps(header).encode(), np.uint8), **arrays)


def load_session(sess: "InSituSession", path: str) -> None:
    """Restore a checkpoint into a session built from the SAME config
    (grid shapes, sim kind, mesh size must match — loudly checked)."""
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.ops.supersegments import ThresholdState

    with np.load(path) as z:
        header = json.loads(bytes(z["__header__"]).decode())
        if header["version"] != _VERSION:
            raise ValueError(f"checkpoint version {header['version']} != "
                             f"{_VERSION}")
        if header["sim_kind"] != sess.sim.kind:
            raise ValueError(
                f"checkpoint sim kind {header['sim_kind']!r} does not "
                f"match session {sess.sim.kind!r}")
        if header["mode"] != sess.mode:
            raise ValueError(
                f"checkpoint mode {header['mode']!r} does not match "
                f"session {sess.mode!r}")
        # bit-exact resume needs the same compiled step: engine, adaptive
        # regime and mesh size all change what the resumed run computes
        for key, have in (("engine", sess.engine),
                          ("temporal", bool(getattr(sess, "_temporal",
                                                    False))),
                          ("mesh_devices",
                           int(sess._n_ranks))):
            want = header.get(key)
            if want is not None and want != have:
                raise ValueError(
                    f"checkpoint {key}={want!r} does not match session "
                    f"{have!r} — same config required")
        sim_arrays = {k.split("/", 1)[1]: z[k]
                      for k in z.files if k.startswith("sim/")}
        want = _sim_arrays(sess.sim)
        for k, cur in want.items():
            if k not in sim_arrays:
                raise ValueError(f"checkpoint missing sim array {k!r}")
            if tuple(sim_arrays[k].shape) != tuple(np.shape(cur)):
                raise ValueError(
                    f"sim array {k!r} shape {sim_arrays[k].shape} does "
                    f"not match session {np.shape(cur)} — same config "
                    "required")
        _restore_sim(sess.sim, sim_arrays)
        sess.camera = Camera(*(jnp.asarray(z[f"camera/{n}"])
                               for n in _CAMERA_FIELDS))
        tf_fields = type(sess.tf)._fields
        present = [n for n in tf_fields if f"tf/{n}" in z.files]
        if present and len(present) != len(tf_fields):
            # some-but-not-all keys = field-set mismatch (e.g. the TF type
            # evolved without a version bump) — silently falling back to
            # the constructor TF would be exactly the wrong-TF resume this
            # block exists to prevent
            raise ValueError(
                f"checkpoint tf/ keys {present} do not match the session "
                f"TransferFunction fields {list(tf_fields)} — checkpoint "
                "and session versions differ")
        if present:
            new_tf = type(sess.tf)(*(jnp.asarray(z[f"tf/{n}"])
                                     for n in tf_fields))
            changed = any(
                not np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(new_tf, sess.tf))
            sess.tf = new_tf
            if changed:
                # the restored TF differs from the constructor's: rebuild
                # the compiled steps exactly like live TF steering does
                # (AttributeError here is the loud failure the module
                # promises — a session type without _build_steps cannot
                # silently keep steps that baked the old TF in)
                sess._build_steps()
        # (older checkpoints have no tf/ keys: constructor TF applies)
        sess.frame_index = int(header["frame_index"])
        sess.orbit_rate = header["orbit_rate"]
        sess._mxu_thr = {}
        for regime in header.get("thr_regimes", []):
            regime = tuple(regime)
            tag = "thr/" + "_".join(str(p) for p in regime)
            state = ThresholdState(
                *(jnp.asarray(z[f"{tag}/{f}"])
                  for f in ThresholdState._fields))
            expect = _thr_shape(sess, regime)
            if expect is not None and tuple(state.thr.shape) != expect:
                raise ValueError(
                    f"threshold state for regime {regime} has shape "
                    f"{tuple(state.thr.shape)}, session expects {expect} "
                    "— same slicer/mesh config required")
            sess._mxu_thr[regime] = state
        # restore the regime tracker VERBATIM: _enter_regime drops the
        # entered regime's carried state on a regime CHANGE, and the
        # resumed run must make the same drop/keep decisions as the
        # uninterrupted one
        last = header.get("last_regime")
        if last is not None:
            sess._last_regime_key = tuple(last)
        elif hasattr(sess, "_last_regime_key"):
            del sess._last_regime_key


def _thr_shape(sess, regime):
    """Expected [n*nj, ni] of a regime's rank-stacked threshold maps under
    this session's config (None for sessions without an mxu VDI pass).
    Hybrid-mode keys are ('hybrid', axis, sign); vdi keys (axis, sign)."""
    if sess.engine != "mxu" or sess.mode not in ("vdi", "hybrid"):
        return None
    axis_sign = tuple(regime[1:]) if regime and regime[0] == "hybrid" \
        else tuple(regime)
    # TOTAL rank count — on a hierarchical (hosts, ranks) mesh the
    # threshold maps stack over the flat axis view (docs/MULTIHOST.md)
    n = sess._n_ranks
    spec = sess._slicer.make_spec(sess.camera, sess.sim.field.shape,
                                  sess.cfg.slicer, axis_sign=axis_sign,
                                  multiple_of=n)
    return (n * spec.nj, spec.ni)


def checkpoint_sink(directory: str, every: int = 50):
    """Session sink: checkpoint every N frames (composable with the other
    sinks, ≅ the reference's periodic VDIDataIO dumps but for the whole
    session). The sink needs the session itself, so bind it:
    ``sess.sinks.append(checkpoint_sink(d).bind(sess))``.

    The file is named by the session's CURRENT frame index (the state the
    checkpoint actually contains) — with the session's one-frame dispatch
    pipelining that is ~2 ahead of the payload index the sink fires on,
    so do not pair ``ckpt_N.npz`` with a same-index VDI dump."""
    import os

    class _Sink:
        def __init__(self):
            self.sess = None

        def bind(self, sess):
            self.sess = sess
            return self

        def __call__(self, index: int, payload: dict) -> None:
            if self.sess is not None and every and index % every == 0:
                os.makedirs(directory, exist_ok=True)
                # zero-padded so lexicographic order == frame order
                save_session(self.sess, os.path.join(
                    directory, f"ckpt_{self.sess.frame_index:05d}.npz"))

    return _Sink()
