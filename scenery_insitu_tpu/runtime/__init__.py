from scenery_insitu_tpu.runtime.timers import Timers  # noqa: F401
