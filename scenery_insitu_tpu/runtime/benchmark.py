"""Render benchmark harness and camera-path tools.

≅ the reference's benchmark machinery:
- multi-view fps sweep: 9 camera angles per dataset, fps stats cleared and
  sampled per window, written as ``avg;min;max;stddev;n`` CSV rows plus a
  screenshot per view (reference VolumeFromFileExample.kt:765-795,
  355-385; DistributedVolumes.kt singleGPUBenchmarks :527-623).
- camera flythrough recorder: interpolate a keyframed path and render every
  frame to disk / a video sink (VolumeFromFileExample.kt:631-745).

The sweep drives whichever render callable it is given, so it benchmarks
either engine (gather or MXU slice-march) and either output (plain image or
VDI) with the same stats path. CLI front end: benchmarks/render_bench.py.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from scenery_insitu_tpu.core.camera import Camera, orbit
from scenery_insitu_tpu.runtime.timers import PhaseStats


def benchmark_views(render: Callable[[Camera], object], cam0: Camera,
                    num_views: int = 9, frames: int = 10, warmup: int = 1,
                    pitch: float = 0.0,
                    screenshot_dir: Optional[str] = None,
                    to_image: Optional[Callable[[object], np.ndarray]] = None,
                    ) -> List[Tuple[float, PhaseStats]]:
    """Sweep ``num_views`` orbit angles; per view run ``frames`` timed
    renders (after ``warmup`` untimed ones) and collect fps stats.

    Returns [(yaw_radians, PhaseStats-of-seconds-per-frame), ...]. When
    ``screenshot_dir`` is set, saves one PNG per view (≅ the reference's
    per-view screenshot, VolumeFromFileExample.kt:793); ``to_image``
    converts the render output to an f32[4, H, W] array for saving
    (defaults to identity).
    """
    import jax

    results = []
    for view in range(num_views):
        yaw = 2.0 * np.pi * view / num_views
        cam = orbit(cam0, np.float32(yaw), np.float32(pitch))
        for _ in range(warmup):
            jax.block_until_ready(render(cam))
        stats = PhaseStats()
        out = None
        for _ in range(frames):
            t0 = time.perf_counter()
            out = render(cam)
            jax.block_until_ready(out)
            stats.add(time.perf_counter() - t0)
        results.append((float(yaw), stats))
        if screenshot_dir is not None:
            from scenery_insitu_tpu.utils.image import save_png
            os.makedirs(screenshot_dir, exist_ok=True)
            img = np.asarray(to_image(out) if to_image else out)
            save_png(os.path.join(screenshot_dir, f"view{view:02d}.png"), img)
    return results


def fps_csv(results: Sequence[Tuple[float, PhaseStats]]) -> str:
    """Render sweep results as the reference's fps CSV: one
    ``yaw_deg;avg;min;max;stddev;n`` row per view, fps units (the stats are
    inverted from seconds-per-frame; min fps = 1/max spf)."""
    lines = ["yaw_deg;avg_fps;min_fps;max_fps;stddev_spf;n"]
    for yaw, st in results:
        inv = lambda s: (1.0 / s) if s > 0 else 0.0
        lines.append(f"{np.degrees(yaw):.1f};{inv(st.avg):.3f};"
                     f"{inv(st.vmax):.3f};{inv(st.vmin):.3f};"
                     f"{st.stddev:.6f};{st.n}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------- flythrough


def interpolate_path(keyframes: Sequence[Camera], frames_per_segment: int,
                     smooth: bool = True) -> List[Camera]:
    """Interpolate a camera path through pose keyframes (≅ the flythrough
    recorder's recorded-pose playback, VolumeFromFileExample.kt:631-745).
    Eye/target/up are interpolated per segment; ``smooth`` applies
    smoothstep easing inside each segment."""
    if len(keyframes) < 2:
        return list(keyframes)
    out: List[Camera] = []
    for a, b in zip(keyframes[:-1], keyframes[1:]):
        for f in range(frames_per_segment):
            t = f / frames_per_segment
            if smooth:
                t = t * t * (3.0 - 2.0 * t)
            lerp = lambda x, y: np.asarray(x) * (1 - t) + np.asarray(y) * t
            out.append(Camera.create(
                lerp(a.eye, b.eye), lerp(a.target, b.target),
                lerp(a.up, b.up)
            )._replace(fov_y=a.fov_y * (1 - t) + b.fov_y * t,
                       near=a.near, far=a.far))
    out.append(keyframes[-1])
    return out


def record_flythrough(render: Callable[[Camera], object],
                      path: Sequence[Camera], out_dir: str,
                      to_image: Optional[Callable[[object], np.ndarray]] = None,
                      video_sink=None) -> int:
    """Render every camera of ``path``; save frame PNGs to ``out_dir`` and
    optionally feed a ``runtime.streaming.video_sink``. Returns the number
    of frames rendered."""
    from scenery_insitu_tpu.utils.image import save_png

    os.makedirs(out_dir, exist_ok=True)
    for i, cam in enumerate(path):
        out = render(cam)
        img = np.asarray(to_image(out) if to_image else out)
        save_png(os.path.join(out_dir, f"fly{i:05d}.png"), img)
        if video_sink is not None:
            video_sink(i, {"image": img})
    return len(path)
