"""SITPU-TRACE — host-sync and retrace hazards inside jitted/scanned code.

The pipelined overlap structure (ring exchange, tile waves, frame scan)
only holds while the compiled step stays on device: one stray ``float(x)``
on a traced value forces a device->host transfer mid-step (serializing the
very collectives PRs 4/8 overlap), a Python ``if`` on a traced boolean is
a trace-time error (or, via weak typing, a silent per-call retrace), and a
``jnp.array`` literal built inside a ``lax.scan`` body re-materializes a
constant every iteration. These never fail loudly on the CPU parity tests
— interpret mode and tiny grids hide them — so they are exactly the class
of bug a static pass must hold the line on.

Mechanics (per module, no execution):

1. **traced contexts** — functions decorated with / passed to ``jit``,
   ``shard_map``, ``vmap``/``pmap``/``grad``, or used as ``lax.scan`` /
   ``cond`` / ``while_loop`` / ``fori_loop`` bodies; plus their nested
   defs and (fixpoint) same-module functions they call. ``lax.scan``
   bodies are additionally tagged for the per-step-literal rule.
2. **a tiny dataflow** inside each traced function: parameters are
   traced unless they are statically-shaped configuration — name
   matches the project's config idiom (``*_cfg``, ``spec``, ``mesh``,
   ``axis``...), scalar/str annotation, or a literal default. ``x.shape``
   / ``.dtype`` / ``.ndim`` /`` .size`` of a traced value is static
   (shapes are trace-time constants); ``is``/``is not None`` tests are
   static (pytree structure). Everything derived from a traced value —
   arithmetic, indexing, ``jnp.*`` results — is traced.
3. **hazards** flagged on traced values: ``float()``/``int()``/
   ``bool()`` concretization, ``np.asarray``/``np.array`` host pulls,
   ``.item()``/``.tolist()``, Python ``if``/``while``/ternary/``assert``
   control flow; in scan bodies, ``jnp.array``/``jnp.asarray`` calls on
   constants-only arguments; and ``jit(..., static_argnames=...)`` naming
   parameters the jitted function does not have.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from scenery_insitu_tpu.tools.lint.core import (Diagnostic, SourceFile,
                                                dotted_name, func_params)

CODE = "SITPU-TRACE"

# jax transforms that trace their function argument
_TRACERS = {"jit", "shard_map", "vmap", "pmap", "grad", "value_and_grad",
            "checkpoint", "remat", "custom_vjp", "custom_jvp"}
_BODY_TAKERS = {"scan": 0, "cond": None, "while_loop": None,
                "fori_loop": 2, "map": 0, "associative_scan": 0}

# parameters that are static configuration by project convention
_STATIC_NAME_RE = re.compile(
    r"(^|_)(cfg|config|spec|specs|mesh|bmap|tf|axis|axis_name|slicer|engine|"
    r"mode|kind|wire|exchange|schedule|fold|background|colormap|"
    r"interpret|temporal|dtype|name|log|rec|recorder|key|sim)$"
    r"|^(self|n|t|k|w|h|d)$")
_STATIC_ANNOT = {"int", "float", "bool", "str", "bytes", "tuple", "list",
                 "dict"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding",
                 "_fields"}
_CONCRETIZERS = {"float", "int", "bool", "complex"}
_NUMPY_BASES = {"np", "numpy", "onp", "_np"}


def _is_static_param(arg: ast.arg) -> bool:
    if _STATIC_NAME_RE.search(arg.arg):
        return True
    ann = arg.annotation
    if ann is not None:
        names = {n.id for n in ast.walk(ann) if isinstance(n, ast.Name)}
        names |= {n.attr for n in ast.walk(ann)
                  if isinstance(n, ast.Attribute)}
        # Optional[int], Tuple[int, int], str, ... — but jnp.ndarray /
        # Camera / VDI pytrees stay traced
        if names and names <= (_STATIC_ANNOT | {"Optional", "Tuple",
                                                "List", "Dict"}):
            return True
    return False


def _static_params(fn) -> Set[str]:
    a = fn.args
    out = set()
    all_args = a.posonlyargs + a.args + a.kwonlyargs
    # literal defaults (trailing-aligned for positional args)
    defaults = {}
    pos = a.posonlyargs + a.args
    for p, dflt in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        defaults[p.arg] = dflt
    for p, dflt in zip(a.kwonlyargs, a.kw_defaults):
        if dflt is not None:
            defaults[p.arg] = dflt
    for p in all_args:
        d = defaults.get(p.arg)
        literal_default = isinstance(d, ast.Constant) and not (
            d.value is None)
        if _is_static_param(p) or literal_default:
            out.add(p.arg)
    return out


# ------------------------------------------------------- context discovery

class _FnIndex:
    """All function defs in a module, with name -> defs map (lexically
    scoped resolution is overkill; bare-name match is right for this
    codebase's flat modules)."""

    def __init__(self, tree: ast.Module):
        self.defs: List = []
        self.by_name: Dict[str, List] = {}
        self.parent: Dict[ast.AST, Optional[ast.AST]] = {}
        self._walk(tree, None)

    def _walk(self, node, parent_fn):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.append(child)
                self.by_name.setdefault(child.name, []).append(child)
                self.parent[child] = parent_fn
                self._walk(child, child)
            else:
                self._walk(child, parent_fn)


def _resolve_fn_arg(expr, idx: _FnIndex):
    """The function a call argument names: bare Name, or
    functools.partial(fn, ...)'s first arg."""
    if isinstance(expr, ast.Name):
        defs = idx.by_name.get(expr.id)
        return defs[-1] if defs else None
    if isinstance(expr, ast.Call):
        dn = dotted_name(expr.func)
        if dn.endswith("partial") and expr.args:
            return _resolve_fn_arg(expr.args[0], idx)
    return None


def _decorated_traced(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        for n in ast.walk(target if not isinstance(dec, ast.Call) else dec):
            if isinstance(n, ast.Attribute) and n.attr in _TRACERS:
                return True
            if isinstance(n, ast.Name) and n.id in _TRACERS:
                return True
    return False


def find_traced(tree: ast.Module, idx: _FnIndex
                ) -> Tuple[Set[ast.AST], Set[ast.AST], Dict[ast.AST,
                                                            Set[str]]]:
    """(traced defs, scan-body defs, per-def jit static_argnames) for one
    module — the traced ROOTS only; argument-aware closure over
    same-module calls happens in :func:`check` (a helper called from a
    traced function is only traced if some call site actually passes it
    a traced value — ``step_pallas`` consulting its host-side candidate
    walkers on static shapes must not drag them in)."""
    traced: Set[ast.AST] = set()
    scan_bodies: Set[ast.AST] = set()
    static_names: Dict[ast.AST, Set[str]] = {}
    for fn in idx.defs:
        if _decorated_traced(fn):
            traced.add(fn)
        for dec in fn.decorator_list:
            names = _jit_static_argnames(dec)
            if names:
                static_names.setdefault(fn, set()).update(names)
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        dn = dotted_name(call.func)
        leaf = dn.rsplit(".", 1)[-1] if dn else ""
        if leaf in _TRACERS and call.args:
            t = _resolve_fn_arg(call.args[0], idx)
            if t is not None:
                traced.add(t)
                names = _jit_static_argnames(call)
                if names:
                    static_names.setdefault(t, set()).update(names)
        if leaf in _BODY_TAKERS:
            argpos = _BODY_TAKERS[leaf]
            cands = (call.args if argpos is None else
                     call.args[argpos:argpos + 1]
                     if len(call.args) > (argpos or 0) else [])
            for a in cands:
                t = _resolve_fn_arg(a, idx)
                if t is not None:
                    traced.add(t)
                    if leaf == "scan":
                        scan_bodies.add(t)
    # nested defs inherit their parent's tracedness
    changed = True
    while changed:
        changed = False
        for fn in idx.defs:
            if fn not in traced and idx.parent.get(fn) in traced:
                traced.add(fn)
                changed = True
    return traced, scan_bodies, static_names


def _jit_static_argnames(call_or_dec) -> List[str]:
    """static_argnames of a ``jit(...)`` / ``partial(jit, ...)`` call."""
    if not isinstance(call_or_dec, ast.Call):
        return []
    dn = dotted_name(call_or_dec.func)
    leaf = dn.rsplit(".", 1)[-1] if dn else ""
    if leaf == "partial":
        if not (call_or_dec.args
                and dotted_name(call_or_dec.args[0]).endswith("jit")):
            return []
    elif leaf != "jit":
        return []
    for k in call_or_dec.keywords:
        if k.arg == "static_argnames":
            return _literal_strs(k.value) or []
    return []


# ------------------------------------------------------------ the dataflow

class _Flow(ast.NodeVisitor):
    def __init__(self, src: SourceFile, fn, scan_body: bool,
                 diags: List[Diagnostic],
                 extra_static: Optional[Set[str]] = None,
                 emit: bool = True):
        self.src = src
        self.fn = fn
        self.scan_body = scan_body
        self.diags = diags
        self.emit = emit
        self.traced_calls: Set[str] = set()   # same-module callees fed a
        #                                       traced argument
        static = _static_params(fn) | (extra_static or set())
        self.traced: Set[str] = {p for p in func_params(fn)
                                 if p not in static}

    def flag(self, node, msg):
        if self.emit:
            self.diags.append(Diagnostic(self.src.path, node.lineno, CODE,
                                         msg, self.fn.name))

    # ------------------------------------------------------- tracedness
    def is_traced(self, e) -> bool:
        if e is None or isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Name):
            return e.id in self.traced
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self.is_traced(e.value)
        if isinstance(e, ast.Subscript):
            return self.is_traced(e.value)
        if isinstance(e, ast.Call):
            # a method call on a traced array (x.max(), x.astype(...))
            # yields a traced value; .item()/.tolist() yield host values
            # (and are flagged as hazards in visit_Call)
            if isinstance(e.func, ast.Attribute) \
                    and e.func.attr not in ("item", "tolist") \
                    and self.is_traced(e.func.value):
                return True
            dn = dotted_name(e.func)
            root = dn.split(".", 1)[0] if dn else ""
            leaf = dn.rsplit(".", 1)[-1] if dn else ""
            if leaf in _CONCRETIZERS or leaf in ("len", "range", "repr",
                                                 "str"):
                return False
            if root in ("jnp", "lax") or dn.startswith(
                    ("jax.lax.", "jax.numpy.", "jax.nn.", "jax.random.",
                     "jax.scipy.")):
                # rank/shape queries are trace-time constants even on
                # traced arrays; everything else these namespaces return
                # is a device value
                return leaf not in ("ndim", "shape", "size",
                                    "result_type", "isdtype")
            # other jax.* (default_backend, ShapeDtypeStruct, tree_util,
            # jit...) are host utilities — fall through to argument-based
            # propagation
            args = list(e.args) + [k.value for k in e.keywords]
            return any(self.is_traced(a) for a in args)
        if isinstance(e, ast.BinOp):
            return self.is_traced(e.left) or self.is_traced(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.is_traced(e.operand)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False               # pytree-structure check
            return (self.is_traced(e.left)
                    or any(self.is_traced(c) for c in e.comparators))
        if isinstance(e, ast.BoolOp):
            return any(self.is_traced(v) for v in e.values)
        if isinstance(e, ast.IfExp):
            return self.is_traced(e.body) or self.is_traced(e.orelse)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self.is_traced(v) for v in e.elts)
        if isinstance(e, ast.Starred):
            return self.is_traced(e.value)
        return False

    def _bind(self, target, traced: bool):
        if isinstance(target, ast.Name):
            if traced:
                self.traced.add(target.id)
            else:
                self.traced.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self._bind(t, traced)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, traced)

    # ------------------------------------------------------- statements
    def visit_Assign(self, node):
        self.generic_visit(node)
        t = self.is_traced(node.value)
        for target in node.targets:
            self._bind(target, t)

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        if self.is_traced(node.value):
            self._bind(node.target, True)

    def visit_AnnAssign(self, node):
        self.generic_visit(node)
        if node.value is not None:
            self._bind(node.target, self.is_traced(node.value))

    def visit_For(self, node):
        self._bind(node.target, self.is_traced(node.iter))
        self.generic_visit(node)

    def visit_If(self, node):
        if self.is_traced(node.test):
            self.flag(node.test, "Python `if` on a traced value — "
                      "trace-time error or silent per-call retrace; use "
                      "lax.cond / jnp.where")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.is_traced(node.test):
            self.flag(node.test, "Python `while` on a traced value — use "
                      "lax.while_loop")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self.is_traced(node.test):
            self.flag(node.test, "assert on a traced value — trace-time "
                      "error; use checkify or a host callback")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        if self.is_traced(node.test):
            self.flag(node.test, "ternary on a traced condition — use "
                      "jnp.where / lax.select")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node is not self.fn:
            return                 # nested defs get their own _Flow pass
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        self.generic_visit(node)
        dn = dotted_name(node.func)
        leaf = dn.rsplit(".", 1)[-1] if dn else ""
        root = dn.split(".", 1)[0] if dn else ""
        args = list(node.args) + [k.value for k in node.keywords]
        any_traced = any(self.is_traced(a) for a in args)
        if any_traced and isinstance(node.func, ast.Name):
            self.traced_calls.add(node.func.id)
        if isinstance(node.func, ast.Name) and leaf in _CONCRETIZERS \
                and any_traced:
            self.flag(node, f"{leaf}() on a traced value forces a "
                      f"device->host sync inside compiled code")
        if root in _NUMPY_BASES and leaf in ("asarray", "array") \
                and any_traced:
            self.flag(node, f"{dn}() pulls a traced value to host "
                      f"memory inside compiled code — use jnp")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") \
                and self.is_traced(node.func.value):
            self.flag(node, f".{node.func.attr}() on a traced value is a "
                      f"host sync inside compiled code")
        if self.scan_body and root == "jnp" \
                and leaf in ("array", "asarray") and args \
                and not any_traced \
                and all(_is_constish(a) for a in args):
            self.flag(node, "jnp." + leaf + " literal constructed inside "
                      "a lax.scan body — hoist it out of the scanned "
                      "step (per-iteration constant re-materialization)")


def _is_constish(e) -> bool:
    return all(isinstance(n, (ast.Constant, ast.Tuple, ast.List,
                              ast.UnaryOp, ast.USub, ast.UAdd,
                              ast.operator, ast.unaryop, ast.Load))
               for n in ast.walk(e))


# -------------------------------------------------- static_argnames checks

def _check_static_argnames(src: SourceFile, idx: _FnIndex
                           ) -> List[Diagnostic]:
    diags = []
    for call in ast.walk(src.tree):
        if not isinstance(call, ast.Call):
            continue
        dn = dotted_name(call.func)
        if not dn or dn.rsplit(".", 1)[-1] not in ("jit", "partial"):
            continue
        is_partial = dn.rsplit(".", 1)[-1] == "partial"
        if is_partial:
            # functools.partial(jax.jit, static_argnames=...) decorator
            if not (call.args and dotted_name(call.args[0]).endswith("jit")):
                continue
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        if "static_argnames" not in kw:
            continue
        names = _literal_strs(kw["static_argnames"])
        if names is None:
            continue
        target = None
        if not is_partial and call.args:
            target = _resolve_fn_arg(call.args[0], idx)
        if is_partial:
            for fn in idx.defs:
                for dec in fn.decorator_list:
                    if dec is call:
                        target = fn
        if target is None:
            continue
        missing = [n for n in names if n not in func_params(target)]
        if missing:
            diags.append(Diagnostic(
                src.path, call.lineno, CODE,
                f"static_argnames {missing} are not parameters of "
                f"{target.name}() — jit will raise (or silently trace "
                f"them) at call time", target.name))
    return diags


def _literal_strs(e) -> Optional[List[str]]:
    if isinstance(e, ast.Constant) and isinstance(e.value, str):
        return [e.value]
    if isinstance(e, (ast.Tuple, ast.List)):
        out = []
        for v in e.elts:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.append(v.value)
            else:
                return None
        return out
    return None


def check(sources: List[SourceFile]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in sources:
        idx = _FnIndex(src.tree)
        traced, scan_bodies, static_names = find_traced(src.tree, idx)
        # argument-aware closure: a same-module top-level helper joins the
        # traced set only when some traced function passes it a traced
        # value (host-side helpers consulted on static shapes stay host)
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                flow = _Flow(src, fn, fn in scan_bodies, diags,
                             static_names.get(fn), emit=False)
                flow.visit(fn)
                for name in flow.traced_calls:
                    for t in idx.by_name.get(name, []):
                        if idx.parent.get(t) is None and t not in traced:
                            traced.add(t)
                            changed = True
        for fn in idx.defs:
            if fn in traced:
                _Flow(src, fn, fn in scan_bodies, diags,
                      static_names.get(fn)).visit(fn)
        diags.extend(_check_static_argnames(src, idx))
    return diags
