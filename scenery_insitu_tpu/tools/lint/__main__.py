"""CLI: ``python -m scenery_insitu_tpu.tools.lint [options] [paths...]``

Exit 0 when every finding is baselined (tools/lint/baseline.json) or
suppressed inline; exit 1 on NEW findings — the CI gate fails only on
regressions, never on the accepted debt (which is listed, with reasons,
in the baseline).

Options:
  --baseline PATH    baseline file (default: tools/lint/baseline.json
                     next to this package)
  --no-baseline      ignore the baseline (show everything)
  --write-baseline   rewrite the baseline from current findings, keeping
                     existing reasons and stamping new entries with
                     "TODO: justify or fix" (then exit 1 until edited)
  --report PATH      write the full JSON report (diagnostics + baseline
                     accounting) — uploaded as a CI artifact
  --fail-on-stale    exit 1 when baseline entries no longer match any
                     finding (CI uses this: paid-off debt must be
                     PRUNED from the baseline, not linger as dead rows
                     that could silently re-absorb a regression)
  paths              files/dirs to scan (default: the package minus
                     tools/, bench.py, benchmarks/)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from scenery_insitu_tpu.tools.lint.core import (Baseline, find_repo_root,
                                                load_sources_with_diags)
from scenery_insitu_tpu.tools.lint.runner import (collect_paths,
                                                  default_baseline_path,
                                                  run_checks)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="sitpu-lint", description=__doc__)
    ap.add_argument("--baseline", default=default_baseline_path())
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--report", default=None)
    ap.add_argument("--fail-on-stale", action="store_true")
    ap.add_argument("paths", nargs="*")
    args = ap.parse_args(argv)

    root = find_repo_root()
    srcs, parse_diags = load_sources_with_diags(
        root, collect_paths(root, args.paths))
    diags = parse_diags + run_checks(srcs)

    if args.write_baseline:
        old = Baseline.load(args.baseline) if os.path.exists(args.baseline) \
            else Baseline([])
        reasons = {(e["code"], e["path"], e["message"]): e["reason"]
                   for e in old.entries}
        entries = [Baseline.entry_for(
            d, reasons.get(d.key(), "TODO: justify or fix"))
            for d in diags]
        Baseline(entries).save(args.baseline)
        todo = sum(1 for e in entries
                   if e["reason"] == "TODO: justify or fix")
        print(f"wrote {len(entries)} baseline entries to {args.baseline}"
              f" ({todo} need a reason)")
        return 1 if todo else 0

    bl = Baseline([]) if args.no_baseline else Baseline.load(args.baseline)
    new, accepted, stale = bl.split(diags)

    for d in new:
        print(d.render())
    if accepted:
        print(f"# {len(accepted)} finding(s) accepted by baseline "
              f"({os.path.relpath(args.baseline, root)})")
    for e in stale:
        print(f"# stale baseline entry (no longer matches): "
              f"{e['code']} {e['path']} — consider removing")

    if args.report:
        report = {
            "tool": "sitpu-lint",
            "counts": {"new": len(new), "baselined": len(accepted),
                       "stale_baseline": len(stale),
                       "files_scanned": len(srcs)},
            "new": [d.__dict__ for d in new],
            "baselined": [d.__dict__ for d in accepted],
            "stale_baseline": stale,
        }
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")

    if new:
        print(f"sitpu-lint: {len(new)} new finding(s) "
              f"({len(accepted)} baselined)")
        return 1
    if stale and args.fail_on_stale:
        print(f"sitpu-lint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} — prune them "
              f"(--fail-on-stale)")
        return 1
    print(f"sitpu-lint: clean ({len(accepted)} baselined finding(s), "
          f"{len(srcs)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
