"""SITPU-THREAD — CompositeConfig knob threading through the distributed
step builders.

PRs 4, 5, 6 and 8 each added a ``CompositeConfig`` knob (``exchange``,
``wire``, ``k_budget``, ``schedule``/``wave_tiles``) and each had to
hand-audit that EVERY distributed step builder and the session plumbing
forwarded it — a mechanical invariant that rots silently: a builder that
drops a knob still renders, it just quietly ignores the configuration
(exactly the reference's three-tier config failure mode the config module
docstring complains about).

The knob matrix is DERIVED from ``config.py``'s ``CompositeConfig``
dataclass fields (minus the composite-internal fields that the composite
fold itself consumes — ``max_output_supersegments``, ``adaptive``,
``adaptive_iters``, ``backend``, ``k_budget_min``), so a future PR that
adds a field gets enforcement for free: the new knob fails SITPU-THREAD on
every builder until it is threaded (or explicitly baselined where
inapplicable, e.g. the plain-image builders have no per-pixel K working
set for ``ring_slots`` to cap).

Rules, per builder (top-level ``distributed_*step*`` / ``_build_mxu_step``
in ``parallel/pipeline.py``):

- **whole-object builders** (a ``comp_cfg`` parameter): the config object
  must be forwarded — appear as a direct argument of some call in the
  body (including nested defs). Rebuilding it (``dataclasses.replace`` /
  a fresh ``CompositeConfig(...)``) inside such a builder is flagged:
  that is how whole-object threading silently drops knobs.
- **explicit-knob builders** (no ``comp_cfg``): every knob in the matrix
  must be accepted as a parameter of that exact name AND forwarded (used
  as a call argument somewhere in the body).
- **session plumbing** (``runtime/session.py``): every call to a
  pipeline builder must bind ``comp_cfg`` (positionally or by keyword)
  for whole-object builders, and pass each accepted knob by name for
  explicit-knob builders.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from scenery_insitu_tpu.tools.lint.core import (Diagnostic, SourceFile,
                                                func_params, iter_calls)

CODE = "SITPU-THREAD"

BUILDER_RE = re.compile(r"^(distributed_.*step.*|_build_mxu_step)$")
COMPOSITE_CLASS = "CompositeConfig"
COMP_PARAM = "comp_cfg"
# the scale-out plane (docs/MULTIHOST.md): every distributed step builder
# must accept AND forward the TopologyConfig — a builder that drops it
# silently renders the flat single-domain composite on a hierarchical
# mesh, exactly the class of rot this checker exists for. Enforced for
# whole-object and explicit-knob builders alike (topology is its own
# config object, not a CompositeConfig field), and the session must bind
# it at every builder call.
TOPO_PARAM = "topology"

# consumed inside the composite fold itself (ops/composite.py), not
# threaded through builder signatures; everything else in CompositeConfig
# is a knob by default — new fields are enforced automatically
NON_THREADED_FIELDS = {"max_output_supersegments", "adaptive",
                       "adaptive_iters", "backend", "k_budget_min"}


def derive_knobs(config_src: SourceFile) -> List[str]:
    """CompositeConfig dataclass fields -> the threaded knob matrix."""
    for node in config_src.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == COMPOSITE_CLASS:
            fields = [s.target.id for s in node.body
                      if isinstance(s, ast.AnnAssign)
                      and isinstance(s.target, ast.Name)]
            knobs = [f for f in fields if f not in NON_THREADED_FIELDS]
            if knobs:
                return knobs
            raise ValueError(
                f"{COMPOSITE_CLASS} in {config_src.path} has no threaded "
                f"knob fields — NON_THREADED_FIELDS is stale")
    raise ValueError(f"no {COMPOSITE_CLASS} dataclass in {config_src.path}")


def _name_used_as_call_arg(fn: ast.AST, name: str) -> bool:
    """Is ``name`` forwarded — a bare-Name argument (positional, keyword
    value, or *args) of any call inside ``fn`` (nested defs included)?"""
    for c in iter_calls(fn):
        for a in c.args:
            if isinstance(a, ast.Starred):
                a = a.value
            if isinstance(a, ast.Name) and a.id == name:
                return True
        for kw in c.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id == name:
                return True
    return False


def _builders(pipeline_src: SourceFile) -> List[ast.FunctionDef]:
    return [n for n in pipeline_src.tree.body
            if isinstance(n, ast.FunctionDef) and BUILDER_RE.match(n.name)]


def _check_builder(src: SourceFile, fn: ast.FunctionDef,
                   knobs: List[str]) -> List[Diagnostic]:
    diags = []
    params = func_params(fn)
    if TOPO_PARAM not in params:
        diags.append(Diagnostic(
            src.path, fn.lineno, CODE,
            f"does not accept '{TOPO_PARAM}' (TopologyConfig; every "
            f"distributed builder must thread the mesh topology — "
            f"docs/MULTIHOST.md)", fn.name))
    elif not _name_used_as_call_arg(fn, TOPO_PARAM):
        diags.append(Diagnostic(
            src.path, fn.lineno, CODE,
            f"accepts '{TOPO_PARAM}' but never consumes it — the "
            f"hierarchical composite is silently dropped", fn.name))
    if COMP_PARAM in params:
        if not _name_used_as_call_arg(fn, COMP_PARAM):
            diags.append(Diagnostic(
                src.path, fn.lineno, CODE,
                f"accepts {COMP_PARAM} but never forwards it — the whole "
                f"knob matrix ({', '.join(knobs)}) is dropped", fn.name))
        for c in iter_calls(fn):
            callee = c.func
            # a bare CompositeConfig() is the `comp_cfg or
            # CompositeConfig()` default fill — only a RE-construction
            # with explicit fields (or dataclasses.replace on the
            # threaded object) can drop knobs
            rebuilt = (isinstance(callee, ast.Name)
                       and callee.id == COMPOSITE_CLASS
                       and (c.args or c.keywords)) or \
                      (isinstance(callee, ast.Attribute)
                       and callee.attr == "replace"
                       and any(isinstance(a, ast.Name)
                               and a.id == COMP_PARAM for a in c.args))
            if rebuilt:
                diags.append(Diagnostic(
                    src.path, c.lineno, CODE,
                    f"rebuilds {COMPOSITE_CLASS} inside a whole-object "
                    f"builder — knobs not restated here are silently "
                    f"dropped; forward {COMP_PARAM} itself", fn.name))
        return diags
    for knob in knobs:
        if knob not in params:
            diags.append(Diagnostic(
                src.path, fn.lineno, CODE,
                f"does not accept knob '{knob}' "
                f"(CompositeConfig field; explicit-knob builder must take "
                f"the full matrix or baseline the gap)", fn.name))
        elif not _name_used_as_call_arg(fn, knob):
            diags.append(Diagnostic(
                src.path, fn.lineno, CODE,
                f"accepts knob '{knob}' but never forwards it",
                fn.name))
    return diags


def _param_index(fn: ast.FunctionDef, name: str) -> Optional[int]:
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    return pos.index(name) if name in pos else None


def _check_session_calls(session_src: SourceFile,
                         builders: Dict[str, ast.FunctionDef],
                         knobs: List[str],
                         pipeline_path: str) -> List[Diagnostic]:
    diags = []
    for c in iter_calls(session_src.tree):
        f = c.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, "id", "")
        fn = builders.get(name)
        if fn is None:
            continue
        params = func_params(fn)
        kw_names = {k.arg for k in c.keywords if k.arg}
        has_doublestar = any(k.arg is None for k in c.keywords)
        if TOPO_PARAM in params:
            idx = _param_index(fn, TOPO_PARAM)
            bound = (TOPO_PARAM in kw_names or has_doublestar
                     or (idx is not None and len(c.args) > idx))
            if not bound:
                diags.append(Diagnostic(
                    session_src.path, c.lineno, CODE,
                    f"call to {name} does not bind '{TOPO_PARAM}' — the "
                    f"session must thread cfg.topology (a hierarchical "
                    f"mesh would silently composite flat)", "session"))
        if COMP_PARAM in params:
            idx = _param_index(fn, COMP_PARAM)
            bound = (COMP_PARAM in kw_names or has_doublestar
                     or (idx is not None and len(c.args) > idx))
            if not bound:
                diags.append(Diagnostic(
                    session_src.path, c.lineno, CODE,
                    f"call to {name} (defined {pipeline_path}) does not "
                    f"bind {COMP_PARAM} — the session must thread "
                    f"cfg.composite, not the builder default", "session"))
            continue
        for knob in knobs:
            if knob not in params:
                continue            # the builder-side rule owns that gap
            idx = _param_index(fn, knob)
            bound = (knob in kw_names or has_doublestar
                     or (idx is not None and len(c.args) > idx))
            if not bound:
                diags.append(Diagnostic(
                    session_src.path, c.lineno, CODE,
                    f"call to {name} does not forward knob '{knob}' "
                    f"(builder defaults mask cfg.composite.{knob})",
                    "session"))
    return diags


def check(sources: List[SourceFile],
          config_path: str = "scenery_insitu_tpu/config.py",
          pipeline_path: str = "scenery_insitu_tpu/parallel/pipeline.py",
          session_paths: tuple = (
              "scenery_insitu_tpu/runtime/session.py",)) -> List[Diagnostic]:
    by_path = {s.path: s for s in sources}
    config_src = by_path.get(config_path)
    pipeline_src = by_path.get(pipeline_path)
    if config_src is None or pipeline_src is None:
        return []            # custom path sets without the core files
    knobs = derive_knobs(config_src)
    diags: List[Diagnostic] = []
    builders = {}
    for fn in _builders(pipeline_src):
        builders[fn.name] = fn
        diags.extend(_check_builder(pipeline_src, fn, knobs))
    for sp in session_paths:
        if sp in by_path:
            diags.extend(_check_session_calls(by_path[sp], builders, knobs,
                                              pipeline_path))
    return diags
