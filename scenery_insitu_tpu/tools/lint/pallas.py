"""SITPU-PALLAS — the Mosaic kernel contracts, checked instead of recited.

PR 1 and PR 6 state the contract in docstrings (``step_pallas``'s
"auto-pick probes / explicit tz is trusted", the ``*_compile_ok``
families); this enforces the checkable parts at every ``pl.pallas_call``
site:

**P1 — compile probe.** Mosaic acceptance is shape-dependent, so a kernel
entry point must be reachable through a one-time compile probe (the
``*_compile_ok`` pattern: ``.lower(...).compile()`` under try/except,
ledgering the rejection) — otherwise a resource rejection fires inside a
traced frame step where nothing can catch it. Checked as: the top-level
function containing the ``pallas_call`` is itself a probe, or is
referenced from a probe function in the same module.

**P2 — tile-divisibility declared.** A grid of ``shape // tile`` silently
leaves output tiles unwritten when the division floors; every kernel
entry must either guard (``if h % TILE_H: raise``, the explicit-tz
checks) or pad by a computed remainder (``(-h) % TILE_H`` feeding a
pad) — some ``%``-derived handling must be visible in the entry function.

**P3 — SMEM scalar outputs are (1, 1).** Mosaic requires scalar SMEM
blocks shaped ``(1, 1)`` (the occupancy ranges epilogue contract,
sim/pallas_stencil.py): any ``pl.BlockSpec`` carrying
``memory_space=pltpu.SMEM`` with an explicit block shape must have every
dimension literally 1.
"""

from __future__ import annotations

import ast
from typing import List, Set

from scenery_insitu_tpu.tools.lint.core import (Diagnostic, SourceFile,
                                                dotted_name, iter_calls)
from scenery_insitu_tpu.tools.lint.ledger import PROBE_NAME_RE

CODE = "SITPU-PALLAS"


def _pallas_call_sites(tree: ast.Module) -> List[ast.Call]:
    return [c for c in iter_calls(tree)
            if dotted_name(c.func).endswith("pallas_call")]


def _top_level_fn_of(tree: ast.Module, node: ast.AST):
    for top in tree.body:
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and top.lineno <= node.lineno <= (top.end_lineno
                                                  or top.lineno):
            return top
    return None


def _compiles_a_lowering(fn) -> bool:
    """try/except around a ``....compile()`` chain — the probe shape."""
    has_try = any(isinstance(n, ast.Try) for n in ast.walk(fn))
    compiles = any(isinstance(c.func, ast.Attribute)
                   and c.func.attr == "compile" for c in iter_calls(fn))
    return has_try and compiles


def _probe_fns(tree: ast.Module) -> List[ast.FunctionDef]:
    out = []
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
                PROBE_NAME_RE.search(n.name) or _compiles_a_lowering(n)):
            out.append(n)
    return out


def _names_referenced(fn) -> Set[str]:
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _has_mod_guard(fn) -> bool:
    """An explicit divisibility guard or a %-derived padding in ``fn``."""
    def has_mod(e):
        return any(isinstance(n, ast.Mod) for n in ast.walk(e))

    pads = any(dotted_name(c.func).rsplit(".", 1)[-1] in ("pad", "cdiv")
               for c in iter_calls(fn))
    for n in ast.walk(fn):
        if isinstance(n, ast.If) and has_mod(n.test) \
                and any(isinstance(b, ast.Raise) for b in ast.walk(n)):
            return True
        if isinstance(n, ast.Assert) and has_mod(n.test):
            return True
        if isinstance(n, (ast.Assign, ast.AnnAssign)) \
                and n.value is not None and has_mod(n.value) and pads:
            return True
    return False


def _smem_blockspec_diags(src: SourceFile) -> List[Diagnostic]:
    diags = []
    for c in iter_calls(src.tree):
        if not dotted_name(c.func).endswith("BlockSpec"):
            continue
        kw = {k.arg: k.value for k in c.keywords if k.arg}
        ms = kw.get("memory_space")
        if ms is None or "SMEM" not in ast.dump(ms):
            continue
        shape = c.args[0] if c.args else kw.get("block_shape")
        if shape is None:
            continue                    # whole-operand SMEM ref (inputs)
        if isinstance(shape, ast.Tuple):
            ones = all(isinstance(e, ast.Constant) and e.value == 1
                       for e in shape.elts)
            if not ones or len(shape.elts) != 2:
                diags.append(Diagnostic(
                    src.path, c.lineno, CODE,
                    "SMEM scalar block must be shaped (1, 1) — Mosaic "
                    "rejects (or miscompiles) other scalar-output "
                    "shapes (see sim/pallas_stencil.py ranges "
                    "epilogue)"))
    return diags


def check(sources: List[SourceFile]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in sources:
        sites = _pallas_call_sites(src.tree)
        if not sites:
            continue
        probes = _probe_fns(src.tree)
        probed_names: Set[str] = set()
        for p in probes:
            probed_names |= _names_referenced(p)
        probe_fn_names = {p.name for p in probes}
        seen_fns = set()
        for site in sites:
            fn = _top_level_fn_of(src.tree, site)
            if fn is None:
                diags.append(Diagnostic(
                    src.path, site.lineno, CODE,
                    "module-level pallas_call — cannot sit behind a "
                    "compile probe"))
                continue
            if fn.name in seen_fns:
                continue
            seen_fns.add(fn.name)
            if fn.name not in probe_fn_names \
                    and fn.name not in probed_names:
                diags.append(Diagnostic(
                    src.path, site.lineno, CODE,
                    f"pallas_call not behind a Mosaic compile probe: no "
                    f"*_compile_ok probe in {src.path} references "
                    f"{fn.name}() — a shape-dependent Mosaic rejection "
                    f"will fire inside a traced step", fn.name))
            if not _has_mod_guard(fn):
                diags.append(Diagnostic(
                    src.path, site.lineno, CODE,
                    f"{fn.name}() declares no tile-divisibility handling "
                    f"(no %-guard raise/assert and no %-derived "
                    f"padding) — a floored grid division silently "
                    f"leaves output tiles unwritten", fn.name))
        diags.extend(_smem_blockspec_diags(src))
    return diags
