"""sitpu-lint — AST-level invariant checkers for this codebase.

Run ``python -m scenery_insitu_tpu.tools.lint`` (docs/STATIC_ANALYSIS.md).

Four project-specific checkers, each born from a hand-audit a landed PR
had to repeat:

- ``SITPU-LEDGER`` (ledger.py): behavior-changing fallback branches must
  mint ``obs.degrade`` entries (PR 3's completeness invariant).
- ``SITPU-THREAD`` (thread.py): the CompositeConfig knob matrix — derived
  from the dataclass fields — threads through every distributed step
  builder and the session plumbing (the PR 4/5/8 audit).
- ``SITPU-TRACE`` (trace.py): host-sync / retrace hazards inside
  jitted/scanned code (protects the pipelined overlap structure).
- ``SITPU-PALLAS`` (pallas.py): every ``pallas_call`` sits behind a
  Mosaic compile probe, declares divisibility handling, shapes SMEM
  scalar outputs (1, 1) (the PR 1/6 kernel contracts).

Pure stdlib ``ast`` — no jax, no execution of the code under analysis.
"""

from scenery_insitu_tpu.tools.lint.core import (Baseline,  # noqa: F401
                                                Diagnostic, SourceFile,
                                                default_scan_paths,
                                                find_repo_root,
                                                load_sources)
from scenery_insitu_tpu.tools.lint.runner import (run_checks,  # noqa: F401
                                                  run_lint)

__all__ = ["Baseline", "Diagnostic", "SourceFile", "default_scan_paths",
           "find_repo_root", "load_sources", "run_checks", "run_lint"]
