"""SITPU-LEDGER — fallback-ledger completeness.

The contract (PR 3, docs/OBSERVABILITY.md): every configured-but-degraded
path mints an ``obs.degrade(component, from, to, reason)`` ledger entry, so
a run can end with an explicit machine-readable list of everything that did
not run as configured. This checker finds the two shapes of silent
degradation the codebase grows:

**R1 — behavior-changing except handlers.** An ``except`` handler that
returns an alternate result, swaps a value the ``try`` body also assigns
(the codec/impl-swap pattern), talks to stdout/stderr/warnings instead of
the ledger, or absorbs a missing optional dependency (``ImportError``)
must call ``obs.degrade`` on that path. Handlers that re-``raise`` are
exempt (nothing degraded — the failure propagates), as are probe
*predicates* (``have_*`` / ``*_compile_ok`` / ``*_supported`` ... returning
constants): the probe reports capability, its CALLER owns the fallback
decision and the ledger entry.

**R2 — unledgered feature-probe consultations.** A function that consults
a probe predicate and is therefore making a capability-dependent choice
must mint a ledger entry on some path — unless the probe itself does
(the ``*_compile_ok`` probes ledger their own rejections) or the caller
is itself a probe predicate (the obligation stays with the ultimate
consumer).

Both rules are heuristics with a principled escape hatch: true positives
that are genuinely fine (e.g. reporting-only error capture that lands in
a bench artifact) belong in ``baseline.json`` with a reason.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from scenery_insitu_tpu.tools.lint.core import (Diagnostic, SourceFile,
                                                call_name, calls_degrade,
                                                iter_calls)

CODE = "SITPU-LEDGER"

# probe predicates: capability reporters whose callers own the fallback
PROBE_NAME_RE = re.compile(
    r"(^_?have_|probe|compile.*ok|_supported$|(^|_)available$|_ok$)")

_IMPORT_ERRORS = {"ImportError", "ModuleNotFoundError"}
_TALK_FUNCS = {"print", "warn", "warning", "error", "info", "debug",
               "print_exc"}


def _handler_exc_names(h: ast.ExceptHandler) -> Set[str]:
    t = h.type
    if t is None:
        return {"BaseException"}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for e in elts:
        if isinstance(e, ast.Attribute):
            out.add(e.attr)
        elif isinstance(e, ast.Name):
            out.add(e.id)
    return out


def _assigned_names(node: ast.AST) -> Set[str]:
    """Simple-Name assignment targets in ``node`` (incl. aug-assign and
    subscript/attribute roots: ``d[k] = ...`` counts as touching ``d``)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        targets = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        for t in targets:
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Name):
                    out.add(leaf.id)
    return out


def _returns_only_constants(node: ast.AST) -> bool:
    rets = [n for n in ast.walk(node) if isinstance(n, ast.Return)]
    return all(r.value is None or isinstance(r.value, ast.Constant)
               for r in rets)


def _is_probe_predicate(fn) -> bool:
    return bool(PROBE_NAME_RE.search(fn.name))


def _talks(node: ast.AST) -> bool:
    return any(call_name(c) in _TALK_FUNCS for c in iter_calls(node))


def _enclosing_fn_of(tree: ast.Module, node: ast.AST):
    """Nearest FunctionDef lexically containing ``node`` (None = module)."""
    best = None
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (n.lineno <= node.lineno
                    and node.lineno <= (n.end_lineno or n.lineno)):
                if best is None or n.lineno > best.lineno:
                    best = n
    return best


def _check_handlers(src: SourceFile) -> List[Diagnostic]:
    diags = []
    tree = src.tree
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        try_assigned = _assigned_names(ast.Module(body=node.body,
                                                  type_ignores=[]))
        for h in node.handlers:
            body = ast.Module(body=h.body, type_ignores=[])
            if any(isinstance(n, ast.Raise) for n in ast.walk(body)):
                continue                      # propagates — not a fallback
            if any(call_name(c) in ("exit", "_exit", "abort")
                   for c in iter_calls(body)):
                continue                      # dies loudly — not a fallback
            if calls_degrade(body):
                continue                      # ledgered
            fn = _enclosing_fn_of(tree, h)
            if fn is not None and _is_probe_predicate(fn) \
                    and _returns_only_constants(body):
                continue                      # probe predicate: caller owns it
            exc = _handler_exc_names(h)
            evidence = []
            if any(isinstance(n, ast.Return) for n in ast.walk(body)):
                evidence.append("returns an alternate result")
            if exc & _IMPORT_ERRORS:
                evidence.append("absorbs a missing optional dependency")
            if _talks(body):
                evidence.append("reports via stdout/warnings only")
            swapped = sorted(_assigned_names(body) & try_assigned)
            if swapped:
                evidence.append(f"swaps {', '.join(swapped)} assigned in "
                                f"the try body")
            if not evidence:
                continue                      # inert handler (cleanup etc.)
            sym = fn.name if fn is not None else "<module>"
            diags.append(Diagnostic(
                src.path, h.lineno, CODE,
                f"except {'/'.join(sorted(exc))} fallback "
                f"({'; '.join(evidence)}) never mints an obs.degrade "
                f"ledger entry", sym))
    return diags


def _functions_with_degrade(sources) -> Set[str]:
    out: Set[str] = set()
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and calls_degrade(node):
                out.add(node.name)
    return out


def _check_probe_consumers(src: SourceFile,
                           degrading_fns: Set[str],
                           known_fns: Set[str]) -> List[Diagnostic]:
    diags = []
    for node in src.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _is_probe_predicate(node):
            continue                          # obligation stays downstream
        if calls_degrade(node):
            continue
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            continue                  # fails loudly instead of degrading
        for c in iter_calls(node):
            name = call_name(c)
            if not name or not PROBE_NAME_RE.search(name):
                continue
            if name in degrading_fns:
                continue                      # the probe ledgers itself
            if name not in known_fns:
                continue                      # external — out of scope
            diags.append(Diagnostic(
                src.path, c.lineno, CODE,
                f"consults feature probe {name}() (which does not ledger "
                f"internally) but mints no obs.degrade entry on any path",
                node.name))
    return diags


def check(sources: List[SourceFile]) -> List[Diagnostic]:
    degrading = _functions_with_degrade(sources)
    known: Set[str] = set()
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                known.add(node.name)
    diags: List[Diagnostic] = []
    for src in sources:
        diags.extend(_check_handlers(src))
        diags.extend(_check_probe_consumers(src, degrading, known))
    return diags


# ------------------------------------------------- registry cross-validation

def discover_degrade_components(sources) -> Dict[str, List[str]]:
    """Statically discovered ledger components: every ``degrade(...)``
    call (or degrade-minting wrapper — ``core.DEGRADE_WRAPPERS``) whose
    component argument is a string literal, mapped to its sites. The
    round-trip test (tests/test_lint.py) holds this equal to
    ``obs.ledger_registry()`` — a new degrade site must register its
    component, and a registry entry must have a live site."""
    from scenery_insitu_tpu.tools.lint.core import DEGRADE_WRAPPERS

    out: Dict[str, List[str]] = {}
    for src in sources:
        for c in iter_calls(src.tree):
            idx = DEGRADE_WRAPPERS.get(call_name(c))
            if idx is None or len(c.args) <= idx:
                continue
            a = c.args[idx]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.setdefault(a.value, []).append(f"{src.path}:{c.lineno}")
    return out
