"""SITPU-COUNTER — counter-catalog completeness.

The contract (PR 17, docs/OBSERVABILITY.md "Device counters"): every
counter name a ``Recorder.count(...)`` site can bump is registered in
``obs.counter_registry()`` with a one-line meaning, so the counter
tables in the docs and the summarizer stay complete. This is the
ledger-registry contract (SITPU-LEDGER) applied to the other half of
the obs surface.

Discovery covers the two shapes counter names take in this codebase:

- ``rec.count("name")`` / ``obs.count("name", n)`` with a **string
  literal** name — the overwhelmingly common case;
- names threaded through ``*_counter``-suffixed **parameters** (the
  shared ring builders in parallel/pipeline.py take ``hop_counter=`` /
  ``build_counter=`` so hier can relabel the same machinery): the
  string **default** of such a parameter and every string **literal
  keyword argument** passed to one are counter names too.

Flagged:

- **C1** — a discovered counter name that is not in
  ``obs.counter_registry()`` (register it or rename to a registered
  one);
- **C2** — a ``.count(x)`` whose name argument is a plain variable that
  is NOT a ``*_counter``-suffixed parameter of the enclosing function
  (an unanalyzable dynamic name defeats the catalog; thread it through
  a ``*_counter`` parameter instead).

The registry's reverse direction (a registry row with no live site)
lives in the round-trip test, not here — this checker only needs the
sources in front of it, the test sees the whole scan surface.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from scenery_insitu_tpu.tools.lint.core import (Diagnostic, SourceFile,
                                                call_name, func_params,
                                                iter_calls)

CODE = "SITPU-COUNTER"

_COUNTER_PARAM_SUFFIX = "_counter"


def _counter_params(fn) -> List[str]:
    return [p for p in func_params(fn)
            if p.endswith(_COUNTER_PARAM_SUFFIX)]


def _param_defaults(fn) -> List[Tuple[str, ast.expr]]:
    """(param_name, default_expr) pairs, positional and keyword-only."""
    a = fn.args
    out = []
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        out.append((p.arg, d))
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            out.append((p.arg, d))
    return out


def _enclosing_fn_of(tree: ast.Module, node: ast.AST):
    best = None
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (n.lineno <= node.lineno
                    and node.lineno <= (n.end_lineno or n.lineno)):
                if best is None or n.lineno > best.lineno:
                    best = n
    return best


def discover_counters(sources) -> Dict[str, List[str]]:
    """Statically discovered counter names -> their sites. Three
    sources: literal ``.count("name")`` args, string defaults of
    ``*_counter`` parameters, and string literals passed to
    ``*_counter=`` keywords. Held equal to ``obs.counter_registry()``
    (both directions) by the round-trip test in tests/test_lint.py."""
    out: Dict[str, List[str]] = {}

    def add(name: str, src: SourceFile, line: int) -> None:
        out.setdefault(name, []).append(f"{src.path}:{line}")

    for src in sources:
        for c in iter_calls(src.tree):
            if call_name(c) == "count" and c.args:
                a = c.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value,
                                                              str):
                    add(a.value, src, c.lineno)
            for kw in c.keywords:
                if kw.arg and kw.arg.endswith(_COUNTER_PARAM_SUFFIX) \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    add(kw.value.value, src, c.lineno)
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for p, d in _param_defaults(node):
                    if p.endswith(_COUNTER_PARAM_SUFFIX) \
                            and isinstance(d, ast.Constant) \
                            and isinstance(d.value, str):
                        add(d.value, src, d.lineno)
    return out


def check(sources: List[SourceFile]) -> List[Diagnostic]:
    # imported here, not at module top: the lint package stays importable
    # without the obs package on the path (and obs is JAX-free, so this
    # costs nothing in CI)
    from scenery_insitu_tpu.obs import counter_registry

    registry = counter_registry()
    diags: List[Diagnostic] = []
    discovered = discover_counters(sources)
    by_site: Dict[str, List[Tuple[str, int]]] = {}
    for name, sites in discovered.items():
        for s in sites:
            path, _, line = s.rpartition(":")
            by_site.setdefault(name, []).append((path, int(line)))
    for name in sorted(discovered):
        if name in registry:
            continue
        for path, line in by_site[name]:
            diags.append(Diagnostic(
                path, line, CODE,
                f"counter name {name!r} is not registered in "
                f"obs.counter_registry() — add it with a one-line "
                f"meaning (docs/OBSERVABILITY.md)",
                ""))
    # C2: dynamic name arguments
    for src in sources:
        for c in iter_calls(src.tree):
            if call_name(c) != "count" or not c.args:
                continue
            a = c.args[0]
            if isinstance(a, ast.Constant):
                # str literals are C1's job; non-str constants are not
                # Recorder calls (itertools.count(1))
                continue
            if not isinstance(a, ast.Name):
                continue          # attribute/expr: out of scope
            fn = _enclosing_fn_of(src.tree, c)
            if fn is not None and a.id in _counter_params(fn):
                continue          # the *_counter-parameter pattern
            diags.append(Diagnostic(
                src.path, c.lineno, CODE,
                f"counter name is the dynamic variable {a.id!r} — "
                f"thread it through a '*_counter'-suffixed parameter "
                f"(with a registered string default) so the catalog "
                f"can see it",
                fn.name if fn is not None else "<module>"))
    return diags
