"""sitpu-lint runner: path collection, checker dispatch, the gate.

Shared by the CLI (``__main__``) and the test suite / tooling
(``run_lint``) — kept out of ``__main__`` so importing the package never
shadows the ``python -m`` entry module.
"""

from __future__ import annotations

import os
from typing import List, Optional

from scenery_insitu_tpu.tools.lint import (counters, ledger, knobs, pallas,
                                           thread, trace)
from scenery_insitu_tpu.tools.lint.core import (Baseline, Diagnostic,
                                                SourceFile,
                                                default_scan_paths,
                                                find_repo_root,
                                                load_sources_with_diags)

CHECKERS = (ledger, counters, thread, trace, pallas, knobs)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def collect_paths(repo_root: str, args_paths: List[str]) -> List[str]:
    if not args_paths:
        return default_scan_paths(repo_root)
    out = []
    for p in args_paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                out.extend(os.path.join(dirpath, f) for f in filenames
                           if f.endswith(".py"))
        else:
            out.append(p)
    return out


def run_checks(sources: List[SourceFile]) -> List[Diagnostic]:
    """All checkers over parsed sources, inline suppressions applied,
    stable ordering."""
    by_path = {s.path: s for s in sources}
    diags: List[Diagnostic] = []
    for checker in CHECKERS:
        diags.extend(checker.check(sources))
    diags = [d for d in diags
             if d.path not in by_path
             or not by_path[d.path].suppressed(d.line, d.code)]
    return sorted(diags, key=lambda d: (d.path, d.line, d.code, d.message))


def run_lint(paths: Optional[List[str]] = None,
             baseline_path: Optional[str] = None,
             repo_root: Optional[str] = None):
    """Library entry (tests, tooling). Returns (new, accepted, stale,
    all_diags). Unparseable files surface as SITPU-PARSE findings."""
    root = repo_root or find_repo_root()
    srcs, parse_diags = load_sources_with_diags(
        root, collect_paths(root, paths or []))
    diags = parse_diags + run_checks(srcs)
    bl = Baseline.load(baseline_path or default_baseline_path())
    new, accepted, stale = bl.split(diags)
    return new, accepted, stale, diags
