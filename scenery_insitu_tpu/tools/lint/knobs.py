"""SITPU-KNOB — every march-path config knob must appear in the LOD
bench's ``KNOB_MATRIX``.

The LOD ladder (``benchmarks/lod_bench.py``, docs/PERF.md "LOD
marching") is the committed PSNR-vs-FLOPs-vs-ms evidence for the
multi-resolution march, and its ``KNOB_MATRIX`` is the ledger of which
march-path knobs that evidence covers (swept, pinned, or argued
irrelevant — each key carries a one-line coverage note). A knob added to
``SliceMarchConfig`` or ``LODConfig`` without a matrix entry is a claim
the ladder silently stops covering: the next person reading the artifact
has no way to know the new knob was never considered. This checker makes
that drift a lint finding on the config field's own line.

Mechanics (pure ast, like the rest of the suite):

1. collect ``slicer.<field>`` / ``lod.<field>`` knob names from the
   ``AnnAssign`` fields of ``SliceMarchConfig`` / ``LODConfig`` in
   ``scenery_insitu_tpu/config.py`` (the dotted names match the
   overrides grammar those classes are configured through);
2. collect the string keys of the module-level ``KNOB_MATRIX`` dict
   literal in ``benchmarks/lod_bench.py``;
3. flag config knobs missing from the matrix, and matrix keys that no
   longer name a config knob (stale coverage claims rot the other way).

When either file is outside the scan set (path-scoped runs) the checker
emits nothing — the invariant spans both files, so it only holds over a
scan that sees both.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from scenery_insitu_tpu.tools.lint.core import Diagnostic, SourceFile

CODE = "SITPU-KNOB"

CONFIG_PATH = "scenery_insitu_tpu/config.py"
BENCH_PATH = "benchmarks/lod_bench.py"

# config classes whose fields are march-path knobs, with the overrides
# prefix each is addressed by (config.py's dotted-override grammar)
_KNOB_CLASSES = {"SliceMarchConfig": "slicer", "LODConfig": "lod"}


def _config_knobs(src: SourceFile) -> Dict[str, Tuple[int, str]]:
    """``"slicer.fold" -> (lineno, "SliceMarchConfig")`` for every
    annotated field of the march-path config classes."""
    out: Dict[str, Tuple[int, str]] = {}
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        prefix = _KNOB_CLASSES.get(node.name)
        if prefix is None:
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and not stmt.target.id.startswith("_"):
                out[f"{prefix}.{stmt.target.id}"] = (stmt.lineno, node.name)
    return out


def _matrix_keys(src: SourceFile) -> Optional[Dict[str, int]]:
    """String keys (with lines) of the module-level KNOB_MATRIX dict
    literal; None when the bench has no parseable matrix (that absence
    is itself a finding — the coverage ledger is the contract)."""
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "KNOB_MATRIX":
                if not isinstance(value, ast.Dict):
                    return None
                return {k.value: k.lineno for k in value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return None


def check(sources: List[SourceFile]) -> List[Diagnostic]:
    cfg_src = next((s for s in sources if s.path == CONFIG_PATH), None)
    bench_src = next((s for s in sources if s.path == BENCH_PATH), None)
    if cfg_src is None or bench_src is None:
        return []
    knobs = _config_knobs(cfg_src)
    matrix = _matrix_keys(bench_src)
    if matrix is None:
        return [Diagnostic(
            bench_src.path, 1, CODE,
            "no module-level KNOB_MATRIX dict literal — the LOD bench "
            "must declare which march-path knobs its ladder covers")]
    diags: List[Diagnostic] = []
    for knob, (line, cls) in sorted(knobs.items()):
        if knob not in matrix:
            diags.append(Diagnostic(
                cfg_src.path, line, CODE,
                f"march-path knob `{knob}` has no {BENCH_PATH} "
                f"KNOB_MATRIX entry — the committed LOD ladder silently "
                f"stops covering it; add a coverage note (swept, pinned, "
                f"or why it cannot move the ladder)", cls))
    for key, line in sorted(matrix.items()):
        if key not in knobs:
            diags.append(Diagnostic(
                bench_src.path, line, CODE,
                f"KNOB_MATRIX key `{key}` names no SliceMarchConfig/"
                f"LODConfig field — stale coverage claim (knob renamed "
                f"or removed?)"))
    return diags
