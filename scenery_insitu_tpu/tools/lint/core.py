"""sitpu-lint core: file loading, suppressions, the baseline gate.

The suite is pure stdlib ``ast`` — no jax import, no execution of the
code under analysis — so it runs in a bare CI container in well under a
second. Checkers receive parsed :class:`SourceFile` objects and return
:class:`Diagnostic` records; this module owns everything around them:

- **inline suppressions**: a ``# sitpu-lint: disable=CODE[,CODE...]``
  comment on the diagnostic's reported line (or ``disable=all``)
  silences it at the source — use for true positives the code cannot
  express otherwise, with a justification in the surrounding comment.
- **the baseline** (``tools/lint/baseline.json``): the committed ledger
  of accepted findings, each with a mandatory human ``reason`` string.
  The gate fails only on findings NOT in the baseline, so the suite can
  hold invariants that have principled exceptions (e.g. the plain-image
  builders genuinely have no ``ring_slots`` working set to cap) without
  those exceptions rotting into "the linter is red, ignore it".
  Baseline entries match on ``(code, path, message)`` — never on line
  numbers, which churn — and entries that no longer match anything are
  reported as stale so the baseline shrinks as debts are paid.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(r"sitpu-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Diagnostic:
    """One finding: ``path:line  CODE  message`` (path repo-relative)."""

    path: str
    line: int
    code: str
    message: str
    symbol: str = ""          # enclosing function, for humans + baseline

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}  {self.code}  {self.message}{sym}"

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers churn, messages are stable."""
        return (self.code, self.path, self.message)


class SourceFile:
    """One parsed file: AST + per-line suppression sets."""

    def __init__(self, abspath: str, relpath: str, text: str):
        self.abspath = abspath
        self.path = relpath            # repo-relative, '/' separators
        self.text = text
        self.tree = ast.parse(text, filename=relpath)
        self.suppressions = _parse_suppressions(text)

    def suppressed(self, line: int, code: str) -> bool:
        codes = self.suppressions.get(line)
        return bool(codes) and (code in codes or "all" in codes)


def _parse_suppressions(text: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                out.setdefault(tok.start[0], set()).update(codes)
    except tokenize.TokenError:
        pass
    return out


def load_sources(root: str, paths: Iterable[str]) -> List[SourceFile]:
    """Parse ``paths`` (absolute) into SourceFiles. Raises on a syntax
    error; gate-facing callers use :func:`load_sources_with_diags` so a
    half-edited file fails as its own SITPU-PARSE finding (with the
    report artifact still written) instead of a raw traceback."""
    out = []
    for p in sorted(set(paths)):
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        with open(p, "r", encoding="utf-8") as f:
            text = f.read()
        out.append(SourceFile(p, rel, text))
    return out


def load_sources_with_diags(root: str, paths: Iterable[str]
                            ) -> Tuple[List[SourceFile], List[Diagnostic]]:
    """Like :func:`load_sources`, but unparseable files become
    ``SITPU-PARSE`` diagnostics (per file) instead of crashing the run —
    the gate must fail loudly AND still produce its report."""
    out, diags = [], []
    for p in sorted(set(paths)):
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        with open(p, "r", encoding="utf-8") as f:
            text = f.read()
        try:
            out.append(SourceFile(p, rel, text))
        except SyntaxError as e:
            diags.append(Diagnostic(rel, e.lineno or 1, "SITPU-PARSE",
                                    f"file does not parse: {e.msg}"))
    return out, diags


def default_scan_paths(repo_root: str) -> List[str]:
    """The repo surface the invariants cover: the package (minus the
    linter itself — host tooling has no degrade/trace semantics), the
    bench driver and the benchmark harnesses."""
    pkg = os.path.join(repo_root, "scenery_insitu_tpu")
    skip = os.path.join(pkg, "tools") + os.sep
    paths = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if (dirpath + os.sep).startswith(skip):
            continue
        for name in filenames:
            if name.endswith(".py"):
                paths.append(os.path.join(dirpath, name))
    bench = os.path.join(repo_root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    bdir = os.path.join(repo_root, "benchmarks")
    if os.path.isdir(bdir):
        for name in os.listdir(bdir):
            if name.endswith(".py"):
                paths.append(os.path.join(bdir, name))
    return paths


def find_repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    # tools/lint -> tools -> scenery_insitu_tpu -> repo
    return os.path.abspath(os.path.join(here, "..", "..", ".."))


# ------------------------------------------------------------------ AST util

def iter_calls(node: ast.AST) -> Iterable[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def call_name(call: ast.Call) -> str:
    """Rightmost name of the called expression: ``obs.degrade`` ->
    ``degrade``, ``degrade`` -> ``degrade``, ``a.b.c()`` -> ``c``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def dotted_name(expr: ast.AST) -> str:
    """``jax.lax.scan`` -> "jax.lax.scan"; "" when not a pure name chain."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return ""


# functions that mint a ledger entry on behalf of their caller, with the
# positional index of the literal component argument (used by both the
# LEDGER checker and the registry round-trip discovery)
DEGRADE_WRAPPERS = {"degrade": 0, "mosaic_probe": 3}


def calls_degrade(node: ast.AST) -> bool:
    """Does ``node`` contain a ledger mint — ``obs.degrade(...)`` /
    ``degrade(...)`` or a degrade-minting wrapper like
    ``pallas_util.mosaic_probe`` (the fallback-ledger contract,
    obs/recorder.py)?"""
    return any(call_name(c) in DEGRADE_WRAPPERS for c in iter_calls(node))


def enclosing_functions(tree: ast.Module):
    """Yield (outermost_top_level_def, def_node) for every function."""
    for top in tree.body:
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for n in ast.walk(top):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield top, n


def func_params(fn) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


# ----------------------------------------------------------------- baseline

class Baseline:
    """Committed suppression ledger. Every entry carries a mandatory
    ``reason`` — a baseline without stated reasons is just a muted
    linter."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []
        bad = [e for e in self.entries
               if not str(e.get("reason", "")).strip()]
        if bad:
            raise ValueError(
                f"baseline entries without a reason string: "
                f"{[(e.get('code'), e.get('path')) for e in bad]}")
        self._index = {(e["code"], e["path"], e["message"]): e
                       for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("entries", []))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": self.entries}, f, indent=2,
                      sort_keys=False)
            f.write("\n")

    def split(self, diags: Sequence[Diagnostic]):
        """(new, accepted, stale_entries)."""
        new, accepted = [], []
        hit: Set[Tuple[str, str, str]] = set()
        for d in diags:
            if d.key() in self._index:
                accepted.append(d)
                hit.add(d.key())
            else:
                new.append(d)
        stale = [e for k, e in self._index.items() if k not in hit]
        return new, accepted, stale

    @staticmethod
    def entry_for(d: Diagnostic, reason: str) -> dict:
        return {"code": d.code, "path": d.path, "message": d.message,
                "symbol": d.symbol, "reason": reason}
