from scenery_insitu_tpu.parallel.mesh import make_mesh  # noqa: F401
from scenery_insitu_tpu.parallel.pipeline import (  # noqa: F401
    distributed_plain_step, distributed_vdi_step)
