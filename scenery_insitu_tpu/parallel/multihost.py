"""Multi-host (DCN) distribution — the reference's 8-node MPI deployment
shape (README.md:4-8: one renderer per cluster node, MPI between them;
externals DistributedVolumes.kt:136-139) mapped to JAX's multi-process
runtime:

- ``initialize()`` ≅ MPI_Init: every process connects to the coordinator
  (jax.distributed), after which ``jax.devices()`` is the GLOBAL device
  list and one jitted SPMD program spans all hosts. Collectives ride ICI
  within a host and DCN between hosts — chosen by XLA, not by this code.
- ``global_mesh()`` ≅ COMM_WORLD: the same 1-D compositing mesh the
  single-host pipeline uses, just over global devices, so
  ``distributed_vdi_step`` / ``_mxu`` / hybrid run UNCHANGED.
- ``shard_global()`` builds a global array from each process's local slab
  (the in-situ case: every node's simulation produces its own slab; no
  host ever holds the whole volume).
- ``gather_vdi_compressed()`` is the explicit HOST hop: each process
  compresses its addressable output columns with the variable-length
  segment codec (io.vdi_io.pack_vdi_segments ≅ the reference's
  per-segment LZ4 + MPI_Alltoallv, VDICompositingTest.kt:251-304) and
  process 0 assembles the full frame. Device collectives stay
  uncompressed — compression pays only on DCN/host/disk paths.

Smoke test (single machine, 2 processes — ≅ mpirun -np 2):

    python -m scenery_insitu_tpu.parallel.multihost --launch 2

Each process pins 2 virtual CPU devices, initializes the coordination
service, runs one distributed_vdi_step over the 4-device global mesh
(``MULTIHOST_OK norm=...``), then the flagship temporal MXU chain —
rank-sharded threshold seed + two carried-state frames —
(``MULTIHOST_MXU_OK norm=...``); norms must agree across processes, and
process 0 checks the compressed host gather (``MULTIHOST_GATHER_OK``).
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Tuple

import numpy as np

from scenery_insitu_tpu.parallel.mesh import DEFAULT_AXIS


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, timeout_s: float = 300.0,
               attempt_timeout_s: float = 60.0, fault=None) -> None:
    """≅ MPI_Init. Call before any other JAX use on every process.

    Wrapped in the bounded-backoff ladder of ``utils/retry.Backoff``
    (docs/ROBUSTNESS.md "Liveness supervision"): a coordinator that is
    still starting, a not-yet-scheduled peer or a transient DCN blip no
    longer hangs the fleet silently — each attempt gets
    ``attempt_timeout_s``, every retry lands on the fallback ledger as
    ``multihost.connect``, and the whole ladder gives up (re-raising the
    last error) after ``timeout_s``. ``fault`` (a config.FaultConfig)
    supplies the backoff base/cap; None uses the retry defaults."""
    import time

    import jax

    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.utils.retry import Backoff

    bo = (Backoff(fault.backoff_base_s, fault.backoff_cap_s)
          if fault is not None else Backoff())
    deadline = time.monotonic() + timeout_s
    attempt = 0
    while True:
        budget = deadline - time.monotonic()
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=max(
                    1, int(min(attempt_timeout_s, max(budget, 1.0)))))
            return
        except Exception as e:
            try:    # clear any half-initialized client before retrying
                jax.distributed.shutdown()
            except Exception:
                pass
            attempt += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"multihost.initialize: process {process_id} could "
                    f"not reach the coordinator at "
                    f"{coordinator_address} within {timeout_s:.0f}s "
                    f"({attempt} attempts)") from e
            _obs.degrade("multihost.connect", "first-attempt connect",
                         f"retry (attempt {attempt})",
                         f"{type(e).__name__}: {e}", warn=False)
            time.sleep(min(bo.next_delay(), max(0.0, remaining)))


def global_mesh(axis_name: str = DEFAULT_AXIS):
    """1-D mesh over ALL processes' devices (call after initialize())."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def shard_global(local_block: np.ndarray, mesh, axis_name: str = DEFAULT_AXIS
                 ):
    """Build the global z-sharded volume array from THIS process's slab
    (each process contributes its local simulation output; the global
    array is never materialized on one host)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis_name, None, None))
    return jax.make_array_from_process_local_data(sharding, local_block)


def _kv_client():
    """The coordination-service key-value client every jax.distributed
    process holds — the host-side DCN side channel (endpoint exchange,
    barriers, and the blob-allgather fallback below)."""
    import jax

    client = jax._src.distributed.global_state.client
    if client is None:
        raise RuntimeError("jax.distributed is not initialized — the "
                           "coordinator KV store only exists multi-process")
    return client


def kv_put_bytes(key: str, value: bytes) -> None:
    """Publish a small blob under ``key`` in the coordinator KV store
    (base64-string fallback where the bytes API is missing)."""
    client = _kv_client()
    if hasattr(client, "key_value_set_bytes"):
        client.key_value_set_bytes(key, value)
    else:
        import base64

        client.key_value_set(key, base64.b64encode(value).decode())


def kv_get_bytes(key: str, timeout_ms: int = 60_000) -> bytes:
    """Blocking fetch of a `kv_put_bytes` blob (waits for the key)."""
    client = _kv_client()
    if hasattr(client, "blocking_key_value_get_bytes"):
        return client.blocking_key_value_get_bytes(key, timeout_ms)
    import base64

    return base64.b64decode(client.blocking_key_value_get(key, timeout_ms))


def barrier(name: str, timeout_ms: int = 60_000) -> None:
    """Coordination-service barrier across every process (≅ MPI_Barrier
    on the host plane — no device collective, works on any backend)."""
    _kv_client().wait_at_barrier(name, timeout_ms)


_KV_AG_SEQ = [0]          # collective call counter (same order everywhere)


def _device_collectives_ok() -> bool:
    """Can this runtime run cross-process DEVICE collectives? The CPU
    backend cannot ("Multiprocess computations aren't implemented"), so
    multi-process CPU runs — the CI harness, testing/multiproc.py —
    route host gathers through the coordinator KV store instead."""
    import jax

    return jax.process_count() == 1 or jax.default_backend() != "cpu"


def _allgather_blobs(blob: bytes, timeout_ms: int = 120_000):
    """Allgather of one variable-length blob per process: returns
    (blobs [P, 1, maxlen], lengths [P, 1]) — the shared transport of the
    compressed VDI gather and the obs-event merge, and the explicit DCN
    hop of the host path (every byte is counted on the
    ``dcn_bytes_sent`` / ``dcn_bytes_received`` obs counters, the hop
    spans as ``dcn_allgather`` — docs/OBSERVABILITY.md).

    Transport: a padded-uint8 ``process_allgather`` over devices where
    the backend supports cross-process collectives; on a multi-process
    CPU backend it degrades (ledgered ``multihost.transport``) to the
    coordinator KV store — same wire contract, pure host plane."""
    from scenery_insitu_tpu import obs as _obs

    rec = _obs.get_recorder()
    rec.count("dcn_bytes_sent", len(blob))
    if not _device_collectives_ok():
        import jax

        _obs.degrade(
            "multihost.transport", "device-allgather", "coordinator-kv",
            "this backend cannot run cross-process device collectives; "
            "host gathers ride the coordination-service KV store",
            warn=False)
        nproc = jax.process_count()
        pid = jax.process_index()
        seq = _KV_AG_SEQ[0]
        _KV_AG_SEQ[0] += 1
        with rec.span("dcn_allgather", transport="kv", seq=seq):
            kv_put_bytes(f"sitpu/ag/{seq}/{pid}", blob)
            # bounded KV footprint over long runs: retire our own blob
            # from TWO collective generations back — any process at call
            # s has completed call s-1's gets, and it could only start
            # call s-1 after finishing call s-2's gets, so no reader can
            # still need a seq-2 key (best-effort: old jax clients lack
            # key_value_delete; the window stays 2 entries either way)
            if seq >= 2:
                try:
                    _kv_client().key_value_delete(
                        f"sitpu/ag/{seq - 2}/{pid}")
                except Exception:  # sitpu-lint: disable=SITPU-LEDGER — cleanup of an already-consumed key; nothing degrades
                    pass
            parts = []
            for p in range(nproc):
                parts.append(blob if p == pid else kv_get_bytes(
                    f"sitpu/ag/{seq}/{p}", timeout_ms))
        maxlen = max(len(b) for b in parts)
        blobs = np.zeros((nproc, 1, max(maxlen, 1)), np.uint8)
        lengths = np.zeros((nproc, 1), np.int64)
        for p, b in enumerate(parts):
            blobs[p, 0, :len(b)] = np.frombuffer(b, np.uint8)
            lengths[p, 0] = len(b)
            if p != pid:
                rec.count("dcn_bytes_received", len(b))
        return blobs, lengths

    from jax.experimental import multihost_utils

    ln = np.zeros((1,), np.int64)
    ln[0] = len(blob)
    with rec.span("dcn_allgather", transport="device"):
        # normalize to [P, 1] / [P, 1, maxlen]: single-process allgather
        # returns the input without a leading process axis
        lengths = np.asarray(
            multihost_utils.process_allgather(ln)).reshape(-1, 1)
        maxlen = int(lengths.max())
        buf = np.zeros((1, maxlen), np.uint8)
        buf[0, :len(blob)] = np.frombuffer(blob, np.uint8)
        blobs = np.asarray(
            multihost_utils.process_allgather(buf)).reshape(-1, 1, maxlen)
    received = int(lengths.sum() - len(blob))
    if received > 0:
        rec.count("dcn_bytes_received", received)
    return blobs, lengths


def gather_vdi_tiles(vdi, codec: str = "zstd"):
    """Tile-granular host gather (docs/PERF.md "Tile waves"): compress
    each process's addressable column block and, on process 0, YIELD the
    blocks as ``(col0, color, depth)`` in ascending column order, each
    decompressed lazily as the consumer reaches it — rank-0 assembly
    (and anything it feeds, e.g. a VDIPublisher publishing tiles) can
    emit the first columns before the whole frame finishes
    decompressing. Returns a generator on process 0, None elsewhere.

    Wire format: one dense zstd/zlib blob per process (its contiguous
    column block: raw color bytes + depth bytes) with per-process byte
    counts — the variable-length-per-sender idea of the reference's
    compressed gather, one segment per process rather than
    io.vdi_io.pack_vdi_segments' per-destination split (here the exchange
    already happened on-device; only the final gather crosses hosts).
    Transport is jax's process_allgather on a padded uint8 buffer."""
    import jax

    from scenery_insitu_tpu.io.vdi_io import compress, decompress

    # addressable column block of this process (contiguous by construction
    # of the 1-D W sharding)
    col_shards = sorted(
        (s for s in vdi.color.addressable_shards),
        key=lambda s: s.index[-1].start or 0)
    dep_shards = sorted(
        (s for s in vdi.depth.addressable_shards),
        key=lambda s: s.index[-1].start or 0)
    local_c = np.concatenate([np.asarray(s.data) for s in col_shards], -1)
    local_d = np.concatenate([np.asarray(s.data) for s in dep_shards], -1)
    blobs, lengths = _allgather_blobs(
        compress(local_c.tobytes() + local_d.tobytes(), codec))

    if jax.process_index() != 0:
        return None
    nproc = jax.process_count()
    k, ch, h, _ = vdi.color.shape
    ch_d = vdi.depth.shape[1]

    def tiles():
        from scenery_insitu_tpu import obs as _obs

        rec = _obs.get_recorder()
        col0 = 0
        for p in range(nproc):
            with rec.span("dcn_decompress", source_rank=p,
                          bytes=int(lengths[p, 0])):
                raw = decompress(bytes(blobs[p, 0, :int(lengths[p, 0])]),
                                 codec)
            arr = np.frombuffer(raw, np.float32)
            wseg = arr.size // (k * (ch + ch_d) * h)
            nc = k * ch * h * wseg
            yield (col0, arr[:nc].reshape(k, ch, h, wseg),
                   arr[nc:].reshape(k, ch_d, h, wseg))
            col0 += wseg

    return tiles()


def gather_vdi_compressed(vdi, codec: str = "zstd"
                          ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Host hop: compress each process's addressable output columns and
    assemble the full (color, depth) on process 0 (returns None
    elsewhere). The whole-frame view of `gather_vdi_tiles` — same wire
    format and transport, blocks concatenated in column order."""
    tiles = gather_vdi_tiles(vdi, codec)
    if tiles is None:
        return None
    cols, deps = [], []
    for _, c, d in tiles:
        cols.append(c)
        deps.append(d)
    return np.concatenate(cols, -1), np.concatenate(deps, -1)


def gather_obs_events(recorder) -> Optional[list]:
    """Rank-0 merge of the observability layer (obs.Recorder): every
    process contributes its structured events + summary (rank is already
    in every event, so the merge is a concatenation sorted by timestamp);
    returns the merged event list on process 0, None elsewhere. Single-
    process: a plain local snapshot, no collective. The blob rides the
    same padded-allgather transport as ``gather_vdi_compressed`` — zlib
    (stdlib, never degrades) since telemetry JSON is small.

    Each rank's ``ts`` is relative to its OWN recorder epoch, so the
    merge rebases every event onto the earliest epoch (via the
    recorder's wall-clock ``epoch_unix``) before sorting — without this,
    a rank whose session started late would sort seconds early."""
    import json as _json
    import zlib

    import jax

    payload = {"events": recorder.events, "summary": recorder.summary(),
               "epoch_unix": recorder.epoch_unix}
    if jax.process_count() == 1:
        return sorted(payload["events"], key=lambda e: e.get("ts", 0.0)) \
            + [{"type": "summary", **payload["summary"]}]

    blobs, lengths = _allgather_blobs(
        zlib.compress(_json.dumps(payload).encode()))

    if jax.process_index() != 0:
        return None
    payloads = []
    for p in range(jax.process_count()):
        raw = zlib.decompress(bytes(blobs[p, 0, :int(lengths[p, 0])]))
        payloads.append(_json.loads(raw))
    base = min(d["epoch_unix"] for d in payloads)
    events, summaries = [], []
    for d in payloads:
        shift = d["epoch_unix"] - base
        for ev in d["events"]:
            ev = dict(ev)
            ev["ts"] = ev.get("ts", 0.0) + shift
            events.append(ev)
        summaries.append({"type": "summary", **d["summary"]})
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events + summaries


# --------------------------------------------------------------- smoke test

def _worker(coordinator: str, nproc: int, pid: int) -> None:
    initialize(coordinator, nproc, pid)

    import jax
    import jax.numpy as jnp

    from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
    from scenery_insitu_tpu.core.camera import Camera
    from scenery_insitu_tpu.core.transfer import for_dataset
    from scenery_insitu_tpu.parallel.pipeline import distributed_vdi_step
    from scenery_insitu_tpu.sim import grayscott as gs

    mesh = global_mesh()
    n = len(jax.devices())
    print(f"[mh {pid}] processes={jax.process_count()} global_devices={n}",
          flush=True)

    d_local_proc = 8 * (n // jax.process_count())
    grid_h = grid_w = 16
    width, height = 8 * n, 16

    # every process seeds the SAME global state and slices out its slab —
    # deterministic, so the result must match a single-process run
    st = gs.GrayScott.init((8 * n, grid_h, grid_w), n_seeds=4)
    z0 = pid * d_local_proc
    local_u = np.asarray(st.v)[z0:z0 + d_local_proc]
    field = shard_global(local_u, mesh)

    tf = for_dataset("gray_scott")
    cam = Camera.create((0.0, 0.4, 3.0), fov_y_deg=50.0, near=0.5, far=20.0)
    step = distributed_vdi_step(
        mesh, tf, width, height,
        VDIConfig(max_supersegments=4, adaptive_iters=2),
        CompositeConfig(max_output_supersegments=6, adaptive_iters=2),
        max_steps=24)
    origin = jnp.array([-1.0, -1.0, -1.0], jnp.float32)
    spacing = jnp.array([2.0 / 16, 2.0 / 16, 2.0 / (8 * n)], jnp.float32)
    vdi = step(field, origin, spacing, cam)

    # replicated reduction: every process must report the same value
    norm = float(jax.jit(lambda c: jnp.linalg.norm(c))(vdi.color))
    print(f"MULTIHOST_OK pid={pid} norm={norm:.6f}", flush=True)

    # flagship path across processes: MXU slice march with carried
    # temporal threshold state (rank-sharded through the global mesh)
    from scenery_insitu_tpu.config import SliceMarchConfig
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.parallel.pipeline import (
        distributed_initial_threshold_mxu, distributed_vdi_step_mxu_temporal)

    spec = slicer.make_spec(cam, (8 * n, grid_h, grid_w),
                            SliceMarchConfig(matmul_dtype="f32"),
                            multiple_of=n)
    cfg_t = VDIConfig(max_supersegments=4, adaptive_mode="temporal")
    comp = CompositeConfig(max_output_supersegments=6, adaptive_iters=2)
    thr = distributed_initial_threshold_mxu(mesh, tf, spec, cfg_t)(
        field, origin, spacing, cam)
    step_t = distributed_vdi_step_mxu_temporal(mesh, tf, spec, cfg_t, comp)
    for _ in range(2):
        (vdi_t, _), thr = step_t(field, origin, spacing, cam, thr)
    norm_t = float(jax.jit(lambda c: jnp.linalg.norm(c))(vdi_t.color))
    print(f"MULTIHOST_MXU_OK pid={pid} norm={norm_t:.6f}", flush=True)

    gathered = gather_vdi_compressed(vdi)
    if pid == 0:
        color, depth = gathered
        assert color.shape == (6, 4, height, width), color.shape
        assert np.isfinite(color).all()
        print(f"MULTIHOST_GATHER_OK shape={color.shape} "
              f"norm={np.linalg.norm(color):.6f}", flush=True)
    jax.distributed.shutdown()


def _launch(nproc: int, devices_per_proc: int = 2) -> int:
    """Spawn nproc workers on this machine (≅ mpirun -np N) and verify
    their replicated outputs agree."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    from scenery_insitu_tpu.utils.backend import virtual_mesh_env

    procs = []
    for pid in range(nproc):
        base = dict(os.environ)
        base["XLA_FLAGS"] = " ".join(
            f for f in base.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f)
        env = virtual_mesh_env(devices_per_proc, base)
        env["_SITPU_POP_AXON"] = "1"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "scenery_insitu_tpu.parallel.multihost",
             "--coordinator", coordinator, "--processes", str(nproc),
             "--process-id", str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))))

    norms = {}
    mxu_norms = {}
    ok = True
    for pid, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            # a wedged worker must still yield a parseable verdict and
            # must not leave its siblings bound to the coordinator port
            for q in procs:
                if q.poll() is None:
                    q.kill()
            for q in procs:     # reap: SIGKILL delivery is asynchronous
                try:
                    q.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            print(f"LAUNCH_FAILED worker {pid} timed out")
            return 1
        text = out.decode("utf-8", "replace")
        print(text)
        if p.returncode != 0:
            ok = False
        for line in text.splitlines():
            if line.startswith("MULTIHOST_OK"):
                norms[pid] = float(line.rsplit("norm=", 1)[1])
            elif line.startswith("MULTIHOST_MXU_OK"):
                mxu_norms[pid] = float(line.rsplit("norm=", 1)[1])

    def agree(d):
        return len(d) == nproc and len(set(round(v, 4)
                                           for v in d.values())) == 1

    if ok and agree(norms) and agree(mxu_norms):
        print(f"LAUNCH_OK processes={nproc} norm={norms[0]:.6f} "
              f"mxu_norm={mxu_norms[0]:.6f}")
        return 0
    print("LAUNCH_FAILED", norms, mxu_norms)
    return 1


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--launch", type=int, default=0,
                    help="spawn N single-machine processes (smoke test)")
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    args = ap.parse_args()

    if args.launch:
        sys.exit(_launch(args.launch))

    if os.environ.get("_SITPU_POP_AXON") == "1":
        from scenery_insitu_tpu.utils.backend import pin_cpu_backend

        pin_cpu_backend()
    _worker(args.coordinator, args.processes, args.process_id)
