"""First-class brick→rank render partitions (docs/SCENARIOS.md "Brick
maps"; ROADMAP item 5).

Every render decomposition before this module was CONVEX: rank r marched
one contiguous z band (the even slab, or PR 10's planned band). But the
supersegment composite never needed convexity — ``merge_vdis_pairwise``
and ``resegment_stream`` operate on per-pixel depth-SORTED fragment
streams whatever region produced them, which is exactly the
deep-fragment-list argument of "GPU-based Data-parallel Rendering of
Large, Unstructured, and Non-convexly Partitioned Data" (PAPERS.md). A
``BrickMap`` makes the assignment first-class: the global z extent
splits into ``nbricks`` equal bricks and an arbitrary ``owner`` table
says which rank marches which brick. ``parallel/mesh.reslab_bricks``
materializes each rank's brick set from the even sim shards, the
distributed builders march each brick through the existing per-chunk
machinery (``slice_march`` ``w_bounds``/``v_bounds`` become per-brick
intervals), and the correctness keystone is COMPOSITE INVARIANCE:
permuting brick ownership leaves the composited frame unchanged
(bitwise on the gather builder, ≤1e-5 on the mxu paths —
tests/test_bricks.py).

The same structure powers ``CompositeConfig.rebalance = "bricks"``:
`steal_plan` generalizes PR 10's occupancy replan from slab-RESIZING to
brick-STEALING — greedy per-brick live-work equalization from the
occupancy pyramid's z profile, with hysteresis and a move-count cap per
replan so the session recompiles rarely and by small deltas.

This module is host-side and jax-free (numpy only): a BrickMap is
static build-time geometry, exactly like a render plan tuple.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class BrickMap:
    """A regular brick grid over the global volume z extent plus an
    arbitrary brick→rank owner table.

    ``depth`` is the global z slice count, split into ``len(owner)``
    equal bricks (``depth % nbricks == 0`` — the even grid keeps
    materialization and ownership masks static); ``owner[i]`` is the
    rank that marches brick ``i`` (any value in ``[0, n_ranks)``; ranks
    may own zero bricks — their march units come up empty). Per-rank
    brick sets pad to ``slots`` = the busiest rank's count, so one SPMD
    program serves every rank; absent slots are dead (zero rows, empty
    ownership interval, occupancy admits them as dead).

    ``level[i]`` is brick ``i``'s refinement level (docs/PERF.md "LOD
    marching"): level ``l`` marches a ``2^l``-downsampled copy of the
    brick through the same slice-march machinery (materialized by
    `parallel.mesh.reslab_bricks_lod`; supersegments composite
    unchanged — the fragment stream is resolution-agnostic). The empty
    tuple (the default) normalizes to all-zero, and an all-level-0 map
    is EXACTLY the flat PR-15 map: every code path, `is_even_convex`
    included, behaves bitwise as before. For SPMD shape uniformity the
    builders group march units BY LEVEL (`slots_at`/`start_table_at`):
    each level present anywhere pads to its own global slot count."""

    depth: int
    n_ranks: int
    owner: Tuple[int, ...]
    level: Tuple[int, ...] = ()

    def __post_init__(self):
        owner = tuple(int(o) for o in self.owner)
        object.__setattr__(self, "owner", owner)
        nb = len(owner)
        if nb < 1:
            raise ValueError("a BrickMap needs at least one brick")
        if self.depth < 1 or self.depth % nb:
            raise ValueError(
                f"{nb} bricks do not evenly divide depth {self.depth} "
                f"(the regular brick grid keeps ownership masks static)")
        if self.n_ranks < 1:
            raise ValueError(f"n_ranks must be >= 1, got {self.n_ranks}")
        bad = [o for o in owner if not 0 <= o < self.n_ranks]
        if bad:
            raise ValueError(
                f"brick owners {sorted(set(bad))} outside the "
                f"{self.n_ranks}-rank mesh (owner table: {owner})")
        level = tuple(int(l) for l in self.level) or (0,) * nb
        object.__setattr__(self, "level", level)
        if len(level) != nb:
            raise ValueError(
                f"level table has {len(level)} entries for {nb} bricks")
        bz = self.depth // nb
        for i, l in enumerate(level):
            if l < 0:
                raise ValueError(f"brick {i} has negative level {l}")
            if bz % (1 << l):
                raise ValueError(
                    f"brick {i} at level {l}: downsample factor "
                    f"{1 << l} does not divide the {bz}-slice brick "
                    f"depth (coarse voxels must tile the brick exactly)")

    # ------------------------------------------------------------ geometry
    @property
    def nbricks(self) -> int:
        return len(self.owner)

    @property
    def brick_depth(self) -> int:
        """Slices per brick (bz)."""
        return self.depth // self.nbricks

    @property
    def slots(self) -> int:
        """Padded per-rank brick-slot count B = max bricks any rank owns
        (every rank marches B units; absent slots are dead)."""
        return max(len(self.rank_bricks(r)) for r in range(self.n_ranks))

    def rank_bricks(self, rank: int) -> Tuple[int, ...]:
        """Ascending brick ids owned by ``rank`` (deterministic slot
        order — invariance tests rely on the composite, not this)."""
        return tuple(i for i, o in enumerate(self.owner) if o == rank)

    def start_table(self) -> np.ndarray:
        """i32[n_ranks, slots] global START ROW of each rank's brick
        slots (``brick_id * brick_depth``), -1 for absent slots — the
        static table the distributed builders index by the traced rank
        id."""
        bz = self.brick_depth
        table = np.full((self.n_ranks, self.slots), -1, np.int32)
        for r in range(self.n_ranks):
            for s, b in enumerate(self.rank_bricks(r)):
                table[r, s] = b * bz
        return table

    def intervals(self, rank: int) -> List[Tuple[int, int]]:
        """[z0, z1) global slice intervals of ``rank``'s bricks."""
        bz = self.brick_depth
        return [(b * bz, (b + 1) * bz) for b in self.rank_bricks(rank)]

    # ------------------------------------------------------------- levels
    @property
    def max_level(self) -> int:
        return max(self.level)

    def levels_present(self) -> Tuple[int, ...]:
        """Ascending distinct refinement levels anywhere in the map —
        GLOBAL, so every rank builds the same per-level unit groups
        (SPMD shape uniformity; ranks owning no brick at a level march
        dead slots there)."""
        return tuple(sorted(set(self.level)))

    def rank_bricks_at(self, rank: int, level: int) -> Tuple[int, ...]:
        """Ascending brick ids owned by ``rank`` AT ``level``."""
        return tuple(i for i, (o, l) in enumerate(zip(self.owner,
                                                      self.level))
                     if o == rank and l == level)

    def slots_at(self, level: int) -> int:
        """Padded per-rank slot count of one level's unit group."""
        return max(len(self.rank_bricks_at(r, level))
                   for r in range(self.n_ranks))

    def start_table_at(self, level: int) -> np.ndarray:
        """i32[n_ranks, slots_at(level)] global start rows of each
        rank's level-``level`` brick slots, -1 for absent slots (the
        per-level twin of `start_table`; identical to it on an
        all-level-0 map)."""
        bz = self.brick_depth
        table = np.full((self.n_ranks, self.slots_at(level)), -1,
                        np.int32)
        for r in range(self.n_ranks):
            for s, b in enumerate(self.rank_bricks_at(r, level)):
                table[r, s] = b * bz
        return table

    @property
    def total_slots(self) -> int:
        """March units per rank across every level group (== ``slots``
        on an all-level-0 map) — the slot count the row-stacked temporal
        threshold state and the concatenated fragment stream carry."""
        return sum(self.slots_at(l) for l in self.levels_present())

    def with_levels(self, levels: Sequence[int]) -> "BrickMap":
        """Same ownership, new per-brick refinement levels (validated
        by construction)."""
        return BrickMap(self.depth, self.n_ranks, self.owner,
                        tuple(int(l) for l in levels))

    # ---------------------------------------------------------- structure
    def is_even_convex(self) -> bool:
        """Does this map reproduce the even contiguous z-slab split?
        True ⇒ the builders short-circuit to the pre-brick path
        (bitwise identical to a brickless step). Any coarse level keeps
        the brick path alive — only an ALL-FINE even map is the slab."""
        if any(self.level):
            return False
        nb, n = self.nbricks, self.n_ranks
        if nb % n:
            return False
        per = nb // n
        return all(o == i // per for i, o in enumerate(self.owner))

    def permute(self, perm: Sequence[int]) -> "BrickMap":
        """Relabel ranks: brick owned by r moves to ``perm[r]`` — the
        composite-invariance test's ownership shuffle (levels ride
        their bricks)."""
        perm = [int(p) for p in perm]
        if sorted(perm) != list(range(self.n_ranks)):
            raise ValueError(f"perm {perm} is not a permutation of "
                             f"0..{self.n_ranks - 1}")
        return BrickMap(self.depth, self.n_ranks,
                        tuple(perm[o] for o in self.owner), self.level)

    # -------------------------------------------------------- constructors
    @classmethod
    def even(cls, depth: int, n_ranks: int,
             nbricks: int = 0) -> "BrickMap":
        """The even contiguous map: ``nbricks`` (default ``n_ranks``)
        bricks owned in rank order — `is_even_convex` by construction."""
        nb = nbricks or n_ranks
        if nb % n_ranks:
            raise ValueError(f"even map needs n_ranks | nbricks, got "
                             f"{n_ranks} ranks x {nb} bricks")
        per = nb // n_ranks
        return cls(depth, n_ranks, tuple(i // per for i in range(nb)))

    @classmethod
    def contiguous(cls, depth: int, n_ranks: int,
                   nbricks: int) -> "BrickMap":
        """Balanced contiguous seed map for ANY brick count (`even` when
        ``n_ranks | nbricks``): brick i goes to rank ``i * n // nb`` —
        the steal planner's starting point when the auto brick count
        does not divide evenly by the rank count."""
        return cls(depth, n_ranks,
                   tuple(min(i * n_ranks // nbricks, n_ranks - 1)
                         for i in range(nbricks)))


def auto_nbricks(depth: int, n_ranks: int, target_per_rank: int = 4) -> int:
    """Default brick count of ``rebalance="bricks"``: the largest
    divisor of ``depth`` at most ``target_per_rank * n_ranks`` (fine
    enough to steal by, coarse enough that per-brick march overhead
    stays small), floored at ``n_ranks`` bricks when the depth allows."""
    cap = max(n_ranks, target_per_rank * n_ranks)
    nb = min(depth, cap)
    while depth % nb:
        nb -= 1
    return nb


# ------------------------------------------------------ brick-work model


def brick_work(live_profile, depth: int, nbricks: int,
               base_cost: Optional[float] = None) -> np.ndarray:
    """f64[nbricks] modeled march work per brick from a per-z-bin live
    profile (`ops.occupancy.z_live_profile`) under the PR-10 slice work
    model: a live slice costs 1 + base, an empty one base (air is cheap,
    not free — the brick march still scans its chunks)."""
    from scenery_insitu_tpu.ops.occupancy import (PLAN_BASE_COST,
                                                  _slice_work)

    if base_cost is None:
        base_cost = PLAN_BASE_COST
    if depth % nbricks:
        raise ValueError(f"{nbricks} bricks do not divide depth {depth}")
    w = _slice_work(live_profile, depth, base_cost)
    return w.reshape(nbricks, depth // nbricks).sum(axis=1)


def rank_work(bmap: BrickMap, work: np.ndarray) -> np.ndarray:
    """f64[n_ranks] summed brick work per owner."""
    out = np.zeros(bmap.n_ranks, np.float64)
    np.add.at(out, np.asarray(bmap.owner), np.asarray(work, np.float64))
    return out


def straggler_factor(bmap: BrickMap, work: np.ndarray) -> float:
    """max/mean per-rank modeled work — the frame-barrier term
    brick-stealing attacks (1.0 = perfectly balanced)."""
    loads = rank_work(bmap, work)
    return float(np.max(loads) / max(float(np.mean(loads)), 1e-12))


def steal_plan(prev: BrickMap, work: np.ndarray, max_moves: int = 2,
               hysteresis: float = 0.1) -> BrickMap:
    """Greedy brick-stealing re-plan (CompositeConfig.rebalance ==
    "bricks"): starting from ``prev``, move up to ``max_moves`` bricks
    from the most- to the least-loaded rank, each move picking the
    donor brick whose work best halves the pair's gap. Deterministic
    (numpy argmax/argmin tie-break to the lowest index), host-side.

    ``hysteresis``: stop (and return ``prev`` OBJECT-EQUAL when nothing
    moved) once ``max - min`` per-rank load falls within ``hysteresis *
    mean`` — the session keys recompiles on map identity, so a stable
    scene must converge to zero moves, not oscillate. The move cap
    bounds both the per-replan recompile delta and the reslab traffic a
    single replan can add.

    Refinement levels ride their bricks unchanged through every move;
    pass ``work`` already scaled to LEVEL UNITS (a level-l brick costs
    a fraction of its fine self — parallel/lod.level_work_scale) so the
    equalizer balances what the ranks actually march."""
    work = np.asarray(work, np.float64)
    if work.shape != (prev.nbricks,):
        raise ValueError(f"work has {work.shape} entries for "
                         f"{prev.nbricks} bricks")
    owner = np.asarray(prev.owner, np.int64).copy()
    n = prev.n_ranks
    loads = rank_work(prev, work)
    mean = max(float(loads.mean()), 1e-12)
    moved = 0
    while moved < max(int(max_moves), 0):
        donor = int(np.argmax(loads))
        recv = int(np.argmin(loads))
        gap = loads[donor] - loads[recv]
        if donor == recv or gap <= hysteresis * mean:
            break
        cand = np.nonzero(owner == donor)[0]
        if cand.size == 0:
            break
        # moving w shrinks the pair's |imbalance| iff w < gap; pick the
        # one closest to gap/2 (best single-move equalizer)
        w = work[cand]
        ok = w < gap
        if not ok.any():
            break
        score = np.where(ok, np.abs(w - gap / 2.0), np.inf)
        b = int(cand[int(np.argmin(score))])
        owner[b] = recv
        loads[donor] -= work[b]
        loads[recv] += work[b]
        moved += 1
    if not moved:
        return prev
    return BrickMap(prev.depth, n, tuple(int(o) for o in owner),
                    prev.level)
