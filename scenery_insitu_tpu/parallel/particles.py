"""Distributed particle rendering: sort-first compositing over the mesh.

The reference's particle mode shards particles by compute rank (OpenFPM
domain decomposition), renders each rank's spheres locally, and min-depth
composites full images on a head node (reference InVisRenderer.kt +
Head.kt:98-134, NaiveCompositor.frag:15-28). Here the same shape is one
jitted shard_map program: per-rank splat, ``all_gather`` of the small
image+depth pair over ICI, per-pixel depth-min select.

Coloring uses globally psum-reduced speed statistics so the distributed
render matches a single-device render of the full particle set (tests
assert this, tests/test_splat.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.ops.composite import composite_depth_min
from scenery_insitu_tpu.ops.splat import (SplatOutput, speed_colors,
                                          splat_particles)
from scenery_insitu_tpu.utils.compat import shard_map


def sort_first_splat(pos, vel, axis: str, width: int, height: int,
                     radius, stamp: int = 9, colormap: str = "jet",
                     cam: Optional[Camera] = None, view=None, proj=None
                     ) -> SplatOutput:
    """The per-rank body of sort-first particle rendering (call inside
    shard_map): speed-color with globally psum-reduced statistics (the
    reference computes these over the full population too,
    InVisRenderer.kt:166-175), splat this rank's spheres, all_gather the
    small image+depth pair, per-pixel depth-min. Returns a replicated
    SplatOutput. Shared by the particle and hybrid pipelines."""
    speed = jnp.linalg.norm(vel, axis=-1)
    cnt = jax.lax.psum(jnp.float32(speed.shape[0]), axis)
    s1 = jax.lax.psum(jnp.sum(speed), axis)
    s2 = jax.lax.psum(jnp.sum(speed * speed), axis)
    mean = s1 / cnt
    std = jnp.sqrt(jnp.maximum(s2 / cnt - mean * mean, 0.0))

    rgba = speed_colors(vel, colormap, mean=mean, std=std)
    out = splat_particles(pos, rgba, radius, cam, width, height, stamp,
                          view=view, proj=proj)
    imgs = jax.lax.all_gather(out.image, axis)              # [n, 4, H, W]
    deps = jax.lax.all_gather(out.depth, axis)              # [n, H, W]
    img, dep = composite_depth_min(imgs, deps)
    return SplatOutput(img, dep)


def distributed_particle_step(mesh: Mesh, width: int, height: int,
                              radius: float = 0.01, stamp: int = 9,
                              colormap: str = "jet",
                              axis_name: Optional[str] = None):
    """Build the jitted distributed particle render step.

    Returns ``f(pos f32[N, 3] (sharded on N), vel f32[N, 3] (same), cam
    Camera) -> SplatOutput`` with replicated full-frame image [4, H, W] +
    depth [H, W]. N must divide by the mesh size.
    """
    axis = axis_name or mesh.axis_names[0]

    def step(pos, vel, cam: Camera) -> SplatOutput:
        return sort_first_splat(pos, vel, axis, width, height, radius,
                                stamp, colormap, cam=cam)

    spec_part = P(axis, None)
    f = shard_map(step, mesh=mesh, in_specs=(spec_part, spec_part, P()),
                  out_specs=SplatOutput(P(), P()), check_vma=False)
    return jax.jit(f)


def shard_particles(arr: jnp.ndarray, mesh: Mesh,
                    axis_name: Optional[str] = None) -> jnp.ndarray:
    """Place a particle array [N, ...] onto the mesh sharded over N."""
    axis = axis_name or mesh.axis_names[0]
    return jax.device_put(arr, NamedSharding(mesh, P(axis)))
