"""First-class mesh topology — the scale-out plane (docs/MULTIHOST.md).

The single-domain pipeline's "communicator" is a flat 1-D
``jax.sharding.Mesh`` over one ICI domain (parallel/mesh.py). This module
makes the ICI/DCN split a construction-time fact instead of an implicit
assumption: a ``TopologyConfig(domain_size, num_hosts)`` resolves to a
2-D ``(hosts, ranks)`` mesh whose *ranks* sub-axis is the fast
intra-domain (ICI) axis and whose *hosts* sub-axis crosses domains over
DCN. Devices are laid out hosts-major, so on a real multi-process run
(``jax.distributed``) each process's local devices land in one domain
and hosts-axis collectives are exactly the cross-process (DCN) hops.

On a single process the same 2-D mesh over the virtual CPU/TPU device
list EMULATES the hierarchy — domains become mesh sub-axes — which is
what lets the two-level composite (parallel/hier.py) run, and be
parity-gated against the flat composite, in ordinary CI.

The generation side of the pipeline (halo exchange, slab ownership,
occupancy psums) is topology-agnostic: it addresses the mesh through the
FLAT axis view ``Topology.flat_axis`` — a ``(hosts, ranks)`` tuple that
every ``jax.lax`` collective accepts wherever a single axis name goes,
linearized hosts-major so flat rank ``h * D + d`` owns z-slab
``h * D + d`` exactly like the 1-D mesh. Only the sort-last composite
consults the split (parallel/hier.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple, Union

from scenery_insitu_tpu.config import MeshConfig, TopologyConfig
from scenery_insitu_tpu.parallel.mesh import DEFAULT_AXIS

DEFAULT_HOSTS_AXIS = "hosts"

AxisName = Union[str, Tuple[str, ...]]


class Topology(NamedTuple):
    """Resolved mesh topology of a hierarchical (two-level) mesh."""

    num_hosts: int          # ICI domains (DCN endpoints)
    domain_size: int        # devices per domain
    hosts_axis: str         # inter-domain (DCN) mesh axis
    ranks_axis: str         # intra-domain (ICI) mesh axis
    dcn_wire: str = "f32"   # wire format of the DCN hop

    @property
    def n_ranks(self) -> int:
        return self.num_hosts * self.domain_size

    @property
    def flat_axis(self) -> Tuple[str, str]:
        """The generation-side flat axis view: collectives over this
        tuple linearize hosts-major, so flat rank ``h * D + d`` matches
        the 1-D mesh's rank ordering (z-slab h*D+d)."""
        return (self.hosts_axis, self.ranks_axis)

    @property
    def out_axis(self) -> Tuple[str, str]:
        """Output-sharding axis order of the two-level composite: level
        1 hands rank ``(h, d)`` column block ``d`` and level 2 sub-block
        ``h`` within it, so its final columns sit at flat position
        ``d * H + h`` — the ranks-major traversal."""
        return (self.ranks_axis, self.hosts_axis)


def resolve_topology(cfg: Optional[TopologyConfig], n_devices: int,
                     ranks_axis: str = DEFAULT_AXIS) -> Optional[Topology]:
    """Resolve a TopologyConfig against a device count.

    Returns None for flat configurations (``num_hosts == 1`` — today's
    single-level path, bitwise). A 1-host config that nevertheless sets
    ``domain_size`` asked for a domain split with nothing to split
    across: the knob is inert and lands on the fallback ledger
    (``topology.hier``) instead of being silently ignored.

    ``domain_size`` must divide the participating device count exactly
    (and ``num_hosts * domain_size`` must equal it) — a hierarchy that
    does not tile the mesh fails here, at build, not inside a trace.
    """
    if cfg is None or cfg.num_hosts == 1:
        if cfg is not None and cfg.domain_size not in (0, n_devices):
            from scenery_insitu_tpu import obs as _obs

            _obs.degrade(
                "topology.hier", f"domain_size={cfg.domain_size}", "flat",
                "num_hosts=1: a single host has no DCN axis — the "
                "two-level composite degenerates to the flat path",
                warn=False)
        return None
    h = cfg.num_hosts
    d = cfg.domain_size or (n_devices // h if n_devices % h == 0 else 0)
    if d <= 0 or n_devices % d or h * d != n_devices:
        raise ValueError(
            f"topology (num_hosts={h}, domain_size={cfg.domain_size}) "
            f"does not tile {n_devices} devices — domain_size must "
            f"divide the device count and num_hosts * domain_size must "
            f"equal it (0 = auto derives {n_devices}/{h})")
    if cfg.hosts_axis == ranks_axis:
        raise ValueError(
            f"hosts_axis {cfg.hosts_axis!r} collides with the ranks "
            f"axis name — the two mesh levels need distinct axes")
    return Topology(num_hosts=h, domain_size=d, hosts_axis=cfg.hosts_axis,
                    ranks_axis=ranks_axis, dcn_wire=cfg.dcn_wire)


def make_topology_mesh(topo_cfg: Optional[TopologyConfig] = None,
                       mesh_cfg: Optional[MeshConfig] = None,
                       devices: Optional[Sequence] = None):
    """Build the compositing mesh under a topology — the topology-aware
    successor of ``mesh.make_mesh`` (which it degenerates to for flat
    configs). Returns ``(mesh, topo)`` where ``topo`` is None for a flat
    1-D mesh and a `Topology` for the 2-D ``(hosts, ranks)`` mesh.

    Devices stay in their natural (process-major) order and reshape to
    ``[num_hosts, domain_size]`` — on a multi-process runtime each
    process's local devices form one domain, so ranks-axis collectives
    ride ICI and hosts-axis collectives ride DCN by construction."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    mesh_cfg = mesh_cfg or MeshConfig()
    devs = list(devices) if devices is not None else jax.devices()
    if mesh_cfg.num_devices:
        if mesh_cfg.num_devices > len(devs):
            raise ValueError(f"requested {mesh_cfg.num_devices} devices, "
                             f"have {len(devs)}")
        devs = devs[:mesh_cfg.num_devices]
    topo = resolve_topology(topo_cfg, len(devs), mesh_cfg.axis_name)
    if topo is None:
        from scenery_insitu_tpu.parallel.mesh import make_mesh

        return make_mesh(len(devs), mesh_cfg.axis_name, devices=devs), None
    grid = np.array(devs).reshape(topo.num_hosts, topo.domain_size)
    return Mesh(grid, (topo.hosts_axis, topo.ranks_axis)), topo


def topology_of(mesh, topology: Optional[TopologyConfig] = None
                ) -> Optional[Topology]:
    """Resolved `Topology` of a mesh: None for 1-D (flat) meshes; for a
    2-D mesh the split is read off the mesh axes themselves, optionally
    cross-checked against a ``TopologyConfig`` (a config that disagrees
    with the mesh it is used with is a caller bug, not a silent pick)."""
    names = mesh.axis_names
    if len(names) == 1:
        if topology is not None and topology.num_hosts > 1:
            raise ValueError(
                f"topology requests num_hosts={topology.num_hosts} but "
                f"the mesh is flat 1-D ({names[0]!r}) — build it with "
                f"topology.make_topology_mesh")
        return None
    if len(names) != 2:
        raise ValueError(f"compositing meshes are 1-D (flat) or 2-D "
                         f"(hosts, ranks); got axes {names}")
    hosts_axis, ranks_axis = names
    h, d = mesh.shape[hosts_axis], mesh.shape[ranks_axis]
    dcn_wire = "f32"
    if topology is not None and topology.num_hosts > 1:
        if (topology.num_hosts != h
                or (topology.domain_size not in (0, d))
                or topology.hosts_axis != hosts_axis):
            raise ValueError(
                f"topology (num_hosts={topology.num_hosts}, domain_size="
                f"{topology.domain_size}, hosts_axis="
                f"{topology.hosts_axis!r}) disagrees with the mesh "
                f"({hosts_axis!r}={h}, {ranks_axis!r}={d})")
        dcn_wire = topology.dcn_wire
    return Topology(num_hosts=h, domain_size=d, hosts_axis=hosts_axis,
                    ranks_axis=ranks_axis, dcn_wire=dcn_wire)


def resolve_mesh_topology(mesh, axis_name: Optional[str] = None,
                          topology: Optional[TopologyConfig] = None):
    """The builder-side resolution every ``distributed_*step*`` runs:
    ``(axis, n, topo)`` where ``axis`` is the flat generation axis (a
    plain name on 1-D meshes, the ``(hosts, ranks)`` tuple on 2-D), ``n``
    the total rank count and ``topo`` the `Topology` driving the
    two-level composite (None = flat single-level)."""
    topo = topology_of(mesh, topology)
    if topo is None:
        axis = axis_name or mesh.axis_names[0]
        return axis, mesh.shape[axis], None
    return topo.flat_axis, topo.n_ranks, topo
