"""The distributed sort-last rendering pipeline (SURVEY.md §7 steps 5-6).

The reference's per-frame chain — per-rank VDI generation, JNI/MPI
``distributeVDIs`` all-to-all of image columns, GPU composite,
``gatherCompositedVDIs`` to rank 0 (DistributedVolumes.kt:683-933 and
:136-139) — collapses here into ONE jitted SPMD function under ``shard_map``:

    generate (local z-slab, halo-exact)
      → lax.all_to_all on the width axis over ICI
      → sort-merge composite of the n received column slices
      → output left sharded by W (the gather is implicit in the output
        sharding; an explicit all_gather is one call away when a host
        needs the full frame)

No postRenderLambda/AtomicInteger interlock machinery survives
(DistributedVolumes.kt:736-796): XLA schedules generation, collective and
composite as one program and overlaps compute with ICI transfers.

Two exchange schedules (``CompositeConfig.exchange``; docs/PERF.md
"Exchange modes"): the default monolithic ``all_to_all`` + N·K-wide
sort-merge above, and a **ring** schedule — each rank keeps its own
column block and the others' fragments circulate over ICI in n-1
``lax.ppermute`` hops, each incoming K-fragment folded into a per-rank
sorted accumulator by the pairwise ordered merge
(ops.composite.merge_vdis_pairwise). The ring needs no N·K bitonic sort,
XLA's async collectives fly the next hop while the current fragment
merges, and with ``ring_slots`` set the per-pixel live state is bounded
at ring_slots + K instead of N·K.

Orthogonally, ``CompositeConfig.wire`` picks the supersegment encoding
that actually crosses ICI in either schedule (docs/PERF.md "Wire
formats"; ops/wire.py): fragments are encoded just before the collective
and decoded right after it — ``f32`` (bit-exact), ``bf16`` (12 B/slot,
2×) or ``qpack8`` (u8 color + u8×2 depth against per-fragment [near,
far] scalars, 6 B/slot, 4×). The merge/composite always runs in f32.

A third axis, ``CompositeConfig.schedule`` (docs/PERF.md "Tile waves"),
sets the GRANULARITY of the whole chain: ``"frame"`` runs one march →
one exchange → one composite per frame (exchange time adds serially to
march time), while ``"waves"`` makes the column block (tile) the unit of
march, exchange, composite and delivery — each rank marches one
column-block wave at a time (`ops.slicer.wave_camera` slices the virtual
camera's u grid; the frame's one `permute_volume` copy and occupancy
pyramid are shared by every wave) and, while wave w+1 marches, wave w's
fragments circulate and fold: a software-pipelined ``lax.scan`` over
waves holds the previous wave's fragments in a double-buffered carry
slot, so XLA schedules the collective (ring ``ppermute`` chain or
per-wave ``all_to_all``, per ``exchange``) concurrently with the next
wave's resampling matmuls inside ONE compiled step. Lossless waves are
parity-exact with the frame schedule (same per-pixel fragments, same
merge order), and the per-wave outputs land in the same W-sharded layout
— plus the session can deliver finished column blocks to subscribers
before the frame closes (runtime/session.py tile sinks).

A fourth axis, ``CompositeConfig.temporal_reuse = "ranges"``
(docs/PERF.md "Temporal deltas"), exploits coherence across FRAMES: the
MXU step carries each rank's previous marched fragment plus a dirty
signature (the occupancy pyramid's value ranges + the camera pose —
ops/delta.py) and skips the march entirely (``lax.cond``) on ranks
whose signature moved at most ``delta.range_tol``; the exchange +
composite are unchanged and still run every frame. The carried state
threads through the step signature exactly like the temporal threshold
maps (seed with `distributed_initial_reuse_mxu`).

The SIM decomposition is 1-D over the volume z axis with one-voxel halo
exchange, making distributed trilinear sampling seam-exact vs a
single-device render (tests assert PSNR, test_parallel.py). The RENDER
decomposition defaults to the same even z-slabs, but
``CompositeConfig.rebalance = "occupancy"`` (docs/PERF.md "Render
rebalancing") decouples it: each rank marches a PLANNED contiguous
z-slice band (``ops/occupancy.slice_plan`` equalizes the occupancy
pyramid's per-z live work; ``parallel/mesh.reslab_z`` materializes the
band from the even shards with the identical halo contract), so on
skewed scenes no rank marches air while another straggles — the
sort-last composite is invariant to which rank rendered which region,
and sampling is decomposition-invariant by construction (the MXU slice
ladder and the gather engine's global sample box), so a rebalanced
frame equals the even frame (tests/test_rebalance.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scenery_insitu_tpu.config import (CompositeConfig, RenderConfig,
                                       VDIConfig)
from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.obs.profiler import phase as _phase
from scenery_insitu_tpu.ops.composite import composite_plain, composite_vdis
from scenery_insitu_tpu.ops.raycast import raycast
from scenery_insitu_tpu.ops.vdi_gen import generate_vdi
from scenery_insitu_tpu.parallel.mesh import (halo_exchange_z,
                                              reslab_bricks,
                                              reslab_bricks_lod, reslab_z)
from scenery_insitu_tpu.parallel.topology import resolve_mesh_topology

from scenery_insitu_tpu.utils.compat import shard_map


def _plan_rank_band(plan: tuple, axis_name: str):
    """Traced (band start, band depth) of this rank under a render plan
    (a static tuple of per-rank z-slice counts — docs/PERF.md "Render
    rebalancing"); helpers below pair it with `mesh.reslab_z`."""
    import numpy as np
    r = jax.lax.axis_index(axis_name)
    starts = np.concatenate([[0], np.cumsum(plan)])[:len(plan)]
    g0 = jnp.asarray(starts, jnp.int32)[r].astype(jnp.float32)
    p_r = jnp.asarray(plan, jnp.int32)[r].astype(jnp.float32)
    return g0, p_r


def _local_volume_and_clip(local_data: jnp.ndarray, origin: jnp.ndarray,
                           spacing: jnp.ndarray, d_global: int,
                           axis_name: str, plan=None
                           ) -> Tuple[Volume, jnp.ndarray, jnp.ndarray]:
    """Build this rank's halo-padded Volume and its exclusive clip AABB.

    ``plan`` switches the RENDER decomposition from the even z-slab to
    this rank's planned contiguous band (docs/PERF.md "Render
    rebalancing"): the volume becomes the `mesh.reslab_z` band (padded
    to the plan's max depth; clip bounds keep padding un-sampled) — the
    clip AABBs still tile the global volume exactly, so the sort-last
    composite is decomposition-invariant."""
    r = jax.lax.axis_index(axis_name)
    dn = local_data.shape[0]
    dz = spacing[2]
    if plan is None:
        with _phase("halo"):
            halo = halo_exchange_z(local_data, axis_name)  # [Dn+2, H, W]
        local_origin = origin.at[2].add((r * dn - 1) * dz)
        z_lo = origin[2] + r * dn * dz
        z_hi = origin[2] + (r + 1) * dn * dz
    else:
        with _phase("halo"):
            halo = reslab_z(local_data, plan, axis_name)   # [Pmax+2, H, W]
        g0, p_r = _plan_rank_band(plan, axis_name)
        local_origin = origin.at[2].add((g0 - 1) * dz)
        z_lo = origin[2] + g0 * dz
        z_hi = origin[2] + (g0 + p_r) * dz
    vol = Volume(halo, local_origin, spacing)
    h, w = local_data.shape[1], local_data.shape[2]
    gmax = origin + jnp.array([w, h, d_global], jnp.float32) * spacing
    clip_min = jnp.stack([origin[0], origin[1], z_lo])
    clip_max = jnp.stack([gmax[0], gmax[1], z_hi])
    # the GLOBAL box: rays ladder their samples against it so sample
    # positions are identical on every rank and under every render plan
    return vol, clip_min, clip_max, origin, gmax


def _exchange_columns(x: jnp.ndarray, n: int, axis_name: str) -> jnp.ndarray:
    """Sort-last column exchange: split trailing W axis into n blocks, block
    j goes to rank j; returns [n, ..., W/n] where the leading axis indexes
    the source rank (≅ distributeVDIs' MPI all-to-all with
    sizePerProcess = H*W*K*4/commSize, DistributedVolumes.kt:860-861)."""
    w = x.shape[-1]
    parts = jnp.moveaxis(x.reshape(x.shape[:-1] + (n, w // n)), -2, 0)
    with _phase("exchange"):
        return jax.lax.all_to_all(parts, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)


def _column_blocks(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Split the trailing W axis into n blocks → [n, ..., W/n]; block j is
    the columns rank j composites (the pre-collective half of
    `_exchange_columns`, reused by the ring schedule which ships blocks
    one hop at a time instead of all at once)."""
    w = x.shape[-1]
    return jnp.moveaxis(x.reshape(x.shape[:-1] + (n, w // n)), -2, 0)


def _take_block(blocks: jnp.ndarray, j) -> jnp.ndarray:
    """blocks[j] for a traced rank index j."""
    return jax.lax.dynamic_index_in_dim(blocks, j, axis=0, keepdims=False)


def _encoded_all_to_all(a: jnp.ndarray, b: jnp.ndarray, n: int,
                        axis_name: str, encode, decode):
    """Wire-aware all_to_all column exchange (docs/PERF.md "Wire
    formats"): ``encode`` the pair before the collective, ``decode``
    after it, so only the narrow encoding crosses ICI. The per-fragment
    scale (qpack8) has no W axis to split — it rides an ``all_gather``
    so every rank decodes each source fragment against its SENDER's
    normalization ([n, 2], row order == all_to_all's source order)."""
    with _phase("wire_encode"):
        enc_a, enc_b, scale = encode(a, b)
    ra = _exchange_columns(enc_a, n, axis_name)
    rb = _exchange_columns(enc_b, n, axis_name)
    scales = (jax.lax.all_gather(scale, axis_name)
              if scale is not None else None)
    with _phase("wire_encode"):
        return decode(ra, rb, scales)


def _exchange_vdi_columns(color: jnp.ndarray, depth: jnp.ndarray,
                          n: int, axis_name: str, wire: str):
    """All_to_all column exchange of a VDI fragment under
    ``CompositeConfig.wire``. ``wire == "f32"`` is exactly the pre-wire
    exchange. Returns f32 ([n, K, 4, H, W/n], [n, K, 2, H, W/n]) with
    the leading axis indexing the source rank."""
    if wire == "f32":
        return (_exchange_columns(color, n, axis_name),
                _exchange_columns(depth, n, axis_name))
    from scenery_insitu_tpu.ops import wire as _wire

    return _encoded_all_to_all(
        color, depth, n, axis_name,
        lambda c, d: _wire.encode_fragment(c, d, wire),
        lambda c, d, s: _wire.decode_fragment(c, d, s, wire))


def _ring_exchange_composite(color: jnp.ndarray, depth: jnp.ndarray,
                             n: int, axis_name: str, cfg,
                             gap_eps: float = 1e-4):
    """Ring-pipelined sort-last compositing (CompositeConfig.exchange ==
    "ring"): this rank keeps its own column block; at hop s = 1..n-1 every
    rank ppermutes ONE K-fragment (its block for rank r-s) so rank r
    receives rank (r+s)'s fragment of ITS columns, and merges it into a
    per-pixel sorted accumulator with the pairwise ordered merge — XLA's
    async collectives let hop s+1 fly while fragment s merges, hiding ICI
    latency behind merge compute. The final accumulator is re-segmented by
    the SAME fold the all_to_all path runs after its global sort
    (ops.composite.resegment_stream), so lossless ring (ring_slots=0)
    output matches the all_to_all composite exactly; ring_slots > 0 caps
    the accumulator (bounded memory, farthest segments dropped on overfull
    pixels).

    Tie order among exactly-equal start depths follows arrival order
    (r, r+1, ... wrapping) instead of the all_to_all path's rank order —
    only observable for bit-identical live start depths, since empty
    slots' payloads are masked identically in both paths.
    """
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.ops.composite import (modeled_exchange_traffic,
                                                  resegment_stream,
                                                  sort_stream)

    k = color.shape[0]
    h, w = color.shape[-2], color.shape[-1]
    cap = _ring_cap(cfg, k)

    # host-side build markers (this runs at trace time, once per compiled
    # step): the per-hop events give the trace one entry per ring step
    # with the modeled fragment bytes the hop moves
    rec = _obs.get_recorder()
    rec.count("ring_exchange_builds")
    rec.event("ring_exchange_build", ranks=n, k=k,
              slots=(cap or n * k), wire=cfg.wire,
              traffic=modeled_exchange_traffic(
                  n, k, h, w, k_out=cfg.max_output_supersegments,
                  mode="ring", ring_slots=cfg.ring_slots, wire=cfg.wire))

    # one K-wide per-pixel sort + stale-color mask of the LOCAL fragment
    # replaces the all_to_all path's N·K-wide post-exchange sort (the VDI
    # convention already promises front-to-back live slots; the sort makes
    # the merge's sorted-input precondition unconditional)
    with _phase("merge"):
        color, depth = sort_stream(color, depth)
    acc_c, acc_d = _ring_accumulate(color, depth, n, axis_name, cfg.wire,
                                    cap)
    with _phase("resegment"):
        return resegment_stream(acc_c, acc_d, cfg, gap_eps)


def _ring_cap(cfg, k: int):
    """Validated per-pixel accumulator cap of a ring merge (None =
    lossless): ring_slots must at least hold one incoming fragment."""
    cap = int(cfg.ring_slots) or None
    if cap is not None and cap < k:
        raise ValueError(
            f"ring_slots={cap} is below the per-rank fragment size K={k} "
            f"— the accumulator could not even hold one incoming fragment "
            f"(use 0 for lossless, or >= K, e.g. 2*K)")
    return cap


def _ring_accumulate(color: jnp.ndarray, depth: jnp.ndarray, n: int,
                     axis_name, wire: str, cap,
                     hop_counter: str = "ring_steps_built",
                     hop_event: str = "ring_step",
                     hop_scope: str = "exchange"):
    """The pipelined ring-merge core, shared by the single-level ring
    exchange above and the hierarchical composite's inter-domain (DCN)
    hop (parallel/hier.py): circulate each rank's column blocks of a
    per-pixel SORTED, empty-masked fragment ``[K, ...]`` around the
    ``n``-rank ``axis_name`` ring in n-1 ``ppermute`` hops, folding each
    arrival into a per-rank sorted accumulator with the pairwise ordered
    merge. Returns this rank's 1/n column-block accumulator (NOT
    re-segmented — callers resegment once at the top of their exchange).

    Wire encode runs ONCE on the local fragment; every hop ships the
    narrow encoding and decodes on receive (docs/PERF.md "Wire
    formats"). The own block round-trips the codec too, so the
    accumulator sees the same quantization whichever schedule ran —
    and the quantizers are monotone, so the pre-sorted stream decodes
    sorted (the pairwise-merge precondition). f32 inserts zero ops."""
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.ops import wire as _wire
    from scenery_insitu_tpu.ops.composite import merge_vdis_pairwise

    rec = _obs.get_recorder()
    if wire == "f32":
        enc_c, enc_d, scale = color, depth, None
    else:
        with _phase("wire_encode"):
            enc_c, enc_d, scale = _wire.encode_fragment(color, depth,
                                                        wire)

    def dec(c, d, sc):
        return _wire.decode_fragment(c, d, sc, wire)

    blk_c = _column_blocks(enc_c, n)                  # [n, K, ..., H, W/n]
    blk_d = _column_blocks(enc_d, n)
    r = jax.lax.axis_index(axis_name)
    acc_c, acc_d = dec(_take_block(blk_c, r), _take_block(blk_d, r), scale)
    frag_bytes = (blk_c.size * blk_c.dtype.itemsize
                  + blk_d.size * blk_d.dtype.itemsize) // n
    for s in range(1, n):
        # rank i ships its block for rank i-s; receiver r hears from r+s
        perm = [(i, (i - s) % n) for i in range(n)]
        send_c = _take_block(blk_c, jnp.mod(r - s, n))
        send_d = _take_block(blk_d, jnp.mod(r - s, n))
        with _phase(hop_scope):
            recv_c = jax.lax.ppermute(send_c, axis_name, perm)
            recv_d = jax.lax.ppermute(send_d, axis_name, perm)
            recv_s = (jax.lax.ppermute(scale, axis_name, perm)
                      if scale is not None else None)
        rec.count(hop_counter)
        rec.event(hop_event, step=s, hops=s, frag_bytes=frag_bytes,
                  wire=wire)
        with _phase("merge"):
            mc, md = dec(recv_c, recv_d, recv_s)
            acc_c, acc_d = merge_vdis_pairwise(acc_c, acc_d, mc, md,
                                               k_cap=cap)
    return acc_c, acc_d


def _composite_exchanged(color: jnp.ndarray, depth: jnp.ndarray,
                         n: int, axis_name: str, comp_cfg, topo=None):
    """Sort-last exchange + composite under the configured schedule
    (CompositeConfig.exchange). Runs inside shard_map; returns the
    composited VDI of this rank's column block. n == 1 always takes the
    all_to_all path (both schedules are the identity exchange there, and
    it keeps the single-VDI fast path of `composite_vdis`). ``topo``
    (a parallel/topology.Topology) switches to the TWO-LEVEL composite:
    intra-domain exchange over the ranks sub-axis (ICI), inter-domain
    merge over the hosts sub-axis (DCN), re-segmented once at the top
    (parallel/hier.py) — parity-gated against this flat path."""
    if topo is not None:
        from scenery_insitu_tpu.parallel.hier import hier_composite_vdi

        return hier_composite_vdi(color, depth, topo, comp_cfg)
    if comp_cfg.exchange == "ring" and n > 1:
        return _ring_exchange_composite(color, depth, n, axis_name,
                                        comp_cfg)
    colors, depths = _exchange_vdi_columns(color, depth, n, axis_name,
                                           comp_cfg.wire)
    with _phase("merge"):
        return composite_vdis(colors, depths, comp_cfg)


# ------------------------------------------------------------- tile waves


def _wave_pipeline(n_waves: int, march_wave, compose, carry0=None):
    """Software-pipelined scan over tile waves (docs/PERF.md "Tile
    waves"): iteration w exchanges+composites wave w-1's fragments (held
    in the double-buffered carry slot) while marching wave w — the two
    are data-independent inside one scan body, so XLA overlaps the
    collective with the next wave's march.

    ``march_wave(w, carry) -> (fragments, carry')`` produces wave ``w``'s
    pre-exchange fragments (any pytree) plus carried per-wave state (the
    temporal threshold maps; None when stateless). ``compose(fragments)
    -> out`` runs the exchange + composite of one wave. Returns (outs
    stacked on a leading wave axis, final carry). The prologue marches
    wave 0 and the epilogue composites wave T-1, so every wave is
    composited exactly once."""
    with _phase("wave"):
        frag, carry = march_wave(jnp.int32(0), carry0)

    def body(c, w):
        fr, cr = c
        out = compose(fr)                  # wave w-1 circulates ...
        with _phase("wave"):
            fr2, cr = march_wave(w, cr)    # ... while wave w marches
        return (fr2, cr), out

    (frag, carry), outs = jax.lax.scan(body, (frag, carry),
                                       jnp.arange(1, n_waves))
    last = compose(frag)
    outs = jax.tree_util.tree_map(
        lambda s, l: jnp.concatenate([s, l[None]], axis=0), outs, last)
    return outs, carry


def _wave_assemble(x: jnp.ndarray) -> jnp.ndarray:
    """[T, ..., wb] per-wave tiles -> [..., T*wb]: wave w's tile is the
    w-th sub-block of this rank's contiguous owned column block, so
    concatenating along waves reproduces EXACTLY the frame schedule's
    output layout (W-sharded, rank blocks contiguous)."""
    t = x.shape[0]
    moved = jnp.moveaxis(x, 0, -2)                    # [..., T, wb]
    return moved.reshape(moved.shape[:-2] + (t * moved.shape[-1],))


def _wave_build_marker(n: int, t: int, k: int, h: int, w: int, k_out: int,
                       exchange: str, ring_slots: int, wire: str,
                       marched: bool) -> None:
    """Host-side trace-time marker of one wave-schedule build
    (docs/OBSERVABILITY.md): counters for the build and its T waves plus
    one event carrying the modeled overlap accounting — what fraction of
    the exchange bytes the pipeline hides behind march compute.
    ``marched=False`` tags the monolithic-march variant (gather/plain
    engines pipeline exchange+composite only)."""
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic

    rec = _obs.get_recorder()
    rec.count("wave_schedule_builds")
    rec.count("wave_steps_built", t)
    rec.event("wave_schedule_build", ranks=n, tiles=t, k=k,
              wave_cols=w // t, tile_cols=w // (n * t),
              march_per_wave=marched,
              traffic=modeled_exchange_traffic(
                  n, k, h, w, k_out=k_out, mode=exchange,
                  ring_slots=ring_slots, wire=wire,
                  schedule="waves", wave_tiles=t))


def _composite_exchanged_waves(color: jnp.ndarray, depth: jnp.ndarray,
                               n: int, axis_name: str, comp_cfg,
                               topo=None) -> VDI:
    """Tile-wave exchange + composite of an ALREADY-generated full-frame
    fragment (the gather-engine waves path — the march was monolithic,
    so the pipeline overlaps each wave's collective with the next wave's
    merge+resegment instead of with march compute). Per wave: slice the
    wave's column blocks, run the frame compositor on them
    (`_composite_exchanged` — ring or all_to_all per ``exchange``), and
    reassemble; per-pixel identical to the frame schedule."""
    from scenery_insitu_tpu.ops import slicer as _slicer

    t = comp_cfg.wave_tiles
    k = color.shape[0]
    h, w = color.shape[-2], color.shape[-1]
    _slicer.wave_block(w, n, t)            # validates the geometry
    _wave_build_marker(n, t, k, h, w, comp_cfg.max_output_supersegments,
                       comp_cfg.exchange, comp_cfg.ring_slots,
                       comp_cfg.wire, marched=False)

    def march(wv, _):
        return (_slicer.wave_cols(color, n, t, wv),
                _slicer.wave_cols(depth, n, t, wv)), None

    def compose(fr):
        out = _composite_exchanged(fr[0], fr[1], n, axis_name, comp_cfg,
                                   topo=topo)
        return out.color, out.depth

    (oc, od), _ = _wave_pipeline(t, march, compose)
    return VDI(_wave_assemble(oc), _wave_assemble(od))


def _composite_exchanged_sched(color: jnp.ndarray, depth: jnp.ndarray,
                               n: int, axis_name: str, comp_cfg,
                               topo=None) -> VDI:
    """Schedule dispatcher of the sort-last exchange + composite
    (CompositeConfig.schedule): "frame" = the monolithic chain above,
    "waves" = the per-column-block-wave scan. A single-rank mesh
    degrades waves -> frame on the ledger — there is no exchange to
    pipeline and the frame path keeps the single-VDI fast path."""
    if comp_cfg.schedule == "waves":
        if n > 1:
            return _composite_exchanged_waves(color, depth, n, axis_name,
                                              comp_cfg, topo=topo)
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("composite.schedule", "waves", "frame",
                     "single-rank mesh has no exchange to pipeline",
                     warn=False)
    return _composite_exchanged(color, depth, n, axis_name, comp_cfg,
                                topo=topo)


def _resolve_waves(comp_cfg, n: int, width: int, slicer_mod=None) -> bool:
    """Build-time resolution of CompositeConfig.schedule for a step
    builder: True = run the tile-wave path (validating that ``width``
    splits into ranks * wave_tiles blocks — a bad geometry fails at
    build, not trace), False = frame path. A waves request on a
    single-rank mesh lands on the ledger (nothing to pipeline)."""
    if comp_cfg.schedule != "waves":
        return False
    if n == 1:
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("composite.schedule", "waves", "frame",
                     "single-rank mesh has no exchange to pipeline",
                     warn=False)
        return False
    if slicer_mod is None:
        from scenery_insitu_tpu.ops import slicer as slicer_mod
    slicer_mod.wave_block(width, n, comp_cfg.wave_tiles)
    return True


def _resolve_reuse(comp_cfg, supported: bool = True,
                   where: str = "") -> bool:
    """Build-time resolution of CompositeConfig.temporal_reuse for a
    step builder (docs/PERF.md "Temporal deltas"): True = thread the
    carried ReuseState through the step signature. Builders with no
    marched VDI fragment to carry (gather engine, hybrid, plain) ledger
    the configured-but-inert knob instead of silently ignoring it."""
    if comp_cfg is None or comp_cfg.temporal_reuse != "ranges":
        return False
    if not supported:
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("delta.reuse", "ranges", "off",
                     f"{where} carries no reusable VDI fragment "
                     "(temporal_reuse is an MXU VDI step feature)",
                     warn=False)
        return False
    from scenery_insitu_tpu import obs as _obs

    rec = _obs.get_recorder()
    rec.count("reuse_steps_built")
    return True


def _reuse_state_spec(axis):
    """Sharding spec of the distributed ReuseState: per-rank leaves
    stack along their leading axis (the thr-state convention) — sig [S]
    → [n*S], fragments [K, ...] → [n*K, ...], valid/dirty [1] → [n]."""
    from scenery_insitu_tpu.ops.delta import ReuseState

    return ReuseState(sig=P(axis), color=P(axis, None, None, None),
                      depth=P(axis, None, None, None),
                      valid=P(axis), dirty=P(axis))


def distributed_initial_reuse_mxu(mesh: Mesh, tf: TransferFunction,
                                  spec,
                                  vdi_cfg: Optional[VDIConfig] = None,
                                  comp_cfg: Optional[CompositeConfig]
                                  = None,
                                  axis_name: Optional[str] = None,
                                  plan=None):
    """Jitted seeder for ``temporal_reuse = "ranges"`` steps: returns
    ``f(vol_data (z-sharded), origin, spacing, cam) -> ReuseState`` with
    ``valid = 0`` everywhere, so the first real frame marches and fills
    the carry (the `distributed_initial_threshold_mxu` pattern). The
    per-rank signature length comes out of the same frame-state prelude
    the step runs, so the shapes can never disagree."""
    from scenery_insitu_tpu.ops import delta as _delta

    vdi_cfg = vdi_cfg or VDIConfig()
    comp_cfg = comp_cfg or CompositeConfig()
    # hierarchical meshes seed over the flat axis view (the carry is
    # per-rank state; the composite levels never see it)
    axis, n, _ = resolve_mesh_topology(mesh, axis_name)
    plan = _resolve_plan(comp_cfg, n, plan)

    def seed(local_data, origin, spacing, cam: Camera):
        # comp_cfg=None: the seed needs only the pyramid's SHAPE — no
        # K-budget psum, no budget ledger rows
        _, _, _, _, _, pyr, _ = _rank_frame_state(
            local_data, origin, spacing, spec, tf, vdi_cfg, axis, n,
            None, plan=plan, need_pyramid=True)
        sig = _delta.reuse_signature(pyr, cam)
        return _delta.init_reuse_like(sig, vdi_cfg.max_supersegments,
                                      spec.nj, spec.ni)

    f = shard_map(seed, mesh=mesh,
                  in_specs=(P(axis, None, None), P(), P(), P()),
                  out_specs=_reuse_state_spec(axis), check_vma=False)
    return jax.jit(f)


def _rebalance_build_marker(plan, n: int) -> None:
    """Host-side trace-time marker of one rebalanced-step build
    (docs/OBSERVABILITY.md): counts the build and records the plan's
    shape — the slice histogram and the pad overhead every rank pays for
    static SPMD shapes (max(plan)/mean(plan) - 1)."""
    from scenery_insitu_tpu import obs as _obs

    rec = _obs.get_recorder()
    rec.count("rebalance_steps_built")
    rec.event("rebalance_build", ranks=n, plan=list(plan),
              max_depth=int(max(plan)), min_depth=int(min(plan)),
              pad_overhead=round(
                  int(max(plan)) * n / float(sum(plan)) - 1.0, 4))


def _resolve_plan(comp_cfg, n: int, plan, min_halo: int = 1):
    """Build-time resolution of a render z-plan for a step builder
    (CompositeConfig.rebalance; docs/PERF.md "Render rebalancing").
    Returns the validated static plan tuple, or None for the even
    fast path: ``plan=None`` (no plan computed yet — the session passes
    one once live fractions are known) and the literal even plan both
    take the even-slab path — no reslab shuffle, no band padding, no
    ownership masks beyond the pre-existing ``v_bounds``. (Note the
    gather engine's SAMPLING semantics changed with this feature for
    every decomposition, even splits included: its t ladder now derives
    from the global box so sample positions match a single-device
    render — see ops/vdi_gen.generate_vdi and docs/PERF.md "Render
    rebalancing".) A plan without ``rebalance="occupancy"`` is a caller
    bug, not a silent ignore."""
    if plan is None:
        return None
    if comp_cfg is None:
        rebalance = "even"
    elif isinstance(comp_cfg, str):
        rebalance = comp_cfg
    else:
        rebalance = comp_cfg.rebalance
    if rebalance != "occupancy":
        raise ValueError(
            f"a render plan was passed but rebalance={rebalance!r} — "
            f"plans are the mechanism of rebalance='occupancy'")
    from scenery_insitu_tpu.parallel.mesh import validate_plan

    plan = validate_plan(plan, n, h=min_halo)
    if n == 1 or all(p == plan[0] for p in plan):
        return None
    _rebalance_build_marker(plan, n)
    return plan


def _bricks_build_marker(bmap, n: int) -> None:
    """Host-side trace-time marker of one brick-partitioned step build
    (docs/OBSERVABILITY.md): the brick grid, the padded slot count every
    rank marches, and the ownership histogram."""
    from scenery_insitu_tpu import obs as _obs

    counts = [len(bmap.rank_bricks(r)) for r in range(n)]
    rec = _obs.get_recorder()
    rec.count("bricks_steps_built")
    rec.event("bricks_build", ranks=n, nbricks=bmap.nbricks,
              brick_depth=bmap.brick_depth, slots=bmap.slots,
              owner=list(bmap.owner), bricks_per_rank=counts,
              level=list(bmap.level), max_level=bmap.max_level,
              total_slots=bmap.total_slots)


def _resolve_bricks(comp_cfg, n: int, bricks):
    """Build-time resolution of a brick→rank render partition for a
    step builder (CompositeConfig.rebalance == "bricks";
    docs/SCENARIOS.md "Brick maps"). Returns the validated
    `parallel.bricks.BrickMap`, or None for the slab fast path: no map,
    a single-rank mesh (every map is the whole volume there), or the
    even-convex map — which short-circuits BITWISE to the pre-brick
    path (the composite-invariance anchor). A map without
    ``rebalance="bricks"`` is a caller bug, not a silent ignore."""
    if bricks is None:
        return None
    from scenery_insitu_tpu.parallel.bricks import BrickMap

    if not isinstance(bricks, BrickMap):
        raise TypeError(f"bricks= takes a parallel.bricks.BrickMap, got "
                        f"{type(bricks).__name__}")
    if comp_cfg is None:
        rebalance = "even"
    elif isinstance(comp_cfg, str):
        rebalance = comp_cfg
    else:
        rebalance = comp_cfg.rebalance
    if rebalance != "bricks":
        raise ValueError(
            f"a brick map was passed but rebalance={rebalance!r} — brick "
            f"partitions are the mechanism of rebalance='bricks'")
    if bricks.n_ranks != n:
        raise ValueError(f"brick map built for {bricks.n_ranks} ranks on "
                         f"a {n}-rank mesh")
    # a single-rank mesh only short-circuits when every brick is level 0
    # — a coarse level still changes WHAT is marched, not just where
    if (n == 1 and bricks.max_level == 0) or bricks.is_even_convex():
        return None
    _bricks_build_marker(bricks, n)
    return bricks


def _bricks_inert(bricks, where: str):
    """Builders with no brick march (hybrid, plain, particle layers)
    must say a configured brick partition is inert, not silently render
    the even decomposition."""
    if bricks is None:
        return None
    from scenery_insitu_tpu import obs as _obs

    _obs.degrade("bricks.partition", "bricks", "slabs",
                 f"{where} has no brick march (gather/MXU VDI steps "
                 "only); the even z-slab decomposition renders",
                 warn=False)
    return None


def _brick_units(local_data, origin, spacing, spec, axis, n, bmap):
    """Per-brick march units of this rank under a BrickMap — the brick
    generalization of `_rank_slab` (docs/SCENARIOS.md "Brick maps").

    Materializes the rank's brick set ONCE (`mesh.reslab_bricks`, halo
    rows from the TRUE global neighbors whichever rank owns them) and
    returns ``([(vol, v_bounds, w_bounds, f)] * total_slots, gmax, dims,
    ref)`` — one unit per brick slot, each a `_rank_slab`-shaped
    (volume, ownership bounds) pair the existing per-chunk march
    consumes unchanged: z marches own their brick through the
    ``w_bounds`` world interval, x/y marches through the ``v_bounds``
    half-open interval (the brick owning the global top keeps the even
    path's +dz edge slack). Absent slots (rank owns fewer bricks than
    the busiest) carry zero rows and an EMPTY interval — every sample
    masks dead, the occupancy pyramid admits them as dead, and the
    fragment comes out all-+inf.

    LOD (docs/PERF.md "LOD marching"): when the map carries levels,
    slots group BY LEVEL (`mesh.reslab_bricks_lod` — global per-level
    slot counts keep SPMD shapes rank-uniform) and a level-l unit is the
    2^l reshape-mean-pooled brick as a Volume with spacing*2^l at the
    SAME corner origin, marched on the shared fine-pitch camera with
    ``dwm*2^l`` / ``step_scale=2^-l`` so coarse slices accumulate the
    opacity of the fine slices they replace. Ownership bounds stay the
    FINE brick world interval — the composited fragment stream is
    resolution-agnostic. ``f`` is the unit's downsample factor (1 for
    level 0); ``ref`` is the fine-pitch reference Volume for the shared
    camera/metadata (the all-level-0 path returns the existing units +
    f=1 + ref=units[0] — BITWISE the pre-LOD build)."""
    if getattr(spec, "render_dtype", "f32") == "bf16" \
            and local_data.dtype == jnp.float32:
        local_data = local_data.astype(jnp.bfloat16)
    r = jax.lax.axis_index(axis)
    dn = local_data.shape[0]
    h, w = local_data.shape[1], local_data.shape[2]
    d = dn * n
    dz = spacing[2]
    gmax = origin + jnp.array([w, h, d], jnp.float32) * spacing
    bz = bmap.brick_depth
    z_march = spec.axis == 2
    units = []
    if bmap.max_level == 0:
        table = jnp.asarray(bmap.start_table(), jnp.int32)  # [n, B]
        with _phase("halo"):
            bands = reslab_bricks(local_data, bmap, axis,
                                  h=0 if z_march else 1)
        for s in range(bmap.slots):
            start = table[r, s]                            # -1 = absent
            present = start >= 0
            startf = start.astype(jnp.float32)
            z_lo = origin[2] + startf * dz
            z_hi = origin[2] + (startf + bz) * dz
            if z_march:
                vol = Volume(bands[s], origin.at[2].add(startf * dz),
                             spacing)
                # open-interval march ownership (slice centers sit half
                # a voxel inside); an absent slot's interval is empty
                wb = (jnp.where(present, z_lo, jnp.inf),
                      jnp.where(present, z_hi, -jnp.inf))
                units.append((vol, None, wb, 1))
            else:
                vol = Volume(bands[s],
                             origin.at[2].add((startf - 1.0) * dz),
                             spacing)
                # the brick covering the global top keeps the even
                # path's +dz slack (its clamped halo row may re-admit
                # pos == max)
                hi = jnp.where(start + bz == d, z_hi + dz, z_hi)
                vb = (jnp.where(present, z_lo, jnp.inf),
                      jnp.where(present, hi, -jnp.inf))
                units.append((vol, vb, None, 1))
        return units, gmax, (w, h, d), units[0][0]
    halo = 0 if z_march else 1
    with _phase("halo"):
        bands = reslab_bricks_lod(local_data, bmap, axis, h=halo)
    for lvl in bmap.levels_present():
        f = 1 << lvl
        arr = bands[lvl]
        table_l = jnp.asarray(bmap.start_table_at(lvl), jnp.int32)
        for s in range(table_l.shape[1]):
            start = table_l[r, s]
            present = start >= 0
            startf = start.astype(jnp.float32)
            z_lo = origin[2] + startf * dz
            z_hi = origin[2] + (startf + bz) * dz
            org = origin.at[2].add((startf - halo * float(f)) * dz)
            vol = Volume(arr[s], org, spacing * float(f))
            if z_march:
                wb = (jnp.where(present, z_lo, jnp.inf),
                      jnp.where(present, z_hi, -jnp.inf))
                units.append((vol, None, wb, f))
            else:
                # coarse top-edge slack scales with the pooled pitch
                # (the clamped halo row spans f fine rows)
                hi = jnp.where(start + bz == d, z_hi + float(f) * dz,
                               z_hi)
                vb = (jnp.where(present, z_lo, jnp.inf),
                      jnp.where(present, hi, -jnp.inf))
                units.append((vol, vb, None, f))
    ref = Volume(jnp.zeros((1, 1, 1), local_data.dtype), origin, spacing)
    return units, gmax, (w, h, d), ref


def _brick_clip_units(local_data, origin, spacing, d_global, axis, bmap):
    """`_local_volume_and_clip`'s brick twin for the gather engine: one
    (volume, clip AABB) per brick slot. The clip AABBs tile the global
    volume exactly like the slab AABBs do (absent slots get an empty
    box), and the sample ladder stays the GLOBAL box — which is what
    makes the composited frame bitwise invariant to ownership.

    The gather engine has no coarse march (its t ladder is global and
    level-free): a level-carrying map renders every brick at level 0
    here, declared on the `lod.engine` ledger — not silently."""
    if bmap.max_level:
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("lod.engine", "lod", "fine",
                     "the gather engine has no LOD march (MXU builders "
                     "only); every brick samples at level 0", warn=False)
    r = jax.lax.axis_index(axis)
    h, w = local_data.shape[1], local_data.shape[2]
    dz = spacing[2]
    gmax = origin + jnp.array([w, h, d_global], jnp.float32) * spacing
    bz = bmap.brick_depth
    table = jnp.asarray(bmap.start_table(), jnp.int32)
    with _phase("halo"):
        bands = reslab_bricks(local_data, bmap, axis, h=1)
    units = []
    for s in range(bmap.slots):
        start = table[r, s]
        present = start >= 0
        startf = start.astype(jnp.float32)
        vol = Volume(bands[s], origin.at[2].add((startf - 1.0) * dz),
                     spacing)
        z_lo = origin[2] + startf * dz
        z_hi = origin[2] + (startf + bz) * dz
        cmin = jnp.stack([origin[0], origin[1],
                          jnp.where(present, z_lo, jnp.inf)])
        cmax = jnp.stack([gmax[0], gmax[1],
                          jnp.where(present, z_hi, -jnp.inf)])
        units.append((vol, cmin, cmax))
    return units, gmax


def _thr_slot(thr, s: int, nj: int):
    """Brick slot ``s``'s [nj, ni] threshold maps out of the row-stacked
    per-rank state (slots stack along rows, ranks along the mesh axis —
    the `_thr_state_spec` sharding is unchanged)."""
    import jax.tree_util as jtu

    return jtu.tree_map(lambda m: m[s * nj:(s + 1) * nj], thr)


def _stack_thr(states):
    import jax.tree_util as jtu

    return jtu.tree_map(lambda *xs: jnp.concatenate(xs, axis=0), *states)


def _mxu_rank_generate_bricks(local_data, origin, spacing, cam, slicer,
                              spec, tf, vdi_cfg, axis, n, bmap,
                              threshold=None):
    """Per-rank brick-set VDI generation on the MXU engine: march each
    brick slot through the existing per-chunk machinery (per-brick
    ownership bounds, per-brick occupancy pyramid) and CONCATENATE the
    K-slot fragments into one ``[slots*K]`` pre-exchange stream — the
    downstream exchange + composite sort per pixel anyway
    (`sort_stream` / the ring's unconditional local sort), so
    interleaved per-brick depth ranges need no pre-merge. Every brick's
    fragment depends only on the brick, the camera and the field —
    never on which rank marched it — which is the composite-invariance
    argument (tests/test_bricks.py). Temporal mode carries one
    [nj, ni] threshold map set PER SLOT, row-stacked.

    Returns (vdi [total_slots*K], meta, axcam, thr'). Coarse slots (LOD
    maps, docs/PERF.md "LOD marching") march on the shared fine-pitch
    camera with per-unit ``dwm*f`` / ``step_scale=1/f`` — the f==1 path
    is bitwise the pre-LOD build (``axc is axcam``, default scale)."""
    units, gmax, dims, ref = _brick_units(local_data, origin, spacing,
                                          spec, axis, n, bmap)
    axcam = slicer.make_axis_camera(ref, cam, spec,
                                    box_min=origin, box_max=gmax)
    nj = spec.nj
    colors, depths, thr2s = [], [], []
    for s, (vol, vb, wb, f) in enumerate(units):
        axc = axcam if f == 1 else axcam._replace(dwm=axcam.dwm * f)
        with _phase("march"):
            if threshold is None:
                vdi, _, _ = slicer.generate_vdi_mxu(
                    vol, tf, cam, spec, vdi_cfg, v_bounds=vb,
                    w_bounds=wb, axcam=axc, step_scale=1.0 / f)
            else:
                vdi, _, _, t2 = slicer.generate_vdi_mxu_temporal(
                    vol, tf, cam, spec, _thr_slot(threshold, s, nj),
                    vdi_cfg, v_bounds=vb, w_bounds=wb, axcam=axc,
                    step_scale=1.0 / f)
                thr2s.append(t2)
        colors.append(vdi.color)
        depths.append(vdi.depth)
    thr2 = _stack_thr(thr2s) if thr2s else None
    meta = slicer._vdi_meta(ref, axcam, spec.ni, spec.nj, 0)
    meta = meta._replace(volume_dims=jnp.array(dims, jnp.float32))
    return (VDI(jnp.concatenate(colors, axis=0),
                jnp.concatenate(depths, axis=0)), meta, axcam, thr2)


def _mxu_rank_generate_bricks_waves(local_data, origin, spacing, cam,
                                    slicer, spec, tf, vdi_cfg, comp_cfg,
                                    axis, n, bmap, threshold=None,
                                    topo=None):
    """Tile-wave twin of `_mxu_rank_generate_bricks`: per wave, march
    every brick slot on the wave camera's column block and concatenate
    the slot fragments into that wave's ``[slots*K]`` pre-exchange
    stream; wave w's fragments circulate while wave w+1 marches exactly
    like the slab path. Per-slot permuted copies and occupancy pyramids
    are built once per frame and shared by every wave."""
    import jax.tree_util as jtu

    from scenery_insitu_tpu.ops import occupancy as _occ

    units, gmax, dims, ref = _brick_units(local_data, origin, spacing,
                                          spec, axis, n, bmap)
    t = comp_cfg.wave_tiles
    slicer.wave_block(spec.ni, n, t)
    axcam = slicer.make_axis_camera(ref, cam, spec,
                                    box_min=origin, box_max=gmax)
    volps = [slicer.permute_volume(vol, spec) for vol, _, _, _ in units]
    pyrs = [(_occ.pyramid_from_volume(vol, tf, spec, volp=vp)
             if spec.skip_empty else None)
            for (vol, _, _, _), vp in zip(units, volps)]
    _wave_build_marker(n, t, len(units) * vdi_cfg.max_supersegments,
                       spec.nj, spec.ni,
                       comp_cfg.max_output_supersegments,
                       comp_cfg.exchange, comp_cfg.ring_slots,
                       comp_cfg.wire, marched=True)
    nj = spec.nj

    def march_wave(w, thr_full):
        axcam_w, spec_w = slicer.wave_camera(axcam, spec, n, t, w)
        cs, ds, t2s = [], [], []
        for s, (vol, vb, wb, f) in enumerate(units):
            axc = (axcam_w if f == 1
                   else axcam_w._replace(dwm=axcam_w.dwm * f))
            thr_s = (None if thr_full is None else
                     jtu.tree_map(lambda m: slicer.wave_cols(m, n, t, w),
                                  _thr_slot(thr_full, s, nj)))
            with _phase("march"):
                if thr_s is None:
                    vdi, _, _ = slicer.generate_vdi_mxu(
                        vol, tf, cam, spec_w, vdi_cfg, v_bounds=vb,
                        w_bounds=wb, occupancy=pyrs[s], axcam=axc,
                        volp=volps[s], step_scale=1.0 / f)
                else:
                    vdi, _, _, t2 = slicer.generate_vdi_mxu_temporal(
                        vol, tf, cam, spec_w, thr_s, vdi_cfg,
                        v_bounds=vb, w_bounds=wb, occupancy=pyrs[s],
                        axcam=axc, volp=volps[s], step_scale=1.0 / f)
                    t2s.append(t2)
            cs.append(vdi.color)
            ds.append(vdi.depth)
        if thr_full is not None:
            parts = [jtu.tree_map(
                lambda m, mw: slicer.wave_update_cols(m, mw, n, t, w),
                _thr_slot(thr_full, s, nj), t2s[s])
                for s in range(len(units))]
            thr_full = _stack_thr(parts)
        return (jnp.concatenate(cs, axis=0),
                jnp.concatenate(ds, axis=0)), thr_full

    def compose(fr):
        out = _composite_exchanged(fr[0], fr[1], n, axis, comp_cfg,
                                   topo=topo)
        return out.color, out.depth

    (oc, od), thr2 = _wave_pipeline(t, march_wave, compose, threshold)
    vdi = VDI(_wave_assemble(oc), _wave_assemble(od))
    meta = slicer._vdi_meta(ref, axcam, spec.ni, spec.nj, 0)
    meta = meta._replace(volume_dims=jnp.array(dims, jnp.float32))
    return vdi, meta, axcam, thr2


def _ring_exchange_plain(image: jnp.ndarray, depth: jnp.ndarray,
                         n: int, axis_name: str, wire: str = "f32",
                         hop_counter: str = "ring_steps_built",
                         build_counter: str = "ring_exchange_builds",
                         hop_scope: str = "exchange"):
    """Ring schedule for the plain-image exchange: n-1 single-fragment
    ppermute hops (pipelined like the VDI ring), then the stacked
    fragments are rolled back into SOURCE-RANK order so the downstream
    `composite_plain` sees the exact [n, ...] layout the all_to_all
    delivers — bitwise-identical output at ``wire="f32"``. Plain
    fragments are one RGBA+depth per pixel, so there is no N·K working
    set to cap; the win is the pipelined exchange, and a quantized wire
    (docs/PERF.md "Wire formats") shrinks what each hop moves — hops ship
    the encoding and decode on receive. Returns (images [n, 4, H, W/n],
    depths [n, H, W/n])."""
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.ops import wire as _wire

    if wire == "f32":
        enc_i, enc_d, scale = image, depth, None
    else:
        with _phase("wire_encode"):
            enc_i, enc_d, scale = _wire.encode_plain(image, depth, wire)

    def dec(i, d, sc):
        return _wire.decode_plain(i, d, sc, wire)

    blk_i = _column_blocks(enc_i, n)                  # [n, ..., H, W/n]
    blk_d = _column_blocks(enc_d, n)                  # [n, H, W/n]
    r = jax.lax.axis_index(axis_name)
    rec = _obs.get_recorder()
    rec.count(build_counter)
    own_i, own_d = dec(_take_block(blk_i, r), _take_block(blk_d, r), scale)
    frags_i = [own_i]
    frags_d = [own_d]
    for s in range(1, n):
        perm = [(i, (i - s) % n) for i in range(n)]
        with _phase(hop_scope):
            recv_i = jax.lax.ppermute(
                _take_block(blk_i, jnp.mod(r - s, n)), axis_name, perm)
            recv_d = jax.lax.ppermute(
                _take_block(blk_d, jnp.mod(r - s, n)), axis_name, perm)
            recv_s = (jax.lax.ppermute(scale, axis_name, perm)
                      if scale is not None else None)
        with _phase("wire_encode"):
            di, dd = dec(recv_i, recv_d, recv_s)
        frags_i.append(di)
        frags_d.append(dd)
        rec.count(hop_counter)
    stacked_i = jnp.stack(frags_i)          # arrival order: r, r+1, ...
    stacked_d = jnp.stack(frags_d)
    # out[i] = stacked[(i - r) % n] = source rank i
    return jnp.roll(stacked_i, r, axis=0), jnp.roll(stacked_d, r, axis=0)


def _composite_plain_exchanged(image: jnp.ndarray, depth: jnp.ndarray,
                               n: int, axis_name: str, background,
                               exchange: str, wire: str = "f32",
                               topo=None):
    """Plain-image exchange + nearest-first composite under the configured
    schedule (`exchange` ∈ {"all_to_all", "ring"}) and wire format
    (`wire` ∈ {"f32", "bf16", "qpack8"}). ``topo`` switches to the
    two-level plain composite (parallel/hier.py): domain partials over
    ICI, nearest-first merge of the partials over DCN."""
    if topo is not None:
        from scenery_insitu_tpu.parallel.hier import hier_composite_plain

        return hier_composite_plain(image, depth, topo, background,
                                    exchange, wire)
    if exchange == "ring" and n > 1:
        images, depths = _ring_exchange_plain(image, depth, n, axis_name,
                                              wire)
    elif wire == "f32":
        images = _exchange_columns(image, n, axis_name)  # [n, 4, H, W/n]
        depths = _exchange_columns(depth, n, axis_name)  # [n, H, W/n]
    else:
        from scenery_insitu_tpu.ops import wire as _wire

        images, depths = _encoded_all_to_all(
            image, depth, n, axis_name,
            lambda i, d: _wire.encode_plain(i, d, wire),
            lambda i, d, s: _wire.decode_plain(i, d, s, wire))
    with _phase("merge"):
        return composite_plain(images, depths, background)


def _composite_plain_waves(image: jnp.ndarray, depth: jnp.ndarray,
                           n: int, axis_name: str, background,
                           exchange: str, wire: str, wave_tiles: int,
                           march_wave=None, topo=None) -> jnp.ndarray:
    """Tile-wave plain-image exchange + composite. ``march_wave(w, _) ->
    ((image_w, depth_w), _)`` optionally RENDERS each wave's column
    blocks (the MXU engine's tile-scoped `render_slices`) so the wave's
    collective overlaps the next wave's march; None slices pre-rendered
    full-frame fragments (the gather engine — exchange/composite
    pipelining only). Output layout == the frame schedule's."""
    from scenery_insitu_tpu.ops import slicer as _slicer

    t = wave_tiles
    w = image.shape[-1] if march_wave is None else None

    def slice_wave(wv, _):
        return (_slicer.wave_cols(image, n, t, wv),
                _slicer.wave_cols(depth, n, t, wv)), None

    if march_wave is None:
        _slicer.wave_block(w, n, t)
        _wave_build_marker(n, t, 1, image.shape[-2], w, 1, exchange, 0,
                           wire, marched=False)
        march_wave = slice_wave

    def compose(fr):
        return (_composite_plain_exchanged(fr[0], fr[1], n, axis_name,
                                           background, exchange, wire,
                                           topo=topo),)

    (img,), _ = _wave_pipeline(t, march_wave, compose)
    return _wave_assemble(img)


def distributed_vdi_step(mesh: Mesh, tf: TransferFunction,
                         width: int, height: int,
                         vdi_cfg: Optional[VDIConfig] = None,
                         comp_cfg: Optional[CompositeConfig] = None,
                         max_steps: int = 256,
                         axis_name: Optional[str] = None,
                         plan=None, bricks=None, topology=None):
    """Build the jitted distributed VDI render step.

    Returns ``f(vol_data f32[D, H, W] (z-sharded), origin f32[3],
    spacing f32[3], cam Camera) -> VDI`` whose color/depth are W-sharded
    global arrays ([K_out, 4, height, width] / [K_out, 2, height, width]).

    ``topology`` (a config.TopologyConfig; docs/MULTIHOST.md) selects
    the two-level composite on a hierarchical ``(hosts, ranks)`` mesh —
    generation and halo exchange run over the flat axis view unchanged,
    the sort-last composite splits into intra-domain (ICI) + inter-domain
    (DCN) levels. None on a flat mesh is exactly the single-level step.
    """
    vdi_cfg = vdi_cfg or VDIConfig()
    comp_cfg = comp_cfg or CompositeConfig()
    axis, n, topo = resolve_mesh_topology(mesh, axis_name, topology)
    if width % n:
        raise ValueError(f"width {width} not divisible by mesh size {n}")
    if comp_cfg.schedule == "waves" and n > 1:
        from scenery_insitu_tpu.ops.slicer import wave_block

        wave_block(width, n, comp_cfg.wave_tiles)   # fail at build time
    if comp_cfg.k_budget == "occupancy":
        # the gather engine has no occupancy pyramid to derive budgets
        # from — a configured-but-inert knob must land on the ledger
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("occupancy.k_budget", "occupancy", "static",
                     "gather-engine distributed step has no occupancy "
                     "pyramid (mxu builders only)", warn=False)
    _resolve_reuse(comp_cfg, supported=False,
                   where="the gather-engine distributed step")
    plan = _resolve_plan(comp_cfg, n, plan)
    bricks = _resolve_bricks(comp_cfg, n, bricks)

    def step(local_data, origin, spacing, cam: Camera) -> VDI:
        d_global = local_data.shape[0] * n
        if bricks is not None:
            # non-convex partition (docs/SCENARIOS.md): one K-fragment
            # per brick against the brick's clip AABB on the GLOBAL
            # sample ladder; the concatenated stream is sorted by the
            # composite, so the frame is bitwise invariant to ownership
            units, smax = _brick_clip_units(
                local_data, origin, spacing, d_global, axis, bricks)
            smin = origin
            cs, ds = [], []
            for vol, cmin, cmax in units:
                with _phase("march"):
                    vdi, _ = generate_vdi(vol, tf, cam, width, height,
                                          vdi_cfg, max_steps=max_steps,
                                          clip_min=cmin, clip_max=cmax,
                                          sample_min=smin,
                                          sample_max=smax)
                cs.append(vdi.color)
                ds.append(vdi.depth)
            return _composite_exchanged_sched(
                jnp.concatenate(cs, axis=0), jnp.concatenate(ds, axis=0),
                n, axis, comp_cfg, topo=topo)
        vol, cmin, cmax, smin, smax = _local_volume_and_clip(
            local_data, origin, spacing, d_global, axis, plan=plan)
        with _phase("march"):
            vdi, _ = generate_vdi(vol, tf, cam, width, height, vdi_cfg,
                                  max_steps=max_steps, clip_min=cmin,
                                  clip_max=cmax, sample_min=smin,
                                  sample_max=smax)
        return _composite_exchanged_sched(vdi.color, vdi.depth, n, axis,
                                          comp_cfg, topo=topo)

    w_axis = axis if topo is None else topo.out_axis
    spec_vol = P(axis, None, None)
    spec_out = VDI(P(None, None, None, w_axis), P(None, None, None, w_axis))
    f = shard_map(step, mesh=mesh,
                  in_specs=(spec_vol, P(), P(), P()),
                  out_specs=spec_out, check_vma=False)
    return jax.jit(f)


def _rank_slab(local_data, origin, spacing, spec, axis, n,
               shade=None, shade_halo: int = 0, plan=None):
    """This rank's halo-padded slab Volume + global box + ownership bounds
    for a slice march (shared by generation and threshold seeding).
    Returns ``(vol, gmax, v_bounds, w_bounds, dims)``.

    ``shade``: optional per-rank volume shader (e.g. the AO pre-shader,
    ops/ao.shade_volume_ao) applied to a ``shade_halo``-deep extended
    slab BEFORE trimming to the march extent — a radius-``shade_halo``
    neighborhood operator inside ``shade`` then sees real neighbor
    slices, making its output seam-exact vs a single-device run. The
    shader may change the channel layout (scalar → pre-shaded RGBA).

    ``spec.render_dtype == "bf16"`` casts the marched slab to bf16 UP
    FRONT — the halo-exchange ICI bytes and every march's volume reads
    halve; shaded (AO) slabs shade in f32 first and cast the result.

    ``plan`` (docs/PERF.md "Render rebalancing") swaps the even slab for
    this rank's PLANNED contiguous z band, assembled from the even
    shards by `mesh.reslab_z` with the identical halo contract. The
    returned ownership bounds extend to the march axis: ``v_bounds``
    masks in-plane rows when z is the in-plane axis (x/y marches,
    exactly as before), and ``w_bounds`` masks marched slices when z IS
    the march axis — the band pads to the plan's max depth for static
    SPMD shapes, and padded slices must never shade."""
    if getattr(spec, "render_dtype", "f32") == "bf16" and shade is None \
            and local_data.dtype == jnp.float32:
        local_data = local_data.astype(jnp.bfloat16)
    r = jax.lax.axis_index(axis)
    dn = local_data.shape[0]
    h, w = local_data.shape[1], local_data.shape[2]
    dz = spacing[2]
    gmax = origin + jnp.array([w, h, dn * n], jnp.float32) * spacing
    if plan is not None:
        return _planned_slab(local_data, origin, spacing, spec, axis, n,
                             plan=plan, shade=shade, shade_halo=shade_halo,
                             dz=dz, gmax=gmax)

    if shade is not None:
        hr = shade_halo + 1
        with _phase("halo"):
            ext = halo_exchange_z(local_data, axis, h=hr)
        ext_origin = origin.at[2].add((r * dn - hr) * dz)
        local_data = shade(Volume(ext, ext_origin, spacing)).data
        if getattr(spec, "render_dtype", "f32") == "bf16" \
                and local_data.dtype == jnp.float32:
            local_data = local_data.astype(jnp.bfloat16)
        # trim back: [hr:hr+dn] is the bare slab; the branches below
        # re-add their own 1-slice interpolation halo from the REAL
        # (already-shaded) neighbors kept around it
        z_slice = lambda lo, hi: (local_data[..., lo:hi, :, :]
                                  if local_data.ndim == 4
                                  else local_data[lo:hi])

    if spec.axis == 2:
        # march along the domain axis: each rank marches only its own
        # slab slices — no halo, no ownership masks needed
        local_origin = origin.at[2].add(r * dn * dz)
        if shade is not None:
            local_data = z_slice(shade_halo + 1, shade_halo + 1 + dn)
        vol = Volume(local_data, local_origin, spacing)
        v_bounds = None
    else:
        # march along x/y: the in-plane v axis is the sharded z axis —
        # halo rows for seam-exact bilinear, half-open ownership so
        # every sample belongs to exactly one rank
        if shade is not None:
            halo = z_slice(shade_halo, shade_halo + dn + 2)
        else:
            with _phase("halo"):
                halo = halo_exchange_z(local_data, axis)   # [Dn+2, H, W]
        local_origin = origin.at[2].add((r * dn - 1) * dz)
        vol = Volume(halo, local_origin, spacing)
        z_lo = origin[2] + r * dn * dz
        z_hi = origin[2] + (r + 1) * dn * dz
        # edge ranks keep the exact global extent as their bound (the
        # clamped halo row must never render the band beyond it, which
        # single-device treats as outside the volume); the +dz slack on
        # the last rank only re-admits pos == global max, which the
        # volume-extent mask in _interp_matrix still caps
        v_bounds = (z_lo, jnp.where(r == n - 1, z_hi + dz, z_hi))
    return vol, gmax, v_bounds, None, (w, h, dn * n)


def _planned_slab(local_data, origin, spacing, spec, axis, n,
                  plan: tuple = (), shade=None, shade_halo=0,
                  dz=None, gmax=None):
    """`_rank_slab`'s planned-band twin (CompositeConfig.rebalance ==
    "occupancy"): the march volume is this rank's contiguous z band from
    the render plan, materialized by `mesh.reslab_z` (same seam-exact
    halo/clamp contract as the even path, zero-padded to the plan's max
    depth). Ownership stays exact and exclusive: x/y marches keep the
    half-open ``v_bounds`` interval — now the BAND interval — and z
    marches gain the ``w_bounds`` twin so padded slices shade nothing;
    together every world sample still belongs to exactly one rank, which
    is what makes the composite decomposition-invariant."""
    r = jax.lax.axis_index(axis)
    dn = local_data.shape[0]
    h, w = local_data.shape[1], local_data.shape[2]
    pmax = int(max(plan))
    g0, p_r = _plan_rank_band(plan, axis)
    z_lo = origin[2] + g0 * dz
    z_hi = origin[2] + (g0 + p_r) * dz

    if shade is not None:
        hr = shade_halo + 1
        with _phase("halo"):
            ext = reslab_z(local_data, plan, axis, h=hr)
        ext_origin = origin.at[2].add((g0 - hr) * dz)
        shaded = shade(Volume(ext, ext_origin, spacing)).data
        if getattr(spec, "render_dtype", "f32") == "bf16" \
                and shaded.dtype == jnp.float32:
            shaded = shaded.astype(jnp.bfloat16)
        # the band start sits at a FIXED offset hr inside the extended
        # band on every rank, so the trims below stay static; rows past
        # a rank's own band + halo were zero going in and are masked by
        # the ownership bounds coming out
        z_slice = lambda lo, hi: (shaded[..., lo:hi, :, :]
                                  if shaded.ndim == 4 else shaded[lo:hi])

    if spec.axis == 2:
        # march along z: the band's slices ARE the marched slices; the
        # pad slices (band depth < pmax) are dropped by w_bounds exactly
        # like v_bounds drops foreign in-plane rows on x/y marches
        if shade is not None:
            band = z_slice(hr, hr + pmax)
        else:
            with _phase("halo"):
                band = reslab_z(local_data, plan, axis,
                                h=0)                       # [Pmax, H, W]
        local_origin = origin.at[2].add(g0 * dz)
        vol = Volume(band, local_origin, spacing)
        return vol, gmax, None, (z_lo, z_hi), (w, h, dn * n)

    # march along x/y: the in-plane v axis is the planned z band — halo
    # rows for seam-exact bilinear, half-open PLAN-interval ownership
    if shade is not None:
        band = z_slice(hr - 1, hr + pmax + 1)              # [Pmax+2, ...]
    else:
        with _phase("halo"):
            band = reslab_z(local_data, plan, axis)        # [Pmax+2, H, W]
    local_origin = origin.at[2].add((g0 - 1) * dz)
    vol = Volume(band, local_origin, spacing)
    # same edge-rank slack as the even path: rank n-1 owns the global
    # top whatever the plan (band starts are monotone)
    v_bounds = (z_lo, jnp.where(r == n - 1, z_hi + dz, z_hi))
    return vol, gmax, v_bounds, None, (w, h, dn * n)


def _rank_frame_state(local_data, origin, spacing, spec, tf, vdi_cfg,
                      axis, n, comp_cfg, plan=None,
                      need_pyramid: bool = False):
    """Per-frame, per-rank shared state of an MXU generation: the
    halo-exact slab (or planned render band, ``plan``), the frame's ONE
    occupancy pyramid, and (when ``comp_cfg.k_budget == "occupancy"``)
    the psum-derived adaptive-K target. Shared by the frame-schedule
    generation (`_mxu_rank_generate`) and the tile-wave path
    (`_mxu_rank_generate_waves`) — T waves must not pay T pyramids or T
    psums. ``need_pyramid`` forces the pyramid even with skipping off —
    the temporal-reuse dirty detector reads its ranges every frame."""
    vol, gmax, v_bounds, w_bounds, dims = _rank_slab(
        local_data, origin, spacing, spec, axis, n, plan=plan)
    occ_pyr = None
    k_target = None
    budgeted = comp_cfg is not None and comp_cfg.k_budget == "occupancy"
    if budgeted and not vdi_cfg.adaptive:
        # a fixed-threshold generation never consults the target — the
        # knob is inert, so say so instead of paying the psum per frame
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("occupancy.k_budget", "occupancy", "static",
                     "k budgets re-target the ADAPTIVE threshold; "
                     "vdi.adaptive=False ignores them", warn=False)
        budgeted = False
    if spec.skip_empty or budgeted or need_pyramid:
        from scenery_insitu_tpu.ops import occupancy as _occ

        with _phase("march"):
            occ_pyr = _occ.pyramid_from_volume(vol, tf, spec)
    if budgeted:
        from scenery_insitu_tpu import obs as _obs
        from scenery_insitu_tpu.ops import occupancy as _occ

        live = occ_pyr.live_fraction()
        k_target = _occ.k_budget_target(
            live, jax.lax.psum(live, axis), n,
            vdi_cfg.max_supersegments, comp_cfg.k_budget_min)
        rec = _obs.get_recorder()
        rec.count("occupancy_kbudget_builds")
        rec.event("occupancy_kbudget_build", ranks=n,
                  k=vdi_cfg.max_supersegments,
                  k_min=comp_cfg.k_budget_min)
    return vol, gmax, v_bounds, w_bounds, dims, occ_pyr, k_target


def _mxu_rank_generate(local_data, origin, spacing, cam, slicer, spec,
                       tf, vdi_cfg, axis, n, threshold=None,
                       comp_cfg=None, plan=None, reuse=None,
                       reuse_tol: float = 0.0):
    """Per-rank slice-march VDI generation on a z-slab (shared by the
    distributed VDI and hybrid steps). Returns (vdi, meta, axcam,
    next_threshold, next_reuse) — the last two are None unless carried
    temporal threshold / reuse state was passed in.

    This is where the frame's ONE occupancy pyramid is built
    (ops/occupancy.pyramid_from_volume on the halo-exact slab) and
    shared by every march of the generation — the legacy path re-ran the
    permute + full-slab reduction per call site. The same pyramid's live
    fraction drives the load-aware per-rank K budget when
    ``comp_cfg.k_budget == "occupancy"``: a psum over the mesh turns the
    per-rank live fractions into shares of the N*K budget
    (occupancy.k_budget_target), so the adaptive threshold on a sparse
    slab stops chasing the same K as the densest rank.

    ``reuse`` (an ops/delta.ReuseState; docs/PERF.md "Temporal deltas")
    carries the previous frame's marched fragment plus its dirty
    signature: when the pyramid's ranges moved at most ``reuse_tol`` and
    the camera is bit-unchanged, the march is skipped under ``lax.cond``
    (no matmul wave issues — both branches are collective-free, so a
    per-rank divergent predicate is sound inside shard_map) and the
    carried fragment feeds the unchanged exchange + composite."""
    vol, gmax, v_bounds, w_bounds, dims, occ_pyr, k_target = \
        _rank_frame_state(local_data, origin, spacing, spec, tf, vdi_cfg,
                          axis, n, comp_cfg, plan=plan,
                          need_pyramid=reuse is not None)
    if reuse is None:
        with _phase("march"):
            if threshold is None:
                vdi, meta, axcam = slicer.generate_vdi_mxu(
                    vol, tf, cam, spec, vdi_cfg,
                    box_min=origin, box_max=gmax, v_bounds=v_bounds,
                    occupancy=occ_pyr, k_target=k_target,
                    w_bounds=w_bounds)
                thr2 = None
            else:
                vdi, meta, axcam, thr2 = slicer.generate_vdi_mxu_temporal(
                    vol, tf, cam, spec, threshold, vdi_cfg,
                    box_min=origin, box_max=gmax, v_bounds=v_bounds,
                    occupancy=occ_pyr, k_target=k_target,
                    w_bounds=w_bounds)
        # metadata must describe the GLOBAL volume, not this rank's slab
        meta = meta._replace(volume_dims=jnp.array(dims, jnp.float32))
        return vdi, meta, axcam, thr2, None

    from scenery_insitu_tpu.ops import delta as _delta

    axcam = slicer.make_axis_camera(vol, cam, spec, box_min=origin,
                                    box_max=gmax)
    sig = _delta.reuse_signature(occ_pyr, cam)
    dirty = _delta.reuse_dirty(sig, reuse.sig, reuse.valid, reuse_tol,
                               2 * occ_pyr.lo.size)

    def marched(_):
        with _phase("march"):
            if threshold is None:
                vdi, _, _ = slicer.generate_vdi_mxu(
                    vol, tf, cam, spec, vdi_cfg, v_bounds=v_bounds,
                    occupancy=occ_pyr, k_target=k_target, axcam=axcam,
                    w_bounds=w_bounds)
                return vdi.color, vdi.depth
            vdi, _, _, thr2 = slicer.generate_vdi_mxu_temporal(
                vol, tf, cam, spec, threshold, vdi_cfg,
                v_bounds=v_bounds, occupancy=occ_pyr, k_target=k_target,
                axcam=axcam, w_bounds=w_bounds)
            return vdi.color, vdi.depth, thr2

    def kept(_):
        # a clean rank: last frame's fragment IS this frame's (the
        # temporal threshold controller holds too — nothing marched, so
        # there is no observation to feed it)
        if threshold is None:
            return reuse.color, reuse.depth
        return reuse.color, reuse.depth, threshold

    out = jax.lax.cond(dirty, marched, kept, None)
    color, depth = out[0], out[1]
    thr2 = out[2] if threshold is not None else None
    reuse2 = _delta.ReuseState(
        # the signature tracks the last MARCHED frame, so sub-tolerance
        # drift accumulates instead of creeping away unseen
        sig=jnp.where(dirty, sig, reuse.sig),
        color=color, depth=depth,
        valid=jnp.ones_like(reuse.valid),
        dirty=dirty.astype(jnp.int32).reshape(1))
    meta = slicer._vdi_meta(vol, axcam, spec.ni, spec.nj, 0)
    meta = meta._replace(volume_dims=jnp.array(dims, jnp.float32))
    return VDI(color, depth), meta, axcam, thr2, reuse2


def _mxu_rank_generate_waves(local_data, origin, spacing, cam, slicer,
                             spec, tf, vdi_cfg, comp_cfg, axis, n,
                             threshold=None, plan=None, reuse=None,
                             reuse_tol: float = 0.0, topo=None):
    """The tile-wave twin of `_mxu_rank_generate` + `_composite_exchanged`
    (CompositeConfig.schedule == "waves"; docs/PERF.md "Tile waves"):
    instead of one whole-frame march followed by one exchange, each rank
    marches ONE column-block wave at a time (a tile-scoped generation on
    `slicer.wave_camera`'s u-sliced virtual camera — same slices, same
    per-pixel samples) and, while wave w+1 marches, wave w's fragments
    circulate and fold through the frame compositor. The slab, the halo
    exchange, the `permute_volume` copy, the occupancy pyramid and the
    occupancy K budget are all built ONCE per frame and shared by every
    wave.

    Temporal mode slices the carried threshold maps to each wave's
    columns and scatters the controller's update back — the full-frame
    state that crosses frames is bit-identical in meaning to the frame
    schedule's (each pixel is marched exactly once per frame either
    way). ``reuse`` (docs/PERF.md "Temporal deltas") works like
    `_mxu_rank_generate`'s: the dirty predicate is per rank (the range
    signature is rank-wide) and every wave of a clean rank skips its
    march under ``lax.cond`` — the wave slice of the carried full-frame
    fragment stands in, so the waves' exchange + composite overlap
    pipeline is untouched. Returns (vdi [K_out over this rank's
    contiguous column block], meta, axcam, thr', reuse')."""
    import jax.tree_util as jtu

    vol, gmax, v_bounds, w_bounds, dims, occ_pyr, k_target = \
        _rank_frame_state(local_data, origin, spacing, spec, tf, vdi_cfg,
                          axis, n, comp_cfg, plan=plan,
                          need_pyramid=reuse is not None)
    t = comp_cfg.wave_tiles
    slicer.wave_block(spec.ni, n, t)       # validates the geometry
    axcam = slicer.make_axis_camera(vol, cam, spec, box_min=origin,
                                    box_max=gmax)
    volp = slicer.permute_volume(vol, spec)
    _wave_build_marker(n, t, vdi_cfg.max_supersegments, spec.nj, spec.ni,
                       comp_cfg.max_output_supersegments,
                       comp_cfg.exchange, comp_cfg.ring_slots,
                       comp_cfg.wire, marched=True)
    if reuse is not None:
        from scenery_insitu_tpu.ops import delta as _delta

        sig = _delta.reuse_signature(occ_pyr, cam)
        dirty = _delta.reuse_dirty(sig, reuse.sig, reuse.valid,
                                   reuse_tol, 2 * occ_pyr.lo.size)

    def march_wave(w, carry):
        if reuse is not None:
            thr_full, acc_c, acc_d = carry
        else:
            thr_full = carry
        axcam_w, spec_w = slicer.wave_camera(axcam, spec, n, t, w)
        thr_w = (None if thr_full is None else
                 jtu.tree_map(lambda m: slicer.wave_cols(m, n, t, w),
                              thr_full))

        def marched(_):
            with _phase("march"):
                if thr_w is None:
                    vdi, _, _ = slicer.generate_vdi_mxu(
                        vol, tf, cam, spec_w, vdi_cfg,
                        v_bounds=v_bounds, occupancy=occ_pyr,
                        k_target=k_target, axcam=axcam_w, volp=volp,
                        w_bounds=w_bounds)
                    return vdi.color, vdi.depth
                vdi, _, _, thr2w = slicer.generate_vdi_mxu_temporal(
                    vol, tf, cam, spec_w, thr_w, vdi_cfg,
                    v_bounds=v_bounds, occupancy=occ_pyr,
                    k_target=k_target, axcam=axcam_w, volp=volp,
                    w_bounds=w_bounds)
                return vdi.color, vdi.depth, thr2w

        if reuse is None:
            out = marched(None)
        else:
            def kept(_):
                cw = slicer.wave_cols(acc_c, n, t, w)
                dw = slicer.wave_cols(acc_d, n, t, w)
                if thr_w is None:
                    return cw, dw
                return cw, dw, thr_w

            out = jax.lax.cond(dirty, marched, kept, None)
        cw, dw = out[0], out[1]
        if thr_full is not None:
            thr_full = jtu.tree_map(
                lambda m, mw: slicer.wave_update_cols(m, mw, n, t, w),
                thr_full, out[2])
        if reuse is None:
            return (cw, dw), thr_full
        # the carried full-frame fragment accumulates wave by wave; a
        # clean rank scatters back exactly what it sliced out (no-op)
        acc_c = slicer.wave_update_cols(acc_c, cw, n, t, w)
        acc_d = slicer.wave_update_cols(acc_d, dw, n, t, w)
        return (cw, dw), (thr_full, acc_c, acc_d)

    def compose(fr):
        out = _composite_exchanged(fr[0], fr[1], n, axis, comp_cfg,
                                   topo=topo)
        return out.color, out.depth

    carry0 = (threshold if reuse is None else
              (threshold, reuse.color, reuse.depth))
    (oc, od), carry = _wave_pipeline(t, march_wave, compose, carry0)
    if reuse is None:
        thr2, reuse2 = carry, None
    else:
        from scenery_insitu_tpu.ops import delta as _delta

        thr2, acc_c, acc_d = carry
        reuse2 = _delta.ReuseState(
            sig=jnp.where(dirty, sig, reuse.sig),
            color=acc_c, depth=acc_d,
            valid=jnp.ones_like(reuse.valid),
            dirty=dirty.astype(jnp.int32).reshape(1))
    vdi = VDI(_wave_assemble(oc), _wave_assemble(od))
    meta = slicer._vdi_meta(vol, axcam, spec.ni, spec.nj, 0)
    meta = meta._replace(volume_dims=jnp.array(dims, jnp.float32))
    return vdi, meta, axcam, thr2, reuse2


def distributed_vdi_step_mxu(mesh: Mesh, tf: TransferFunction,
                             spec, vdi_cfg: Optional[VDIConfig] = None,
                             comp_cfg: Optional[CompositeConfig] = None,
                             axis_name: Optional[str] = None,
                             plan=None, bricks=None,
                             reuse_tol: float = 0.0,
                             topology=None):
    """Distributed sort-last VDI pipeline on the MXU slice-march engine
    (ops/slicer.py) — generation runs as banded-matmul slice resampling
    instead of per-ray gathers; the rest of the chain (width-axis column
    exchange under ``comp_cfg.exchange`` — all_to_all or ring — then the
    sort-merge composite) is unchanged.

    ``spec`` is the static `slicer.AxisSpec` for the *current camera
    regime* (march axis/sign + intermediate resolution); the session keeps
    one jitted step per regime. The output VDI lives on the virtual
    axis camera's global pixel grid, sharded over its width (i) axis.

    Domain decomposition is the same z-slab sharding as
    `distributed_vdi_step`; ownership of in-plane samples is half-open per
    rank, halo rows make boundary interpolation seam-exact.

    ``comp_cfg.temporal_reuse == "ranges"`` changes the signature to
    ``f(vol_data, origin, spacing, cam, reuse) -> ((VDI, meta),
    reuse')`` — seed ``reuse`` with `distributed_initial_reuse_mxu`;
    ``reuse_tol`` is the dirty tolerance (cfg.delta.range_tol).
    """
    return _build_mxu_step(mesh, tf, spec, vdi_cfg, comp_cfg, axis_name,
                           temporal=False, plan=plan, bricks=bricks,
                           reuse_tol=reuse_tol, topology=topology)


def _build_mxu_step(mesh, tf, spec, vdi_cfg, comp_cfg, axis_name,
                    temporal: bool, plan=None, bricks=None,
                    reuse_tol: float = 0.0, topology=None):
    """Shared builder of the MXU sort-last step (generate → column
    exchange under ``comp_cfg.exchange`` → composite), with or without
    carried temporal threshold state threaded through.

    ``comp_cfg.temporal_reuse == "ranges"`` (docs/PERF.md "Temporal
    deltas") appends a second carry: the step signature gains a trailing
    ``reuse`` argument (an ops/delta.ReuseState from
    `distributed_initial_reuse_mxu`) and the return gains ``reuse'`` —
    ranks whose occupancy-range signature moved at most ``reuse_tol``
    (``FrameworkConfig.delta.range_tol``) skip their march and feed the
    carried fragment to the exchange."""
    from scenery_insitu_tpu.core.vdi import VDIMetadata
    from scenery_insitu_tpu.ops import slicer

    vdi_cfg = vdi_cfg or VDIConfig()
    comp_cfg = comp_cfg or CompositeConfig()
    axis, n, topo = resolve_mesh_topology(mesh, axis_name, topology)
    if spec.ni % n:
        raise ValueError(f"intermediate width {spec.ni} not divisible by "
                         f"mesh size {n}")
    waves = _resolve_waves(comp_cfg, n, spec.ni, slicer)
    plan = _resolve_plan(comp_cfg, n, plan)
    bricks = _resolve_bricks(comp_cfg, n, bricks)
    if bricks is not None and comp_cfg.k_budget == "occupancy":
        # per-brick marches derive no per-rank psum budget (a brick's
        # pyramid sees one brick, not the rank's live share)
        from scenery_insitu_tpu import obs as _obs

        _obs.degrade("occupancy.k_budget", "occupancy", "static",
                     "brick-partitioned MXU steps derive no per-rank "
                     "psum budget (slab decompositions only)", warn=False)
    reuse = _resolve_reuse(comp_cfg, supported=bricks is None,
                           where="the brick-partitioned MXU step")

    def body(local_data, origin, spacing, cam, thr, ru):
        if bricks is not None:
            if waves:
                out, meta, _, thr2 = _mxu_rank_generate_bricks_waves(
                    local_data, origin, spacing, cam, slicer, spec, tf,
                    vdi_cfg, comp_cfg, axis, n, bricks, threshold=thr,
                    topo=topo)
                return out, meta, thr2, None
            vdi, meta, _, thr2 = _mxu_rank_generate_bricks(
                local_data, origin, spacing, cam, slicer, spec, tf,
                vdi_cfg, axis, n, bricks, threshold=thr)
            return (_composite_exchanged(vdi.color, vdi.depth, n, axis,
                                         comp_cfg, topo=topo), meta,
                    thr2, None)
        if waves:
            out, meta, _, thr2, ru2 = _mxu_rank_generate_waves(
                local_data, origin, spacing, cam, slicer, spec, tf,
                vdi_cfg, comp_cfg, axis, n, threshold=thr, plan=plan,
                reuse=ru, reuse_tol=reuse_tol, topo=topo)
            return out, meta, thr2, ru2
        vdi, meta, _, thr2, ru2 = _mxu_rank_generate(
            local_data, origin, spacing, cam, slicer, spec, tf, vdi_cfg,
            axis, n, threshold=thr, comp_cfg=comp_cfg, plan=plan,
            reuse=ru, reuse_tol=reuse_tol)
        return (_composite_exchanged(vdi.color, vdi.depth, n, axis,
                                     comp_cfg, topo=topo), meta, thr2,
                ru2)

    w_axis = axis if topo is None else topo.out_axis
    spec_vol = P(axis, None, None)
    out_vdi = VDI(P(None, None, None, w_axis), P(None, None, None, w_axis))
    out_meta = VDIMetadata(*(P() for _ in VDIMetadata._fields))

    if temporal and reuse:
        thr_spec = _thr_state_spec(axis)
        ru_spec = _reuse_state_spec(axis)

        def step(local_data, origin, spacing, cam: Camera, thr, ru):
            out, meta, thr2, ru2 = body(local_data, origin, spacing,
                                        cam, thr, ru)
            return (out, meta), thr2, ru2

        f = shard_map(step, mesh=mesh,
                      in_specs=(spec_vol, P(), P(), P(), thr_spec,
                                ru_spec),
                      out_specs=((out_vdi, out_meta), thr_spec, ru_spec),
                      check_vma=False)
    elif temporal:
        thr_spec = _thr_state_spec(axis)

        def step(local_data, origin, spacing, cam: Camera, thr):
            out, meta, thr2, _ = body(local_data, origin, spacing, cam,
                                      thr, None)
            return (out, meta), thr2

        f = shard_map(step, mesh=mesh,
                      in_specs=(spec_vol, P(), P(), P(), thr_spec),
                      out_specs=((out_vdi, out_meta), thr_spec),
                      check_vma=False)
    elif reuse:
        ru_spec = _reuse_state_spec(axis)

        def step(local_data, origin, spacing, cam: Camera, ru):
            out, meta, _, ru2 = body(local_data, origin, spacing, cam,
                                     None, ru)
            return (out, meta), ru2

        f = shard_map(step, mesh=mesh,
                      in_specs=(spec_vol, P(), P(), P(), ru_spec),
                      out_specs=((out_vdi, out_meta), ru_spec),
                      check_vma=False)
    else:
        def step(local_data, origin, spacing, cam: Camera):
            out, meta, _, _ = body(local_data, origin, spacing, cam,
                                   None, None)
            return out, meta

        f = shard_map(step, mesh=mesh,
                      in_specs=(spec_vol, P(), P(), P()),
                      out_specs=(out_vdi, out_meta), check_vma=False)
    return jax.jit(f)


def _thr_state_spec(axis):
    """Sharding spec of the distributed temporal ThresholdState: each
    rank's [nj, ni] maps stack on a leading rank axis → global
    [n*nj, ni] arrays, rank-sharded."""
    from scenery_insitu_tpu.ops import supersegments as ss

    return ss.ThresholdState(
        *(P(axis, None) for _ in ss.ThresholdState._fields))


def distributed_initial_threshold_mxu(mesh: Mesh, tf: TransferFunction,
                                      spec,
                                      vdi_cfg: Optional[VDIConfig] = None,
                                      axis_name: Optional[str] = None,
                                      plan=None, bricks=None):
    """Jitted seeder for `distributed_vdi_step_mxu_temporal`: one
    histogram counting march per rank on its own slab. Returns
    ``f(vol_data (z-sharded), origin, spacing, cam) -> ThresholdState``
    with rank-stacked [n*nj, ni] maps (``bricks``: one map set per
    brick slot, row-stacked like the step carries them)."""
    from scenery_insitu_tpu.ops import slicer

    vdi_cfg = vdi_cfg or VDIConfig()
    axis, n, _ = resolve_mesh_topology(mesh, axis_name)
    # the seeding march must run the SAME render decomposition the step
    # it seeds will march (no CompositeConfig here, so the mode is
    # implied by the plan/brick map itself)
    plan = _resolve_plan("occupancy", n, plan)
    bricks = _resolve_bricks("bricks", n, bricks)

    def seed(local_data, origin, spacing, cam: Camera):
        if bricks is not None:
            units, gmax, _, ref = _brick_units(local_data, origin,
                                               spacing, spec, axis, n,
                                               bricks)
            axcam = slicer.make_axis_camera(ref, cam, spec,
                                            box_min=origin, box_max=gmax)
            return _stack_thr([
                slicer.initial_threshold(
                    vol, tf, cam, spec, vdi_cfg,
                    box_min=origin, box_max=gmax,
                    v_bounds=vb, w_bounds=wb,
                    axcam=(axcam if f == 1
                           else axcam._replace(dwm=axcam.dwm * f)),
                    step_scale=1.0 / f)
                for vol, vb, wb, f in units])
        vol, gmax, v_bounds, w_bounds, _ = _rank_slab(
            local_data, origin, spacing, spec, axis, n, plan=plan)
        return slicer.initial_threshold(vol, tf, cam, spec, vdi_cfg,
                                        box_min=origin, box_max=gmax,
                                        v_bounds=v_bounds,
                                        w_bounds=w_bounds)

    f = shard_map(seed, mesh=mesh,
                  in_specs=(P(axis, None, None), P(), P(), P()),
                  out_specs=_thr_state_spec(axis), check_vma=False)
    return jax.jit(f)


def distributed_vdi_step_mxu_temporal(mesh: Mesh, tf: TransferFunction,
                                      spec,
                                      vdi_cfg: Optional[VDIConfig] = None,
                                      comp_cfg: Optional[CompositeConfig]
                                      = None,
                                      axis_name: Optional[str] = None,
                                      plan=None, bricks=None,
                                      reuse_tol: float = 0.0,
                                      topology=None):
    """`distributed_vdi_step_mxu` with carried per-rank temporal threshold
    state (adaptive_mode="temporal": ONE march per rank per frame instead
    of counting + write — see slicer.generate_vdi_mxu_temporal).

    Returns ``f(vol_data (z-sharded), origin, spacing, cam, thr) ->
    ((VDI, meta), thr')`` where thr is the rank-sharded ThresholdState
    from `distributed_initial_threshold_mxu`. Each rank adapts the
    threshold map of its own generation camera footprint; the sort-last
    exchange and composite are unchanged. With ``comp_cfg.temporal_reuse
    == "ranges"`` the signature gains a trailing ``reuse`` carry and
    return (see `distributed_vdi_step_mxu`).
    """
    return _build_mxu_step(mesh, tf, spec, vdi_cfg, comp_cfg, axis_name,
                           temporal=True, plan=plan, bricks=bricks,
                           reuse_tol=reuse_tol, topology=topology)


def distributed_hybrid_step_mxu(mesh: Mesh, tf: TransferFunction,
                                spec, vdi_cfg: Optional[VDIConfig] = None,
                                comp_cfg: Optional[CompositeConfig] = None,
                                radius: float = 0.02, stamp: int = 5,
                                colormap: str = "jet",
                                axis_name: Optional[str] = None,
                                temporal: bool = False,
                                plan=None, bricks=None, topology=None):
    """Distributed hybrid volume+particle frame (BASELINE.md Config 5):
    z-sharded volume through the sort-last MXU VDI chain, N-sharded
    tracers through the sort-first splat chain (per-rank z-buffer,
    all_gather, depth-min — ≅ InVisRenderer + Head running concurrently
    with DistributedVolumes), then the particle layer is depth-inserted
    into each rank's composited VDI columns (ops/hybrid.py). One jitted
    SPMD program.

    Returns ``f(vol_data f32[D,H,W] (z-sharded), origin, spacing,
    tracer_world f32[N,3] (N-sharded), tracer_vel f32[N,3] (same), cam)
    -> (image f32[4, Nj, Ni] W-sharded on the virtual grid, meta)``.
    Warp to the display camera with ops.slicer.warp_to_camera.

    ``temporal=True`` threads carried per-rank threshold state through the
    VDI pass exactly like `distributed_vdi_step_mxu_temporal` (seed with
    `distributed_initial_threshold_mxu`): the signature gains a trailing
    ``thr`` argument and the return becomes ``((image, meta), thr')`` —
    the hybrid frame then pays ONE march/frame like the plain VDI path
    (the steady-state economy of DistributedVolumes.kt:683-933).
    """
    from scenery_insitu_tpu.ops import slicer
    from scenery_insitu_tpu.ops.hybrid import composite_vdi_with_particles
    from scenery_insitu_tpu.ops.splat import SplatOutput
    from scenery_insitu_tpu.parallel.particles import sort_first_splat

    vdi_cfg = vdi_cfg or VDIConfig()
    comp_cfg = comp_cfg or CompositeConfig()
    axis, n, topo = resolve_mesh_topology(mesh, axis_name, topology)
    if spec.ni % n:
        raise ValueError(f"intermediate width {spec.ni} not divisible by "
                         f"mesh size {n}")
    waves = _resolve_waves(comp_cfg, n, spec.ni, slicer)
    plan = _resolve_plan(comp_cfg, n, plan)
    _bricks_inert(bricks, "the hybrid step")
    # the hybrid frame re-splats particles every frame anyway; carrying
    # the VDI half's fragments is future work — say so, don't ignore
    _resolve_reuse(comp_cfg, supported=False, where="the hybrid step")

    def body(local_data, origin, spacing, tr_pos, tr_vel, cam, thr):
        if waves:
            # the VDI half runs at tile-wave granularity; the splat half
            # is per-frame (particles are sort-first, exchange-free) and
            # inserts into the ASSEMBLED contiguous column block — the
            # same block the frame schedule composites
            comp, meta, axcam, thr2, _ = _mxu_rank_generate_waves(
                local_data, origin, spacing, cam, slicer, spec, tf,
                vdi_cfg, comp_cfg, axis, n, threshold=thr, plan=plan,
                topo=topo)
        else:
            vdi, meta, axcam, thr2, _ = _mxu_rank_generate(
                local_data, origin, spacing, cam, slicer, spec, tf,
                vdi_cfg, axis, n, threshold=thr, comp_cfg=comp_cfg,
                plan=plan)
            comp = _composite_exchanged(vdi.color, vdi.depth, n, axis,
                                        comp_cfg, topo=topo)
            # [Ko, ·, Nj, Ni/n]

        # sort-first particle pass on the virtual camera's rays
        with _phase("march"):
            sp = sort_first_splat(tr_pos, tr_vel, axis, spec.ni,
                                  spec.nj, radius, stamp, colormap,
                                  view=axcam.view, proj=axcam.proj)

        # my column block of the (replicated) particle layer — under a
        # hierarchical topology the composite hands this rank the block
        # at ranks-major flat position (topology.Topology.out_axis)
        r = _out_block_index(axis, topo)
        wb = spec.ni // n
        img_b = jax.lax.dynamic_slice_in_dim(sp.image, r * wb, wb, axis=2)
        dep_b = jax.lax.dynamic_slice_in_dim(sp.depth, r * wb, wb, axis=1)
        with _phase("merge"):
            hyb = composite_vdi_with_particles(
                comp, SplatOutput(img_b, dep_b))
        return hyb, meta, thr2

    from scenery_insitu_tpu.core.vdi import VDIMetadata
    w_axis = axis if topo is None else topo.out_axis
    out_meta = VDIMetadata(*(P() for _ in VDIMetadata._fields))
    in_base = (P(axis, None, None), P(), P(), P(axis, None), P(axis, None),
               P())

    if temporal:
        thr_spec = _thr_state_spec(axis)

        def step(local_data, origin, spacing, tr_pos, tr_vel, cam: Camera,
                 thr):
            img, meta, thr2 = body(local_data, origin, spacing, tr_pos,
                                   tr_vel, cam, thr)
            return (img, meta), thr2

        f = shard_map(step, mesh=mesh, in_specs=in_base + (thr_spec,),
                      out_specs=((P(None, None, w_axis), out_meta),
                                 thr_spec),
                      check_vma=False)
    else:
        def step(local_data, origin, spacing, tr_pos, tr_vel, cam: Camera):
            img, meta, _ = body(local_data, origin, spacing, tr_pos,
                                tr_vel, cam, None)
            return img, meta

        f = shard_map(step, mesh=mesh, in_specs=in_base,
                      out_specs=(P(None, None, w_axis), out_meta),
                      check_vma=False)
    return jax.jit(f)


def _out_block_index(axis, topo):
    """Traced flat index of this rank's OUTPUT column block: the plain
    axis index on flat meshes; on hierarchical meshes the two-level
    composite hands rank (h, d) the block at ranks-major position
    ``d * H + h`` (topology.Topology.out_axis)."""
    if topo is None:
        return jax.lax.axis_index(axis)
    return (jax.lax.axis_index(topo.ranks_axis) * topo.num_hosts
            + jax.lax.axis_index(topo.hosts_axis))


def distributed_plain_step_mxu(mesh: Mesh, tf: TransferFunction,
                               spec, cfg: Optional[RenderConfig] = None,
                               axis_name: Optional[str] = None,
                               exchange: str = "all_to_all",
                               wire: str = "f32",
                               schedule: str = "frame",
                               wave_tiles: int = 4,
                               rebalance: str = "even",
                               rebalance_period: int = 8,
                               rebalance_hysteresis: float = 0.25,
                               rebalance_min_depth: int = 4,
                               rebalance_quantum: int = 4,
                               rebalance_bricks: int = 0,
                               rebalance_max_moves: int = 2,
                               temporal_reuse: str = "off",
                               plan=None, bricks=None, topology=None):
    """Distributed plain-image rendering on the MXU slice-march engine —
    the TPU-fast counterpart of `distributed_plain_step` (the reference's
    non-VDI mode, VolumeRaycaster.comp:94-161 composited by
    PlainImageCompositor.comp; mode switch DistributedVolumeRenderer.kt:
    175-189). Per rank: `render_slices` on its z-slab (banded-matmul
    resampling, no gathers), then the same sort-last column all_to_all +
    nearest-first `composite_plain` as the gather path.

    Returns ``f(vol_data f32[D,H,W] (z-sharded), origin, spacing, cam) ->
    (image f32[4, Nj, Ni] W-sharded on the virtual grid, axcam)``. The
    intermediate image is background-free; warp to the display camera
    (which blends the background exactly once) with
    ``slicer.warp_to_camera(image, axcam, spec, cam, width, height,
    background)``. ``axcam`` is replicated (every rank derives it from the
    shared global box), so the warp runs on the gathered global image.

    ``exchange``: "all_to_all" (one collective) or "ring" (n-1 pipelined
    single-fragment ppermute hops; bitwise-identical output — see
    `_ring_exchange_plain`). ``wire``: the fragment encoding that crosses
    ICI ("f32" bit-exact | "bf16" | "qpack8" — docs/PERF.md "Wire
    formats"; lossy modes quantize the exchanged RGBA+depth only, the
    composite runs in f32). Plain steps take both knobs directly because
    they carry no CompositeConfig; the session forwards
    ``cfg.composite.exchange`` / ``cfg.composite.wire`` (and
    ``schedule``/``wave_tiles`` — docs/PERF.md "Tile waves": under
    "waves" each rank `render_slices`-marches one column-block wave at a
    time while the previous wave's fragments exchange+composite, sharing
    one permuted copy and occupancy gate per frame). The ``rebalance*``
    knobs + ``plan`` select the uneven render z bands (docs/PERF.md
    "Render rebalancing") exactly like the whole-object builders'
    ``comp_cfg.rebalance``.
    """
    from scenery_insitu_tpu.ops import slicer

    cfg = cfg or RenderConfig()
    axis, n, topo = resolve_mesh_topology(mesh, axis_name, topology)
    if spec.ni % n:
        raise ValueError(f"intermediate width {spec.ni} not divisible by "
                         f"mesh size {n}")
    # validates schedule/wave_tiles/rebalance_* values exactly like
    # CompositeConfig (the plain builders carry the knob matrix
    # explicitly; the session forwards cfg.composite.*)
    knob_cfg = CompositeConfig(schedule=schedule, wave_tiles=wave_tiles,
                               rebalance=rebalance,
                               rebalance_period=rebalance_period,
                               rebalance_hysteresis=rebalance_hysteresis,
                               rebalance_min_depth=rebalance_min_depth,
                               rebalance_quantum=rebalance_quantum,
                               rebalance_bricks=rebalance_bricks,
                               rebalance_max_moves=rebalance_max_moves,
                               temporal_reuse=temporal_reuse)
    waves = _resolve_waves(knob_cfg, n, spec.ni, slicer)
    # a planned band must be at least as deep as the AO shade halo
    plan = _resolve_plan(knob_cfg, n, plan,
                         min_halo=(cfg.ao_radius + 1
                                   if cfg.ao_strength > 0.0 else 1))
    _bricks_inert(bricks, "the plain-image MXU step")
    _resolve_reuse(knob_cfg, supported=False,
                   where="the plain-image MXU step")

    # distributed AO: pre-shade each rank's slab with TF + occlusion on a
    # radius-deep halo (seam-exact — see _rank_slab's shade hook), then
    # march the pre-shaded volume with tf=None exactly like the
    # single-device MXU AO path (ops/ao.shade_volume_ao)
    ao_on = cfg.ao_strength > 0.0
    if ao_on:
        from scenery_insitu_tpu.ops import ao as _ao

        shade = lambda v: _ao.shade_volume_ao(v, tf, cfg.ao_radius,
                                              cfg.ao_strength)

    def step(local_data, origin, spacing, cam: Camera):
        if ao_on:
            vol, gmax, v_bounds, w_bounds, _ = _rank_slab(
                local_data, origin, spacing, spec, axis, n,
                shade=shade, shade_halo=cfg.ao_radius, plan=plan)
        else:
            vol, gmax, v_bounds, w_bounds, _ = _rank_slab(
                local_data, origin, spacing, spec, axis, n, plan=plan)
        axcam = slicer.make_axis_camera(vol, cam, spec, box_min=origin,
                                        box_max=gmax)
        tf_r = tf if not ao_on else None
        bg = (0.0, 0.0, 0.0, 0.0)
        # rank partials stay background-free; the display warp blends it
        if waves:
            # tile-wave schedule: march ONE column-block wave at a time
            # (u-sliced wave camera), sharing the frame's permuted copy
            # and occupancy gate, while the previous wave's fragments
            # exchange + composite (docs/PERF.md "Tile waves")
            volp = slicer.permute_volume(vol, spec)
            occ = slicer.occupancy_for(vol, tf_r, spec, volp=volp)
            _wave_build_marker(n, wave_tiles, 1, spec.nj, spec.ni, 1,
                               exchange, 0, wire, marched=True)

            def march_wave(w, _):
                axcam_w, spec_w = slicer.wave_camera(axcam, spec, n,
                                                     wave_tiles, w)
                with _phase("march"):
                    out = slicer.render_slices(
                        vol, tf_r, axcam_w, spec_w,
                        cfg.early_exit_alpha, v_bounds=v_bounds,
                        step_scale=cfg.step_scale, occupancy=occ,
                        volp=volp, w_bounds=w_bounds)
                return (out.image, out.depth), None

            img = _composite_plain_waves(
                None, None, n, axis, bg, exchange, wire, wave_tiles,
                march_wave=march_wave, topo=topo)
            return img, axcam
        with _phase("march"):
            out = slicer.render_slices(vol, tf_r, axcam, spec,
                                       cfg.early_exit_alpha,
                                       v_bounds=v_bounds,
                                       step_scale=cfg.step_scale,
                                       w_bounds=w_bounds)
        return _composite_plain_exchanged(out.image, out.depth, n, axis,
                                          bg, exchange, wire,
                                          topo=topo), axcam

    from scenery_insitu_tpu.ops.slicer import AxisCamera
    w_axis = axis if topo is None else topo.out_axis
    out_axcam = AxisCamera(*(P() for _ in AxisCamera._fields))
    f = shard_map(step, mesh=mesh,
                  in_specs=(P(axis, None, None), P(), P(), P()),
                  out_specs=(P(None, None, w_axis), out_axcam),
                  check_vma=False)
    return jax.jit(f)


def distributed_plain_step(mesh: Mesh, tf: TransferFunction,
                           width: int, height: int,
                           cfg: Optional[RenderConfig] = None,
                           axis_name: Optional[str] = None,
                           exchange: str = "all_to_all",
                           wire: str = "f32",
                           schedule: str = "frame",
                           wave_tiles: int = 4,
                           rebalance: str = "even",
                           rebalance_period: int = 8,
                           rebalance_hysteresis: float = 0.25,
                           rebalance_min_depth: int = 4,
                           rebalance_quantum: int = 4,
                           rebalance_bricks: int = 0,
                           rebalance_max_moves: int = 2,
                           temporal_reuse: str = "off",
                           plan=None, bricks=None, topology=None):
    """Build the jitted distributed plain-image render step (the reference's
    non-VDI mode: VolumeRaycaster + PlainImageCompositor,
    DistributedVolumeRenderer.kt:175-189). Returns ``f(vol_data, origin,
    spacing, cam) -> image f32[4, height, width]`` sharded by W.
    ``exchange`` selects the column-exchange schedule ("all_to_all" |
    "ring"), ``wire`` the fragment encoding that crosses ICI, and
    ``schedule``/``wave_tiles`` the frame granularity (the gather march
    is monolithic, so "waves" pipelines exchange against composite at
    column-block granularity) — see `distributed_plain_step_mxu`."""
    cfg = cfg or RenderConfig(width=width, height=height)
    axis, n, topo = resolve_mesh_topology(mesh, axis_name, topology)
    if width % n:
        raise ValueError(f"width {width} not divisible by mesh size {n}")
    knob_cfg = CompositeConfig(schedule=schedule, wave_tiles=wave_tiles,
                               rebalance=rebalance,
                               rebalance_period=rebalance_period,
                               rebalance_hysteresis=rebalance_hysteresis,
                               rebalance_min_depth=rebalance_min_depth,
                               rebalance_quantum=rebalance_quantum,
                               rebalance_bricks=rebalance_bricks,
                               rebalance_max_moves=rebalance_max_moves,
                               temporal_reuse=temporal_reuse)
    waves = _resolve_waves(knob_cfg, n, width)
    plan = _resolve_plan(knob_cfg, n, plan,
                         min_halo=(cfg.ao_radius + 1
                                   if cfg.ao_strength > 0.0 else 1))
    _bricks_inert(bricks, "the plain-image gather step")
    _resolve_reuse(knob_cfg, supported=False,
                   where="the plain-image gather step")

    # rank partials must stay background-free — the background is blended
    # exactly once, by the final composite (blending it per rank would
    # occlude farther ranks for any non-transparent background).
    # ao_strength is zeroed in the RANK config because the per-rank AO
    # field is built here from a RADIUS-DEEP halo (h = ao_radius + 1, so
    # each rank's occlusion blur sees the neighbor's slices; raycast's
    # own cfg-driven field would blur the 1-halo slab and band the
    # seams), then trimmed to the 1-halo extent the raycaster samples —
    # seam-exact vs the single-device AO render.
    rank_cfg = dataclasses.replace(cfg, background=(0.0, 0.0, 0.0, 0.0),
                                   ao_strength=0.0)
    ao_on = cfg.ao_strength > 0.0

    def step(local_data, origin, spacing, cam: Camera) -> jnp.ndarray:
        d_global = local_data.shape[0] * n
        vol, cmin, cmax, smin, smax = _local_volume_and_clip(
            local_data, origin, spacing, d_global, axis, plan=plan)
        ao_vol = None
        if ao_on:
            from scenery_insitu_tpu.ops import ao as _ao

            dn = local_data.shape[0]
            hr = cfg.ao_radius + 1
            if plan is None:
                with _phase("halo"):
                    ext = halo_exchange_z(local_data, axis, h=hr)
                n_keep = dn
            else:
                # the occlusion blur needs the radius-deep halo around
                # the PLANNED band; the trim below keeps the band's
                # 1-halo extent (matches vol.data row-for-row)
                with _phase("halo"):
                    ext = reslab_z(local_data, plan, axis, h=hr)
                n_keep = int(max(plan))
            with _phase("march"):
                occ = _ao.occlusion_field(
                    _ao.tf_alpha(Volume(ext, vol.origin, spacing), tf),
                    cfg.ao_radius, cfg.ao_strength)
            ao_vol = Volume(occ[hr - 1:hr + n_keep + 1], vol.origin,
                            spacing)
        with _phase("march"):
            out = raycast(vol, tf, cam, width, height, rank_cfg,
                          clip_min=cmin, clip_max=cmax, ao_field=ao_vol,
                          sample_min=smin, sample_max=smax)
        if waves:
            return _composite_plain_waves(out.image, out.depth, n, axis,
                                          cfg.background, exchange, wire,
                                          wave_tiles, topo=topo)
        return _composite_plain_exchanged(out.image, out.depth, n, axis,
                                          cfg.background, exchange, wire,
                                          topo=topo)

    w_axis = axis if topo is None else topo.out_axis
    f = shard_map(step, mesh=mesh,
                  in_specs=(P(axis, None, None), P(), P(), P()),
                  out_specs=P(None, None, w_axis), check_vma=False)
    return jax.jit(f)


def shard_volume(data: jnp.ndarray, mesh: Mesh,
                 axis_name: Optional[str] = None) -> jnp.ndarray:
    """Place a global volume onto the mesh z-sharded (host → HBM shards)."""
    axis = axis_name or mesh.axis_names[0]
    return jax.device_put(data, NamedSharding(mesh, P(axis, None, None)))


def frame_scan(step, advance, frames: int, temporal: bool = False,
               field=lambda s: s.field, sim_ranges: bool = False):
    """Roll ``frames`` (sim advance → render step → camera orbit)
    iterations into ONE ``lax.scan``-based jitted executable — a single
    launch per block instead of one executable launch per frame,
    amortizing the per-launch dispatch tax (docs/PERF.md hypothesis H2;
    bench.py's SCAN_FRAMES A/B measures the same lever single-chip).

    ``step``: a built frame step — any of this module's distributed
    steps or a single-chip equivalent — with signature
    ``f(field, origin, spacing, cam) -> out`` (``temporal=True``:
    ``f(field, origin, spacing, cam, thr) -> (out, thr')``).
    ``advance``: traceable one-frame sim advance, ``state -> state``.
    ``field``: extracts the rendered f32[D, H, W] field from the sim
    state (default: the ``.field`` property every built-in volume sim
    exposes).

    Returns jitted ``run(state, origin, spacing, cam, orbit_rate
    [, thr]) -> ((state', cam', thr'), outs)`` where ``outs`` stacks the
    per-frame step outputs on a leading frame axis. The camera orbits by
    ``orbit_rate`` radians AFTER each frame (pass 0.0 for a static
    camera — ``orbit(cam, 0.0)`` is exact), so frame i renders with the
    same camera the eager session loop would use. Steering (and, on the
    MXU engine, march-regime changes) can only take effect at block
    boundaries — the caller owns that check.

    ``sim_ranges=True`` threads the occupancy pyramid's sim-fused update
    through the scan body (ISSUE 6): ``advance`` must return ``(state,
    ops/occupancy.FieldRanges)`` (e.g. grayscott.multi_step_fast_ranges)
    and ``step`` gains a trailing ``ranges`` argument — frame i renders
    with the ranges its own advance emitted, so no frame in the block
    re-derives occupancy from the volume.

    Tile-wave steps (CompositeConfig.schedule == "waves") scan cleanly:
    the per-wave state lives INSIDE the step (the wave scan's
    double-buffered fragment slot; temporal threshold maps update
    wave-by-wave but cross frames as the same full-frame carry), so the
    frame scan nests a wave scan per frame — the step's
    ``wave_schedule_build`` trace event fires when the block traces.
    """
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.core.camera import orbit as _orbit

    # host-side build marker: every frame_scan() call mints one scanned
    # executable per (step, block) — the trace correlates a dispatch
    # stall with this rather than with the frames inside the block
    rec = _obs.get_recorder()
    rec.count("frame_scan_builds")
    rec.event("frame_scan_build", frames=frames, temporal=temporal,
              sim_ranges=sim_ranges)

    def run(state, origin, spacing, cam, orbit_rate, thr=None):
        def body(carry, _):
            st, cam, thr = carry
            if sim_ranges:
                with _phase("sim_step"):
                    st, rng = advance(st)
                extra = (rng,)
            else:
                with _phase("sim_step"):
                    st = advance(st)
                extra = ()
            if temporal:
                out, thr2 = step(field(st), origin, spacing, cam, thr,
                                 *extra)
            else:
                out, thr2 = step(field(st), origin, spacing, cam,
                                 *extra), thr
            return (st, _orbit(cam, orbit_rate), thr2), out

        return jax.lax.scan(body, (state, cam, thr), None, length=frames)

    return jax.jit(run)
