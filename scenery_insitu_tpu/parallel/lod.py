"""Host-side LOD level planner for multi-resolution brick maps
(docs/PERF.md "LOD marching").

"Distributed-Memory Forest-of-Octrees Raycasting" (PAPERS.md) selects
per-block refinement from data occupancy plus a screen-space error
bound and composites the resulting fragments resolution-agnostically —
exactly what our supersegment streams already are by construction. This
module is that selection policy, host-side and numpy like `slice_plan`
(`ops/occupancy.py`): the session feeds it the per-brick live fraction
(`z_live_profile`), the per-brick sampled value range
(`z_range_profile`), the TF's opacity edges
(`core.transfer.opacity_edges`) and the camera, and gets back the level
tuple a `BrickMap` carries (`parallel/bricks.py`). The march itself
never sees this code — levels change WHAT `mesh.reslab_bricks_lod`
materializes and which `step_scale` the builders pass, nothing else.

Selection order (each stage may only REFINE the previous one's pick,
except the empty shortcut; the TF gate runs last and is absolute):

1. screen-space error cap: the coarsest level whose projected voxel
   footprint stays under ``error_px`` for this brick's distance;
2. empty bricks (live fraction <= ``live_eps``) coarsen to the full
   admissible cap — air has no detail to lose;
3. hysteresis against the previous plan: refinement applies
   immediately (quality first), coarsening moves at most ONE level per
   replan and only once the error bound clears a ``1 - hysteresis``
   deadband — so a camera hovering at a level boundary cannot flap
   recompiles;
4. the TF-straddle gate: a brick whose sampled value range crosses an
   opacity edge keeps level 0, ALWAYS — pooling across an alpha
   feature can erase or invent it, and no error bound argues with
   that (tests/test_lod.py property test).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["per_brick", "admissible_max_level", "screen_error_caps",
           "select_levels", "level_work_scale", "modeled_march_flops"]


def per_brick(profile, nbricks: int, red: str = "mean") -> np.ndarray:
    """Regrid a per-z-bin profile (f32[nb]) onto ``nbricks`` bricks:
    reduce when bins are finer (``red`` = "mean" | "min" | "max"),
    repeat when coarser. Bin and brick grids must nest (one divides the
    other) — anything else means the profile was built for a different
    depth split, a caller bug."""
    prof = np.asarray(profile, np.float64)
    nb = prof.shape[0]
    if nbricks <= 0 or nb <= 0:
        raise ValueError(f"empty regrid: {nb} bins -> {nbricks} bricks")
    if nb % nbricks == 0:
        r = prof.reshape(nbricks, nb // nbricks)
        if red == "mean":
            return r.mean(axis=1)
        if red == "min":
            return r.min(axis=1)
        if red == "max":
            return r.max(axis=1)
        raise ValueError(f"unknown reduction {red!r}")
    if nbricks % nb == 0:
        return np.repeat(prof, nbricks // nb)
    raise ValueError(f"profile bins ({nb}) and bricks ({nbricks}) do "
                     f"not nest")


def admissible_max_level(brick_depth: int, h: int, w: int,
                         max_level: int) -> int:
    """The coarsest level ANY brick may take: ``2^l`` must divide the
    brick depth (BrickMap's own invariant) and the in-plane dims
    (`mesh.reslab_bricks_lod` pools whole volumes)."""
    lvl = 0
    while (lvl < max_level and brick_depth % (1 << (lvl + 1)) == 0
           and h % (1 << (lvl + 1)) == 0 and w % (1 << (lvl + 1)) == 0):
        lvl += 1
    return lvl


def _focal_px(fov_y: float, height_px: int) -> float:
    return height_px / (2.0 * math.tan(0.5 * float(fov_y)))


def screen_error_caps(centers: np.ndarray, radius: float, eye,
                      fov_y: float, height_px: int, voxel: float,
                      error_px: float, cap: int) -> np.ndarray:
    """i64[B] per-brick coarsest level whose projected voxel footprint
    stays under ``error_px``: a level-l voxel spans ``voxel * 2^l``
    world units and projects to ``voxel * 2^l * focal_px / dist``
    pixels. ``dist`` is conservative — the distance to the NEAREST
    point of the brick's bounding sphere (``radius``), floored well
    away from zero, so a brick the camera is inside always demands
    level 0."""
    eye = np.asarray(eye, np.float64).reshape(1, 3)
    dist = np.linalg.norm(centers - eye, axis=1) - float(radius)
    dist = np.maximum(dist, 1e-6)
    focal = _focal_px(fov_y, height_px)
    # largest l with voxel * 2^l * focal / dist <= error_px
    budget = error_px * dist / max(voxel * focal, 1e-12)
    lvls = np.floor(np.log2(np.maximum(budget, 1e-12)))
    return np.clip(lvls, 0, cap).astype(np.int64)


def _brick_centers(nbricks: int, dims, origin, spacing) -> np.ndarray:
    w, h, d = dims
    origin = np.asarray(origin, np.float64)
    spacing = np.asarray(spacing, np.float64)
    bz = d // nbricks
    cx = origin[0] + 0.5 * w * spacing[0]
    cy = origin[1] + 0.5 * h * spacing[1]
    cz = origin[2] + (np.arange(nbricks) + 0.5) * bz * spacing[2]
    out = np.empty((nbricks, 3), np.float64)
    out[:, 0] = cx
    out[:, 1] = cy
    out[:, 2] = cz
    return out


def select_levels(live, lo, hi, edges, *, dims, origin, spacing, eye,
                  fov_y: float, height_px: int, cfg,
                  prev: Optional[Sequence[int]] = None,
                  nbricks: int = 0) -> Tuple[int, ...]:
    """The per-brick refinement levels for one replan — host-side,
    numpy, static (the selection order in the module docstring).

    ``live``/``lo``/``hi`` are per-brick (f32[B], `per_brick`-regridded
    live fraction and clipped value range), ``edges`` the TF's active
    opacity knot positions (`opacity_edges`), ``dims`` the global
    (w, h, d) voxel dims, ``eye``/``fov_y``/``height_px`` the camera,
    ``cfg`` a `config.LODConfig`, ``prev`` the previous level tuple
    (hysteresis; None = first plan, no damping). Returns a tuple of B
    ints ready for `BrickMap.with_levels`."""
    live = np.asarray(live, np.float64)
    lo = np.asarray(lo, np.float64)
    hi = np.asarray(hi, np.float64)
    b = nbricks or live.shape[0]
    if not (live.shape[0] == lo.shape[0] == hi.shape[0] == b):
        raise ValueError(
            f"profile lengths disagree: live={live.shape[0]} "
            f"lo={lo.shape[0]} hi={hi.shape[0]} nbricks={b}")
    w, h, d = dims
    if b == 0 or d % b:
        raise ValueError(f"{b} bricks do not divide depth {d}")
    bz = d // b
    cap = admissible_max_level(bz, h, w, cfg.max_level)
    spacing_np = np.asarray(spacing, np.float64)
    voxel = float(spacing_np.max())
    centers = _brick_centers(b, dims, origin, spacing)
    radius = 0.5 * math.sqrt((w * spacing_np[0]) ** 2
                             + (h * spacing_np[1]) ** 2
                             + (bz * spacing_np[2]) ** 2)

    err_caps = screen_error_caps(centers, radius, eye, fov_y, height_px,
                                 voxel, cfg.error_px, cap)
    levels = err_caps.copy()
    if cfg.coarsen_empty:
        levels = np.where(live <= cfg.live_eps, cap, levels)

    if prev is not None and len(prev) == b:
        prev_np = np.asarray(prev, np.int64)
        # refine immediately; coarsen one level per replan and only
        # past the deadband (re-evaluate the error bound at the
        # TIGHTENED budget so a boundary-hovering camera stays put)
        damped = screen_error_caps(
            centers, radius, eye, fov_y, height_px, voxel,
            cfg.error_px * (1.0 - cfg.hysteresis), cap)
        if cfg.coarsen_empty:
            damped = np.where(live <= cfg.live_eps, cap, damped)
        coarser = levels > prev_np
        step = np.where(damped > prev_np, prev_np + 1, prev_np)
        levels = np.where(coarser, step, levels)

    if len(edges):
        e = np.asarray(edges, np.float64).reshape(1, -1)
        eps = cfg.tf_edge_eps
        straddle = np.any((e > lo[:, None] - eps)
                          & (e < hi[:, None] + eps), axis=1)
        straddle &= hi >= lo          # degenerate/absent ranges pass
        levels = np.where(straddle, 0, levels)

    return tuple(int(l) for l in levels)


def _per_slice_flops(h: int, w: int, ni: int, nj: int, f: int) -> float:
    """Modeled MXU cost of one march slice at downsample ``f``: the two
    resample matmuls [nj, H/f]@[H/f, W/f] and [nj, W/f]@[W/f, ni]
    (docs/PERF.md "The MXU slicer")."""
    hf, wf = h // f, w // f
    return 2.0 * nj * hf * wf + 2.0 * nj * wf * ni


def level_work_scale(levels, dims, ni: int, nj: int) -> np.ndarray:
    """f64[B] relative march work of each brick vs level 0 — the factor
    `runtime/session.py` multiplies into the per-brick work vector
    before `bricks.steal_plan`, so stealing equalizes MODELED cost in
    level units (a level-2 brick is ~64x cheaper than its level-0
    self, and pretending otherwise re-creates the straggler)."""
    levels = np.asarray(levels, np.int64)
    w, h, d = dims
    b = levels.shape[0]
    bz = d // b
    base = _per_slice_flops(h, w, ni, nj, 1) * bz
    out = np.empty(b, np.float64)
    for i, lvl in enumerate(levels):
        f = 1 << int(lvl)
        out[i] = _per_slice_flops(h, w, ni, nj, f) * (bz // f) / base
    return out


def modeled_march_flops(levels, dims, ni: int, nj: int) -> float:
    """Total modeled march FLOPs of one frame under a level tuple — the
    bench/projection ladder metric (`benchmarks/lod_bench.py`,
    `benchmarks/modeled_projection.py`). All-level-0 recovers the exact
    pre-LOD cost; the ratio exact/lod is the headline reduction."""
    levels = np.asarray(levels, np.int64)
    w, h, d = dims
    b = levels.shape[0]
    bz = d // b
    total = 0.0
    for lvl in levels:
        f = 1 << int(lvl)
        total += _per_slice_flops(h, w, ni, nj, f) * (bz // f)
    return total
