"""Hierarchical two-level sort-last composite across ICI domains over DCN
(docs/MULTIHOST.md; ROADMAP item 3 — the scale-out plane).

The flat pipeline composites all N ranks in one exchange, which assumes
every pair of ranks shares a fast link (one ICI domain). Past one domain
the fabric splits into a fast intra-domain level and a slow inter-domain
(DCN) level, and the composite must split with it — the "Scalable Ray
Tracing Using the Distributed FrameBuffer" shape (PAPERS.md): dense
collective compositing inside the fast domain, compressed tile exchange
between domains, incremental head assembly.

Two implementations of the same two-level algebra live here:

- **Device path** (`hier_composite_vdi` / `hier_composite_plain`): runs
  inside one SPMD program on a 2-D ``(hosts, ranks)`` mesh
  (parallel/topology.py). Level 1 exchanges fragments over the *ranks*
  sub-axis (ICI — ring or all_to_all per ``CompositeConfig.exchange``,
  the existing machinery verbatim) but STOPS before re-segmentation,
  leaving each rank a per-pixel sorted [D*K]-slot accumulator of its
  column block. Level 2 circulates column sub-blocks of those
  accumulators over the *hosts* sub-axis (DCN — a pipelined ring with
  its own wire codec, ``TopologyConfig.dcn_wire``) and merges them
  pairwise. Re-segmentation happens ONCE, at the top — which is what
  makes a hierarchical frame match the flat composite (bitwise on the
  f32 gather path; tests/test_topology.py). On one process the 2-D mesh
  over the virtual device list EMULATES the hierarchy; on a multi-pod
  runtime XLA lowers hosts-axis collectives onto DCN.

- **Host path** (`domain_partial_vdi_step` + `publish_partial_tiles` +
  `HierTileAssembler`): for runtimes whose backend cannot run
  cross-process device collectives (the CPU backend of the multiprocess
  CI harness — testing/multiproc.py) or when the DCN hop should ride the
  delivery plane. Each host runs level 1 on its LOCAL mesh, fetches the
  domain-partial accumulator, and ships its column blocks to the head as
  qpack8/delta-compressed tile streams on the PR-11 sequenced+CRC
  substrate (runtime/streaming.VDIPublisher.publish_tile); the head
  merges each tile's H partials as they arrive — incremental assembly,
  the `multihost.gather_vdi_tiles` shape generalized to merge rather
  than concatenate — and re-segments once. A lost host follows the PR-11
  failure semantics: the head composes WITHOUT it, degraded, rather than
  stalling the fleet (docs/MULTIHOST.md "Failure semantics").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from scenery_insitu_tpu.config import CompositeConfig, VDIConfig
from scenery_insitu_tpu.core.vdi import VDI
from scenery_insitu_tpu.obs.profiler import phase as _phase
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops.composite import (composite_plain,
                                              resegment_stream,
                                              sort_stream)
from scenery_insitu_tpu.parallel.mesh import halo_exchange_z
from scenery_insitu_tpu.parallel.topology import Topology
from scenery_insitu_tpu.utils.compat import shard_map

GAP_EPS = 1e-4


# ---------------------------------------------------------- traffic model

def modeled_dcn_traffic(num_hosts: int, domain_size: int, k: int,
                        height: int, width: int, dcn_wire: str = "f32",
                        ring_slots: int = 0) -> dict:
    """Modeled DCN bytes of the inter-domain hop for one frame — the
    hosts-level counterpart of ``ops.composite.modeled_exchange_traffic``
    (consumed by the hier build event, benchmarks/scaling_bench.py and
    benchmarks/modeled_projection.py).

    What crosses DCN is the level-1 accumulator: ``D * K`` slots per
    pixel lossless, ``min(D*K, ring_slots)`` under a capped ring (the
    pairwise merge truncates the accumulator to the cap — the ``+ K``
    incoming-fragment term of ``peak_stream_slots_per_pixel`` is live
    MEMORY during the merge, not shipped bytes). Each rank ships its
    ``1/(D*H)`` column sub-block to the other ``H - 1`` domains in the
    hosts-axis ring, encoded at the ``dcn_wire`` slot widths. Per-host
    numbers sum the domain's D ranks. Sent == received (a ring moves
    every block exactly once per hop)."""
    from scenery_insitu_tpu.ops.wire import wire_slot_bytes

    cb, db = wire_slot_bytes(dcn_wire)
    m = domain_size * k
    if ring_slots:
        m = min(int(ring_slots), m)
    sub = max(width // max(domain_size * num_hosts, 1), 1)
    per_rank = (num_hosts - 1) * m * height * sub * (cb + db)
    return {
        "hosts": num_hosts, "domain_size": domain_size, "k": k,
        "dcn_wire": dcn_wire, "slots_per_pixel": m,
        "dcn_bytes_sent_per_rank": per_rank,
        "dcn_bytes_sent_per_host": domain_size * per_rank,
        "dcn_bytes_received_per_host": domain_size * per_rank,
    }


def _hier_build_marker(topo: Topology, k: int, h: int, w: int,
                       comp_cfg) -> None:
    """Host-side trace-time marker of one two-level composite build
    (docs/OBSERVABILITY.md): one counter per build plus an event carrying
    the modeled intra-domain (ICI) and inter-domain (DCN) traffic."""
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.ops.composite import modeled_exchange_traffic

    rec = _obs.get_recorder()
    rec.count("hier_composite_builds")
    rec.event(
        "hier_composite_build", hosts=topo.num_hosts,
        domain_size=topo.domain_size, k=k, dcn_wire=topo.dcn_wire,
        ici=modeled_exchange_traffic(
            topo.domain_size, k, h, w,
            k_out=comp_cfg.max_output_supersegments,
            mode=comp_cfg.exchange, ring_slots=comp_cfg.ring_slots,
            wire=comp_cfg.wire),
        dcn=modeled_dcn_traffic(topo.num_hosts, topo.domain_size, k, h, w,
                                dcn_wire=topo.dcn_wire,
                                ring_slots=comp_cfg.ring_slots))


# ------------------------------------------------------------ device path

def domain_accumulate(color: jnp.ndarray, depth: jnp.ndarray, d: int,
                      ranks_axis: str, comp_cfg) -> Tuple[jnp.ndarray,
                                                          jnp.ndarray]:
    """Level 1 — the intra-domain (ICI) exchange, stopped BEFORE
    re-segmentation: this rank's 1/d column block as a per-pixel sorted,
    empty-masked accumulator of the domain's fragments ([D*K] slots
    lossless; ``ring_slots`` caps the ring accumulator exactly as in the
    flat schedule). Runs inside shard_map over the domain's mesh axis —
    the 2-D mesh's ranks sub-axis on the device path, a per-host local
    mesh on the host path."""
    from scenery_insitu_tpu.parallel.pipeline import (_exchange_vdi_columns,
                                                      _ring_accumulate,
                                                      _ring_cap)

    k = color.shape[0]
    if comp_cfg.exchange == "ring" and d > 1:
        with _phase("merge"):
            color, depth = sort_stream(color, depth)
        return _ring_accumulate(color, depth, d, ranks_axis,
                                comp_cfg.wire, _ring_cap(comp_cfg, k))
    colors, depths = _exchange_vdi_columns(color, depth, d, ranks_axis,
                                           comp_cfg.wire)
    flat_c = colors.reshape((d * k,) + colors.shape[2:])
    flat_d = depths.reshape((d * k,) + depths.shape[2:])
    with _phase("merge"):
        return sort_stream(flat_c, flat_d)


def hier_composite_vdi(color: jnp.ndarray, depth: jnp.ndarray,
                       topo: Topology, comp_cfg,
                       gap_eps: float = GAP_EPS) -> VDI:
    """The two-level sort-last VDI composite (device path; runs inside
    shard_map over the 2-D ``(hosts, ranks)`` mesh). Level 1 accumulates
    the domain's fragments over ICI, level 2 ring-merges the domain
    accumulators' column sub-blocks over DCN (``dcn_wire`` encoded), and
    the merged stream re-segments ONCE — so lossless configurations
    reproduce the flat composite exactly (the parity contract,
    tests/test_topology.py). Returns the composited VDI of this rank's
    final column block (ranks-major layout — ``Topology.out_axis``)."""
    from scenery_insitu_tpu.parallel.pipeline import _ring_accumulate

    _hier_build_marker(topo, color.shape[0], color.shape[-2],
                       color.shape[-1], comp_cfg)
    acc_c, acc_d = domain_accumulate(color, depth, topo.domain_size,
                                     topo.ranks_axis, comp_cfg)
    if topo.num_hosts > 1:
        # level 2: the accumulator is already sorted + masked — circulate
        # its column sub-blocks around the hosts (DCN) ring, lossless
        # merge (the wire codec is the DCN byte lever, not truncation)
        acc_c, acc_d = _ring_accumulate(
            acc_c, acc_d, topo.num_hosts, topo.hosts_axis, topo.dcn_wire,
            None, hop_counter="dcn_hops_built", hop_event="dcn_hop",
            hop_scope="dcn_hop")
    with _phase("resegment"):
        return resegment_stream(acc_c, acc_d, comp_cfg, gap_eps)


def hier_composite_plain(image: jnp.ndarray, depth: jnp.ndarray,
                         topo: Topology, background,
                         exchange: str, wire: str) -> jnp.ndarray:
    """The two-level plain-image composite (device path): level 1
    exchanges the domain's RGBA+depth fragments over ICI and folds them
    nearest-first into a background-free domain partial (alpha-under is
    associative over depth-ordered groups — domains are disjoint z
    bands, so the partial's min depth orders the level-2 merge), level 2
    circulates the partials over the hosts (DCN) ring at ``dcn_wire``
    precision and folds them WITH the background, exactly once."""
    from scenery_insitu_tpu import obs as _obs
    from scenery_insitu_tpu.parallel.pipeline import (_encoded_all_to_all,
                                                      _exchange_columns,
                                                      _ring_exchange_plain)
    from scenery_insitu_tpu.ops import wire as _wire

    d, h = topo.domain_size, topo.num_hosts
    rec = _obs.get_recorder()
    rec.count("hier_composite_builds")
    if exchange == "ring" and d > 1:
        images, depths = _ring_exchange_plain(image, depth, d,
                                              topo.ranks_axis, wire)
    elif wire == "f32":
        images = _exchange_columns(image, d, topo.ranks_axis)
        depths = _exchange_columns(depth, d, topo.ranks_axis)
    else:
        images, depths = _encoded_all_to_all(
            image, depth, d, topo.ranks_axis,
            lambda i, z: _wire.encode_plain(i, z, wire),
            lambda i, z, s: _wire.decode_plain(i, z, s, wire))
    with _phase("merge"):
        partial = composite_plain(images, depths, (0.0, 0.0, 0.0, 0.0))
    pdepth = jnp.min(depths, axis=0)        # nearest contribution, +inf empty
    if h == 1:
        bg = jnp.asarray(background, jnp.float32).reshape(4, 1, 1)
        return partial + (1.0 - partial[3:4]) * bg
    imgs2, deps2 = _ring_exchange_plain(
        partial, pdepth, h, topo.hosts_axis, topo.dcn_wire,
        hop_counter="dcn_hops_built", build_counter="hier_plain_levels",
        hop_scope="dcn_hop")
    with _phase("merge"):
        return composite_plain(imgs2, deps2, background)


# -------------------------------------------------------------- host path

def _offset_slab_and_clip(local_data, origin, spacing, d_global: int,
                          axis: str, n_local: int, rank_offset,
                          halo_lo, halo_hi):
    """`pipeline._local_volume_and_clip`'s multi-process twin: this
    LOCAL rank's halo-padded Volume and exclusive clip AABB when the
    local mesh covers only ranks ``[rank_offset, rank_offset + n_local)``
    of an ``n_total``-rank global decomposition. Cross-host halo rows
    (``halo_lo``/``halo_hi``, each [1, H, W]) replace the clamped copies
    on the host-boundary ranks — pass the host's own boundary slice at
    the global edges to keep the single-device CLAMP_TO_EDGE semantics,
    and the neighbor host's boundary slice elsewhere (the harness ships
    them host-side; one slice per seam per frame)."""
    rl = jax.lax.axis_index(axis)
    r = rank_offset + rl                               # global rank
    dn = local_data.shape[0]
    dz = spacing[2]
    halo = halo_exchange_z(local_data, axis)           # [Dn+2, H, W]
    bottom = jnp.where(jnp.equal(rl, 0), halo_lo, halo[:1])
    top = jnp.where(jnp.equal(rl, n_local - 1), halo_hi, halo[-1:])
    halo = jnp.concatenate([bottom, halo[1:-1], top], axis=0)
    local_origin = origin.at[2].add((r * dn - 1) * dz)
    z_lo = origin[2] + r * dn * dz
    z_hi = origin[2] + (r + 1) * dn * dz
    vol = Volume(halo, local_origin, spacing)
    hh, w = local_data.shape[1], local_data.shape[2]
    gmax = origin + jnp.array([w, hh, d_global], jnp.float32) * spacing
    clip_min = jnp.stack([origin[0], origin[1], z_lo])
    clip_max = jnp.stack([gmax[0], gmax[1], z_hi])
    return vol, clip_min, clip_max, origin, gmax


def domain_partial_vdi_step(mesh, tf, width: int, height: int,
                            vdi_cfg: Optional[VDIConfig] = None,
                            comp_cfg: Optional[CompositeConfig] = None,
                            max_steps: int = 256,
                            axis_name: Optional[str] = None,
                            rank_offset: int = 0,
                            n_total: Optional[int] = None):
    """Build THIS HOST's half of the two-level composite (host path):
    generate on the host's slice of the global z decomposition, exchange
    + merge over the LOCAL mesh (level 1, ICI), and return the
    domain-partial accumulator — NOT re-segmented; that happens once, on
    the head, after the DCN hop (`HierTileAssembler`).

    Returns ``f(local_data f32[D_host, H, W] (z-sharded on the local
    mesh), origin f32[3] (GLOBAL), spacing f32[3], cam, halo_lo
    f32[1, H, W], halo_hi f32[1, H, W]) -> (acc_color [M, 4, height,
    width], acc_depth [M, 2, height, width])`` W-sharded over the local
    mesh, ``M = D_local * K`` (or ring_slots + K capped). ``rank_offset``
    / ``n_total`` place the host in the global decomposition (process p
    of H hosts with D-rank domains passes ``rank_offset=p*D,
    n_total=H*D``)."""
    from scenery_insitu_tpu.ops.vdi_gen import generate_vdi

    vdi_cfg = vdi_cfg or VDIConfig()
    comp_cfg = comp_cfg or CompositeConfig()
    axis = axis_name or mesh.axis_names[0]
    d = mesh.shape[axis]
    nt = n_total or d
    if width % (d or 1):
        raise ValueError(f"width {width} not divisible by the local mesh "
                         f"size {d}")

    def step(local_data, origin, spacing, cam, halo_lo, halo_hi):
        d_global = local_data.shape[0] * nt
        vol, cmin, cmax, smin, smax = _offset_slab_and_clip(
            local_data, origin, spacing, d_global, axis, d, rank_offset,
            halo_lo, halo_hi)
        vdi, _ = generate_vdi(vol, tf, cam, width, height, vdi_cfg,
                              max_steps=max_steps, clip_min=cmin,
                              clip_max=cmax, sample_min=smin,
                              sample_max=smax)
        return domain_accumulate(vdi.color, vdi.depth, d, axis, comp_cfg)

    f = shard_map(step, mesh=mesh,
                  in_specs=(P(axis, None, None), P(), P(), P(), P(), P()),
                  out_specs=(P(None, None, None, axis),
                             P(None, None, None, axis)),
                  check_vma=False)
    return jax.jit(f)


def publish_partial_tiles(pub, acc_c, acc_d, meta, tiles: int) -> int:
    """Ship one host's domain-partial accumulator over DCN as the PR-11
    tile stream (docs/MULTIHOST.md "DCN wire protocol"): ``tiles``
    column blocks through ``VDIPublisher.publish_tile`` — seq + epoch +
    CRC continuity, optional qpack8 pre-codec and temporal-delta records
    all inherited from the substrate. Returns the wire bytes sent
    (counted on the ``dcn_bytes_sent`` obs counter, one ``dcn_send``
    span per tile)."""
    from scenery_insitu_tpu import obs as _obs

    c = np.ascontiguousarray(np.asarray(acc_c))
    d = np.ascontiguousarray(np.asarray(acc_d))
    wb = c.shape[-1] // tiles
    rec = _obs.get_recorder()
    sent = 0
    for t in range(tiles):
        with rec.span("dcn_send", frame=int(np.asarray(meta.index)),
                      tile=t):
            nb = pub.publish_tile(
                VDI(c[..., t * wb:(t + 1) * wb],
                    d[..., t * wb:(t + 1) * wb]),
                meta, tile=t, tiles=tiles, col0=t * wb)
        rec.count("dcn_bytes_sent", nb)
        sent += nb
    return sent


def merge_partial_blocks(parts: List[Tuple[np.ndarray, np.ndarray]],
                         comp_cfg, gap_eps: float = GAP_EPS) -> VDI:
    """Head-side top of the two-level composite: merge the H domains'
    partial accumulators for the SAME columns into the final composited
    block — concatenate, per-pixel sort, re-segment ONCE (the same fold
    the flat composite runs after its global sort, so a complete merge
    is parity-exact with the flat frame). Jitted per shape on the head's
    local device."""
    flat_c = jnp.concatenate([jnp.asarray(c) for c, _ in parts], axis=0)
    flat_d = jnp.concatenate([jnp.asarray(z) for _, z in parts], axis=0)
    return _merge_resegment(flat_c, flat_d, comp_cfg, gap_eps)


def _merge_resegment(flat_c, flat_d, comp_cfg, gap_eps):
    from functools import partial

    @partial(jax.jit, static_argnums=(2, 3))
    def run(c, z, cfg, eps):
        sc, sd = sort_stream(c, z)
        return resegment_stream(sc, sd, cfg, eps)

    return run(flat_c, flat_d, comp_cfg, gap_eps)


class HierTileAssembler:
    """Incremental head-node assembly of the hosts' domain-partial tile
    streams — ``multihost.gather_vdi_tiles`` generalized from
    concatenation to a sort-last MERGE (docs/MULTIHOST.md): feed each
    arriving ``(host, vdi, meta, tile)`` from the per-host
    `VDISubscriber.receive_tile`; the moment a column block has all
    ``num_hosts`` partials it merges + re-segments and is emitted — the
    head publishes the first columns while later tiles are still in
    flight.

    A host that stays silent past ``frame window`` frames follows the
    PR-11 HeadNode semantics: `flush_incomplete` composes the block from
    the partials that DID arrive, stamps it degraded and ledgers
    ``multihost.host_down`` — one lost host costs its slab's content,
    not the frame."""

    def __init__(self, num_hosts: int, comp_cfg=None,
                 gap_eps: float = GAP_EPS):
        self.num_hosts = num_hosts
        self.comp_cfg = comp_cfg or CompositeConfig()
        self.gap_eps = gap_eps
        # (frame, tile) -> {host: (color, depth)}
        self._parts: Dict[Tuple[int, int], Dict[int, tuple]] = {}
        self.stats = {"tiles_in": 0, "blocks_out": 0, "degraded": 0,
                      "dcn_bytes_received": 0}

    def add(self, host: int, vdi, meta, tile: dict,
            nbytes: int = 0) -> List[tuple]:
        """Feed one received tile; returns the finished blocks it
        completes as ``[(frame, tile_idx, col0, VDI, degraded)]``."""
        from scenery_insitu_tpu import obs as _obs

        rec = _obs.get_recorder()
        frame = int(np.asarray(meta.index))
        key = (frame, int(tile["tile"]))
        self.stats["tiles_in"] += 1
        if nbytes:
            self.stats["dcn_bytes_received"] += nbytes
            rec.count("dcn_bytes_received", nbytes)
        slot = self._parts.setdefault(key, {})
        slot[int(host)] = (np.asarray(vdi.color), np.asarray(vdi.depth),
                           int(tile["col0"]))
        if len(slot) < self.num_hosts:
            return []
        return [self._emit(key, degraded=False)]

    def _emit(self, key, degraded: bool) -> tuple:
        from scenery_insitu_tpu import obs as _obs

        slot = self._parts.pop(key)
        col0 = next(iter(slot.values()))[2]
        with _obs.get_recorder().span("dcn_merge", frame=key[0],
                                      tile=key[1]):
            out = merge_partial_blocks(
                [(c, d) for c, d, _ in
                 (slot[h] for h in sorted(slot))],
                self.comp_cfg, self.gap_eps)
        self.stats["blocks_out"] += 1
        if degraded:
            self.stats["degraded"] += 1
        return (key[0], key[1], col0, out, degraded)

    def flush_incomplete(self) -> List[tuple]:
        """Compose every pending block from the partials that arrived —
        the lost-host degraded path (PR-11 HeadNode semantics): emitted
        blocks carry ``degraded=True`` and each missing host lands on
        the ledger as ``multihost.host_down``."""
        from scenery_insitu_tpu import obs as _obs

        out = []
        for key in sorted(self._parts):
            missing = self.num_hosts - len(self._parts[key])
            _obs.degrade(
                "multihost.host_down", f"{self.num_hosts} hosts",
                f"{self.num_hosts - missing} hosts",
                "a host's domain partial never arrived; the block "
                "composites without its slab content (degraded)",
                warn=False)
            out.append(self._emit(key, degraded=True))
        return out


def assemble_hier_frame(subs, num_hosts: int, comp_cfg=None,
                        tiles: Optional[int] = None,
                        timeout_ms: int = 10_000,
                        gap_eps: float = GAP_EPS):
    """Convenience head loop over per-host subscribers: drain ``tiles``
    column blocks from every host's stream, merge incrementally, return
    the assembled frame ``(VDI, degraded)`` in column order. ``subs`` is
    ``{host_index: VDISubscriber}``. Hosts that time out degrade (their
    content is dropped, the frame still assembles) — the chaos-tested
    PR-11 contract rather than a fleet-wide stall."""
    import time as _time

    asm = HierTileAssembler(num_hosts, comp_cfg, gap_eps)
    done: Dict[int, tuple] = {}
    want: Optional[int] = tiles
    deadline = _time.monotonic() + timeout_ms / 1000.0
    alive = dict(subs)
    while alive and (want is None or len(done) < want):
        if _time.monotonic() > deadline:
            break
        for host, sub in list(alive.items()):
            got = sub.receive_tile(timeout_ms=200)
            if got is None or hasattr(got, "kind"):      # timeout / drop
                continue
            vdi, meta, tile = got
            if tile is None:
                continue
            if want is None:
                want = int(tile["tiles"])
            nb = getattr(sub, "last_recv_bytes", 0)
            for frame, t, col0, block, deg in asm.add(host, vdi, meta,
                                                      tile, nbytes=nb):
                done[t] = (col0, block, deg)
    degraded = False
    for frame, t, col0, block, deg in asm.flush_incomplete():
        if t not in done:
            done[t] = (col0, block, deg)
            degraded = True
    if not done:
        return None, True
    blocks = [done[t] for t in sorted(done)]
    color = np.concatenate([np.asarray(b.color) for _, b, _ in blocks],
                           axis=-1)
    depth = np.concatenate([np.asarray(b.depth) for _, b, _ in blocks],
                           axis=-1)
    degraded = degraded or any(d for _, _, d in blocks)
    return VDI(color, depth), degraded
