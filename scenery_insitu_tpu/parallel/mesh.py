"""Device mesh construction and halo exchange.

This replaces the reference's rank/commSize bookkeeping received from MPI
through JNI (reference DistributedVolumes.kt:103-117): here the "communicator"
is a ``jax.sharding.Mesh`` and collectives are XLA ops over ICI/DCN, not
NCCL/MPI calls.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scenery_insitu_tpu.config import MeshConfig

DEFAULT_AXIS = "ranks"


def make_mesh(num_devices: int = 0, axis_name: str = DEFAULT_AXIS,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1D mesh over the compositing axis (≅ MPI COMM_WORLD of render ranks).
    num_devices == 0 → all local devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if num_devices:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devs)}")
        devs = devs[:num_devices]
    import numpy as np
    return Mesh(np.array(devs), (axis_name,))


def from_config(cfg: MeshConfig) -> Mesh:
    return make_mesh(cfg.num_devices, cfg.axis_name)


def volume_sharding(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    """Shard a global volume f32[D, H, W] along z (domain decomposition;
    ≅ OpenFPM splitting the grid across ranks)."""
    return NamedSharding(mesh, P(axis_name, None, None))


def halo_exchange_z(local: jnp.ndarray, axis_name: str = DEFAULT_AXIS,
                    h: int = 1) -> jnp.ndarray:
    """Pad a z-sharded block f32[Dn, H, W] with ``h`` neighbor slices on
    each side via ``ppermute`` over ICI → f32[Dn+2h, H, W].

    Edge ranks receive clamped copies of their own boundary slice,
    matching the single-device CLAMP_TO_EDGE sampling exactly — so
    distributed trilinear interpolation (h=1) AND radius-deep
    neighborhood operators like the AO box blur (h=radius+1) are
    seam-exact vs a single-device render (the reference's per-rank Volume
    nodes cannot interpolate across rank boundaries at all). ``h`` may
    not exceed the slab depth — deeper halos would need multi-hop
    exchanges; use fewer ranks or a smaller radius instead.
    """
    from scenery_insitu_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    dn = local.shape[0]
    if h > dn:
        raise ValueError(
            f"halo depth {h} exceeds the {dn}-slice slab — a neighbor "
            "holds fewer slices than the halo needs (shrink ao_radius or "
            "use fewer ranks / a deeper slab; planned render bands go "
            "through reslab_z, whose floor is min(plan), not D//n)")
    clamp_bot = jnp.repeat(local[:1], h, axis=0)
    clamp_top = jnp.repeat(local[-1:], h, axis=0)
    if n == 1:
        return jnp.concatenate([clamp_bot, local, clamp_top], axis=0)
    up = [(i, (i + 1) % n) for i in range(n)]
    down = [(i, (i - 1) % n) for i in range(n)]
    from_below = jax.lax.ppermute(local[-h:], axis_name, up)   # r-1's last h
    from_above = jax.lax.ppermute(local[:h], axis_name, down)  # r+1's first h
    bottom = jnp.where(idx == 0, clamp_bot, from_below)
    top = jnp.where(idx == n - 1, clamp_top, from_above)
    return jnp.concatenate([bottom, local, top], axis=0)


def validate_plan(plan, n: int, h: int = 1,
                  knob: str = "composite.rebalance_min_depth") -> tuple:
    """Static validation of a render z-plan (one band depth per rank).

    The min-slab constraint of a planned decomposition is ``min(plan)``,
    not ``D // n``: the shallowest band must still hold the deepest halo
    any consumer needs (1 slice for seam-exact trilinear; ``ao_radius +
    1`` for AO pre-shading). The diagnostic names the offending rank and
    the knob that fixes it."""
    plan = tuple(int(p) for p in plan)
    if len(plan) != n:
        raise ValueError(f"render plan has {len(plan)} bands for {n} "
                         f"ranks")
    if min(plan) < max(h, 1):
        r = min(range(n), key=lambda i: plan[i])
        raise ValueError(
            f"render plan band of rank {r} is {plan[r]} slice(s) deep — "
            f"below the {h}-slice halo this step needs (min-slab "
            f"constraint is min(plan), not D//n; raise {knob} to >= {h} "
            f"or use fewer ranks)")
    return plan


def _reslab_rows(local: jnp.ndarray, g_all, live_all,
                 axis_name: str = DEFAULT_AXIS) -> jnp.ndarray:
    """Materialize per-rank row sets from even z-shards — the shared
    core of `reslab_z` (contiguous bands) and `reslab_bricks`
    (arbitrary brick sets).

    ``g_all`` i32[n, R]: each rank's clamped GLOBAL source row per
    output row; ``live_all`` bool[n, R]: rows to fill (dead rows stay
    zero). Both are static numpy — the ladder is build-time geometry.
    Mechanism: one ``ppermute`` rotation per distinct (source − dest)
    shard offset any live row needs; each received even shard
    contributes its rows via a masked row gather. Near-even plans need
    2-3 hops; an adversarial brick map can need up to n-1 (correctness
    first — the steal planner's move cap keeps production maps local)."""
    import numpy as np

    from scenery_insitu_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    dn = local.shape[0]
    g_all = np.asarray(g_all, np.int64)
    live_all = np.asarray(live_all, bool)
    offsets = sorted({int(o) for r in range(n)
                      for o in np.unique(g_all[r][live_all[r]] // dn) - r
                      } or {0})

    ri = jax.lax.axis_index(axis_name)
    g = jnp.asarray(g_all, jnp.int32)[ri]                 # [R]
    live = jnp.asarray(live_all)[ri]                      # [R]
    src = g // dn                                         # absolute source
    loc = g - src * dn                                    # row within shard
    bshape = (g_all.shape[1],) + (1,) * (local.ndim - 1)
    out = jnp.zeros((g_all.shape[1],) + local.shape[1:], local.dtype)
    for o in offsets:
        if o == 0:
            recv = local
        else:
            perm = [(i, (i - o) % n) for i in range(n)]
            recv = jax.lax.ppermute(local, axis_name, perm)
        sel = (src == ri + o) & live
        out = jnp.where(sel.reshape(bshape), jnp.take(recv, loc, axis=0),
                        out)
    return out


def reslab_z(local: jnp.ndarray, plan, axis_name: str = DEFAULT_AXIS,
             h: int = 1) -> jnp.ndarray:
    """Materialize this rank's PLANNED render band from the even z-slab
    shards (docs/PERF.md "Render rebalancing"): the sim sharding stays
    the even ``[Dn, H, W]`` split, and each rank assembles the contiguous
    global band ``[start_r - h, start_r + plan[r] + h)`` where ``start_r
    = sum(plan[:r])`` — with exactly `halo_exchange_z`'s boundary
    contract (edge halos are clamped copies of the global boundary
    slice, so distributed interpolation stays seam-exact vs a
    single-device render).

    shard_map needs one static shape per program, so every rank's band
    pads to ``max(plan) + 2h`` rows; rows past a rank's own ``plan[r] +
    2h`` are ZERO (the march masks them by its ownership bounds, and the
    occupancy pyramid admits zero for padded chunks, so skipping eats
    the padding).

    Mechanism: one ``ppermute`` rotation per distinct (source − dest)
    rank offset any band needs — near-even plans (the hysteresis/quantum
    regime) need 2-3 hops, like the halo exchange; each received even
    shard contributes its overlapping rows via a masked row gather. An
    even plan reproduces ``halo_exchange_z(local, h=h)`` exactly
    (row-for-row; tests assert equality)."""
    import numpy as np

    from scenery_insitu_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    plan = validate_plan(plan, n, h=h)
    dn = local.shape[0]
    d = dn * n
    if sum(plan) != d:
        raise ValueError(f"render plan {plan} covers {sum(plan)} slices "
                         f"but the volume has {d}")
    starts = np.concatenate([[0], np.cumsum(plan)])[:n]
    out_depth = max(plan) + 2 * h
    # clamped global row ladder of every dest rank's output buffer
    lo = starts - h                                       # may be negative
    g_all = np.clip(lo[:, None] + np.arange(out_depth)[None, :], 0, d - 1)
    live_all = (np.arange(out_depth)[None, :]
                < (np.asarray(plan)[:, None] + 2 * h))    # trailing pad dead
    return _reslab_rows(local, g_all, live_all, axis_name)


def reslab_bricks(local: jnp.ndarray, bmap, axis_name: str = DEFAULT_AXIS,
                  h: int = 1) -> jnp.ndarray:
    """Materialize this rank's BRICK SET from the even z-slab shards
    (docs/SCENARIOS.md "Brick maps"): ``bmap`` is a
    `parallel.bricks.BrickMap`; each of the rank's ``bmap.slots`` slots
    holds one brick's global rows ``[start - h, start + bz + h)`` with
    exactly `halo_exchange_z`'s boundary contract (rows clamp only at
    the GLOBAL edges; interior brick faces receive their true
    neighbors, whichever rank owns them — what keeps per-brick
    interpolation seam-exact under any ownership). Absent slots (a rank
    owning fewer bricks than the busiest) come back all-zero.

    Returns ``[slots, bz + 2h, H, W]`` — `_reslab_rows` does the
    ppermute routing on the flattened ladder."""
    import numpy as np

    from scenery_insitu_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    if bmap.n_ranks != n:
        raise ValueError(f"brick map built for {bmap.n_ranks} ranks on a "
                         f"{n}-rank mesh")
    dn = local.shape[0]
    d = dn * n
    if bmap.depth != d:
        raise ValueError(f"brick map covers depth {bmap.depth} but the "
                         f"volume has {d} slices")
    bz = bmap.brick_depth
    rows = bz + 2 * h
    table = bmap.start_table()                            # [n, B]
    ladder = np.arange(rows)[None, None, :] - h
    g_all = np.clip(table[:, :, None] + ladder, 0, d - 1)
    live_all = np.broadcast_to((table >= 0)[:, :, None], g_all.shape)
    out = _reslab_rows(local, g_all.reshape(n, -1),
                       live_all.reshape(n, -1), axis_name)
    return out.reshape((bmap.slots, rows) + local.shape[1:])


def reslab_bricks_lod(local: jnp.ndarray, bmap,
                      axis_name: str = DEFAULT_AXIS, h: int = 1):
    """Materialize this rank's MULTI-RESOLUTION brick set from the even
    z-slab shards (docs/PERF.md "LOD marching"): the level-aware twin of
    `reslab_bricks`. Returns ``{level: [slots_at(level), bz/f + 2h,
    H/f, W/f]}`` for every level present in the map (f = 2^level) —
    downsampling happens HERE, on device, after the ppermute routing of
    the FINE rows, so HBM holds fine data only for level-0 bricks.

    Per level, each slot gathers the fine global rows ``[start - h*f,
    start + bz + h*f)`` (the halo deepens with the level so the pooled
    copy still carries ``h`` COARSE halo rows, with exactly
    `halo_exchange_z`'s boundary contract at the global edges) and
    average-pools by ``f`` in all three dims — f32 accumulation, cast
    back to the input dtype, so a bf16 render copy pools without
    compounding rounding. A coarse voxel tiles ``f^3`` fine voxels
    exactly: the pooled volume keeps the band's corner origin with
    ``spacing * f`` (the corner-origin convention makes the pooled
    centers land where trilinear expects them — no half-voxel shift).

    The brick depth divides by ``f`` by BrickMap construction; the
    in-plane extents must too — a clear error here, not a silent
    mis-shape. Level 0 reproduces `reslab_bricks`' rows bit-for-bit
    (same ladder, same routing, no pooling)."""
    import numpy as np

    from scenery_insitu_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    if bmap.n_ranks != n:
        raise ValueError(f"brick map built for {bmap.n_ranks} ranks on a "
                         f"{n}-rank mesh")
    dn = local.shape[0]
    d = dn * n
    if bmap.depth != d:
        raise ValueError(f"brick map covers depth {bmap.depth} but the "
                         f"volume has {d} slices")
    bz = bmap.brick_depth
    hh, ww = local.shape[1], local.shape[2]
    out = {}
    for lvl in bmap.levels_present():
        f = 1 << lvl
        if hh % f or ww % f:
            raise ValueError(
                f"brick level {lvl} pools by {f} but the in-plane "
                f"extents ({hh}, {ww}) do not divide — cap "
                f"lod.max_level so 2^level tiles every axis")
        rows_f = bz + 2 * h * f
        table = bmap.start_table_at(lvl)                  # [n, B_l]
        slots = table.shape[1]
        ladder = np.arange(rows_f)[None, None, :] - h * f
        g_all = np.clip(table[:, :, None] + ladder, 0, d - 1)
        live_all = np.broadcast_to((table >= 0)[:, :, None], g_all.shape)
        fine = _reslab_rows(local, g_all.reshape(n, -1),
                            live_all.reshape(n, -1), axis_name)
        fine = fine.reshape((slots, rows_f) + local.shape[1:])
        if f == 1:
            out[lvl] = fine
            continue
        x = fine.reshape(slots, rows_f // f, f, hh // f, f, ww // f, f)
        x = jnp.mean(x.astype(jnp.float32), axis=(2, 4, 6))
        out[lvl] = x.astype(local.dtype)
    return out
