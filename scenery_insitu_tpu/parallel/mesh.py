"""Device mesh construction and halo exchange.

This replaces the reference's rank/commSize bookkeeping received from MPI
through JNI (reference DistributedVolumes.kt:103-117): here the "communicator"
is a ``jax.sharding.Mesh`` and collectives are XLA ops over ICI/DCN, not
NCCL/MPI calls.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scenery_insitu_tpu.config import MeshConfig

DEFAULT_AXIS = "ranks"


def make_mesh(num_devices: int = 0, axis_name: str = DEFAULT_AXIS,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1D mesh over the compositing axis (≅ MPI COMM_WORLD of render ranks).
    num_devices == 0 → all local devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if num_devices:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devs)}")
        devs = devs[:num_devices]
    import numpy as np
    return Mesh(np.array(devs), (axis_name,))


def from_config(cfg: MeshConfig) -> Mesh:
    return make_mesh(cfg.num_devices, cfg.axis_name)


def volume_sharding(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    """Shard a global volume f32[D, H, W] along z (domain decomposition;
    ≅ OpenFPM splitting the grid across ranks)."""
    return NamedSharding(mesh, P(axis_name, None, None))


def halo_exchange_z(local: jnp.ndarray, axis_name: str = DEFAULT_AXIS,
                    h: int = 1) -> jnp.ndarray:
    """Pad a z-sharded block f32[Dn, H, W] with ``h`` neighbor slices on
    each side via ``ppermute`` over ICI → f32[Dn+2h, H, W].

    Edge ranks receive clamped copies of their own boundary slice,
    matching the single-device CLAMP_TO_EDGE sampling exactly — so
    distributed trilinear interpolation (h=1) AND radius-deep
    neighborhood operators like the AO box blur (h=radius+1) are
    seam-exact vs a single-device render (the reference's per-rank Volume
    nodes cannot interpolate across rank boundaries at all). ``h`` may
    not exceed the slab depth — deeper halos would need multi-hop
    exchanges; use fewer ranks or a smaller radius instead.
    """
    from scenery_insitu_tpu.utils.compat import axis_size
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    dn = local.shape[0]
    if h > dn:
        raise ValueError(
            f"halo depth {h} exceeds the {dn}-slice slab — a neighbor "
            "holds fewer slices than the halo needs (shrink ao_radius or "
            "use fewer ranks / a deeper slab)")
    clamp_bot = jnp.repeat(local[:1], h, axis=0)
    clamp_top = jnp.repeat(local[-1:], h, axis=0)
    if n == 1:
        return jnp.concatenate([clamp_bot, local, clamp_top], axis=0)
    up = [(i, (i + 1) % n) for i in range(n)]
    down = [(i, (i - 1) % n) for i in range(n)]
    from_below = jax.lax.ppermute(local[-h:], axis_name, up)   # r-1's last h
    from_above = jax.lax.ppermute(local[:h], axis_name, down)  # r+1's first h
    bottom = jnp.where(idx == 0, clamp_bot, from_below)
    top = jnp.where(idx == n - 1, clamp_top, from_above)
    return jnp.concatenate([bottom, local, top], axis=0)
