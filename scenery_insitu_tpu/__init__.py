"""scenery_insitu_tpu — a TPU-native in-situ distributed visualization framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
``Brockaaa/scenery-insitu`` (Kotlin/Vulkan/MPI/SysV-shm): in-situ volume
rendering of distributed simulations via Volumetric Depth Images (VDIs),
sort-last compositing over device meshes, particle rendering, simulation
ingest, steering and streaming.

Conventions (chosen once, used everywhere — the reference mixed NDC-z,
world-length and integer-step depth encodings behind #defines and needed a
converter pass to clean up; see /root/reference
src/test/resources/.../VDIGenerator.comp:41-45 and ConvertToNDC.comp):

- Volumes are scalar fields ``f32[D, H, W]`` indexed ``vol[z, y, x]`` with a
  world-space ``origin`` and per-axis ``spacing`` (Volume dataclass).
- Images are channels-first on device: ``f32[4, H, W]`` premultiplied RGBA,
  converted to ``[H, W, 4]`` only at host/API boundaries. (H, W) occupy the
  TPU (sublane, lane) tile dims.
- VDIs store per-pixel supersegment lists with a *fixed* K
  (``max_supersegments``) so every shape is static under jit:
  ``color f32[K, 4, H, W]`` (premultiplied RGBA), ``depth f32[K, 2, H, W]``
  (start/end). Unused slots have alpha == 0 and depth == (inf, inf).
- Supersegment depths are the world-space ray parameter ``t`` of the *shared*
  camera (all ranks render with the same camera, so t is comparable across
  ranks per pixel and reconstructs world positions exactly:
  ``w = origin + t * dir``).
- Camera matrices follow the OpenGL convention (right-handed, camera looks
  down -z, NDC z in [-1, 1]); helpers in core.camera.
"""

__version__ = "0.1.0"

from scenery_insitu_tpu.config import FrameworkConfig  # noqa: F401
