"""Temporal-delta VDI streams (docs/PERF.md "Temporal deltas").

Frames of an in-situ run are temporally coherent, yet the pre-delta
pipeline re-marched, re-encoded and re-published every frame from
scratch. The reference ships H264 — an inter-frame codec — for exactly
this reason (SURVEY §2, VideoEncoder); here the same delta principle is
applied to the VDI representation itself, in two stacked plays:

**P-frame wire codec (host side).** The qpack8 quantizer
(ops/wire.qpack8_quantize_np) is monotone and deterministic, so two
frames of the same tile can be compared EXACTLY in code space: equal
codes + equal [near, far] scale means the dequantized tile is
bit-identical. Per published tile (the PR-8 column block is the dirty
unit) `DeltaEncoder` retains the previous frame's code arrays and emits
one of three records:

- ``SKIP``   codes and scale unchanged — the wire carries only the
             continuity header (~100 B vs a full compressed tile);
- ``P``      a sparse residual: runs of changed code slots (start,
             length) plus the new code values, chosen only when it is
             smaller than a full tile;
- ``I``      the full code arrays — the first contact, every
             ``delta.iframe_period`` frames (forced, so a joining or
             recovering subscriber is whole again within one period),
             after a ``reset()`` (scene cut), and whenever a residual
             would not pay.

`DeltaDecoder` holds the mirrored per-tile state and reconstructs the
current frame's codes BIT-EXACTLY from (last retained tile + residual).
Records chain through a per-tile generation counter: a P/SKIP record
names the generation it patches, so a dropped message simply breaks the
chain and the decoder answers ``None`` — "wait for the next I-tile" —
which the subscriber ledgers as ``stream.delta_resync`` (the PR-11
seq/epoch/CRC machinery is the recovery substrate).

**Dirty-region re-marching (device side).** ``CompositeConfig.
temporal_reuse = "ranges"`` carries each rank's previous marched VDI
fragment across frames (`ReuseState`) together with a dirty
*signature*: the occupancy pyramid's per-(chunk × v-tile) [lo, hi]
value ranges — already computed every frame since PR 6 — concatenated
with the camera pose. A rank whose signature moved by at most
``delta.range_tol`` (and whose camera is bit-unchanged) skips the march
entirely (`lax.cond` — the matmul waves never issue) and feeds last
frame's fragment to the unchanged exchange + composite. The detector is
conservative ON THE SIGNAL: any range motion beyond the tolerance
re-marches; a field change that preserves every per-brick [lo, hi]
exactly is invisible to it — that is the contract of a range-based
detector, and ``range_tol = 0`` with a static camera makes reuse
bit-exact against recompute for any scene the signature can see.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import numpy as np

DELTA_MODES = ("I", "P", "SKIP")

# wire cost of one changed-slot run: u32 start + u32 length
_RUN_BYTES = 8


# ======================================================================
# host-side code-space residuals (numpy)
# ======================================================================


def diff_runs(prev: np.ndarray, cur: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Changed-slot runs of ``cur`` against ``prev`` (same shape/dtype,
    compared flat): returns ``(starts u32[R], lengths u32[R], values[N])``
    where ``values`` are ``cur``'s codes at the changed slots in flat
    order (``N == lengths.sum()``). Code arrays compare exactly —
    integer codes, no epsilon."""
    if prev.shape != cur.shape or prev.dtype != cur.dtype:
        raise ValueError(f"delta operands disagree: {prev.shape}/"
                         f"{prev.dtype} vs {cur.shape}/{cur.dtype}")
    p, c = prev.ravel(), cur.ravel()
    changed = p != c
    idx = np.flatnonzero(changed)
    if idx.size == 0:
        return (np.zeros(0, np.uint32), np.zeros(0, np.uint32),
                np.zeros(0, cur.dtype))
    brk = np.flatnonzero(np.diff(idx) > 1)
    starts = idx[np.concatenate([[0], brk + 1])]
    ends = idx[np.concatenate([brk, [idx.size - 1]])]
    return (starts.astype(np.uint32),
            (ends - starts + 1).astype(np.uint32), c[changed])


def apply_runs(base: np.ndarray, starts: np.ndarray, lengths: np.ndarray,
               values: np.ndarray) -> np.ndarray:
    """Inverse of `diff_runs`: patch ``base`` (flat view of the retained
    tile) with the residual → the current tile, bit-exact."""
    out = base.copy().ravel()
    if starts.size:
        total = int(lengths.sum())
        if total != values.size:
            raise ValueError(f"residual says {total} changed slots but "
                             f"carries {values.size} values")
        off = np.cumsum(lengths) - lengths      # value offset of each run
        idx = (np.repeat(starts.astype(np.int64), lengths)
               + np.arange(total) - np.repeat(off.astype(np.int64),
                                              lengths))
        if idx.size and idx[-1] >= out.size:
            raise ValueError("residual run exceeds the tile extent")
        out[idx] = values
    return out.reshape(base.shape)


def runs_wire_bytes(starts: np.ndarray, values: np.ndarray) -> int:
    """Pre-codec wire bytes of one residual stream: (start, length)
    pairs plus the changed code values."""
    return starts.size * _RUN_BYTES + values.size * values.dtype.itemsize


class DeltaRecord(NamedTuple):
    """One encoded tile: what `DeltaEncoder.encode` hands the transport
    (io/vdi_io.pack_delta_blobs serializes it). ``c_payload``/
    ``d_payload`` are ``(codes,)`` for I, ``(starts, lengths, values)``
    for P, ``()`` for SKIP. ``full_bytes``/``wire_bytes`` are pre-codec
    code bytes — the publish-traffic accounting (compressed sizes are
    the transport's to report)."""

    mode: str
    gen: int
    base_gen: int            # generation this record patches (I: -1)
    c_payload: tuple
    d_payload: tuple
    scale: Tuple[float, float]
    full_bytes: int
    wire_bytes: int
    reason: str              # why this mode ("periodic", "reset", ...)


def _full_bytes(ccodes: np.ndarray, dcodes: np.ndarray) -> int:
    return ccodes.nbytes + dcodes.nbytes


class DeltaEncoder:
    """Publisher-side P-frame state machine: one instance per stream,
    keyed by tile index (``-1`` for whole-frame publishes). Retains the
    previous frame's qpack8 code arrays per tile and chooses
    SKIP / P / I per `encode` call; mints the delta counters
    (docs/OBSERVABILITY.md): ``delta_tiles_skipped``,
    ``delta_bytes_saved`` and ``iframe_forced``."""

    def __init__(self, iframe_period: int = 8):
        if iframe_period < 1:
            raise ValueError(f"iframe_period must be >= 1, "
                             f"got {iframe_period}")
        self.iframe_period = int(iframe_period)
        # key -> [gen, ccodes, dcodes, (near, far), frames_since_i]
        self._state = {}
        self.stats = {"i": 0, "p": 0, "skip": 0, "forced_i": 0,
                      "bytes_full": 0, "bytes_wire": 0}
        self._reset_keys = set()

    def reset(self) -> None:
        """Scene cut: drop all retained tiles — every previously
        retained tile's next record is a forced I-frame (counted as
        ``iframe_forced``). Idempotent: a second reset before the next
        publish must not erase the pending forced-I bookkeeping."""
        self._reset_keys |= set(self._state)
        self._state.clear()

    def _mint(self, rec: DeltaRecord) -> DeltaRecord:
        from scenery_insitu_tpu import obs as _obs

        self.stats["bytes_full"] += rec.full_bytes
        self.stats["bytes_wire"] += rec.wire_bytes
        rec_r = _obs.get_recorder()
        if rec.mode == "SKIP":
            self.stats["skip"] += 1
            rec_r.count("delta_tiles_skipped")
        elif rec.mode == "P":
            self.stats["p"] += 1
        else:
            self.stats["i"] += 1
            if rec.reason in ("periodic", "reset"):
                self.stats["forced_i"] += 1
                rec_r.count("iframe_forced")
        if rec.wire_bytes < rec.full_bytes:
            rec_r.count("delta_bytes_saved",
                        rec.full_bytes - rec.wire_bytes)
        return rec

    def encode(self, key, ccodes: np.ndarray, dcodes: np.ndarray,
               near: float, far: float) -> DeltaRecord:
        """Encode one quantized tile (``ccodes`` u32, ``dcodes`` u16 —
        the qpack8_quantize_np outputs) against the retained previous
        tile under ``key``."""
        full = _full_bytes(ccodes, dcodes)
        st = self._state.get(key)
        scale = (float(near), float(far))

        def itile(gen: int, reason: str, first: bool) -> DeltaRecord:
            # stagger the forced-I phase per tile ON FIRST CONTACT:
            # tiles of one frame are all first published together, and
            # lockstep counters would re-ship EVERY tile as a full I in
            # the same frame every period — a bytes burst ~1/ratio the
            # steady frame. The one-time per-key offset spreads the
            # re-ships across the period (the first interval SHORTENS
            # to period - offset, later ones are the full period, so
            # the recovery bound holds); whole-frame streams (key -1)
            # have nothing to stagger against.
            off = 0
            if first and isinstance(key, int) and key >= 0:
                off = key % self.iframe_period
            self._state[key] = [gen, ccodes.copy(), dcodes.copy(),
                                scale, off]
            return self._mint(DeltaRecord(
                "I", gen, -1, (ccodes,), (dcodes,), scale, full, full,
                reason))

        if st is None:
            reason = "reset" if key in self._reset_keys else "first"
            self._reset_keys.discard(key)
            return itile(1, reason, first=True)
        gen, pc, pd, pscale, since_i = st
        if ccodes.shape != pc.shape or dcodes.shape != pd.shape:
            # a resized stream (regime change) cannot be patched
            return itile(gen + 1, "shape_change", first=False)
        if since_i + 1 >= self.iframe_period:
            return itile(gen + 1, "periodic", first=False)
        # one comparison pass: the residual's empty-run case IS the
        # SKIP decision (a separate array_equal would re-compare the
        # same elements)
        cs, cl, cv = diff_runs(pc, ccodes)
        ds, dl, dv = diff_runs(pd, dcodes)
        if scale == pscale and cs.size == 0 and ds.size == 0:
            st[0] = gen + 1
            st[4] = since_i + 1
            return self._mint(DeltaRecord(
                "SKIP", gen + 1, gen, (), (), scale, full, 0, "unchanged"))
        wire = runs_wire_bytes(cs, cv) + runs_wire_bytes(ds, dv)
        if wire >= full:
            return itile(gen + 1, "dense_residual", first=False)
        self._state[key] = [gen + 1, ccodes.copy(), dcodes.copy(), scale,
                            since_i + 1]
        return self._mint(DeltaRecord(
            "P", gen + 1, gen, (cs, cl, cv), (ds, dl, dv), scale, full,
            wire, "residual"))


class DeltaDecoder:
    """Subscriber-side mirror of `DeltaEncoder`: retains the last
    reconstructed code arrays per tile and applies SKIP/P/I records.
    ``apply`` returns ``None`` when the record's base generation is not
    the retained one (a dropped message broke the chain) — the caller
    drops the message and waits for the next I-tile (forced within
    ``iframe_period`` frames by the encoder)."""

    def __init__(self):
        self._state = {}     # key -> [gen, ccodes, dcodes, (near, far)]
        self.stats = {"i": 0, "p": 0, "skip": 0, "resync": 0}

    def reset(self) -> None:
        """Publisher restart (epoch change): the new encoder shares no
        state with the old stream — drop everything retained."""
        self._state.clear()

    def apply(self, key, mode: str, gen: int, base_gen: int,
              c_payload: tuple, d_payload: tuple,
              scale: Tuple[float, float]
              ) -> Optional[Tuple[np.ndarray, np.ndarray, float, float]]:
        """One record → the reconstructed (ccodes, dcodes, near, far),
        bit-exact vs the encoder's input, or None when a resync is
        needed."""
        if mode == "I":
            ccodes, dcodes = c_payload[0], d_payload[0]
            self._state[key] = [gen, ccodes, dcodes, scale]
            self.stats["i"] += 1
            return ccodes, dcodes, scale[0], scale[1]
        st = self._state.get(key)
        if st is None or st[0] != base_gen:
            self.stats["resync"] += 1
            return None
        if mode == "SKIP":
            st[0] = gen
            self.stats["skip"] += 1
            ccodes, dcodes, scale = st[1], st[2], st[3]
            return ccodes, dcodes, scale[0], scale[1]
        if mode != "P":
            raise ValueError(f"unknown delta mode {mode!r}; "
                             f"have {DELTA_MODES}")
        ccodes = apply_runs(st[1], *c_payload)
        dcodes = apply_runs(st[2], *d_payload)
        self._state[key] = [gen, ccodes, dcodes, scale]
        self.stats["p"] += 1
        return ccodes, dcodes, scale[0], scale[1]


# ======================================================================
# device-side dirty-region re-marching (jax)
# ======================================================================


class ReuseState(NamedTuple):
    """Per-rank carried state of ``CompositeConfig.temporal_reuse ==
    "ranges"`` (threaded through the MXU step like the temporal
    threshold maps). ``sig`` is the dirty signature of the LAST MARCHED
    frame — occupancy-pyramid [lo, hi] ranges concatenated with the
    camera pose — so drift under a nonzero ``range_tol`` accumulates
    against the marched reference instead of creeping. ``color`` /
    ``depth`` are the rank's last PRE-EXCHANGE marched fragment;
    ``valid`` is 0 only for the seeded state (first frame always
    marches); ``dirty`` reports the last frame's decision (host-side
    counters/events read it — [1] so ranks stack to [n])."""

    sig: Any       # f32[2 * cells + cam]
    color: Any     # f32[K, 4, nj, ni]
    depth: Any     # f32[K, 2, nj, ni]
    valid: Any     # i32[1]
    dirty: Any     # i32[1]


def reuse_signature(pyramid, cam) -> "jnp.ndarray":
    """Flattened dirty signature: the occupancy pyramid's per-cell
    [lo, hi] value ranges (the change detector the sim already computes
    every frame — PR 6) followed by every camera leaf. The ranges OCCUPY
    the first ``2 * pyramid.lo.size`` slots; `reuse_dirty` applies
    ``range_tol`` to that prefix only (the camera compares exactly — a
    moved camera invalidates every fragment)."""
    import jax
    import jax.numpy as jnp

    parts = [jnp.ravel(pyramid.lo).astype(jnp.float32),
             jnp.ravel(pyramid.hi).astype(jnp.float32)]
    parts += [jnp.ravel(x).astype(jnp.float32)
              for x in jax.tree_util.tree_leaves(cam)]
    return jnp.concatenate(parts)


def reuse_dirty(sig, prev_sig, valid, range_tol: float, n_ranges: int):
    """Scalar bool: must this rank re-march? True when the state is the
    seed (``valid == 0``), when any camera leaf changed bit-for-bit, or
    when the range prefix moved by more than ``range_tol`` (``0`` =
    any difference; NaN compares dirty — conservative)."""
    import jax.numpy as jnp

    cur_r, cur_c = sig[:n_ranges], sig[n_ranges:]
    prev_r, prev_c = prev_sig[:n_ranges], prev_sig[n_ranges:]
    if range_tol > 0.0:
        moved = ~(jnp.max(jnp.abs(cur_r - prev_r)) <= range_tol)
    else:
        moved = ~jnp.all(cur_r == prev_r)
    cam_moved = ~jnp.all(cur_c == prev_c)
    return (valid[0] == 0) | moved | cam_moved


def init_reuse_like(sig, k: int, nj: int, ni: int) -> ReuseState:
    """Zero-valid seed state shaped for a step whose signature is
    ``sig`` and whose marched fragments are [k, 4|2, nj, ni] (the seed
    builder runs this inside shard_map so shapes come out per rank)."""
    import jax.numpy as jnp

    return ReuseState(
        sig=jnp.zeros_like(sig),
        color=jnp.zeros((k, 4, nj, ni), jnp.float32),
        depth=jnp.zeros((k, 2, nj, ni), jnp.float32),
        valid=jnp.zeros((1,), jnp.int32),
        dirty=jnp.zeros((1,), jnp.int32))


# ======================================================================
# traffic model
# ======================================================================


def modeled_delta_traffic(k: int, h: int, w: int, *,
                          skip_frac: float, p_frac: float = 0.0,
                          residual_frac: float = 0.1,
                          iframe_period: int = 8) -> dict:
    """Steady-state publish bytes/frame of ONE delta stream (k×h×w
    supersegment slots — per-stream, rank-agnostic) vs qpack8-only
    (pre-codec code bytes — the same unit `DeltaEncoder` accounts).
    ``skip_frac``/``p_frac`` are tile fractions in steady state
    (remainder publishes I); ``residual_frac`` is the changed-slot
    fraction of a P tile. The forced I every ``iframe_period`` frames
    re-ships each tile once per period regardless (staggered per tile,
    so the amortized accounting here is also the per-frame shape)."""
    if not (0.0 <= skip_frac <= 1.0 and 0.0 <= p_frac <= 1.0
            and skip_frac + p_frac <= 1.0):
        raise ValueError("skip_frac/p_frac must be fractions summing "
                         "to <= 1")
    full = k * h * w * 6                       # qpack8: 6 B/slot
    # P cost: values (6 B/slot changed) + run bookkeeping (modeled as
    # one run per 4 changed slots)
    p_tile = full * residual_frac * (1.0 + _RUN_BYTES / (6.0 * 4.0))
    steady = ((1.0 - skip_frac - p_frac) * full + p_frac * p_tile)
    # amortized forced-I re-ship of the otherwise skipped/P tiles
    forced = (skip_frac + p_frac) * full / iframe_period
    per_frame = steady + forced
    return {
        "qpack8_bytes_per_frame": full,
        "delta_bytes_per_frame": per_frame,
        "reduction_vs_qpack8": (full / per_frame if per_frame else
                                float("inf")),
        "skip_frac": skip_frac, "p_frac": p_frac,
        "residual_frac": residual_frac, "iframe_period": iframe_period,
    }
