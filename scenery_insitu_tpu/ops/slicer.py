"""MXU slice-march volume rendering — the TPU-native raycaster core.

The reference raycasts per pixel through GPU texture hardware: every march
step does a trilinear texture fetch at an arbitrary world position
(reference VDIGenerator.comp:333-529, VolumeRaycaster.comp:94-161). The
literal translation — per-step random gathers into the ``[D, H, W]``
volume — is the one access pattern a TPU cannot run fast: XLA lowers it to
serialized HBM gathers (measured ~19 s/frame at 256³, 720p, 256 steps on a
v5e chip). GPUs have texture units; TPUs have a 128×128 systolic array.
So this module re-derives volume raycasting as matrix multiplication:

1. Pick the volume axis ``w`` most aligned with the view direction
   (`choose_axis`) and build a **virtual axis-aligned camera**: same eye,
   looking straight down ``w``, off-axis frustum whose *near plane is the
   nearest slice plane* and covers the whole volume footprint
   (`make_axis_camera`). This is the shear-warp factorization of the view
   transform, MXU-style.
2. March slice by slice, front to back. Because every virtual-camera ray
   passes through the eye, its crossing of slice ``w = z`` is a uniform
   scale-and-shift of the intermediate pixel grid (scale ``s(z) =
   depth(z)/depth(ref plane)``), so resampling a slice onto the whole ray
   bundle is **separable bilinear** — two banded interpolation matrices
   applied as ``Wv @ slice @ Wuᵀ``, built on the fly from ``iota`` and run
   on the MXU. The hot loop contains no gathers at all.
3. The per-slice samples feed any per-pixel fold: alpha-under
   accumulation (plain image, ≅ AccumulatePlainImage.comp) or the
   supersegment counting/writing machines (VDI generation,
   ≅ AccumulateVDI.comp) — the same folds the gather-path raycaster uses.
4. Outputs live on the virtual camera's pixel grid, and the virtual
   camera's projection/view matrices go into `VDIMetadata`, so every
   downstream consumer — sort-last compositor, novel-view VDI renderer,
   streaming — works unchanged. For display, `warp_to_camera` reprojects
   to the real camera: both cameras share an eye, so the warp is an exact
   plane-induced homography (depth-independent, no parallax error).

Sampling schedule vs the gather path: samples land exactly on slice
planes (in-plane bilinear, exact in ``w``) instead of at uniform
per-ray parameter steps; opacity correction by the per-ray inter-slice
path length (`adjust_opacity`) makes the accumulated integral agree —
parity is asserted by tests/test_slicer.py.

The march axis and intermediate resolution are static (compile-time):
an orbiting camera triggers at most one recompile per (axis, sign)
regime, cached by jit.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.config import SliceMarchConfig, VDIConfig
from scenery_insitu_tpu.core.camera import Camera, frustum, look_at
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import pallas_march as pm
from scenery_insitu_tpu.ops import pallas_seg as psg
from scenery_insitu_tpu.ops import seg_fold as sf
from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.ops.raycast import RaycastOutput, nominal_step
from scenery_insitu_tpu.ops.sampling import adjust_opacity

# xyz axis index -> data dim of Volume.data [..., z, y, x], counted from
# the END so an optional leading channel dim (pre-shaded RGBA volumes)
# never shifts the lookup
_DATA_DIM = {0: -1, 1: -2, 2: -3}
# march axis -> (u axis, v axis), both xyz indices
_UV = {2: (0, 1), 1: (0, 2), 0: (1, 2)}


@dataclasses.dataclass(frozen=True)
class AxisSpec:
    """Static (compile-time) parameters of a slice march."""

    axis: int                 # march axis, xyz index (0=x, 1=y, 2=z)
    sign: int                 # +1: march toward +axis; -1: toward -axis
    ni: int                   # intermediate image width (u direction)
    nj: int                   # intermediate image height (v direction)
    chunk: int = 16           # slices folded per scan step
    matmul_dtype: str = "bf16"   # resampling matmul operand dtype
    s_floor: float = 1e-3     # min depth ratio: slices closer are dropped
    skip_empty: bool = True   # chunk_occupancy-based empty-space skipping
    # supersegment-fold schedule: "xla" (sequential machine, lax.scan) |
    # "pallas" (round-3 two-phase machine kernel) | "seg" (round-4
    # segmented-scan fold, ops/seg_fold.py) | "pallas_seg" (its VMEM twin)
    fold: str = "xla"
    # storage dtype of the marched volume copy: "bf16" makes
    # `permute_volume` emit a bf16 march layout — volume bytes halve for
    # every march (and for the distributed halo exchange) while all
    # accumulation stays f32 (the resampling einsum sets
    # preferred_element_type=f32 and the folds run f32 throughout)
    render_dtype: str = "f32"
    # in-plane occupancy granularity: 0 = whole-chunk skipping only;
    # N > 0 additionally splits each slice plane into N row (v) tiles and
    # skips the resampling matmuls + TF for OUTPUT row blocks whose
    # bilinear support lies entirely in empty tiles (≅ the reference's
    # per-(8x8 pixel, z-interval) OctreeCells skip,
    # VDIGenerator.comp:232-254 — here at (chunk x v-tile) granularity,
    # the axis the banded-matmul factorization can gate with static
    # shapes). Conservative: gated blocks are provably zero-alpha.
    vtiles: int = 0

    @property
    def u_axis(self) -> int:
        return _UV[self.axis][0]

    @property
    def v_axis(self) -> int:
        return _UV[self.axis][1]


def resolve_engine(engine: str) -> str:
    """Resolve a render-engine name ("auto" | "mxu" | "gather") against the
    current backend; raises on anything else so typos can't silently bench
    the wrong engine."""
    if engine == "auto":
        return "mxu" if jax.default_backend() == "tpu" else "gather"
    if engine not in ("mxu", "gather"):
        raise ValueError(f"unknown render engine {engine!r} "
                         "(expected 'auto', 'mxu' or 'gather')")
    return engine


def choose_axis(cam: Camera) -> Tuple[int, int]:
    """Pick the volume axis most aligned with the view direction (host-side,
    concrete camera). Returns (axis, sign)."""
    d = np.asarray(cam.target, np.float64) - np.asarray(cam.eye, np.float64)
    axis = int(np.argmax(np.abs(d)))
    return axis, (1 if d[axis] >= 0 else -1)


def make_spec(cam: Camera, vol_shape: Tuple[int, int, int],
              cfg: Optional[SliceMarchConfig] = None,
              axis_sign: Optional[Tuple[int, int]] = None,
              multiple_of: int = 1) -> AxisSpec:
    """Build the static spec for a camera + volume shape ([D, H, W]).

    ``multiple_of``: round the intermediate dims up to this multiple — the
    distributed pipeline needs ni divisible by the mesh size for its
    width-axis all_to_all."""
    cfg = cfg or SliceMarchConfig()
    axis, sign = axis_sign or choose_axis(cam)
    u_axis, v_axis = _UV[axis]
    dims_xyz = (vol_shape[2], vol_shape[1], vol_shape[0])
    step = int(8 * multiple_of // np.gcd(8, multiple_of))
    rnd = lambda n: max(step, int(-(-int(n * cfg.scale) // step)) * step)
    # bf16 matmuls are MXU-native on TPU but emulated (slowly) on CPU
    dtype = cfg.matmul_dtype
    if dtype == "bf16" and jax.default_backend() != "tpu":
        dtype = "f32"
    ni = rnd(dims_xyz[u_axis])
    nj = rnd(dims_xyz[v_axis])
    fold = cfg.fold
    if fold == "auto":
        # On TPU the default is the round-4 segmented-scan fold: the
        # Pallas VMEM twin when a one-time Mosaic compile probe AT THIS
        # SPEC'S frame width accepts it (the probe fixes the budget-capped
        # BLOCK width and thus the exact kernel Mosaic sees; K probed at a
        # conservative 32 — VDIConfig's K is not known here), else the
        # pure-XLA seg schedule — still chunk-granular state traffic, no
        # Mosaic exposure. On CPU the sequential machine wins (state
        # lives in cache, and seg's K-masked reductions are real extra
        # compute on a scalar core — measured 3x slower at 64x96^2), so
        # tests and the virtual mesh keep "xla".
        # BOTH kernels a pallas_seg spec can run must pass the probe: the
        # write fold (pallas_seg.seg_fold_chunk) and the counting kernel
        # the histogram/temporal-seed march uses (pm.count_multi_chunk) —
        # a spec whose write kernel compiles but whose counting kernel is
        # rejected would still fail inside initial_threshold(). EVERY
        # kernel GEOMETRY must pass too: the occupancy-skip branch of
        # slice_march feeds a 1-slice chunk (slicer.skip), compiling a
        # second c=1 variant of each kernel inside the traced step, so
        # probe that geometry alongside cfg.chunk (cheap, cached) — but
        # only when the skip path is reachable (skip_empty): with
        # skipping off the c=1 kernels are never built, and a c=1
        # rejection must not demote a config that would never trace it.
        if jax.default_backend() == "tpu":
            c1_ok = (not cfg.skip_empty
                     or (psg.seg_compile_ok(32, 1, ni)
                         and pm.count_compile_ok(32, 1, ni)))
            fold = ("pallas_seg" if psg.seg_compile_ok(32, cfg.chunk, ni)
                    and pm.count_compile_ok(32, cfg.chunk, ni)
                    and c1_ok else "seg")
        else:
            fold = "xla"
    if fold not in ("xla", "pallas", "seg", "pallas_seg", "pallas_fused",
                    "fused_stream"):
        raise ValueError(f"unknown fold schedule {fold!r} (expected 'auto', "
                         "'xla', 'pallas', 'seg', 'pallas_seg', "
                         "'pallas_fused' or 'fused_stream')")
    if fold in ("pallas_fused", "fused_stream") \
            and jax.default_backend() == "tpu" \
            and not psg.fused_compile_ok(32, cfg.chunk, ni,
                                         stream=(fold == "fused_stream")):
        # an explicitly requested fused fold that Mosaic rejects AT THIS
        # GEOMETRY must degrade here (the probe ledgered it as
        # ops.seg_fold), not compile-crash inside a traced frame step;
        # fall back to the same probed stack the auto resolution uses.
        # Off-TPU the fused folds run in interpret mode — never probed,
        # never degraded.
        fold = ("pallas_seg" if psg.seg_compile_ok(32, cfg.chunk, ni)
                and pm.count_compile_ok(32, cfg.chunk, ni) else "seg")
    # resolve the benched auto default (-1): in-plane tiling pays on the
    # TPU march (the A/B in benchmarks/occupancy_bench.py — sparse
    # fields skip most cells) but adds nt lax.cond branches per chunk,
    # pure overhead for the CPU/test path, which keeps chunk-only
    # skipping unless a tile count is configured explicitly
    vt = cfg.occupancy_vtiles
    if vt < 0:
        from scenery_insitu_tpu.config import OCCUPANCY_VTILES_DEFAULT

        vt = (OCCUPANCY_VTILES_DEFAULT
              if jax.default_backend() == "tpu" else 0)
    # clamp the tile count to what the geometry supports: each band needs
    # >= 2 volume rows (the apron + a zero-size reduction guard) and each
    # output block >= 2 rows — a too-large request degrades to coarser
    # tiles instead of an obscure trace-time error, and the degradation
    # goes on the fallback ledger (it silently coarsens skip granularity;
    # distributed slabs re-clamp again in occupancy.resolved_tiles)
    if vt:
        vt_req = vt
        vt = max(1, min(vt, dims_xyz[v_axis] // 2, nj // 2))
        # ledger only EXPLICITLY configured counts the geometry cannot
        # honor — the auto default clamping on a small grid is the
        # default adapting, not a configuration silently ignored
        if vt < vt_req and cfg.occupancy_vtiles > 0:
            from scenery_insitu_tpu import obs

            obs.degrade("occupancy.vtiles_clamp", str(vt_req), str(vt),
                        f"volume v extent {dims_xyz[v_axis]} / grid nj "
                        f"{nj} support at most {vt} bands of >= 2 rows",
                        warn=False)
    return AxisSpec(axis=axis, sign=sign, ni=ni, nj=nj,
                    chunk=cfg.chunk, matmul_dtype=dtype,
                    s_floor=cfg.s_floor, skip_empty=cfg.skip_empty,
                    fold=fold, vtiles=vt, render_dtype=cfg.render_dtype)


class AxisCamera(NamedTuple):
    """The traced (per-frame) state of the virtual axis-aligned camera.
    All fields are jnp arrays; pairs with a static `AxisSpec`."""

    eye_uvw: jnp.ndarray   # f32[3] eye in (u, v, w) component order
    view: jnp.ndarray      # f32[4, 4]  (goes into VDIMetadata)
    proj: jnp.ndarray      # f32[4, 4]  off-axis frustum projection
    u_grid: jnp.ndarray    # f32[Ni] world u of intermediate pixel columns
    v_grid: jnp.ndarray    # f32[Nj] world v of intermediate pixel rows
    zp: jnp.ndarray        # f32[] eye→reference-plane distance (near plane)
    w0: jnp.ndarray        # f32[] world w of marched slice 0 (= ref plane)
    dwm: jnp.ndarray       # f32[] signed world w step per marched slice
    far: jnp.ndarray       # f32[]

    @property
    def eye_u(self):
        return self.eye_uvw[0]

    @property
    def eye_v(self):
        return self.eye_uvw[1]

    @property
    def eye_w(self):
        return self.eye_uvw[2]

    def ray_lengths(self) -> jnp.ndarray:
        """f32[Nj, Ni]: distance from the eye to each reference-plane grid
        point = the ray parameter t at depth ratio s == 1."""
        du = self.u_grid - self.eye_u
        dv = self.v_grid - self.eye_v
        return jnp.sqrt(dv[:, None] ** 2 + du[None, :] ** 2 + self.zp ** 2)


def permute_volume(vol: Volume, spec: AxisSpec) -> jnp.ndarray:
    """Volume data -> march layout ``[S, (ch,) Nv, Nu]`` (slice, optional
    channels, in-plane v, u), flipped so marched slice index ascends
    front-to-back. A leading channel dim of pre-shaded RGBA volumes moves
    BEHIND the slice dim so the march can slab-slice on dim 0.

    ``spec.render_dtype == "bf16"`` emits the march layout in bf16 — the
    copy every march reads halves in HBM (XLA CSEs the one cast+transpose
    across the occupancy pass and the marches of a frame); accumulation
    downstream stays f32."""
    data = vol.data
    if spec.render_dtype == "bf16" and data.dtype == jnp.float32:
        data = data.astype(jnp.bfloat16)
    nd = data.ndim
    perm3 = {2: (0, 1, 2), 1: (1, 0, 2), 0: (2, 0, 1)}[spec.axis]
    dims = [nd - 3 + p for p in perm3]
    volp = jnp.transpose(data,
                         [dims[0]] + list(range(nd - 3)) + dims[1:])
    if spec.sign < 0:
        volp = jnp.flip(volp, axis=0)
    return volp


def make_axis_camera(vol: Volume, cam: Camera, spec: AxisSpec,
                     box_min: Optional[jnp.ndarray] = None,
                     box_max: Optional[jnp.ndarray] = None) -> AxisCamera:
    """Build the virtual camera for this frame (all values traced).

    box_min/box_max override the footprint AABB — the distributed pipeline
    passes the *global* volume AABB so every rank shares one intermediate
    grid (a requirement for the sort-last column exchange)."""
    a, ua, va = spec.axis, spec.u_axis, spec.v_axis
    box_min = vol.world_min if box_min is None else box_min
    box_max = vol.world_max if box_max is None else box_max

    eye = cam.eye
    ew, eu, ev = eye[a], eye[ua], eye[va]
    dw = vol.spacing[a]

    # nearest slice plane (= reference/near plane) and signed march step.
    # NOTE: w0 is derived from the *global* box when given, so all ranks of
    # a decomposed volume agree on the slice ladder.
    gw0 = box_min[a]
    gw1 = box_max[a]
    w0 = jnp.where(spec.sign > 0, gw0 + 0.5 * dw, gw1 - 0.5 * dw)
    dwm = spec.sign * dw

    zp = jnp.maximum(spec.sign * (w0 - ew), dw)            # eye may sit inside

    # static unit basis of the virtual camera
    fwd = np.zeros(3, np.float32)
    fwd[a] = spec.sign
    up = np.zeros(3, np.float32)
    up[va] = 1.0
    right = np.cross(fwd, up)
    true_up = np.cross(right, fwd)
    right_u = float(right[ua])                             # exactly ±1
    up_v = float(true_up[va])

    fwd_j = jnp.asarray(fwd)
    right_j = jnp.asarray(right)
    true_up_j = jnp.asarray(true_up)

    view = look_at(eye, eye + fwd_j, jnp.asarray(up))

    # off-axis frustum covering the box footprint projected from the eye
    # onto the reference plane (corners closer than the plane clamp to it)
    xs, ys, zs = [], [], []
    for bits in range(8):
        c = jnp.stack([(box_max if bits >> d & 1 else box_min)[d]
                       for d in range(3)])
        rel = c - eye
        ze = jnp.dot(rel, fwd_j)
        zec = jnp.maximum(ze, zp)
        xs.append(jnp.dot(rel, right_j) * zp / zec)
        ys.append(jnp.dot(rel, true_up_j) * zp / zec)
        zs.append(ze)
    xs, ys, zs = jnp.stack(xs), jnp.stack(ys), jnp.stack(zs)
    mu = vol.spacing[ua]
    mv = vol.spacing[va]
    l, r = jnp.min(xs) - mu, jnp.max(xs) + mu
    b, t = jnp.min(ys) - mv, jnp.max(ys) + mv
    r = jnp.maximum(r, l + 1e-5)
    t = jnp.maximum(t, b + 1e-5)
    far = jnp.maximum(jnp.max(zs), zp * 1.001) + dw

    proj = frustum(l, r, b, t, zp, far)

    # intermediate pixel grids, consistent with the projection: column i
    # center ↔ ndc_x = 2(i+.5)/Ni - 1; row j center ↔ ndc_y = 1 - 2(j+.5)/Nj
    ndc_x = (jnp.arange(spec.ni, dtype=jnp.float32) + 0.5) / spec.ni * 2 - 1
    ndc_y = 1.0 - (jnp.arange(spec.nj, dtype=jnp.float32) + 0.5) / spec.nj * 2
    u_grid = eu + (ndc_x * (r - l) + (r + l)) * 0.5 * right_u
    v_grid = ev + (ndc_y * (t - b) + (t + b)) * 0.5 * up_v

    return AxisCamera(eye_uvw=jnp.stack([eu, ev, ew]), view=view, proj=proj,
                      u_grid=u_grid, v_grid=v_grid, zp=zp, w0=w0, dwm=dwm,
                      far=far)


# ------------------------------------------------------------- tile waves


def wave_block(ni: int, n_ranks: int, wave_tiles: int) -> int:
    """Column width of one tile wave's per-rank block: the intermediate
    width splits into ``n_ranks`` rank-owned blocks, each into
    ``wave_tiles`` tiles (docs/PERF.md "Tile waves"). Raises when the
    geometry does not divide — the wave schedule needs exact blocks."""
    if ni % (n_ranks * wave_tiles):
        raise ValueError(
            f"intermediate width {ni} not divisible by ranks*wave_tiles "
            f"= {n_ranks}*{wave_tiles} (pick wave_tiles so every rank's "
            f"{ni // n_ranks if n_ranks and ni % n_ranks == 0 else ni}"
            f"-column block splits evenly)")
    return ni // (n_ranks * wave_tiles)


def wave_cols(x: jnp.ndarray, n_ranks: int, wave_tiles: int, w):
    """Slice the trailing (width) axis of ``x [..., Ni]`` to tile wave
    ``w``'s columns: for each of the ``n_ranks`` rank-owned blocks, the
    w-th of ``wave_tiles`` sub-tiles → ``[..., n_ranks * wb]``. ``w``
    may be traced (the wave scan's induction variable)."""
    ni = x.shape[-1]
    wb = wave_block(ni, n_ranks, wave_tiles)
    # reshaped dims: x.shape[:-1] + (n_ranks @ x.ndim-1, T @ x.ndim, wb)
    g = x.reshape(x.shape[:-1] + (n_ranks, wave_tiles, wb))
    g = jax.lax.dynamic_index_in_dim(g, w, axis=x.ndim, keepdims=False)
    return g.reshape(x.shape[:-1] + (n_ranks * wb,))


def wave_update_cols(x: jnp.ndarray, xw: jnp.ndarray, n_ranks: int,
                     wave_tiles: int, w) -> jnp.ndarray:
    """Inverse of `wave_cols`: scatter wave ``w``'s columns ``xw
    [..., n_ranks * wb]`` back into ``x [..., Ni]`` (the temporal
    threshold maps update only the wave they marched)."""
    ni = x.shape[-1]
    wb = wave_block(ni, n_ranks, wave_tiles)
    g = x.reshape(x.shape[:-1] + (n_ranks, wave_tiles, wb))
    upd = xw.reshape(xw.shape[:-1] + (n_ranks, 1, wb))
    g = jax.lax.dynamic_update_index_in_dim(g, upd, w, axis=x.ndim)
    return g.reshape(x.shape)


def wave_camera(axcam: AxisCamera, spec: AxisSpec, n_ranks: int,
                wave_tiles: int, w) -> Tuple[AxisCamera, AxisSpec]:
    """Column-sliced (AxisCamera, AxisSpec) of tile wave ``w``.

    Every virtual-camera column is an independent ray fan (the banded
    resampling matrices are built per output column from ``u_grid``), so
    marching a subset of columns is exactly the column slice of the full
    march — the wave camera just carries wave ``w``'s ``n_ranks * wb``
    u-grid entries (one ``wb``-wide tile per rank-owned block, so the
    sliced frame still splits into n rank blocks for the sort-last
    exchange). The spec's ``ni`` shrinks to match; everything else
    (march axis, chunking, fold, occupancy gating — all u-independent)
    is reused, as are the frame's one ``permute_volume`` copy and
    occupancy pyramid. ``w`` may be traced."""
    ug = wave_cols(axcam.u_grid, n_ranks, wave_tiles, w)
    return (axcam._replace(u_grid=ug),
            dataclasses.replace(spec, ni=ug.shape[-1]))


def slice_march_wave(vol: Volume, tf: TransferFunction, axcam: AxisCamera,
                     spec: AxisSpec, consume: Callable, carry0,
                     n_ranks: int, wave_tiles: int, w, **kwargs):
    """Tile-scoped `slice_march`: march only tile wave ``w``'s column
    blocks (docs/PERF.md "Tile waves"). Accepts every `slice_march`
    keyword — pass the frame's shared ``volp`` (permute_volume copy) and
    ``occupancy`` (the per-frame pyramid gate, u-independent) so T waves
    cost one permuted copy and one pyramid, not T."""
    axcam_w, spec_w = wave_camera(axcam, spec, n_ranks, wave_tiles, w)
    return slice_march(vol, tf, axcam_w, spec_w, consume, carry0, **kwargs)


# ------------------------------------------------------------------ march


def _axis_params(vol: Volume, spec: AxisSpec):
    """(origin, spacing, count) of the u and v axes of this volume."""
    ua, va = spec.u_axis, spec.v_axis
    nu = vol.data.shape[_DATA_DIM[ua]]
    nv = vol.data.shape[_DATA_DIM[va]]
    return (vol.origin[ua], vol.spacing[ua], nu,
            vol.origin[va], vol.spacing[va], nv)


def _interp_matrix(pos: jnp.ndarray, origin, spacing, n: int,
                   bounds: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None
                   ) -> jnp.ndarray:
    """Banded bilinear interpolation weights for world positions ``pos
    [C, M]`` against voxel rows 0..n-1 → ``[C, M, n]``. Clamp-to-edge
    inside the volume extent, zero outside; `bounds` further restricts to a
    half-open world interval (domain-decomposition ownership).
    ``origin``/``spacing`` may be scalars or per-chunk [C] arrays (the
    novel-view renderer resamples slices whose grids scale per slice)."""
    origin = jnp.reshape(origin, (-1, 1)) if jnp.ndim(origin) else origin
    spacing = jnp.reshape(spacing, (-1, 1)) if jnp.ndim(spacing) else spacing
    x = (pos - origin) / spacing - 0.5
    valid = (x >= -0.5) & (x <= n - 0.5)
    if bounds is not None:
        valid &= (pos >= bounds[0]) & (pos < bounds[1])
    xc = jnp.clip(x, 0.0, n - 1.0)
    cols = jax.lax.broadcasted_iota(jnp.float32, (1, 1, n), 2)
    w = jnp.maximum(0.0, 1.0 - jnp.abs(xc[..., None] - cols))
    return w * valid[..., None].astype(jnp.float32)


def chunk_occupancy(vol: Volume, tf: TransferFunction, spec: AxisSpec,
                    alpha_eps: float = 1e-5,
                    volp: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """bool[nchunks]: can the slab of ``spec.chunk`` slices contribute any
    opacity? The TPU-native occupancy structure (≅ the reference's
    OctreeCells grid, VDIGenerator.comp:232-254 + GridCellsToZero.comp —
    but computed in one cheap reduction pass per frame instead of
    atomic-add during the march, and consumed by `slice_march` to skip
    whole chunks). Conservative: in-plane bilinear resampling keeps values
    inside each slice's [min, max], so a slab whose value range maps to
    zero alpha everywhere (``tf.max_alpha_in``) is provably invisible.

    Since ISSUE 6 this (and the vtile refinement below) is the nt=1
    level of the shared occupancy pyramid — ops/occupancy.py owns the
    band-range machinery; ``volp`` shares one permuted copy per frame."""
    from scenery_insitu_tpu.ops import occupancy as _occ

    return _occ.pyramid_from_volume(vol, tf, spec, volp=volp,
                                    alpha_eps=alpha_eps, ntiles=1).chunks


def _pad_to_chunks(volp: jnp.ndarray, c: int) -> Tuple[jnp.ndarray, int]:
    """Zero-pad the march-layout volume along slices to a chunk multiple;
    returns (padded, nchunks). One implementation for the march and every
    occupancy pass, so slab boundaries can never disagree."""
    s_total = volp.shape[0]
    nchunks = -(-s_total // c)
    if nchunks * c != s_total:
        volp = jnp.concatenate(
            [volp, jnp.zeros((nchunks * c - s_total,) + volp.shape[1:],
                             volp.dtype)], axis=0)
    return volp, nchunks


def chunk_occupancy_vtiles(vol: Volume, tf: TransferFunction,
                           spec: AxisSpec, alpha_eps: float = 1e-5,
                           volp: Optional[jnp.ndarray] = None
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(bool[nchunks], bool[nchunks, vtiles]): chunk- and
    (chunk x v-row-band)-granular occupancy in ONE pass over the volume —
    the in-plane refinement of `chunk_occupancy` (≅ OctreeCells' per-cell
    skip, VDIGenerator.comp:232-254), with the chunk level derived from
    the same per-band value ranges (identical to the separate whole-slab
    reduction, at no extra volume traffic).

    Each band's range carries a ONE-ROW APRON into its neighbors: an
    output row's bilinear support is two adjacent volume rows which may
    straddle a band boundary, and the interpolated value lies between
    values in the gap of the two bands' ranges — with a band-pass (non-
    monotone) transfer function that gap can hit an alpha peak neither
    apron-less band sees. The apron makes every adjacent-row pair fully
    contained in at least one band, restoring the conservative argument.
    Tiles split the VOLUME's v axis; the last band absorbs the remainder.

    The tile count re-clamps against THIS volume's v extent
    (occupancy.resolved_tiles — distributed ranks march slabs far
    smaller than the global shape `make_spec` clamped against; the
    reduction lands on the fallback ledger). Consumers read the count
    from the array's shape, so the clamp propagates automatically.
    Implementation lives in ops/occupancy.py (the shared pyramid)."""
    from scenery_insitu_tpu.ops import occupancy as _occ

    pyr = _occ.pyramid_from_volume(vol, tf, spec, volp=volp,
                                   alpha_eps=alpha_eps)
    return pyr.chunks, pyr.tiles


def _fused_vdi_march(vol, tf, axcam, spec, threshold, k, occ,
                     u_bounds, v_bounds, step_scale: float = 1.0,
                     volp=None, w_bounds=None):
    """One write march through the fused shade+fold kernel (raw mode).
    The length/ds/ratio geometry matches slice_march's own shading
    formula INCLUDING step_scale — one implementation for both the plain
    and temporal generators."""
    length = axcam.ray_lengths()
    ds = jnp.abs(axcam.dwm) / axcam.zp
    ratio = ds * length / nominal_step(vol, step_scale)

    def consume(packed, val, sk):
        return psg.fused_fold_chunk(packed, val, length, ratio, sk,
                                    sk + ds, threshold, max_k=k, tf=tf)

    packed = slice_march(vol, tf, axcam, spec, consume,
                         psg.init_seg_packed(k, spec.nj, spec.ni),
                         u_bounds, v_bounds, step_scale=step_scale,
                         occupancy=occ, raw=True, volp=volp,
                         w_bounds=w_bounds)
    return psg.unpack_seg_state(packed)


def _fused_stream_vdi_march(vol, tf, axcam, spec, threshold, k, occ,
                            u_bounds, v_bounds, step_scale: float = 1.0,
                            volp=None, w_bounds=None):
    """Two-phase whole-march fused fold: phase M materializes the raw
    value stream (the matmul phase, chunk-skipping intact — skipped
    chunks write -1 planes), then ONE pallas_call folds the entire
    stream with the [K] state VMEM-resident per strip
    (ops/pallas_seg.fused_stream_fold). Costs a f32[S,Nj,Ni] stream
    buffer (~840 MB at the 512^3 flagship scale: 512 x 640^2 x 4 B) — the chunked
    fold="pallas_fused" is the memory-constrained alternative
    (e.g. 1024^3, where this buffer would be 6.7 GB)."""
    length = axcam.ray_lengths()
    ds = jnp.abs(axcam.dwm) / axcam.zp
    ratio = ds * length / nominal_step(vol, step_scale)
    c = spec.chunk
    # static slice count straight from the shape — permute_volume here
    # would materialize a full transposed copy in eager execution
    s_total = vol.data.shape[_DATA_DIM[spec.axis]]
    s_pad = -(-s_total // c) * c

    def consume(carry, val, sk):
        buf, skb, idx = carry
        buf = jax.lax.dynamic_update_slice(buf, val, (idx * c, 0, 0))
        skb = jax.lax.dynamic_update_slice(skb, sk, (idx * c,))
        return buf, skb, idx + 1

    buf0 = jnp.zeros((s_pad, spec.nj, spec.ni), jnp.float32)
    sk0 = jnp.zeros((s_pad,), jnp.float32)
    buf, skb, _ = slice_march(vol, tf, axcam, spec, consume,
                              (buf0, sk0, jnp.int32(0)), u_bounds,
                              v_bounds, step_scale=step_scale,
                              occupancy=occ, raw=True, raw_full_skip=True,
                              volp=volp, w_bounds=w_bounds)
    packed = psg.fused_stream_fold(
        psg.init_seg_packed(k, spec.nj, spec.ni), buf, length, ratio,
        skb, skb + ds, threshold, max_k=k, chunk=c, tf=tf)
    return psg.unpack_seg_state(packed)


def occupancy_for(vol: Volume, tf: TransferFunction, spec: AxisSpec,
                  volp: Optional[jnp.ndarray] = None):
    """The occupancy structure `slice_march` consumes for this spec:
    None (skipping off), bool[nchunks], or (chunk, tile) tuple when
    ``spec.vtiles > 0`` — one occupancy-pyramid build
    (ops/occupancy.pyramid_from_volume), gated down to the march's
    contract. ``volp`` shares the frame's permuted volume copy."""
    if not spec.skip_empty:
        return None
    from scenery_insitu_tpu.ops import occupancy as _occ

    return _occ.pyramid_from_volume(vol, tf, spec, volp=volp).gate(spec)


def _resolve_occupancy(vol: Volume, tf: TransferFunction, spec: AxisSpec,
                       occupancy, volp: Optional[jnp.ndarray]):
    """Normalize a caller-provided occupancy (an ops/occupancy
    OccupancyPyramid — built once per frame, possibly from sim-fused
    field ranges — or the legacy gate arrays) to the `slice_march`
    contract; None builds the per-call pyramid like the pre-ISSUE-6
    path did. Skipping off always wins."""
    if not spec.skip_empty:
        return None
    if occupancy is None:
        return occupancy_for(vol, tf, spec, volp=volp)
    from scenery_insitu_tpu.ops import occupancy as _occ

    if isinstance(occupancy, _occ.OccupancyPyramid):
        return occupancy.gate(spec)
    return occupancy


def slice_march(vol: Volume, tf: TransferFunction, axcam: AxisCamera,
                spec: AxisSpec, consume: Callable, carry0,
                u_bounds=None, v_bounds=None, step_scale: float = 1.0,
                occupancy: Optional[jnp.ndarray] = None,
                early_stop: Optional[Callable] = None, raw: bool = False,
                raw_full_skip: bool = False,
                shaded_compact: bool = False,
                volp: Optional[jnp.ndarray] = None,
                w_bounds=None):
    """The chunked slice march. Calls ``consume(carry, rgba [C,4,Nj,Ni],
    t0 [C,Nj,Ni], t1 [C,Nj,Ni]) -> carry`` for each chunk of slices, front
    to back, and returns the final carry.

    rgba is premultiplied, already opacity-corrected for the per-ray
    inter-slice path length, and zero outside the volume/ownership bounds.

    Pre-shaded RGBA volumes (``vol.data f32[4, D, H, W]``, premultiplied,
    alpha encoded for a ``nominal_step(vol)``-long traversal — the
    novel-view proxy) march without a transfer function: pass ``tf=None``
    and the per-slice shading resamples the stored channels instead.

    ``occupancy`` (bool[nchunks], from `chunk_occupancy`) skips the
    resampling matmuls and fold for provably-empty chunks; the skipped
    branch still feeds ONE all-empty sample so stream-gap semantics
    (supersegment closing on empty) are identical to the full march.
    A TUPLE ``(chunk_occ, tile_occ)`` (see `chunk_occupancy_vtiles` and
    `occupancy_for`) additionally gates output row BLOCKS inside live
    chunks on the in-plane tile occupancy — the reference's OctreeCells
    granularity along the axis the matmul factorization can skip.
    ``early_stop(carry) -> bool[]`` additionally skips every chunk after
    the predicate turns true (alpha-saturation early-out, ≅ the
    reference's early exit in AccumulatePlainImage.comp:8-13).

    ``raw=True`` changes the consume contract to ``consume(carry,
    val [C,Nj,Ni], sk [C]) -> carry``: the RESAMPLED VALUE plane with a
    ``-1`` sentinel for dead samples (outside volume/bounds, dropped
    slices) and the per-slice eye-depth ratios — no transfer function,
    no opacity correction, no t0/t1 streams. This is the fused-kernel
    feed (ops/pallas_seg.fused_fold_chunk shades in-kernel); scalar
    volumes only.

    ``w_bounds`` (an open world interval ``(w_lo, w_hi)`` on the march
    axis) additionally drops slices whose plane lies outside it — the
    ownership mask of a PLANNED render band (docs/PERF.md "Render
    rebalancing"): a band volume padded to the plan's max depth marches
    only its own slices, exactly like ``v_bounds`` owns in-plane rows.
    Slice centers sit half a voxel inside any slice-aligned boundary, so
    the open comparison is exact.

    ``shaded_compact=True`` keeps the full shading (premultiplied,
    opacity-corrected rgba) but replaces the depth planes with the
    per-slice ratios: ``consume(carry, rgba [C,4,Nj,Ni], sk0 [C],
    sk1 [C]) -> carry`` where the plane path's t0/t1 are exactly
    ``sk0*length`` / ``sk1*length`` (length = axcam.ray_lengths()).
    Occupancy-skipped iterations feed a C=1 all-empty chunk, like the
    default contract. This is the compact pallas_seg feed — the
    [C,2,Nj,Ni] depth planes never materialize in HBM.
    """
    pre_shaded = vol.data.ndim == 4
    if raw and pre_shaded:
        raise ValueError("raw slice_march feeds a transfer-function "
                         "kernel; pre-shaded volumes have no TF")
    occ_tiles = None
    if isinstance(occupancy, tuple):
        occupancy, occ_tiles = occupancy
    # ``volp`` shares the frame's one permuted copy (occupancy pass +
    # every march of the frame read the same layout; XLA CSEs the
    # transpose either way inside one jit, but the explicit handoff also
    # serves eager callers and keeps the structure visible)
    volp0 = permute_volume(vol, spec) if volp is None else volp
    s_total = volp0.shape[0]
    c = spec.chunk
    volp, nchunks = _pad_to_chunks(volp0, c)
    if occupancy is not None and occupancy.shape[0] != nchunks:
        # both sides chunk through the shared _pad_to_chunks, so a
        # mismatch means the occupancy was built for a DIFFERENT volume
        # or chunk size — skipping with it would be silently wrong
        raise ValueError(
            f"occupancy describes {occupancy.shape[0]} chunks but this "
            f"march has {nchunks} (volume {vol.data.shape}, chunk {c})")

    ou, su, nu, ov, sv, nv = _axis_params(vol, spec)
    eu, ev, ew = axcam.eye_u, axcam.eye_v, axcam.eye_w
    mm = jnp.bfloat16 if spec.matmul_dtype == "bf16" else jnp.float32

    # per-ray geometry (constant over the march)
    length = axcam.ray_lengths()                           # [Nj, Ni]
    ds = jnp.abs(axcam.dwm) / axcam.zp                     # depth-ratio step
    ratio = ds * length / (nominal_step(vol, step_scale))  # [Nj, Ni]

    # the volume's own w ladder may start offset from the global one
    # (distributed slabs): marched slice k of THIS volume sits at world
    # w = local_w0 + k*dwm
    a = spec.axis
    now_ = vol.data.shape[_DATA_DIM[a]]
    local_w0 = jnp.where(axcam.dwm > 0,
                         vol.origin[a] + 0.5 * vol.spacing[a],
                         vol.origin[a] + (now_ - 0.5) * vol.spacing[a])

    def work(carry, ci):
        ks = ci * c + jnp.arange(c, dtype=jnp.float32)     # [C]
        wk = local_w0 + ks * axcam.dwm
        sk = jnp.float32(spec.sign) * (wk - ew) / axcam.zp   # depth ratios
        live = (sk > spec.s_floor) & (ks < s_total)
        if w_bounds is not None:
            live &= (wk > w_bounds[0]) & (wk < w_bounds[1])

        slices = jax.lax.dynamic_slice_in_dim(volp, ci * c, c, 0)

        pos_u = eu + (axcam.u_grid[None, :] - eu) * sk[:, None]    # [C, Ni]
        pos_v = ev + (axcam.v_grid[None, :] - ev) * sk[:, None]    # [C, Nj]
        wu = _interp_matrix(pos_u, ou, su, nu, u_bounds)           # [C,Ni,Nu]
        wv = _interp_matrix(pos_v, ov, sv, nv, v_bounds)           # [C,Nj,Nv]

        inside = (wv.sum(-1) > 0.0)[:, :, None] & (wu.sum(-1) > 0.0)[:, None, :]
        keep = inside & live[:, None, None]

        def rows_val(wv_r, keep_r):
            """Raw-mode block: resampled values, -1 where dead."""
            val = jnp.einsum("cjy,cyx,cix->cji",
                             wv_r.astype(mm), slices.astype(mm),
                             wu.astype(mm),
                             preferred_element_type=jnp.float32)
            # clip BEFORE the sentinel so a genuine value <= -0.5 (un-
            # normalized field) can't be conflated with a dead sample;
            # exact — every shading path clips to [0,1] anyway
            return jnp.where(keep_r, jnp.clip(val, 0.0, 1.0), -1.0)

        def rows_rgba(wv_r, keep_r, ratio_r):
            """Resample + shade one block of output rows ([C,B,*])."""
            if pre_shaded:
                # stored premultiplied RGBA; alpha encoded per nominal step
                val = jnp.einsum("cjy,cdyx,cix->cdji",
                                 wv_r.astype(mm), slices.astype(mm),
                                 wu.astype(mm),
                                 preferred_element_type=jnp.float32)
                a_res = jnp.clip(val[:, 3], 0.0, 1.0 - 1e-6)
                a_res = jnp.where(keep_r, a_res, 0.0)
                alpha = adjust_opacity(a_res, ratio_r[None])
                # premultiplied rgb scales with its alpha re-correction
                scale = alpha / jnp.maximum(a_res, 1e-6)
                return jnp.concatenate(
                    [jnp.clip(val[:, :3], 0.0, 1.0) * scale[:, None],
                     alpha[:, None]], axis=1)
            val = jnp.einsum("cjy,cyx,cix->cji",
                             wv_r.astype(mm), slices.astype(mm),
                             wu.astype(mm),
                             preferred_element_type=jnp.float32)
            val = jnp.clip(val, 0.0, 1.0)

            rgb, alpha = tf(val)                   # [C,B,Ni,3], [C,B,Ni]
            # outside-volume samples must be fully transparent even when
            # the transfer function maps value 0 to nonzero alpha
            alpha = jnp.where(keep_r, alpha, 0.0)
            alpha = adjust_opacity(alpha, ratio_r[None])
            return jnp.concatenate(
                [jnp.moveaxis(rgb, -1, 1) * alpha[:, None],
                 alpha[:, None]], axis=1)

        rows_fn = ((lambda wv_r, keep_r, ratio_r: rows_val(wv_r, keep_r))
                   if raw else rows_rgba)
        if occ_tiles is None:
            rgba = rows_fn(wv, keep, ratio)
        else:
            # in-plane skipping: gate each OUTPUT row block on whether
            # its bilinear support intersects any occupied (chunk,
            # v-tile). The support of output rows is derived from the
            # block's sampled voxel coordinates over LIVE slices; a block
            # whose whole support lies in empty tiles is provably
            # zero-alpha (value ranges are preserved by interpolation).
            nt = occ_tiles.shape[1]
            tv = nv // nt
            occ_row = occ_tiles[ci]                        # bool[nt]
            tile_ids = jnp.arange(nt)
            xv = (pos_v - ov) / sv - 0.5                   # [C, Nj] voxels
            nb = nt
            bsz = spec.nj // nb
            blocks = []
            for b in range(nb):
                b0 = b * bsz
                b1 = spec.nj if b == nb - 1 else (b0 + bsz)
                xb = xv[:, b0:b1]
                big = jnp.float32(2 * nv)
                xlo = jnp.min(jnp.where(live[:, None], xb, big))
                xhi = jnp.max(jnp.where(live[:, None], xb, -big))
                r_lo = jnp.clip(jnp.floor(xlo), 0, nv - 1)
                r_hi = jnp.clip(jnp.floor(xhi) + 1.0, 0, nv - 1)
                t_lo = jnp.minimum(r_lo // tv, nt - 1).astype(jnp.int32)
                t_hi = jnp.minimum(r_hi // tv, nt - 1).astype(jnp.int32)
                hit = jnp.any(occ_row & (tile_ids >= t_lo)
                              & (tile_ids <= t_hi)) & (xlo <= xhi)
                wv_b = wv[:, b0:b1]
                keep_b = keep[:, b0:b1]
                ratio_b = ratio[b0:b1]
                fill = -1.0 if raw else 0.0
                shp = ((c, b1 - b0, spec.ni) if raw
                       else (c, 4, b1 - b0, spec.ni))
                cat_ax = 1 if raw else 2
                blocks.append(jax.lax.cond(
                    hit,
                    lambda wv_b=wv_b, keep_b=keep_b, ratio_b=ratio_b:
                        rows_fn(wv_b, keep_b, ratio_b),
                    lambda shp=shp, fill=fill: jnp.full(shp, fill,
                                                        jnp.float32)))
            rgba = jnp.concatenate(blocks, axis=cat_ax)

        if raw:
            return consume(carry, rgba, sk)
        if shaded_compact:
            # compact contract: shaded rgba + BOTH per-slice depth ratios
            # (sk0, sk1 = sk + ds) so the step geometry stays defined in
            # ONE place; the consumer owns only t = sk*length (in-kernel
            # for the compact pallas_seg fold — the [C,2,Nj,Ni] planes
            # never materialize)
            return consume(carry, rgba, sk, sk + ds)
        t0 = sk[:, None, None] * length[None]
        t1 = (sk + ds)[:, None, None] * length[None]
        return consume(carry, rgba, t0, t1)

    def skip(carry, ci):
        # one explicit empty sample: closes any open supersegment exactly
        # like the stream of empties the full march would have produced
        s0 = jnp.float32(spec.sign) * (local_w0 + ci * c * axcam.dwm - ew) \
            / axcam.zp
        if raw:
            if raw_full_skip:
                # stream builders need every chunk at full C rows: emit
                # the whole chunk of -1 sentinels + its true depth ratios
                sk_c = s0 + jnp.arange(c, dtype=jnp.float32) * ds
                return consume(carry,
                               jnp.full((c, spec.nj, spec.ni), -1.0,
                                        jnp.float32), sk_c)
            return consume(carry,
                           jnp.full((1, spec.nj, spec.ni), -1.0,
                                    jnp.float32), s0[None])
        empty = jnp.zeros((1, 4, spec.nj, spec.ni), jnp.float32)
        if shaded_compact:
            # all-empty chunk: slot -1 records never match a depth mask,
            # so sk1 = sk0 + ds vs the plane path's t0 == t1 is moot
            return consume(carry, empty, s0[None], s0[None] + ds)
        t = (s0 * length)[None]                            # [1, Nj, Ni]
        return consume(carry, empty, t, t)

    gated = occupancy is not None or early_stop is not None

    def body(carry, ci):
        if not gated:
            return work(carry, ci), None
        occupied = jnp.bool_(True) if occupancy is None else occupancy[ci]
        if early_stop is not None:
            occupied &= ~early_stop(carry)
        return jax.lax.cond(occupied, work, skip, carry, ci), None

    carry, _ = jax.lax.scan(body, carry0, jnp.arange(nchunks))
    return carry


# ------------------------------------------------------- plain-image render


def hittable_mask(vol: Volume, axcam: AxisCamera, spec: AxisSpec
                  ) -> jnp.ndarray:
    """bool[Nj, Ni]: can this intermediate-grid pixel's ray intersect the
    volume AABB at any marched depth? The intermediate grid covers the
    whole projected footprint plus margins, so its edge pixels never
    accumulate alpha — any all-pixels predicate (saturation early-out)
    must ignore them. Per pixel, pos_u(s) = eu + (u_i - eu)·s lies in the
    volume's u extent for an interval of depth ratios s; the pixel is
    hittable iff the u and v intervals overlap somewhere in s > 0
    (conservative: the actual march range is a subset)."""
    a, ua, va = spec.axis, spec.u_axis, spec.v_axis

    def axis_interval(grid, e, lo, hi):
        d = grid - e
        big = jnp.float32(1e30)
        s0 = jnp.where(d > 0, (lo - e) / jnp.where(d == 0, 1.0, d),
                       jnp.where(d < 0, (hi - e) / jnp.where(d == 0, 1.0, d),
                                 jnp.where((e >= lo) & (e <= hi), 0.0, big)))
        s1 = jnp.where(d > 0, (hi - e) / jnp.where(d == 0, 1.0, d),
                       jnp.where(d < 0, (lo - e) / jnp.where(d == 0, 1.0, d),
                                 jnp.where((e >= lo) & (e <= hi), big, -big)))
        return s0, s1

    u0, u1 = axis_interval(axcam.u_grid, axcam.eye_u,
                           vol.world_min[ua], vol.world_max[ua])
    v0, v1 = axis_interval(axcam.v_grid, axcam.eye_v,
                           vol.world_min[va], vol.world_max[va])
    # the march only visits depth ratios between the volume's w faces
    sa = jnp.float32(spec.sign) * (vol.world_min[a] - axcam.eye_w) / axcam.zp
    sb = jnp.float32(spec.sign) * (vol.world_max[a] - axcam.eye_w) / axcam.zp
    s_lo = jnp.minimum(sa, sb)
    s_hi = jnp.maximum(sa, sb)
    lo = jnp.maximum(jnp.maximum(u0[None, :], v0[:, None]), s_lo)
    hi = jnp.minimum(jnp.minimum(u1[None, :], v1[:, None]), s_hi)
    return jnp.maximum(lo, 0.0) <= hi


def render_slices(vol: Volume, tf: TransferFunction, axcam: AxisCamera,
                  spec: AxisSpec, early_exit_alpha: float = 0.999,
                  u_bounds=None, v_bounds=None,
                  step_scale: float = 1.0,
                  occupancy=None,
                  volp: Optional[jnp.ndarray] = None,
                  w_bounds=None) -> RaycastOutput:
    """Front-to-back alpha-under accumulation on the intermediate grid
    (≅ VolumeRaycaster.comp, but slice-order). Background-free premultiplied
    image + first-hit depth (ray parameter; +inf where empty). Skips
    provably-empty chunks; saturated pixels stop accumulating via the
    per-pixel gate (≅ AccumulatePlainImage.comp:8-13 — a whole-chunk
    saturation stop is NOT wired up: silhouette pixels get tapered
    partial-weight edge samples and never reach the threshold, so an
    all-pixels predicate can essentially never fire)."""

    def consume(carry, rgba, t0, t1):
        # chunk-parallel alpha-under (same factorization as the seg fold:
        # contribution_s = rgba_s * prod_{s'<s}(1-alpha)), EXACT including
        # the per-pixel saturation gate: the sequential gate tests the
        # PRE-update accumulated alpha, which equals 1-(1-A0)*Tl_excl(s)
        # — a prefix quantity — and once a pixel crosses, every later
        # sample is zeroed either way, so masking with the unmasked
        # prefix reproduces the frozen-accumulator semantics (up to fp
        # association; a pixel landing within ~1 ulp of the threshold
        # can round the gate differently and shift by one sample —
        # measure-zero in practice, bounded by one sample's alpha).
        acc, first_t = carry
        cc = rgba.shape[0]
        t_run = jnp.ones_like(acc[3])
        tls = []
        for i in range(cc):                    # 2 ops/slice, tiny loop
            tls.append(t_run)
            t_run = t_run * (1.0 - rgba[i, 3])
        tl = jnp.stack(tls)                                # [C, Nj, Ni]
        a0 = acc[3:4]
        a_pre = 1.0 - (1.0 - a0) * tl                      # [C, Nj, Ni]
        gate = a_pre < early_exit_alpha
        contrib = jnp.sum(rgba * (tl * gate)[:, None], axis=0)
        acc = acc + (1.0 - a0) * contrib
        hit = gate & (rgba[:, 3] > 1e-4)
        t_hit = jnp.min(jnp.where(hit, t0, jnp.inf), axis=0)
        return acc, jnp.minimum(first_t, t_hit)

    acc0 = jnp.zeros((4, spec.nj, spec.ni), jnp.float32)
    t0 = jnp.full((spec.nj, spec.ni), jnp.inf, jnp.float32)
    if volp is None:
        volp = permute_volume(vol, spec)
    occ = _resolve_occupancy(vol, tf, spec, occupancy, volp)
    acc, first_t = slice_march(vol, tf, axcam, spec, consume, (acc0, t0),
                               u_bounds, v_bounds, step_scale,
                               occupancy=occ, volp=volp,
                               w_bounds=w_bounds)
    return RaycastOutput(acc, first_t)


def bilinear_image_sample(img: jnp.ndarray, gy: jnp.ndarray, gx: jnp.ndarray,
                          fill: float = 0.0) -> jnp.ndarray:
    """Sample ``img f32[ch, H, W]`` at continuous pixel coords (gy, gx)
    ``[...]`` (pixel centers at integers). Out-of-range → fill."""
    ch, h, w = img.shape
    inb = (gx >= -0.5) & (gx <= w - 0.5) & (gy >= -0.5) & (gy <= h - 0.5)
    x = jnp.clip(gx, 0.0, w - 1.0)
    y = jnp.clip(gy, 0.0, h - 1.0)
    x0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, max(w - 2, 0))
    y0 = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, max(h - 2, 0))
    fx = x - x0
    fy = y - y0
    flat = img.reshape(ch, h * w)

    def at(yi, xi):
        return jnp.take(flat, yi * w + xi, axis=1)

    x1 = jnp.minimum(x0 + 1, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    out = (at(y0, x0) * ((1 - fx) * (1 - fy))[None]
           + at(y0, x1) * (fx * (1 - fy))[None]
           + at(y1, x0) * ((1 - fx) * fy)[None]
           + at(y1, x1) * (fx * fy)[None])
    return jnp.where(inb[None], out, fill)


def warp_to_camera(image: jnp.ndarray, axcam: AxisCamera, spec: AxisSpec,
                   cam: Camera, width: int, height: int,
                   background: Optional[Tuple[float, ...]] = (0.0, 0.0, 0.0, 0.0),
                   fill: float = 0.0, nearest: bool = False) -> jnp.ndarray:
    """Resample an intermediate-grid image ``[ch, Nj, Ni]`` to the real
    camera's ``[ch, H, W]``. Exact: both cameras share an eye, so the map
    is the homography induced by the reference plane. ``fill`` is used for
    rays that miss the reference plane or fall outside the grid;
    ``background`` (4-channel images only) is alpha-under-composited.
    ``nearest`` disables bilinear blending — required for channels with
    sentinel values (depth maps), where blending a sentinel with a valid
    neighbor would fabricate a value."""
    from scenery_insitu_tpu.core.camera import pixel_rays

    _, dirs = pixel_rays(cam, width, height)               # [3, H, W]
    de = jnp.float32(spec.sign) * dirs[spec.axis]
    hit = de > 1e-6
    tp = axcam.zp / jnp.where(hit, de, 1.0)
    pu = axcam.eye_u + tp * dirs[spec.u_axis]
    pv = axcam.eye_v + tp * dirs[spec.v_axis]
    du = axcam.u_grid[1] - axcam.u_grid[0]
    dv = axcam.v_grid[1] - axcam.v_grid[0]
    gi = (pu - axcam.u_grid[0]) / du
    gj = (pv - axcam.v_grid[0]) / dv
    if nearest:
        gi = jnp.round(gi)
        gj = jnp.round(gj)
    out = bilinear_image_sample(image, gj, gi, fill)
    out = jnp.where(hit[None], out, fill)
    if background is None:
        return out
    bg = jnp.asarray(background, jnp.float32).reshape(-1, 1, 1)
    return out + (1.0 - out[3:4]) * bg


def raycast_mxu(vol: Volume, tf: TransferFunction, cam: Camera,
                width: int, height: int, spec: AxisSpec,
                early_exit_alpha: float = 0.999,
                background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0),
                step_scale: float = 1.0) -> RaycastOutput:
    """Full plain render: slice march on the intermediate grid + homography
    warp to the display camera. Drop-in output-compatible with
    ops.raycast.raycast."""
    axcam = make_axis_camera(vol, cam, spec)
    inter = render_slices(vol, tf, axcam, spec, early_exit_alpha,
                          step_scale=step_scale)
    img = warp_to_camera(inter.image, axcam, spec, cam, width, height,
                         background)
    # depth: nearest-sample warp with -1 standing in for "empty" (bilinear
    # would blend the sentinel with valid neighbors at silhouette pixels)
    depth = warp_to_camera(
        jnp.where(jnp.isfinite(inter.depth), inter.depth, -1.0)[None],
        axcam, spec, cam, width, height, background=None, fill=-1.0,
        nearest=True)[0]
    depth = jnp.where(depth >= 0.0, depth, jnp.inf)
    return RaycastOutput(img, depth)


# ----------------------------------------------------------- VDI generation


def generate_vdi_mxu(vol: Volume, tf: TransferFunction, cam: Camera,
                     spec: AxisSpec, cfg: Optional[VDIConfig] = None,
                     frame_index: int = 0,
                     box_min: Optional[jnp.ndarray] = None,
                     box_max: Optional[jnp.ndarray] = None,
                     u_bounds=None, v_bounds=None,
                     occupancy=None, k_target=None,
                     axcam: Optional[AxisCamera] = None,
                     volp: Optional[jnp.ndarray] = None,
                     w_bounds=None, step_scale: float = 1.0,
                     ) -> Tuple[VDI, VDIMetadata, AxisCamera]:
    """VDI generation on the MXU slice march (≅ VDIGenerator.comp +
    AccumulateVDI.comp, see ops.vdi_gen for the gather-path equivalent).

    The VDI lives on the virtual camera's pixel grid; its metadata carries
    the virtual projection/view, so compositing, novel-view rendering and
    streaming treat it exactly like a gather-path VDI. Depths are the world
    ray parameter of the (virtual = real) eye.

    ``occupancy``: a per-frame ops/occupancy.OccupancyPyramid (built once
    and shared across every march of the frame — possibly from sim-fused
    field ranges, costing no volume sweep at all) or a legacy gate; None
    rebuilds from the volume here. ``k_target`` (traced scalar or
    [nj, ni]) re-targets the adaptive threshold at fewer than
    ``cfg.max_supersegments`` segments — output SHAPES stay at K; this is
    the load-aware K budget hook (occupancy.k_budget_target).

    ``axcam`` overrides the virtual camera (the tile-wave path passes a
    column-sliced `wave_camera` whose u_grid matches ``spec.ni``);
    ``volp`` shares a pre-built `permute_volume` copy across calls (T
    waves march the same frame copy).

    ``step_scale`` rescales the opacity-correction reference step
    (`nominal_step(vol, step_scale)`) — the LOD brick path marches a
    2^l-downsampled volume with ``step_scale = 2^-l`` so coarse slices
    accumulate the opacity of the 2^l fine slices they replace (the
    shared reference stays the FINE voxel pitch; docs/PERF.md "LOD
    marching")."""
    cfg = cfg or VDIConfig()
    k = cfg.max_supersegments
    kt = k if k_target is None else k_target
    nj, ni = spec.nj, spec.ni
    if axcam is None:
        axcam = make_axis_camera(vol, cam, spec, box_min, box_max)

    # ONE permuted copy + one occupancy structure shared by every
    # counting + writing march of this generation
    if volp is None:
        volp = permute_volume(vol, spec)
    occ = _resolve_occupancy(vol, tf, spec, occupancy, volp)
    march = lambda consume, carry0: slice_march(
        vol, tf, axcam, spec, consume, carry0, u_bounds, v_bounds,
        step_scale=step_scale, occupancy=occ, volp=volp,
        w_bounds=w_bounds)

    if cfg.adaptive and cfg.adaptive_mode == "temporal":
        raise ValueError(
            "adaptive_mode='temporal' carries per-frame threshold state — "
            "call generate_vdi_mxu_temporal(..., threshold=...) instead "
            "(seed the state with initial_threshold())")
    if cfg.adaptive and cfg.adaptive_mode == "histogram":
        threshold = _histogram_threshold(march, cfg, kt, nj, ni, spec.fold)
    elif cfg.adaptive:
        # "search" mode: adaptive_iters counting marches (XLA fold — the
        # default modes are histogram/temporal; search stays the portable
        # reference schedule)
        def count_fn(thr):
            def consume(st, rgba, t0, t1):
                for i in range(rgba.shape[0]):
                    st = ss.push_count(st, thr, rgba[i])
                return st
            return march(consume, ss.init_count(nj, ni)).count
        threshold = ss.adaptive_threshold(count_fn, kt, cfg.adaptive_iters,
                                          nj, ni)
    else:
        threshold = jnp.full((nj, ni), cfg.threshold, jnp.float32)

    if spec.fold == "pallas":
        def consume(packed, rgba, t0, t1):
            return pm.fold_chunk(packed, rgba, t0, t1, threshold, max_k=k)

        packed = march(consume, pm.init_packed(k, nj, ni))
        color, depth = ss.finalize(pm.unpack_state(packed))
    elif spec.fold == "pallas_seg":
        # packed-carry: the [K,...] state keeps one layout across the
        # whole scan so the kernel's input_output_aliases update it in
        # place (a NamedTuple carry would pay a stack/slice copy of the
        # depth plane per chunk). Compact depth: the kernel computes
        # t = sk*length itself — the [C,2,Nj,Ni] planes never hit HBM.
        length = axcam.ray_lengths()

        def consume(packed, rgba, sk0, sk1):
            return psg.fold_chunk_packed(packed, rgba, threshold=threshold,
                                         max_k=k, sk0=sk0, sk1=sk1,
                                         length=length)

        packed = slice_march(vol, tf, axcam, spec, consume,
                             psg.init_seg_packed(k, nj, ni),
                             u_bounds, v_bounds, step_scale=step_scale,
                             occupancy=occ,
                             shaded_compact=True, volp=volp,
                             w_bounds=w_bounds)
        color, depth = sf.seg_finalize(psg.unpack_seg_state(packed))
    elif spec.fold in ("pallas_fused", "fused_stream"):
        # shade-in-kernel: the march feeds the raw resampled value plane
        # and the kernel applies TF + opacity correction + depths itself
        # (≅ the reference's one-kernel generation) — the 4-channel rgba
        # and two depth streams never exist in HBM. fused_stream further
        # moves the chunk loop inside the kernel grid (state resident in
        # VMEM per strip, one HBM round trip per march).
        marcher = (_fused_stream_vdi_march if spec.fold == "fused_stream"
                   else _fused_vdi_march)
        state = marcher(vol, tf, axcam, spec, threshold, k, occ,
                        u_bounds, v_bounds, step_scale=step_scale,
                        volp=volp, w_bounds=w_bounds)
        color, depth = sf.seg_finalize(state)
    elif spec.fold == "seg":
        def consume(st, rgba, t0, t1):
            return sf.seg_fold_chunk(st, rgba, t0, t1, threshold, max_k=k)

        state = march(consume, sf.init_seg_state(k, nj, ni))
        color, depth = sf.seg_finalize(state)
    else:
        def consume(st, rgba, t0, t1):
            for i in range(rgba.shape[0]):
                st = ss.push(st, k, threshold, rgba[i], t0[i], t1[i])
            return st

        state = march(consume, ss.init_state(k, nj, ni))
        color, depth = ss.finalize(state)

    meta = _vdi_meta(vol, axcam, ni, nj, frame_index, step_scale)
    return VDI(color, depth), meta, axcam


def _vdi_meta(vol: Volume, axcam: AxisCamera, ni: int, nj: int,
              frame_index: int, step_scale: float = 1.0) -> VDIMetadata:
    dims = jnp.asarray(vol.dims_xyz, jnp.float32)
    # model = voxel->world affine (diag spacing + origin): consumers that
    # only get metadata (axis_camera_from_meta) read the per-axis pitch
    # from here — nw alone is min(spacing), wrong for anisotropic volumes
    model = jnp.diag(jnp.concatenate([vol.spacing, jnp.ones(1)]))
    model = model.at[:3, 3].set(vol.origin)
    return VDIMetadata.create(projection=axcam.proj, view=axcam.view,
                              model=model, volume_dims=dims,
                              window_dims=(ni, nj),
                              nw=nominal_step(vol, step_scale),
                              index=frame_index)


def _histogram_threshold(march, cfg: VDIConfig, k: int, nj: int, ni: int,
                         fold: str = "xla") -> jnp.ndarray:
    """One counting march for ALL candidate thresholds at once."""
    tvec = ss.threshold_candidates(cfg.histogram_bins, cfg.thr_max)

    # any pallas fold implies a TPU backend where the VMEM counting
    # kernel is also the right schedule for the histogram march
    if fold.startswith("pallas") or fold == "fused_stream":
        def consume_multi(carry, rgba, t0, t1):
            return pm.count_multi_chunk(carry, rgba, tvec)

        counts = march(consume_multi, pm.init_count_multi_packed(
            cfg.histogram_bins, nj, ni))[0]
    else:
        def consume_multi(st, rgba, t0, t1):
            for i in range(rgba.shape[0]):
                st = ss.push_count(st, tvec[:, None, None], rgba[i])
            return st

        counts = march(consume_multi,
                       ss.init_count_multi(cfg.histogram_bins, nj, ni)).count
    return ss.pick_threshold(counts, tvec, k)


def initial_threshold(vol: Volume, tf: TransferFunction, cam: Camera,
                      spec: AxisSpec, cfg: Optional[VDIConfig] = None,
                      box_min: Optional[jnp.ndarray] = None,
                      box_max: Optional[jnp.ndarray] = None,
                      u_bounds=None, v_bounds=None,
                      occupancy=None, k_target=None,
                      w_bounds=None,
                      axcam: Optional[AxisCamera] = None,
                      step_scale: float = 1.0) -> ss.ThresholdState:
    """Seed state for the temporal threshold controller ([nj, ni] maps):
    one histogram counting march on the current scene (the same pass
    adaptive_mode="histogram" runs every frame — temporal mode runs it
    once at session start, then `generate_vdi_mxu_temporal` keeps the map
    in band for one-march frames). ``occupancy``/``k_target``/``axcam``/
    ``step_scale``: see `generate_vdi_mxu` (the LOD brick path passes the
    shared fine-pitch camera with rescaled dwm)."""
    cfg = cfg or VDIConfig()
    if axcam is None:
        axcam = make_axis_camera(vol, cam, spec, box_min, box_max)
    volp = permute_volume(vol, spec)
    occ = _resolve_occupancy(vol, tf, spec, occupancy, volp)
    march = lambda consume, carry0: slice_march(
        vol, tf, axcam, spec, consume, carry0, u_bounds, v_bounds,
        step_scale=step_scale, occupancy=occ, volp=volp,
        w_bounds=w_bounds)
    kt = cfg.max_supersegments if k_target is None else k_target
    thr = _histogram_threshold(march, cfg, kt,
                               spec.nj, spec.ni, spec.fold)
    return ss.init_threshold_state(thr, cfg.thr_min, cfg.thr_max)


def generate_vdi_mxu_temporal(vol: Volume, tf: TransferFunction,
                              cam: Camera, spec: AxisSpec,
                              threshold: ss.ThresholdState,
                              cfg: Optional[VDIConfig] = None,
                              frame_index: int = 0,
                              box_min: Optional[jnp.ndarray] = None,
                              box_max: Optional[jnp.ndarray] = None,
                              u_bounds=None, v_bounds=None,
                              occupancy=None, k_target=None,
                              axcam: Optional[AxisCamera] = None,
                              volp: Optional[jnp.ndarray] = None,
                              w_bounds=None, step_scale: float = 1.0,
                              ) -> Tuple[VDI, VDIMetadata, AxisCamera,
                                         ss.ThresholdState]:
    """VDI generation with ONE march per frame (adaptive_mode="temporal").

    ``threshold`` is carried controller state (seed with
    `initial_threshold`). The write march folds the supersegment writer
    and the O(1) start counter side by side — same slices, same threshold —
    so the true per-pixel segment count comes out of the march that wrote
    the VDI, and `ss.update_threshold` bisects the map toward the target
    band for the next frame. Returns (vdi, meta, axcam, next_threshold).

    Compared to "histogram" mode this halves the march count per frame at
    the cost of one-frame adaptation lag: a pixel whose content changed
    drastically this frame is written with last frame's threshold (its
    overflow merges into the last slot — the same graceful degradation
    every mode shares) and corrected over the following frames.

    ``occupancy``/``k_target``/``axcam``/``volp``: see
    `generate_vdi_mxu` — the controller bisects toward ``k_target`` (the
    occupancy K budget) instead of K when given; output shapes stay at
    K; the tile-wave path passes a column-sliced camera, the shared
    frame copy, and column-sliced threshold maps.
    """
    cfg = cfg or VDIConfig()
    k = cfg.max_supersegments
    kt = k if k_target is None else k_target
    nj, ni = spec.nj, spec.ni
    thr = threshold.thr
    if axcam is None:
        axcam = make_axis_camera(vol, cam, spec, box_min, box_max)
    if volp is None:
        volp = permute_volume(vol, spec)
    occ = _resolve_occupancy(vol, tf, spec, occupancy, volp)

    if spec.fold == "pallas":
        # fused write+count: ONE kernel per chunk, the count rides the
        # writer's own prev-item stream (≅ the reference's single-kernel
        # generate+accumulate, VDIGenerator.comp + AccumulateVDI.comp)
        def consume(carry, rgba, t0, t1):
            packed, count = carry
            return pm.fold_chunk(packed, rgba, t0, t1, thr, max_k=k,
                                 count=count)

        packed, count = slice_march(
            vol, tf, axcam, spec, consume,
            (pm.init_packed(k, nj, ni), jnp.zeros((nj, ni), jnp.int32)),
            u_bounds, v_bounds, step_scale=step_scale, occupancy=occ,
            volp=volp, w_bounds=w_bounds)
        color, depth = ss.finalize(pm.unpack_state(packed))
    elif spec.fold in ("seg", "pallas_seg", "pallas_fused",
                       "fused_stream"):
        # the segmented-scan fold's own running start count IS the true
        # per-pixel segment count — the temporal controller's feedback
        # signal comes out of the write fold for free
        if spec.fold in ("pallas_fused", "fused_stream"):
            marcher = (_fused_stream_vdi_march
                       if spec.fold == "fused_stream"
                       else _fused_vdi_march)
            state = marcher(vol, tf, axcam, spec, thr, k, occ,
                            u_bounds, v_bounds, step_scale=step_scale,
                            volp=volp, w_bounds=w_bounds)
        elif spec.fold == "pallas_seg":
            length = axcam.ray_lengths()

            def consume(packed, rgba, sk0, sk1):
                return psg.fold_chunk_packed(packed, rgba, threshold=thr,
                                             max_k=k, sk0=sk0, sk1=sk1,
                                             length=length)

            packed = slice_march(vol, tf, axcam, spec, consume,
                                 psg.init_seg_packed(k, nj, ni),
                                 u_bounds, v_bounds,
                                 step_scale=step_scale, occupancy=occ,
                                 shaded_compact=True, volp=volp,
                                 w_bounds=w_bounds)
            state = psg.unpack_seg_state(packed)
        else:
            def consume(st, rgba, t0, t1):
                return sf.seg_fold_chunk(st, rgba, t0, t1, thr, max_k=k)

            state = slice_march(vol, tf, axcam, spec, consume,
                                sf.init_seg_state(k, nj, ni),
                                u_bounds, v_bounds,
                                step_scale=step_scale, occupancy=occ,
                                volp=volp, w_bounds=w_bounds)
        color, depth = sf.seg_finalize(state)
        count = state.cnt
    else:
        def consume(carry, rgba, t0, t1):
            st, cst = carry
            for i in range(rgba.shape[0]):
                st = ss.push(st, k, thr, rgba[i], t0[i], t1[i])
                cst = ss.push_count(cst, thr, rgba[i])
            return st, cst

        state, cstate = slice_march(
            vol, tf, axcam, spec, consume,
            (ss.init_state(k, nj, ni), ss.init_count(nj, ni)),
            u_bounds, v_bounds, step_scale=step_scale, occupancy=occ,
            volp=volp, w_bounds=w_bounds)
        color, depth = ss.finalize(state)
        count = cstate.count
    next_thr = ss.update_threshold(threshold, count, kt,
                                   cfg.adaptive_delta, cfg.thr_min,
                                   cfg.thr_max, cfg.temporal_track)
    meta = _vdi_meta(vol, axcam, ni, nj, frame_index, step_scale)
    return VDI(color, depth), meta, axcam, next_thr
