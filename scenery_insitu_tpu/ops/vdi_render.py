"""Novel-view rendering *of* a VDI (SURVEY.md §7 step 9; ≅ reference
EfficientVDIRaycast.comp + SimpleVDIRenderer.comp).

A VDI is a per-original-pixel list of depth slabs. To view it from a new
camera the reference marches each output ray through the original camera's
frustum grid, maps world position → original pixel list (findListNumber,
EfficientVDIRaycast.comp:173-190), binary-searches that list's depth ranges
(:110-141), and computes the exact in-slab path length for opacity
correction (intersectSupersegment, :274-450).

TPU redesign: a static-trip march over the new ray. Each step projects the
world point into the original camera (one matmul), gathers that pixel's K
slabs, and reduces "am I inside a slab" over K with a mask — K ≤ 20, so a
masked reduction beats a divergent binary search on a vector machine. The
per-step opacity correction uses traversed-length/slab-length through
``adjust_opacity`` (≅ the reference's exact path-length correction, applied
per step instead of per slab crossing).

Depth bookkeeping is trivial here by design: framework depths are always
the world-space ray parameter of the generating camera (= distance from its
eye for unit directions), so "is the sample inside the slab" is one
distance comparison — the reference needed a whole conversion pass
(ConvertToNDC.comp) to clean up mixed NDC/world/step encodings before this
could work.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.core.camera import Camera, pixel_rays
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.ops.sampling import adjust_opacity, intersect_aabb


def original_eye(meta: VDIMetadata) -> jnp.ndarray:
    """Recover the generating camera's world position from its view matrix
    (eye = -R^T t)."""
    rot = meta.view[:3, :3]
    return -rot.T @ meta.view[:3, 3]


def frustum_aabb(meta: VDIMetadata) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """World-space AABB of the original camera's frustum — the region where
    the VDI has content (≅ the frustum grid the reference marches,
    EfficientVDIRaycast.comp:173-190)."""
    inv = jnp.linalg.inv(meta.projection @ meta.view)
    corners = jnp.stack(jnp.meshgrid(jnp.array([-1.0, 1.0]),
                                     jnp.array([-1.0, 1.0]),
                                     jnp.array([-1.0, 1.0]),
                                     indexing="ij"), axis=-1).reshape(-1, 3)
    h = jnp.concatenate([corners, jnp.ones((8, 1))], axis=-1)
    w = h @ inv.T
    pts = w[:, :3] / w[:, 3:4]
    return jnp.min(pts, axis=0), jnp.max(pts, axis=0)


def render_vdi(vdi: VDI, meta: VDIMetadata, cam: Camera,
               width: int, height: int, steps: int = 256,
               background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0)
               ) -> jnp.ndarray:
    """Render a VDI from an arbitrary new camera -> f32[4, H, W]
    premultiplied.

    ``steps`` is the static march length along each output ray; the march
    is clipped to the original frustum's AABB so steps are spent where
    content can exist.
    """
    k, _, h0, w0 = vdi.color.shape
    origin, dirs = pixel_rays(cam, width, height)

    box_min, box_max = frustum_aabb(meta)
    tnear, tfar = intersect_aabb(origin, dirs, box_min, box_max)
    hit = tfar > tnear
    tfar = jnp.maximum(tfar, tnear)
    dt = (tfar - tnear) / steps                             # [H, W]

    eye0 = original_eye(meta)
    pv0 = meta.projection @ meta.view                       # [4, 4]

    # flatten the per-pixel lists for gathering
    flat_c = vdi.color.reshape(k, 4, h0 * w0)
    flat_start = vdi.depth[:, 0].reshape(k, h0 * w0)
    flat_end = vdi.depth[:, 1].reshape(k, h0 * w0)

    def body(i, acc):
        t = tnear + (i + 0.5) * dt                          # [H, W]
        pos = origin.reshape(3, 1, 1) + t[None] * dirs      # [3, H, W]
        # project into the original camera's pixel grid (findListNumber)
        ph = jnp.concatenate([pos, jnp.ones_like(pos[:1])])
        clip = jnp.einsum("ab,bhw->ahw", pv0, ph)
        behind = clip[3] <= 1e-6
        ndc = clip[:3] / jnp.where(behind, 1.0, clip[3])[None]
        u = (ndc[0] + 1.0) * 0.5 * w0
        v = (1.0 - ndc[1]) * 0.5 * h0
        iu = jnp.clip(u.astype(jnp.int32), 0, w0 - 1)
        iv = jnp.clip(v.astype(jnp.int32), 0, h0 - 1)
        in_view = (~behind & (u >= 0) & (u < w0) & (v >= 0) & (v < h0)
                   & (ndc[2] >= -1.0) & (ndc[2] <= 1.0) & hit)
        lin = iv * w0 + iu                                  # [H, W]

        # distance from the original eye = the VDI's depth coordinate
        r = jnp.linalg.norm(pos - eye0.reshape(3, 1, 1), axis=0)

        lists_c = flat_c[:, :, lin]                         # [K, 4, H, W]
        starts = flat_start[:, lin]                         # [K, H, W]
        ends = flat_end[:, lin]
        inside = (r[None] >= starts) & (r[None] < ends) & in_view[None]
        slab_len = jnp.maximum(ends - starts, 1e-6)

        # masked reduction over K: at most one slab contains r (slabs are
        # disjoint per pixel), so a sum selects it
        sel = inside.astype(jnp.float32)[:, None]           # [K, 1, H, W]
        rgba = jnp.sum(lists_c * sel, axis=0)               # [4, H, W]
        length = jnp.sum(slab_len * inside, axis=0)         # [H, W]

        # step contribution: alpha for traversing dt of a slab whose full-
        # thickness opacity is rgba[3]
        a_slab = jnp.clip(rgba[3], 0.0, 1.0 - 1e-6)
        a_step = adjust_opacity(a_slab, dt / jnp.maximum(length, 1e-6))
        a_step = jnp.where(jnp.any(inside, axis=0), a_step, 0.0)
        rgb_unit = rgba[:3] / jnp.maximum(a_slab, 1e-6)[None]
        src = jnp.concatenate([rgb_unit * a_step[None], a_step[None]])
        return acc + (1.0 - acc[3:4]) * src

    acc = jax.lax.fori_loop(0, steps, body,
                            jnp.zeros((4, height, width), jnp.float32))
    bg = jnp.asarray(background, jnp.float32).reshape(4, 1, 1)
    return acc + (1.0 - acc[3:4]) * bg
