"""Pallas TPU kernels for the slice-march supersegment folds — the fused
counterpart of the reference's single-kernel generation (VDIGenerator.comp:
380-529 + AccumulateVDI.comp:69-98, where raycast sampling and the
supersegment state machine live in ONE GPU kernel and the per-ray state
never leaves registers).

The XLA march (ops/slicer.slice_march + ops/supersegments.push) carries the
full ``SegState`` — ~107 floats per pixel, dominated by ``out_color
[K,4,H,W]`` — through a ``lax.scan``, and every per-slice ``push`` inside
the scan body reads and rewrites those full-frame tensors through HBM.
Profiling put that write fold at ~40% of generation and matmul MFU at 0.8%:
the march is fold-bandwidth-bound, not MXU-bound.

These kernels keep the resampling einsum in XLA (it IS the MXU work) and
run the fold over VMEM-resident pixel tiles instead:

- `fold_chunk`: feed one chunk of C depth-ordered slices through the
  writer state machine (`ss.push`), one kernel launch per chunk. State
  enters and leaves the kernel once per CHUNK instead of per slice, and
  the C pushes in between touch only VMEM. Optionally counts true segment
  starts in the same pass (the temporal controller's feedback signal —
  `ss.push_count` shares the writer's prev-item stream, so the count is
  free here, where the XLA path folds a separate CountState).
- `count_multi_chunk`: the histogram counting march — evaluates every
  candidate threshold simultaneously (`ss.init_count_multi` semantics)
  on the VMEM tile; candidates are compile-time constants.

Both kernels call the exact `ops.supersegments` fold functions the XLA
path uses — one implementation of the semantics, two schedules — so
tests/test_pallas_march.py asserts exact equality, chunk by chunk.

State is packed into 7 arrays (bool → f32 flags, as in pallas_composite):
``color [K,4,H,W], depth [K,2,H,W], seg [4,H,W], segse [2,H,W],
prev [3,H,W], flags [2,H,W] (open_, prev_empty), k i32[H,W]``.
``input_output_aliases`` pins each state input to its output so XLA can
update in place.

Tiling: (8, W) strips — 8 sublanes × the full row width, grid over H/8.
W needn't be a multiple of 128: a strip is the whole (only) block of its
row range, so Mosaic masks the lane padding and no HBM copy is spent on
alignment. H must be a multiple of 8 (`slicer.make_spec` guarantees it).
On CPU (tests, the virtual mesh) the kernels run in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.ops.pallas_util import TILE_H, should_interpret

# packed-state field count; see pack_state
_STATE_FIELDS = 7


# ------------------------------------------------------------- state packing


def init_packed(k: int, height: int, width: int):
    """Packed fold state ≅ ss.init_state(k, height, width)."""
    return pack_state(ss.init_state(k, height, width))


def pack_state(st: ss.SegState):
    flags = jnp.stack([st.open_.astype(jnp.float32),
                       st.prev_empty.astype(jnp.float32)])
    return (st.out_color,
            jnp.stack([st.out_start, st.out_end], axis=1),
            st.seg_rgba,
            jnp.stack([st.seg_start, st.seg_end]),
            st.prev_rgb,
            flags,
            st.k)


def unpack_state(packed) -> ss.SegState:
    color, depth, seg, segse, prev, flags, k = packed
    return ss.SegState(
        out_color=color, out_start=depth[:, 0], out_end=depth[:, 1],
        k=k, open_=flags[0] > 0.5, seg_rgba=seg,
        seg_start=segse[0], seg_end=segse[1],
        prev_rgb=prev, prev_empty=flags[1] > 0.5)


# ------------------------------------------------------------ write(+count)


def _fold_kernel(*refs, max_k: int, gap_eps: float, with_count: bool):
    if with_count:
        (rgba_ref, td_ref, thr_ref,
         ci, di, si, ssei, pi, fi, ki, cnt_i,
         co, do_, so, sseo, po, fo, ko, cnt_o) = refs
    else:
        (rgba_ref, td_ref, thr_ref,
         ci, di, si, ssei, pi, fi, ki,
         co, do_, so, sseo, po, fo, ko) = refs
        cnt_i = cnt_o = None
    nc = rgba_ref.shape[0]
    thr = thr_ref[...]

    # working state lives in the OUTPUT refs (VMEM blocks): seed from the
    # inputs once, fold all C slices, leave the result in place. The
    # fori_loop carries nothing — Mosaic cannot legalize a loop with a
    # dozen carried vectors (see pallas_composite._kernel).
    co[...] = ci[...]
    do_[...] = di[...]
    so[...] = si[...]
    sseo[...] = ssei[...]
    po[...] = pi[...]
    fo[...] = fi[...]
    ko[...] = ki[...]
    if with_count:
        cnt_o[...] = cnt_i[...]

    def load() -> ss.SegState:
        return ss.SegState(
            out_color=co[...], out_start=do_[:, 0], out_end=do_[:, 1],
            k=ko[...], open_=fo[0] > 0.5, seg_rgba=so[...],
            seg_start=sseo[0], seg_end=sseo[1],
            prev_rgb=po[...], prev_empty=fo[1] > 0.5)

    def store(st: ss.SegState) -> None:
        co[...] = st.out_color
        do_[:, 0] = st.out_start
        do_[:, 1] = st.out_end
        so[...] = st.seg_rgba
        sseo[0] = st.seg_start
        sseo[1] = st.seg_end
        po[...] = st.prev_rgb
        fo[0] = st.open_.astype(jnp.float32)
        fo[1] = st.prev_empty.astype(jnp.float32)
        ko[...] = st.k

    def body(i, _):
        st = load()
        if with_count:
            # true (uncapped) segment starts — ss.push_count's predicate on
            # the writer's own prev-item stream (identical tracking rules)
            starts, _ = ss._start_mask(st.prev_rgb, st.prev_empty, None,
                                       rgba_ref[i], thr, None, -1.0)
            cnt_o[...] = cnt_o[...] + starts.astype(jnp.int32)
        store(ss.push(st, max_k, thr, rgba_ref[i],
                      td_ref[i, 0], td_ref[i, 1], gap_eps))
        return 0

    jax.lax.fori_loop(0, nc, body, 0)


def fold_chunk(packed, rgba: jnp.ndarray, t0: jnp.ndarray, t1: jnp.ndarray,
               threshold: jnp.ndarray, *, max_k: int,
               count: Optional[jnp.ndarray] = None, gap_eps: float = -1.0,
               interpret: Optional[bool] = None):
    """Fold one chunk of slices through the writer machine on pixel strips.

    packed: `pack_state` tuple ([K,…,H,W] / […,H,W]); rgba f32[C,4,H,W]
    premultiplied; t0/t1 f32[C,H,W]; threshold f32[H,W] (or scalar).
    ``count`` (i32[H,W], optional) additionally accumulates TRUE segment
    starts at this threshold (the temporal controller's signal). Returns
    the updated packed state (and count when given) — bit-identical to C
    sequential ``ss.push``/``ss.push_count`` calls.
    """
    if interpret is None:
        interpret = should_interpret()
    color = packed[0]
    kk, _, h, w = color.shape
    c = rgba.shape[0]
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))
    td = jnp.stack([t0, t1], axis=1)                       # [C, 2, H, W]
    with_count = count is not None

    grid = (h // TILE_H,)
    row = lambda *lead: pl.BlockSpec(lead + (TILE_H, w),
                                     lambda j: (0,) * len(lead) + (j, 0))
    state_specs = [row(kk, 4), row(kk, 2), row(4), row(2), row(3), row(2),
                   row()]
    state_shapes = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in packed]
    in_specs = [row(c, 4), row(c, 2), row()] + list(state_specs)
    out_specs = list(state_specs)
    out_shapes = list(state_shapes)
    operands = [rgba, td, threshold, *packed]
    # state input i+3 aliases output i (in-place update under jit)
    aliases = {i + 3: i for i in range(_STATE_FIELDS)}
    if with_count:
        in_specs.append(row())
        out_specs.append(row())
        out_shapes.append(jax.ShapeDtypeStruct((h, w), jnp.int32))
        operands.append(count)
        aliases[3 + _STATE_FIELDS] = _STATE_FIELDS

    kernel = functools.partial(_fold_kernel, max_k=max_k, gap_eps=gap_eps,
                               with_count=with_count)
    out = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shapes, input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    if with_count:
        return tuple(out[:_STATE_FIELDS]), out[_STATE_FIELDS]
    return tuple(out)


# ------------------------------------------------------- histogram counting


def _count_kernel(rgba_ref, tvec_ref, cnt_i, prev_i, fe_i,
                  cnt_o, prev_o, fe_o):
    nc = rgba_ref.shape[0]
    thr = tvec_ref[...]                                    # [B, 1, 1]
    cnt_o[...] = cnt_i[...]
    prev_o[...] = prev_i[...]
    fe_o[...] = fe_i[...]

    def body(i, _):
        rgba = rgba_ref[i]
        starts, is_empty = ss._start_mask(prev_o[...], fe_o[...] > 0.5,
                                          None, rgba, thr, None, -1.0)
        cnt_o[...] = cnt_o[...] + starts.astype(jnp.int32)
        prev_o[...] = jnp.where(is_empty[None], prev_o[...], rgba[:3])
        fe_o[...] = is_empty.astype(jnp.float32)
        return 0

    jax.lax.fori_loop(0, nc, body, 0)


def count_multi_chunk(carry, rgba: jnp.ndarray, tvec, *,
                      interpret: Optional[bool] = None):
    """One chunk of the all-candidates counting march (≅ feeding
    `ss.init_count_multi` state through `ss.push_count` with
    ``threshold=tvec[:,None,None]``, VMEM-tiled). ``carry`` is
    ``(count i32[B,H,W], prev f32[3,H,W], prev_empty f32[H,W])``;
    ``tvec`` is the B candidate thresholds (any array-like; a pallas
    kernel cannot close over array constants, so they ride as a [B,1,1]
    input).
    """
    if interpret is None:
        interpret = should_interpret()
    count, prev, fe = carry
    b, h, w = count.shape
    c = rgba.shape[0]
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    tvec3 = jnp.asarray(tvec, jnp.float32).reshape(b, 1, 1)

    row = lambda *lead: pl.BlockSpec(lead + (TILE_H, w),
                                     lambda j: (0,) * len(lead) + (j, 0))
    out = pl.pallas_call(
        _count_kernel, grid=(h // TILE_H,),
        in_specs=[row(c, 4),
                  pl.BlockSpec((b, 1, 1), lambda j: (0, 0, 0)),
                  row(b), row(3), row()],
        out_specs=[row(b), row(3), row()],
        out_shape=[jax.ShapeDtypeStruct((b, h, w), jnp.int32),
                   jax.ShapeDtypeStruct((3, h, w), jnp.float32),
                   jax.ShapeDtypeStruct((h, w), jnp.float32)],
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(rgba, tvec3, count, prev, fe)
    return tuple(out)


def init_count_multi_packed(bins: int, height: int, width: int):
    return (jnp.zeros((bins, height, width), jnp.int32),
            jnp.zeros((3, height, width), jnp.float32),
            jnp.ones((height, width), jnp.float32))


# ------------------------------------------------------------ compile probe

_FOLD_PROBE: dict = {}


def fold_compile_ok(max_k: int = 32, chunk: int = 16,
                    width: int = 2048) -> bool:
    """One-time probe: does Mosaic accept the fold kernel AT THIS SHAPE on
    the current backend? Like sim/pallas_stencil._compile_ok, this
    catches a compile rejection (typically VMEM exhaustion — shape
    dependent, so the probe must use the real K/chunk/width, not a toy
    shape) HERE, where `slicer.make_spec`'s "auto" resolution can fall
    back to the XLA fold — instead of inside a traced frame step (e.g.
    the driver's entry() compile check) where nothing can. The kernel's
    VMEM use per strip scales with (max_k, chunk, width) and is
    height-independent (one TILE_H strip per grid step); defaults are
    conservative upper bounds for this framework's configs. Cached per
    (backend, shape); failures are warned, not silent."""
    key = (jax.default_backend(), int(max_k), int(chunk), int(width))
    ok = _FOLD_PROBE.get(key)
    if ok is None:
        try:
            k, c, h, w = int(max_k), int(chunk), TILE_H, int(width)
            sds = jax.ShapeDtypeStruct
            packed = (sds((k, 4, h, w), jnp.float32),
                      sds((k, 2, h, w), jnp.float32),
                      sds((4, h, w), jnp.float32),
                      sds((2, h, w), jnp.float32),
                      sds((3, h, w), jnp.float32),
                      sds((2, h, w), jnp.float32),
                      sds((h, w), jnp.int32))

            def f(packed, rgba, t0, t1, thr, count):
                return fold_chunk(packed, rgba, t0, t1, thr, max_k=k,
                                  count=count)

            jax.jit(f).lower(
                packed, sds((c, 4, h, w), jnp.float32),
                sds((c, h, w), jnp.float32), sds((c, h, w), jnp.float32),
                sds((h, w), jnp.float32), sds((h, w), jnp.int32)).compile()
            ok = True
        except Exception as e:
            import warnings

            warnings.warn(
                f"Pallas march fold rejected at k={max_k} chunk={chunk} "
                f"width={width} ({type(e).__name__}: {str(e)[:200]}) — "
                "falling back to the XLA fold schedule. If this was a "
                "transient backend error, restart the process or set "
                "fold='pallas' explicitly.", stacklevel=2)
            ok = False
        _FOLD_PROBE[key] = ok
    return ok
