"""Pallas TPU kernels for the slice-march supersegment folds — the fused
counterpart of the reference's single-kernel generation (VDIGenerator.comp:
380-529 + AccumulateVDI.comp:69-98, where raycast sampling and the
supersegment state machine live in ONE GPU kernel and the per-ray state
never leaves registers).

The XLA march (ops/slicer.slice_march + ops/supersegments.push) carries the
full ``SegState`` — ~107 floats per pixel, dominated by ``out_color
[K,4,H,W]`` — through a ``lax.scan``, and every per-slice ``push`` inside
the scan body reads and rewrites those full-frame tensors through HBM.

The first fused kernel (round 3, commit 2358581) moved that fold onto VMEM
pixel strips but kept the XLA fold's schedule: per SLICE, load the whole
packed K-state from the VMEM refs, run ``ss.push`` (whose ``_write`` does
an O(K) one-hot select over every [K,...] array), store the whole state
back. On real hardware that was a regression — the 2026-07-30 TPU captures
(benchmarks/results/bench_tpu_r3_*.json) put the write march at ~390 ms at
512^3 vs ~34 ms for the O(1)-state counting march: ~100 floats/pixel of
VMEM state round-tripped per slice drowns the ~30-op state machine.

This kernel therefore splits the fold into two phases with the K-state
touched ONCE per chunk (benchmarks/fold_microbench.py measures the
schedules side by side):

- **Phase 1** unrolls the C-slice loop with the O(1) segment machine
  (open-segment RGBA/extent, prev-item, slot counter — 12 floats/pixel)
  carried as SSA values (registers; Mosaic spills what doesn't fit), and
  records each slice's potential close event (slot, rgba, t0, t1) as
  values. The optional temporal start-count accumulates here for free —
  it shares the writer's own prev-item stream exactly like the XLA
  ``ss.push_count`` twin.
- **Phase 2** loops over the K output slots; each slot row sums its (at
  most one — slots close at most once per march, the counter only moves
  forward) matching event from the C records and merges with the incoming
  row. [K,...] state: one read + one write per chunk.

Both phases implement exactly ``ss.push``'s semantics (same predicates,
same merge-overflow into the last slot); tests/test_pallas_march.py and
the committed golden fixture (tests/test_golden.py) pin equality with the
XLA fold chunk by chunk.

State is packed into 3 arrays: ``color f32[K,4,H,W]``, ``depth
f32[K,2,H,W]`` (start/end in [:,0]/[:,1]), and ``small f32[12,H,W]`` =
seg_rgba[0:4], seg_start[4], seg_end[5], prev_rgb[6:9], open[9],
prev_empty[10], k-count[11] (f32-encoded). ``input_output_aliases`` pins
each state input to its output so XLA updates in place.

Tiling: (8, WB) strips — 8 sublanes × a width block, grid over
(H/8, ceil(W/WB)). WB is the full row when the strip's VMEM estimate fits
the scoped budget (320-wide frames keep the round-2 single-block schedule)
and otherwise the largest multiple of 128 that does: at the 512^3 bench
scale (W=640, K=C=16) the full-width strip demands 16.39 MB scoped VMEM
against Mosaic's 16 MB limit — over by 2.5% — and the standalone compile
probe passes while the same kernel embedded in the frame's while/cond
fails on the extra stack frames, so the geometry must leave headroom
rather than ride the limit. W needn't be a multiple of the block: the
last block's lane padding is masked by Mosaic and no HBM copy is spent on
alignment. H must be a multiple of 8 (`slicer.make_spec` guarantees it).
On CPU (tests, the virtual mesh) the kernels run in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.ops.pallas_util import TILE_H, should_interpret

# packed-state arrays: color, depth, small
_STATE_FIELDS = 3
# small-state rows
_SEG_RGBA = slice(0, 4)
_SEG_START, _SEG_END = 4, 5
_PREV_RGB = slice(6, 9)
_OPEN, _PREV_EMPTY, _K = 9, 10, 11
_NSMALL = 12


# VMEM budget the strip ESTIMATE must fit in. The estimate is deliberately
# conservative — ~1.65x the 16.39 MB Mosaic measured for the K=16/C=16
# 640-wide strip (scoped-vmem error, window 2) — so 14 MB of estimate is
# ~8.5 MB of true usage: ample headroom under the 16 MB scoped limit for
# Mosaic's stack frames when the kernel sits inside lax control flow (the
# 512^3 OOM rode the limit and lost by 404 KB). 14 MB is calibrated so the
# default-config 320-wide strip (estimate 13.5 MB, true ~8.4 MB) keeps the
# round-2 single-block schedule the window-2 microbench numbers were
# captured under, while 640-wide strips tile to wb=256.
_VMEM_STRIP_BUDGET = 14 * 1024 * 1024
# geometry override for benchmarks/fold_microbench.py's hardware sweeps;
# None = budget-driven choice
_FORCE_BLOCK_W: Optional[int] = None
# fold_chunk's VMEM estimate treats K as at least this value, so the block
# width is IDENTICAL for every K <= _EST_K and `fold_compile_ok` (which
# probes at _EST_K) compiles the exact geometry production will run; with
# a K-dependent estimate a K=32 probe would pick a NARROWER (cheaper)
# block than a K=16 production kernel and could pass where production
# OOMs. K > _EST_K shrinks the block further (VMEM-safe) but then the
# probe geometry no longer matches — probe explicitly at that K.
_EST_K = 32
# bins floor for the counting kernel's block-width estimate (see
# count_multi_chunk / count_compile_ok)
_EST_B = 32
# phase-2 schedule experiment (benchmarks/fold_microbench.py variant
# "pallas_gated"): skip the event-extraction math for slot rows with no
# close event anywhere in the block — a chunk typically closes only a few
# consecutive slots per pixel, so most of the K x C extraction work sums
# zeros. Off by default until hardware shows it wins (the gate adds a
# scalar reduction + branch per slot row, and Mosaic's lowering cost for
# that is unknown).
_PHASE2_GATED = False


def strip_fpp(c: int, k: int, small_rows: int = _NSMALL,
              count_plane: bool = True, per_slice_records: int = 7,
              stream_per_slice: int = 6, extra_planes: int = 0) -> int:
    """Strip VMEM estimate in floats per pixel column — THE one budget
    formula every fold kernel and its microbench twins share: in+out
    blocks double-buffered (x2x2) over (stream_per_slice*C stream +
    1 threshold + extra per-pixel planes + 6K state + small rows +
    optional count plane), plus the per-slice record arrays (events or
    seg (slot,v) records) and slack for phase temporaries. K floored at
    _EST_K for probe-geometry invariance. Callers differing from the
    production fold pass their deltas explicitly instead of hand-copying
    the formula."""
    return (2 * 2 * (stream_per_slice * c + 1 + extra_planes
                     + 6 * max(k, _EST_K) + small_rows
                     + (1 if count_plane else 0))
            + per_slice_records * c + 64)


def _pick_block_w(w: int, bytes_per_col: int) -> int:
    """Widest block (full row, else a multiple of 128 lanes) whose strip
    VMEM estimate stays under the budget. ``bytes_per_col`` is the
    estimate for one pixel column of the strip (all TILE_H rows)."""
    if _FORCE_BLOCK_W is not None:
        return min(w, _FORCE_BLOCK_W)
    if w * bytes_per_col <= _VMEM_STRIP_BUDGET:
        return w
    wb = (_VMEM_STRIP_BUDGET // bytes_per_col) // 128 * 128
    if wb < 128:
        from scenery_insitu_tpu import obs

        obs.degrade(
            "ops.pallas_march.block_width", "budgeted strip",
            "128-lane floor",
            f"strip needs {bytes_per_col * 128 / 2**20:.1f} MB VMEM at "
            "the 128-lane minimum block width — over the "
            f"{_VMEM_STRIP_BUDGET / 2**20:.0f} MB budget; compiling at "
            "the floor anyway (Mosaic may reject it; the fold probe / "
            "auto mode falls back to the XLA fold)", stacklevel=3)
    return max(128, min(wb, w))


# ------------------------------------------------------------- state packing


def init_packed(k: int, height: int, width: int):
    """Packed fold state ≅ ss.init_state(k, height, width)."""
    color = jnp.zeros((k, 4, height, width), jnp.float32)
    depth = jnp.full((k, 2, height, width), jnp.inf, jnp.float32)
    small = jnp.zeros((_NSMALL, height, width), jnp.float32)
    small = small.at[_PREV_EMPTY].set(1.0)
    return (color, depth, small)


def pack_state(st: ss.SegState):
    small = jnp.concatenate([
        st.seg_rgba,
        st.seg_start[None], st.seg_end[None],
        st.prev_rgb,
        st.open_.astype(jnp.float32)[None],
        st.prev_empty.astype(jnp.float32)[None],
        st.k.astype(jnp.float32)[None]])
    return (st.out_color,
            jnp.stack([st.out_start, st.out_end], axis=1),
            small)


def unpack_state(packed) -> ss.SegState:
    color, depth, small = packed
    return ss.SegState(
        out_color=color, out_start=depth[:, 0], out_end=depth[:, 1],
        k=small[_K].astype(jnp.int32), open_=small[_OPEN] > 0.5,
        seg_rgba=small[_SEG_RGBA],
        seg_start=small[_SEG_START], seg_end=small[_SEG_END],
        prev_rgb=small[_PREV_RGB], prev_empty=small[_PREV_EMPTY] > 0.5)


# ------------------------------------------------------------ write(+count)


def _fold_kernel(*refs, max_k: int, gap_eps: float, with_count: bool):
    if with_count:
        (rgba_ref, td_ref, thr_ref,
         ci_, di_, smi_, cnt_i,
         co, do_, smo, cnt_o) = refs
    else:
        (rgba_ref, td_ref, thr_ref,
         ci_, di_, smi_,
         co, do_, smo) = refs
        cnt_i = cnt_o = None
    nc = rgba_ref.shape[0]
    thr = thr_ref[...]

    # ---- phase 1: O(1) machine over the C slices, state in SSA values
    sm = smi_[...]
    seg_rgba = sm[_SEG_RGBA]
    seg_start, seg_end = sm[_SEG_START], sm[_SEG_END]
    prev_rgb = sm[_PREV_RGB]
    open_ = sm[_OPEN] > 0.5
    prev_empty = sm[_PREV_EMPTY] > 0.5
    kcnt = sm[_K]
    n_starts = None

    events = []                        # (slot f32, rgba [4], t0, t1)
    for i in range(nc):
        rgba = rgba_ref[i]
        t0 = td_ref[i, 0]
        t1 = td_ref[i, 1]
        is_empty = rgba[3] < ss.EMPTY_ALPHA
        d = rgba[:3] - prev_rgb
        diff = jnp.sqrt(jnp.sum(d * d, axis=0))
        break_metric = ~is_empty & ~prev_empty & (diff > thr)
        want_break = break_metric | (is_empty & ~prev_empty)
        if gap_eps >= 0.0:
            want_break |= ~is_empty & open_ & (t0 > seg_end + gap_eps)
        do_close = open_ & want_break & (kcnt < max_k - 1)
        if with_count:
            # TRUE segment starts at this threshold (temporal feedback):
            # ss.push_count's predicate on the writer's prev-item stream
            starts = ~is_empty & (prev_empty | (diff > thr))
            sf = starts.astype(jnp.float32)
            n_starts = sf if n_starts is None else n_starts + sf
        events.append((jnp.where(do_close, kcnt, -1.0),
                       jnp.where(do_close[None], seg_rgba, 0.0),
                       jnp.where(do_close, seg_start, 0.0),
                       jnp.where(do_close, seg_end, 0.0)))
        kcnt = jnp.where(do_close, kcnt + 1.0, kcnt)
        open_ = open_ & ~do_close
        start_new = ~is_empty & ~open_
        accumulate = ~is_empty & open_
        seg_rgba = jnp.where(
            start_new[None], rgba,
            jnp.where(accumulate[None],
                      seg_rgba + (1.0 - seg_rgba[3:4]) * rgba, seg_rgba))
        seg_start = jnp.where(start_new, t0, seg_start)
        seg_end = jnp.where(start_new | accumulate, t1, seg_end)
        open_ = open_ | start_new
        prev_rgb = jnp.where(is_empty[None], prev_rgb, rgba[:3])
        prev_empty = is_empty

    smo[...] = jnp.concatenate([
        seg_rgba, seg_start[None], seg_end[None], prev_rgb,
        open_.astype(jnp.float32)[None],
        prev_empty.astype(jnp.float32)[None], kcnt[None]])
    if with_count:
        cnt_o[...] = cnt_i[...] + n_starts.astype(jnp.int32)

    # ---- phase 2: per-slot event extraction; K-state touched once.
    # Rolled over K (the event arrays are loop-INVARIANT captures — only
    # carried state breaks Mosaic legalization) so the kernel graph stays
    # small: the unrolled K×C version compiled ~4× slower everywhere and
    # dominated interpret-mode test time.
    ev_slot = jnp.stack([e[0] for e in events])            # [C, TH, W]
    ev_rgba = jnp.stack([e[1] for e in events])            # [C, 4, TH, W]
    ev_s = jnp.stack([e[2] for e in events])               # [C, TH, W]
    ev_e = jnp.stack([e[3] for e in events])               # [C, TH, W]

    def _extract(kk):
        m = ev_slot == kk.astype(jnp.float32)
        mf = m.astype(jnp.float32)
        hit = jnp.any(m, axis=0)
        acc_c = jnp.sum(ev_rgba * mf[:, None], axis=0)
        acc_s = jnp.sum(ev_s * mf, axis=0)
        acc_e = jnp.sum(ev_e * mf, axis=0)
        # + is a select: a slot closes at most once over the whole march
        # (the counter only moves forward), and color rows start at 0;
        # depth rows start at +inf so they need the explicit where
        co[pl.dslice(kk, 1)] = (ci_[pl.dslice(kk, 1)]
                                + acc_c[None].astype(jnp.float32))
        drow = di_[pl.dslice(kk, 1)]
        do_[pl.dslice(kk, 1)] = jnp.stack(
            [jnp.where(hit, acc_s, drow[0, 0]),
             jnp.where(hit, acc_e, drow[0, 1])])[None]

    def _copy_row(kk):
        co[pl.dslice(kk, 1)] = ci_[pl.dslice(kk, 1)]
        do_[pl.dslice(kk, 1)] = di_[pl.dslice(kk, 1)]

    if _PHASE2_GATED:
        # a row with no event anywhere in the block only needs the
        # passthrough copy (the out block must still be fully written —
        # it is a fresh VMEM buffer, not the input). NOTE: the jnp.any
        # reduces over the WHOLE block including the masked lane padding
        # of a partial last block on hardware; garbage in the padding can
        # only flip the gate CONSERVATIVELY true (extract where a copy
        # would do — correct, just slower), so a flat gated-vs-ungated
        # hardware result on non-128-multiple widths must not be misread
        # as the gate being worthless. Untestable in interpret mode.
        def slot_body(kk, _):
            kf = kk.astype(jnp.float32)
            row_has_event = jnp.any(ev_slot == kf)
            jax.lax.cond(row_has_event, _extract, _copy_row, kk)
            return 0
    else:
        def slot_body(kk, _):
            _extract(kk)
            return 0

    jax.lax.fori_loop(0, max_k, slot_body, 0)


def fold_chunk(packed, rgba: jnp.ndarray, t0: jnp.ndarray, t1: jnp.ndarray,
               threshold: jnp.ndarray, *, max_k: int,
               count: Optional[jnp.ndarray] = None, gap_eps: float = -1.0,
               interpret: Optional[bool] = None):
    """Fold one chunk of slices through the writer machine on pixel strips.

    packed: `pack_state` triple (color [K,4,H,W], depth [K,2,H,W], small
    [12,H,W]); rgba f32[C,4,H,W] premultiplied; t0/t1 f32[C,H,W];
    threshold f32[H,W] (or scalar). ``count`` (i32[H,W], optional)
    additionally accumulates TRUE segment starts at this threshold (the
    temporal controller's signal). Returns the updated packed state (and
    count when given) — bit-identical to C sequential
    ``ss.push``/``ss.push_count`` calls.
    """
    if interpret is None:
        interpret = should_interpret()
    color, depth, small = packed
    kk = color.shape[0]
    _, _, h, w = color.shape
    c = rgba.shape[0]
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    threshold = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), (h, w))
    td = jnp.stack([t0, t1], axis=1)                       # [C, 2, H, W]
    with_count = count is not None

    # the count plane is budgeted whether or not it rides along, for the
    # same probe-geometry-invariance reason as strip_fpp's K floor
    wb = _pick_block_w(w, 4 * TILE_H * strip_fpp(c, kk))
    grid = (h // TILE_H, pl.cdiv(w, wb))
    row = lambda *lead: pl.BlockSpec(lead + (TILE_H, wb),
                                     lambda j, i: (0,) * len(lead) + (j, i))
    state_specs = [row(kk, 4), row(kk, 2), row(_NSMALL)]
    state_shapes = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in packed]
    in_specs = [row(c, 4), row(c, 2), row()] + list(state_specs)
    out_specs = list(state_specs)
    out_shapes = list(state_shapes)
    operands = [rgba, td, threshold, *packed]
    # state input i+3 aliases output i (in-place update under jit)
    aliases = {i + 3: i for i in range(_STATE_FIELDS)}
    if with_count:
        in_specs.append(row())
        out_specs.append(row())
        out_shapes.append(jax.ShapeDtypeStruct((h, w), jnp.int32))
        operands.append(count)
        aliases[3 + _STATE_FIELDS] = _STATE_FIELDS

    kernel = functools.partial(_fold_kernel, max_k=max_k, gap_eps=gap_eps,
                               with_count=with_count)
    out = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shapes, input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    if with_count:
        return tuple(out[:_STATE_FIELDS]), out[_STATE_FIELDS]
    return tuple(out)


# ------------------------------------------------------- histogram counting


def _count_kernel(rgba_ref, tvec_ref, cnt_i, prev_i, fe_i,
                  cnt_o, prev_o, fe_o):
    nc = rgba_ref.shape[0]
    thr = tvec_ref[...]                                    # [B, 1, 1]
    cnt_o[...] = cnt_i[...]
    prev_o[...] = prev_i[...]
    fe_o[...] = fe_i[...]

    def body(i, _):
        rgba = rgba_ref[i]
        starts, is_empty = ss._start_mask(prev_o[...], fe_o[...] > 0.5,
                                          None, rgba, thr, None, -1.0)
        cnt_o[...] = cnt_o[...] + starts.astype(jnp.int32)
        prev_o[...] = jnp.where(is_empty[None], prev_o[...], rgba[:3])
        fe_o[...] = is_empty.astype(jnp.float32)
        return 0

    jax.lax.fori_loop(0, nc, body, 0)


def count_multi_chunk(carry, rgba: jnp.ndarray, tvec, *,
                      interpret: Optional[bool] = None):
    """One chunk of the all-candidates counting march (≅ feeding
    `ss.init_count_multi` state through `ss.push_count` with
    ``threshold=tvec[:,None,None]``, VMEM-tiled). ``carry`` is
    ``(count i32[B,H,W], prev f32[3,H,W], prev_empty f32[H,W])``;
    ``tvec`` is the B candidate thresholds (any array-like; a pallas
    kernel cannot close over array constants, so they ride as a [B,1,1]
    input).
    """
    if interpret is None:
        interpret = should_interpret()
    count, prev, fe = carry
    b, h, w = count.shape
    c = rgba.shape[0]
    if h % TILE_H:
        raise ValueError(f"height {h} not a multiple of {TILE_H}")
    tvec3 = jnp.asarray(tvec, jnp.float32).reshape(b, 1, 1)

    # b floored at _EST_B so the block width (the exact kernel geometry
    # Mosaic sees) is identical for every bins <= _EST_B and matches
    # `count_compile_ok`'s probe — same invariance argument as _EST_K
    floats_per_px = 2 * 2 * (4 * c + 2 * (max(b, _EST_B) + 4)) + 32
    wb = _pick_block_w(w, 4 * TILE_H * floats_per_px)
    row = lambda *lead: pl.BlockSpec(lead + (TILE_H, wb),
                                     lambda j, i: (0,) * len(lead) + (j, i))
    out = pl.pallas_call(
        _count_kernel, grid=(h // TILE_H, pl.cdiv(w, wb)),
        in_specs=[row(c, 4),
                  pl.BlockSpec((b, 1, 1), lambda j, i: (0, 0, 0)),
                  row(b), row(3), row()],
        out_specs=[row(b), row(3), row()],
        out_shape=[jax.ShapeDtypeStruct((b, h, w), jnp.int32),
                   jax.ShapeDtypeStruct((3, h, w), jnp.float32),
                   jax.ShapeDtypeStruct((h, w), jnp.float32)],
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(rgba, tvec3, count, prev, fe)
    return tuple(out)


def init_count_multi_packed(bins: int, height: int, width: int):
    return (jnp.zeros((bins, height, width), jnp.int32),
            jnp.zeros((3, height, width), jnp.float32),
            jnp.ones((height, width), jnp.float32))


# ------------------------------------------------------------ compile probe

_COUNT_PROBE: dict = {}


def count_compile_ok(bins: int = 32, chunk: int = 16,
                     width: int = 2048) -> bool:
    """One-time Mosaic-acceptance probe for the COUNTING kernel
    (`count_multi_chunk`) at the real (chunk, width) geometry
    geometry. The round-4 "auto" fold resolution requires this alongside
    the write-fold probe before selecting a pallas schedule: the
    histogram/temporal-seed counting march runs this kernel, and a
    rejection must degrade to the XLA counting scan in `make_spec`, not
    fail inside a traced frame step. Probed at max(bins, _EST_B): the
    bins floor in the kernel's block-width estimate pins the block
    geometry for every bins <= _EST_B to what the _EST_B probe
    exercises (conservative direction — the probe's kernel is the
    bigger one), and bins > _EST_B probe at their real size."""
    key = (jax.default_backend(), int(max(bins, _EST_B)), int(chunk),
           int(width))
    ok = _COUNT_PROBE.get(key)
    if ok is None:
        try:
            b, c, h, w = int(max(bins, _EST_B)), int(chunk), TILE_H, \
                int(width)
            sds = jax.ShapeDtypeStruct

            def f(carry, rgba, tvec):
                return count_multi_chunk(carry, rgba, tvec)

            carry = (sds((b, h, w), jnp.int32), sds((3, h, w), jnp.float32),
                     sds((h, w), jnp.float32))
            jax.jit(f).lower(carry, sds((c, 4, h, w), jnp.float32),
                             sds((b,), jnp.float32)).compile()
            ok = True
        except Exception as e:
            from scenery_insitu_tpu import obs

            obs.degrade(
                "ops.count_fold", "pallas_count", "xla",
                f"Mosaic rejected the counting kernel at bins={bins} "
                f"chunk={chunk} width={width} ({type(e).__name__}: "
                f"{str(e)[:200]})")
            ok = False
        _COUNT_PROBE[key] = ok
    return ok


_FOLD_PROBE: dict = {}


def fold_compile_ok(max_k: int = 32, chunk: int = 16,
                    width: int = 2048) -> bool:
    """One-time probe: does Mosaic accept the fold kernel AT THIS SHAPE on
    the current backend? Like sim/pallas_stencil._compile_ok, this
    catches a compile rejection (typically a Mosaic resource limit —
    shape dependent, so the probe must use the real K/chunk/width, not a
    toy shape) HERE, where `slicer.make_spec`'s "auto" resolution can
    fall back to the XLA fold — instead of inside a traced frame step
    (e.g. the driver's entry() compile check) where nothing can. Strip
    VMEM scales with (max_k, chunk) and — since `_pick_block_w` caps the
    block width by the budget — is insensitive to width beyond the cap;
    probing at the real width still matters because it fixes the BLOCK
    width (and thus the exact kernel Mosaic sees), not because wider
    frames cost more VMEM. Height never matters (one TILE_H strip per
    grid step). Defaults are conservative upper bounds for this
    framework's configs. Cached per (backend, shape); failures are
    warned, not silent."""
    key = (jax.default_backend(), int(max_k), int(chunk), int(width))
    ok = _FOLD_PROBE.get(key)
    if ok is None:
        try:
            k, c, h, w = int(max_k), int(chunk), TILE_H, int(width)
            sds = jax.ShapeDtypeStruct
            packed = (sds((k, 4, h, w), jnp.float32),
                      sds((k, 2, h, w), jnp.float32),
                      sds((_NSMALL, h, w), jnp.float32))

            def f(packed, rgba, t0, t1, thr, count):
                return fold_chunk(packed, rgba, t0, t1, thr, max_k=k,
                                  count=count)

            jax.jit(f).lower(
                packed, sds((c, 4, h, w), jnp.float32),
                sds((c, h, w), jnp.float32), sds((c, h, w), jnp.float32),
                sds((h, w), jnp.float32), sds((h, w), jnp.int32)).compile()
            ok = True
        except Exception as e:
            from scenery_insitu_tpu import obs

            obs.degrade(
                "ops.march_fold", "pallas", "xla",
                f"Mosaic rejected the march fold at k={max_k} "
                f"chunk={chunk} width={width} ({type(e).__name__}: "
                f"{str(e)[:200]}). If this was a transient backend "
                "error, restart the process or set fold='pallas' "
                "explicitly.")
            ok = False
        _FOLD_PROBE[key] = ok
    return ok
