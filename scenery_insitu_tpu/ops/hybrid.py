"""Hybrid volume + particle compositing (BASELINE.md Config 5: a sharded
sim volume rendered as a VDI with opaque tracer spheres inside it).

The reference's closest analog is crude: the head node min-depth PICKS one
rank's full image per pixel (Head.kt:98-134, NaiveCompositor.frag:15-28),
so a particle either fully hides the volume or is fully hidden. Here the
particle z-buffer is inserted INTO the volume's transparency integral: for
each pixel, supersegments in front of the particle contribute in full,
the supersegment containing the particle depth contributes its traversed
fraction (opacity re-corrected with ``1-(1-A)^f`` — the same
traversed-fraction law as ops.sampling.adjust_opacity / the reference's
adjustOpacity, VDIGenerator.comp:80-82), the particle is alpha-undered at
its depth, and everything behind an opaque particle is occluded for free.

Both inputs must share rays: same camera, same pixel grid, and the ONE
framework depth convention (world ray-parameter t). The slice-march
pipeline guarantees this by splatting particles onto the virtual axis
camera's grid (ops.splat.splat_particles with view/proj overrides).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.core.vdi import VDI
from scenery_insitu_tpu.ops.splat import SplatOutput


def composite_vdi_with_particles(vdi: VDI, splat: SplatOutput
                                 ) -> jnp.ndarray:
    """Merge a VDI (supersegments sorted front-to-back per pixel, the
    generation output order) with an opaque particle layer. Returns the
    premultiplied image f32[4, H, W] (background-free).

    Pixels without a particle (splat depth +inf) reproduce the plain VDI
    decode exactly; pixels whose particle sits in front of everything show
    the particle over nothing.
    """
    tp = splat.depth                                       # [H, W]

    def body(acc, slot):
        c, t0, t1 = slot                                   # [4,H,W],[H,W],[H,W]
        # fraction of the slab in front of the particle (1 when t1<=tp or
        # no particle; 0 when the slab is fully behind it)
        denom = jnp.maximum(t1 - t0, 1e-12)
        frac = jnp.clip((tp - t0) / denom, 0.0, 1.0)
        frac = jnp.where(jnp.isfinite(tp), frac, 1.0)
        a = c[3]
        a_eff = 1.0 - jnp.power(jnp.maximum(1.0 - a, 0.0), frac)
        scale = jnp.where(a > 1e-12, a_eff / jnp.maximum(a, 1e-12), 0.0)
        src = c * scale[None]
        return acc + (1.0 - acc[3:4]) * src, None

    acc0 = jnp.zeros_like(vdi.color[0])
    acc, _ = jax.lax.scan(body, acc0,
                          (jnp.where(jnp.isfinite(vdi.depth[:, 0:1]),
                                     vdi.color, 0.0),
                           jnp.where(jnp.isfinite(vdi.depth[:, 0]),
                                     vdi.depth[:, 0], 0.0),
                           jnp.where(jnp.isfinite(vdi.depth[:, 1]),
                                     vdi.depth[:, 1], 0.0)))
    # the opaque particle layer sits behind exactly the front fraction
    return acc + (1.0 - acc[3:4]) * splat.image
