from scenery_insitu_tpu.ops.raycast import raycast  # noqa: F401
