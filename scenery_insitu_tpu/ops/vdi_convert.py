"""VDI depth-convention conversion and validation
(≅ reference VDIConverter.kt:44-275 + ConvertToNDC.comp:59-239).

The reference accumulated three depth encodings behind #defines (NDC-z,
world distance, integer step counts — VDIGenerator.comp:41-43,
AccumulateVDI.comp:108-128) and needed a whole GPU pass (ConvertToNDC.comp)
to normalize stored VDIs before novel-view rendering. This framework keeps
ONE internal encoding — the world-space ray parameter t of the generating
camera (core/vdi.py docstring) — and this module is the explicit boundary
converter for interchange with reference-convention consumers:

- ``depths_to_ndc`` / ``depths_from_ndc``: world-t ↔ NDC-z of the
  generating camera (exact, analytic per pixel; works for the off-axis
  virtual cameras the MXU slice-march engine produces, because everything
  goes through the metadata's projection/view matrices).
- ``pack_reference_layout`` / ``unpack_reference_layout``: the reference's
  GPU texture layouts — color rgba32f ``[K, H, W, 4]`` and depth r32f
  ``[2K, H, W]`` with start/end interleaved (OutputSubVDIColor/
  OutputSubVDIDepth, reference DistributedVolumes.kt:331-368).
- ``validate_vdi``: the monotonicity/range assertions ConvertToNDC.comp
  carried as debugPrintf error paths (:155-157, 197-208), as a host-side
  report.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from scenery_insitu_tpu.core.camera import _normalize
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.ops.vdi_render import original_eye


def rays_from_metadata(meta: VDIMetadata) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pixel world rays of the generating camera, reconstructed from
    the metadata matrices (generalizes camera.pixel_rays to any projection,
    including the slice-march engine's off-axis frusta). Returns
    (eye f32[3], dirs f32[3, H, W]) with unit-length dirs."""
    w = int(meta.window_dims[0])
    h = int(meta.window_dims[1])
    inv_vp = jnp.linalg.inv(meta.projection @ meta.view)
    ndc_x = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w * 2.0 - 1.0
    ndc_y = 1.0 - (jnp.arange(h, dtype=jnp.float32) + 0.5) / h * 2.0
    gx, gy = jnp.meshgrid(ndc_x, ndc_y, indexing="xy")
    ndc = jnp.stack([gx, gy, jnp.full_like(gx, -1.0), jnp.ones_like(gx)])
    pw = jnp.einsum("ab,bhw->ahw", inv_vp, ndc)
    near_pt = pw[:3] / pw[3:4]
    eye = original_eye(meta)
    dirs = _normalize(near_pt - eye.reshape(3, 1, 1), axis=0)
    return eye, dirs


def depths_to_ndc(vdi: VDI, meta: VDIMetadata) -> VDI:
    """World-t depths -> NDC z of the generating camera (the reference's
    storage convention after ConvertToNDC.comp). Empty slots (+inf) map to
    +inf so emptiness stays recognizable."""
    _, dirs = rays_from_metadata(meta)
    p22 = meta.projection[2, 2]
    p23 = meta.projection[2, 3]
    dir_ze = jnp.einsum("b,bhw->hw", meta.view[2, :3], dirs)   # < 0 in front

    def conv(t):                                               # t: [K, H, W]
        ze = dir_ze[None] * t                # eye-space z, negative in front
        # ndc_z = (p22*ze + p23) / (-ze)
        ndc = -(p22 + p23 / jnp.where(ze == 0, -1e-20, ze))
        return jnp.where(jnp.isfinite(t), ndc, jnp.inf)

    start = conv(vdi.depth[:, 0])
    end = conv(vdi.depth[:, 1])
    return VDI(vdi.color, jnp.stack([start, end], axis=1))


def depths_from_ndc(vdi_ndc: VDI, meta: VDIMetadata) -> VDI:
    """Inverse of `depths_to_ndc`: NDC-z depths -> world ray parameter t
    (the framework's internal convention)."""
    _, dirs = rays_from_metadata(meta)
    p22 = meta.projection[2, 2]
    p23 = meta.projection[2, 3]
    dir_ze = jnp.einsum("b,bhw->hw", meta.view[2, :3], dirs)   # < 0

    def conv(ndc):
        ze = -p23 / (p22 + ndc)          # eye-space z (negative in front)
        t = ze / dir_ze[None]
        return jnp.where(jnp.isfinite(ndc), t, jnp.inf)

    start = conv(vdi_ndc.depth[:, 0])
    end = conv(vdi_ndc.depth[:, 1])
    return VDI(vdi_ndc.color, jnp.stack([start, end], axis=1))


# ------------------------------------------------------ reference layouts


def pack_reference_layout(vdi: VDI) -> Tuple[np.ndarray, np.ndarray]:
    """Framework VDI -> the reference's texture memory layouts: color
    rgba32f ``[K, H, W, 4]`` and depth r32f ``[2K, H, W]`` with start/end
    interleaved per supersegment (OutputSubVDIColor/OutputSubVDIDepth,
    reference DistributedVolumes.kt:331-368; VDIGenerator.comp:204-226).
    Empty slots are zero-filled as the generator does (:553-590)."""
    color = np.moveaxis(np.asarray(vdi.color), 1, -1)          # [K, H, W, 4]
    depth = np.asarray(vdi.depth)                              # [K, 2, H, W]
    live = np.isfinite(depth[:, 0])
    color = np.where(live[..., None], color, 0.0).astype(np.float32)
    d = np.where(live[:, None], depth, 0.0).astype(np.float32)
    k, _, h, w = d.shape
    interleaved = d.reshape(2 * k, h, w)                       # start,end,...
    return color, interleaved


def unpack_reference_layout(color_khw4: np.ndarray,
                            depth_2khw: np.ndarray) -> VDI:
    """Inverse of `pack_reference_layout`. Slots with zero alpha AND zero
    depth extent are treated as empty (depth -> +inf)."""
    color = jnp.asarray(np.moveaxis(color_khw4, -1, 1), jnp.float32)
    k2, h, w = depth_2khw.shape
    d = np.asarray(depth_2khw, np.float32).reshape(k2 // 2, 2, h, w)
    empty = (np.asarray(color_khw4)[..., 3] <= 0.0) & (d[:, 1] <= d[:, 0])
    d = np.where(empty[:, None], np.inf, d)
    return VDI(color, jnp.asarray(d))


def pack_3layer(vdi: VDI) -> np.ndarray:
    """Framework VDI -> the older 3-layer packed SINGLE-texture layout:
    rgba32f ``[3K, H, W, 4]`` where supersegment k occupies layers
    ``3k`` (color RGBA), ``3k+1`` (start depth in .r) and ``3k+2`` (end
    depth in .r) — the ``3 * maxSupersegments`` texture of the legacy
    InVisVolumeRenderer (InVisVolumeRenderer.kt:138-141, consumed by
    SimpleVDIRenderer.comp). Empty slots zero-filled."""
    color = np.moveaxis(np.asarray(vdi.color), 1, -1)          # [K, H, W, 4]
    depth = np.asarray(vdi.depth)                              # [K, 2, H, W]
    live = np.isfinite(depth[:, 0])
    k, h, w = live.shape
    out = np.zeros((3 * k, h, w, 4), np.float32)
    out[0::3] = np.where(live[..., None], color, 0.0)
    out[1::3, :, :, 0] = np.where(live, depth[:, 0], 0.0)
    out[2::3, :, :, 0] = np.where(live, depth[:, 1], 0.0)
    return out


def unpack_3layer(packed: np.ndarray) -> VDI:
    """Inverse of `pack_3layer` (zero-alpha zero-extent slots -> empty)."""
    color = jnp.asarray(np.moveaxis(packed[0::3], -1, 1), jnp.float32)
    start = np.asarray(packed[1::3, :, :, 0], np.float32)
    end = np.asarray(packed[2::3, :, :, 0], np.float32)
    empty = (packed[0::3, :, :, 3] <= 0.0) & (end <= start)
    d = np.stack([start, end], axis=1)
    d = np.where(empty[:, None], np.inf, d)
    return VDI(color, jnp.asarray(d))


def render_packed_vdi(packed: np.ndarray,
                      background=(0.0, 0.0, 0.0, 0.0)) -> jnp.ndarray:
    """Decode + same-view render of a 3-layer packed VDI (the
    SimpleVDIRenderer.comp role: alpha-under of the packed supersegments,
    SimpleVDIRenderer.comp:43-74)."""
    from scenery_insitu_tpu.core.vdi import render_vdi_same_view

    return render_vdi_same_view(unpack_3layer(packed),
                                background=background)


# ------------------------------------------------------------- validation


def validate_vdi(vdi: VDI, ndc: bool = False,
                 gap_eps: float = 1e-4) -> Dict[str, int]:
    """Host-side structural checks (≅ the in-shader assertions,
    ConvertToNDC.comp:155-157, 197-208): per live slot end >= start,
    consecutive live slots depth-sorted and non-overlapping, alpha in
    [0, 1], and (ndc mode) depths within [-1, 1]. Returns violation
    counts; all zeros = valid."""
    color = np.asarray(vdi.color)
    depth = np.asarray(vdi.depth)
    start, end = depth[:, 0], depth[:, 1]
    live = np.isfinite(start)
    a = color[:, 3]

    rep: Dict[str, int] = {}
    rep["inverted_extent"] = int(np.sum(live & (end < start)))
    overlap = 0
    unsorted = 0
    for s in range(vdi.k - 1):
        both = live[s] & live[s + 1]
        overlap += int(np.sum(both & (start[s + 1] < end[s] - gap_eps)))
        unsorted += int(np.sum(both & (start[s + 1] < start[s])))
    rep["overlapping"] = overlap
    rep["unsorted"] = unsorted
    rep["alpha_out_of_range"] = int(np.sum((a < -1e-6) | (a > 1.0 + 1e-6)))
    rep["dead_slot_after_live"] = int(np.sum(~live[:-1] & live[1:]))
    if ndc:
        rep["ndc_out_of_range"] = int(np.sum(
            live & ((start < -1.0 - 1e-4) | (end > 1.0 + 1e-4))))
    rep["live_slots"] = int(np.sum(live))
    return rep


# ------------------------------------- Vulkan reference-frame normalization
#
# The three conventions that break naive pixel comparison against the
# Vulkan reference (SURVEY.md §7 "Image parity vs Vulkan"), as explicit,
# individually-tested converters. The composition `to_reference_frame`
# maps one of this framework's linear premultiplied images into the frame
# a reference screenshot/dump lives in; with these, a Vulkan render (the
# day one exists next to this repo) is comparable by plain PSNR, and the
# golden-fixture tests (tests/test_golden.py) pin the protocol.


def vulkan_projection_fix() -> np.ndarray:
    """The reference's GL→Vulkan clip-space correction matrix (reference
    DistributedVolumes.kt:67-79): Vulkan's NDC y points DOWN and its
    depth range is [0, 1] where GL's is [-1, 1]. Left-multiply a GL-style
    projection with this to get the matrix the reference's shaders used:
    ``P_vk = fix @ P_gl`` → y' = -y, z' = (z + w)/2."""
    return np.array([[1.0, 0.0, 0.0, 0.0],
                     [0.0, -1.0, 0.0, 0.0],
                     [0.0, 0.0, 0.5, 0.5],
                     [0.0, 0.0, 0.0, 1.0]], np.float32)


def projection_gl_to_vulkan(proj: jnp.ndarray) -> jnp.ndarray:
    """GL-convention projection (what core/camera.py builds and all VDI
    metadata carries) → the Vulkan-convention projection the reference
    stored in its VDIData (its shaders consumed the fixed matrix:
    VDIGenerator.comp uses ipv = inv(View)*inv(P_vk))."""
    return jnp.asarray(vulkan_projection_fix()) @ proj


def projection_vulkan_to_gl(proj_vk: jnp.ndarray) -> jnp.ndarray:
    """Inverse of `projection_gl_to_vulkan` — apply to matrices read from
    reference-written VDIData dumps before using them with this
    framework's GL-convention NDC math (depths_to/from_ndc)."""
    return jnp.asarray(np.linalg.inv(vulkan_projection_fix())) @ proj_vk


def gamma_encode(image: jnp.ndarray, gamma: float = 2.2) -> jnp.ndarray:
    """The reference's write-time gamma on rgb (``pow(v, 1/2.2)``,
    VDIGenerator.comp:537); alpha stays linear. Accepts [..., 4, H, W]
    (channel-first, this framework's layout)."""
    rgb = jnp.power(jnp.clip(image[..., :3, :, :], 0.0, 1.0), 1.0 / gamma)
    return jnp.concatenate([rgb, image[..., 3:4, :, :]], axis=-3)


def gamma_decode(image: jnp.ndarray, gamma: float = 2.2) -> jnp.ndarray:
    """Inverse of `gamma_encode` (reference screenshots → linear)."""
    rgb = jnp.power(jnp.clip(image[..., :3, :, :], 0.0, 1.0), gamma)
    return jnp.concatenate([rgb, image[..., 3:4, :, :]], axis=-3)


def flip_y(image: jnp.ndarray) -> jnp.ndarray:
    """Row flip between this framework's top-down pixel rows and the
    reference's bottom-up framebuffer order (the reference flips y when
    re-projecting stored VDIs: ConvertToNDC.comp:238)."""
    return image[..., ::-1, :]


def to_reference_frame(image: jnp.ndarray, gamma: float = 2.2,
                       flip: bool = True) -> jnp.ndarray:
    """Linear premultiplied [4, H, W] (row 0 = top) → the reference
    screenshot frame: gamma-encoded rgb, bottom-up rows. THE comparison
    protocol: normalize ours with this, then plain PSNR against the
    Vulkan image."""
    out = gamma_encode(image, gamma)
    return flip_y(out) if flip else out
