"""The supersegment state machine — shared core of VDI generation and VDI
compositing.

A stream of depth-ordered items (raycast samples during generation,
already-built supersegments during compositing) is folded front-to-back into
at most K output supersegments per pixel. An open segment accumulates items
by alpha-under composition; it closes when

- the premultiplied-RGB distance between the incoming item and the previous
  item exceeds a threshold (≅ the reference's close test,
  AccumulateVDI.comp:69-98), or
- the stream transitions non-empty -> empty (a transparent gap; ≅ the
  transparent-sample truncation ``steps_trunc_trans``,
  AccumulateVDI.comp:239-249).

Differences from the reference, on purpose (TPU-first redesign):

- The break metric compares *consecutive items*, not the running segment
  accumulator. This makes the per-pixel segment count a monotone function of
  the threshold that can be evaluated by a cheap counting pass with O(1)
  state — so the reference's adaptive per-pixel threshold binary search
  (VDIGenerator.comp:380-529, a nested data-dependent loop that would
  serialize terribly on TPU) becomes ``adaptive_iters`` fully-vectorized
  counting marches followed by one writing march. No divergence, static
  shapes throughout.
- Overflow merges into the last slot instead of dropping segments, so a too-
  low threshold degrades gracefully; the adaptive search keeps counts near K
  anyway (target band [K*(1-delta), K], same as the reference's delta=15%).
- Segments store the *fully composited* premultiplied RGBA of their samples;
  re-rendering adjusts opacity by traversed-fraction with
  ``1-(1-A)^(len_in/len_slab)`` (see ops.sampling.adjust_opacity), replacing
  the reference's write-time ``adjustOpacity(a, 1/segLen)``
  (VDIGenerator.comp:80-82).

All functions are shaped ``[H, W]``-batched and jit/vmap/shard_map friendly.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

EMPTY_ALPHA = 1e-4


class SegState(NamedTuple):
    """Per-pixel fold state; every field is [H, W]-shaped (or [K.., H, W])."""

    out_color: jnp.ndarray    # [K, 4, H, W]
    out_start: jnp.ndarray    # [K, H, W]
    out_end: jnp.ndarray      # [K, H, W]
    k: jnp.ndarray            # i32[H, W] next free slot
    open_: jnp.ndarray        # bool[H, W] a segment is accumulating
    seg_rgba: jnp.ndarray     # [4, H, W] open segment premultiplied RGBA
    seg_start: jnp.ndarray    # [H, W]
    seg_end: jnp.ndarray      # [H, W]
    prev_rgb: jnp.ndarray     # [3, H, W] previous item premultiplied RGB
    prev_empty: jnp.ndarray   # bool[H, W]


def init_state(k: int, height: int, width: int) -> SegState:
    f = lambda *s: jnp.zeros(s, jnp.float32)
    return SegState(
        out_color=f(k, 4, height, width),
        out_start=jnp.full((k, height, width), jnp.inf, jnp.float32),
        out_end=jnp.full((k, height, width), jnp.inf, jnp.float32),
        k=jnp.zeros((height, width), jnp.int32),
        open_=jnp.zeros((height, width), bool),
        seg_rgba=f(4, height, width),
        seg_start=f(height, width),
        seg_end=f(height, width),
        prev_rgb=f(3, height, width),
        prev_empty=jnp.ones((height, width), bool),
    )


def push(state: SegState, max_k: int, threshold: jnp.ndarray,
         rgba: jnp.ndarray, t0: jnp.ndarray, t1: jnp.ndarray,
         gap_eps: float = -1.0) -> SegState:
    """Feed one depth-ordered item per pixel into the machine.

    rgba: [4, H, W] premultiplied; t0/t1: [H, W] item depth extent.
    threshold: scalar or [H, W]. If ``gap_eps >= 0`` a depth gap between the
    open segment's end and the incoming item also breaks (used when merging
    already-built supersegments, where gaps are implicit; during generation
    gaps arrive as explicit empty samples instead — ≅ the compositor's
    gap-as-transparent handling, VDICompositor.comp:299-315).
    """
    is_empty = rgba[3] < EMPTY_ALPHA
    diff = jnp.linalg.norm(rgba[:3] - state.prev_rgb, axis=0)
    want_break = (~is_empty & ~state.prev_empty & (diff > threshold)) | \
                 (is_empty & ~state.prev_empty)
    if gap_eps >= 0.0:
        want_break |= ~is_empty & state.open_ & (t0 > state.seg_end + gap_eps)
    # merge-overflow: the last slot never closes mid-stream
    do_close = state.open_ & want_break & (state.k < max_k - 1)

    out_color, out_start, out_end, k = _write(
        state, do_close, state.out_color, state.out_start, state.out_end)
    open_ = state.open_ & ~do_close

    # start a new segment / accumulate into the open one
    start_new = ~is_empty & ~open_
    accumulate = ~is_empty & open_

    seg_rgba = jnp.where(start_new[None], rgba, state.seg_rgba)
    seg_rgba = jnp.where(accumulate[None],
                         state.seg_rgba + (1.0 - state.seg_rgba[3:4]) * rgba,
                         seg_rgba)
    seg_start = jnp.where(start_new, t0, state.seg_start)
    seg_end = jnp.where(start_new | accumulate, t1, state.seg_end)
    open_ = open_ | start_new

    return SegState(out_color, out_start, out_end, k, open_,
                    seg_rgba, seg_start, seg_end,
                    jnp.where(is_empty[None], state.prev_rgb, rgba[:3]),
                    is_empty)


def finalize(state: SegState) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Close any open segment; returns (color [K,4,H,W], depth [K,2,H,W])."""
    out_color, out_start, out_end, _ = _write(
        state, state.open_, state.out_color, state.out_start, state.out_end)
    depth = jnp.stack([out_start, out_end], axis=1)
    return out_color, depth


def _write(state: SegState, do_write: jnp.ndarray,
           out_color, out_start, out_end):
    kmax = out_color.shape[0]
    slot = jnp.minimum(state.k, kmax - 1)
    # broadcasted_iota (not arange+reshape): Mosaic can't lower a 1D iota
    # shape-cast, and this fold also runs inside the Pallas composite kernel
    slot_ids = jax.lax.broadcasted_iota(jnp.int32, (kmax, 1, 1), 0)
    onehot = (slot_ids == slot[None]) & do_write[None]     # [K, H, W]
    out_color = jnp.where(onehot[:, None], state.seg_rgba[None], out_color)
    out_start = jnp.where(onehot, state.seg_start[None], out_start)
    out_end = jnp.where(onehot, state.seg_end[None], out_end)
    k = jnp.where(do_write, state.k + 1, state.k)
    return out_color, out_start, out_end, k


# ---------------------------------------------------------------- counting

class CountState(NamedTuple):
    count: jnp.ndarray       # i32[H, W] segments started so far
    prev_rgb: jnp.ndarray    # [3, H, W]
    prev_empty: jnp.ndarray  # bool[H, W]
    prev_end: jnp.ndarray    # [H, W] end depth of previous live item


def init_count(height: int, width: int) -> CountState:
    return CountState(jnp.zeros((height, width), jnp.int32),
                      jnp.zeros((3, height, width), jnp.float32),
                      jnp.ones((height, width), bool),
                      jnp.full((height, width), -jnp.inf, jnp.float32))


def _start_mask(prev_rgb, prev_empty, prev_end, rgba, thr, t0,
                gap_eps: float):
    """Segment-START predicate shared by every counting variant. ``thr``
    is anything broadcastable against [H, W] (per-pixel [H, W], scalar, or
    candidate stack [B, 1, 1]). Returns (starts, is_empty)."""
    is_empty = rgba[3] < EMPTY_ALPHA
    diff = jnp.linalg.norm(rgba[:3] - prev_rgb, axis=0)
    starts = ~is_empty & (prev_empty | (diff > thr))
    if gap_eps >= 0.0 and t0 is not None:
        starts = starts | (~is_empty & ~prev_empty
                           & (t0 > prev_end + gap_eps))
    return starts, is_empty


def push_count(state: CountState, threshold: jnp.ndarray,
               rgba: jnp.ndarray, t0: jnp.ndarray = None,
               t1: jnp.ndarray = None, gap_eps: float = -1.0) -> CountState:
    """O(1)-state counterpart of `push`: counts segment *starts*."""
    starts, is_empty = _start_mask(state.prev_rgb, state.prev_empty,
                                   state.prev_end, rgba, threshold, t0,
                                   gap_eps)
    prev_end = state.prev_end if t1 is None else \
        jnp.where(is_empty, state.prev_end, t1)
    return CountState(state.count + starts.astype(jnp.int32),
                      jnp.where(is_empty[None], state.prev_rgb, rgba[:3]),
                      is_empty, prev_end)


def adaptive_threshold(count_fn: Callable[[jnp.ndarray], jnp.ndarray],
                       max_k: int, iters: int, height: int, width: int,
                       thr_max: float = 2.0) -> jnp.ndarray:
    """Per-pixel binary search for the smallest threshold whose segment count
    is <= max_k (vectorized replacement for the reference's in-kernel search,
    VDIGenerator.comp:380-529). `count_fn(thr [H,W]) -> i32[H,W]`."""
    lo = jnp.zeros((height, width), jnp.float32)
    hi = jnp.full((height, width), thr_max, jnp.float32)

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        c = count_fn(mid)
        too_many = c > max_k
        return jnp.where(too_many, mid, lo), jnp.where(too_many, hi, mid)

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return hi


# --------------------------------------------- one-pass histogram threshold

def threshold_candidates(bins: int, thr_max: float = 2.0,
                         octaves: float = 8.0) -> jnp.ndarray:
    """f32[B] ascending candidate thresholds: 0 (maximal segmentation)
    followed by log spacing over ``octaves`` doublings up to thr_max —
    small thresholds matter most (they control fine segmentation)."""
    import numpy as np

    t = np.geomspace(thr_max / 2.0 ** octaves, thr_max, bins - 1)
    return jnp.asarray(np.concatenate([[0.0], t]), jnp.float32)


def init_count_multi(bins: int, height: int, width: int) -> CountState:
    """CountState whose count is [B, H, W] — feed it through the ORDINARY
    `push_count` with ``threshold=tvec[:, None, None]`` to evaluate every
    candidate threshold in one march (the `_start_mask` predicate
    broadcasts, and the prev_* tracking is threshold-independent). The
    break metric compares CONSECUTIVE items (by design — see module
    docstring), which is what makes count(thr) separable per candidate —
    the payoff of diverging from the reference's accumulator-relative
    break test."""
    return CountState(jnp.zeros((bins, height, width), jnp.int32),
                      jnp.zeros((3, height, width), jnp.float32),
                      jnp.ones((height, width), bool),
                      jnp.full((height, width), -jnp.inf, jnp.float32))


class ThresholdState(NamedTuple):
    """Carried state of the temporal threshold controller
    (adaptive_mode="temporal"): the active per-pixel threshold plus the
    bisection bracket [lo, hi] — lo is known (or decayed toward) to
    overflow, hi known to fit. All [H, W]."""

    thr: jnp.ndarray
    lo: jnp.ndarray
    hi: jnp.ndarray


def init_threshold_state(thr: jnp.ndarray, thr_min: float = 1e-3,
                         thr_max: float = 2.0) -> ThresholdState:
    thr = jnp.clip(thr, thr_min, thr_max)
    return ThresholdState(thr, jnp.full_like(thr, thr_min),
                          jnp.full_like(thr, thr_max))


def update_threshold(state: ThresholdState, count: jnp.ndarray, max_k,
                     delta: float = 0.15, thr_min: float = 1e-3,
                     thr_max: float = 2.0, track: float = 0.9
                     ) -> ThresholdState:
    """Temporal-coherence threshold controller: ONE bisection step per
    frame toward the reference's target band ``[K*(1-delta), K]``
    (VDIGenerator.comp:380-529 re-marches a full per-pixel binary search
    every frame; an in-situ loop can amortize that search across frames,
    because neither the simulation state nor the camera moves much between
    consecutive frames).

    ``count`` is the TRUE (uncapped) per-pixel segment count observed
    while writing with ``state.thr``. Over the cap → the threshold
    becomes the bracket's lower bound and bisects up; under the band → it
    becomes the upper bound and bisects down; in band → hold. A plain
    multiplicative controller oscillates forever on pixels whose count
    jumps across the band (lower → overflow → raise → under → lower …);
    the persistent bracket makes those pixels converge onto the knife
    edge. Asymmetry, on purpose: overflow is corrected immediately (it
    costs fidelity via the merge-overflow slot), while downward probes —
    pure fidelity *improvements* — only fire when the bracket allows a
    ≥25% step, so knife-edge pixels sit on the fitting side instead of
    dipping into overflow every other frame. Each frame the bracket
    decays outward by ``track`` (lo shrinking, hi growing) so a drifting
    scene re-opens the search window instead of being pinned by stale
    bounds.

    ``max_k`` may be a TRACED scalar (the occupancy-driven per-rank K
    budget, ops/occupancy.k_budget_target) — the floor keeps the static
    int path's band edges bit-identical (int() truncation == floor for
    positive K)."""
    over = count > max_k
    under = count < jnp.floor(max_k * (1.0 - delta))
    thr, lo, hi = state

    lo = jnp.where(over, thr, lo)
    hi = jnp.where(~over, jnp.minimum(hi, thr), hi)
    # a drifting scene can invert a decayed bracket; when it is, fall back
    # to a multiplicative step (over: ×1.5 up, under: ×0.75 down)
    up = 0.5 * (thr + jnp.where(hi > thr, hi, 2.0 * thr))
    dn = 0.5 * (thr + jnp.where(lo < thr, lo, 0.5 * thr))
    new = jnp.where(over, up,
                    jnp.where(under & (dn <= 0.75 * thr), dn, thr))
    new = jnp.clip(new, thr_min, thr_max)
    # bracket decay: keeps tracking ability; bounds steady-state wobble
    lo = jnp.maximum(jnp.float32(thr_min), lo * track)
    hi = jnp.minimum(jnp.float32(thr_max), hi / track)
    return ThresholdState(new, lo, hi)


def pick_threshold(counts: jnp.ndarray, tvec: jnp.ndarray, max_k: int
                   ) -> jnp.ndarray:
    """Smallest candidate whose count is <= max_k (counts are non-
    increasing in threshold). counts i32[B, H, W] -> thr f32[H, W]."""
    ok = counts <= max_k                                   # [B, H, W]
    # first True along B (guaranteed at the largest candidate by the
    # overflow-merge fallback; if even that fails, use the last candidate)
    idx = jnp.argmax(ok, axis=0)
    idx = jnp.where(jnp.any(ok, axis=0), idx, len(tvec) - 1)
    onehot = jax.lax.broadcasted_iota(
        jnp.int32, (len(tvec), 1, 1), 0) == idx[None]
    return jnp.sum(jnp.where(onehot, tvec[:, None, None], 0.0), axis=0)
