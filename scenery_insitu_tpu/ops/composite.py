"""Sort-last compositing kernels (SURVEY.md §7 step 4).

- ``composite_vdis``: merge N ranks' sub-VDIs for the same pixels into one
  composited VDI (≅ VDICompositor.comp). The reference does a sequential
  k-way merge with per-process front pointers (VDICompositor.comp:58-91);
  on TPU we instead flatten to N*K segments per pixel, sort by start depth
  (one vectorized ``jnp.sort`` — XLA lowers to a bitonic network, no
  divergence), and fold the sorted stream through the shared supersegment
  state machine for re-segmentation.
- ``merge_vdis_pairwise``: the ring-exchange counterpart (docs/PERF.md
  "Exchange modes"): two per-pixel depth-SORTED segment streams interleave
  by searchsorted-style rank selection — no bitonic sort, peak live state
  is the two streams instead of all N·K slots. The ring compositor
  (parallel.pipeline) folds one incoming K-fragment per ``ppermute`` hop
  into its accumulator with this, then re-segments the final stream
  through ``resegment_stream`` — the same backend dispatch + adaptive
  threshold + fold ``composite_vdis`` runs after its global sort, which is
  what makes lossless ring output exactly match the all_to_all path.
- ``composite_plain``: depth-ordered alpha-under of N plain images
  (≅ PlainImageCompositor.comp:35-92).
- ``composite_depth_min``: sort-first min-depth pick across ranks
  (≅ NaiveCompositor.frag / Head.composite, Head.kt:98-134).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import CompositeConfig
from scenery_insitu_tpu.core.vdi import VDI
from scenery_insitu_tpu.obs.profiler import phase as _profile_phase
from scenery_insitu_tpu.ops import supersegments as ss


def composite_vdis(colors: jnp.ndarray, depths: jnp.ndarray,
                   cfg: Optional[CompositeConfig] = None,
                   gap_eps: float = 1e-4,
                   assume_sorted: Optional[bool] = None) -> VDI:
    """colors f32[N, K, 4, H, W], depths f32[N, K, 2, H, W] -> VDI[K_out].

    Segments from different ranks are assumed depth-disjoint per pixel up to
    interpolation overlap at domain boundaries (the sort-last invariant the
    reference also relies on); overlapping segments are composited in
    start-depth order.

    ``assume_sorted``: skip the per-pixel depth sort + stale-color masking.
    Defaults to True for N == 1, whose single VDI comes out of generation
    already front-to-back ordered with zeroed empty slots.
    """
    cfg = cfg or CompositeConfig()
    n, k, _, h, w = colors.shape
    nk = n * k
    flat_c = colors.reshape(nk, 4, h, w)
    flat_d = depths.reshape(nk, 2, h, w)

    if assume_sorted is None:
        assume_sorted = (n == 1)
    if assume_sorted:
        sc, sd = flat_c, flat_d
    else:
        with _profile_phase("merge"):
            sc, sd = sort_stream(flat_c, flat_d)

    k_out = cfg.max_output_supersegments

    if (assume_sorted and n == 1 and k_out >= k and cfg.adaptive
            and cfg.backend == "auto"):
        # Single already-segmented ray with enough output slots: the input
        # is returned verbatim (padded to K_out). This intentionally
        # differs from the merge fold, whose adaptive search floor
        # (thr_max / 2^iters) re-merges segments whose RGB differs by up
        # to ~0.03 — pure information loss when everything already fits.
        # Identity is the DEFINED behavior for the default backend; an
        # explicit backend= request ("xla"/"pallas") still runs the real
        # fold so kernel parity checks and timings stay meaningful.
        pad = k_out - k
        color = jnp.concatenate(
            [flat_c, jnp.zeros((pad,) + flat_c.shape[1:], flat_c.dtype)]) \
            if pad else flat_c
        depth = jnp.concatenate(
            [flat_d, jnp.full((pad,) + flat_d.shape[1:], jnp.inf,
                              flat_d.dtype)]) if pad else flat_d
        return VDI(color, depth)

    with _profile_phase("resegment"):
        return resegment_stream(sc, sd, cfg, gap_eps)


def sort_stream(flat_c: jnp.ndarray, flat_d: jnp.ndarray):
    """Per-pixel depth sort + stale-color masking of a stacked segment
    stream — the pre-fold half of ``composite_vdis``, shared with the
    hierarchical composite (parallel/hier.py), whose intra-domain
    accumulator is exactly this sorted masked stream before the
    once-at-the-top re-segmentation.

    ``flat_c`` f32[M, 4, H, W], ``flat_d`` f32[M, 2, H, W] → the same
    shapes sorted by start depth per pixel (empty slots carry +inf start
    so they sort to the back) with non-live slots' colors zeroed (they
    may carry stale payloads)."""
    order = jnp.argsort(flat_d[:, 0], axis=0)              # [M, H, W]
    sc = jnp.take_along_axis(flat_c, order[:, None], axis=0)
    sd = jnp.take_along_axis(flat_d, order[:, None], axis=0)
    live = jnp.isfinite(sd[:, 0])
    return jnp.where(live[:, None], sc, 0.0), sd


def resegment_stream(sc: jnp.ndarray, sd: jnp.ndarray,
                     cfg: Optional[CompositeConfig] = None,
                     gap_eps: float = 1e-4) -> VDI:
    """Re-segment one per-pixel depth-SORTED segment stream into at most
    ``cfg.max_output_supersegments`` output supersegments.

    ``sc`` f32[M, 4, H, W] premultiplied colors, ``sd`` f32[M, 2, H, W]
    depth extents, sorted by start depth per pixel with empty slots masked
    (zero color, +inf depth). This is the post-sort half of
    ``composite_vdis`` — backend dispatch, adaptive threshold search and
    the supersegment fold — shared with the ring exchange path
    (parallel.pipeline), whose pairwise-merged accumulator arrives here
    already sorted. Identical streams produce identical output whichever
    path built them.
    """
    cfg = cfg or CompositeConfig()
    _, _, h, w = sc.shape
    k_out = cfg.max_output_supersegments

    backend = cfg.backend
    if backend == "auto":
        # auto is probe-gated like every other auto-picked Pallas
        # schedule (ADVICE r5 #4): a shape-dependent Mosaic rejection of
        # the fused resegment kernel must degrade to the XLA scan HERE
        # (the probe ledgers it as ops.composite_fold), not fire inside
        # a traced frame step. An explicit backend="pallas" stays
        # trusted-unprobed.
        if jax.default_backend() == "tpu":
            from scenery_insitu_tpu.ops.pallas_composite import \
                composite_compile_ok
            nk = sc.shape[0]
            backend = "pallas" if composite_compile_ok(
                nk, k_out, cfg.adaptive_iters if cfg.adaptive else 0) \
                else "xla"
        else:
            backend = "xla"

    if backend == "pallas":
        # fully fused: the adaptive threshold search runs inside the kernel
        from scenery_insitu_tpu.ops.pallas_composite import resegment_sorted
        color, depth = resegment_sorted(
            sc, sd, None, k_out, gap_eps,
            adaptive_iters=cfg.adaptive_iters if cfg.adaptive else 0)
        return VDI(color, depth)

    if cfg.adaptive:
        def count_fn(thr):
            def body(st, item):
                c, d = item
                return ss.push_count(st, thr, c, d[0], d[1], gap_eps), None
            st, _ = jax.lax.scan(body, ss.init_count(h, w), (sc, sd))
            return st.count
        threshold = ss.adaptive_threshold(count_fn, k_out,
                                          cfg.adaptive_iters, h, w)
    else:
        threshold = jnp.zeros((h, w), jnp.float32)

    def body(st, item):
        c, d = item
        return ss.push(st, k_out, threshold, c, d[0], d[1], gap_eps), None

    state, _ = jax.lax.scan(body, ss.init_state(k_out, h, w), (sc, sd))
    color, depth = ss.finalize(state)
    return VDI(color, depth)


def merge_vdis_pairwise(color_a: jnp.ndarray, depth_a: jnp.ndarray,
                        color_b: jnp.ndarray, depth_b: jnp.ndarray,
                        k_cap: Optional[int] = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pairwise ordered merge of two per-pixel depth-SORTED segment
    streams (the ring-exchange merge operator; docs/PERF.md "Exchange
    modes").

    ``color_a`` f32[Ka, 4, H, W] / ``depth_a`` f32[Ka, 2, H, W] and the
    ``b`` pair likewise. PRECONDITION: each stream is sorted by start
    depth per pixel (empty slots at +inf — the VDI convention; generation
    output and any previous merge's output both satisfy it) with empty
    slots' colors masked to zero. Unsorted inputs produce garbage — the
    position arithmetic below is only a permutation for sorted inputs.

    This is the sort-last depth-disjointness payoff: because the two
    lists are already ordered, the merged position of every segment is
    its own index plus how many of the OTHER list precede it — a
    searchsorted-style rank selection of O(Ka·Kb) vectorized compares per
    pixel, not an O(M log² M) bitonic network over the concatenation, and
    the only live state is the two input streams (2K slots for two
    K-lists vs the N·K slots the all_to_all sort materializes). Ties
    break toward stream ``a`` (the accumulator), keeping the merge
    deterministic. Payloads move by gather, so depth +inf survives
    bit-exactly (no one-hot arithmetic against inf).

    ``k_cap``: truncate the merged stream to its nearest ``k_cap``
    segments (drop the farthest) — the bounded-memory ring mode
    (CompositeConfig.ring_slots). None keeps all Ka+Kb slots.

    Returns the merged (color [M, 4, H, W], depth [M, 2, H, W]),
    M = min(Ka+Kb, k_cap or Ka+Kb), sorted with empties at the back.
    """
    ka, kb = color_a.shape[0], color_b.shape[0]
    sa, sb = depth_a[:, 0], depth_b[:, 0]                  # [K?, H, W]
    # merged position = own index + count of the other list before me;
    # b_j precedes a_i iff sb_j < sa_i (ties -> a first)
    b_before_a = jnp.sum((sb[None] < sa[:, None]).astype(jnp.int32), axis=1)
    a_before_b = jnp.sum((sa[None] <= sb[:, None]).astype(jnp.int32), axis=1)
    ia = jax.lax.broadcasted_iota(jnp.int32, (ka, 1, 1), 0)
    ib = jax.lax.broadcasted_iota(jnp.int32, (kb, 1, 1), 0)
    pos = jnp.concatenate([ia + b_before_a, ib + a_before_b], axis=0)
    m = ka + kb
    m_out = m if k_cap is None else min(int(k_cap), m)
    # invert the permutation by an O(M) scatter (pos is a permutation of
    # 0..M-1 per pixel for sorted inputs, so every update is in bounds),
    # then GATHER payloads — depth +inf must survive bit-exactly, so no
    # arithmetic ever touches the payload values. Truncation = dropping
    # the output slots past m_out (the farthest segments).
    in_ids = jnp.broadcast_to(
        jax.lax.broadcasted_iota(jnp.int32, (m, 1, 1), 0), pos.shape)
    inv = jnp.put_along_axis(jnp.zeros_like(pos), pos, in_ids, axis=0,
                             inplace=False)[:m_out]        # [M_out, H, W]
    all_c = jnp.concatenate([color_a, color_b], axis=0)
    all_d = jnp.concatenate([depth_a, depth_b], axis=0)
    color = jnp.take_along_axis(all_c, inv[:, None], axis=0)
    depth = jnp.take_along_axis(all_d, inv[:, None], axis=0)
    return color, depth


def modeled_exchange_traffic(n: int, k: int, height: int, width: int,
                             k_out: Optional[int] = None,
                             mode: str = "all_to_all", ring_slots: int = 0,
                             itemsize: int = 4,
                             wire: str = "f32",
                             schedule: str = "frame",
                             wave_tiles: int = 1) -> dict:
    """Modeled per-rank bytes of the sort-last exchange + composite for
    one frame — the composite counterpart of
    ``sim.pallas_stencil.modeled_sim_traffic`` (probe-free, usable
    off-TPU), consumed by ``benchmarks/composite_bench.py`` and the ring
    build's obs event.

    ``ici_bytes_per_rank`` is the wire traffic each rank ships (n-1
    K-fragments of its W/n column block — identical in both modes; the
    ring only changes WHEN it moves and what must be live meanwhile). It
    scales with the per-component ``wire`` itemsizes
    (``ops.wire.WIRE_SLOT_BYTES``): f32 24 B/slot, bf16 12, qpack8 6 —
    the model matches what the pipeline actually ships (qpack8's 8-byte
    per-fragment [near, far] sideband is scalar noise and excluded).
    ``peak_stream_slots_per_pixel`` is the per-pixel working set of the
    merge: the all_to_all path materializes and sorts all N·K received
    slots; the capped ring holds ring_slots + K (accumulator + incoming
    fragment, e.g. 2K at ring_slots=K); the lossless ring (ring_slots=0)
    grows back to N·K by the last hop. ``stream_bytes_per_rank`` is that
    working set PLUS the resegmented ``k_out``-slot output write, both in
    f32 ``itemsize`` — the composite always decodes to and folds in f32,
    so HBM stream bytes do not shrink with the wire.

    ``schedule="waves"`` (+ ``wave_tiles``; docs/PERF.md "Tile waves")
    adds the overlap accounting of the tile-wave pipeline: total wire
    bytes are unchanged (every fragment still crosses ICI once), but the
    exchange is issued per column-block wave and each wave's collective
    flies while the NEXT wave marches — so the bytes of waves 0..T-2 are
    hidden behind march compute and only the LAST wave's exchange (plus
    wave 0's march) stays exposed on the critical path:
    ``ici_bytes_hidden_per_rank = (T-1)/T`` of the total, and the
    per-pixel merge working set is unchanged (waves split columns, not
    slots).
    """
    from scenery_insitu_tpu.ops.wire import wire_slot_bytes

    wb = max(width // max(n, 1), 1)
    cb, db = wire_slot_bytes(wire)        # per-slot wire bytes (color, depth)
    seg = 6 * itemsize                    # 4 color + 2 depth f32 HBM lanes
    frag = k * height * wb * (cb + db)
    if mode == "ring" and ring_slots:
        slots = min(int(ring_slots), n * k) + k
    else:
        slots = n * k
    out = {
        "mode": mode, "ranks": n, "k": k,
        "k_out": k_out, "ring_slots": ring_slots,
        "wire": wire,
        "schedule": schedule,
        "wire_color_bytes_per_slot": cb,
        "wire_depth_bytes_per_slot": db,
        "ici_bytes_per_rank": (n - 1) * frag,
        "peak_stream_slots_per_pixel": slots,
        "stream_bytes_per_rank": (slots + (k_out or 0)) * height * wb * seg,
    }
    if schedule == "waves":
        t = max(int(wave_tiles), 1)
        # split the TOTAL so hidden + exposed always equals
        # ici_bytes_per_rank — a tiling the pipeline would reject
        # (wb % t != 0) still yields a self-consistent model, with the
        # remainder charged to the exposed (last) wave
        total = out["ici_bytes_per_rank"]
        per_wave = total // t
        hidden = (t - 1) * per_wave
        out["wave_tiles"] = t
        out["ici_bytes_per_wave_per_rank"] = per_wave
        # waves 0..T-2 circulate while wave 1..T-1 march; the last wave's
        # exchange has no next march to hide behind
        out["ici_bytes_hidden_per_rank"] = hidden
        out["ici_bytes_exposed_per_rank"] = total - hidden
        out["overlap_hidden_frac"] = round(hidden / total, 4) if total \
            else 0.0
    return out


def composite_plain(images: jnp.ndarray, depths: jnp.ndarray,
                    background: Tuple[float, ...] = (0, 0, 0, 0)
                    ) -> jnp.ndarray:
    """images f32[N, 4, H, W] premultiplied, depths f32[N, H, W] (+inf for
    empty pixels) -> composited f32[4, H, W] by per-pixel nearest-first
    alpha-under (≅ PlainImageCompositor.comp:35-92)."""
    order = jnp.argsort(depths, axis=0)                    # [N, H, W]
    sorted_imgs = jnp.take_along_axis(images, order[:, None], axis=0)

    def body(acc, src):
        return acc + (1.0 - acc[3:4]) * src, None

    acc, _ = jax.lax.scan(body, jnp.zeros_like(images[0]), sorted_imgs)
    bg = jnp.asarray(background, jnp.float32).reshape(4, 1, 1)
    return acc + (1.0 - acc[3:4]) * bg


def composite_depth_min(images: jnp.ndarray, depths: jnp.ndarray
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort-first composite: per pixel, take the rank whose fragment is
    nearest (≅ the head node's NaiveCompositor min-depth selection,
    NaiveCompositor.frag:15-28). Returns (image [4,H,W], depth [H,W])."""
    idx = jnp.argmin(depths, axis=0)                       # [H, W]
    img = jnp.take_along_axis(images, idx[None, None], axis=0)[0]
    d = jnp.take_along_axis(depths, idx[None], axis=0)[0]
    return img, d
