"""Sort-last compositing kernels (SURVEY.md §7 step 4).

- ``composite_vdis``: merge N ranks' sub-VDIs for the same pixels into one
  composited VDI (≅ VDICompositor.comp). The reference does a sequential
  k-way merge with per-process front pointers (VDICompositor.comp:58-91);
  on TPU we instead flatten to N*K segments per pixel, sort by start depth
  (one vectorized ``jnp.sort`` — XLA lowers to a bitonic network, no
  divergence), and fold the sorted stream through the shared supersegment
  state machine for re-segmentation.
- ``composite_plain``: depth-ordered alpha-under of N plain images
  (≅ PlainImageCompositor.comp:35-92).
- ``composite_depth_min``: sort-first min-depth pick across ranks
  (≅ NaiveCompositor.frag / Head.composite, Head.kt:98-134).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import CompositeConfig
from scenery_insitu_tpu.core.vdi import VDI
from scenery_insitu_tpu.ops import supersegments as ss


def composite_vdis(colors: jnp.ndarray, depths: jnp.ndarray,
                   cfg: Optional[CompositeConfig] = None,
                   gap_eps: float = 1e-4,
                   assume_sorted: Optional[bool] = None) -> VDI:
    """colors f32[N, K, 4, H, W], depths f32[N, K, 2, H, W] -> VDI[K_out].

    Segments from different ranks are assumed depth-disjoint per pixel up to
    interpolation overlap at domain boundaries (the sort-last invariant the
    reference also relies on); overlapping segments are composited in
    start-depth order.

    ``assume_sorted``: skip the per-pixel depth sort + stale-color masking.
    Defaults to True for N == 1, whose single VDI comes out of generation
    already front-to-back ordered with zeroed empty slots.
    """
    cfg = cfg or CompositeConfig()
    n, k, _, h, w = colors.shape
    nk = n * k
    flat_c = colors.reshape(nk, 4, h, w)
    flat_d = depths.reshape(nk, 2, h, w)

    if assume_sorted is None:
        assume_sorted = (n == 1)
    if assume_sorted:
        sc, sd = flat_c, flat_d
    else:
        # Empty slots carry +inf start so they sort to the back.
        order = jnp.argsort(flat_d[:, 0], axis=0)          # [NK, H, W]
        sc = jnp.take_along_axis(flat_c, order[:, None], axis=0)
        sd = jnp.take_along_axis(flat_d, order[:, None], axis=0)
        # Mask non-live slots to zero alpha (they may carry stale colors).
        live = jnp.isfinite(sd[:, 0])
        sc = jnp.where(live[:, None], sc, 0.0)

    k_out = cfg.max_output_supersegments

    if (assume_sorted and n == 1 and k_out >= k and cfg.adaptive
            and cfg.backend == "auto"):
        # Single already-segmented ray with enough output slots: the input
        # is returned verbatim (padded to K_out). This intentionally
        # differs from the merge fold, whose adaptive search floor
        # (thr_max / 2^iters) re-merges segments whose RGB differs by up
        # to ~0.03 — pure information loss when everything already fits.
        # Identity is the DEFINED behavior for the default backend; an
        # explicit backend= request ("xla"/"pallas") still runs the real
        # fold so kernel parity checks and timings stay meaningful.
        pad = k_out - k
        color = jnp.concatenate(
            [flat_c, jnp.zeros((pad,) + flat_c.shape[1:], flat_c.dtype)]) \
            if pad else flat_c
        depth = jnp.concatenate(
            [flat_d, jnp.full((pad,) + flat_d.shape[1:], jnp.inf,
                              flat_d.dtype)]) if pad else flat_d
        return VDI(color, depth)

    backend = cfg.backend
    if backend == "auto":
        backend = "pallas" if jax.default_backend() == "tpu" else "xla"

    if backend == "pallas":
        # fully fused: the adaptive threshold search runs inside the kernel
        from scenery_insitu_tpu.ops.pallas_composite import resegment_sorted
        color, depth = resegment_sorted(
            sc, sd, None, k_out, gap_eps,
            adaptive_iters=cfg.adaptive_iters if cfg.adaptive else 0)
        return VDI(color, depth)

    if cfg.adaptive:
        def count_fn(thr):
            def body(st, item):
                c, d = item
                return ss.push_count(st, thr, c, d[0], d[1], gap_eps), None
            st, _ = jax.lax.scan(body, ss.init_count(h, w), (sc, sd))
            return st.count
        threshold = ss.adaptive_threshold(count_fn, k_out,
                                          cfg.adaptive_iters, h, w)
    else:
        threshold = jnp.zeros((h, w), jnp.float32)

    def body(st, item):
        c, d = item
        return ss.push(st, k_out, threshold, c, d[0], d[1], gap_eps), None

    state, _ = jax.lax.scan(body, ss.init_state(k_out, h, w), (sc, sd))
    color, depth = ss.finalize(state)
    return VDI(color, depth)


def composite_plain(images: jnp.ndarray, depths: jnp.ndarray,
                    background: Tuple[float, ...] = (0, 0, 0, 0)
                    ) -> jnp.ndarray:
    """images f32[N, 4, H, W] premultiplied, depths f32[N, H, W] (+inf for
    empty pixels) -> composited f32[4, H, W] by per-pixel nearest-first
    alpha-under (≅ PlainImageCompositor.comp:35-92)."""
    order = jnp.argsort(depths, axis=0)                    # [N, H, W]
    sorted_imgs = jnp.take_along_axis(images, order[:, None], axis=0)

    def body(acc, src):
        return acc + (1.0 - acc[3:4]) * src, None

    acc, _ = jax.lax.scan(body, jnp.zeros_like(images[0]), sorted_imgs)
    bg = jnp.asarray(background, jnp.float32).reshape(4, 1, 1)
    return acc + (1.0 - acc[3:4]) * bg


def composite_depth_min(images: jnp.ndarray, depths: jnp.ndarray
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sort-first composite: per pixel, take the rank whose fragment is
    nearest (≅ the head node's NaiveCompositor min-depth selection,
    NaiveCompositor.frag:15-28). Returns (image [4,H,W], depth [H,W])."""
    idx = jnp.argmin(depths, axis=0)                       # [H, W]
    img = jnp.take_along_axis(images, idx[None, None], axis=0)[0]
    d = jnp.take_along_axis(depths, idx[None], axis=0)[0]
    return img, d
