"""MXU-native novel-view VDI rendering — the TPU-fast streamed-VDI client
(≅ EfficientVDIRaycast.comp, the reference's 848-line novel-view renderer:
per output pixel it marches the original camera's frustum grid, binary-
searches each crossed pixel-list and intersects supersegments exactly,
EfficientVDIRaycast.comp:110-141,173-190,274-450).

The portable equivalent here (ops.vdi_render.render_vdi) re-imports the
per-step gather pattern — the exact access pattern ops/slicer.py exists to
avoid. This module re-derives novel-view VDI rendering as banded matmuls,
exploiting a structural property of slice-march VDIs: their generating
camera is a *virtual axis-aligned camera*, so

1. the set of samples at original depth-ratio ``s`` lies on the world
   plane ``w = const`` (the original march's own slice plane), and
2. that plane carries a UNIFORM pixel grid — the original intermediate
   grid scaled about the original eye by ``s``.

So a VDI slice at depth s is an ordinary image (decoded from the per-pixel
slab lists with an elementwise masked reduction over K — no gathers), its
world footprint is a scale+shift of a uniform grid, and resampling it onto
a new camera's ray bundle at the same plane is the SAME separable banded-
matmul machinery the forward march uses. Novel-view rendering = march the
original slice planes in the new camera's front-to-back order, resample
each decoded slice, alpha-under accumulate, homography-warp to the display
camera. The march is gather-free end to end.

Validity: the new camera must march the same volume axis as the VDI's
generating camera (``slicer.choose_axis(new_cam)[0] == spec.axis``) — the
same per-regime constraint the forward engine has. Either sign works (the
plane stack is composited in the new camera's order). Opacity is corrected
per-pixel by the ratio of the new ray's inter-plane path length to the
original one (both resampled alongside the color planes), the same
traversed-fraction law the rest of the framework uses.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.core.camera import Camera
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.ops import slicer
from scenery_insitu_tpu.ops.sampling import adjust_opacity
from scenery_insitu_tpu.ops.slicer import (AxisCamera, AxisSpec,
                                           _interp_matrix, make_axis_camera,
                                           warp_to_camera)


def axis_spec_from_meta(meta: VDIMetadata, chunk: int = 16,
                        matmul_dtype: str = "bf16") -> AxisSpec:
    """Reconstruct the static AxisSpec of a slice-march VDI from metadata
    alone: the virtual camera's forward axis is a volume axis by
    construction (view row 2 = -forward), and the grid size is the window
    dims — so a streamed-VDI client needs nothing beyond the wire data."""
    import numpy as np

    fwd = -np.asarray(meta.view)[2, :3]
    axis = int(np.argmax(np.abs(fwd)))
    sign = 1 if fwd[axis] >= 0 else -1
    return AxisSpec(axis=axis, sign=sign,
                    ni=int(meta.window_dims[0]), nj=int(meta.window_dims[1]),
                    chunk=chunk, matmul_dtype=matmul_dtype)


def axis_camera_from_meta(meta: VDIMetadata, spec: AxisSpec) -> AxisCamera:
    """Reconstruct the generating virtual axis camera of a slice-march VDI
    from its metadata (for stored/streamed VDIs whose AxisCamera wasn't
    shipped; ≅ the reference hardcoding original-camera matrices into
    EfficientVDIRaycast.comp:584-606).

    The slice pitch comes from ``meta.model``'s diagonal (the voxel->world
    affine the generator stores); only ``w0`` is approximate when the eye
    sat inside the volume along the march axis (make_axis_camera clamps zp
    to one voxel there, and the clamp is not recoverable from metadata)."""
    view = meta.view
    proj = meta.projection
    rot = view[:3, :3]
    eye = -rot.T @ view[:3, 3]
    a, ua, va = spec.axis, spec.u_axis, spec.v_axis

    # standard frustum: proj[0,0]=2n/(r-l), proj[0,2]=(r+l)/(r-l), ...
    zp = proj[2, 3] / (proj[2, 2] - 1.0)                   # = near
    rl = 2.0 * zp / proj[0, 0]                             # r - l
    tb = 2.0 * zp / proj[1, 1]                             # t - b
    rpl = proj[0, 2] * rl                                  # r + l
    tpb = proj[1, 2] * tb                                  # t + b

    ni, nj = spec.ni, spec.nj
    # virtual basis: fwd = sign * axis; right/up from the same cross
    # products make_axis_camera uses
    import numpy as np
    fwd = np.zeros(3, np.float32)
    fwd[a] = spec.sign
    up = np.zeros(3, np.float32)
    up[va] = 1.0
    right = np.cross(fwd, up)
    true_up = np.cross(right, fwd)
    right_u = float(right[ua])
    up_v = float(true_up[va])

    ndc_x = (jnp.arange(ni, dtype=jnp.float32) + 0.5) / ni * 2 - 1
    ndc_y = 1.0 - (jnp.arange(nj, dtype=jnp.float32) + 0.5) / nj * 2
    u_grid = eye[ua] + (ndc_x * rl + rpl) * 0.5 * right_u
    v_grid = eye[va] + (ndc_y * tb + tpb) * 0.5 * up_v

    # per-axis pitch from the voxel->world model; identity model = legacy
    # metadata without placement, fall back to nw (exact for cubic voxels)
    legacy = jnp.all(jnp.abs(meta.model - jnp.eye(4)) < 1e-12)
    dw = jnp.where(legacy, meta.nw, meta.model[a, a])
    w0 = eye[a] + jnp.float32(spec.sign) * zp
    far = proj[2, 3] / (proj[2, 2] + 1.0)
    return AxisCamera(
        eye_uvw=jnp.stack([eye[ua], eye[va], eye[a]]),
        view=view, proj=proj, u_grid=u_grid, v_grid=v_grid,
        zp=zp, w0=w0, dwm=jnp.float32(spec.sign) * dw, far=far)


def decode_slice(vdi: VDI, t: jnp.ndarray, dt_ref: jnp.ndarray
                 ) -> jnp.ndarray:
    """Decode the VDI at per-pixel depths ``t [C, Nj, Ni]`` into per-step
    source planes ``[C, 5, Nj, Ni]``: premultiplied step rgb (3), step
    alpha for traversing ``dt_ref`` (1), and dt_ref itself (1) so the
    consumer can re-correct opacity for ITS path length after resampling.
    Elementwise masked reduction over the K slabs — no gathers."""
    starts = vdi.depth[:, 0]                               # [K, Nj, Ni]
    ends = vdi.depth[:, 1]
    inside = (t[:, None] >= starts[None]) & (t[:, None] < ends[None])
    insf = inside.astype(jnp.float32)                      # [C, K, Nj, Ni]
    rgba = jnp.einsum("ckji,kdji->cdji", insf, vdi.color)  # [C, 4, Nj, Ni]
    length = jnp.einsum("ckji,kji->cji", insf,
                        jnp.where(jnp.isfinite(ends - starts),
                                  ends - starts, 0.0))
    a_slab = jnp.clip(rgba[:, 3], 0.0, 1.0 - 1e-6)
    frac = dt_ref / jnp.maximum(length, 1e-6)
    a_step = adjust_opacity(a_slab, jnp.minimum(frac, 1.0))
    a_step = jnp.where(length > 0.0, a_step, 0.0)
    scale = a_step / jnp.maximum(a_slab, 1e-6)
    rgb_step = rgba[:, :3] * scale[:, None]
    return jnp.concatenate([rgb_step, a_step[:, None], dt_ref[:, None]],
                           axis=1)


def _default_slices(ni0: int) -> int:
    """Static plane-count heuristic when the generating volume's true
    slice count is unknown: intermediate grids are sized ~1.25× the
    in-plane voxel count and volumes are roughly cubic."""
    return max(16, int(round(ni0 / 1.25)))


def _content_aabb(vdi: VDI, axcam0: AxisCamera, s_count: int):
    """In-plane world extent of the marched frustum content over the VDI's
    actual depth range (traced; shared by the plane-sweep renderer's new
    grid and the proxy volume's target grid). Returns
    (u_lo, u_hi, v_lo, v_hi, smax)."""
    eu0, ev0 = axcam0.eye_u, axcam0.eye_v
    length0 = axcam0.ray_lengths()
    ds0 = jnp.abs(axcam0.dwm) / axcam0.zp
    ends = vdi.depth[:, 1]
    s_of_end = jnp.where(jnp.isfinite(ends), ends, 0.0) / length0[None]
    smax = jnp.clip(jnp.max(s_of_end), 1.0, 1.0 + ds0 * s_count)
    u_vals = jnp.stack([axcam0.u_grid[0], axcam0.u_grid[-1],
                        eu0 + (axcam0.u_grid[0] - eu0) * smax,
                        eu0 + (axcam0.u_grid[-1] - eu0) * smax])
    v_vals = jnp.stack([axcam0.v_grid[0], axcam0.v_grid[-1],
                        ev0 + (axcam0.v_grid[0] - ev0) * smax,
                        ev0 + (axcam0.v_grid[-1] - ev0) * smax])
    return (jnp.min(u_vals), jnp.max(u_vals),
            jnp.min(v_vals), jnp.max(v_vals), smax)


def _resample_planes(vdi: VDI, axcam0: AxisCamera, s0: jnp.ndarray,
                     dt_ref: jnp.ndarray, pos_u: jnp.ndarray,
                     pos_v: jnp.ndarray, mm) -> jnp.ndarray:
    """Shared per-plane kernel of both novel-view consumers: decode the
    VDI on original planes at depth ratios ``s0 [C]`` (per-step alpha for
    ``dt_ref``) and resample the decoded channels from each plane's
    uniform perspective grid (the original grid scaled about the eye by
    s0) onto per-plane sample positions ``pos_u [C, M] / pos_v [C, N]``.
    Returns ``[C, 5, N, M]`` (rgb, alpha, dt_ref)."""
    _, _, nj0, ni0 = vdi.color.shape
    length0 = axcam0.ray_lengths()
    t_at = s0[:, None, None] * length0[None]
    src = decode_slice(vdi, t_at, jnp.broadcast_to(dt_ref, t_at.shape))

    eu0, ev0 = axcam0.eye_u, axcam0.eye_v
    du0 = axcam0.u_grid[1] - axcam0.u_grid[0]
    dv0 = axcam0.v_grid[1] - axcam0.v_grid[0]
    su_org = eu0 + (axcam0.u_grid[0] - eu0) * s0           # [C]
    su_sp = du0 * s0
    sv_org = ev0 + (axcam0.v_grid[0] - ev0) * s0
    sv_sp = dv0 * s0
    wu = _interp_matrix(pos_u, su_org, su_sp, ni0)         # [C, M, Ni0]
    wv = _interp_matrix(pos_v, sv_org, sv_sp, nj0)         # [C, N, Nj0]
    return jnp.einsum("cjy,cdyx,cix->cdji",
                      wv.astype(mm), src.astype(mm), wu.astype(mm),
                      preferred_element_type=jnp.float32)


def vdi_to_rgba_volume(vdi: VDI, axcam0: AxisCamera, spec0: AxisSpec,
                       num_slices: Optional[int] = None):
    """Expand a slice-march VDI into an axis-aligned pre-shaded RGBA proxy
    volume (``Volume`` with data f32[4, D, H, W], premultiplied, alpha
    encoded per ``nominal_step``) — gather-free: each original slice plane
    is decoded (masked reduction over K) and resampled from its uniform
    perspective grid onto a regular world grid with the same banded-matmul
    machinery as the forward march (the plane's depth ratio is constant,
    so the frustum→AABB warp is separable per plane).

    This is the bridge to CROSS-REGIME novel views: the proxy renders
    through the ordinary slice march along ANY axis (`render_vdi_any`),
    where the same-axis plane sweep (`render_vdi_mxu`) cannot order the
    planes front-to-back. Resolution follows the VDI's own grid (in-plane)
    and the original march's plane count (depth): the proxy adds one
    bilinear resample of loss on top of the VDI's own quantization.
    """
    from scenery_insitu_tpu.core.volume import Volume

    k, _, nj0, ni0 = vdi.color.shape
    if num_slices is None:
        num_slices = _default_slices(ni0)
    s_count = num_slices
    a, ua, va = spec0.axis, spec0.u_axis, spec0.v_axis

    ew0 = axcam0.eye_w

    # world AABB of the marched frustum content: in-plane extent at the
    # deepest live depth ratio (shared with render_vdi_mxu)
    u_lo, u_hi, v_lo, v_hi, _ = _content_aabb(vdi, axcam0, s_count)

    nu_t, nv_t = ni0, nj0                                  # static
    sp_u = (u_hi - u_lo) / nu_t
    sp_v = (v_hi - v_lo) / nv_t
    dw = jnp.abs(axcam0.dwm)
    # ascending-world target grids (Volume layout wants min-corner origin)
    tu = u_lo + (jnp.arange(nu_t, dtype=jnp.float32) + 0.5) * sp_u
    tv = v_lo + (jnp.arange(nv_t, dtype=jnp.float32) + 0.5) * sp_v
    nominal = jnp.minimum(jnp.minimum(sp_u, sp_v), dw)

    c = spec0.chunk
    nchunks = -(-s_count // c)

    mm = jnp.bfloat16 if spec0.matmul_dtype == "bf16" else jnp.float32

    def body(_, ci):
        q = ci * c + jnp.arange(c, dtype=jnp.float32)      # march order
        wq = axcam0.w0 + q * axcam0.dwm                    # [C] plane w
        s0 = jnp.float32(spec0.sign) * (wq - ew0) / axcam0.zp
        live = (q < s_count) & (s0 > spec0.s_floor)
        # dead planes are zeroed below, but their arithmetic must stay
        # finite (s0 == 0 would put NaNs through the interp weights)
        s0 = jnp.where(live, s0, 1.0)
        plane = _resample_planes(
            vdi, axcam0, s0, nominal,
            jnp.broadcast_to(tu, (c, nu_t)),
            jnp.broadcast_to(tv, (c, nv_t)), mm)[:, :4]    # drop dt chan
        plane = plane * live[:, None, None, None].astype(jnp.float32)
        return None, plane

    _, planes = jax.lax.scan(body, None, jnp.arange(nchunks))
    stack = planes.reshape(nchunks * c, 4, nv_t, nu_t)[:s_count]

    # march order ascends w only for sign>0; Volume wants ascending w
    if spec0.sign < 0:
        stack = jnp.flip(stack, axis=0)
        w_min = axcam0.w0 + (s_count - 1) * axcam0.dwm
    else:
        w_min = axcam0.w0

    data = jnp.moveaxis(stack, 1, 0)                       # [4, w, v, u]
    # arrange (w, v, u) -> (z, y, x) for the volume layout
    if a == 2:                                             # w=z, v=y, u=x
        pass
    elif a == 1:                                           # w=y, v=z, u=x
        data = jnp.transpose(data, (0, 2, 1, 3))
    else:                                                  # w=x, v=z, u=y
        data = jnp.transpose(data, (0, 2, 3, 1))
    origin = jnp.zeros(3).at[ua].set(u_lo).at[va].set(v_lo) \
        .at[a].set(w_min - 0.5 * dw)
    spacing = jnp.zeros(3).at[ua].set(sp_u).at[va].set(sp_v).at[a].set(dw)
    return Volume(data, origin, spacing)


def render_vdi_any(vdi: VDI, axcam0: AxisCamera, spec0: AxisSpec,
                   cam: Camera, width: int, height: int,
                   num_slices: Optional[int] = None,
                   background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0),
                   axis_sign: Optional[Tuple[int, int]] = None,
                   slicer_cfg=None, proxy=None,
                   exact: bool = False) -> jnp.ndarray:
    """Gather-free novel-view rendering from ANY camera: same-regime views
    use the direct plane sweep (`render_vdi_mxu`); cross-regime views
    expand the VDI into the pre-shaded proxy volume and slice-march it
    along the new camera's own axis (≅ EfficientVDIRaycast.comp's
    arbitrary-view capability, re-derived as two matmul passes instead of
    per-pixel binary searches).

    ``proxy``: prebuilt `vdi_to_rgba_volume` result — the proxy depends
    only on the VDI, so a client rendering several views of one received
    VDI should build it once and pass it here instead of paying the
    expansion per view.

    ``exact=True`` routes to `render_vdi_exact` (closed-form in-slab path
    lengths, any regime, no resampling error) — the quality reference;
    the proxy path's deviation from it is quantified per view angle in
    docs/NOVEL_VIEW.md."""
    if exact:
        return render_vdi_exact(vdi, axcam0, spec0, cam, width, height,
                                background=background)
    new_axis, new_sign = axis_sign or slicer.choose_axis(cam)
    if new_axis == spec0.axis:
        return render_vdi_mxu(vdi, axcam0, spec0, cam, width, height,
                              num_slices=num_slices, background=background,
                              axis_sign=(new_axis, new_sign))
    if proxy is None:
        proxy = vdi_to_rgba_volume(vdi, axcam0, spec0,
                                   num_slices=num_slices)
    from scenery_insitu_tpu.config import SliceMarchConfig
    cfg = slicer_cfg or SliceMarchConfig(matmul_dtype=spec0.matmul_dtype)
    spec_new = slicer.make_spec(cam, proxy.data.shape[-3:], cfg,
                                axis_sign=(new_axis, new_sign))
    return render_vdi_proxy(proxy, cam, width, height, spec_new,
                            background=background)


def render_vdi_proxy(proxy, cam: Camera, width: int, height: int,
                     spec_new: AxisSpec,
                     background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0)
                     ) -> jnp.ndarray:
    """March a prebuilt `vdi_to_rgba_volume` proxy from one camera ->
    f32[4, H, W] premultiplied — the per-view half of the proxy path
    (`render_vdi_any` builds + marches in one call; the serving tier
    builds the proxy ONCE per VDI frame and marches it per viewer, so the
    split is the amortization seam). ``spec_new`` must be the static spec
    of the proxy's grid for the camera's march regime — required
    explicitly because ``cam`` may be traced (the batched path maps over
    cameras inside one compiled program)."""
    out = slicer.raycast_mxu(proxy, None, cam, width, height, spec_new,
                             background=background)
    return out.image


def stack_cameras(cams: Sequence[Camera]) -> Camera:
    """Stack N cameras into one batched Camera pytree (every leaf gains a
    leading [N] axis) — the input shape of `render_vdi_batch`."""
    cams = list(cams)
    if not cams:
        raise ValueError("stack_cameras needs at least one camera")
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *cams)


def render_vdi_batch(vdi: Optional[VDI], axcam0: Optional[AxisCamera],
                     spec0: AxisSpec, cams: Camera, width: int, height: int,
                     *, tier: str = "proxy",
                     num_slices: Optional[int] = None,
                     axis_sign: Optional[Tuple[int, int]] = None,
                     proxy=None, spec_new: Optional[AxisSpec] = None,
                     slicer_cfg=None,
                     background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0)
                     ) -> jnp.ndarray:
    """Batched novel-view rendering: N cameras (one stacked Camera pytree,
    `stack_cameras`) against ONE VDI in ONE compiled dispatch ->
    f32[N, 4, H, W]. The edge-serving tier's core op (docs/SERVING.md):
    the VDI fetch, the slab decode and (on the proxy tier) the whole
    pre-shaded proxy expansion are paid once per frame and amortized
    across every viewer in the batch.

    The batch axis runs under ``jax.lax.map`` (sweep/proxy tiers) — a
    scan whose body is the UNMODIFIED single-camera renderer — rather
    than ``jax.vmap``: batched matmul shapes change XLA's
    contraction/fusion choices, so a vmapped batch drifts ~1e-5 from the
    independent single calls, while the scanned body is the same program
    element-for-element. The exact tier unrolls the batch instead
    (stacked copies of the single-camera graph inside one program):
    under lax.map its camera-independent slab sort is hoisted out of the
    loop with a different fusion and drifts ~2e-6 — the unroll keeps
    each element the literal single-camera graph, at a compile cost
    bounded by the serve bucket ladder. Contract (tests pin all three):
    each batch element is BITWISE equal to the independent
    `render_vdi_exact` / `render_vdi_mxu` / `render_vdi_proxy` call,
    elements are independent of what else shares the batch, and padding
    a batch to a larger bucket leaves the real entries bit-unchanged.

    Tiers (the serving quality ladder):

    - ``"exact"``   `render_vdi_exact` per camera — any regime, the
                    quality reference; every stage is per-camera, so the
                    batch amortizes only the dispatch + VDI fetch.
    - ``"sweep"``   `render_vdi_mxu` per camera — the same-regime direct
                    plane sweep (``axis_sign`` REQUIRED and shared by the
                    whole batch; cameras are traced inside the scan).
                    The per-plane decode is camera-independent and
                    hoisted out of the scan by XLA.
    - ``"proxy"``   `render_vdi_proxy` per camera over one shared
                    `vdi_to_rgba_volume` expansion (prebuilt ``proxy``
                    or built here) — ANY regime per bucket via
                    ``spec_new``/``axis_sign``, and the strongest
                    amortization: the expansion (decode + resample of
                    every plane) is outside the scan entirely. With
                    ``proxy`` and ``spec_new`` given, ``vdi``/``axcam0``
                    may be None (the serving loop holds the proxy, not
                    the VDI).
    """
    if tier == "exact":
        b = jax.tree_util.tree_leaves(cams)[0].shape[0]
        return jnp.stack([
            render_vdi_exact(
                vdi, axcam0, spec0,
                jax.tree_util.tree_map(lambda x: x[i], cams),
                width, height, background=background)
            for i in range(b)])
    if tier == "sweep":
        if axis_sign is None:
            raise ValueError(
                "tier='sweep' needs the batch's shared axis_sign regime "
                "(cameras are traced inside the scan, so choose_axis "
                "cannot run per element)")
        return jax.lax.map(
            lambda c: render_vdi_mxu(vdi, axcam0, spec0, c, width, height,
                                     num_slices=num_slices,
                                     background=background,
                                     axis_sign=axis_sign),
            cams)
    if tier != "proxy":
        raise ValueError(f"unknown tier {tier!r} "
                         "(expected 'exact', 'sweep' or 'proxy')")
    if proxy is None:
        if vdi is None or axcam0 is None:
            raise ValueError("tier='proxy' needs either a prebuilt proxy "
                             "or the (vdi, axcam0) pair to build one")
        proxy = vdi_to_rgba_volume(vdi, axcam0, spec0,
                                   num_slices=num_slices)
    if spec_new is None:
        if axis_sign is None:
            raise ValueError(
                "tier='proxy' needs spec_new or the batch's shared "
                "axis_sign regime to derive it")
        from scenery_insitu_tpu.config import SliceMarchConfig
        cfg = slicer_cfg or SliceMarchConfig(matmul_dtype=spec0.matmul_dtype)
        cam0 = jax.tree_util.tree_map(lambda x: x[0], cams)
        spec_new = slicer.make_spec(cam0, proxy.data.shape[-3:], cfg,
                                    axis_sign=axis_sign)
    return jax.lax.map(
        lambda c: render_vdi_proxy(proxy, c, width, height, spec_new,
                                   background=background),
        cams)


def render_vdi_exact(vdi: VDI, axcam0: AxisCamera, spec0: AxisSpec,
                     cam: Camera, width: int, height: int,
                     background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0),
                     s_cap: Optional[float] = None, frac_cap: float = 16.0
                     ) -> jnp.ndarray:
    """EXACT arbitrary-view rendering of a slice-march VDI -> f32[4, H, W]
    premultiplied — per-ray in-slab path lengths computed in closed form,
    any view regime (≅ intersectSupersegment + the frustum-cell walk,
    EfficientVDIRaycast.comp:110-141,173-190,274-450; the reference walks
    cells sequentially per pixel with binary searches, this derivation
    vectorizes the same geometry).

    Exactness argument: along a straight output ray, the generating
    virtual axis camera's pixel coordinates are PROJECTIVE-LINEAR in the
    ray parameter t (u_ref(t) = eu0 + (pos_u(t)-eu0)/s(t), both parts
    linear in t), so every crossing of an original pixel-cell edge has a
    closed form and the crossed cells form a monotone staircase with at
    most Ni0+Nj0+2 boundaries. Between consecutive boundaries the pixel
    (hence its K slabs AND its reference ray length) is constant and the
    VDI depth coordinate r(t) = s(t)·len0[pixel] is LINEAR in t, so each
    slab's traversed world length is an exact interval overlap — no
    sampling anywhere. Per event-interval, the ≤K disjoint slabs are
    alpha-under composed in traversal order (ascending or descending r);
    across intervals the sort order of t gives front-to-back directly in
    the OUTPUT camera's pixel space (no intermediate grid, no warp).

    Cost and memory scale with H·W·E where E = Ni0+Nj0+4 (the event
    arrays and a handful of per-interval temporaries; the K loop holds
    one slab's gather at a time) — a client-side op; jit it per view and
    chunk rows outside jit for very large frames.

    ``s_cap`` bounds the marched depth-ratio range; the default derives
    it from the VDI's own deepest finite slab end (eye-inside-volume
    generations legitimately reach depth ratios ~ the axis voxel count,
    so a fixed cap would truncate them). ``frac_cap`` caps the
    path/thickness ratio fed to the opacity law (matches the plane
    sweep's clip).
    """
    from scenery_insitu_tpu.core.camera import pixel_rays

    k, _, nj0, ni0 = vdi.color.shape
    a, ua, va = spec0.axis, spec0.u_axis, spec0.v_axis
    eu0, ev0, ew0 = axcam0.eye_u, axcam0.eye_v, axcam0.eye_w
    du0 = axcam0.u_grid[1] - axcam0.u_grid[0]
    dv0 = axcam0.v_grid[1] - axcam0.v_grid[0]
    len0 = axcam0.ray_lengths()                             # [Nj0, Ni0]

    # slabs sorted by start depth per pixel (the folds emit in march
    # order, composites in sorted order — sort defensively, it's cheap
    # and the within-interval composition relies on it)
    starts0 = vdi.depth[:, 0]
    order = jnp.argsort(jnp.where(jnp.isfinite(starts0), starts0, jnp.inf),
                        axis=0)
    starts = jnp.take_along_axis(starts0, order, axis=0)
    ends = jnp.take_along_axis(vdi.depth[:, 1], order, axis=0)
    colors = jnp.take_along_axis(vdi.color, order[:, None], axis=0)
    flat_s = starts.reshape(k, nj0 * ni0)
    flat_e = ends.reshape(k, nj0 * ni0)
    flat_c = colors.reshape(k, 4, nj0 * ni0)
    flat_len = len0.reshape(nj0 * ni0)

    origin, dirs = pixel_rays(cam, width, height)           # [3], [3,H,W]
    o_u, o_v, o_w = origin[ua], origin[va], origin[a]
    d_u, d_v, d_w = dirs[ua], dirs[va], dirs[a]             # [H, W]

    sgn = jnp.float32(spec0.sign)
    s_A = sgn * (o_w - ew0) / axcam0.zp                     # s(t) = A + B t
    s_B = sgn * d_w / axcam0.zp                             # [H, W]

    eps = jnp.float32(1e-12)

    # depth-ratio cap: the VDI's own deepest finite slab end (+ one
    # slice of slack) unless overridden — eye-inside-volume generations
    # legitimately reach s ~ the axis voxel count
    if s_cap is None:
        ends_all = vdi.depth[:, 1]
        s_cap = jnp.maximum(jnp.max(jnp.where(
            jnp.isfinite(ends_all), ends_all, 0.0) / len0[None]),
            1.0) * 1.001 + jnp.abs(axcam0.dwm) / axcam0.zp
    s_cap = jnp.float32(s_cap)

    def edge_crossings(o_c, d_c, e0, grid0, dg, count):
        """t of each original-grid cell-edge crossing (inf = no
        crossing): solve (o_c + t·d_c - e0) = (edge - e0)·s(t)."""
        edges = grid0[0] + (jnp.arange(count + 1, dtype=jnp.float32) - 0.5) \
            * dg - e0                                       # [M]
        u_a = (o_c - e0)[..., None]                         # [H, W, 1]
        u_b = d_c[..., None]
        den = u_b - edges * s_B[..., None]
        t = (edges * s_A[..., None] - u_a) / jnp.where(
            jnp.abs(den) < eps, eps, den)
        return jnp.where(jnp.abs(den) < eps, jnp.inf, t)

    def s_crossing(s_val):
        """t where the depth ratio reaches s_val (inf for in-plane
        rays, s_B == 0)."""
        den = jnp.where(jnp.abs(s_B) < eps, eps, s_B)
        t = (s_val - s_A) / den
        return jnp.where(jnp.abs(s_B) < eps, jnp.inf, t)[..., None]

    raw = jnp.concatenate(
        [edge_crossings(o_u, d_u, eu0, axcam0.u_grid, du0, ni0),
         edge_crossings(o_v, d_v, ev0, axcam0.v_grid, dv0, nj0),
         s_crossing(jnp.float32(spec0.s_floor)),
         s_crossing(s_cap)], axis=-1)                       # [H, W, E-1]
    # scale-free sentinel: the largest real forward crossing of THIS ray
    # (+ margin); invalid/backward events collapse onto it as zero-width
    # intervals, so no fixed world-scale cap can truncate content
    fwd = jnp.isfinite(raw) & (raw >= 0.0)
    t_hi = jnp.max(jnp.where(fwd, raw, 0.0), axis=-1,
                   keepdims=True) + 1.0                     # [H, W, 1]
    events = jnp.clip(jnp.where(fwd, raw, t_hi), 0.0, t_hi)
    events = jnp.concatenate(
        [events, jnp.zeros(d_w.shape + (1,), jnp.float32)], axis=-1)
    events = jnp.sort(events, axis=-1)
    t_a = events[..., :-1]                                  # [H, W, E-1]
    t_b = events[..., 1:]
    t_mid = 0.5 * (t_a + t_b)

    # constant cell data per interval (from the midpoint)
    s_mid = s_A[..., None] + s_B[..., None] * t_mid
    s_safe = jnp.where(jnp.abs(s_mid) < eps, eps, s_mid)
    u_ref = eu0 + (o_u[..., None] + t_mid * d_u[..., None] - eu0) / s_safe
    v_ref = ev0 + (o_v[..., None] + t_mid * d_v[..., None] - ev0) / s_safe
    fx = (u_ref - (axcam0.u_grid[0] - 0.5 * du0)) / du0
    fy = (v_ref - (axcam0.v_grid[0] - 0.5 * dv0)) / dv0
    ix = jnp.floor(fx).astype(jnp.int32)
    iy = jnp.floor(fy).astype(jnp.int32)
    valid = ((ix >= 0) & (ix < ni0) & (iy >= 0) & (iy < nj0)
             & (s_mid > spec0.s_floor) & (s_mid < s_cap)
             & (t_b > t_a))
    lin = (jnp.clip(iy, 0, nj0 - 1) * ni0
           + jnp.clip(ix, 0, ni0 - 1))                      # [H, W, E-1]

    lp = flat_len[lin]                                      # [H, W, E-1]
    r_a = (s_A[..., None] + s_B[..., None] * t_a) * lp
    r_b = (s_A[..., None] + s_B[..., None] * t_b) * lp
    dt_int = t_b - t_a
    dr = r_b - r_a
    flat_r = jnp.abs(dr) < 1e-9                            # in-plane ray

    # per-slab exact overlap + BOTH composition orders in one ascending
    # pass over k (one slab's gather live at a time — no K-sized
    # retention). Ascending-r alpha-under is the usual
    #   asc += T·c_k ; T *= (1-a_k);
    # for descending r, the identity
    #   R ← R·(1-a_k) + c_k   (k ascending)
    # yields R = Σ_k c_k·Π_{j>k}(1-a_j) — exactly the composite in
    # descending slab order.
    asc_rgb = jnp.zeros((height, width, t_a.shape[-1], 3), jnp.float32)
    dsc_rgb = jnp.zeros_like(asc_rgb)
    t_asc = jnp.ones(t_a.shape, jnp.float32)
    for kk in range(k):
        sk = flat_s[kk][lin]
        ek = flat_e[kk][lin]
        ck = flat_c[kk][:, lin]                             # [4, H, W, E-1]
        thick = ek - sk
        live = jnp.isfinite(sk) & jnp.isfinite(ek) & (thick > 0.0)
        # t-interval of the slab inside [t_a, t_b]: r is linear
        inv = dt_int / jnp.where(jnp.abs(dr) < eps, eps, dr)
        ts = t_a + (sk - r_a) * inv
        te = t_a + (ek - r_a) * inv
        lo = jnp.minimum(ts, te)
        hi = jnp.maximum(ts, te)
        ov = jnp.clip(jnp.minimum(hi, t_b) - jnp.maximum(lo, t_a),
                      0.0, None)
        ov_flat = dt_int * ((r_a >= sk) & (r_a < ek))
        length = jnp.where(flat_r, ov_flat, ov)             # world units
        frac = length / jnp.maximum(thick, 1e-6)
        a_slab = jnp.clip(ck[3], 0.0, 1.0 - 1e-6)
        alpha = adjust_opacity(a_slab, jnp.clip(frac, 0.0, frac_cap))
        alpha = jnp.where(live & valid, alpha, 0.0)
        prem = (jnp.moveaxis(ck[:3], 0, -1)
                / jnp.maximum(a_slab, 1e-6)[..., None]
                * alpha[..., None])                         # premult c_k
        asc_rgb = asc_rgb + t_asc[..., None] * prem
        t_asc = t_asc * (1.0 - alpha)
        dsc_rgb = dsc_rgb * (1.0 - alpha)[..., None] + prem
    rgb_int = jnp.where((dr >= 0)[..., None], asc_rgb, dsc_rgb)
    a_int = 1.0 - t_asc                                     # order-free

    # front-to-back across intervals: exclusive transmittance along the
    # (already t-sorted) event axis — fully vectorized
    t_excl = jnp.cumprod(1.0 - a_int, axis=-1)
    t_excl = jnp.concatenate([jnp.ones_like(t_excl[..., :1]),
                              t_excl[..., :-1]], axis=-1)
    # rgb_int is already premultiplied (per-slab alpha folded in above)
    rgb = jnp.sum(t_excl[..., None] * rgb_int, axis=-2)
    alpha = 1.0 - jnp.prod(1.0 - a_int, axis=-1)
    img = jnp.concatenate([jnp.moveaxis(rgb, -1, 0), alpha[None]], axis=0)
    bg = jnp.asarray(background, jnp.float32).reshape(4, 1, 1)
    return img + (1.0 - img[3:4]) * bg


def render_vdi_mxu(vdi: VDI, axcam0: AxisCamera, spec0: AxisSpec,
                   cam: Camera, width: int, height: int,
                   num_slices: Optional[int] = None,
                   spec_new: Optional[AxisSpec] = None,
                   background: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0),
                   early_exit_alpha: float = 0.999,
                   axis_sign: Optional[Tuple[int, int]] = None
                   ) -> jnp.ndarray:
    """Render a slice-march VDI from a new camera -> f32[4, H, W]
    premultiplied. Gather-free: per original slice plane, decode + two
    banded resampling matmuls + alpha-under fold.

    ``num_slices``: STATIC number of planes to march. The default estimates
    the original march's slice count from the intermediate grid size
    (``ni0 / scale`` — grids are sized ~1.25x the in-plane voxel count and
    volumes are roughly cubic); pass the real slice count (the generating
    volume's extent along the march axis, in voxels) when you have it —
    too few planes truncates the far content.
    ``spec_new``: static spec for the new camera's intermediate grid.
    ``axis_sign``: the new camera's march regime; REQUIRED when ``cam`` is
    traced inside jit (the default calls ``slicer.choose_axis``, which
    needs a concrete eye).
    """
    k, _, nj0, ni0 = vdi.color.shape
    axis = spec0.axis
    new_axis, new_sign = axis_sign or slicer.choose_axis(cam)
    if new_axis != axis:
        raise ValueError(
            f"novel view marches axis {new_axis} but the VDI was generated "
            f"along axis {axis}; use ops.vdi_render.render_vdi for "
            "cross-regime views")
    if spec_new is None:
        # the new frustum must cover the original one's far-plane footprint
        # (bigger than the near-plane one by the depth-ratio range), so give
        # the intermediate grid proportionally more pixels or the resample
        # blurs even for the identity view
        rnd = lambda n: max(8, -(-int(n) // 8) * 8)
        spec_new = AxisSpec(axis=axis, sign=new_sign,
                            ni=rnd(ni0 * 1.75), nj=rnd(nj0 * 1.75),
                            chunk=spec0.chunk,
                            matmul_dtype=spec0.matmul_dtype)

    # depth ladder: the original march's slice planes (count must be
    # static; see docstring for the default heuristic)
    if num_slices is None:
        num_slices = _default_slices(ni0)
    s_count = num_slices

    eu0, ev0, ew0 = axcam0.eye_u, axcam0.eye_v, axcam0.eye_w
    length0 = axcam0.ray_lengths()                         # [Nj0, Ni0]
    ds0 = jnp.abs(axcam0.dwm) / axcam0.zp

    # new virtual camera over the same world box footprint: derive the box
    # from the original grid's extent at s=1 … use the original reference
    # plane's footprint propagated to the new camera via make_axis_camera
    # on a synthetic volume is awkward — build the new grid directly from
    # the original one's world extent (the content cannot leave the
    # original frustum anyway).
    du0 = axcam0.u_grid[1] - axcam0.u_grid[0]
    dv0 = axcam0.v_grid[1] - axcam0.v_grid[0]

    # world w of original slice plane q (q ascending = original march
    # front-to-back); new camera visits them in its own order
    def plane_w(q):
        return axcam0.w0 + q * axcam0.dwm

    same_dir = (spec_new.sign == spec0.sign)
    # new-order index -> original plane index
    def orig_index(qn):
        return qn if same_dir else (s_count - 1.0 - qn)

    # new camera geometry: reuse make_axis_camera by synthesizing the
    # content AABB in world space from the original frustum's footprint
    # over the VDI's ACTUAL depth range (traced values may size the box —
    # only the pixel counts must stay static); a loose box wastes
    # intermediate resolution and blurs the resample
    u_lo, u_hi, v_lo, v_hi, smax = _content_aabb(vdi, axcam0, s_count)
    w_far = ew0 + jnp.float32(spec0.sign) * smax * axcam0.zp
    w_lo = jnp.minimum(plane_w(0.0), w_far)
    w_hi = jnp.maximum(plane_w(0.0), w_far)

    box_min = jnp.zeros(3).at[spec0.u_axis].set(u_lo) \
        .at[spec0.v_axis].set(v_lo).at[axis].set(w_lo)
    box_max = jnp.zeros(3).at[spec0.u_axis].set(u_hi) \
        .at[spec0.v_axis].set(v_hi).at[axis].set(w_hi)

    from scenery_insitu_tpu.core.volume import Volume
    # the dummy volume only feeds make_axis_camera's spacing reads (slice
    # pitch, footprint margins, zp floor) — give it the ORIGINAL grid's
    # pitches, not a box-sized spacing that would inflate all three
    sp = jnp.zeros(3).at[spec0.u_axis].set(jnp.abs(du0)) \
        .at[spec0.v_axis].set(jnp.abs(dv0)).at[axis].set(jnp.abs(axcam0.dwm))
    dummy = Volume(jnp.zeros((2, 2, 2), jnp.float32), box_min, sp)
    axcam_n = make_axis_camera(dummy, cam, spec_new,
                               box_min=box_min, box_max=box_max)

    eun, evn, ewn = axcam_n.eye_u, axcam_n.eye_v, axcam_n.eye_w
    length_n = axcam_n.ray_lengths()                       # [Njn, Nin]
    mm = jnp.bfloat16 if spec_new.matmul_dtype == "bf16" else jnp.float32

    c = spec_new.chunk
    nchunks = -(-s_count // c)

    def body(carry, ci):
        qn = ci * c + jnp.arange(c, dtype=jnp.float32)     # new-order idx
        live = qn < s_count
        q0 = orig_index(qn)                                # original idx
        wq = plane_w(q0)                                   # [C] plane w

        # original-ladder depth ratio of this plane (always >= 1 on live
        # planes — plane 0 sits on the reference plane itself)
        s0 = jnp.float32(spec0.sign) * (wq - ew0) / axcam0.zp

        # new camera's sample positions on the plane
        sn = jnp.float32(spec_new.sign) * (wq - ewn) / axcam_n.zp
        pos_u = eun + (axcam_n.u_grid[None, :] - eun) * sn[:, None]
        pos_v = evn + (axcam_n.v_grid[None, :] - evn) * sn[:, None]
        front = sn > spec_new.s_floor                      # plane before eye

        dt0 = ds0 * length0                                # per-step len
        val = _resample_planes(vdi, axcam0, s0, dt0, pos_u, pos_v, mm)
        rgb = val[:, :3]
        a_res = jnp.clip(val[:, 3], 0.0, 1.0 - 1e-6)
        dt0_res = val[:, 4]

        # re-correct opacity for the NEW ray's inter-plane path length:
        # planes are |dwm| apart in w; a new ray whose eye-to-refplane
        # distance is length_n crosses them every |dwm|·length_n/zp_n
        dtn = jnp.abs(axcam0.dwm) / axcam_n.zp * length_n  # [Njn, Nin]
        ratio = dtn[None] / jnp.maximum(dt0_res, 1e-6)
        a_new = adjust_opacity(a_res, jnp.clip(ratio, 0.0, 16.0))
        gate = (live & front)[:, None, None].astype(jnp.float32)
        a_new = a_new * gate
        scale = a_new / jnp.maximum(a_res, 1e-6)
        rgb_new = rgb * scale[:, None]

        acc = carry
        for i in range(c):
            pgate = (acc[3] < early_exit_alpha).astype(jnp.float32)
            srcp = jnp.concatenate([rgb_new[i], a_new[i][None]]) * pgate[None]
            acc = acc + (1.0 - acc[3:4]) * srcp
        return acc, None

    acc0 = jnp.zeros((4, spec_new.nj, spec_new.ni), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, jnp.arange(nchunks))

    return warp_to_camera(acc, axcam_n, spec_new, cam, width, height,
                          background)
