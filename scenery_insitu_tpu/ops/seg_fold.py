"""Segmented-scan supersegment WRITE fold — the round-4 redesign of the
march's hot loop.

Why this exists: the round-3 hardware captures localized ~390 of the 420 ms
512^3 frame in the supersegment write march, ~300x above the counting
march's O(1)-state floor (benchmarks/results/README.md). Both prior
schedules shared one structural property: a *sequential* per-slice state
machine (``ss.push``) whose every step either round-trips the full
``[K,4,H,W]`` output state through HBM (the XLA scan) or defers per-slice
close events across a long unrolled live range (the two-phase Pallas
kernel, which hardware showed was no faster). The machine itself is the
bottleneck shape, not its scheduling.

This module removes the sequential machine. The observation that unlocks
it: the break metric only ever compares a slice against its **immediate
predecessor** — when the predecessor is empty the break fires regardless
of the color diff, and when it is non-empty the machine's ``prev_rgb`` IS
the predecessor's rgb. So the per-slice segment-START flags are computable
in parallel from a shift by one slice, and everything else follows from
parallel primitives:

- segment ids = running count of start flags (a cumulative sum);
  ``slot = min(id, K-1)`` reproduces the machine's merge-overflow exactly
  (once the counter passes K-1 the machine never closes again, so every
  later item lands in the last slot);
- within-segment alpha-under composition factors as
  ``sum_s rgba_s * T_s`` where ``T_s`` is the product of ``(1 - alpha)``
  over earlier items of the same slot — a *segmented* running product that
  resets at each slot's first item (and only there: merged-overflow starts
  do not reset, matching the machine's never-closing last slot);
- the K output slots accumulate via K masked reductions over the chunk,
  touching the ``[K,...]`` state ONCE per chunk, and composition across
  chunks is the ordinary under rule ``out += (1 - out_alpha) * contrib``
  (for a slot continuing across the boundary, ``1 - out_alpha`` *is* its
  carried transmittance).

The result is bit-for-bit the same set of supersegments as C sequential
``ss.push`` calls (same predicates, same overflow), differing only in
floating-point association of the within-segment sums (tests pin allclose
at 1e-5). The true per-pixel start count — the temporal threshold
controller's feedback signal (``ss.update_threshold``) — is the fold's own
``cnt`` field, free.

Reference parity: this is the TPU-native replacement for the fused
generate+accumulate GPU kernel (VDIGenerator.comp:380-529 +
AccumulateVDI.comp:69-98); the reference's per-ray sequential loop is a
good GPU shape and a terrible TPU one, hence the re-derivation.

The same algorithm also has a Pallas twin (ops/pallas_seg.py) that keeps
the stream strip and K-state in VMEM; this XLA version is the portable
schedule and the fallback when Mosaic rejects the kernel.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.ops import supersegments as ss


class SegFoldState(NamedTuple):
    """Carried fold state. Unlike ``ss.SegState`` there is no open-segment
    accumulator: slots are written incrementally, and the carried
    ``out_color`` alpha of the newest slot encodes its transmittance for
    cross-chunk continuation. ``out_end`` holds ``-inf`` for untouched
    slots internally (max-merge identity); `seg_finalize` maps unused
    slots to the ``(+inf, +inf)`` convention of ``ss.finalize``."""

    out_color: jnp.ndarray   # f32[K, 4, H, W] premultiplied, composited
    out_start: jnp.ndarray   # f32[K, H, W]  (+inf until first item)
    out_end: jnp.ndarray     # f32[K, H, W]  (-inf until first item)
    cnt: jnp.ndarray         # i32[H, W] TRUE segment starts so far (uncapped)
    prev_rgb: jnp.ndarray    # f32[3, H, W] last item's rgb where non-empty
    prev_empty: jnp.ndarray  # bool[H, W] last item was empty


def init_seg_state(k: int, height: int, width: int) -> SegFoldState:
    return SegFoldState(
        out_color=jnp.zeros((k, 4, height, width), jnp.float32),
        out_start=jnp.full((k, height, width), jnp.inf, jnp.float32),
        out_end=jnp.full((k, height, width), -jnp.inf, jnp.float32),
        cnt=jnp.zeros((height, width), jnp.int32),
        prev_rgb=jnp.zeros((3, height, width), jnp.float32),
        prev_empty=jnp.ones((height, width), bool),
    )


def chunk_flags(rgba: jnp.ndarray, prev_rgb: jnp.ndarray,
                prev_empty: jnp.ndarray, threshold: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Parallel (empty, start) flags for a chunk of depth-ordered slices.

    ``rgba f32[C,4,H,W]`` premultiplied; carried prev_rgb/prev_empty seed
    slice 0. The shift-by-one is exact vs the sequential machine: the
    machine's prev_rgb (last NON-empty rgb) is only consulted when the
    immediate predecessor was non-empty — in which case they coincide.
    """
    emp = rgba[:, 3] < ss.EMPTY_ALPHA                      # [C, H, W]
    rgb = rgba[:, :3]
    pr = jnp.concatenate([prev_rgb[None], rgb[:-1]], axis=0)
    pe = jnp.concatenate([prev_empty[None], emp[:-1]], axis=0)
    diff = jnp.linalg.norm(rgb - pr, axis=1)
    starts = ~emp & (pe | (diff > threshold))
    return emp, starts


def seg_fold_chunk(st: SegFoldState, rgba: jnp.ndarray, t0: jnp.ndarray,
                   t1: jnp.ndarray, threshold: jnp.ndarray, *,
                   max_k: int) -> SegFoldState:
    """Fold one chunk of slices. Semantically = C sequential ``ss.push``
    calls (up to fp association). rgba f32[C,4,H,W]; t0/t1 f32[C,H,W];
    threshold [H,W] or scalar."""
    c, _, h, w = rgba.shape
    emp, starts = chunk_flags(rgba, st.prev_rgb, st.prev_empty, threshold)

    # uncapped segment id per slice; non-empty slices always have id >= 0
    # (a non-empty slice either starts a segment or continues one, and a
    # continued segment implies cnt >= 1 on entry)
    sid = st.cnt[None] + jnp.cumsum(starts.astype(jnp.int32), axis=0) - 1
    slot = jnp.clip(sid, 0, max_k - 1)
    # transmittance resets only at each slot's FIRST item: merged-overflow
    # starts (sid > K-1) keep composing into the last slot
    reset = starts & (sid <= max_k - 1)

    # no clipping: the factorization sum_s rgba_s * prod(1 - alpha) is the
    # exact algebraic expansion of the under recurrence for ANY alpha, and
    # clipping here would silently diverge from ss.push on out-of-range
    # inputs (range enforcement belongs to the march, not the fold)
    alpha = jnp.where(emp, 0.0, rgba[:, 3])
    p = 1.0 - alpha
    # exclusive within-slot transmittance: tiny sequential loop, 2 live
    # [H,W] arrays (this is the only sequential dependence left, ~3 ops
    # per slice; the prev_rgb update rides along for exact state parity)
    t_run = jnp.ones((h, w), jnp.float32)
    pr_run = st.prev_rgb
    tls = []
    for s in range(c):
        t_here = jnp.where(reset[s], 1.0, t_run)
        tls.append(t_here)
        t_run = t_here * p[s]
        pr_run = jnp.where(emp[s][None], pr_run, rgba[s, :3])
    tl = jnp.stack(tls)                                    # [C, H, W]

    live = tl * (~emp).astype(jnp.float32)
    v = rgba * live[:, None]                               # [C, 4, H, W]

    # K masked reductions; [K,...] state touched once per chunk. The merge
    # is plain alpha-under: a slot continuing across the chunk boundary is
    # scaled by (1 - out_alpha) == its carried transmittance; fresh slots
    # have out_alpha == 0; untouched slots get contrib == 0.
    out_c, out_s, out_e = [], [], []
    for k in range(max_k):
        m = (slot == k) & ~emp                             # [C, H, W]
        mf = m.astype(jnp.float32)
        contrib = jnp.sum(v * mf[:, None], axis=0)         # [4, H, W]
        d0 = jnp.min(jnp.where(m, t0, jnp.inf), axis=0)
        d1 = jnp.max(jnp.where(m, t1, -jnp.inf), axis=0)
        oc = st.out_color[k]
        out_c.append(oc + (1.0 - oc[3:4]) * contrib)
        out_s.append(jnp.minimum(st.out_start[k], d0))
        out_e.append(jnp.maximum(st.out_end[k], d1))

    return SegFoldState(
        out_color=jnp.stack(out_c),
        out_start=jnp.stack(out_s),
        out_end=jnp.stack(out_e),
        cnt=st.cnt + jnp.sum(starts.astype(jnp.int32), axis=0),
        prev_rgb=pr_run,
        prev_empty=emp[-1],
    )


def seg_finalize(st: SegFoldState) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(color [K,4,H,W], depth [K,2,H,W]) in ``ss.finalize``'s format:
    unused slots carry (+inf, +inf) depths and zero color."""
    k = st.out_color.shape[0]
    used = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0) < st.cnt[None]
    depth = jnp.stack([jnp.where(used, st.out_start, jnp.inf),
                       jnp.where(used, st.out_end, jnp.inf)], axis=1)
    return st.out_color, depth
