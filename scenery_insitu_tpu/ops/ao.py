"""Ambient occlusion for the plain raycasters (≅ the AO scaffolding in the
reference's newer raycaster, ComputeRaycast.comp:147-191: 24 cone rays ×
5 density samples around each shading point — present but never enabled).

TPU-first re-derivation: per-sample AO rays are exactly the scattered
gather pattern this framework exists to avoid, and the reference's 24-ray
average is itself just a spherical estimate of nearby opacity. So compute
the estimate ONCE per frame as a volume — a separable edge-clamped box
blur of the per-voxel opacity (three cumsum passes, one per axis; no
gathers, fully fused by XLA) — and shade each sample by ``1 - occlusion``:

- gather path: `ops.raycast.raycast(..., ao_field=...)` samples the field
  trilinearly alongside the value volume (one extra fetch per step).
- MXU slice march: `shade_volume_ao` bakes TF + AO into a premultiplied
  RGBA volume that the existing pre-shaded march renders (the vdi_novel
  proxy mechanism) — pre-classified rendering, so interpolation happens
  in color space rather than value space; visually equivalent for smooth
  transfer functions and entirely gather-free.

Flag-gated and off by default (``RenderConfig.ao_strength = 0``), like
the reference's own inactive scaffolding.
"""

from __future__ import annotations

import jax.numpy as jnp

from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import Volume


def _box_blur_1d(x: jnp.ndarray, r: int, axis: int) -> jnp.ndarray:
    """Edge-clamped box blur, window ``2r + 1``, via cumulative sums —
    O(1) in the radius."""
    if r <= 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (r, r)
    xp = jnp.pad(x, pad, mode="edge")
    zero = [(0, 0)] * x.ndim
    zero[axis] = (1, 0)
    c = jnp.pad(jnp.cumsum(xp, axis=axis), zero)           # c[k] = sum[:k]
    n = x.shape[axis]
    w = 2 * r + 1
    hi = jnp.take(c, jnp.arange(w, w + n), axis=axis)
    lo = jnp.take(c, jnp.arange(0, n), axis=axis)
    return (hi - lo) / w


def occlusion_field(alpha: jnp.ndarray, radius: int = 4,
                    strength: float = 0.8, max_occ: float = 0.85
                    ) -> jnp.ndarray:
    """Occlusion in [0, max_occ] from a per-voxel opacity volume
    ``alpha [D, H, W]``: the mean opacity in a ``(2r+1)³`` neighborhood
    (the separable stand-in for the reference's 24-ray density average),
    scaled by ``strength``."""
    occ = alpha
    for ax in range(3):
        occ = _box_blur_1d(occ, radius, ax)
    return jnp.clip(strength * occ, 0.0, max_occ)


def tf_alpha(vol: Volume, tf: TransferFunction) -> jnp.ndarray:
    """Per-voxel opacity of a scalar volume under a transfer function."""
    _, alpha = tf(jnp.clip(vol.data, 0.0, 1.0))
    return alpha


def ao_field_volume(vol: Volume, tf: TransferFunction, radius: int = 4,
                    strength: float = 0.8) -> Volume:
    """The occlusion field as a Volume sharing ``vol``'s placement — the
    gather raycaster samples it trilinearly per step."""
    return Volume(occlusion_field(tf_alpha(vol, tf), radius, strength),
                  vol.origin, vol.spacing)


def shade_volume_ao(vol: Volume, tf: TransferFunction, radius: int = 4,
                    strength: float = 0.8) -> Volume:
    """Premultiplied RGBA volume with TF + AO baked in (``f32[4, D, H, W]``,
    alpha encoded per nominal step — the pre-shaded-volume convention of
    ops/slicer.slice_march). Render with the existing pre-shaded march:
    ``render_slices(shaded, tf=None, ...)`` / ``raycast_mxu(shaded, None,
    ...)`` — the AO'd MXU plain path with zero new march code."""
    rgb, alpha = tf(jnp.clip(vol.data, 0.0, 1.0))          # [D,H,W,3], [D,H,W]
    occ = occlusion_field(alpha, radius, strength)
    rgb = rgb * (1.0 - occ)[..., None]
    rgba = jnp.concatenate(
        [jnp.moveaxis(rgb * alpha[..., None], -1, 0), alpha[None]], axis=0)
    return Volume(rgba, vol.origin, vol.spacing)
