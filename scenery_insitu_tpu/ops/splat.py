"""Particle rendering: sphere-impostor splatting (SURVEY.md §7 step 8).

The reference renders particles as scenery ``Sphere`` nodes, one mesh per
particle, recreated/moved by a 5 ms fixed-rate update thread
(reference InVisRenderer.kt:119-209). On TPU the whole pass is one
vectorized scatter program instead of a scene graph:

  project N particles -> per-particle S×S pixel stamps -> z-buffer
  scatter-min -> winner-takes-pixel color scatter

Spheres are shaded as impostors (per-pixel depth offset + headlight
Lambert), so a particle occludes correctly against other particles both
within a rank and across ranks (sort-first depth-min composite,
ops.composite.composite_depth_min ≅ Head.kt:98-134).

Depths are the world-space ray parameter t — the Euclidean distance from
the eye, the ONE depth convention of the whole framework (core/vdi.py
docstring; the raycasters and VDIs use the same), so particle fragments
depth-compare and hybrid-composite exactly against volume renders and VDI
supersegments everywhere in the frame, not just at the image center. (The
reference mixed conventions and needed a converter pass; see SURVEY.md §7
"hard parts".)
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from scenery_insitu_tpu.core.camera import (Camera, projection_matrix,
                                            view_matrix)
from scenery_insitu_tpu.core.transfer import colormap_lut


class SplatOutput(NamedTuple):
    image: jnp.ndarray   # f32[4, H, W] premultiplied RGBA
    depth: jnp.ndarray   # f32[H, W] ray-parameter depth; +inf where empty


def speed_colors(vel: jnp.ndarray, colormap: str = "jet",
                 alpha: float = 1.0, mean: Optional[jnp.ndarray] = None,
                 std: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Color particles by sigmoid-normalized speed (≅ the reference's
    speed-statistics sigmoid scale, InVisRenderer.kt:166-185: speeds are
    standardized against the population mean/std, squashed through a
    sigmoid, and used as the colormap coordinate). -> f32[N, 4] straight
    (non-premultiplied) RGBA.

    mean/std override the population statistics — distributed callers pass
    globally psum-reduced values so coloring matches a single-device run."""
    speed = jnp.linalg.norm(vel, axis=-1)
    mean = jnp.mean(speed) if mean is None else mean
    std = jnp.maximum(jnp.std(speed) if std is None else std, 1e-8)
    u = 1.0 / (1.0 + jnp.exp(-(speed - mean) / std))
    lut = jnp.asarray(colormap_lut(colormap))
    n = lut.shape[0]
    x = jnp.clip(u, 0.0, 1.0) * (n - 1)
    i0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, n - 2)
    frac = (x - i0)[..., None]
    rgb = lut[i0] * (1 - frac) + lut[i0 + 1] * frac
    return jnp.concatenate([rgb, jnp.full_like(rgb[..., :1], alpha)], axis=-1)


def splat_particles(pos: jnp.ndarray, rgba: jnp.ndarray, radius,
                    cam: Optional[Camera], width: int, height: int,
                    stamp: int = 9, ambient: float = 0.25,
                    radii: Optional[jnp.ndarray] = None,
                    view: Optional[jnp.ndarray] = None,
                    proj: Optional[jnp.ndarray] = None,
                    near: float = 1e-3, far: float = jnp.inf) -> SplatOutput:
    """Render particles as lit opaque spheres.

    pos f32[N, 3] world positions; rgba f32[N, 4] straight colors;
    ``radius`` scalar world-space sphere radius (or per-particle via
    ``radii`` f32[N]); ``stamp`` static odd stamp side in pixels — the
    on-screen radius is clamped to ``stamp // 2`` px, so pick stamp to fit
    the nearest particles.

    Pass explicit ``view``/``proj`` 4×4 matrices (with ``cam=None``) to
    splat onto an arbitrary frustum — e.g. the slice-march engine's virtual
    axis camera, which is how the hybrid pipeline shares rays between
    particles and the volume VDI (ops/hybrid.py).
    """
    n = pos.shape[0]
    if view is None:
        view = view_matrix(cam)
    if proj is None:
        proj = projection_matrix(cam, width, height)
    if cam is not None:
        near, far = cam.near, cam.far
    r_world = jnp.broadcast_to(jnp.asarray(radius, jnp.float32), (n,)) \
        if radii is None else radii

    p_eye = pos @ view[:3, :3].T + view[:3, 3]             # [N, 3]
    z = -p_eye[:, 2]                                        # view depth, >0 in front
    t_ray = jnp.linalg.norm(p_eye, axis=-1)                 # ray parameter
    clip = p_eye @ proj[:3, :3].T + proj[:3, 3]
    w_clip = -p_eye[:, 2]                                   # proj[3] = (0,0,-1,0)
    ndc = clip[:, :2] / jnp.where(w_clip == 0.0, 1e-12, w_clip)[:, None]
    px = (ndc[:, 0] + 1.0) * 0.5 * width - 0.5
    py = (1.0 - ndc[:, 1]) * 0.5 * height - 0.5
    r_px = r_world * proj[1, 1] * (height * 0.5) / jnp.maximum(z, 1e-6)
    r_px = jnp.minimum(r_px, stamp // 2)
    visible = (z > near) & (z < far) & (r_px > 0.05)

    # S×S stamp around each particle's center pixel
    half = stamp // 2
    offs = jnp.arange(-half, half + 1, dtype=jnp.float32)
    oy, ox = jnp.meshgrid(offs, offs, indexing="ij")
    ox = ox.reshape(-1)                                     # [S²]
    oy = oy.reshape(-1)
    cx = jnp.round(px)[:, None] + ox[None]                  # [N, S²]
    cy = jnp.round(py)[:, None] + oy[None]
    dx = cx - px[:, None]
    dy = cy - py[:, None]
    d2 = dx * dx + dy * dy
    covered = d2 <= r_px[:, None] ** 2

    # impostor depth offset + normal: the pixel samples the sphere surface
    frac2 = jnp.clip(d2 / jnp.maximum(r_px[:, None] ** 2, 1e-12), 0.0, 1.0)
    nz = jnp.sqrt(1.0 - frac2)                              # [N, S²]
    depth = t_ray[:, None] - nz * r_world[:, None]          # ray-parameter t
    shade = ambient + (1.0 - ambient) * nz
    a = rgba[:, 3:4]
    prgb = rgba[:, :3][:, None, :] * (shade * a)[:, :, None]  # [N, S², 3]

    in_bounds = (cx >= 0) & (cx < width) & (cy >= 0) & (cy < height)
    ok = covered & in_bounds & visible[:, None]
    lin = (cy.astype(jnp.int32) * width + cx.astype(jnp.int32)).reshape(-1)
    lin = jnp.where(ok.reshape(-1), lin, height * width)    # out-of-range -> drop
    d_flat = depth.reshape(-1)

    zbuf = jnp.full((height * width,), jnp.inf, jnp.float32)
    zbuf = zbuf.at[lin].min(d_flat, mode="drop")

    # winner-takes-pixel: only the fragment whose depth equals the z-buffer
    # writes color (ties between coincident fragments resolve arbitrarily)
    won = jnp.concatenate([zbuf, jnp.array([jnp.inf])])[lin] == d_flat
    lin_w = jnp.where(won, lin, height * width)
    img = jnp.zeros((height * width, 4), jnp.float32)
    frag = jnp.concatenate(
        [prgb.reshape(-1, 3),
         jnp.broadcast_to(a, depth.shape).reshape(-1, 1)], axis=-1)
    img = img.at[lin_w].set(frag, mode="drop")

    return SplatOutput(jnp.moveaxis(img.reshape(height, width, 4), -1, 0),
                       zbuf.reshape(height, width))
