"""VDI generation: raycast a volume into per-pixel supersegment lists
(SURVEY.md §7 step 3; ≅ reference VDIGenerator.comp + AccumulateVDI.comp).

The march is a static-trip ``lax.fori_loop`` feeding the vectorized
supersegment state machine (ops.supersegments). Adaptive per-pixel
thresholding runs ``adaptive_iters`` cheap counting marches first — see the
supersegments module docstring for why this replaces the reference's
in-kernel binary search.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import VDIConfig
from scenery_insitu_tpu.core.camera import (Camera, pixel_rays,
                                            projection_matrix, view_matrix)
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.vdi import VDI, VDIMetadata
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.ops.raycast import nominal_step
from scenery_insitu_tpu.ops.sampling import (adjust_opacity, intersect_aabb,
                                             sample_volume_world)


def generate_vdi(vol: Volume, tf: TransferFunction, cam: Camera,
                 width: int, height: int,
                 cfg: Optional[VDIConfig] = None,
                 max_steps: int = 512,
                 frame_index: int = 0,
                 clip_min: Optional[jnp.ndarray] = None,
                 clip_max: Optional[jnp.ndarray] = None,
                 sample_min: Optional[jnp.ndarray] = None,
                 sample_max: Optional[jnp.ndarray] = None
                 ) -> Tuple[VDI, VDIMetadata]:
    """clip_min/clip_max: optional ray-clip AABB override (see
    ops.raycast.raycast — used for halo-exact domain decomposition).

    sample_min/sample_max: optional GLOBAL sampling AABB — the per-ray t
    ladder derives from this box while clip_min/clip_max only gate
    ownership, so every rank of a decomposed volume marches the SAME
    sample positions a single-device render would (decomposition-
    invariant sampling; docs/PERF.md "Render rebalancing" — what makes
    the sort-last composite exact across different render plans)."""
    cfg = cfg or VDIConfig()
    k = cfg.max_supersegments
    origin, dirs = pixel_rays(cam, width, height)
    box_min = vol.world_min if clip_min is None else clip_min
    box_max = vol.world_max if clip_max is None else clip_max
    if sample_min is None:
        tnear, tfar = intersect_aabb(origin, dirs, box_min, box_max)
        own = None
    else:
        tnear, tfar = intersect_aabb(origin, dirs, sample_min, sample_max)
        cn, cf = intersect_aabb(origin, dirs, box_min, box_max)
        # half-open ownership on the shared t ladder: the shared-plane t
        # is the same f32 expression on both neighbor ranks, so every
        # sample belongs to exactly one rank
        own = (cn, jnp.maximum(cf, cn))
    hit = tfar > tnear
    tfar = jnp.maximum(tfar, tnear)
    n = max_steps
    dt = (tfar - tnear) / n                                   # [H, W]
    nw = nominal_step(vol)

    def sample_at(i):
        """Premultiplied RGBA of march step i -> [4, H, W] plus (t0, t1)."""
        t = tnear + (i + 0.5) * dt
        pos = origin.reshape(3, 1, 1) + t[None] * dirs
        val = sample_volume_world(vol, jnp.moveaxis(pos, 0, -1))
        rgb, a = tf(val)
        a = jnp.where(hit, adjust_opacity(a, dt / nw), 0.0)
        if own is not None:
            a = jnp.where((t >= own[0]) & (t < own[1]), a, 0.0)
        rgba = jnp.concatenate([jnp.moveaxis(rgb, -1, 0) * a[None], a[None]])
        return rgba, t - 0.5 * dt, t + 0.5 * dt

    if cfg.adaptive and cfg.adaptive_mode == "temporal":
        raise ValueError(
            "adaptive_mode='temporal' is an MXU slice-march feature "
            "(slicer.generate_vdi_mxu_temporal carries its per-frame "
            "state); the gather path supports 'search' and 'histogram'")
    if cfg.adaptive and cfg.adaptive_mode == "histogram":
        # ONE counting march evaluating every candidate threshold (the
        # consecutive-item break metric makes count(thr) separable per
        # candidate — see ops/supersegments.py)
        tvec = ss.threshold_candidates(cfg.histogram_bins)

        def body_multi(i, st):
            rgba, _, _ = sample_at(i)
            return ss.push_count(st, tvec[:, None, None], rgba)

        counts = jax.lax.fori_loop(
            0, n, body_multi,
            ss.init_count_multi(cfg.histogram_bins, height, width)).count
        threshold = ss.pick_threshold(counts, tvec, k)
    elif cfg.adaptive:
        def count_fn(thr):
            def body(i, st):
                rgba, _, _ = sample_at(i)
                return ss.push_count(st, thr, rgba)
            return jax.lax.fori_loop(0, n, body,
                                     ss.init_count(height, width)).count
        threshold = ss.adaptive_threshold(count_fn, k, cfg.adaptive_iters,
                                          height, width)
    else:
        threshold = jnp.full((height, width), cfg.threshold, jnp.float32)

    def body(i, st):
        rgba, t0, t1 = sample_at(i)
        return ss.push(st, k, threshold, rgba, t0, t1)

    state = jax.lax.fori_loop(0, n, body, ss.init_state(k, height, width))
    color, depth = ss.finalize(state)

    meta = VDIMetadata.create(
        projection=projection_matrix(cam, width, height),
        view=view_matrix(cam),
        volume_dims=jnp.asarray(vol.dims_xyz, jnp.float32),
        window_dims=(width, height), nw=nw, index=frame_index)
    return VDI(color, depth), meta


def occupancy_grid(vdi: VDI, tnear: jnp.ndarray, tfar: jnp.ndarray,
                   cell: int = 8, depth_bins: Optional[int] = None) -> jnp.ndarray:
    """Screen-space occupancy acceleration structure
    (≅ OctreeCells r32ui [W/8, H/8, K] filled by imageAtomicAdd,
    VDIGenerator.comp:232-254 — here a post-pass count over the finished VDI
    instead of in-march atomics). Returns i32[B, H//cell, W//cell]: number of
    supersegments overlapping each depth bin in each pixel cell; depth bins
    span [min tnear, max tfar] linearly."""
    b = depth_bins or vdi.k
    lo = jnp.min(tnear)
    hi = jnp.maximum(jnp.max(jnp.where(jnp.isfinite(tfar), tfar, lo)), lo + 1e-6)
    edges = jnp.linspace(lo, hi, b + 1)
    start, end = vdi.depth[:, 0], vdi.depth[:, 1]          # [K, H, W]
    live = vdi.color[:, 3] > 0.0
    overlap = (start[None] < edges[1:, None, None, None]) & \
              (end[None] > edges[:-1, None, None, None]) & live[None]  # [B,K,H,W]
    per_pixel = jnp.sum(overlap, axis=1)                   # [B, H, W]
    hh = (per_pixel.shape[1] // cell) * cell
    ww = (per_pixel.shape[2] // cell) * cell
    pooled = per_pixel[:, :hh, :ww].reshape(b, hh // cell, cell, ww // cell, cell)
    return pooled.sum(axis=(2, 4)).astype(jnp.int32)
