"""Shared bits of the Pallas TPU kernels (composite + march folds)."""

from __future__ import annotations

import jax

# f32 native tile: 8 sublanes x 128 lanes
TILE_H = 8
TILE_W = 128


def should_interpret() -> bool:
    """Run kernels in interpret mode off-TPU (tests, the virtual mesh)."""
    return jax.default_backend() != "tpu"


def mosaic_probe(cache: dict, key: tuple, compile_fn,
                 component: str, from_: str, to: str, detail: str) -> bool:
    """Shared skeleton of the one-time Mosaic compile probes: run
    ``compile_fn`` (a closure lowering+compiling the REAL kernel
    geometry) once per ``key``, cache the verdict in ``cache``, and on
    rejection mint one ``obs.degrade(component, from_, to, ...)`` ledger
    entry carrying ``detail`` plus the truncated backend error. Keeps the
    probe family (composite/fused folds) in sync on the except-breadth,
    message truncation and caching semantics instead of hand-copying the
    try/except per kernel."""
    ok = cache.get(key)
    if ok is None:
        try:
            compile_fn()
            ok = True
        except Exception as e:
            from scenery_insitu_tpu import obs

            obs.degrade(component, from_, to,
                        f"{detail} ({type(e).__name__}: {str(e)[:200]})")
            ok = False
        cache[key] = ok
    return ok
