"""Shared bits of the Pallas TPU kernels (composite + march folds)."""

from __future__ import annotations

import jax

# f32 native tile: 8 sublanes x 128 lanes
TILE_H = 8
TILE_W = 128


def should_interpret() -> bool:
    """Run kernels in interpret mode off-TPU (tests, the virtual mesh)."""
    return jax.default_backend() != "tpu"
