"""Plain front-to-back volume raycaster — the minimum end-to-end slice
(SURVEY.md §7 step 2; ≅ reference VolumeRaycaster.comp:94-161 +
AccumulatePlainImage.comp + ComputeRaycast.comp).

Pure-JAX implementation: the march is a ``lax.fori_loop`` with a static trip
count over ``[H, W]``-shaped vectorized steps, so XLA sees one fused
elementwise+gather body — no per-pixel Python control flow, no dynamic
shapes. The per-step trilinear gathers make this the *portable reference
path*; the TPU-native engine is the MXU slice march in ``ops/slicer.py``
(no gathers in the hot loop; tests assert cross-engine parity).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from scenery_insitu_tpu.config import RenderConfig
from scenery_insitu_tpu.core.camera import Camera, pixel_rays
from scenery_insitu_tpu.core.transfer import TransferFunction
from scenery_insitu_tpu.core.volume import Volume
from scenery_insitu_tpu.ops.sampling import (adjust_opacity, intersect_aabb,
                                             sample_volume_world)


class RaycastOutput(NamedTuple):
    image: jnp.ndarray    # f32[4, H, W] premultiplied RGBA
    depth: jnp.ndarray    # f32[H, W] ray parameter t of first hit (alpha>eps);
                          # +inf where the ray saw nothing (≅ the RGBA-encoded
                          # start-depth image, VolumeRaycaster.comp:128-141)


def nominal_step(vol: Volume, scale: float = 1.0) -> jnp.ndarray:
    """World-space nominal sampling distance: one (min-axis) voxel * scale.
    This is the "nw" the reference carries in VDIData."""
    return jnp.min(vol.spacing) * scale


def raycast(vol: Volume, tf: TransferFunction, cam: Camera,
            width: int, height: int, cfg: Optional[RenderConfig] = None,
            clip_min: Optional[jnp.ndarray] = None,
            clip_max: Optional[jnp.ndarray] = None,
            ao_field: Optional[Volume] = None,
            sample_min: Optional[jnp.ndarray] = None,
            sample_max: Optional[jnp.ndarray] = None,
            ) -> RaycastOutput:
    """clip_min/clip_max override the ray-clipping AABB — used by the
    distributed pipeline so a rank renders exactly its domain region while
    its Volume carries halo slices for seam-exact boundary interpolation
    (the reference instead positions per-rank Volume nodes at their grid
    origins: DistributedVolumeRenderer.kt:341-386).

    ``ao_field`` (or ``cfg.ao_strength > 0``, which builds one): ambient
    occlusion volume sampled per step, darkening rgb by ``1 - occ``
    (≅ ComputeRaycast.comp:147-191's inactive AO scaffolding; see
    ops/ao.py for the TPU re-derivation)."""
    cfg = cfg or RenderConfig(width=width, height=height)
    if ao_field is None and cfg.ao_strength > 0.0:
        from scenery_insitu_tpu.ops.ao import ao_field_volume

        ao_field = ao_field_volume(vol, tf, cfg.ao_radius, cfg.ao_strength)
    origin, dirs = pixel_rays(cam, width, height)          # [3], [3, H, W]
    box_min = vol.world_min if clip_min is None else clip_min
    box_max = vol.world_max if clip_max is None else clip_max
    # sample_min/sample_max: the t ladder derives from this (global) box
    # and clip_min/clip_max only gate ownership — every rank of a
    # decomposed volume then marches the SAME sample positions a
    # single-device render would, whatever the render plan (see
    # ops/vdi_gen.generate_vdi)
    if sample_min is None:
        tnear, tfar = intersect_aabb(origin, dirs, box_min, box_max)
        own = None
    else:
        tnear, tfar = intersect_aabb(origin, dirs, sample_min, sample_max)
        cn, cf = intersect_aabb(origin, dirs, box_min, box_max)
        own = (cn, jnp.maximum(cf, cn))
    hit = tfar > tnear                                     # [H, W]
    tfar = jnp.maximum(tfar, tnear)

    n = cfg.max_steps
    dt = (tfar - tnear) / n                                # [H, W] per-pixel
    nw = nominal_step(vol, cfg.step_scale)

    def body(i, carry):
        acc, first_t = carry
        t = tnear + (i + 0.5) * dt                         # [H, W]
        pos = origin.reshape(3, 1, 1) + t[None] * dirs     # [3, H, W]
        val = sample_volume_world(vol, jnp.moveaxis(pos, 0, -1))
        rgb, a = tf(val)                                   # [H,W,3], [H,W]
        if ao_field is not None:
            occ = sample_volume_world(ao_field,
                                      jnp.moveaxis(pos, 0, -1))
            rgb = rgb * (1.0 - occ)[..., None]
        a = adjust_opacity(a, dt / nw)
        a = jnp.where(hit & (acc[3] < cfg.early_exit_alpha), a, 0.0)
        if own is not None:
            a = jnp.where((t >= own[0]) & (t < own[1]), a, 0.0)
        src = jnp.concatenate([jnp.moveaxis(rgb, -1, 0) * a[None], a[None]])
        acc = acc + (1.0 - acc[3:4]) * src
        first_t = jnp.where((first_t == jnp.inf) & (a > 1e-4), t, first_t)
        return acc, first_t

    acc0 = jnp.zeros((4, height, width), jnp.float32)
    t0 = jnp.full((height, width), jnp.inf, jnp.float32)
    acc, first_t = jax.lax.fori_loop(0, n, body, (acc0, t0))

    bg = jnp.asarray(cfg.background, jnp.float32).reshape(4, 1, 1)
    image = acc + (1.0 - acc[3:4]) * bg
    return RaycastOutput(image, first_t)


def raycast_image(vol: Volume, tf: TransferFunction, cam: Camera,
                  width: int, height: int,
                  cfg: Optional[RenderConfig] = None) -> jnp.ndarray:
    """Convenience wrapper returning just the image f32[4, H, W]."""
    return raycast(vol, tf, cam, width, height, cfg).image
