"""Pallas TPU kernel for the sort-last composite merge
(≅ VDICompositor.comp's per-pixel k-way merge + re-segmentation,
VDICompositor.comp:58-91,209-459).

The XLA path (ops.composite.composite_vdis) runs the supersegment state
machine as a ``lax.scan`` over the N*K depth-sorted slots with full-frame
[H, W] state — every scan iteration round-trips the state through HBM, and
with ``CompositeConfig.adaptive`` the threshold binary search multiplies
that by ``adaptive_iters`` more counting scans. This kernel fuses the
WHOLE composite — the adaptive search's counting passes AND the write pass
— over a (8, 128)-pixel tile held in VMEM: the slab stream is read from
HBM exactly once per tile, every counting/write iteration runs on
VMEM-resident state, and nothing intermediate ever spills.

The kernel body calls the very same ``supersegments.push``/``push_count``/
``finalize``/``adaptive_threshold``-equivalent logic the XLA path uses —
one implementation of the merge semantics, two schedules — so the parity
test (tests/test_pallas.py) can assert exact equality.

On CPU (tests, the 8-device virtual mesh) the kernel runs in interpret
mode automatically; on TPU it compiles with Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from scenery_insitu_tpu.ops import supersegments as ss
from scenery_insitu_tpu.ops.pallas_util import TILE_H, TILE_W, should_interpret


def _kernel(sc_ref, sd_ref, thr_ref, color_ref, depth_ref,
            seg_ref, ends_ref, prev_ref, flags_ref, k_ref,
            *, k_out: int, gap_eps: float, adaptive_iters: int,
            thr_max: float):
    # State lives in VMEM scratch, not in the fori_loop carry: Mosaic cannot
    # legalize an scf.for with dozens of carried vectors (one per [th, tw]
    # plane of SegState), and bool carries are illegal outright. The loop
    # carries nothing; each iteration loads state from the scratch refs,
    # runs the shared supersegments fold, and stores it back.
    nk = sc_ref.shape[0]
    th, tw = thr_ref.shape

    # ------------------------------------------- adaptive threshold search
    # (≅ ss.adaptive_threshold, but the counting marches run on the VMEM-
    # resident slab tile instead of re-scanning HBM adaptive_iters times)
    if adaptive_iters > 0:
        def count_pass(mid):
            # CountState in scratch: k_ref=count, prev_ref=prev_rgb,
            # flags_ref[1]=prev_empty, ends_ref[0]=prev_end
            k_ref[...] = jnp.zeros((th, tw), jnp.int32)
            prev_ref[...] = jnp.zeros((3, th, tw), jnp.float32)
            flags_ref[1] = jnp.ones((th, tw), jnp.float32)
            ends_ref[0] = jnp.full((th, tw), -jnp.inf, jnp.float32)

            def body(i, _):
                st = ss.CountState(count=k_ref[...], prev_rgb=prev_ref[...],
                                   prev_empty=flags_ref[1] > 0.5,
                                   prev_end=ends_ref[0])
                st = ss.push_count(st, mid, sc_ref[i], sd_ref[i, 0],
                                   sd_ref[i, 1], gap_eps)
                k_ref[...] = st.count
                prev_ref[...] = st.prev_rgb
                flags_ref[1] = st.prev_empty.astype(jnp.float32)
                ends_ref[0] = st.prev_end
                return 0

            jax.lax.fori_loop(0, nk, body, 0)
            return k_ref[...]

        lo = jnp.zeros((th, tw), jnp.float32)
        hi = jnp.full((th, tw), thr_max, jnp.float32)
        for _ in range(adaptive_iters):
            mid = 0.5 * (lo + hi)
            too_many = count_pass(mid) > k_out
            lo = jnp.where(too_many, mid, lo)
            hi = jnp.where(too_many, hi, mid)
        thr = hi
    else:
        thr = thr_ref[...]

    # ---------------------------------------------------------- write pass
    color_ref[...] = jnp.zeros_like(color_ref)
    depth_ref[...] = jnp.full_like(depth_ref, jnp.inf)
    seg_ref[...] = jnp.zeros_like(seg_ref)
    ends_ref[...] = jnp.zeros_like(ends_ref)
    prev_ref[...] = jnp.zeros_like(prev_ref)
    flags_ref[...] = jnp.stack([jnp.zeros((th, tw), jnp.float32),
                                jnp.ones((th, tw), jnp.float32)])
    k_ref[...] = jnp.zeros((th, tw), jnp.int32)

    def load_state() -> ss.SegState:
        return ss.SegState(
            out_color=color_ref[...],
            out_start=depth_ref[:, 0],
            out_end=depth_ref[:, 1],
            k=k_ref[...],
            open_=flags_ref[0] > 0.5,
            seg_rgba=seg_ref[...],
            seg_start=ends_ref[0],
            seg_end=ends_ref[1],
            prev_rgb=prev_ref[...],
            prev_empty=flags_ref[1] > 0.5,
        )

    def store_state(st: ss.SegState) -> None:
        color_ref[...] = st.out_color
        depth_ref[:, 0] = st.out_start
        depth_ref[:, 1] = st.out_end
        k_ref[...] = st.k
        flags_ref[0] = st.open_.astype(jnp.float32)
        flags_ref[1] = st.prev_empty.astype(jnp.float32)
        seg_ref[...] = st.seg_rgba
        ends_ref[0] = st.seg_start
        ends_ref[1] = st.seg_end
        prev_ref[...] = st.prev_rgb

    def body(i, _):
        st = ss.push(load_state(), k_out, thr, sc_ref[i],
                     sd_ref[i, 0], sd_ref[i, 1], gap_eps)
        store_state(st)
        return 0

    jax.lax.fori_loop(0, nk, body, 0)
    color, depth = ss.finalize(load_state())
    color_ref[...] = color
    depth_ref[...] = depth


def resegment_sorted(sc: jnp.ndarray, sd: jnp.ndarray,
                     threshold: Optional[jnp.ndarray], k_out: int,
                     gap_eps: float = 1e-4,
                     interpret: Optional[bool] = None,
                     adaptive_iters: int = 0, thr_max: float = 2.0
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a depth-sorted slab stream into K_out supersegments per pixel.

    sc f32[NK, 4, H, W] premultiplied (empty slots alpha 0),
    sd f32[NK, 2, H, W] (start, end; +inf when empty).
    ``adaptive_iters > 0`` runs the per-pixel threshold binary search
    inside the kernel (``threshold`` may be None); otherwise ``threshold``
    f32[H, W] is used as-is. Returns (color f32[K_out, 4, H, W], depth
    f32[K_out, 2, H, W]) — exactly what the XLA scans in composite_vdis
    produce.
    """
    nk, _, h, w = sc.shape
    if interpret is None:
        interpret = should_interpret()
    if threshold is None:
        threshold = jnp.zeros((h, w), jnp.float32)

    # pad pixels to tile multiples; padded pixels see only empty slabs
    ph = (-h) % TILE_H
    pw = (-w) % TILE_W
    if ph or pw:
        pad = ((0, 0), (0, 0), (0, ph), (0, pw))
        sc = jnp.pad(sc, pad)
        sd = jnp.pad(sd, pad, constant_values=jnp.inf)
        threshold = jnp.pad(threshold, ((0, ph), (0, pw)))
    hp, wp = h + ph, w + pw
    grid = (hp // TILE_H, wp // TILE_W)

    kernel = functools.partial(_kernel, k_out=k_out, gap_eps=gap_eps,
                               adaptive_iters=adaptive_iters,
                               thr_max=thr_max)
    color, depth = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nk, 4, TILE_H, TILE_W), lambda i, j: (0, 0, i, j)),
            pl.BlockSpec((nk, 2, TILE_H, TILE_W), lambda i, j: (0, 0, i, j)),
            pl.BlockSpec((TILE_H, TILE_W), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((k_out, 4, TILE_H, TILE_W),
                         lambda i, j: (0, 0, i, j)),
            pl.BlockSpec((k_out, 2, TILE_H, TILE_W),
                         lambda i, j: (0, 0, i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_out, 4, hp, wp), jnp.float32),
            jax.ShapeDtypeStruct((k_out, 2, hp, wp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((4, TILE_H, TILE_W), jnp.float32),   # open seg rgba
            pltpu.VMEM((2, TILE_H, TILE_W), jnp.float32),   # seg start/end
            pltpu.VMEM((3, TILE_H, TILE_W), jnp.float32),   # prev rgb
            pltpu.VMEM((2, TILE_H, TILE_W), jnp.float32),   # open/prev_empty
            pltpu.VMEM((TILE_H, TILE_W), jnp.int32),        # next free slot
        ],
        interpret=interpret,
    )(sc, sd, threshold)

    if ph or pw:
        color = color[:, :, :h, :w]
        depth = depth[:, :, :h, :w]
    return color, depth


# ------------------------------------------------------------ compile probe

_COMPOSITE_PROBE: dict = {}


def composite_compile_ok(nk: int, k_out: int,
                         adaptive_iters: int = 0) -> bool:
    """One-time Mosaic-acceptance probe for the composite resegment
    kernel at the real (nk, k_out, adaptive_iters) — the knobs the VMEM
    working set and the statically-unrolled threshold search scale with.
    The block geometry is one (TILE_H, TILE_W) pixel tile whatever the
    frame size, so the probe shape IS the kernel Mosaic sees and the
    cache key needs no width. ``composite.backend == "auto"`` consults
    this before picking the Pallas schedule (ops/composite.py): a
    rejection degrades to the XLA scan on the ledger instead of firing
    inside a traced frame step where nothing can catch it. Explicit
    ``backend="pallas"`` stays trusted-unprobed, like an explicit
    stencil tz (ADVICE r5 #4)."""
    from scenery_insitu_tpu.ops.pallas_util import mosaic_probe

    def compile_fn():
        sds = jax.ShapeDtypeStruct

        def f(sc, sd):
            return resegment_sorted(sc, sd, None, k_out,
                                    adaptive_iters=adaptive_iters,
                                    interpret=False)

        jax.jit(f).lower(
            sds((nk, 4, TILE_H, TILE_W), jnp.float32),
            sds((nk, 2, TILE_H, TILE_W), jnp.float32)).compile()

    return mosaic_probe(
        _COMPOSITE_PROBE,
        (jax.default_backend(), int(nk), int(k_out), int(adaptive_iters)),
        compile_fn, "ops.composite_fold", "pallas", "xla",
        f"Mosaic rejected the composite resegment kernel at nk={nk} "
        f"k_out={k_out} iters={adaptive_iters}")
